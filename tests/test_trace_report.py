"""Tier-1 smoke for scripts/trace_report.py: a tiny traced FakeEngine
game exports a Chrome trace, and the report CLI renders a non-empty
latency table + counters from it (ISSUE-4 CI satellite)."""

import json
import os
import subprocess
import sys

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.obs import tracer as obs_tracer
from bcg_tpu.serve.engine import ServingEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "trace_report.py")


@pytest.fixture
def traced(monkeypatch):
    monkeypatch.setenv("BCG_TPU_TRACE", "1")
    monkeypatch.delenv("BCG_TPU_TRACE_OUT", raising=False)
    obs_tracer.reset()
    yield obs_tracer.get_tracer()
    obs_tracer.reset()


def test_report_renders_traced_game(traced, tmp_path):
    serving = ServingEngine(FakeEngine(seed=0, policy="stubborn"),
                            linger_ms=1)
    out = run_simulation(n_agents=3, byzantine_count=0, max_rounds=2,
                         backend="fake", seed=0, engine=serving)
    serving.shutdown()
    assert out["metrics"]["total_rounds"] == 2
    trace_path = tmp_path / "game_trace.json"
    traced.export(str(trace_path))

    proc = subprocess.run(
        [sys.executable, SCRIPT, str(trace_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip(), "report rendered empty"
    # The latency table names the game's spans with real statistics...
    for name in ("round", "decide", "serve.device", "engine.decode"):
        assert name in proc.stdout, f"{name!r} missing from report"
    assert "p50_ms" in proc.stdout and "p95_ms" in proc.stdout
    # ... and the counters section surfaces the serve accounting.
    assert "top counters" in proc.stdout
    assert "serve.requests" in proc.stdout


def test_report_derives_spec_acceptance(tmp_path):
    """engine.spec.* counters in an export turn into a one-line draft
    acceptance rate (and the line is absent without them)."""
    trace = {
        "traceEvents": [],
        "otherData": {"counters": {
            "engine.spec.drafted": 80,
            "engine.spec.accepted": 60,
            "engine.spec.rejected": 20,
        }},
    }
    path = tmp_path / "spec_trace.json"
    path.write_text(json.dumps(trace))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "60/80 draft tokens accepted (75.0%)" in proc.stdout
    # No spec counters -> no spec line.
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({"traceEvents": [], "otherData": {}}))
    proc2 = subprocess.run(
        [sys.executable, SCRIPT, str(bare)],
        capture_output=True, text=True, timeout=60,
    )
    assert "speculative" not in proc2.stdout


def test_report_derives_round_fusion_line(tmp_path):
    """engine.megaround.rounds in an export turns into the one-line
    round-fusion summary with syncs/round from the game.host_syncs
    histogram flats (and the line is absent without fused rounds)."""
    trace = {
        "traceEvents": [],
        "otherData": {"counters": {
            "engine.megaround.rounds": 4,
            "game.host_syncs.count": 4,
            "game.host_syncs.sum": 4,
        }},
    }
    path = tmp_path / "megaround_trace.json"
    path.write_text(json.dumps(trace))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "round fusion: 4 fused round(s)" in proc.stdout
    assert "1.0 sync(s)/round" in proc.stdout
    # No fused rounds -> no line (a lockstep game must not render one).
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({
        "traceEvents": [],
        "otherData": {"counters": {"game.host_syncs.count": 4,
                                   "game.host_syncs.sum": 24}},
    }))
    proc2 = subprocess.run(
        [sys.executable, SCRIPT, str(bare)],
        capture_output=True, text=True, timeout=60,
    )
    assert "round fusion" not in proc2.stdout


def test_report_renders_hlo_census_table(tmp_path):
    """engine.hlo.* gauges in an export render as the per-jit-entry
    kernel-census table — still with no bcg_tpu import (the report must
    read a trace copied off a TPU host anywhere)."""
    trace = {
        "traceEvents": [],
        "otherData": {"counters": {
            "engine.hlo.decode_loop.fusions": 114,
            "engine.hlo.decode_loop.custom_calls": 0,
            "engine.hlo.decode_loop.collectives": 0,
            "engine.hlo.decode_loop.step_ops": 297,
            "engine.hlo.decode_loop.step_fusions": 77,
            "engine.hlo.decode_loop.total_ops": 443,
            "engine.hlo.decode_loop.flops": 1750287.0,
            "engine.hlo.decode_loop.bytes_accessed": 4306799.0,
            "engine.hlo.prefill.fusions": 29,
            "engine.hlo.prefill.total_ops": 130,
            "hbm.params_bytes": 1650000000,
            "hbm.total_bytes": 1650000000,
            "serve.requests": 12,
        }},
    }
    path = tmp_path / "census_trace.json"
    path.write_text(json.dumps(trace))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "hlo kernel census" in proc.stdout
    assert "decode_loop" in proc.stdout and "prefill" in proc.stdout
    # hbm gauges get their own section AND stay out of the ranked
    # top-counter list (their byte values would crowd event counters
    # out — serve.requests must survive at the top).
    assert "hbm ledger gauges" in proc.stdout
    assert "hbm.params_bytes" in proc.stdout
    top_section = proc.stdout.split("top counters")[1].split("\n==")[0]
    assert "serve.requests" in top_section
    assert "hbm.params_bytes" not in top_section
    assert "engine.hlo" not in top_section
    # Row values land under their columns (spot-check the step family).
    row = [l for l in proc.stdout.splitlines() if l.startswith("decode_loop")][0]
    assert "297" in row and "77" in row and "114" in row
    # The script itself stays dependency-free.
    src = open(SCRIPT).read()
    assert "import bcg_tpu" not in src and "from bcg_tpu" not in src
    # No census gauges -> no census section.
    bare = tmp_path / "bare2.json"
    bare.write_text(json.dumps({"traceEvents": [], "otherData": {}}))
    proc2 = subprocess.run(
        [sys.executable, SCRIPT, str(bare)],
        capture_output=True, text=True, timeout=60,
    )
    assert "hlo kernel census" not in proc2.stdout


def test_report_renders_histogram_quantile_table(tmp_path):
    """Flat registry-histogram entries (.bucket.le_* / .sum / .count)
    render as a per-family p50/p95/p99 table AND stay out of the ranked
    top-counter list (the hlo/hbm crowding fix applied to histograms)."""
    trace = {
        "traceEvents": [],
        "otherData": {"counters": {
            "serve.e2e_ms.bucket.le_5": 2,
            "serve.e2e_ms.bucket.le_10": 6,
            "serve.e2e_ms.bucket.le_25": 8,
            "serve.e2e_ms.sum": 90.0,
            "serve.e2e_ms.count": 8,
            "game.round_ms.bucket.le_50": 3,
            "game.round_ms.bucket.le_2_5": 1,   # non-integer bound label
            "game.round_ms.sum": 61.0,
            "game.round_ms.count": 4,
            "serve.requests": 12,
        }},
    }
    path = tmp_path / "hist_trace.json"
    path.write_text(json.dumps(trace))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "histogram quantiles" in proc.stdout
    rows = {
        l.split()[0]: l for l in proc.stdout.splitlines()
        if l.startswith(("serve.e2e_ms", "game.round_ms"))
    }
    assert set(rows) == {"serve.e2e_ms", "game.round_ms"}
    # serve.e2e_ms: count 8; median rank 4 lands in the (5,10] bucket.
    e2e = rows["serve.e2e_ms"].split()
    assert e2e[1] == "8"
    assert 5.0 < float(e2e[2]) <= 10.0
    # Raw bucket/sum/count entries never reach the ranked counter list.
    top_section = proc.stdout.split("top counters")[1].split("\n==")[0]
    assert "serve.requests" in top_section
    assert ".bucket.le_" not in top_section
    assert "serve.e2e_ms.count" not in top_section
    assert "game.round_ms.sum" not in top_section
    # No histograms -> no table.
    bare = tmp_path / "bare3.json"
    bare.write_text(json.dumps(
        {"traceEvents": [], "otherData": {"counters": {"serve.requests": 1}}}
    ))
    proc2 = subprocess.run(
        [sys.executable, SCRIPT, str(bare)],
        capture_output=True, text=True, timeout=60,
    )
    assert "histogram quantiles" not in proc2.stdout


def test_report_renders_hostsync_attribution_table(tmp_path):
    """engine.hostsync.* counters in an export render as the
    host-syncs-by-span attribution table with a coverage footer, AND
    stay out of the ranked top-counter list (the hlo/hbm crowding fix
    applied to the audit namespace) — still with no bcg_tpu import."""
    trace = {
        "traceEvents": [],
        "otherData": {"counters": {
            "engine.hostsync.total": 12,
            "engine.hostsync.attributed": 11,
            "engine.hostsync.unattributed": 1,
            "engine.hostsync.span.engine_decode": 6,
            "engine.hostsync.span.jit_decode_loop": 4,
            "engine.hostsync.span.engine_prefill": 1,
            "engine.hostsync.span.unattributed": 1,
            "engine.hostsync.site.decode_readback": 6,
            "engine.hostsync.site.prefill_barrier": 6,
            "serve.requests": 3,
        }},
    }
    path = tmp_path / "hostsync_trace.json"
    path.write_text(json.dumps(trace))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "host syncs by span" in proc.stdout
    # Hottest attribution first; coverage footer derived from totals.
    section = proc.stdout.split("host syncs by span")[1]
    assert section.index("engine_decode") < section.index("jit_decode_loop")
    assert "total 12 sync(s), 11 attributed (91.7% attributed)" in section
    # The audit namespace never crowds the ranked counter list.
    top_section = proc.stdout.split("top counters")[1].split("\n==")[0]
    assert "serve.requests" in top_section
    assert "engine.hostsync" not in top_section
    # No audit counters -> no section.
    bare = tmp_path / "bare4.json"
    bare.write_text(json.dumps(
        {"traceEvents": [], "otherData": {"counters": {"serve.requests": 1}}}
    ))
    proc2 = subprocess.run(
        [sys.executable, SCRIPT, str(bare)],
        capture_output=True, text=True, timeout=60,
    )
    assert "host syncs by span" not in proc2.stdout


def test_report_renders_compile_cost_tables(tmp_path):
    """The compile-cost families (engine.compile_ms.* histograms,
    engine.retrace_cause.* taxonomy counters, engine.compile_obs.*
    cumulative totals — bcg_tpu/obs/compile.py) render as the
    compile-time-by-entry and retraces-by-cause tables AND stay out of
    the ranked top-counter list (the hlo/hbm/hostsync crowding fix
    applied to the compile namespace) — still with no bcg_tpu
    import."""
    trace = {
        "traceEvents": [],
        "otherData": {"counters": {
            "engine.compile.decode_loop": 2,
            "engine.retrace.decode_loop": 1,
            "engine.compile.prefill": 2,
            "engine.retrace.prefill": 1,
            "engine.compile_ms.decode_loop.bucket.le_250": 1,
            "engine.compile_ms.decode_loop.bucket.le_500": 2,
            "engine.compile_ms.decode_loop.sum": 600.0,
            "engine.compile_ms.decode_loop.count": 2,
            "engine.compile_ms.prefill.bucket.le_250": 2,
            "engine.compile_ms.prefill.sum": 320.0,
            "engine.compile_ms.prefill.count": 2,
            "engine.retrace_cause.static_knob": 1,
            "engine.retrace_cause.shape": 1,
            "engine.compile_obs.first_compile_ms": 700.0,
            "engine.compile_obs.retrace_ms": 220.0,
            "engine.compile_obs.aot_ms": 0.0,
            "engine.compile_obs.cache_entries": 4,
            "serve.requests": 3,
        }},
    }
    path = tmp_path / "compile_trace.json"
    path.write_text(json.dumps(trace))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "compile time by entry" in proc.stdout
    section = proc.stdout.split("compile time by entry")[1]
    # Hottest entry (decode_loop, 600 ms) first.
    assert section.index("decode_loop") < section.index("prefill")
    assert "4 trace-cache entries" in section
    assert "700.0 ms first-compile" in section
    assert "retraces by cause" in proc.stdout
    cause = proc.stdout.split("retraces by cause")[1]
    assert "static_knob" in cause and "shape" in cause
    # The compile families never crowd the ranked counter list.
    top_section = proc.stdout.split("top counters")[1].split("\n==")[0]
    assert "serve.requests" in top_section
    for family in ("engine.compile_ms", "engine.retrace_cause",
                   "engine.compile_obs"):
        assert family not in top_section, family
    # No compile counters -> no sections.
    bare = tmp_path / "bare5.json"
    bare.write_text(json.dumps(
        {"traceEvents": [], "otherData": {"counters": {"serve.requests": 1}}}
    ))
    proc2 = subprocess.run(
        [sys.executable, SCRIPT, str(bare)],
        capture_output=True, text=True, timeout=60,
    )
    assert "compile time by entry" not in proc2.stdout
    assert "retraces by cause" not in proc2.stdout


def test_report_renders_alerts_line(tmp_path):
    """alert.* transition counters in an export render as the one-line
    alert-plane summary with firing rule names, AND stay out of the
    ranked top-counter list (the crowding fix applied to the alert
    namespace) — still with no bcg_tpu import."""
    trace = {
        "traceEvents": [],
        "otherData": {"counters": {
            "alert.evaluations": 40,
            "alert.fired": 2,
            "alert.resolved": 1,
            "alert.flaps": 0,
            "alert.rules": 12,
            "alert.firing.engine_errors": 1,
            "alert.firing.slo_burn": 0,
            "serve.requests": 3,
        }},
    }
    path = tmp_path / "alerts_trace.json"
    path.write_text(json.dumps(trace))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert ("== alerts: 2 fired / 1 resolved over 40 evaluation(s), "
            "0 flap(s); firing: engine_errors ==") in proc.stdout
    # The alert namespace never crowds the ranked counter list.
    top_section = proc.stdout.split("top counters")[1].split("\n==")[0]
    assert "serve.requests" in top_section
    assert "alert." not in top_section
    # No alert counters -> no line; resolved-quiet exports drop the
    # firing suffix.
    bare = tmp_path / "bare6.json"
    bare.write_text(json.dumps(
        {"traceEvents": [], "otherData": {"counters": {"serve.requests": 1}}}
    ))
    proc2 = subprocess.run(
        [sys.executable, SCRIPT, str(bare)],
        capture_output=True, text=True, timeout=60,
    )
    assert "== alerts:" not in proc2.stdout
    quiet = tmp_path / "quiet.json"
    quiet.write_text(json.dumps({
        "traceEvents": [],
        "otherData": {"counters": {"alert.evaluations": 5,
                                   "alert.fired": 1,
                                   "alert.resolved": 1,
                                   "alert.firing.slo_burn": 0}},
    }))
    proc3 = subprocess.run(
        [sys.executable, SCRIPT, str(quiet)],
        capture_output=True, text=True, timeout=60,
    )
    assert ("== alerts: 1 fired / 1 resolved over 5 evaluation(s), "
            "0 flap(s) ==") in proc3.stdout


def test_report_handles_empty_trace(tmp_path):
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"traceEvents": [], "otherData": {}}))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(empty)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "no spans" in proc.stdout


def test_report_rejects_unreadable_file(tmp_path):
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(tmp_path / "missing.json")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    assert "cannot read" in proc.stderr
