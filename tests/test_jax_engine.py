"""JAX engine tests: guided generation with the tiny random-weight model.

The decisive property: even with RANDOM weights, guided decoding must
yield schema-valid JSON for every sequence — the automaton, not the
model, guarantees structure.  This is also the full-system integration
test: BCGSimulation runs end-to-end on the JAX engine.
"""

import dataclasses
import json

import pytest

from bcg_tpu.config import BCGConfig, EngineConfig, GameConfig, MetricsConfig
from bcg_tpu.engine.chat_template import format_chat_prompt
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.engine.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def engine():
    return JaxEngine(EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                                  max_model_len=2048))


VOTE_SCHEMA = {
    "type": "object",
    "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
    "required": ["decision"],
    "additionalProperties": False,
}
# Bounded strings keep random-weight generation inside the token budget
# (a real model closes its strings; a random one rambles to max_tokens).
DECISION_SCHEMA = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string", "minLength": 1, "maxLength": 30},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string", "minLength": 1, "maxLength": 30},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}


class TestMaxNumSeqs:
    @pytest.mark.slow
    def test_oversized_batch_chunks(self, monkeypatch):
        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=1024,
            max_num_seqs=2,
        ))
        calls = []
        orig = engine._decode_batch

        def spy(*a, **k):
            calls.append(len(a[0]))
            return orig(*a, **k)

        monkeypatch.setattr(engine, "_decode_batch", spy)
        prompts = [("sys", f"user {i}", VOTE_SCHEMA) for i in range(5)]
        out = engine.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        assert len(out) == 5
        assert all(o.get("decision") in ("stop", "continue") for o in out)
        assert len(calls) == 3  # ceil(5 / 2) chunks
        assert all(c <= 2 for c in calls)
        engine.shutdown()


class TestHbmProvisioner:
    """hbm_utilization as an actual row provisioner (the reference's
    gpu_memory_utilization provisions the vLLM KV pool)."""

    def _engine(self):
        return JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=512,
        ))

    def test_no_cap_when_limits_unknown_or_batch_fits(self):
        engine = self._engine()
        parts = [("sys ", "", f"user {i}") for i in range(4)]
        # CPU: no device memory limit -> no derived cap.
        engine._mem_limit = None
        assert engine._provisioned_row_cap(parts, [24] * 4) is None
        # Huge limit: batch fits -> no cap (and no chunk event).
        engine._mem_limit = 1 << 40
        assert engine._provisioned_row_cap(parts, [24] * 4) is None
        assert engine.provision_chunk_events == 0
        engine.shutdown()

    @pytest.mark.slow
    def test_oversized_batch_chunks_under_tight_limit(self, monkeypatch):
        engine = self._engine()
        parts = [("sys ", "", f"user {i}") for i in range(4)]
        # Tight limit: per-row cache bytes at these shapes are ~100 KB;
        # allow roughly two rows' worth above the (tiny) weights.
        per_row = 600 * engine.spec.num_kv_heads * engine.spec.head_dim \
            * 4 * engine.spec.num_layers
        engine._mem_limit = int(
            (engine._param_bytes + 2.5 * per_row)
            / engine.config.hbm_utilization
        )
        cap = engine._provisioned_row_cap(parts, [24] * 4)
        assert cap is not None and 1 <= cap < 4
        # The chunk-event counter bumps when the cap actually splits a
        # batch (in _run_guided), not when the cap is merely derived.
        assert engine.provision_chunk_events == 0
        # End to end: the oversized batch still answers every row.
        calls = []
        orig = engine._decode_batch

        def spy(*a, **k):
            calls.append(len(a[0]))
            return orig(*a, **k)

        monkeypatch.setattr(engine, "_decode_batch", spy)
        prompts = [("sys ", f"user {i}", VOTE_SCHEMA) for i in range(4)]
        out = engine.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        assert len(out) == 4
        assert all(o.get("decision") in ("stop", "continue") for o in out)
        assert all(c <= cap for c in calls)
        assert len(calls) >= 2
        assert engine.provision_chunk_events >= 1, \
            "the provisioner-forced split must be counted"
        engine.shutdown()


class TestChatTemplate:
    def test_qwen3_no_think(self):
        p = format_chat_prompt("Qwen/Qwen3-14B", "sys", "user")
        assert "<|im_start|>system\nsys<|im_end|>" in p
        assert "user /no_think<|im_end|>" in p
        assert p.endswith("<|im_start|>assistant\n")

    def test_qwen3_instruct_2507_no_soft_switch(self):
        p = format_chat_prompt("Qwen/Qwen3-4B-Instruct-2507", "sys", "user")
        assert "/no_think" not in p

    def test_llama3(self):
        p = format_chat_prompt("meta-llama/Meta-Llama-3.1-8B-Instruct", "s", "u")
        assert "<|start_header_id|>assistant<|end_header_id|>" in p

    def test_mistral(self):
        p = format_chat_prompt("mistralai/Mistral-Small-Instruct-2409", "s", "u")
        assert p.startswith("<s>[INST]") and p.endswith("[/INST]")


class TestByteTokenizer:
    def test_roundtrip(self):
        tk = ByteTokenizer()
        ids = tk.encode("hello {}")
        assert tk.decode(ids) == "hello {}"

    def test_token_bytes_layout(self):
        tk = ByteTokenizer(512)
        tb = tk.token_bytes()
        assert len(tb) == 512
        assert tb[65] == b"A"
        assert tb[tk.eos_id] == b""


class TestGuidedGeneration:
    def test_vote_batch_valid_json(self, engine):
        prompts = [("you vote", f"agent {i}: stop or continue?", VOTE_SCHEMA) for i in range(3)]
        results = engine.batch_generate_json(prompts, temperature=0.7, max_tokens=48)
        assert len(results) == 3
        for r in results:
            assert r.get("decision") in ("stop", "continue"), r

    def test_decision_schema_with_random_weights(self, engine):
        results = engine.batch_generate_json(
            [("sys", "round 1", DECISION_SCHEMA)], temperature=0.9, max_tokens=220
        )
        r = results[0]
        assert "error" not in r, r
        assert isinstance(r["value"], int) and 0 <= r["value"] <= 50
        assert isinstance(r["internal_strategy"], str)

    def test_heterogeneous_schemas_one_batch(self, engine):
        byz = {
            "type": "object",
            "properties": {"decision": {"type": "string",
                                        "enum": ["stop", "continue", "abstain"]}},
            "required": ["decision"],
            "additionalProperties": False,
        }
        results = engine.batch_generate_json(
            [("s", "u", VOTE_SCHEMA), ("s", "u", byz), ("s", "u", VOTE_SCHEMA)],
            temperature=0.8, max_tokens=48,
        )
        assert results[0]["decision"] in ("stop", "continue")
        assert results[1]["decision"] in ("stop", "continue", "abstain")

    def test_greedy_is_deterministic(self, engine):
        p = [("s", "u", VOTE_SCHEMA)]
        a = engine.batch_generate_json(p, temperature=0.0, max_tokens=48)
        b = engine.batch_generate_json(p, temperature=0.0, max_tokens=48)
        assert a == b

    def test_generate_free_text(self, engine):
        out = engine.generate("hello", temperature=0.5, max_tokens=12)
        assert isinstance(out, str)

    def test_prompt_too_long_reports_error(self):
        eng = JaxEngine(EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                                     max_model_len=160))
        res = eng.batch_generate_json(
            [("s" * 400, "u" * 400, VOTE_SCHEMA)], max_tokens=64
        )
        # Prompt is truncated to fit; generation still succeeds.
        assert res[0].get("decision") in ("stop", "continue") or "error" in res[0]


@pytest.mark.slow
class TestSimulationOnJaxEngine:
    @pytest.mark.parametrize("tp", [1, 2])
    def test_full_game_on_tiny_model(self, tp):
        """Complete BCG game over the JAX engine with random weights:
        guided decoding keeps every response schema-valid, so the game
        must run to a clean termination.  With tp=2 the same serving
        stack — orchestrator batching, guided decoding, prefix caching,
        retry ladder — runs composed over the mesh (round-3 verdict
        missing #3; the reference's TP path is its engine's,
        vllm_agent.py:139-142)."""
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        engine_cfg = EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                                  max_model_len=2048, tensor_parallel_size=tp)
        cfg = BCGConfig(
            game=GameConfig(num_honest=2, num_byzantine=1, max_rounds=2, seed=3),
            engine=engine_cfg,
            metrics=MetricsConfig(save_results=False),
        )
        sim = BCGSimulation(config=cfg)
        if tp > 1:
            assert sim.engine.mesh is not None
            assert sim.engine.mesh.shape.get("tp") == tp
        stats = sim.run()
        assert stats["total_rounds"] >= 1
        assert stats["termination_reason"] in (
            "vote_with_consensus", "vote_without_consensus", "max_rounds",
        )
        # Proposals that were made must be in range.
        for r in stats["rounds_data"]:
            for v in r["honest_values"] + r["byzantine_values"]:
                assert 0 <= v <= 50
        sim.engine.shutdown()


class TestGuaranteedParse:
    """Force-completion: guided output parses even when the budget is far
    too small for the model's rambling (random weights never emit EOS)."""

    def test_unbounded_strings_tiny_budget_still_parse(self, engine):
        schema = {
            "type": "object",
            "properties": {
                "internal_strategy": {"type": "string", "minLength": 3},
                "value": {"type": "integer", "minimum": 0, "maximum": 50},
                "public_reasoning": {"type": "string", "minLength": 10},
            },
            "required": ["internal_strategy", "value", "public_reasoning"],
            "additionalProperties": False,
        }
        # The minimal valid completion is ~69 byte-tokens (object skeleton
        # + minLengths); any budget >= that must yield parseable JSON.
        results = engine.batch_generate_json(
            [("sys", f"user prompt {i}", schema) for i in range(3)],
            temperature=0.9, max_tokens=96,
        )
        for r in results:
            assert "error" not in r, r
            assert isinstance(r["value"], int) and 0 <= r["value"] <= 50
            assert len(r["internal_strategy"]) >= 3
            assert len(r["public_reasoning"]) >= 10

    def test_budget_smaller_than_min_completion_ends_clean(self, engine):
        # Budget 8 can't even finish the object; the sampler walks the
        # completion path from the start and EOSes at the dead end —
        # output may be invalid JSON but decoding must not crash and the
        # engine must return the parse-failure dict, not raise.
        schema = {
            "type": "object",
            "properties": {"a": {"type": "string", "minLength": 40}},
            "required": ["a"],
            "additionalProperties": False,
        }
        out = engine.batch_generate_json(
            [("", "p", schema)], temperature=0.9, max_tokens=8
        )
        assert isinstance(out[0], dict)


@pytest.mark.slow
class TestChunkedPrefill:
    VOTE_SCHEMA = {
        "type": "object",
        "properties": {"d": {"type": "string", "enum": ["stop", "continue"]}},
        "required": ["d"],
        "additionalProperties": False,
    }

    @staticmethod
    def _engine_pair(prefill_chunk: int, prefix_caching: bool):
        """(one-pass engine, chunked engine) over identical configs."""
        import dataclasses

        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        base = EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                            max_model_len=2048, prefix_caching=prefix_caching)
        return JaxEngine(base), JaxEngine(
            dataclasses.replace(base, prefill_chunk=prefill_chunk)
        )

    def _assert_chunked_matches(self, prompts, prefill_chunk, prefix_caching):
        one, chunked = self._engine_pair(prefill_chunk, prefix_caching)
        r_one = one.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        r_chunked = chunked.batch_generate_json(
            prompts, temperature=0.0, max_tokens=24
        )
        assert r_chunked == r_one
        assert all("error" not in r for r in r_one)
        one.shutdown()
        chunked.shutdown()

    def test_chunk_offsets_share_one_compiled_program(self):
        """The single-shape chunk step (prefill_chunk_at) must serve every
        full-width chunk offset from ONE traced program — per-offset
        shapes cost minutes of remote compiles on an 8B boot."""
        import dataclasses

        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=1024, prefix_caching=False, prefill_chunk=64,
        ))
        # ~7 chunks of prompt; all full-width offsets must share a trace.
        prompts = [("sys " * 60, "user prompt " * 25, self.VOTE_SCHEMA)]
        out = engine.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        assert "error" not in out[0]
        traces = engine._prefill_chunk_at._cache_size()
        assert traces <= 2, f"expected <=2 chunk-program traces, got {traces}"
        engine.shutdown()

    def test_chunked_matches_single_pass(self):
        """prefill_chunk slices the full-prompt prefill through the
        prefix-suffix jit; greedy output must be identical to one-pass
        prefill (same KV, same positions, chunk boundaries invisible)."""
        self._assert_chunked_matches(
            [
                ("sys " * 40, "user prompt " * 30, self.VOTE_SCHEMA),  # multi-chunk
                ("other sys " * 25, "short", self.VOTE_SCHEMA),        # ragged lengths
            ],
            prefill_chunk=64, prefix_caching=False,
        )

    def test_chunked_with_prefix_caching_matches(self):
        """The suffix region of a prefix-cached prefill chunks too (each
        chunk extends the cached prefix) — greedy-identical output."""
        self._assert_chunked_matches(
            [("sys " * 60, "user prompt " * 40, self.VOTE_SCHEMA)],
            prefill_chunk=64, prefix_caching=True,
        )

    def test_non_divisor_chunk_matches(self):
        """A chunk size that does not divide the bucketed length (512 %
        100 != 0) leaves a ragged final slice — output must still match
        one-pass exactly."""
        self._assert_chunked_matches(
            [("sys " * 50, "user words " * 25, self.VOTE_SCHEMA)],
            prefill_chunk=100, prefix_caching=False,
        )

    def test_negative_chunk_rejected(self):
        import dataclasses

        import pytest

        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        with pytest.raises(ValueError, match="prefill_chunk"):
            JaxEngine(dataclasses.replace(
                EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test"),
                prefill_chunk=-64,
            ))

    def test_non_bf16_dtype_rejected(self):
        """EngineConfig.dtype exists for serving-config interface parity
        but TPU serving computes in bf16 — other values must be a loud
        error, not a silently ignored knob."""
        with pytest.raises(ValueError, match="bfloat16"):
            JaxEngine(dataclasses.replace(
                EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test"),
                dtype="float32",
            ))


def test_fine_suffix_ladder_config(monkeypatch):
    """EngineConfig.fine_suffix_buckets selects the 1536/3072-rung
    ladder PER ENGINE (opt-in: decode streams allocated suffix slots
    every step, and measured vote suffixes land just past the coarse
    rungs); env BCG_TPU_FINE_SUFFIX=1 is the bench/sweep override."""
    import dataclasses

    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine

    monkeypatch.delenv("BCG_TPU_FINE_SUFFIX", raising=False)
    base = EngineConfig(
        backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=512,
    )
    coarse = JaxEngine(base)
    fine = JaxEngine(
        dataclasses.replace(base, fine_suffix_buckets=True),
        params=coarse.params,
    )
    assert 1536 not in coarse._suffix_buckets
    assert 3072 not in coarse._suffix_buckets
    assert 1536 in fine._suffix_buckets and 3072 in fine._suffix_buckets

    monkeypatch.setenv("BCG_TPU_FINE_SUFFIX", "1")
    via_env = JaxEngine(base, params=coarse.params)
    assert 1536 in via_env._suffix_buckets
    via_env.shutdown()
    fine.shutdown()
    coarse.shutdown()


def test_int8_decode_kernel_kill_switch(monkeypatch):
    """BCG_TPU_DISABLE_INT8_DECODE_KERNEL=1 routes int8-KV decode to the
    dequant fallback (operational escape for a kernel lowering failure;
    scripts/probe_int8_decode.py)."""
    import warnings

    import jax as _jax

    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine

    # tiny-dh128 has the lane-aligned head dim the Pallas gate requires;
    # the monkeypatched backend makes the selection logic believe it is
    # on TPU (construction only — nothing is generated).
    monkeypatch.setattr(_jax, "default_backend", lambda: "tpu")
    # A pre-set ambient kill-switch (the escape hatch's own use case)
    # must not poison the default-path assertion.
    monkeypatch.delenv("BCG_TPU_DISABLE_INT8_DECODE_KERNEL", raising=False)
    cfg = EngineConfig(
        backend="jax", model_name="bcg-tpu/tiny-dh128",
        max_model_len=512, kv_cache_dtype="int8",
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        eng_default = JaxEngine(cfg)
    assert eng_default.decode_attention_impl == "pallas"

    monkeypatch.setenv("BCG_TPU_DISABLE_INT8_DECODE_KERNEL", "1")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        # Weight sharing is valid here: shutdown() nulls .params, so the
        # donor must stay alive until the recipient is constructed.
        eng = JaxEngine(cfg, params=eng_default.params)
    assert eng.decode_attention_impl != "pallas"
    eng.shutdown()
    eng_default.shutdown()


class TestEngineUnderMesh:
    """The FULL engine composed under a mesh (round-3 verdict missing #3).

    The reference's TP path is its engine's, not its game's
    (vllm_agent.py:139-142 boots vLLM with tensor_parallel_size and a
    multiprocess executor); parity demands the same here: JaxEngine
    built with tensor_parallel_size=2 over the virtual 8-device CPU
    mesh, serving batch_generate_json end-to-end — guided DFA gathers,
    prefix-cache assembly, and the jitted decode loop all running over
    sharded params.
    """

    def _engine(self, **kw):
        from bcg_tpu.engine.interface import create_engine

        kw.setdefault("max_model_len", 1024)
        cfg = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", **kw,
        )
        return create_engine(cfg)

    @staticmethod
    def _spy_prefill_sp(eng):
        """Wrap eng._prefill_sp with a call counter (dispatch reads the
        attribute per call, so the wrapper is seen)."""
        calls = []
        orig = eng._prefill_sp
        eng._prefill_sp = lambda *a, **kw: (calls.append(1) or orig(*a, **kw))
        return calls

    def test_params_actually_sharded_tp2(self):
        eng = self._engine(tensor_parallel_size=2)
        assert eng.mesh is not None and eng.mesh.shape["tp"] == 2
        # A column-parallel projection must be split over two devices.
        wq = eng.params["layers"][0]["wq"]
        devs = {s.device for s in wq.addressable_shards}
        assert len(devs) == 2
        shard_shape = wq.addressable_shards[0].data.shape
        assert shard_shape[1] == wq.shape[1] // 2
        eng.shutdown()

    def test_batch_generate_json_tp2_end_to_end(self):
        """Heterogeneous schemas, one batch, greedy, under tp=2: every
        row schema-valid and repeated runs byte-identical.  (No
        cross-engine byte comparison: the TP all-reduce changes float
        reduction order, which flips greedy argmax on the near-ties
        random weights produce — and once any token diverges, every
        later token is conditioned on a different prefix.  Schema
        validity is the automaton's guarantee, the property that must
        survive sharding.)"""
        eng_tp = self._engine(tensor_parallel_size=2)
        prompts = [
            ("You are honest.", "Pick a value.", DECISION_SCHEMA),
            ("You vote.", "Stop or continue?", VOTE_SCHEMA),
            ("You are honest.", "Pick another value.", DECISION_SCHEMA),
        ]
        out_tp = eng_tp.batch_generate_json(prompts, temperature=0.0, max_tokens=96)
        out_tp2 = eng_tp.batch_generate_json(prompts, temperature=0.0, max_tokens=96)
        for o in out_tp:
            assert "error" not in o, o
        assert out_tp == out_tp2  # deterministic under the mesh
        assert out_tp[1]["decision"] in ("stop", "continue")
        assert 0 <= out_tp[0]["value"] <= 50
        assert 0 <= out_tp[2]["value"] <= 50
        eng_tp.shutdown()

    def test_quant_scan_tp_sp_full_composition(self):
        """The widest serving composition in one engine: int4 weights x
        scan-over-layers x tp=2 x sp=2 — the 32B-preset pod-slice layout
        WITH long context (ring prefill + sp-sharded decode inside the
        lax.scan layer loop).  Every triple is covered elsewhere; the
        quadruple is what a 32B long-context deployment actually boots."""
        eng = self._engine(
            tensor_parallel_size=2, sequence_parallel_size=2,
            quantization="int4", scan_layers=True, prefix_caching=False,
        )
        assert eng.mesh.shape["tp"] == 2 and eng.mesh.shape["sp"] == 2
        calls = self._spy_prefill_sp(eng)
        out = eng.batch_generate_json(
            [("You are honest.", "Pick a value.", DECISION_SCHEMA),
             ("You vote.", "Stop or continue?", VOTE_SCHEMA)],
            temperature=0.0, max_tokens=96,
        )
        assert calls and eng._decode_ring_active and eng.sp_bypasses == 0
        for o in out:
            assert "error" not in o, o
        assert 0 <= out[0]["value"] <= 50
        assert out[1]["decision"] in ("stop", "continue")
        eng.shutdown()

    @pytest.mark.slow
    def test_maximal_composition_dp_tp_sp_quant_scan_int8kv(self):
        """Every serving axis at once on the full 8-device virtual mesh:
        int4 weights x int8 KV cache x scan-over-layers x dp=2 x tp=2 x
        sp=2.  The quantized cache tree-shards over all three axes
        (kv_cache_tree_sharding), batches dp-align and dp-place, ring
        prefill + sp decode run inside the scan loop over physically
        tp-split int4 leaves — the widest configuration any pod-slice
        deployment of the 14B/32B presets would boot."""
        eng = self._engine(
            data_parallel_size=2, tensor_parallel_size=2,
            sequence_parallel_size=2, quantization="int4",
            kv_cache_dtype="int8", scan_layers=True, prefix_caching=False,
        )
        assert eng.mesh.shape == {"dp": 2, "tp": 2, "sp": 2}
        out = eng.batch_generate_json(
            [("You are honest.", "Pick a value.", DECISION_SCHEMA),
             ("You vote.", "Stop or continue?", VOTE_SCHEMA)],
            temperature=0.0, max_tokens=96,
        )
        assert eng.dp_batches >= 1 and eng.dp_bypasses == 0
        assert eng.sp_bypasses == 0
        for o in out:
            assert "error" not in o, o
        assert 0 <= out[0]["value"] <= 50
        assert out[1]["decision"] in ("stop", "continue")
        eng.shutdown()

    @pytest.mark.parametrize("quant", ["int8", "int4"])
    def test_quantized_scan_tp2_end_to_end(self, quant):
        """The pod-slice serving configuration for the reference's
        large presets (8B: int8 + scan + tp; 14B/32B: int4 + scan + tp —
        config.py:20-25 presets served at vllm_agent.py:139-142 with
        tensor_parallel_size>1): quantized stacked weight trees sharded
        over a tp mesh, serving guided JSON through the full engine.
        Each pairwise composition is covered elsewhere; this is the
        triple the real large-model boot actually runs."""
        eng = self._engine(
            tensor_parallel_size=2, quantization=quant, scan_layers=True,
        )
        assert eng.mesh is not None and eng.mesh.shape["tp"] == 2
        # The stacked quantized projection must be physically split over
        # two devices (axis 0 of each leaf is the layer stack).
        wq = eng.params["layers"]["wq"]
        q = wq["q4"] if quant == "int4" else wq["q"]
        assert q.shape[0] == eng.spec.num_layers  # stacked for lax.scan
        assert len({s.device for s in q.addressable_shards}) == 2
        out = eng.batch_generate_json(
            [("You are honest.", "Pick a value.", DECISION_SCHEMA),
             ("You vote.", "Stop or continue?", VOTE_SCHEMA)],
            temperature=0.0, max_tokens=96,
        )
        for o in out:
            assert "error" not in o, o
        assert 0 <= out[0]["value"] <= 50
        assert out[1]["decision"] in ("stop", "continue")
        eng.shutdown()

    def test_sequence_parallel_prefill_end_to_end(self):
        """sequence_parallel_size=2: the engine's full-prompt prefill
        dispatches to the ring-attention path (transformer.prefill_sp)
        and the game-facing contract — schema-valid guided JSON — holds.
        Long-context SP is an ENGINE capability, not just an op."""
        eng = self._engine(sequence_parallel_size=2, prefix_caching=False)
        assert eng._prefill_sp is not None and eng._sp_devices == 2
        calls = self._spy_prefill_sp(eng)
        out = eng.batch_generate_json(
            [("You are honest.", "Pick a value.", DECISION_SCHEMA),
             ("You vote.", "Stop or continue?", VOTE_SCHEMA)],
            temperature=0.0, max_tokens=96,
        )
        assert calls, "ring prefill path was never taken"
        # Decode ran over the sp-sharded cache (sp_decode_attention
        # inside the jitted loop), not a replicated one.
        assert eng._decode_ring_active
        for o in out:
            assert "error" not in o, o
        assert 0 <= out[0]["value"] <= 50
        assert out[1]["decision"] in ("stop", "continue")
        eng.shutdown()

    def test_sequence_parallel_fast_forward_decode(self):
        """The fast-forward loop (the bench-default decode path) also
        keeps its bf16 cache sp-sharded (sp_chunk_decode_attention)."""
        eng = self._engine(sequence_parallel_size=2, prefix_caching=False,
                           decode_fast_forward=True)
        out = eng.batch_generate_json(
            [("You are honest.", "Pick a value.", DECISION_SCHEMA),
             ("You vote.", "Stop or continue?", VOTE_SCHEMA)],
            temperature=0.0, max_tokens=96,
        )
        assert eng._decode_ring_active
        assert eng.sp_bypasses == 0
        for o in out:
            assert "error" not in o, o
        assert out[1]["decision"] in ("stop", "continue")
        # Same schema-valid result twice: deterministic under the mesh.
        assert out == eng.batch_generate_json(
            [("You are honest.", "Pick a value.", DECISION_SCHEMA),
             ("You vote.", "Stop or continue?", VOTE_SCHEMA)],
            temperature=0.0, max_tokens=96,
        )
        eng.shutdown()

    def test_sequence_parallel_speculative_decode(self):
        """The speculative loop keeps the cache sp-sharded too: its
        verify chunk goes through sp_chunk_decode_attention with
        PER-ROW scatter writes into the sharded cache, and its greedy
        output matches the plain loop's under the same mesh."""
        eng = self._engine(sequence_parallel_size=2, prefix_caching=False,
                           spec_decode=True)
        plain = self._engine(sequence_parallel_size=2, prefix_caching=False)
        prompts = [
            ("You are honest.", "Pick a value.", DECISION_SCHEMA),
            ("You vote.", "Stop or continue?", VOTE_SCHEMA),
        ]
        out = eng.batch_generate_json(prompts, temperature=0.0, max_tokens=96)
        n_spec = eng.last_decode_steps
        assert eng._decode_ring_active
        assert eng.sp_bypasses == 0
        ref = plain.batch_generate_json(prompts, temperature=0.0, max_tokens=96)
        assert out == ref
        assert n_spec < plain.last_decode_steps
        eng.shutdown()
        plain.shutdown()

    @pytest.mark.slow
    def test_long_context_serving_via_sp(self):
        """An ~8K-byte-token prompt served end-to-end under sp=4: ring
        prefill shards the long prompt's activations, decode attends the
        long sp-sharded cache — the long-context capability claim (the
        reference TRUNCATES at this scale, SURVEY §5.7) exercised as one
        serving call, not just op tests.  The prompt deliberately
        exceeds the window limit so L clamps to max_model_len - budget
        - 1 = 8095 — the sp-indivisible shape that once bypassed the
        ring path (the engine now sp-aligns the window)."""
        eng = self._engine(sequence_parallel_size=4, prefix_caching=False,
                           max_model_len=8192)
        calls = self._spy_prefill_sp(eng)
        long_history = " ".join(
            f"Round {i}: agent_{i % 10} proposed {i % 50}." for i in range(260)
        )
        out = eng.batch_generate_json(
            [("You are honest.", long_history + " Pick a value.",
              DECISION_SCHEMA)],
            temperature=0.0, max_tokens=96,
        )
        assert calls, "long prompt did not take the ring prefill path"
        assert eng._decode_ring_active
        assert eng.sp_bypasses == 0  # window clamp stayed sp-aligned
        assert "error" not in out[0], out[0]
        assert 0 <= out[0]["value"] <= 50
        # Pin the clamp scenario: the tokenized prompt must exceed every
        # ladder bucket, or this test degrades to an already-divisible
        # bucket and stops covering the alignment fix.
        assert len(eng.tokenizer.encode(long_history)) > 6144
        eng.shutdown()

    @pytest.mark.parametrize("ff", [False, True])
    def test_sequence_parallel_int8_kv_decode(self, ff):
        """int8 KV cache under sp=2: the decode loops shard the
        quantized cache and dequantize per-slice — no bypass."""
        eng = self._engine(sequence_parallel_size=2, prefix_caching=False,
                           kv_cache_dtype="int8", decode_fast_forward=ff)
        out = eng.batch_generate_json(
            [("You vote.", "Stop or continue?", VOTE_SCHEMA)],
            temperature=0.0, max_tokens=64,
        )
        assert eng._decode_ring_active
        assert eng.sp_bypasses == 0
        assert "error" not in out[0], out[0]
        assert out[0]["decision"] in ("stop", "continue")
        eng.shutdown()

    def test_chunked_prefill_runs_sp_sharded(self):
        """prefill_chunk and sequence_parallel_size compose: the large
        size class DEFAULTS to chunked prefill, so sp must shard the
        chunk path (transformer.prefill_chunk_at ring branch), not
        bypass it — and the output must match the unchunked sp engine."""
        eng = self._engine(sequence_parallel_size=2, prefix_caching=False,
                           prefill_chunk=64)
        prompts = [("You are honest.", "Pick a value. " * 20,
                    DECISION_SCHEMA)]
        out = eng.batch_generate_json(prompts, temperature=0.0, max_tokens=96)
        assert "error" not in out[0], out[0]
        assert eng.sp_bypasses == 0
        # Deterministic per config; schema-valid.  (No byte comparison
        # against the unchunked sp engine: per-chunk partial-softmax
        # merges change bf16 reduction order, which flips greedy argmax
        # on random-weight near-ties — the same caveat as the tp tests.
        # The plain path's chunked==one-pass identity is covered by
        # test_chunked_matches_single_pass.)
        assert out == eng.batch_generate_json(
            prompts, temperature=0.0, max_tokens=96
        )
        assert 0 <= out[0]["value"] <= 50
        eng.shutdown()

    def test_cached_prefix_prefill_runs_sp_sharded(self):
        """Prefix caching composes with sp: the suffix serves as ONE
        chunk against the cached prefix through the ring-capable chunk
        jit — no sp path remains that bypasses sharding."""
        eng = self._engine(sequence_parallel_size=2, prefix_caching=True)
        prompts = [("You are honest.", "Pick a value.", DECISION_SCHEMA)]
        out = eng.batch_generate_json(prompts, temperature=0.0, max_tokens=96)
        assert "error" not in out[0], out[0]
        # Non-vacuous: tiny-test's template family IS prefix-split-safe,
        # so the prefix path engaged — and it must not have bypassed sp.
        assert eng._prefix_safe
        assert eng.prefix_fallbacks == 0
        assert eng.sp_bypasses == 0
        assert 0 <= out[0]["value"] <= 50
        # Deterministic on the warm prefix cache too.
        assert out == eng.batch_generate_json(
            prompts, temperature=0.0, max_tokens=96
        )
        eng.shutdown()

    def test_near_cap_clamp_prefix_sp_aligns_up(self):
        """A system prefix that only fits the UNALIGNED clamp rung
        (limit - 64, with no ladder rung left below the limit) must be
        cached at the next sp multiple UP — padded entry, not the
        counted replicated fallback.  Closes the last off-ladder bypass
        class by construction (VERDICT r4 #4)."""
        from bcg_tpu.engine.chat_template import format_chat_parts

        eng = self._engine(sequence_parallel_size=4, prefix_caching=True,
                           max_model_len=1024)
        # ByteTokenizer: 1 ASCII char = 1 token.  limit = 1024-96-1 =
        # 927; clamp = 863; sp=4 aligns down to 860 — a prefix of 862
        # tokens fits ONLY the unaligned clamp, forcing the align-UP
        # rung (864).
        probe, _ = format_chat_parts(
            "bcg-tpu/tiny-test", "", "u", eng.config.disable_qwen3_thinking)
        overhead = len(eng.tokenizer.encode(probe))
        system = "R" * (862 - overhead)
        prefix, _ = format_chat_parts(
            "bcg-tpu/tiny-test", system, "u", eng.config.disable_qwen3_thinking)
        assert len(eng.tokenizer.encode(prefix)) == 862
        out = eng.batch_generate_json(
            [(system, "Pick a value.", DECISION_SCHEMA)],
            temperature=0.0, max_tokens=96,
        )
        assert "error" not in out[0], out[0]
        assert eng.sp_bypasses == 0
        assert eng.prefix_fallbacks == 0
        buckets = [b for (_p, b) in eng._prefix_cache]
        assert buckets and all(b % 4 == 0 for b in buckets)
        assert any(b >= 862 for b in buckets)
        eng.shutdown()

    def test_randomized_prompt_length_sweep_no_bypasses(self):
        """Seeded random prompt lengths spanning ladder rungs plus the
        near-cap clamp region: NO reachable shape may bypass sp —
        the flipped all-shapes assertion from VERDICT r4 #4."""
        import numpy as np

        eng = self._engine(sequence_parallel_size=2, prefix_caching=True,
                           max_model_len=1024)
        rng = np.random.RandomState(42)
        # Two random in-ladder lengths (cheap: shared bucket compiles)
        # plus both sides of the clamp boundary at limit-64 = 863.
        lengths = sorted(set(
            [int(x) for x in rng.randint(40, 700, size=2)] + [861, 863]
        ))
        for n in lengths:
            system = "R" * n
            out = eng.batch_generate_json(
                [(system, "Pick a value.", DECISION_SCHEMA)],
                temperature=0.0, max_tokens=96,
            )
            assert "error" not in out[0], (n, out[0])
        assert eng.sp_bypasses == 0, f"bypass at one of {lengths}"
        eng.shutdown()

    def test_shared_core_rows_under_sp(self):
        """(system, (core, tail)) rows with sp=2: the two-level core
        entry build routes through the ring-capable chunk jit
        (_get_core_entry), and serving stays schema-valid and
        deterministic with zero sp bypasses."""
        eng = self._engine(sequence_parallel_size=2)
        system = "You are an honest agent voting. " + "Rules. " * 30
        core = "=== PROPOSALS ===\n  agent_0: 5\n  agent_1: 5\n" * 4
        rows = [(system, (core, f"\n\nYou are agent_{i}. Decide now."),
                 VOTE_SCHEMA) for i in range(2)]
        out = eng.batch_generate_json(rows, temperature=0.0, max_tokens=48)
        assert all(r.get("decision") in ("stop", "continue") for r in out)
        assert eng.sp_bypasses == 0
        assert [k for k, _b in eng._prefix_cache if "\x1e" in k], \
            "core entry never built - the sp core path was not exercised"
        assert out == eng.batch_generate_json(
            rows, temperature=0.0, max_tokens=48
        )
        eng.shutdown()

    def test_batch_generate_json_dp2_tp2(self):
        """Composed dp x tp mesh: batch rows shard over dp while weights
        shard over tp — the one-agent-per-device scale-out layout."""
        eng = self._engine(tensor_parallel_size=2, data_parallel_size=2)
        prompts = [
            ("sys", f"user {i}", VOTE_SCHEMA if i % 2 else DECISION_SCHEMA)
            for i in range(4)
        ]
        out = eng.batch_generate_json(prompts, temperature=0.0, max_tokens=96)
        assert len(out) == 4
        for i, o in enumerate(out):
            assert "error" not in o, (i, o)
            if i % 2:
                assert o["decision"] in ("stop", "continue")
            else:
                assert 0 <= o["value"] <= 50
        eng.shutdown()



def test_spmd_exchange_composes_with_engine_mesh():
    """Real serving engine (tp=2 mesh) + SPMD collective exchange (dp
    mesh) in ONE simulation: two meshes over the same devices, the
    layout a one-agent-per-chip sweep with a TP-sharded model uses.
    Previously covered only separately (dryrun stages 7/8)."""
    import dataclasses

    from bcg_tpu.runtime.orchestrator import BCGSimulation

    base = BCGConfig()
    cfg = dataclasses.replace(
        base,
        game=GameConfig(num_honest=3, num_byzantine=1, max_rounds=2, seed=7),
        network=dataclasses.replace(base.network, spmd_exchange=True),
        engine=EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                            max_model_len=2048, tensor_parallel_size=2),
        metrics=MetricsConfig(save_results=False),
    )
    sim = BCGSimulation(config=cfg)
    stats = sim.run()
    assert stats["total_rounds"] >= 1
    assert sim._spmd_mesh is not None and sim._spmd_mesh.shape["dp"] == 4
    assert sim.engine.mesh is not None and sim.engine.mesh.shape["tp"] == 2
    sim.engine.shutdown()
