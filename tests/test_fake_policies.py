"""Scripted fake-engine policies (engine/fake.py).

The fake backend's policy set is a seeded, LLM-free fault-model axis:
role-aware mixes ("mixed:<honest>:<byzantine>") script the adversary
while honest agents play a convergence dynamic — the reference's only
fault model is the LLM itself, so none of this is reproducible there.
"""

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.config import BCGConfig, EngineConfig
from bcg_tpu.engine.fake import FakeEngine

HONEST_DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string"},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string"},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}
BYZ_DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string"},
        "value": {"anyOf": [{"type": "integer", "minimum": 0, "maximum": 50},
                            {"const": "abstain"}]},
        "public_reasoning": {"type": "string"},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}
HONEST_VOTE = {
    "type": "object",
    "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
    "required": ["decision"], "additionalProperties": False,
}
BYZ_VOTE = {
    "type": "object",
    "properties": {"decision": {"type": "string",
                                "enum": ["stop", "continue", "abstain"]}},
    "required": ["decision"], "additionalProperties": False,
}

PROMPT = ("Round 2 of 10.\nYour current value: 30\n"
          "agent_0 value: 10\nagent_1 value: 10\nagent_2 value: 40\n")


class TestPolicyUnits:
    def test_mixed_dispatch_by_schema_shape(self):
        eng = FakeEngine(policy="mixed:stubborn:silent")
        assert eng._policy_for(HONEST_DECISION) == "stubborn"
        assert eng._policy_for(BYZ_DECISION) == "silent"
        assert eng._policy_for(HONEST_VOTE) == "stubborn"
        assert eng._policy_for(BYZ_VOTE) == "silent"

    def test_malformed_or_typo_policy_raises_at_construction(self):
        """A typo'd policy must fail at config time, not silently run
        the consensus branch (review finding)."""
        with pytest.raises(ValueError, match="mixed:"):
            FakeEngine(policy="mixed:only_one")
        with pytest.raises(ValueError, match="unknown fake policy"):
            FakeEngine(policy="oscilate")  # the one-letter typo
        with pytest.raises(ValueError, match="mixed:"):
            FakeEngine(policy="mixed:consensus:oscilate")

    def test_oscillate_uses_current_round_header(self):
        """Real prompts carry an uppercase '=== ROUND N ===' header and
        LOWER-case history lines for earlier rounds; parity must come
        from the current round (the max), not stale history."""
        eng = FakeEngine(policy="oscillate")
        real_shape = ("=== ROUND 2 ===\nYour current value: 30\n"
                      "PREVIOUS ROUNDS:\nRound 1: agent_0 value: 10\n")
        assert eng.generate_json(real_shape, BYZ_DECISION)["value"] == 50
        real_shape3 = real_shape.replace("ROUND 2", "ROUND 3")
        assert eng.generate_json(real_shape3, BYZ_DECISION)["value"] == 0

    def test_stubborn_keeps_current_value(self):
        eng = FakeEngine(policy="stubborn")
        out = eng.generate_json(PROMPT, HONEST_DECISION)
        assert out["value"] == 30

    def test_stubborn_clamps_out_of_range_current_value(self):
        """A 'Your current value' line outside [lo, hi] must not be
        echoed as a schema-violating emission (advisor finding)."""
        eng = FakeEngine(policy="stubborn")
        out = eng.generate_json(
            PROMPT.replace("Your current value: 30",
                           "Your current value: 999"),
            HONEST_DECISION,
        )
        assert out["value"] == 50  # clamped to the schema maximum

    def test_median_proposes_order_statistic(self):
        eng = FakeEngine(policy="median")
        out = eng.generate_json(PROMPT, HONEST_DECISION)
        assert out["value"] == 10  # sorted [10, 10, 40] -> middle

    def test_oscillate_alternates_by_round_parity(self):
        eng = FakeEngine(policy="oscillate")
        even = eng.generate_json(PROMPT, BYZ_DECISION)  # Round 2
        odd = eng.generate_json(PROMPT.replace("Round 2", "Round 3"), BYZ_DECISION)
        assert {even["value"], odd["value"]} == {0, 50}
        assert eng.generate_json(PROMPT, BYZ_VOTE)["decision"] == "continue"

    def test_mimic_joins_mode_and_votes_stop(self):
        eng = FakeEngine(policy="mimic")
        out = eng.generate_json(PROMPT, BYZ_DECISION)
        assert out["value"] == 10  # the observed mode
        assert eng.generate_json(PROMPT, BYZ_VOTE)["decision"] == "stop"

    def test_silent_abstains_everywhere_allowed(self):
        eng = FakeEngine(policy="silent")
        assert eng.generate_json(PROMPT, BYZ_DECISION)["value"] == "abstain"
        assert eng.generate_json(PROMPT, BYZ_VOTE)["decision"] == "abstain"
        # Honest-shaped schemas cannot abstain: degrade to the bound.
        assert eng.generate_json(PROMPT, HONEST_DECISION)["value"] == 0


class TestPolicyGames:
    def _run(self, policy, honest=4, byz=0, rounds=6, seed=0):
        import dataclasses

        cfg = dataclasses.replace(
            BCGConfig(), engine=EngineConfig(backend="fake", fake_policy=policy),
        )
        return run_simulation(
            n_agents=honest + byz, byzantine_count=byz, max_rounds=rounds,
            backend="fake", seed=seed, config=cfg,
        )["metrics"]

    def test_stubborn_honest_never_converge(self):
        m = self._run("stubborn")
        assert not m["consensus_reached"]
        assert m["termination_reason"] in ("max_rounds", "vote_without_consensus")

    def test_consensus_still_converges(self):
        m = self._run("consensus")
        assert m["consensus_reached"]

    def test_mixed_silent_byzantine_never_infiltrates(self):
        m = self._run("mixed:consensus:silent", honest=6, byz=2)
        assert all(v is None for v in m["byzantine_final_values"])
        assert (m["byzantine_infiltration"] or 0) == 0

    def test_mixed_oscillate_byzantine_proposes_extremes(self):
        m = self._run("mixed:consensus:oscillate", honest=6, byz=2, rounds=4)
        observed = {v for v in m["byzantine_final_values"] if v is not None}
        assert observed <= {0, 50}

    def test_mixed_mimic_joins_consensus_value(self):
        m = self._run("mixed:consensus:mimic", honest=6, byz=2)
        if m["consensus_reached"]:
            assert all(
                v == m["consensus_value"] for v in m["byzantine_final_values"]
            )
