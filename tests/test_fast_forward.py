"""Forced-chain fast-forward decoding (engine _get_ff_decode_loop +
models decode_chunk + guided _forced_chains).

The decisive property: with greedy sampling, fast-forward output is
IDENTICAL to the standard loop's — forced tokens carry no sampling
freedom, so riding them through one weight pass must not change anything
observable.  Plus: chain-table correctness against a hand-walked DFA and
iteration counts actually dropping on skeleton-heavy schemas.
"""

import dataclasses

import numpy as np
import pytest

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.guided.processor import FF_CHUNK, GuidedBatch, _forced_chains, compile_schema

VOTE = {
    "type": "object",
    "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
    "required": ["decision"],
    "additionalProperties": False,
}
DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string", "minLength": 1, "maxLength": 25},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string", "minLength": 1, "maxLength": 25},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}


class TestForcedChains:
    def test_chains_follow_single_token_states(self):
        import jax

        jax.config.update("jax_platforms", "cpu")
        tb = [bytes([i]) for i in range(256)]
        guide = compile_schema(VOTE, tb, vocab_id=99)
        td = guide.token_dfa
        ct, cl, cn = _forced_chains(td.transitions, td.accepting)
        S = td.num_states
        for s in range(S):
            allowed = np.nonzero(td.transitions[s] >= 0)[0]
            if len(allowed) == 1 and not td.accepting[s]:
                assert cl[s] >= 1
                # Walking the chain through the DFA reproduces chain_next.
                cur = s
                for j in range(cl[s]):
                    nxt = td.transitions[cur, ct[s, j]]
                    assert nxt >= 0
                    cur = nxt
                assert cur == cn[s]
            else:
                assert cl[s] == 0 and cn[s] == s

    def test_vote_schema_is_skeleton_heavy(self):
        """For an enum-only schema nearly every byte is forced, so chains
        should cover most states."""
        tb = [bytes([i]) for i in range(256)]
        td = compile_schema(VOTE, tb, vocab_id=98).token_dfa
        _, cl, _ = _forced_chains(td.transitions, td.accepting)
        forced_states = ((td.transitions >= 0).sum(axis=1) == 1) & ~td.accepting
        assert forced_states.sum() > td.num_states * 0.5
        assert cl.max() == FF_CHUNK - 1


def _engines():
    base = EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                        max_model_len=2048)
    return (
        JaxEngine(base),
        JaxEngine(dataclasses.replace(base, decode_fast_forward=True)),
    )


@pytest.mark.slow
class TestGreedyEquivalence:
    def test_vote_and_decision_outputs_identical(self):
        std, ff = _engines()
        prompts = [
            ("honest system", "vote on round 3", VOTE),
            ("byzantine system", "decide round 3", DECISION),
        ]
        r_std = std.batch_generate_json(prompts, temperature=0.0, max_tokens=60)
        r_ff = ff.batch_generate_json(prompts, temperature=0.0, max_tokens=60)
        assert r_ff == r_std
        std.shutdown()
        ff.shutdown()

    def test_capacity_guard_tight_budget_identical(self):
        """With a budget barely above the schema's minimum completion, the
        compacted-write capacity guard must kick in (chains disabled late
        in the generation) without changing the greedy output: a forced
        state has exactly one legal token either way."""
        std, ff = _engines()
        # The guided sampler guarantees parseability, so any budget the
        # standard loop can complete in, fast-forward must match exactly.
        for max_tokens in (24, 30, 40):
            r_std = std.batch_generate_json(
                [("s", "vote", VOTE)], temperature=0.0, max_tokens=max_tokens
            )
            n_std = std.last_decode_steps
            r_ff = ff.batch_generate_json(
                [("s", "vote", VOTE)], temperature=0.0, max_tokens=max_tokens
            )
            assert r_ff == r_std
            assert "error" not in r_std[0]
            # Chains must be ACTIVE overall (fewer weight passes than the
            # standard loop) — a broken always-off guard would pass the
            # equality check while silently erasing the fast-forward win.
            assert ff.last_decode_steps < n_std, (ff.last_decode_steps, n_std)
        std.shutdown()
        ff.shutdown()

    def test_capacity_guard_fires_and_degrades_safely(self, monkeypatch):
        """Force the guard by shrinking the allocated tail to the bare
        single-advance minimum: chains must switch off (weight passes rise
        to ~the standard loop's count), the compacted writes must stay in
        bounds, and the greedy output must be unchanged."""
        import bcg_tpu.engine.jax_engine as je

        std, _ = _engines()
        r_std = std.batch_generate_json(
            [("s", "vote", VOTE)], temperature=0.0, max_tokens=40
        )
        n_std = std.last_decode_steps
        std.shutdown()

        # tail = max_new + 2K makes room_ok's bound i+1, so chains die
        # after the first iteration.
        monkeypatch.setattr(
            je, "_ff_decode_slots", lambda max_new: max_new + 2 * FF_CHUNK
        )
        ff = JaxEngine(dataclasses.replace(
            EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                         max_model_len=2048),
            decode_fast_forward=True,
        ))
        r_ff = ff.batch_generate_json(
            [("s", "vote", VOTE)], temperature=0.0, max_tokens=40
        )
        assert r_ff == r_std
        # Nearly every iteration degraded to a single-token advance.
        assert ff.last_decode_steps >= n_std - FF_CHUNK, (
            ff.last_decode_steps, n_std)
        ff.shutdown()

    def test_budget_respected_and_clean_parse(self):
        ff = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048, decode_fast_forward=True,
        ))
        out = ff.batch_generate_json(
            [("s", "u", DECISION)], temperature=0.8, max_tokens=80
        )[0]
        assert "error" not in out
        assert isinstance(out.get("value"), int)
        ff.shutdown()

    def test_int8_kv_composes(self):
        """Fast-forward over an int8 KV cache (off-TPU this exercises the
        full-dequant fallback in _block_chunk) must produce the same
        greedy output as the standard int8-KV loop — the quantization
        error is identical because both attend the same stored cache."""
        base = EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                            max_model_len=2048, kv_cache_dtype="int8")
        with pytest.warns(UserWarning, match="int8"):
            std = JaxEngine(base)
        with pytest.warns(UserWarning, match="int8"):
            ff = JaxEngine(
                dataclasses.replace(base, decode_fast_forward=True)
            )
        prompts = [
            ("honest system", "vote on round 3", VOTE),
            ("byzantine system", "decide round 3", DECISION),
        ]
        r_std = std.batch_generate_json(prompts, temperature=0.0, max_tokens=60)
        r_ff = ff.batch_generate_json(prompts, temperature=0.0, max_tokens=60)
        assert r_ff == r_std
        assert all("error" not in r for r in r_std)
        std.shutdown()
        ff.shutdown()


class TestCompactJson:
    def test_compact_output_has_no_interstitial_whitespace(self):
        import json as _json

        ff = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048,
            decode_fast_forward=True, guided_compact_json=True,
        ))
        texts = ff._run_guided(
            [("s ", "", "vote"), ("s ", "", "decide")], [VOTE, DECISION],
            temperature=0.7, max_tokens=120,
        )
        for t in texts:
            obj = _json.loads(t)
            # Exactly compact serialization (spaces INSIDE string content
            # are preserved by dumps, so strict equality is correct).
            assert t == _json.dumps(obj, separators=(",", ":"))
        ff.shutdown()

    def test_compact_shortens_votes_and_extends_chains(self):
        import numpy as np

        tb = [bytes([i]) for i in range(256)]
        loose = compile_schema(VOTE, tb, vocab_id=97, compact=False)
        tight = compile_schema(VOTE, tb, vocab_id=97, compact=True)
        # Compact automaton is strictly smaller and its forced chains
        # cover a larger share of states.
        assert tight.token_dfa.num_states < loose.token_dfa.num_states
        _, cl_l, _ = _forced_chains(
            loose.token_dfa.transitions, loose.token_dfa.accepting)
        _, cl_t, _ = _forced_chains(
            tight.token_dfa.transitions, tight.token_dfa.accepting)
        assert (cl_t > 0).mean() >= (cl_l > 0).mean()
