"""Hermetic perf gate (scripts/perf_gate.py + perf_baseline.json) in
tier-1.

The gate's contract, asserted here:

* green at HEAD — both CPU scenarios (FakeEngine serving, tiny real
  engine) measure inside every baseline band;
* an injected regression (disabling spec-decode acceptance in the gate
  scenario) FAILS with the metric and tolerance named in the message;
* the baseline is load-bearing: every entry justified, every entry
  matched by a measured metric, removing an entry resurfaces an
  "unbaselined metric" failure (the lint_baseline.json idiom);
* the script exits non-zero on regression (pipefail-composable).

The ``hlo`` scenario is exercised by tests/test_hlo_census.py (same
drift check, no double census cost here).
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "perf_gate.py")

# Metric namespace -> owning test file.  The namespaces this file's
# ``gate`` fixture measures in-process own their resurface contract
# here; every OTHER namespace must name the test file that runs its
# scenario and asserts the same contract there.  PRs 7-11 extended the
# skip-lists below by hand — this mapping is now ASSERTED
# (TestBaselineLoadBearing.test_every_baseline_namespace_has_an_owner),
# so a new perf_baseline.json namespace without a registered owner is a
# tier-1 failure, not a silently unowned gate.
NAMESPACE_OWNERS = {
    "serve": "tests/test_perf_gate.py",
    "engine": "tests/test_perf_gate.py",
    "consensus": "tests/test_perf_gate.py",
    "hlo": "tests/test_hlo_census.py",
    "paged": "tests/test_paged_kv.py",
    "sampler": "tests/test_guided_sampler.py",
    "int4": "tests/test_int4_kv.py",
    "fleet": "tests/test_fleet.py",
    "hostsync": "tests/test_hostsync.py",
    "megaround": "tests/test_megaround.py",
    "compile": "tests/test_compile_obs.py",
    "sweep": "tests/test_sweep.py",
    "chaos": "tests/test_resilience.py",
    "scenarios": "tests/test_scenarios.py",
    "alerts": "tests/test_alerts.py",
}
# Namespaces owned elsewhere, as the prefix tuple the measurement-match
# tests skip (derived, not hand-maintained).
FOREIGN_PREFIXES = tuple(
    f"{ns}." for ns, owner in sorted(NAMESPACE_OWNERS.items())
    if owner != "tests/test_perf_gate.py"
)


def _load_script():
    spec = importlib.util.spec_from_file_location("perf_gate", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def gate():
    mod = _load_script()
    measured = {}
    measured.update(mod.run_serve_scenario())
    measured.update(mod.run_engine_scenario())
    measured.update(mod.run_consensus_scenario())
    return mod, measured


class TestGreenAtHead:
    def test_gate_passes(self, gate):
        mod, measured = gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(
            measured, mod.load_baseline(), ("serve", "engine", "consensus")
        )
        assert findings == [], "\n".join(findings)

    def test_scenarios_measure_the_advertised_metrics(self, gate):
        _, measured = gate
        for name in (
            "engine.decode_steps_per_decision",
            "engine.spec_step_reduction",
            "engine.spec_acceptance_rate",
            "engine.steady_state_retraces",
            "serve.completed_fraction",
            "serve.rows_per_dispatch",
            "serve.spec_acceptance_rate",
            "consensus.convergence_rate",
            "consensus.rounds_to_consensus_mean",
            "consensus.event_schema_completeness",
            "consensus.events_dropped",
            "consensus.histogram_quantile_sanity",
        ):
            assert name in measured, sorted(measured)

    def test_consensus_games_converge_with_complete_schemas(self, gate):
        """Acceptance criterion: the hermetic consensus scenario is
        green — every seeded game converges, every event type lands in
        the JSONL, nothing dropped, quantiles sane."""
        _, measured = gate
        assert measured["consensus.convergence_rate"] == 1.0
        assert measured["consensus.event_schema_completeness"] == 1.0
        assert measured["consensus.events_dropped"] == 0
        assert measured["consensus.histogram_quantile_sanity"] == 1.0

    def test_steady_state_retraces_are_zero(self, gate):
        _, measured = gate
        assert measured["engine.steady_state_retraces"] == 0

    def test_speculation_reduces_decode_iterations(self, gate):
        _, measured = gate
        assert measured["engine.spec_step_reduction"] >= 0.30


class TestInjectedRegression:
    def test_spec_off_fails_with_named_metric_and_tolerance(self, gate):
        """Acceptance criterion: disabling spec-decode acceptance in the
        gate scenario fails the gate, and the failure message carries
        the metric name and its tolerance band."""
        mod, _ = gate
        measured = mod.run_serve_scenario(inject="spec-off")
        findings = mod.check_metrics(measured, mod.load_baseline())
        hits = [f for f in findings if "serve.spec_acceptance_rate" in f]
        assert hits, findings
        assert "tol_rel" in hits[0] and ">=" in hits[0]

    def test_failing_rows_fail_the_gate(self, gate):
        mod, _ = gate
        measured = mod.run_serve_scenario(inject="fail-rows")
        findings = mod.check_metrics(measured, mod.load_baseline())
        assert any("serve.error_row_fraction" in f for f in findings), findings

    def test_events_off_fails_rather_than_passing_vacuously(self, gate):
        """With game-event telemetry silently disabled the consensus
        scenario must FAIL naming its outcome metrics — an empty event
        file can never read as a green convergence gate."""
        mod, _ = gate
        measured = mod.run_consensus_scenario(inject="events-off")
        findings = mod.check_metrics(measured, mod.load_baseline())
        assert any(
            "consensus.event_schema_completeness" in f for f in findings
        ), findings
        assert any(
            "consensus.convergence_rate" in f for f in findings
        ), findings


class TestBaselineLoadBearing:
    def test_every_entry_has_a_reason_and_band(self):
        mod = _load_script()
        baseline = mod.load_baseline()
        assert baseline and baseline["metrics"]
        for name, entry in baseline["metrics"].items():
            assert entry.get("reason", "").strip(), name
            assert entry.get("op") in ("min", "max", "range"), name
            assert "value" in entry, name

    def test_every_baseline_namespace_has_an_owner(self):
        """The NAMESPACE_OWNERS mapping is load-bearing in both
        directions: every namespace present in perf_baseline.json maps
        to an owning test file that EXISTS, and the mapping carries no
        stale namespaces the baseline no longer holds — so adding a
        gate namespace without registering (and writing) its owner
        fails here instead of riding unowned."""
        mod = _load_script()
        baseline = mod.load_baseline()
        namespaces = {n.split(".", 1)[0] for n in baseline["metrics"]}
        assert namespaces == set(NAMESPACE_OWNERS), (
            "perf_baseline.json namespaces and NAMESPACE_OWNERS "
            f"disagree: baseline has {sorted(namespaces)}, owners map "
            f"{sorted(NAMESPACE_OWNERS)} — register the owning test "
            "file for new namespaces (and prune removed ones)"
        )
        for ns, owner in NAMESPACE_OWNERS.items():
            assert os.path.exists(os.path.join(REPO, owner)), (ns, owner)

    def test_every_entry_is_matched_by_a_measurement(self, gate):
        mod, measured = gate
        baseline = mod.load_baseline()
        hlo_entries = [
            n for n in baseline["metrics"] if n.startswith("hlo.")
        ]
        assert hlo_entries == ["hlo.census_drift_findings"]
        for name in baseline["metrics"]:
            if name.startswith(FOREIGN_PREFIXES):
                continue  # owned by NAMESPACE_OWNERS[namespace]
            assert name in measured, name

    def test_removing_an_entry_resurfaces_its_finding(self, gate):
        mod, measured = gate
        baseline = mod.load_baseline()
        for removed in baseline["metrics"]:
            if removed.startswith(FOREIGN_PREFIXES):
                # The same resurface contract is asserted by the
                # namespace's owning test file over its own scenario
                # (NAMESPACE_OWNERS above).
                continue
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(measured, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)

    def test_stale_entry_is_a_finding(self, gate):
        mod, measured = gate
        baseline = json.loads(json.dumps(mod.load_baseline()))
        baseline["metrics"]["serve.ghost_metric"] = {
            "value": 1, "op": "min", "reason": "synthetic",
        }
        stale = mod.check_stale(measured, baseline, ("serve", "engine"))
        assert any("serve.ghost_metric" in f for f in stale), stale

    def test_skipped_scenarios_entries_are_not_stale(self, gate):
        mod, measured = gate
        serve_only = {
            k: v for k, v in measured.items() if k.startswith("serve.")
        }
        stale = mod.check_stale(serve_only, mod.load_baseline(), ("serve",))
        assert stale == [], stale


class TestScriptExitCodes:
    def test_green_scenario_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, SCRIPT, "--scenarios", "serve"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_injected_regression_exits_nonzero_and_names_metric(self):
        proc = subprocess.run(
            [sys.executable, SCRIPT, "--scenarios", "serve",
             "--inject-regression", "spec-off"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "serve.spec_acceptance_rate" in proc.stderr
        assert "PERF REGRESSION" in proc.stderr
