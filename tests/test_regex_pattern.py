"""JSON-schema ``pattern`` support (guided/regex_parser.py).

The reference's guided decoding (vLLM outlines-style) accepts
``pattern`` on string schemas; these tests pin the TPU pipeline's
parser, its JSON-escape transform, byte-DFA acceptance, and end-to-end
guided generation through the real engine.
"""

import json

import pytest

from bcg_tpu.guided.dfa import ast_to_dfa
from bcg_tpu.guided.regex_parser import (
    PatternError,
    json_escape_transform,
    parse_pattern,
)
from bcg_tpu.guided.schema_compiler import schema_to_ast


def matches(pattern: str, value: str) -> bool:
    dfa = ast_to_dfa(parse_pattern(pattern))
    return dfa.matches(value.encode())


class TestParser:
    @pytest.mark.parametrize("pattern,yes,no", [
        ("abc", ["abc"], ["ab", "abcd", ""]),
        ("a|bc", ["a", "bc"], ["b", "abc"]),
        ("a*", ["", "a", "aaaa"], ["b"]),
        ("a+b?", ["a", "ab", "aaab"], ["", "b", "abb"]),
        ("[a-c]x", ["ax", "bx", "cx"], ["dx", "x"]),
        ("[^a-y]", ["z", "0", "!"], ["a", "m"]),
        (r"\d{3}", ["123", "000"], ["12", "1234", "abc"]),
        (r"\d{2,}", ["12", "123456"], ["1"]),
        (r"\d{1,3}", ["1", "12", "123"], ["", "1234"]),
        (r"\w+@\w+", ["a@b", "user_1@host9"], ["@b", "a@"]),
        (r"a\.b", ["a.b"], ["axb"]),
        (r"(ab)+", ["ab", "abab"], ["a", "aba"]),
        (r"(?:x|y)z", ["xz", "yz"], ["z", "xyz"]),
        ("^AB-[0-9]{2}$", ["AB-07"], ["AB-7", "ab-07"]),
        (r"a\sb", ["a b", "a\tb"], ["ab"]),
        (r"\S+", ["abc!"], ["a b", ""]),
        (".+", ["anything at all"], [""]),
    ])
    def test_match_semantics(self, pattern, yes, no):
        for v in yes:
            assert matches(pattern, v), (pattern, v)
        for v in no:
            assert not matches(pattern, v), (pattern, v)

    @pytest.mark.parametrize("bad", [
        "a{2,1}", "a{x}", "(ab", "[a", "[]", "a**b$x", "mid^dle",
        "a$b", r"\q", "(?=look)",
    ])
    def test_malformed_or_unsupported_raises(self, bad):
        with pytest.raises((PatternError, ValueError)):
            parse_pattern(bad)

    @pytest.mark.parametrize("pattern,yes,no", [
        (r"a\"b", ['a"b'], ["ab", "a\\b"]),
        (r"\!\@\#", ["!@#"], ["!@", "!@#$"]),
        (r"x\~y", ["x~y"], ["xy"]),
    ])
    def test_identity_escapes(self, pattern, yes, no):
        """ECMA identity escapes (\\" etc.) on printable punctuation are
        accepted; alphanumeric escapes without a meaning still raise
        (covered by test_malformed_or_unsupported_raises's \\q)."""
        for v in yes:
            assert matches(pattern, v), (pattern, v)
        for v in no:
            assert not matches(pattern, v), (pattern, v)

    def test_anchors_are_whole_string(self):
        # Anchored and unanchored parse to the SAME automaton (documented
        # outlines-convention divergence from JSON-Schema search
        # semantics).
        assert matches("^abc$", "abc")
        assert not matches("abc", "xabcy")


class TestJsonEscapeTransform:
    def test_quote_and_backslash_become_escapes(self):
        ast = json_escape_transform(parse_pattern(r'.+'))
        dfa = ast_to_dfa(ast)
        # A raw '"' in the VALUE must be emitted as the two bytes \" .
        assert dfa.matches(b'a\\"b')
        assert not dfa.matches(b'a"b')
        assert dfa.matches(b"a\\\\b")

    def test_newline_class_emits_escape(self):
        ast = json_escape_transform(parse_pattern(r"a\nb"))
        dfa = ast_to_dfa(ast)
        assert dfa.matches(b"a\\nb")
        assert not dfa.matches(b"a\nb")


class TestSchemaIntegration:
    def test_pattern_schema_accepts_only_matching_json(self):
        schema = {
            "type": "object",
            "properties": {"code": {"type": "string",
                                    "pattern": "^[A-Z]{2}-[0-9]{3}$"}},
            "required": ["code"],
            "additionalProperties": False,
        }
        dfa = ast_to_dfa(schema_to_ast(schema))
        assert dfa.matches(json.dumps({"code": "AB-123"}).encode())
        assert not dfa.matches(json.dumps({"code": "ab-123"}).encode())
        assert not dfa.matches(json.dumps({"code": "AB-12"}).encode())

    def test_pattern_with_length_bounds_rejected(self):
        schema = {"type": "string", "pattern": "a+", "minLength": 2}
        with pytest.raises(ValueError, match="pattern and"):
            schema_to_ast(schema)

    def test_engine_generates_matching_string(self):
        import re

        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=512,
        ))
        schema = {
            "type": "object",
            "properties": {"tag": {"type": "string",
                                   "pattern": "^[a-c]{2}[0-9]$"}},
            "required": ["tag"],
            "additionalProperties": False,
        }
        out = engine.generate_json("name a tag", schema,
                                   temperature=0.9, max_tokens=24)
        assert re.fullmatch(r"[a-c]{2}[0-9]", out.get("tag", "")), out
        engine.shutdown()


class TestNonAscii:
    """Non-ASCII input must fail loudly (review findings: ord(c) byte
    classes outside the alphabet either force broken UTF-8 or silently
    dead-end generation)."""

    @pytest.mark.parametrize("bad", ["é", "a→b", "[aé]", "x[α-ω]"])
    def test_non_ascii_raises(self, bad):
        with pytest.raises(PatternError, match="non-ASCII"):
            parse_pattern(bad)


class TestQuantifierAndRangeEdges:
    """Review findings: stacked/lazy quantifiers and escaped-char ranges
    must behave like ECMA or fail loudly — never silently diverge."""

    @pytest.mark.parametrize("bad", ["a+?", "a**", "a{2,3}?", "a?+"])
    def test_stacked_or_lazy_quantifiers_raise(self, bad):
        with pytest.raises(PatternError, match="quantifier"):
            parse_pattern(bad)

    def test_escaped_range_start(self):
        # [\t-\n] is the range 0x09-0x0A, not {tab, '-', newline}.
        dfa = ast_to_dfa(parse_pattern(r"[\t-\n]"))
        assert dfa.matches(b"\x09")
        assert dfa.matches(b"\x0a")
        assert not dfa.matches(b"-")

    def test_range_spanning_alphabet_hole_raises(self):
        # [\t-\r] includes VT/FF (0x0B/0x0C), which a JSON string in
        # this pipeline's ASCII alphabet cannot emit — loud rejection
        # beats silently narrowing the author's range.
        with pytest.raises(PatternError, match="outside the ASCII"):
            parse_pattern(r"[\t-\r]")

    def test_named_class_cannot_start_range(self):
        # \d is multi-char: '-' after it is a literal member.
        dfa = ast_to_dfa(parse_pattern(r"[\d-]"))
        assert dfa.matches(b"5") and dfa.matches(b"-")
