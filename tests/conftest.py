"""Test configuration.

Force JAX onto an 8-device virtual CPU mesh so multi-chip sharding logic
(tp/dp/sp over a Mesh) is exercised hermetically without TPU hardware
(SURVEY.md §4's test-strategy requirement).

Note: this environment's axon sitecustomize force-registers the TPU
backend and overrides JAX_PLATFORMS, so the env var alone is NOT enough —
``jax.config.update("jax_platforms", "cpu")`` must run before any
computation.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (must import after XLA_FLAGS is set)

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
