"""REAL multi-process distributed runtime test (2-rank CPU cluster).

The reference's distributed story is vLLM's internal torch.distributed
stack (`vllm_agent.py:139-142`); ours is `bcg_tpu.parallel.distributed`
over JAX's process group + XLA collectives.  Until round 4 that module
was only unit-tested single-process ("untestable here").  JAX's CPU
backend supports true multi-process clusters (Gloo for cross-host
collectives), so this test launches TWO actual OS processes that:

1. join one process group via ``distributed.initialize`` (coordinator
   handshake — the same call a Cloud TPU pod worker makes),
2. build a hybrid mesh and verify tp groups never straddle a host,
3. run the SPMD game round (all_gather exchange, psum vote tally,
   consensus check) over a dp mesh spanning both processes — the
   cross-"DCN" layout of the one-agent-per-chip scale sweeps.

Each rank gets 4 virtual CPU devices -> 8 global devices across 2
processes.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cluster_runs_spmd_game_round():
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO,
    )
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=REPO,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"MULTIHOST-OK pid={pid} procs=2 global_devices=8" in out, (
            out[-1000:]
        )
