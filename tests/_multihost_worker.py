"""Worker process for tests/test_multihost.py (not a pytest module).

Runs as one rank of a REAL 2-process JAX cluster (CPU devices, Gloo
collectives): joins the process group through
bcg_tpu.parallel.distributed.initialize — the exact call a Cloud TPU
pod worker makes — then drives cross-process collectives through the
library's own mesh builders and SPMD game step.

Usage: python tests/_multihost_worker.py <coordinator> <num_procs> <pid>
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

COORD, NPROC, PID = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

from bcg_tpu.parallel import distributed  # noqa: E402

distributed.initialize(
    coordinator_address=COORD, num_processes=NPROC, process_id=PID
)

info = distributed.process_info()
assert info["process_count"] == NPROC, info
assert info["global_device_count"] == NPROC * info["local_device_count"], info
n_local = info["local_device_count"]
n_global = info["global_device_count"]

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from bcg_tpu.parallel.game_step import (  # noqa: E402
    exchange_values, spmd_round_arrays, tally_votes,
)

# --- hybrid mesh: tp groups must stay inside one host ------------------
mesh_h = distributed.build_hybrid_mesh(tp=2, sp=1)
assert mesh_h.shape["tp"] == 2 and mesh_h.shape["dp"] == n_global // 2
for row in mesh_h.devices.reshape(mesh_h.shape["dp"], 2):
    hosts = {d.process_index for d in row}
    assert len(hosts) == 1, f"tp group straddles hosts: {row}"

# --- pure-dp mesh spanning both hosts: the game exchange over "DCN" ----
mesh = distributed.build_hybrid_mesh(tp=1, sp=1)  # dp = n_global
n = n_global


def global_array(np_arr, spec):
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        np_arr.shape, sharding, lambda idx: np_arr[idx]
    )


values_np = np.arange(10, 10 + n, dtype=np.int32)
values_np[1] = -1  # one abstainer
mask_np = ~np.eye(n, dtype=bool)  # fully connected
votes_np = np.array([1] * (n - 2) + [0, -1], dtype=np.int32)
byz_np = np.zeros(n, dtype=bool)
inits_np = values_np.copy()

values = global_array(values_np, P("dp"))
mask = global_array(mask_np, P("dp", None))
votes = global_array(votes_np, P("dp"))
byz = global_array(byz_np, P("dp"))
inits = global_array(inits_np, P("dp"))

received = exchange_values(values, mask, mesh)
# Expected: row i holds j's value for j != i when j proposed, else -1.
expected = np.where(mask_np & (values_np >= 0)[None, :], values_np[None, :], -1)
for shard in received.addressable_shards:
    rows = shard.index[0]
    np.testing.assert_array_equal(np.asarray(shard.data), expected[rows])

tally = tally_votes(votes, mesh)
assert int(tally["stop"]) == n - 2
assert int(tally["continue"]) == 1
assert int(tally["abstain"]) == 1
assert bool(tally["terminate"]) == ((n - 2) * 3 >= n * 2)

# Full round helper (exchange + tally + consensus) on the same mesh.
received2, tally2, consensus = spmd_round_arrays(
    values, votes, mask, byz, inits, mesh
)
jax.block_until_ready(received2)
assert int(tally2["stop"]) == n - 2
assert not bool(consensus["has_consensus"])  # distinct values: no consensus

# Unanimous case crossing hosts: every agent holds agent 0's value.
uni_np = np.full(n, 10, dtype=np.int32)
uni = global_array(uni_np, P("dp"))
_, _, consensus_u = spmd_round_arrays(uni, votes, mask, byz, inits, mesh)
assert bool(consensus_u["has_consensus"])

# --- long-context sp ops on the hybrid mesh: sp in-host (the ICI ring),
# --- dp across the process boundary (DCN) — the engine's layout --------
from bcg_tpu.ops.ring_attention import (  # noqa: E402
    ring_attention, sp_decode_attention,
)

mesh_sp = distributed.build_hybrid_mesh(tp=1, sp=2)
dp_sz, sp_sz = mesh_sp.shape["dp"], mesh_sp.shape["sp"]
assert sp_sz == 2 and dp_sz == n_global // 2  # sp in-host, dp over DCN
B, T, H, Hkv, Dh = dp_sz, 16, 4, 2, 8
rng = np.random.default_rng(42)  # identical on both ranks
q_np = rng.standard_normal((B, T, H, Dh)).astype(np.float32)
k_np = rng.standard_normal((B, T, Hkv, Dh)).astype(np.float32)
v_np = rng.standard_normal((B, T, Hkv, Dh)).astype(np.float32)
pad_np = rng.integers(0, T // 2, size=B)
valid_np = (np.arange(T)[None, :] >= pad_np[:, None])


def np_attention(q4, k4, v4, mask3):
    """Grouped-query masked softmax attention in numpy (reference)."""
    g = q4.shape[2] // k4.shape[2]
    out = np.empty_like(q4)
    scale = 1.0 / np.sqrt(q4.shape[-1])
    for b in range(q4.shape[0]):
        for h in range(q4.shape[2]):
            logits = q4[b, :, h] @ k4[b, :, h // g].T * scale
            logits = np.where(mask3[b], logits, -np.inf)
            m = np.max(logits, axis=-1, keepdims=True)
            m = np.where(np.isfinite(m), m, 0.0)
            p = np.exp(logits - m)
            p = np.where(np.isfinite(logits), p, 0.0)
            l = p.sum(-1, keepdims=True)
            out[b, :, h] = (p / np.maximum(l, 1e-30)) @ v4[b, :, h // g]
    return out


def hybrid_array(np_arr, spec):
    sharding = NamedSharding(mesh_sp, spec)
    return jax.make_array_from_callback(
        np_arr.shape, sharding, lambda idx: np_arr[idx]
    )


q_g = hybrid_array(q_np, P("dp", "sp", None, None))
k_g = hybrid_array(k_np, P("dp", "sp", None, None))
v_g = hybrid_array(v_np, P("dp", "sp", None, None))
valid_g = hybrid_array(valid_np, P("dp", "sp"))

ring_out = ring_attention(q_g, k_g, v_g, mesh_sp, axis_name="sp",
                          causal=True, kv_valid=valid_g)
causal_np = np.tril(np.ones((T, T), bool))[None]
mask3 = causal_np & valid_np[:, None, :] & valid_np[:, :, None]
ref = np_attention(q_np, k_np, v_np, mask3)
for shard in ring_out.addressable_shards:
    got = np.asarray(shard.data)
    want = ref[shard.index]
    vm = valid_np[shard.index[:2]]
    np.testing.assert_allclose(got[vm], want[vm], rtol=2e-4, atol=2e-4)

# Decode over the sp-sharded cache, merged with pmax/psum across ICI.
qd_np = rng.standard_normal((B, H, Dh)).astype(np.float32)
qd_g = hybrid_array(qd_np, P("dp", None, None))
dec_out = sp_decode_attention(qd_g, k_g, v_g, valid_g, mesh_sp,
                              axis_name="sp")
dec_ref = np_attention(qd_np[:, None], k_np, v_np,
                       valid_np[:, None, :])[:, 0]
for shard in dec_out.addressable_shards:
    np.testing.assert_allclose(
        np.asarray(shard.data), dec_ref[shard.index],
        rtol=2e-4, atol=2e-4,
    )

print(f"MULTIHOST-OK pid={PID} procs={NPROC} global_devices={n_global}",
      flush=True)
