"""Worker process for tests/test_multihost.py (not a pytest module).

Runs as one rank of a REAL 2-process JAX cluster (CPU devices, Gloo
collectives): joins the process group through
bcg_tpu.parallel.distributed.initialize — the exact call a Cloud TPU
pod worker makes — then drives cross-process collectives through the
library's own mesh builders and SPMD game step.

Usage: python tests/_multihost_worker.py <coordinator> <num_procs> <pid>
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

COORD, NPROC, PID = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

from bcg_tpu.parallel import distributed  # noqa: E402

distributed.initialize(
    coordinator_address=COORD, num_processes=NPROC, process_id=PID
)

info = distributed.process_info()
assert info["process_count"] == NPROC, info
assert info["global_device_count"] == NPROC * info["local_device_count"], info
n_local = info["local_device_count"]
n_global = info["global_device_count"]

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from bcg_tpu.parallel.game_step import (  # noqa: E402
    exchange_values, spmd_round_arrays, tally_votes,
)

# --- hybrid mesh: tp groups must stay inside one host ------------------
mesh_h = distributed.build_hybrid_mesh(tp=2, sp=1)
assert mesh_h.shape["tp"] == 2 and mesh_h.shape["dp"] == n_global // 2
for row in mesh_h.devices.reshape(mesh_h.shape["dp"], 2):
    hosts = {d.process_index for d in row}
    assert len(hosts) == 1, f"tp group straddles hosts: {row}"

# --- pure-dp mesh spanning both hosts: the game exchange over "DCN" ----
mesh = distributed.build_hybrid_mesh(tp=1, sp=1)  # dp = n_global
n = n_global


def global_array(np_arr, spec):
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        np_arr.shape, sharding, lambda idx: np_arr[idx]
    )


values_np = np.arange(10, 10 + n, dtype=np.int32)
values_np[1] = -1  # one abstainer
mask_np = ~np.eye(n, dtype=bool)  # fully connected
votes_np = np.array([1] * (n - 2) + [0, -1], dtype=np.int32)
byz_np = np.zeros(n, dtype=bool)
inits_np = values_np.copy()

values = global_array(values_np, P("dp"))
mask = global_array(mask_np, P("dp", None))
votes = global_array(votes_np, P("dp"))
byz = global_array(byz_np, P("dp"))
inits = global_array(inits_np, P("dp"))

received = exchange_values(values, mask, mesh)
# Expected: row i holds j's value for j != i when j proposed, else -1.
expected = np.where(mask_np & (values_np >= 0)[None, :], values_np[None, :], -1)
for shard in received.addressable_shards:
    rows = shard.index[0]
    np.testing.assert_array_equal(np.asarray(shard.data), expected[rows])

tally = tally_votes(votes, mesh)
assert int(tally["stop"]) == n - 2
assert int(tally["continue"]) == 1
assert int(tally["abstain"]) == 1
assert bool(tally["terminate"]) == ((n - 2) * 3 >= n * 2)

# Full round helper (exchange + tally + consensus) on the same mesh.
received2, tally2, consensus = spmd_round_arrays(
    values, votes, mask, byz, inits, mesh
)
jax.block_until_ready(received2)
assert int(tally2["stop"]) == n - 2
assert not bool(consensus["has_consensus"])  # distinct values: no consensus

# Unanimous case crossing hosts: every agent holds agent 0's value.
uni_np = np.full(n, 10, dtype=np.int32)
uni = global_array(uni_np, P("dp"))
_, _, consensus_u = spmd_round_arrays(uni, votes, mask, byz, inits, mesh)
assert bool(consensus_u["has_consensus"])

print(f"MULTIHOST-OK pid={PID} procs={NPROC} global_devices={n_global}",
      flush=True)
