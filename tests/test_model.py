"""Transformer model tests (CPU, tiny spec)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.models import init_params, prefill, decode_step, spec_for_model
from bcg_tpu.models.transformer import init_kv_cache, param_count

SPEC = spec_for_model("bcg-tpu/tiny-test")


@pytest.fixture(scope="module")
def params():
    return init_params(SPEC, jax.random.PRNGKey(0))


def test_param_shapes(params):
    assert params["embed"].shape == (SPEC.vocab_size, SPEC.hidden_size)
    assert len(params["layers"]) == SPEC.num_layers
    l0 = params["layers"][0]
    assert l0["wq"].shape == (SPEC.hidden_size, SPEC.q_size)
    assert l0["wk"].shape == (SPEC.hidden_size, SPEC.kv_size)
    assert l0["w_gate"].shape == (SPEC.hidden_size, SPEC.intermediate_size)
    assert "q_norm" in l0  # qk_norm model
    assert param_count(params) > 0


def test_prefill_shapes_and_finiteness(params):
    B, L, S = 2, 8, 16
    tokens = jnp.arange(B * L).reshape(B, L) % SPEC.vocab_size
    valid = jnp.ones((B, L), bool)
    cache = init_kv_cache(SPEC, B, S)
    logits, cache = prefill(params, SPEC, tokens, valid, cache)
    assert logits.shape == (B, SPEC.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    assert cache[0]["k"].shape == (B, S, SPEC.num_kv_heads, SPEC.head_dim)


def test_decode_step_matches_prefill(params):
    """Teacher-forcing equivalence: running the prompt token-by-token
    through decode_step must give the same final logits as one prefill."""
    B, L, S = 1, 6, 12
    tokens = jnp.asarray([[3, 7, 11, 13, 17, 19]], dtype=jnp.int32)
    valid = jnp.ones((B, L), bool)

    cache = init_kv_cache(SPEC, B, S)
    ref_logits, _ = prefill(params, SPEC, tokens, valid, cache)

    cache = init_kv_cache(SPEC, B, S)
    valid_mask = np.zeros((B, S), bool)
    logits = None
    for t in range(L):
        valid_mask[:, t] = True
        logits, cache = decode_step(
            params, SPEC,
            tokens[:, t], jnp.int32(t), jnp.asarray([t]),
            cache, jnp.asarray(valid_mask),
        )
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(logits), rtol=2e-2, atol=2e-2
    )


def test_left_padding_equivalence(params):
    """A left-padded prompt must produce the same last-token logits as the
    unpadded prompt (pads masked out + positions shifted)."""
    toks = [5, 9, 2, 31]
    B = 1
    unpadded = jnp.asarray([toks], dtype=jnp.int32)
    cache = init_kv_cache(SPEC, B, 8)
    ref, _ = prefill(params, SPEC, unpadded, jnp.ones((1, 4), bool), cache)

    pad = 3
    padded = jnp.asarray([[0] * pad + toks], dtype=jnp.int32)
    valid = jnp.asarray([[False] * pad + [True] * 4])
    cache = init_kv_cache(SPEC, B, 8 + pad)
    out, _ = prefill(params, SPEC, padded, valid, cache)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-2, atol=2e-2)


def test_real_model_specs_registered():
    for name in ("Qwen/Qwen3-8B", "Qwen/Qwen3-14B", "Qwen/Qwen3-32B",
                 "mistralai/Mistral-Small-Instruct-2409"):
        spec = spec_for_model(name)
        assert spec is not None
        assert spec.num_heads % spec.num_kv_heads == 0


def test_param_count_size_classes():
    """param_count drives the bench's size-class gates (kv dtype, scan):
    it must land in the right ballpark for every preset family."""
    billions = {
        "bcg-tpu/bench-1b": (1, 2),
        "bcg-tpu/bench-8b": (7, 10),
        "bcg-tpu/bench-14b": (13, 16),
        "bcg-tpu/bench-32b": (30, 36),
        "Qwen/Qwen3-8B": (7, 10),
        "meta-llama/Meta-Llama-3.1-8B-Instruct": (7, 10),
        "mistralai/Mistral-Small-Instruct-2409": (20, 25),
    }
    for name, (lo, hi) in billions.items():
        spec = spec_for_model(name)
        count = spec.param_count
        assert lo * 1e9 <= count <= hi * 1e9, (name, count)
        # The per-layer matmul unit must agree with the total.
        assert spec.num_layers * spec.matmul_params_per_layer <= count


def test_attn_bias_models():
    """Qwen2-style projection biases: present in the pytree and actually
    applied (nonzero bias must change the logits)."""
    import dataclasses

    spec = dataclasses.replace(SPEC, attn_bias=True)
    params = init_params(spec, jax.random.PRNGKey(0))
    layer0 = params["layers"][0]
    assert layer0["bq"].shape == (spec.q_size,)
    assert layer0["bk"].shape == (spec.kv_size,)

    tokens = jnp.asarray([[3, 7, 11]], dtype=jnp.int32)
    valid = jnp.ones((1, 3), bool)
    base, _ = prefill(params, spec, tokens, valid, init_kv_cache(spec, 1, 4))
    for lay in params["layers"]:
        lay["bq"] = jnp.ones_like(lay["bq"]) * 0.5
    biased, _ = prefill(params, spec, tokens, valid, init_kv_cache(spec, 1, 4))
    assert not np.allclose(np.asarray(base), np.asarray(biased), atol=1e-3)


def test_llama3_rope_scaling():
    """NTK-by-parts: high-frequency dims untouched, low-frequency dims
    stretched by ~factor; tables stay bounded."""
    from bcg_tpu.models.configs import RopeScaling
    from bcg_tpu.models.transformer import rope_table

    positions = jnp.arange(0, 16000, 500)[None, :]
    sc = RopeScaling(factor=8.0, original_max_position=8192)
    cos_p, sin_p = rope_table(positions, 128, 500_000.0)
    cos_s, sin_s = rope_table(positions, 128, 500_000.0, sc)
    # Highest-frequency dim (index 0): wavelength tiny -> identical.
    np.testing.assert_allclose(np.asarray(cos_p[..., 0]), np.asarray(cos_s[..., 0]))
    # Lowest-frequency dim: scaled (angle divided by factor).
    assert not np.allclose(np.asarray(cos_p[..., -1]), np.asarray(cos_s[..., -1]))
    assert np.isfinite(np.asarray(cos_s)).all() and np.isfinite(np.asarray(sin_s)).all()
    # The registered Llama-3.1 spec carries the scaling config.
    spec = spec_for_model("meta-llama/Meta-Llama-3.1-8B-Instruct")
    assert spec.rope_scaling is not None and spec.rope_scaling.factor == 8.0
    assert spec_for_model("Qwen/Qwen2.5-7B-Instruct").attn_bias


class TestCapacityMath:
    """Single-chip fit story as tested arithmetic (16 GB v5e, ~15.75
    usable): which presets board one chip at which quantization —
    weights must leave room for KV cache + activations (~3 GB at game
    shapes), so the serving-fit bar is ~12 GB of weights."""

    USABLE = 15.75 * (1 << 30)
    SERVING_FIT = 12.0 * (1 << 30)

    def _wb(self, name, mode):
        return spec_for_model(name).weight_bytes(mode)

    def test_fit_matrix(self):
        # 1B serves even in bf16.
        assert self._wb("bcg-tpu/bench-1b", None) < self.SERVING_FIT
        # 8B needs quantized weights; int8 fits with room for cache.
        assert self._wb("bcg-tpu/bench-8b", None) > self.SERVING_FIT
        assert self._wb("bcg-tpu/bench-8b", "int8") < self.SERVING_FIT
        # 14B: int8 weights alone nearly fill the chip; int4 serves.
        assert self._wb("bcg-tpu/bench-14b", "int8") > self.SERVING_FIT
        assert self._wb("bcg-tpu/bench-14b", "int4") < self.SERVING_FIT
        # 32B cannot board one chip even at int4 -> tp>=2 territory.
        assert self._wb("bcg-tpu/bench-32b", "int4") > self.USABLE
        # Mistral-Small-22B (the reference's 4th preset): int8 exceeds
        # the chip, int4 boards it — same class as 14B.
        assert self._wb("mistralai/Mistral-Small-Instruct-2409", "int8") \
            > self.SERVING_FIT
        assert self._wb("mistralai/Mistral-Small-Instruct-2409", "int4") \
            < self.SERVING_FIT

    def test_estimates_track_modes(self):
        for name in ("bcg-tpu/bench-1b", "bcg-tpu/bench-8b"):
            bf16 = self._wb(name, None)
            i8 = self._wb(name, "int8")
            i4 = self._wb(name, "int4")
            assert bf16 > i8 > i4
            # int8 halves the matmul bytes (embedding stays bf16).
            assert 0.4 * bf16 < i8 < 0.62 * bf16

    def test_tied_embeddings_not_double_counted_bf16(self):
        import dataclasses

        spec = spec_for_model("bcg-tpu/bench-1b")
        tied = dataclasses.replace(spec, tie_embeddings=True)
        embed_bytes = spec.vocab_size * spec.hidden_size * 2
        # bf16: tied serving shares one table -> exactly one head less.
        assert spec.weight_bytes(None) - tied.weight_bytes(None) == embed_bytes
        # Quantized: tied models materialize an explicit quantized head
        # (models/quantize.py ensure_quantized_head) -> same estimate.
        assert spec.weight_bytes("int8") == tied.weight_bytes("int8")
