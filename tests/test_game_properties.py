"""Property-based tests (hypothesis) for the game core.

SURVEY.md §4 prescribes property tests for ``check_consensus`` edge
cases; these state the reference semantics as independent predicates and
check them against randomized games — consensus (byzantine_consensus.py
:182-249), the 2/3 stop vote (:373-398), deadline-always-loses
(:507-518), statistics bounds (:544-839), and snapshot/resume fidelity.
"""

import json
import random

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from bcg_tpu.game import ByzantineConsensusGame  # noqa: E402

LO, HI = 0, 20


@st.composite
def games(draw, max_honest=8, max_byz=4):
    nh = draw(st.integers(1, max_honest))
    nb = draw(st.integers(0, max_byz))
    seed = draw(st.integers(0, 2**31 - 1))
    return ByzantineConsensusGame(
        num_honest=nh, num_byzantine=nb, seed=seed, value_range=(LO, HI),
        max_rounds=draw(st.integers(1, 6)),
    )


class TestConsensusPredicate:
    @given(games(), st.data())
    @settings(max_examples=120, deadline=None)
    def test_check_consensus_matches_reference_predicate(self, g, data):
        for aid in g.agents:
            v = data.draw(
                st.one_of(st.none(), st.integers(LO, HI)), label=aid
            )
            if v is not None:
                g.update_agent_proposal(aid, v)
        g.apply_proposals()
        ok, pct = g.check_consensus()

        known = [
            int(s.current_value) for s in g.agents.values()
            if not s.is_byzantine and s.current_value is not None
        ]
        initials = {
            int(s.initial_value) for s in g.agents.values()
            if not s.is_byzantine and s.initial_value is not None
        }
        expected = (
            bool(known)
            and len(set(known)) == 1
            and known[0] in initials
        )
        assert ok == expected
        if known:
            top = max(set(known), key=known.count)
            assert pct == (100.0 if len(known) == 1
                           else known.count(top) / len(known) * 100)
        else:
            assert pct == 0.0

    @given(games(), st.data())
    @settings(max_examples=120, deadline=None)
    def test_stop_vote_supermajority_rule(self, g, data):
        votes = {
            aid: data.draw(st.sampled_from([True, False, None]), label=aid)
            for aid in g.agents
        }
        stop = sum(1 for v in votes.values() if v is True)
        assert g.should_terminate_by_vote(votes) == (
            stop >= 2 * len(votes) / 3
        )


def _play_random_game(g, seed):
    """Drive a full game with seeded random proposals/votes."""
    rng = random.Random(seed)
    while not g.game_over:
        for aid, s in g.agents.items():
            if rng.random() < 0.8:
                g.update_agent_proposal(aid, rng.randint(LO, HI))
        g.store_round_reasoning(
            {aid: "strategic reasoning" for aid in g.agents}
        )
        g.advance_round({
            aid: rng.choice([True, False, None]) for aid in g.agents
        })
    return g


class TestFullGameInvariants:
    @given(games(), st.integers(0, 2**31 - 1))
    @settings(max_examples=60, deadline=None)
    def test_termination_and_statistics_bounds(self, g, seed):
        _play_random_game(g, seed)
        assert g.termination_reason in (
            "vote_with_consensus", "vote_without_consensus", "max_rounds"
        )
        # Deadline always loses; winning requires consensus-at-stop.
        if g.termination_reason == "max_rounds":
            assert g.honest_agents_won is False
        if g.termination_reason == "vote_with_consensus":
            assert g.consensus_reached and g.honest_agents_won
        if g.termination_reason == "vote_without_consensus":
            assert g.honest_agents_won is False

        stats = g.get_statistics()
        json.dumps(stats)  # payload must be JSON-serializable
        assert stats["consensus_outcome"] in (
            "valid", "invalid", "timeout", "none"
        )
        q = stats.get("consensus_quality_score")
        if q is not None:
            assert 0.0 <= q <= 100.0
        for key in ("centrality", "inclusivity", "convergence_rate"):
            v = stats.get(key)
            if v is not None:
                assert 0.0 <= v <= 1.0, (key, v)
        # Percentage scale, matching the reference
        # (byzantine_consensus.py:693-698 / statistics.py:143).
        infil = stats.get("byzantine_infiltration")
        if infil is not None:
            assert 0.0 <= infil <= 100.0
        assert 1 <= stats["total_rounds"] <= g.max_rounds

    @given(games(), st.integers(0, 2**31 - 1))
    @settings(max_examples=40, deadline=None)
    def test_snapshot_roundtrip_preserves_statistics(self, g, seed):
        _play_random_game(g, seed)
        restored = ByzantineConsensusGame.from_snapshot(
            json.loads(json.dumps(g.snapshot()))
        )
        assert restored.get_statistics() == g.get_statistics()
