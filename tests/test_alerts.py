"""Health & alerting plane (bcg_tpu/obs/alerts.py) in tier-1.

ISSUE-19 contracts asserted here:

* **Rule kinds** — threshold (level, absent-metric never fires),
  delta_rate (window movement, trailing-``*`` family sums,
  ``unless_metric`` suppression), burn_rate (fast+slow dual windows
  against ``budget * burn_factor``), staleness (epoch-ms heartbeat age
  and stalled-value arms); ``for_cycles`` debounce; firing is an edge
  (one episode per condition run, re-fire after resolve = flap).
* **Readiness/health** — pushed component vetoes with a deduped
  bounded transition history, pull probes read at request time,
  ``health()`` wired to page severity only.
* **Endpoints** — ``/healthz`` + ``/readyz`` on the metrics HTTP
  server: JSON bodies, 200/503 verdicts, query strings tolerated,
  ``/metrics`` and 404 behavior unchanged.
* **Zero surface off** — with ``BCG_TPU_ALERTS`` unset nothing is
  registered, no evaluator thread exists, and the Prometheus
  exposition of a serving run minus the alert namespace is
  BYTE-identical to an unalerted run (subprocess pin — registries
  don't unregister in-process).
* **Streams** — the ``BCG_TPU_ALERT_EVENTS`` JSONL sink is
  manifest-headed with one record per transition, and
  ``scripts/alert_report.py`` merges it (with
  ``bench_trajectory --alert-out`` records) into one timeline.
* **Drift gate** — the perf_gate ``alerts`` scenario is green against
  justified ``perf_baseline.json`` entries, ``--inject-regression
  alerts-off`` fails naming the floored metrics, and removing any
  ``alerts.*`` entry resurfaces an unbaselined-metric finding (this
  file is the namespace's registered owner —
  tests/test_perf_gate.py NAMESPACE_OWNERS).
"""

import importlib.util
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from bcg_tpu.obs import alerts as obs_alerts
from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.obs import export as obs_export
from bcg_tpu.runtime import metrics as runtime_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE_SCRIPT = os.path.join(REPO, "scripts", "perf_gate.py")
ALERT_REPORT = os.path.join(REPO, "scripts", "alert_report.py")
TRAJECTORY = os.path.join(REPO, "scripts", "bench_trajectory.py")


def _load_gate():
    spec = importlib.util.spec_from_file_location("perf_gate", GATE_SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _engine_with(monkeypatch, rules):
    """A standalone AlertEngine over the given rules, installed as the
    module-level engine (so health()/evaluate_now()/the exposition
    provider see it) without touching the read-once env flag."""
    monkeypatch.delenv("BCG_TPU_ALERT_EVENTS", raising=False)
    eng = obs_alerts.AlertEngine(rules=rules, period_ms=3_600_000)
    monkeypatch.setattr(obs_alerts, "_engine", eng)
    monkeypatch.setattr(obs_alerts, "_configured", True)
    return eng


@pytest.fixture
def clean_readiness():
    obs_alerts.reset_readiness()
    yield
    obs_alerts.reset_readiness()


@pytest.fixture
def no_module_engine(monkeypatch):
    """Force the module surface to 'alerting off' regardless of what
    other tests configured, without re-reading the env flag."""
    monkeypatch.setattr(obs_alerts, "_engine", None)
    monkeypatch.setattr(obs_alerts, "_configured", True)
    yield


# ------------------------------------------------------------- rule kinds
class TestRuleValidation:
    def test_bad_name_kind_severity_op_raise(self):
        with pytest.raises(ValueError):
            obs_alerts.AlertRule(name="Bad-Name", kind="threshold")
        with pytest.raises(ValueError):
            obs_alerts.AlertRule(name="x", kind="nope")
        with pytest.raises(ValueError):
            obs_alerts.AlertRule(name="x", kind="threshold",
                                 severity="fatal")
        with pytest.raises(ValueError):
            obs_alerts.AlertRule(name="x", kind="threshold", op="ge")

    def test_staleness_needs_a_window(self):
        with pytest.raises(ValueError):
            obs_alerts.AlertRule(name="x", kind="staleness",
                                 metric="serve.zz")
        obs_alerts.AlertRule(name="x", kind="staleness",
                             metric="serve.zz", stall_cycles=1)

    def test_duplicate_rule_names_raise(self, monkeypatch):
        monkeypatch.delenv("BCG_TPU_ALERT_EVENTS", raising=False)
        r = obs_alerts.AlertRule(name="dup", kind="threshold",
                                 metric="serve.zz")
        with pytest.raises(ValueError):
            obs_alerts.AlertEngine(rules=[r, r], period_ms=3_600_000)

    def test_default_ruleset_is_valid_and_named(self):
        rules = obs_alerts.build_default_rules()
        names = {r.name for r in rules}
        assert len(names) == len(rules)
        for expected in ("slo_burn", "engine_errors", "engine_rebuilt",
                         "dispatch_retries", "heartbeat_stale",
                         "fleet_straggler", "chaos_unrecovered"):
            assert expected in names
        assert {r.severity for r in rules} <= set(obs_alerts.SEVERITIES)


class TestThresholdRule:
    def test_fires_above_resolves_below_and_absent_never_fires(
            self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="t_level", kind="threshold",
            metric="serve.zz_alerts_level", op="gt", value=10,
        )
        eng = _engine_with(monkeypatch, [rule])
        eng.evaluate_once()
        assert eng.firing() == []  # absent metric: absence != breach
        obs_counters.set_gauge("serve.zz_alerts_level", 11)
        eng.evaluate_once()
        assert eng.firing() == ["t_level"]
        assert obs_counters.value("alert.firing.t_level") == 1
        obs_counters.set_gauge("serve.zz_alerts_level", 3)
        eng.evaluate_once()
        assert eng.firing() == []
        assert obs_counters.value("alert.firing.t_level") == 0
        assert (eng.fired, eng.resolved, eng.flaps) == (1, 1, 0)

    def test_lt_op(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="t_floor", kind="threshold",
            metric="serve.zz_alerts_floor", op="lt", value=5,
        )
        eng = _engine_with(monkeypatch, [rule])
        obs_counters.set_gauge("serve.zz_alerts_floor", 7)
        eng.evaluate_once()
        assert eng.firing() == []
        obs_counters.set_gauge("serve.zz_alerts_floor", 2)
        eng.evaluate_once()
        assert eng.firing() == ["t_floor"]

    def test_for_cycles_debounce(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="t_slow", kind="threshold", for_cycles=2,
            metric="serve.zz_alerts_debounce", op="gt", value=0,
        )
        eng = _engine_with(monkeypatch, [rule])
        obs_counters.set_gauge("serve.zz_alerts_debounce", 1)
        eng.evaluate_once()
        eng.evaluate_once()
        assert eng.fired == 0  # held 2 cycles: still within the debounce
        eng.evaluate_once()
        assert eng.firing() == ["t_slow"] and eng.fired == 1
        # A blip that clears before the debounce expires never fires.
        obs_counters.set_gauge("serve.zz_alerts_debounce", 0)
        eng.evaluate_once()
        obs_counters.set_gauge("serve.zz_alerts_debounce", 1)
        eng.evaluate_once()
        obs_counters.set_gauge("serve.zz_alerts_debounce", 0)
        eng.evaluate_once()
        assert eng.fired == 1


class TestDeltaRateRule:
    def test_movement_fires_quiet_resolves_refire_flaps(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="d_move", kind="delta_rate", metric="serve.zz_alerts_errs",
        )
        eng = _engine_with(monkeypatch, [rule])
        obs_counters.inc("serve.zz_alerts_errs", 100)
        eng.evaluate_once()
        # First cycle has no base snapshot: pre-existing counts are NOT
        # movement (a process with history can't page at boot).
        assert eng.firing() == []
        obs_counters.inc("serve.zz_alerts_errs", 2)
        eng.evaluate_once()
        assert eng.firing() == ["d_move"]
        obs_counters.inc("serve.zz_alerts_errs", 1)
        eng.evaluate_once()
        assert eng.fired == 1  # still moving: SAME episode, no re-fire
        eng.evaluate_once()
        assert eng.firing() == [] and eng.resolved == 1
        obs_counters.inc("serve.zz_alerts_errs", 5)
        eng.evaluate_once()
        assert eng.fired == 2 and eng.flaps == 1
        assert obs_counters.value("alert.flaps") >= 1

    def test_wildcard_sums_family(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="d_fam", kind="delta_rate", metric="engine.zz_alerts_re.*",
            value=1,  # more than one retrace per window
        )
        eng = _engine_with(monkeypatch, [rule])
        eng.evaluate_once()
        obs_counters.inc("engine.zz_alerts_re.a", 1)
        eng.evaluate_once()
        assert eng.firing() == []  # family moved by 1: not > 1
        obs_counters.inc("engine.zz_alerts_re.a", 1)
        obs_counters.inc("engine.zz_alerts_re.b", 1)
        eng.evaluate_once()
        assert eng.firing() == ["d_fam"]

    def test_unless_metric_suppresses_recovered_movement(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="d_unless", kind="delta_rate",
            metric="chaos.zz_alerts_inj",
            unless_metric="serve.zz_alerts_rec",
        )
        eng = _engine_with(monkeypatch, [rule])
        eng.evaluate_once()
        obs_counters.inc("chaos.zz_alerts_inj", 1)
        obs_counters.inc("serve.zz_alerts_rec", 1)
        eng.evaluate_once()
        assert eng.firing() == []  # injected WITH recovery: suppressed
        obs_counters.inc("chaos.zz_alerts_inj", 1)
        eng.evaluate_once()
        assert eng.firing() == ["d_unless"]  # injected, no recovery


class TestBurnRateRule:
    RULE = dict(
        name="b_slo", kind="burn_rate", metric="serve.zz_alerts_viol",
        requests_metric="serve.zz_alerts_req", budget=0.05,
        burn_factor=2.0, fast_cycles=1, slow_cycles=3,
    )

    def test_burn_above_budget_fires_and_recovery_resolves(
            self, monkeypatch):
        eng = _engine_with(monkeypatch, [obs_alerts.AlertRule(**self.RULE)])
        eng.evaluate_once()
        obs_counters.inc("serve.zz_alerts_req", 100)
        obs_counters.inc("serve.zz_alerts_viol", 50)
        eng.evaluate_once()
        # 50% violation fraction > 0.05 * 2 in both windows (slow
        # clamps to since-start early in a run).
        assert eng.firing() == ["b_slo"]
        obs_counters.inc("serve.zz_alerts_req", 100)
        eng.evaluate_once()
        assert eng.firing() == []  # fast window clean: burn over

    def test_within_budget_never_fires(self, monkeypatch):
        eng = _engine_with(monkeypatch, [obs_alerts.AlertRule(**self.RULE)])
        eng.evaluate_once()
        for _ in range(4):
            obs_counters.inc("serve.zz_alerts_req", 100)
            obs_counters.inc("serve.zz_alerts_viol", 1)  # 1% < 10% burn
            eng.evaluate_once()
        assert eng.fired == 0

    def test_no_denominator_movement_no_fire(self, monkeypatch):
        eng = _engine_with(monkeypatch, [obs_alerts.AlertRule(**self.RULE)])
        eng.evaluate_once()
        obs_counters.inc("serve.zz_alerts_viol", 50)
        eng.evaluate_once()
        assert eng.fired == 0  # violations without traffic: no fraction


class TestStalenessRule:
    def test_heartbeat_age_fires_and_fresh_beat_resolves(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="s_hb", kind="staleness", metric="fleet.zz_alerts_hb",
            max_age_ms=15_000.0,
        )
        eng = _engine_with(monkeypatch, [rule])
        t0 = 1_000_000_000_000.0  # synthetic epoch-ms clock
        obs_counters.set_gauge("fleet.zz_alerts_hb", t0)
        eng.evaluate_once(now_ms=t0 + 1_000)
        assert eng.firing() == []
        eng.evaluate_once(now_ms=t0 + 20_000)
        assert eng.firing() == ["s_hb"]
        obs_counters.set_gauge("fleet.zz_alerts_hb", t0 + 20_000)
        eng.evaluate_once(now_ms=t0 + 21_000)
        assert eng.firing() == [] and eng.resolved == 1

    def test_stalled_value_fires_and_movement_resolves(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="s_wm", kind="staleness", metric="fleet.zz_alerts_wm",
            stall_cycles=2,
        )
        eng = _engine_with(monkeypatch, [rule])
        obs_counters.set_gauge("fleet.zz_alerts_wm", 5)
        eng.evaluate_once()  # first sight: nothing to compare
        eng.evaluate_once()  # unchanged x1
        assert eng.firing() == []
        eng.evaluate_once()  # unchanged x2: stalled
        assert eng.firing() == ["s_wm"]
        obs_counters.set_gauge("fleet.zz_alerts_wm", 6)
        eng.evaluate_once()
        assert eng.firing() == []

    def test_absent_metric_never_stalls(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="s_gone", kind="staleness",
            metric="fleet.zz_alerts_never_registered", stall_cycles=1,
        )
        eng = _engine_with(monkeypatch, [rule])
        for _ in range(4):
            eng.evaluate_once()
        assert eng.fired == 0


# ------------------------------------------------- readiness & health state
class TestReadiness:
    def test_push_veto_and_recovery(self, clean_readiness):
        ok, detail = obs_alerts.readiness()
        assert ok and detail["reasons"] == {}
        obs_alerts.mark_unready("engine", "device call hung")
        ok, detail = obs_alerts.readiness()
        assert not ok and detail["reasons"] == {"engine": "device call hung"}
        assert detail["status"] == "unready"
        obs_alerts.mark_ready("engine")
        ok, _ = obs_alerts.readiness()
        assert ok

    def test_transition_history_dedupes_and_bounds(self, clean_readiness):
        obs_alerts.mark_ready("scheduler")
        obs_alerts.mark_ready("scheduler")  # no state change: no record
        obs_alerts.mark_unready("engine", "hang")
        obs_alerts.mark_unready("engine", "hang")  # dedup
        obs_alerts.mark_ready("engine")
        hist = obs_alerts.readiness_history()
        assert [h["ready"] for h in hist] == [True, False, True]
        assert hist[1]["reasons"] == {"engine": "hang"}
        assert all("ts" in h for h in hist)

    def test_probes_read_at_request_time(self, clean_readiness):
        state = {"why": "queue over watermark"}
        obs_alerts.register_readiness_probe(
            "backpressure", lambda: state["why"]
        )
        ok, detail = obs_alerts.readiness()
        assert not ok
        assert detail["reasons"]["backpressure"] == "queue over watermark"
        state["why"] = None  # probe clears WITHOUT any push call
        ok, _ = obs_alerts.readiness()
        assert ok
        obs_alerts.clear_readiness("backpressure")
        state["why"] = "stale probe must be gone"
        ok, _ = obs_alerts.readiness()
        assert ok

    def test_health_wired_to_page_severity_only(self, monkeypatch,
                                                clean_readiness):
        page = obs_alerts.AlertRule(
            name="h_page", kind="threshold", severity="page",
            metric="serve.zz_alerts_page", op="gt", value=0,
        )
        warn = obs_alerts.AlertRule(
            name="h_warn", kind="threshold", severity="warn",
            metric="serve.zz_alerts_warn", op="gt", value=0,
        )
        eng = _engine_with(monkeypatch, [page, warn])
        obs_counters.set_gauge("serve.zz_alerts_page", 0)
        obs_counters.set_gauge("serve.zz_alerts_warn", 1)
        eng.evaluate_once()
        ok, detail = obs_alerts.health()
        assert ok and detail["page_firing"] == []  # warn is not a page
        obs_counters.set_gauge("serve.zz_alerts_page", 1)
        eng.evaluate_once()
        ok, detail = obs_alerts.health()
        assert not ok and detail["page_firing"] == ["h_page"]
        assert detail["status"] == "failing"

    def test_health_ok_with_alerting_off(self, no_module_engine,
                                         clean_readiness):
        ok, detail = obs_alerts.health()
        assert ok and detail["page_firing"] == []


# ----------------------------------------------------------- HTTP endpoints
def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture
def http_port():
    server, port = obs_export.start_http_server(0)
    yield port
    server.shutdown()


class TestEndpoints:
    def test_readyz_flips_with_pushed_state(self, http_port,
                                            clean_readiness,
                                            no_module_engine):
        code, body = _get(http_port, "/readyz")
        assert code == 200
        assert json.loads(body) == {"reasons": {}, "status": "ready"}
        obs_alerts.mark_unready("engine", "device call hung")
        code, body = _get(http_port, "/readyz")
        assert code == 503
        detail = json.loads(body)
        assert detail["status"] == "unready"
        assert detail["reasons"]["engine"] == "device call hung"
        obs_alerts.mark_ready("engine")
        code, _ = _get(http_port, "/readyz?verbose=1")  # query tolerated
        assert code == 200

    def test_healthz_flips_with_page_alert(self, http_port, monkeypatch,
                                           clean_readiness):
        rule = obs_alerts.AlertRule(
            name="h_http", kind="threshold", severity="page",
            metric="serve.zz_alerts_http", op="gt", value=0,
        )
        eng = _engine_with(monkeypatch, [rule])
        obs_counters.set_gauge("serve.zz_alerts_http", 0)
        eng.evaluate_once()
        code, body = _get(http_port, "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        obs_counters.set_gauge("serve.zz_alerts_http", 1)
        eng.evaluate_once()
        code, body = _get(http_port, "/healthz")
        assert code == 503
        assert json.loads(body)["page_firing"] == ["h_http"]

    def test_healthz_ok_without_alerting(self, http_port,
                                         no_module_engine,
                                         clean_readiness):
        code, body = _get(http_port, "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"

    def test_metrics_and_404_unchanged(self, http_port):
        code, body = _get(http_port, "/metrics")
        assert code == 200
        code, _ = _get(http_port, "/nope")
        assert code == 404


class TestExpositionFamily:
    def test_labeled_firing_family_rendered_while_engine_live(
            self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="x_expo", kind="threshold",
            metric="serve.zz_alerts_expo", op="gt", value=0,
        )
        eng = _engine_with(monkeypatch, [rule])
        obs_export.set_extra_blocks_provider(obs_alerts._firing_blocks)
        try:
            expo = obs_export.render_prometheus()
            assert "# HELP bcg_alert_firing" in expo
            assert "# TYPE bcg_alert_firing gauge" in expo
            assert 'bcg_alert_firing{rule="x_expo"} 0' in expo
            obs_counters.set_gauge("serve.zz_alerts_expo", 2)
            eng.evaluate_once()
            expo = obs_export.render_prometheus()
            assert 'bcg_alert_firing{rule="x_expo"} 1' in expo
        finally:
            obs_export.set_extra_blocks_provider(None)
        # Provider gone: the LABELED family disappears (the unlabeled
        # alert.firing.* registry gauges legitimately persist —
        # registries don't unregister).
        assert "bcg_alert_firing{" not in obs_export.render_prometheus()


# ------------------------------------------------------------ event stream
class TestEventStream:
    def _drive(self, monkeypatch, tmp_path):
        path = tmp_path / "alerts.jsonl"
        monkeypatch.setenv("BCG_TPU_ALERT_EVENTS", str(path))
        rules = [
            obs_alerts.AlertRule(
                name="e_page", kind="threshold", severity="page",
                metric="serve.zz_alerts_evt", op="gt", value=0,
                summary="synthetic page",
            ),
        ]
        eng = obs_alerts.AlertEngine(rules=rules, period_ms=3_600_000)
        obs_counters.set_gauge("serve.zz_alerts_evt", 1)
        eng.evaluate_once()
        obs_counters.set_gauge("serve.zz_alerts_evt", 0)
        eng.evaluate_once()
        eng.stop()  # closes + drains the sink
        return path

    def test_manifest_headed_transition_records(self, monkeypatch,
                                                tmp_path):
        path = self._drive(monkeypatch, tmp_path)
        recs = [json.loads(line) for line in
                path.read_text().splitlines() if line.strip()]
        assert recs[0]["event"] == "manifest"
        assert recs[0]["kind"] == "alert"
        assert "run_id" in recs[0] and "flags" in recs[0]
        alerts = [r for r in recs if r["event"] == "alert"]
        assert [(r["rule"], r["state"]) for r in alerts] == [
            ("e_page", "firing"), ("e_page", "resolved"),
        ]
        assert alerts[0]["severity"] == "page"
        assert alerts[0]["kind"] == "threshold"
        assert alerts[0]["value"] == 1
        assert alerts[0]["summary"] == "synthetic page"

    def test_alert_report_merges_streams(self, monkeypatch, tmp_path):
        path = self._drive(monkeypatch, tmp_path)
        proc = subprocess.run(
            [sys.executable, ALERT_REPORT, str(path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "alert timeline" in proc.stdout
        assert "FIRING" in proc.stdout and "resolved" in proc.stdout
        assert "e_page: 1 fired / 1 resolved (all resolved)" in proc.stdout
        assert "still firing" not in proc.stdout
        # Severity floor: an info filter keeps the page rule...
        proc2 = subprocess.run(
            [sys.executable, ALERT_REPORT, "--severity", "page", str(path)],
            capture_output=True, text=True, timeout=60,
        )
        assert "e_page" in proc2.stdout
        # ... and the script stays dependency-free (laptop-runnable).
        src = open(ALERT_REPORT).read()
        assert "import bcg_tpu" not in src and "from bcg_tpu" not in src

    def test_bench_trajectory_alert_out_joins_the_timeline(
            self, monkeypatch, tmp_path):
        runtime_stream = self._drive(monkeypatch, tmp_path)
        good = tmp_path / "BENCH_r01.json"
        bad = tmp_path / "BENCH_r02.json"
        good.write_text(json.dumps(
            {"n": 1, "rc": 0, "parsed": {"value": 10.0, "vs_baseline": 1.0}}
        ))
        bad.write_text(json.dumps(
            {"n": 2, "rc": 0, "parsed": {"value": 1.0, "vs_baseline": 0.1}}
        ))
        bench_stream = tmp_path / "bench-alerts.jsonl"
        proc = subprocess.run(
            [sys.executable, TRAJECTORY, str(good), str(bad),
             "--alert-out", str(bench_stream)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "BENCH REGRESSION" in proc.stderr
        recs = [json.loads(line) for line in
                bench_stream.read_text().splitlines()]
        assert recs[0]["event"] == "manifest"
        assert recs[0]["run_id"] == "bench-trajectory"
        assert recs[1]["rule"] == "bench_regression"
        assert recs[1]["state"] == "firing"
        # One merged timeline: the runtime stream AND the rc-2 verdict.
        merged = subprocess.run(
            [sys.executable, ALERT_REPORT, str(runtime_stream),
             str(bench_stream)],
            capture_output=True, text=True, timeout=60,
        )
        assert merged.returncode == 0, merged.stderr
        assert "bench_regression" in merged.stdout
        assert "e_page" in merged.stdout
        assert "still firing" in merged.stdout  # bench never resolves


# ----------------------------------------------------- publish + summaries
class TestPublish:
    def test_last_alerts_published_on_evaluate(self, monkeypatch):
        rule = obs_alerts.AlertRule(
            name="p_rule", kind="threshold",
            metric="serve.zz_alerts_pub", op="gt", value=0,
        )
        _engine_with(monkeypatch, [rule])
        monkeypatch.setattr(runtime_metrics, "LAST_ALERTS", None)
        obs_counters.set_gauge("serve.zz_alerts_pub", 1)
        obs_alerts.evaluate_now()
        snap = runtime_metrics.LAST_ALERTS
        assert snap is not None and snap["enabled"]
        assert snap["fired"] == 1 and snap["firing"] == ["p_rule"]
        assert snap["fired_by_rule"] == {"p_rule": 1}
        assert obs_alerts.summary()["firing"] == ["p_rule"]

    def test_off_surface_returns_none(self, no_module_engine, monkeypatch):
        monkeypatch.setattr(runtime_metrics, "LAST_ALERTS", None)
        assert obs_alerts.engine() is None
        assert not obs_alerts.enabled()
        assert obs_alerts.summary() is None
        obs_alerts.evaluate_now()  # no-op, must not publish
        assert runtime_metrics.LAST_ALERTS is None


# ------------------------------------------------------------- zero surface
# Worker for the exact-bytes subprocess pin: boots a scheduler (the
# production alerts-boot seam), serves one request, bumps one
# deterministic non-alert counter (so the unalerted exposition is
# non-empty and the byte comparison can't pass vacuously), asserts the
# thread/registry surface matches the flag, prints the exposition.
_EXPO_WORKER = """
import sys, threading
sys.path.insert(0, sys.argv[1])
expect_on = sys.argv[2] == "on"
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.obs import counters as obs_counters, export as obs_export
from bcg_tpu.serve.scheduler import Scheduler
SCHEMA = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string", "minLength": 1,
                              "maxLength": 25},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string", "minLength": 1,
                             "maxLength": 25},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}
sched = Scheduler(FakeEngine(seed=0, policy="consensus"),
                  linger_ms=0, bucket_rows=4)
out = sched.submit_and_wait(
    ("json",),
    [("sys", "Round 2. agent_1 value: 17. Your current value: 17. "
      "Decide.", SCHEMA)],
    [0.0], [64],
)
assert len(out) == 1 and "error" not in out[0], out
sched.close()
obs_counters.inc("engine.probe", 3)
names = [t.name for t in threading.enumerate()]
assert ("bcg-alert-eval" in names) == expect_on, names
registered = [n for n in obs_counters.snapshot() if n.startswith("alert.")]
assert bool(registered) == expect_on, registered
sys.stdout.write(obs_export.render_prometheus())
"""


class TestZeroSurface:
    def test_in_process_off_adds_no_alert_names(self, no_module_engine):
        before = set(obs_counters.snapshot())
        assert obs_alerts.maybe_start() is None
        obs_alerts.evaluate_now()
        obs_alerts.mark_ready("probe_component")  # plain module state
        obs_alerts.clear_readiness("probe_component")
        new = set(obs_counters.snapshot()) - before
        assert not [n for n in new if n.startswith("alert.")], new

    def test_exposition_exact_bytes_vs_unalerted_subprocess(self):
        """The only exposition difference an enabled alert plane may
        make is the alert namespace itself (``bcg_alert_*`` counters,
        gauges, and the labeled firing family): filtering those lines
        out of the alerted run's exposition must reproduce the
        unalerted run's exposition EXACTLY, byte for byte (fresh
        subprocess per arm = a pristine registry, which an in-process
        test cannot get back once other tests constructed engines)."""
        def scrape(flag_on: bool) -> str:
            env = {**os.environ, "JAX_PLATFORMS": "cpu",
                   "PYTHONPATH": REPO, "BCG_TPU_ALERT_MS": "3600000"}
            env.pop("BCG_TPU_ALERTS", None)
            env.pop("BCG_TPU_ALERT_EVENTS", None)
            if flag_on:
                env["BCG_TPU_ALERTS"] = "1"
            proc = subprocess.run(
                [sys.executable, "-c", _EXPO_WORKER, REPO,
                 "on" if flag_on else "off"],
                capture_output=True, text=True, timeout=180, env=env,
                cwd=REPO,
            )
            assert proc.returncode == 0, proc.stderr
            return proc.stdout

        def mask_wall_clock(expo: str) -> str:
            # The serve run's *_ms histogram SUMS are wall-clock and
            # differ between any two runs; every other line (names,
            # bucket counts, event counters) must stay byte-exact.
            return "\n".join(
                line.split(" ")[0] + " <wall>"
                if "_ms_sum" in line.split(" ")[0] else line
                for line in expo.splitlines()
            ) + "\n"

        expo_off = scrape(flag_on=False)
        expo_on = scrape(flag_on=True)
        assert "bcg_engine_probe_total" in expo_off  # non-vacuous
        assert "bcg_alert_" not in expo_off
        # The alerted run really surfaced the namespace...
        assert "bcg_alert_evaluations_total" in expo_on
        assert 'bcg_alert_firing{rule="slo_burn"} 0' in expo_on
        # ... and removing it reproduces the unalerted bytes exactly.
        kept = [line for line in expo_on.splitlines()
                if "bcg_alert_" not in line]
        filtered = "\n".join(kept) + ("\n" if kept else "")
        assert mask_wall_clock(filtered) == mask_wall_clock(expo_off)


# ----------------------------------------------------------- the perf gate
@pytest.fixture(scope="module")
def alerts_gate():
    """One in-process run of the perf_gate alerts scenario — this file
    owns the ``alerts.`` namespace's resurface contract
    (tests/test_perf_gate.py NAMESPACE_OWNERS)."""
    mod = _load_gate()
    return mod, mod.run_alerts_scenario()


class TestPerfGateAlerts:
    def test_scenario_green_and_nothing_stale(self, alerts_gate):
        mod, measured = alerts_gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(measured, mod.load_baseline(),
                                    ("alerts",))
        assert findings == [], "\n".join(findings)

    def test_acceptance_values(self, alerts_gate):
        _, measured = alerts_gate
        # One episode per expected recovery rule for 3 injected faults.
        assert measured["alerts.chaos_alerts_fired"] == 3.0
        assert measured["alerts.fault_coverage"] >= 1.0
        # Acceptance: flap count and false positives 0 EXACT; every
        # fired alert resolved by run end.
        assert measured["alerts.flaps"] == 0.0
        assert measured["alerts.false_positives"] == 0.0
        assert measured["alerts.unresolved_at_end"] == 0.0
        assert measured["alerts.unexpected_alerts"] == 0.0
        # Health flipped failing during the page episode and back;
        # readiness flipped unready inside the hang window and back.
        assert measured["alerts.healthz_flip"] == 1.0
        assert measured["alerts.readyz_flip"] == 1.0
        assert measured["alerts.event_stream_ok"] == 1.0

    def test_alerts_off_fails_naming_the_metrics(self, alerts_gate):
        """Acceptance: the evaluator silently off can never read as a
        green alerting gate — the injection must fail naming the
        floored metrics."""
        mod, _ = alerts_gate
        measured = mod.run_alerts_scenario(inject="alerts-off")
        findings = mod.check_metrics(measured, mod.load_baseline())
        for name in ("alerts.rules_evaluated", "alerts.chaos_alerts_fired",
                     "alerts.fault_coverage", "alerts.healthz_flip",
                     "alerts.event_stream_ok"):
            assert any(name in f for f in findings), (name, findings)
        # Readiness is plain module state the scheduler pushes with
        # alerting off too — the gateway's /readyz does not dim.
        assert measured["alerts.readyz_flip"] == 1.0

    def test_removing_each_entry_resurfaces_its_finding(self, alerts_gate):
        mod, measured = alerts_gate
        baseline = mod.load_baseline()
        entries = [n for n in baseline["metrics"]
                   if n.startswith("alerts.")]
        assert sorted(entries) == [
            "alerts.chaos_alerts_fired", "alerts.event_stream_ok",
            "alerts.false_positives", "alerts.fault_coverage",
            "alerts.flaps", "alerts.healthz_flip", "alerts.readyz_flip",
            "alerts.rules_evaluated", "alerts.unexpected_alerts",
            "alerts.unresolved_at_end",
        ]
        for removed in entries:
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(measured, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)

    @pytest.mark.slow
    def test_cli_injection_exits_nonzero_and_names_metric(self):
        """Subprocess CLI arm (slow: cold jax import + two serve runs).
        The exit-code/naming contract is already pinned in-process
        above; this run keeps the exact `--scenarios alerts
        --inject-regression alerts-off` invocation honest in the full
        suite."""
        proc = subprocess.run(
            [sys.executable, GATE_SCRIPT, "--scenarios", "alerts",
             "--inject-regression", "alerts-off"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "alerts.chaos_alerts_fired" in proc.stderr
        assert "PERF REGRESSION" in proc.stderr
