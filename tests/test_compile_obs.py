"""Compile-cost observability (bcg_tpu/obs/compile.py,
BCG_TPU_COMPILE_OBS) + profiler capture windows (BCG_TPU_PROFILE).

The PR's acceptance contract, asserted here:

* flag off => ZERO surface: nothing registered, no threads, Prometheus
  exposition byte-identical to an untouched process (subprocess
  exact-bytes pin, the hostsync idiom);
* a provoked retrace (new shape signature on a warm engine) yields
  exactly ONE structured cause record naming the changed argument
  (``max_new 64→96``), counted under ``engine.retrace_cause.<kind>``
  and streamed as JSONL when the flag value is a path;
* per-entry compile-time histograms (``engine.compile_ms.<entry>``)
  populate at every trace-cache-miss seam, split first-compile vs
  retrace, with the census's AOT lower+compile charged separately;
* the perf_gate ``compile`` scenario is green vs justified baselines,
  its entries resurface when removed, and ``--inject-regression
  compile-off`` fails NAMING the metrics (this file owns the
  ``compile.`` namespace in tests/test_perf_gate.py's
  NAMESPACE_OWNERS);
* ``BCG_TPU_PROFILE`` + ``BCG_TPU_PROFILE_ROUNDS=a-b`` bound one
  jax.profiler window over the selected rounds/dispatches, stamped
  with a fleet-identity manifest.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading

import pytest

import bench
from bcg_tpu.obs import compile as obs_compile
from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.obs.compile import _parse_flag, _parse_rounds, diff_signature
from bcg_tpu.runtime import metrics as runtime_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO, "scripts", "perf_gate.py")

DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string", "minLength": 1,
                              "maxLength": 25},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string", "minLength": 1,
                             "maxLength": 25},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}


def _load_script(name):
    path = os.path.join(REPO, "scripts", name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------- signature diff
class TestSignatureDiff:
    def test_single_changed_argument_named(self):
        cause = diff_signature(
            (("sig",), 48, 1.0, "xla", "xla"),
            [(("sig",), 32, 1.0, "xla", "xla")],
            names=("guided_sig", "max_new", "top_p", "attn_impl",
                   "sampler_impl"),
        )
        assert cause["arg"] == "max_new"
        assert cause["old"] == 32 and cause["new"] == 48
        assert cause["cause"] == "static_knob"
        assert cause["changed"] == ["max_new"]

    def test_numeric_non_knob_is_shape(self):
        cause = diff_signature(
            ("full", 4, 128, 256), [("full", 3, 128, 256)],
            names=("path", "batch", "prompt_window", "cache_len"),
        )
        assert cause["cause"] == "shape"
        assert cause["arg"] == "batch"

    def test_path_change_classified_path(self):
        cause = diff_signature(
            ("suffix", 3, 64, 0, 256), [("paged", 3, 64, 0, 256)],
            names=("path", "batch", "suffix_window", "prefix_len",
                   "cache_len"),
        )
        assert cause["cause"] == "path"

    def test_dtype_change_classified_dtype(self):
        cause = diff_signature(("x", "int8"), [("x", "bf16")],
                               names=("guided_sig", "kv"))
        assert cause["cause"] == "dtype"

    def test_impl_marker_is_static_knob(self):
        cause = diff_signature(
            (("s",), 32, 1.0, "pallas", "xla"),
            [(("s",), 32, 1.0, "xla", "xla")],
            names=("guided_sig", "max_new", "top_p", "attn_impl",
                   "sampler_impl"),
        )
        assert cause["cause"] == "static_knob"
        assert cause["arg"] == "attn_impl"

    def test_nearest_prior_wins_fewest_diffs(self):
        # Two priors: one differs in 1 position, one in 3 — the diff
        # must anchor on the 1-position neighbor.
        cause = diff_signature(
            ("full", 4, 128, 256),
            [("full", 2, 64, 512), ("full", 4, 128, 192)],
            names=("path", "batch", "prompt_window", "cache_len"),
        )
        assert cause["arg"] == "cache_len"
        assert cause["old"] == 192 and cause["new"] == 256
        assert cause["changed"] == ["cache_len"]

    def test_recency_breaks_ties(self):
        # Both priors differ in exactly one position; the LATER one
        # (most recently compiled) anchors the diff.
        cause = diff_signature(
            ("full", 4, 128, 256),
            [("full", 4, 128, 512), ("full", 4, 128, 192)],
            names=("path", "batch", "prompt_window", "cache_len"),
        )
        assert cause["old"] == 192

    def test_arity_mismatch(self):
        cause = diff_signature(("full", 4, 128, 256),
                               [("suffix", 4, 16, 0, 256)])
        assert cause["cause"] == "arity"
        assert cause["old"] == 5 and cause["new"] == 4

    def test_nested_tuple_recurses(self):
        cause = diff_signature(
            ((("json", 3), 4, 96), 32),
            [((("json", 3), 4, 64), 32)],
            names=("guided_sig", "max_new"),
        )
        assert cause["arg"] == "guided_sig"
        assert cause["cause"] == "shape"

    def test_multiple_changed_args_listed_primary_first(self):
        cause = diff_signature(
            ("full", 8, 256, 512), [("full", 4, 128, 256)],
            names=("path", "batch", "prompt_window", "cache_len"),
        )
        assert cause["arg"] == "batch"
        assert cause["changed"] == ["batch", "prompt_window", "cache_len"]


class TestFlagParsing:
    @pytest.mark.parametrize("raw,expect", [
        (None, (False, None)),
        ("", (False, None)),
        ("0", (False, None)),
        ("off", (False, None)),
        ("1", (True, None)),
        ("true", (True, None)),
        ("/tmp/causes.jsonl", (True, "/tmp/causes.jsonl")),
    ])
    def test_dual_mode_flag(self, raw, expect):
        assert _parse_flag(raw) == expect

    @pytest.mark.parametrize("raw,expect", [
        ("3-5", (3, 5)),
        ("4", (4, 4)),
        (" 2 - 7 ", (2, 7)),
        ("9-3", (3, 9)),  # normalized, never an empty window
    ])
    def test_rounds_parse(self, raw, expect):
        assert _parse_rounds(raw) == expect

    def test_rounds_unparseable_warns_and_defaults(self, capsys):
        assert _parse_rounds("round-two") == (1, 2)
        assert "BCG_TPU_PROFILE_ROUNDS" in capsys.readouterr().err


# ------------------------------------------------------------ zero surface
@pytest.fixture
def unobserved(monkeypatch):
    """Compile observability OFF with a fresh read-once cache."""
    monkeypatch.delenv("BCG_TPU_COMPILE_OBS", raising=False)
    monkeypatch.delenv("BCG_TPU_PROFILE", raising=False)
    obs_compile.reset()
    yield
    obs_compile.reset()


# Worker for the exact-bytes subprocess pin: plays the hermetic game,
# pokes the compile-observer seam directly (twice — the second note is
# a retrace, so an ENABLED observer registers its whole namespace),
# bumps one deterministic non-compile counter (non-vacuous comparison),
# and prints the exposition + live thread names as JSON.
_EXPO_WORKER = """
import json, sys, threading
sys.path.insert(0, sys.argv[1])
from bcg_tpu.api import run_simulation
from bcg_tpu.obs import compile as obs_compile
from bcg_tpu.obs import counters as obs_counters, export as obs_export
out = run_simulation(n_agents=5, byzantine_count=1, max_rounds=6,
                     backend="fake", seed=7)
assert out["metrics"]["total_rounds"] >= 1
obs_compile.note_signature("probe_entry", ("x", 1), [])
obs_compile.note_signature("probe_entry", ("x", 2), [("x", 1)],
                           names=("path", "n"))
with obs_compile.time_block("probe_entry"):
    pass
obs_counters.inc("engine.probe", 3)
print(json.dumps({
    "expo": obs_export.render_prometheus(),
    "threads": sorted(t.name for t in threading.enumerate()),
}))
"""

_COMPILE_MARKERS = ("compile_obs", "compile_ms", "retrace_cause")


class TestZeroSurface:
    def test_disabled_module_is_inert(self, unobserved):
        before = set(obs_counters.snapshot())
        assert obs_compile.observer() is None
        assert not obs_compile.enabled()
        obs_compile.note_signature("probe", ("a",), [])
        with obs_compile.time_block("probe"):
            pass
        with obs_compile.measure_aot("probe"):
            pass
        obs_compile.publish()
        assert obs_compile.summary() is None
        assert obs_compile.brief() is None
        assert obs_compile.cause_records() == []
        new = set(obs_counters.snapshot()) - before
        assert not [n for n in new
                    if any(m in n for m in _COMPILE_MARKERS)], new

    def test_disabled_profile_span_is_shared_noop(self, unobserved):
        cm = obs_compile.profile_span("round", 1)
        assert cm is obs_compile._NULL_CM
        assert obs_compile.profile_dispatch() is obs_compile._NULL_CM

    def test_exposition_exact_bytes_and_threads_vs_subprocess(self):
        """Flag off => the exposition is byte-identical to an untouched
        process and no thread starts; flag on ('1', no sink path) =>
        the ONLY difference is the compile namespace itself, and STILL
        no thread (the JSONL sink thread exists only when the flag
        value is a path)."""
        def run(flag: str = None) -> dict:
            env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
            env.pop("BCG_TPU_COMPILE_OBS", None)
            if flag is not None:
                env["BCG_TPU_COMPILE_OBS"] = flag
            proc = subprocess.run(
                [sys.executable, "-c", _EXPO_WORKER, REPO],
                capture_output=True, text=True, timeout=180, env=env,
                cwd=REPO,
            )
            assert proc.returncode == 0, proc.stderr
            return json.loads(proc.stdout)

        off = run(None)
        on = run("1")
        assert "bcg_engine_probe_total" in off["expo"]  # non-vacuous
        assert not any(m in off["expo"] for m in _COMPILE_MARKERS)
        # The enabled run really surfaced the namespace...
        assert "bcg_engine_compile_obs_cache_entries" in on["expo"]
        assert "bcg_engine_retrace_cause_shape_total" in on["expo"]
        assert "bcg_engine_compile_ms_probe_entry_bucket" in on["expo"]
        # ... and removing it reproduces the untouched bytes exactly.
        kept = [
            line for line in on["expo"].splitlines()
            if not any(m in line for m in _COMPILE_MARKERS)
        ]
        assert "\n".join(kept) + "\n" == off["expo"]
        # Zero new threads, off AND on-without-sink.
        assert off["threads"] == on["threads"]


# ------------------------------------------------- observed engine workload
@pytest.fixture(scope="module")
def workload(tmp_path_factory):
    """One tiny real-engine run with the observer ON and the JSONL sink
    engaged (flag = path): cold call, identical warm repeat, provoked
    retrace (max_tokens 64 -> 96).  Shared module-wide — engine boots
    are the expensive part of this file."""
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine

    events = str(tmp_path_factory.mktemp("compile-obs") / "causes.jsonl")
    prior = os.environ.get("BCG_TPU_COMPILE_OBS")  # lint: ignore[BCG-ENV-RAW]
    os.environ["BCG_TPU_COMPILE_OBS"] = events
    obs_compile.reset()
    before = obs_counters.snapshot()
    prompts = [("honest agent system prompt", "Round 3: propose a value",
                DECISION)]
    try:
        eng = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048,
        ))
        try:
            cold = eng.batch_generate_json(prompts, temperature=0.0,
                                           max_tokens=64)
            warm_before = obs_counters.snapshot()
            eng.batch_generate_json(prompts, temperature=0.0, max_tokens=64)
            warm_moved = obs_counters.delta(warm_before)
            eng.batch_generate_json(prompts, temperature=0.0, max_tokens=96)
        finally:
            eng.shutdown()
        causes = obs_compile.cause_records()
        summary = obs_compile.summary()
        brief = obs_compile.brief()
        published = runtime_metrics.LAST_COMPILE_OBS
        moved = obs_counters.delta(before)
        snapshot = obs_counters.snapshot()
    finally:
        if prior is None:
            os.environ.pop("BCG_TPU_COMPILE_OBS", None)
        else:
            os.environ["BCG_TPU_COMPILE_OBS"] = prior
        obs_compile.reset()  # closes + drains the sink
    return {
        "rows": cold, "causes": causes, "summary": summary,
        "brief": brief, "published": published, "moved": moved,
        "warm_moved": warm_moved, "snapshot": snapshot, "events": events,
    }


class TestCompileAccounting:
    def test_rows_valid(self, workload):
        assert all(isinstance(r, dict) and "error" not in r
                   for r in workload["rows"])

    def test_per_entry_histograms_populate(self, workload):
        moved = workload["moved"]
        # Cold + provoked = 2 timed compiles per entry.
        assert moved.get("engine.compile_ms.prefill.count") == 2
        assert moved.get("engine.compile_ms.decode_loop.count") == 2
        assert workload["snapshot"]["engine.compile_ms.prefill.sum"] > 0

    def test_first_vs_retrace_split(self, workload):
        snap = workload["snapshot"]
        assert snap["engine.compile_obs.first_compile_ms"] > 0
        assert snap["engine.compile_obs.retrace_ms"] > 0

    def test_cache_entry_gauge(self, workload):
        # prefill (cold + provoked) + decode_loop (cold + provoked).
        assert workload["snapshot"]["engine.compile_obs.cache_entries"] == 4
        assert workload["brief"]["cache_entries"] == 4

    def test_warm_repeat_observes_nothing(self, workload):
        warm = {
            k: v for k, v in workload["warm_moved"].items()
            if any(m in k for m in _COMPILE_MARKERS)
        }
        assert warm == {}, warm

    def test_summary_per_entry_table(self, workload):
        table = workload["summary"]["compile_ms_by_entry"]
        assert set(table) == {"prefill", "decode_loop"}
        for row in table.values():
            assert row["count"] == 2 and row["total_ms"] > 0

    def test_published_to_last_compile_obs(self, workload):
        pub = workload["published"]
        assert pub is not None
        assert pub["cache_entries"] == 4
        assert "compile_ms_by_entry" in pub


class TestRetraceCause:
    def test_exactly_one_cause_record_per_retrace(self, workload):
        # Provoked max_tokens 64->96 retraces exactly two entries:
        # decode_loop (max_new) and prefill (cache_len) — one record
        # each, and the cause counters agree.
        assert len(workload["causes"]) == 2
        moved = workload["moved"]
        cause_total = sum(
            v for k, v in moved.items()
            if k.startswith("engine.retrace_cause.")
        )
        retrace_total = sum(
            v for k, v in moved.items()
            if k.startswith("engine.retrace.")
        )
        assert cause_total == retrace_total == 2

    def test_decode_loop_cause_names_max_new(self, workload):
        records = [c for c in workload["causes"]
                   if c["entry"] == "decode_loop"]
        assert len(records) == 1
        rec = records[0]
        assert rec["arg"] == "max_new"
        assert rec["old"] == 64 and rec["new"] == 96
        assert rec["cause"] == "static_knob"
        assert rec["changed"] == ["max_new"]

    def test_prefill_cause_names_cache_len(self, workload):
        records = [c for c in workload["causes"] if c["entry"] == "prefill"]
        assert len(records) == 1
        assert records[0]["arg"] == "cache_len"
        assert records[0]["cause"] == "shape"

    def test_attribution_jit_entry_when_untraced(self, workload):
        # Tracing is off in this workload, so the hostsync attribution
        # ladder lands on the jit-entry rung.
        assert {c["span"] for c in workload["causes"]} == {
            "jit_decode_loop", "jit_prefill"
        }

    def test_jsonl_stream_manifest_and_records(self, workload):
        with open(workload["events"]) as f:
            lines = [json.loads(line) for line in f if line.strip()]
        assert lines[0]["event"] == "manifest"
        assert lines[0]["kind"] == "compile"
        assert lines[0]["schema_version"] is not None
        assert "run_id" in lines[0] and "host" in lines[0]
        records = [r for r in lines if r["event"] == "retrace_cause"]
        assert len(records) == 2
        by_entry = {r["entry"]: r for r in records}
        assert by_entry["decode_loop"]["arg"] == "max_new"
        assert by_entry["decode_loop"]["old"] == 64
        assert by_entry["decode_loop"]["new"] == 96


class TestTimingHandoff:
    """The note/dispatch ordering protocol, on a controlled clock —
    regression cover for the stale-stash bug: a retrace that follows
    warm (steady-state) dispatches must time the actual compile, not
    consume the previous warm call's execute time."""

    @pytest.fixture
    def clocked(self, monkeypatch):
        monkeypatch.setenv("BCG_TPU_COMPILE_OBS", "1")
        obs_compile.reset()
        clock = {"t": 0.0}
        monkeypatch.setattr(obs_compile.time, "perf_counter",
                            lambda: clock["t"])
        yield obs_compile.observer(), clock
        obs_compile.reset()

    def test_retrace_after_warm_dispatch_times_the_compile(self, clocked):
        o, clock = clocked
        first_before = obs_counters.value(
            "engine.compile_obs.first_compile_ms")
        retrace_before = obs_counters.value("engine.compile_obs.retrace_ms")
        hist_before = obs_counters.value(
            "engine.compile_ms.handoff_loop.count")
        # Cold: note (pending), then the dispatch pays a 300 ms compile.
        o.note_signature("handoff_loop", ("g", 32), [],
                         names=("guided_sig", "max_new"))
        with o.time_block("handoff_loop"):
            clock["t"] += 0.300
        # Warm steady-state dispatch: 10 ms execute, no note.
        with o.time_block("handoff_loop"):
            clock["t"] += 0.010
        # Retrace: note (pending — must DISCARD the warm stash), then
        # the dispatch pays a 250 ms compile.
        o.note_signature("handoff_loop", ("g", 48), [("g", 32)],
                         names=("guided_sig", "max_new"))
        with o.time_block("handoff_loop"):
            clock["t"] += 0.250
        first = (obs_counters.value("engine.compile_obs.first_compile_ms")
                 - first_before)
        retrace = (obs_counters.value("engine.compile_obs.retrace_ms")
                   - retrace_before)
        timed = (obs_counters.value("engine.compile_ms.handoff_loop.count")
                 - hist_before)
        assert first == pytest.approx(300.0)
        assert retrace == pytest.approx(250.0)  # NOT the warm 10 ms
        assert timed == 2  # the warm dispatch is never observed

    def test_stash_mode_consumes_the_preceding_block(self, clocked):
        o, clock = clocked
        first_before = obs_counters.value(
            "engine.compile_obs.first_compile_ms")
        # Prefill ordering: timed dispatch first, note after ("stash").
        with o.time_block("handoff_prefill"):
            clock["t"] += 0.120
        o.note_signature("handoff_prefill", ("full", 3, 64, 256), [],
                         names=("path", "batch", "prompt_window",
                                "cache_len"),
                         timing="stash")
        first = (obs_counters.value("engine.compile_obs.first_compile_ms")
                 - first_before)
        assert first == pytest.approx(120.0)

    def test_failed_dispatch_clears_pending_without_recording(self, clocked):
        o, clock = clocked
        hist_before = obs_counters.value(
            "engine.compile_ms.handoff_fail.count")
        o.note_signature("handoff_fail", ("a",), [])
        with pytest.raises(RuntimeError):
            with o.time_block("handoff_fail"):
                clock["t"] += 0.5
                raise RuntimeError("dispatch died")
        # A later successful warm dispatch must not inherit the marker.
        with o.time_block("handoff_fail"):
            clock["t"] += 0.010
        timed = (obs_counters.value("engine.compile_ms.handoff_fail.count")
                 - hist_before)
        assert timed == 0


class TestAotSeam:
    def test_census_aot_compile_charged(self, unobserved, monkeypatch,
                                        tmp_path):
        import numpy as np
        import jax

        from bcg_tpu.obs import hlo as obs_hlo

        monkeypatch.setenv("BCG_TPU_COMPILE_OBS", "1")
        obs_compile.reset()
        obs_hlo.enable(True)
        before = obs_counters.snapshot()
        try:
            jitted = jax.jit(lambda x: x + 1)
            obs_hlo.maybe_record("compile_obs_probe", jitted,
                                 (np.ones(4, np.float32),))
        finally:
            obs_hlo.reset()
            obs_compile.reset()
        moved = obs_counters.delta(before)
        # Own histogram name (aot_<entry>), never the serving entry's:
        # the AOT runs inside the entry's first dispatch, so sharing the
        # name would double-count the enclosing time_block's window.
        assert moved.get("engine.compile_ms.aot_compile_obs_probe.count") == 1
        assert moved.get("engine.compile_ms.compile_obs_probe.count") is None
        assert obs_counters.value("engine.compile_obs.aot_ms") > 0


class TestServeSnapshotBlock:
    def test_block_none_when_off(self, unobserved):
        from bcg_tpu.engine.fake import FakeEngine
        from bcg_tpu.serve.scheduler import Scheduler

        sched = Scheduler(FakeEngine(seed=0, policy="consensus"),
                          linger_ms=0, bucket_rows=4)
        try:
            assert sched.snapshot()["compile"] is None
        finally:
            sched.close()

    def test_block_present_when_on(self, monkeypatch):
        from bcg_tpu.engine.fake import FakeEngine
        from bcg_tpu.serve.scheduler import Scheduler

        monkeypatch.setenv("BCG_TPU_COMPILE_OBS", "1")
        obs_compile.reset()
        try:
            obs_compile.note_signature("probe_serve", ("a",), [])
            sched = Scheduler(FakeEngine(seed=0, policy="consensus"),
                              linger_ms=0, bucket_rows=4)
            try:
                block = sched.snapshot()["compile"]
            finally:
                sched.close()
            assert block["cache_entries"] >= 1
            assert "retraces" in block and "causes" in block
        finally:
            obs_compile.reset()


class TestBenchHelper:
    def test_compile_stats_none_when_unpublished(self, unobserved,
                                                 monkeypatch):
        monkeypatch.setattr(runtime_metrics, "LAST_COMPILE_OBS", None)
        assert bench._compile_stats_or_none() is None

    def test_compile_stats_reads_published(self, monkeypatch):
        probe = {"cache_entries": 7}
        monkeypatch.setattr(runtime_metrics, "LAST_COMPILE_OBS", probe)
        assert bench._compile_stats_or_none() is probe

    def test_error_result_attaches_compile_block(self, monkeypatch):
        probe = {"cache_entries": 7}
        monkeypatch.setattr(runtime_metrics, "LAST_COMPILE_OBS", probe)
        out = bench._error_result(RuntimeError("boom"), retried=False)
        assert out["compile"] is probe
        assert out["vs_baseline"] is None

    def test_flags_are_config_overrides(self):
        for flag in ("BCG_TPU_COMPILE_OBS", "BCG_TPU_PROFILE",
                     "BCG_TPU_PROFILE_ROUNDS"):
            assert flag in bench._CONFIG_OVERRIDE_ENVS


class TestProfileWindow:
    """Window selection/ownership logic runs in tier-1 against a
    STUBBED profiler (jax.profiler's cold start/stop costs ~10s of CPU
    — the real capture is the slow-marked end-to-end test below, and
    the verify recipe drives it through the CLI)."""

    @pytest.fixture
    def stubbed_profiler(self, monkeypatch, tmp_path):
        monkeypatch.setenv("BCG_TPU_PROFILE", str(tmp_path / "p"))
        calls = {"started": 0, "stopped": 0}

        def fake_start(state, kind):
            calls["started"] += 1
            calls["owner"] = kind
            return True

        def fake_stop(state):
            calls["stopped"] += 1
            state["active"] = False
            state["done"] = True

        monkeypatch.setattr(obs_compile, "_start_profiler", fake_start)
        monkeypatch.setattr(obs_compile, "_stop_profiler", fake_stop)
        obs_compile.reset()
        yield calls
        obs_compile.reset()

    @pytest.mark.slow
    def test_game_rounds_window_writes_manifest_and_trace(
            self, monkeypatch, tmp_path):
        from bcg_tpu.api import run_simulation

        prof_dir = tmp_path / "profile"
        monkeypatch.setenv("BCG_TPU_PROFILE", str(prof_dir))
        monkeypatch.setenv("BCG_TPU_PROFILE_ROUNDS", "1-2")
        obs_compile.reset()
        try:
            out = run_simulation(n_agents=5, byzantine_count=1,
                                 max_rounds=6, backend="fake", seed=7)
            assert out["metrics"]["total_rounds"] >= 2
            state = obs_compile._profile_cfg()
            assert state["done"] and not state["active"]
            manifest = json.loads(
                (prof_dir / "manifest.json").read_text()
            )
            assert manifest["kind"] == "profile"
            assert manifest["window_kind"] == "round"
            assert manifest["first_index"] == 1
            assert manifest["last_index"] == 2
            assert "run_id" in manifest and "host" in manifest
            # jax.profiler wrote its capture tree next to the manifest.
            captured = [
                os.path.join(root, f)
                for root, _, files in os.walk(prof_dir) for f in files
                if f != "manifest.json"
            ]
            assert captured, "profiler window captured no files"
        finally:
            obs_compile.reset()

    def test_dispatch_window_start_stop(self, monkeypatch,
                                        stubbed_profiler):
        monkeypatch.setenv("BCG_TPU_PROFILE_ROUNDS", "2-3")
        with obs_compile.profile_dispatch():  # index 1: before window
            pass
        assert not obs_compile._profile_cfg()["active"]
        assert stubbed_profiler["started"] == 0
        with obs_compile.profile_dispatch():  # index 2: starts
            assert obs_compile._profile_cfg()["active"]
        with obs_compile.profile_dispatch():  # index 3: stops after
            pass
        state = obs_compile._profile_cfg()
        assert state["done"] and not state["active"]
        assert stubbed_profiler == {"started": 1, "stopped": 1,
                                    "owner": "dispatch"}
        # A closed window never restarts.
        assert obs_compile.profile_dispatch() is obs_compile._NULL_CM

    def test_round_stream_owns_window_and_closes_it(self, monkeypatch,
                                                    stubbed_profiler):
        monkeypatch.setenv("BCG_TPU_PROFILE_ROUNDS", "1-2")
        with obs_compile.profile_span("round", 1):
            pass
        assert obs_compile._profile_cfg()["active"]
        # A competing dispatch stream cannot steal or close the window.
        with obs_compile.profile_dispatch():
            pass
        assert obs_compile._profile_cfg()["active"]
        with obs_compile.profile_span("round", 2):
            pass
        assert stubbed_profiler == {"started": 1, "stopped": 1,
                                    "owner": "round"}

    def test_short_run_window_closed_by_reset(self, monkeypatch,
                                              stubbed_profiler):
        # A run shorter than the window leaves the profiler recording;
        # reset() (standing in for the registered atexit hook) must
        # close it rather than leak a torn trace.
        monkeypatch.setenv("BCG_TPU_PROFILE_ROUNDS", "1-99")
        with obs_compile.profile_span("round", 1):
            pass
        assert obs_compile._profile_cfg()["active"]
        obs_compile.reset()  # must stop the trace without raising
        assert stubbed_profiler["stopped"] == 1
        # The re-read state (same env via monkeypatch) starts idle —
        # the previous window really closed.
        state = obs_compile._profile_cfg()
        assert state is not None and not state["active"]


# ------------------------------------------------------------- perf gate
@pytest.fixture(scope="module")
def gate():
    spec = importlib.util.spec_from_file_location("perf_gate", GATE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, mod.run_compile_scenario()


class TestGate:
    def test_green_at_head(self, gate):
        mod, measured = gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(measured, mod.load_baseline(),
                                    ("compile",))
        assert findings == []

    def test_advertised_metrics_measured(self, gate):
        _, measured = gate
        assert set(measured) == {
            "compile.steady_state_retraces",
            "compile.retrace_cause_coverage",
            "compile.compile_cache_entries",
            "compile.error_rows",
        }
        assert measured["compile.steady_state_retraces"] == 0.0
        assert measured["compile.retrace_cause_coverage"] >= 0.95

    def test_every_compile_entry_matched(self, gate):
        mod, measured = gate
        baseline = mod.load_baseline()
        for name in baseline["metrics"]:
            if name.startswith("compile."):
                assert name in measured, f"stale baseline entry {name}"

    def test_removing_entry_resurfaces(self, gate):
        mod, measured = gate
        baseline = json.loads(json.dumps(mod.load_baseline()))
        del baseline["metrics"]["compile.retrace_cause_coverage"]
        findings = mod.check_metrics(measured, baseline)
        assert any("compile.retrace_cause_coverage" in f
                   and "no entry" in f for f in findings)

    def test_compile_off_injection_fails_naming_metrics(self, gate):
        mod, _ = gate
        measured = mod.run_compile_scenario("compile-off")
        findings = mod.check_metrics(measured, mod.load_baseline())
        named = "\n".join(findings)
        assert "compile.retrace_cause_coverage" in named
        assert "compile.compile_cache_entries" in named


# ------------------------------------------------------- compile_report.py
class TestCompileReportScript:
    def test_import_free(self):
        src = open(os.path.join(REPO, "scripts", "compile_report.py")).read()
        assert "bcg_tpu" not in [
            line.split()[1].split(".")[0]
            for line in src.splitlines()
            if line.startswith(("import ", "from "))
        ]

    def test_renders_workload_counters(self, workload, tmp_path):
        mod = _load_script("compile_report.py")
        # The bench-JSON shape: counters under extra.
        payload = {"extra": {"counters": workload["snapshot"]}}
        report = mod.render_report(mod.extract_counters(payload))
        assert "compile time by entry" in report
        assert "decode_loop" in report and "prefill" in report
        assert "retraces by cause" in report
        assert "static_knob" in report
        assert "trace-cache entries" in report

    def test_events_table_names_argument(self, workload):
        mod = _load_script("compile_report.py")
        events = mod.load_events(workload["events"])
        report = mod.render_report(workload["snapshot"], events)
        assert "max_new" in report
        assert "64→96" in report

    def test_cli_on_trace_shape(self, workload, tmp_path):
        trace = tmp_path / "trace.json"
        trace.write_text(json.dumps(
            {"traceEvents": [],
             "otherData": {"counters": workload["snapshot"]}}
        ))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "compile_report.py"),
             str(trace), "--events", workload["events"]],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert "compile time by entry" in proc.stdout
        assert "max_new" in proc.stdout

    def test_empty_export_says_so(self):
        mod = _load_script("compile_report.py")
        report = mod.render_report({})
        assert "no compile observability" in report
