"""Worker process for the perf-gate "fleet" scenario (not a pytest
module; launched by scripts/perf_gate.py run_fleet_scenario and
tests/test_fleet.py).

Runs as one rank of a REAL 2-process JAX CPU cluster (the
tests/_multihost_worker.py coordinator-handshake idiom): joins the
process group through bcg_tpu.parallel.distributed.initialize — which
hands the observability plane its process identity — then exercises the
fleet plane end to end:

* starts the metric-shard flusher (BCG_TPU_METRICS_SHARD_DIR, set by
  the launcher together with a shared BCG_TPU_RUN_ID),
* observes a DETERMINISTIC per-rank probe set into the
  ``fleet.probe_ms`` histogram and ``fleet.probe`` counter — the
  launcher recomputes the same formulas as the single-stream oracle the
  merged shards must match,
* plays one seeded FakeEngine consensus game with game-event telemetry
  on (per-rank BCG_TPU_GAME_EVENTS path),
* straggler arm (argv[4] = 1): freezes this rank's fleet watermark
  (the documented chaos hook) so the HEALTHY rank's runtime straggler
  pass must flag it — never vacuously green,
* rank 0 polls ``fleet.check_stragglers`` until the lagging rank is
  flagged (or a deadline passes — the gate then fails loudly on
  ``fleet.straggler_flagged``).

Usage: python tests/_fleet_worker.py <coordinator> <num_procs> <pid> <straggle>
"""

import sys
import time

# Per-rank probe distribution — the launcher mirrors these two
# definitions to build the single-stream oracle; a drift between the
# two fails the merged-quantile gate loudly.
PROBE_BOUNDS = (5, 10, 25, 50, 100, 250)


def probe_values(rank: int):
    return [((7 * i + 13 * rank) % 240) + 1 for i in range(50)]


def main() -> None:
    coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    straggle = bool(int(sys.argv[4]))

    import jax

    jax.config.update("jax_platforms", "cpu")

    from bcg_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=coord, num_processes=nproc, process_id=pid
    )

    from bcg_tpu.obs import counters as obs_counters, fleet, game_events
    from bcg_tpu.runtime import envflags

    writer = fleet.maybe_start_shard_writer()
    assert writer is not None, "launcher must set BCG_TPU_METRICS_SHARD_DIR"
    assert fleet.process_index() == pid, fleet.identity()
    assert fleet.process_count() == nproc, fleet.identity()
    assert fleet.enabled()

    if straggle:
        fleet.freeze_watermark()

    hist = obs_counters.histogram("fleet.probe_ms", PROBE_BOUNDS)
    for value in probe_values(pid):
        hist.observe(value)
    obs_counters.inc("fleet.probe", 100 + pid)

    import dataclasses

    from bcg_tpu.config import (
        BCGConfig, EngineConfig, GameConfig, MetricsConfig, NetworkConfig,
    )
    from bcg_tpu.runtime.orchestrator import BCGSimulation

    cfg = dataclasses.replace(
        BCGConfig(),
        game=GameConfig(num_honest=4, num_byzantine=1, max_rounds=4,
                        seed=7 + pid),
        network=NetworkConfig(topology_type="fully_connected"),
        engine=EngineConfig(backend="fake"),
        metrics=MetricsConfig(save_results=False),
        verbose=False,
    )
    sim = BCGSimulation(config=cfg)
    try:
        sim.run()
    finally:
        sim.close()
    game_events.reset_sink()  # drain + close this rank's event file

    # Straggler phase: the healthy rank 0 polls detection until the
    # frozen rank is flagged; other ranks linger so their shards stay
    # fresh while rank 0 looks.  With detection disabled (factor 0, the
    # --inject-regression straggler-off arm) rank 0 skips the poll and
    # the fleet.stragglers gauge never appears — the gate must then
    # fail loudly on fleet.straggler_flagged.
    factor = envflags.get_int("BCG_TPU_FLEET_STRAGGLER_FACTOR")
    if pid == 0 and factor > 0 and nproc > 1:
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if fleet.check_stragglers(force=True):
                break
            time.sleep(0.15)
    else:
        time.sleep(1.5)
    fleet.flush_shards()
    print(
        f"FLEET-OK pid={pid} "
        f"watermark={obs_counters.value('fleet.watermark', 0)} "
        f"stragglers={obs_counters.value('fleet.stragglers', 0)}",
        flush=True,
    )


if __name__ == "__main__":
    main()
