"""Real-model pipeline, proven hermetically (VERDICT round-1 item #1).

Builds a GENUINE HF artifact set on disk — a byte-level-BPE
``tokenizer.json`` trained with the ``tokenizers`` library (GPT-2
byte-unicode alphabet, ChatML specials), real-layout safetensors shards,
HF ``config.json`` — and drives the full checkpoint path the reference
exercises with hub checkpoints (``vllm_agent.py:100-157``):

    find_checkpoint_dir -> load_checkpoint_params ->
    HFTokenizer.token_bytes -> token DFA -> chat template -> game.

Also covers the round-1 ``_token_to_bytes`` defect directly: a byte-BPE
vocab entry containing a literal metaspace (``▁``) must decode through
the byte table / raw-string path, never the SentencePiece branch.
"""

import json
import os

import pytest

from bcg_tpu.engine.tokenizer import HFTokenizer, tokenizer_for_model
from bcg_tpu.models.configs import spec_for_model
from bcg_tpu.models.hf_fixture import (
    METASPACE_PROBE_TOKEN,
    build_checkpoint,
    build_tokenizer_files,
)
from bcg_tpu.models.loader import find_checkpoint_dir, load_checkpoint_params

TINY = "bcg-hf/tiny"


@pytest.fixture(scope="session")
def hf_checkpoint(tmp_path_factory):
    """The bcg-hf/tiny artifact set, built once per session."""
    root = tmp_path_factory.mktemp("hf_ckpt")
    out = build_checkpoint(TINY, out_dir=str(root / "bcg-hf--tiny"))
    return out


@pytest.fixture()
def hf_env(hf_checkpoint, monkeypatch):
    """Point checkpoint discovery at the session fixture."""
    monkeypatch.setenv(
        "BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint)
    )
    return hf_checkpoint


# ------------------------------------------------------------ discovery


def test_find_checkpoint_dir_resolves_fixture(hf_env):
    found = find_checkpoint_dir(TINY)
    assert found is not None
    assert os.path.samefile(found, hf_env)


def test_artifact_set_is_genuine_hf_layout(hf_checkpoint):
    files = set(os.listdir(hf_checkpoint))
    assert "tokenizer.json" in files
    assert "tokenizer_config.json" in files
    assert "config.json" in files
    assert any(f.endswith(".safetensors") for f in files)
    with open(os.path.join(hf_checkpoint, "config.json")) as f:
        cfg = json.load(f)
    spec = spec_for_model(TINY)
    assert cfg["hidden_size"] == spec.hidden_size
    assert cfg["num_hidden_layers"] == spec.num_layers
    assert cfg["num_key_value_heads"] == spec.num_kv_heads


# ------------------------------------------------------------ tokenizer


@pytest.fixture(scope="session")
def hf_tok(hf_checkpoint):
    return HFTokenizer(hf_checkpoint)


def test_byte_level_detected(hf_tok):
    assert hf_tok._byte_level is True


def test_token_bytes_concatenation_invariant(hf_tok):
    """The DFA-correctness invariant: for any encoded text, the
    concatenation of per-token byte strings reproduces the text's UTF-8
    bytes exactly.  A single mis-decoded vocab entry breaks the token
    DFA for every schema that can reach it."""
    tb = hf_tok.token_bytes()
    samples = [
        '{"internal_strategy": "hold", "value": 42, "public_reasoning": '
        '"Values cluster near 42."}',
        "Round 3: agent_1 value: 17 | Reasoning: moving toward median",
        "unicode: café ▁ 中文 — em-dash",
        "  leading and   multiple spaces\nand newlines\t tabs",
    ]
    for text in samples:
        ids = hf_tok.encode(text)
        assert b"".join(tb[i] for i in ids) == text.encode("utf-8"), text


def test_literal_metaspace_token_not_misdecoded(hf_tok):
    """Round-1 defect: '▁' checked before the byte table sent byte-BPE
    entries containing a literal metaspace down the SentencePiece branch
    (token.replace('▁', ' ')), silently corrupting their bytes."""
    tid = hf_tok.tk.convert_tokens_to_ids(METASPACE_PROBE_TOKEN)
    assert tid is not None and tid >= 0
    tb = hf_tok.token_bytes()
    assert tb[tid] == METASPACE_PROBE_TOKEN.encode("utf-8")
    assert b" " not in tb[tid]  # the old heuristic produced ' probe '


def test_special_tokens_single_id_and_forbidden(hf_tok):
    tb = hf_tok.token_bytes()
    for tok in ("<|im_start|>", "<|im_end|>", "<|endoftext|>"):
        tid = hf_tok.tk.convert_tokens_to_ids(tok)
        assert hf_tok.encode(tok) == [tid]
        assert tb[tid] == b""  # specials are unreachable in guided decode
    assert hf_tok.eos_id == hf_tok.tk.convert_tokens_to_ids("<|im_end|>")


def test_prefix_suffix_encode_split_is_safe(hf_tok):
    """Prefix caching relies on encode(prefix) + encode(suffix) ==
    encode(prefix + suffix) at the ChatML seam (chat_template.py
    prefix_split_safe)."""
    from bcg_tpu.engine.chat_template import format_chat_parts

    prefix, suffix = format_chat_parts(TINY, "You are agent_1.", "Pick a value.")
    assert hf_tok.encode(prefix) + hf_tok.encode(suffix) == hf_tok.encode(
        prefix + suffix
    )


def test_tokenizer_for_model_routes_to_hf(hf_env):
    t = tokenizer_for_model(TINY)
    assert isinstance(t, HFTokenizer)
    # Distinct vocabularies must not collide in the guided-DFA cache.
    assert t.vocab_id != 1


def test_sentencepiece_vocab_detected_and_decoded(tmp_path):
    """A true SentencePiece-style vocab (Metaspace pre-tokenizer) takes
    the metaspace branch: '▁the' -> b' the', byte-fallback '<0xNN>'
    pieces -> single bytes."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Metaspace()
    trainer = trainers.BpeTrainer(
        vocab_size=400, special_tokens=["<unk>", "<s>", "</s>"],
        show_progress=False,
    )
    corpus = ["the quick brown fox jumps over the lazy dog"] * 50
    tok.train_from_iterator(corpus, trainer)
    d = tmp_path / "sp"
    d.mkdir()
    tok.save(str(d / "tokenizer.json"))
    (d / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "eos_token": "</s>", "unk_token": "<unk>",
    }))
    t = HFTokenizer(str(d))
    assert t._byte_level is False
    vocab = t.tk.get_vocab()
    sp_tokens = [tok for tok in vocab if tok.startswith("▁") and len(tok) > 1]
    assert sp_tokens, "trained SP vocab should contain metaspace pieces"
    tb = t.token_bytes()
    piece = sp_tokens[0]
    assert tb[vocab[piece]] == piece.replace("▁", " ").encode()
    # Byte-fallback piece decodes to its single byte (unit-level: real SP
    # vocabs carry <0xNN> entries as regular tokens).
    assert t._token_to_bytes("<0x41>", tid=-1) == b"A"


# ------------------------------------------------------------ checkpoint


def test_load_checkpoint_params_from_fixture(hf_env):
    spec = spec_for_model(TINY)
    params = load_checkpoint_params(spec, TINY)
    assert len(params["layers"]) == spec.num_layers
    assert params["embed"].shape == (spec.vocab_size, spec.hidden_size)
    assert params["layers"][0]["wq"].shape == (spec.hidden_size, spec.q_size)
    assert str(params["embed"].dtype) == "bfloat16"


# ------------------------------------------------------------ end to end


def _run_short_game(model_name, n_honest=3, n_byz=1, max_rounds=2):
    """A complete game through the real JaxEngine on CPU: checkpoint
    discovery, safetensors loading, HFTokenizer byte table, guided token
    DFA, family chat template."""
    import dataclasses

    from bcg_tpu.config import BCGConfig
    from bcg_tpu.runtime.orchestrator import BCGSimulation

    base = BCGConfig()
    cfg = dataclasses.replace(
        base,
        game=dataclasses.replace(
            base.game, num_honest=n_honest, num_byzantine=n_byz,
            max_rounds=max_rounds, seed=0,
        ),
        engine=dataclasses.replace(
            base.engine, model_name=model_name, backend="jax",
            max_model_len=2048,
        ),
        llm=dataclasses.replace(
            base.llm, max_tokens_decide=80, max_tokens_vote=40
        ),
        metrics=dataclasses.replace(base.metrics, save_results=False),
    )
    sim = BCGSimulation(config=cfg)
    try:
        stats = sim.run()
    finally:
        sim.engine.shutdown()
        sim.close()
    assert stats["total_rounds"] >= 1
    assert sim.engine.total_decode_steps > 0
    # The guided DFA guarantees parseable JSON: with a real tokenizer in
    # the loop, generation failures would show up as failed rows.
    assert sim.engine.failed_rows == 0
    return stats


@pytest.mark.slow
def test_full_game_through_hf_checkpoint(hf_env):
    """THE hermetic real-model proof (ChatML/byte-BPE family)."""
    _run_short_game(TINY)


# ------------------------------------------- family fidelity (VERDICT #7)

LLAMA3 = "bcg-hf/tiny-llama3"
MISTRAL = "bcg-hf/tiny-mistral"


@pytest.fixture(scope="session")
def llama3_checkpoint(tmp_path_factory):
    root = tmp_path_factory.mktemp("hf_llama3")
    return build_checkpoint(LLAMA3, out_dir=str(root / "bcg-hf--tiny-llama3"))


@pytest.fixture(scope="session")
def mistral_checkpoint(tmp_path_factory):
    root = tmp_path_factory.mktemp("hf_mistral")
    return build_checkpoint(MISTRAL, out_dir=str(root / "bcg-hf--tiny-mistral"))


@pytest.mark.slow
class TestLlama3Family:
    def test_detection_template_and_seam(self, llama3_checkpoint):
        from bcg_tpu.engine.chat_template import (
            format_chat_parts, prefix_split_safe,
        )
        from bcg_tpu.models.hf_fixture import LLAMA3_SPECIALS

        t = HFTokenizer(llama3_checkpoint)
        assert t._byte_level is True
        assert t.eos_id == t.tk.convert_tokens_to_ids("<|eot_id|>")
        tb = t.token_bytes()
        for s in LLAMA3_SPECIALS:
            tid = t.tk.convert_tokens_to_ids(s)
            assert t.encode(s) == [tid], s
            assert tb[tid] == b""  # specials unreachable in guided decode
        prefix, suffix = format_chat_parts(
            LLAMA3, "You are agent_1.", "Pick a value."
        )
        assert "<|start_header_id|>system<|end_header_id|>" in prefix
        assert prefix.endswith("<|eot_id|>")
        assert suffix.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")
        # The Llama-3 seam ends at a special-token boundary: prefix
        # caching is sound on this family.
        assert prefix_split_safe(LLAMA3)
        assert t.encode(prefix) + t.encode(suffix) == t.encode(prefix + suffix)
        text = '{"decision": "stop"}'
        assert b"".join(tb[i] for i in t.encode(text)) == text.encode()

    @pytest.mark.slow
    def test_short_engine_game(self, llama3_checkpoint, monkeypatch):
        monkeypatch.setenv(
            "BCG_TPU_CHECKPOINT_DIR", os.path.dirname(llama3_checkpoint)
        )
        _run_short_game(LLAMA3, n_honest=2, n_byz=1, max_rounds=1)


@pytest.mark.slow
class TestMistralSPFamily:
    def test_detection_and_template(self, mistral_checkpoint):
        from bcg_tpu.engine.chat_template import (
            format_chat_parts, prefix_split_safe,
        )

        t = HFTokenizer(mistral_checkpoint)
        # True SentencePiece shape: Metaspace pieces, NOT byte-level.
        assert t._byte_level is False
        vocab = t.tk.get_vocab()
        sp_pieces = [tok for tok in vocab if tok.startswith("▁") and len(tok) > 1]
        assert sp_pieces, "SP vocab must contain metaspace pieces"
        tb = t.token_bytes()
        piece = sp_pieces[0]
        assert tb[vocab[piece]] == piece.replace("▁", " ").encode()
        assert t.eos_id == t.tk.convert_tokens_to_ids("</s>")
        prefix, suffix = format_chat_parts(MISTRAL, "Sys rules.", "Decide.")
        assert prefix.startswith("<s>[INST] <<SYS>>")
        assert suffix.endswith("[/INST]")
        # Bare-text seam: prefix caching must stay OFF for this family.
        assert not prefix_split_safe(MISTRAL)

    @pytest.mark.slow
    def test_short_engine_game(self, mistral_checkpoint, monkeypatch):
        monkeypatch.setenv(
            "BCG_TPU_CHECKPOINT_DIR", os.path.dirname(mistral_checkpoint)
        )
        _run_short_game(MISTRAL, n_honest=2, n_byz=1, max_rounds=1)
