"""End-to-end runtime tests on the fake backend: orchestrator round loop,
retry ladder, metrics sinks, checkpoint/resume, CLI, batch API."""

import csv
import dataclasses
import json
import os

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.config import (
    AgentConfig,
    BCGConfig,
    EngineConfig,
    GameConfig,
    MetricsConfig,
    NetworkConfig,
)
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.runtime.orchestrator import BCGSimulation, build_topology


def make_config(tmp_path=None, nh=4, nb=0, max_rounds=8, seed=0, **game_kw):
    return BCGConfig(
        game=GameConfig(
            num_honest=nh, num_byzantine=nb, max_rounds=max_rounds, seed=seed, **game_kw
        ),
        engine=EngineConfig(backend="fake", model_name="bcg-tpu/tiny-test"),
        metrics=MetricsConfig(
            save_results=tmp_path is not None,
            results_dir=str(tmp_path) if tmp_path else "results",
        ),
    )


class TestEndToEnd:
    def test_honest_game_converges_and_wins(self):
        sim = BCGSimulation(config=make_config(nh=4, max_rounds=10))
        stats = sim.run()
        assert stats["consensus_outcome"] == "valid"
        assert stats["honest_agents_won"] is True
        assert stats["total_rounds"] <= 4  # fake consensus policy converges fast
        assert stats["termination_reason"] == "vote_with_consensus"

    def test_seeded_runs_are_identical(self):
        s1 = BCGSimulation(config=make_config(seed=5)).run()
        s2 = BCGSimulation(config=make_config(seed=5)).run()
        assert s1["consensus_value"] == s2["consensus_value"]
        assert s1["total_rounds"] == s2["total_rounds"]
        assert s1["rounds_data"] == s2["rounds_data"]

    def test_byzantine_game_runs_to_completion(self):
        cfg = make_config(nh=4, nb=2, max_rounds=6)
        sim = BCGSimulation(config=cfg, engine=FakeEngine(seed=3))
        stats = sim.run()
        assert stats["total_rounds"] >= 1
        assert stats["termination_reason"] in (
            "vote_with_consensus",
            "vote_without_consensus",
            "max_rounds",
        )
        assert len(stats["byzantine_agent_ids"]) == 2

    def test_sequential_mode_matches_contract(self):
        cfg = dataclasses.replace(
            make_config(nh=3, max_rounds=6),
            agent=AgentConfig(use_batched_inference=False),
        )
        stats = BCGSimulation(config=cfg).run()
        assert stats["consensus_outcome"] == "valid"

    def test_ring_topology_limits_messages(self):
        cfg = dataclasses.replace(
            make_config(nh=4, max_rounds=3),
            network=NetworkConfig(topology_type="ring"),
        )
        sim = BCGSimulation(config=cfg)
        sim.run_round()
        # ring: each of 4 agents broadcasts to 2 neighbours
        assert sim.network.protocol.get_message_count(1) == 8

    def test_grid_topology_wired(self):
        cfg = dataclasses.replace(
            make_config(nh=4, max_rounds=3),
            network=NetworkConfig(topology_type="grid", grid_shape=(2, 2)),
        )
        sim = BCGSimulation(config=cfg)
        assert sim.topology.topology_type == "grid"
        sim.run_round()
        assert sim.network.protocol.get_message_count(1) == 8  # 4 agents x 2 nbrs

    def test_grid_shape_mismatch_raises(self):
        with pytest.raises(ValueError, match="grid"):
            build_topology(5, NetworkConfig(topology_type="grid", grid_shape=(2, 2)))


class TestRetryLadder:
    def test_batch_failures_recover_via_retry(self):
        # First batch call (4 prompts) fails entirely -> full batch retry.
        eng = FakeEngine(fail_first_n_calls=4)
        sim = BCGSimulation(config=make_config(nh=4, max_rounds=6), engine=eng)
        stats = sim.run()
        assert stats["consensus_outcome"] == "valid"

    def test_partial_failure_takes_sequential_path(self):
        # One agent of four fails on attempt 1 (25% <= 30% threshold).
        eng = FakeEngine(fail_first_n_calls=1)
        sim = BCGSimulation(config=make_config(nh=4, max_rounds=6), engine=eng)
        sim.run_round()
        proposals = sim.game.get_all_proposals()
        assert all(v is not None for v in proposals.values())

    def test_total_failure_abstains_and_game_survives(self):
        eng = FakeEngine(fail_first_n_calls=10**9)
        sim = BCGSimulation(config=make_config(nh=3, max_rounds=2), engine=eng)
        stats = sim.run()
        # Nobody ever proposes; game rides to the deadline and loses.
        assert stats["termination_reason"] == "max_rounds"
        assert stats["honest_agents_won"] is False


class TestSinks:
    def test_results_files_layout(self, tmp_path):
        cfg = make_config(tmp_path=tmp_path, nh=3, max_rounds=6)
        sim = BCGSimulation(config=cfg)
        sim.run()
        sim.close()
        json_path = tmp_path / "json" / "run_001.json"
        csv_path = tmp_path / "metrics" / "run_001.csv"
        log_path = tmp_path / "logs" / "run_001_log.txt"
        assert json_path.exists() and csv_path.exists() and log_path.exists()

        blob = json.loads(json_path.read_text())
        assert blob["run_number"] == 1
        assert {"config", "statistics", "metrics", "rounds", "final_state"} <= set(blob)
        assert blob["statistics"]["consensus_outcome"] == "valid"
        assert blob["a2a_message_count"] > 0

        with open(csv_path) as f:
            rows = list(csv.DictReader(f))
        assert len(rows) == 1
        row = rows[0]
        assert row["consensus_outcome"] == "valid"
        assert row["value_range"] == "0-50"
        assert row["consensus_reached"] == "True"
        assert float(row["rounds_per_sec"]) > 0

        log_text = log_path.read_text()
        assert "Round 1" in log_text and "SIMULATION COMPLETE" in log_text

    def test_track_flags_gate_metric_families(self, tmp_path):
        """METRICS_CONFIG's track_* flags (dead in the reference,
        config.py:71-73) actually gate their families here: off = the
        family's fields are nulled, CSV header unchanged."""
        cfg = make_config(tmp_path=tmp_path, nh=3, max_rounds=6)
        cfg = dataclasses.replace(
            cfg,
            metrics=dataclasses.replace(
                cfg.metrics, track_convergence=False,
                track_byzantine_impact=False, track_communication=False,
            ),
        )
        sim = BCGSimulation(config=cfg)
        sim.run()
        sim.close()
        blob = json.loads((tmp_path / "json" / "run_001.json").read_text())
        m = blob["metrics"]
        assert m["convergence_speed"] is None          # Q1 gated
        assert m["consensus_quality_score"] is None    # Q2 gated
        assert m["a2a_message_count"] is None          # comm gated
        assert m["consensus_reached"] is not None      # core outcome stays
        with open(tmp_path / "metrics" / "run_001.csv") as f:
            rows = list(csv.DictReader(f))
        assert "convergence_speed" in rows[0]          # fixed header

    def test_run_numbering_increments(self, tmp_path):
        for expected in ("001", "002"):
            cfg = make_config(tmp_path=tmp_path, nh=3, max_rounds=6)
            sim = BCGSimulation(config=cfg)
            assert sim.run_number == expected
            sim.run()
            sim.close()


class TestCheckpoint:
    def test_checkpoint_and_resume(self, tmp_path):
        cfg = dataclasses.replace(
            make_config(tmp_path=tmp_path, nh=4, nb=1, max_rounds=10, seed=11),
            metrics=MetricsConfig(
                save_results=True,
                results_dir=str(tmp_path),
                checkpoint_every_round=True,
            ),
        )
        sim = BCGSimulation(config=cfg, engine=FakeEngine(seed=2, policy="schema_min"))
        sim.run_round()
        ckpt = tmp_path / "checkpoints" / "run_001.json"
        assert ckpt.exists()

        from bcg_tpu.runtime.checkpoint import resume_simulation

        cfg2 = dataclasses.replace(cfg, metrics=dataclasses.replace(cfg.metrics, save_results=False))
        sim2 = resume_simulation(str(ckpt), config=cfg2, engine=FakeEngine(seed=2, policy="schema_min"))
        assert sim2.game.current_round == sim.game.current_round
        assert sim2.game.get_game_state() == sim.game.get_game_state()
        for aid in sim.agents:
            assert sim2.agents[aid].memory.last_k_rounds == sim.agents[aid].memory.last_k_rounds
            assert sim2.agents[aid].my_value == sim.agents[aid].my_value
        # Resumed game can continue running.
        sim2.run_round()
        assert sim2.game.current_round >= sim.game.current_round

    def test_checkpoint_restores_lossy_channel_state(self, tmp_path):
        """Channel state (in-flight delayed messages, fault counters, RNG
        position) must survive checkpoint/resume — a resumed lossy run
        continues the exact seeded fault stream."""
        from bcg_tpu.config import CommunicationConfig

        cfg = dataclasses.replace(
            make_config(tmp_path=tmp_path, nh=4, nb=1, max_rounds=10, seed=11),
            communication=CommunicationConfig(
                protocol_type="lossy_sim", drop_prob=0.3, delay_prob=0.3,
                max_delay_rounds=2,
            ),
            metrics=MetricsConfig(
                save_results=True,
                results_dir=str(tmp_path),
                checkpoint_every_round=True,
            ),
        )
        sim = BCGSimulation(config=cfg, engine=FakeEngine(seed=2, policy="schema_min"))
        sim.run_round()
        ckpt = tmp_path / "checkpoints" / "run_001.json"
        assert ckpt.exists()

        from bcg_tpu.runtime.checkpoint import resume_simulation

        cfg2 = dataclasses.replace(
            cfg, metrics=dataclasses.replace(cfg.metrics, save_results=False)
        )
        sim2 = resume_simulation(
            str(ckpt), config=cfg2, engine=FakeEngine(seed=2, policy="schema_min")
        )
        p1, p2 = sim.network.protocol, sim2.network.protocol
        assert p2.get_fault_stats() == p1.get_fault_stats()
        assert p2._rng.getstate() == p1._rng.getstate()
        assert p2.message_buffer == p1.message_buffer  # in-flight delayed
        # (Exact post-resume fault-stream continuation is proven at the
        # protocol level — test_comm.py — where inputs are controlled;
        # here the engines' own sampling streams are not checkpointed, so
        # round content may differ.)  The resumed game must keep running.
        sim2.run_round()
        assert sim2.game.current_round >= sim.game.current_round

    def test_resume_unseeded_preserves_byzantine_roles(self, tmp_path):
        # Without a seed, a fresh simulation would roll a DIFFERENT
        # Byzantine assignment; resume must rebuild agents from the
        # checkpointed game's roles.
        cfg = dataclasses.replace(
            make_config(tmp_path=tmp_path, nh=3, nb=3, max_rounds=10, seed=0),
            game=GameConfig(num_honest=3, num_byzantine=3, max_rounds=10, seed=None),
            metrics=MetricsConfig(
                save_results=True, results_dir=str(tmp_path), checkpoint_every_round=True
            ),
        )
        sim = BCGSimulation(config=cfg, engine=FakeEngine(seed=1))
        sim.run_round()
        sim.close()
        ckpt = tmp_path / "checkpoints" / "run_001.json"

        from bcg_tpu.runtime.checkpoint import resume_simulation

        for attempt in range(5):  # several resumes, roles must match every time
            sim2 = resume_simulation(str(ckpt), config=cfg, engine=FakeEngine(seed=1))
            for aid, game_agent in sim2.game.agents.items():
                assert sim2.agents[aid].is_byzantine == game_agent.is_byzantine
            assert sim2.run_number == "001"
            sim2.close()

    def test_resume_appends_to_original_log(self, tmp_path):
        cfg = dataclasses.replace(
            make_config(tmp_path=tmp_path, nh=3, max_rounds=10, seed=4),
            metrics=MetricsConfig(
                save_results=True, results_dir=str(tmp_path), checkpoint_every_round=True
            ),
        )
        sim = BCGSimulation(config=cfg)
        sim.run_round()
        sim.close()
        log_path = tmp_path / "logs" / "run_001_log.txt"
        size_before = log_path.stat().st_size

        from bcg_tpu.runtime.checkpoint import resume_simulation

        sim2 = resume_simulation(str(ckpt := str(tmp_path / "checkpoints" / "run_001.json")), config=cfg)
        sim2.run_round()
        sim2.close()
        assert log_path.stat().st_size > size_before  # appended, not truncated
        assert not (tmp_path / "logs" / "run_002_log.txt").exists()


class TestCLI:
    def test_cli_end_to_end(self, tmp_path, capsys):
        from bcg_tpu.cli import main

        rc = main(
            [
                "--honest", "3", "--byzantine", "0", "--rounds", "6",
                "--backend", "fake", "--seed", "0",
                "--results-dir", str(tmp_path),
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "Results:" in out and "Metrics:" in out
        assert (tmp_path / "json" / "run_001.json").exists()

    def test_cli_bad_value_range(self):
        from bcg_tpu.cli import main

        with pytest.raises(SystemExit):
            main(["--value-range", "banana"])

    def test_cli_engine_flags_reach_config(self):
        from bcg_tpu.cli import build_parser, config_from_args

        args = build_parser().parse_args(
            ["--quantization", "int8", "--kv-cache-dtype", "int8",
             "--no-prefix-caching", "--tensor-parallel", "2",
             "--sequence-parallel", "2"]
        )
        cfg = config_from_args(args)
        assert cfg.engine.quantization == "int8"
        assert cfg.engine.kv_cache_dtype == "int8"
        assert cfg.engine.prefix_caching is False
        assert cfg.engine.tensor_parallel_size == 2
        assert cfg.engine.sequence_parallel_size == 2

    def test_cli_no_save(self, tmp_path, capsys):
        from bcg_tpu.cli import main

        rc = main(
            ["--honest", "3", "--rounds", "5", "--backend", "fake",
             "--seed", "1", "--no-save", "--results-dir", str(tmp_path)]
        )
        assert rc == 0
        assert not (tmp_path / "json").exists()


class TestBatchAPI:
    def test_run_simulation_returns_metrics(self):
        out = run_simulation(
            n_agents=4, max_rounds=6, byzantine_count=1, backend="fake", seed=0
        )
        stats = out["metrics"]
        assert stats["num_honest"] == 3 and stats["num_byzantine"] == 1
        assert stats["byzantine_awareness"] == "may_exist"
        assert "consensus_outcome" in stats


class TestPlots:
    def test_generate_plots_flag_writes_png(self, tmp_path):
        import dataclasses

        import pytest
        pytest.importorskip("matplotlib")

        from bcg_tpu.config import BCGConfig
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        base = BCGConfig()
        cfg = dataclasses.replace(
            base,
            game=dataclasses.replace(
                base.game, num_honest=3, num_byzantine=1, max_rounds=4, seed=0
            ),
            engine=dataclasses.replace(base.engine, backend="fake"),
            metrics=dataclasses.replace(
                base.metrics,
                save_results=True,
                generate_plots=True,
                results_dir=str(tmp_path),
            ),
        )
        sim = BCGSimulation(config=cfg)
        try:
            sim.run()
        finally:
            sim.close()
        pngs = list((tmp_path / "plots").glob("run_*.png"))
        assert len(pngs) == 1 and pngs[0].stat().st_size > 1000
