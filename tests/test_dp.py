"""Data-parallel (agent-parallel) engine batching.

The mesh's `dp` axis shards game batches one-row-per-device-slice
(BASELINE config 4's one-agent-per-chip scale sweep; the reference's
agent parallelism is vLLM server-side batching, vllm_agent.py:417-455).
Covers: _pad_rows dp alignment, _put_batch/_put_cache placement,
dp=1-equivalence of results, dp x tp x sp composition, and — via a
16-virtual-device subprocess — the full 16-agent game through
JaxEngine(dp=16) + --spmd-exchange.
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from bcg_tpu.config import BCGConfig
from bcg_tpu.engine.interface import create_engine
from bcg_tpu.engine.jax_engine import _pad_rows

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = {
    "type": "object",
    "properties": {"value": {"type": "integer"}},
    "required": ["value"],
}


def _engine(dp=1, tp=1, sp=1, **kw):
    base = BCGConfig()
    return create_engine(dataclasses.replace(
        base.engine, backend="jax", model_name="bcg-tpu/tiny-test",
        max_model_len=512, data_parallel_size=dp,
        tensor_parallel_size=tp, sequence_parallel_size=sp, **kw,
    ))


class TestPadRows:
    def test_multiple_aligns_up(self):
        real_B, B, rows = _pad_rows(["a", "b", "c"], multiple=4)
        assert (real_B, B) == (3, 4)
        assert rows == ["a", "b", "c", "a"]

    def test_multiple_beyond_pow2(self):
        # 3 rows pow2-pad to 4, then align to dp=16.
        real_B, B, rows = _pad_rows(["a", "b", "c"], multiple=16)
        assert (real_B, B) == (3, 16)
        assert len(rows) == 16

    def test_exact_multiple_untouched(self):
        real_B, B, rows = _pad_rows(list("abcdefgh") * 2, multiple=16)
        assert (real_B, B) == (16, 16)

    def test_default_is_pow2_only(self):
        real_B, B, rows = _pad_rows(["a", "b", "c"])
        assert (real_B, B) == (3, 4)


class TestPlacement:
    def test_put_batch_shards_over_dp(self):
        eng = _engine(dp=4)
        x = eng._put_batch(np.zeros((8, 6), np.float32))
        spec = x.sharding.spec
        assert spec[0] == "dp"
        assert all(s is None for s in spec[1:])

    def test_put_batch_indivisible_falls_back(self):
        eng = _engine(dp=4)
        x = eng._put_batch(np.zeros((3, 6), np.float32))
        # Replicated placement, no crash, no counter bump (single-row
        # prefix-entry builds take this path by design).
        assert eng.dp_bypasses == 0
        np.testing.assert_array_equal(np.asarray(x), np.zeros((3, 6)))

    def test_fresh_cache_allocated_dp_sharded(self):
        eng = _engine(dp=4)
        cache = eng._init_cache_sharded(4, 64)
        leaf = cache[0]["k"]
        assert leaf.sharding.spec[0] == "dp"

    def test_cache_tree_sharding_layouts(self):
        """kv_cache_tree_sharding is the ONE place the cache mesh layout
        lives (engine fresh-cache init and the _assemble_cache
        constraint both consume it): pin its per-layout specs."""
        from jax.sharding import PartitionSpec as P

        from bcg_tpu.models.transformer import init_kv_cache
        from bcg_tpu.parallel.mesh import build_mesh
        from bcg_tpu.parallel.sharding import kv_cache_tree_sharding

        eng = _engine(dp=4)
        mesh = build_mesh(dp=4, tp=1, sp=1)
        spec = eng.spec
        plain = kv_cache_tree_sharding(
            mesh, jax.eval_shape(lambda: init_kv_cache(spec, 4, 64)))
        assert plain[0]["k"].spec == P("dp", None, None, None)
        stacked = kv_cache_tree_sharding(
            mesh,
            jax.eval_shape(lambda: init_kv_cache(spec, 4, 64, stacked=True)),
            stacked=True)
        assert stacked["k"].spec == P(None, "dp", None, None, None)
        quant = kv_cache_tree_sharding(
            mesh,
            jax.eval_shape(
                lambda: init_kv_cache(spec, 4, 64, quantized=True)),
            quantized=True)
        assert quant[0]["k"].spec == P("dp", None, None, None)
        assert quant[0]["k_scale"].spec == P("dp", None, None)

    def test_cache_tree_sharding_guards_indivisible_axes(self):
        from jax.sharding import PartitionSpec as P

        from bcg_tpu.models.transformer import init_kv_cache
        from bcg_tpu.parallel.mesh import build_mesh
        from bcg_tpu.parallel.sharding import kv_cache_tree_sharding

        eng = _engine(dp=1)
        mesh = build_mesh(dp=1, tp=2, sp=2)
        spec = eng.spec
        # S=66 not divisible by sp=2? 66 % 2 == 0 — use 65 for the
        # indivisible case and Hkv vs tp=2 from the spec itself.
        tree = kv_cache_tree_sharding(
            mesh, jax.eval_shape(lambda: init_kv_cache(spec, 3, 65)))
        sp_ax, tp_ax = tree[0]["k"].spec[1], tree[0]["k"].spec[2]
        assert sp_ax is None  # 65 % 2 != 0 -> replicated, not crashed
        assert tp_ax == ("tp" if spec.num_kv_heads % 2 == 0 else None)


class TestDpGeneration:
    def test_dp4_matches_dp1(self):
        rows = [("sys", f"agent {i}: pick a value", SCHEMA) for i in range(4)]
        eng4 = _engine(dp=4)
        out4 = eng4.batch_generate_json(rows, temperature=0.0, max_tokens=24)
        assert eng4.dp_batches >= 1
        assert eng4.dp_bypasses == 0
        eng1 = _engine(dp=1)
        out1 = eng1.batch_generate_json(rows, temperature=0.0, max_tokens=24)
        assert out4 == out1

    def test_small_batch_pads_to_dp(self):
        # 2 rows pad up to dp=4; results for real rows are unaffected.
        rows = [("sys", f"agent {i}: value?", SCHEMA) for i in range(2)]
        eng = _engine(dp=4)
        out = eng.batch_generate_json(rows, temperature=0.0, max_tokens=24)
        assert len(out) == 2
        assert eng.dp_batches >= 1
        assert eng.dp_bypasses == 0

    def test_dp_tp_sp_composition(self):
        # 8 virtual devices: dp=2 x tp=2 x sp=2 — the engine shards
        # batch, heads, and sequence at once, and results still match
        # the unsharded engine.
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        rows = [("sys", f"agent {i}: pick a value", SCHEMA) for i in range(4)]
        eng = _engine(dp=2, tp=2, sp=2)
        out = eng.batch_generate_json(rows, temperature=0.0, max_tokens=24)
        assert eng.dp_batches >= 1
        assert eng.dp_bypasses == 0
        assert eng.sp_bypasses == 0
        eng1 = _engine(dp=1)
        assert out == eng1.batch_generate_json(
            rows, temperature=0.0, max_tokens=24
        )


@pytest.mark.slow
class TestScaleSweep16:
    def test_16_agents_one_per_chip(self):
        """BASELINE config 4's shape, hermetically: 16 agents through
        the REAL engine over a 16-virtual-device mesh, one agent per
        device slice (dp=16), SPMD value exchange, full game."""
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("BCG_TPU_SCAN_LAYERS", None)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "scale_sweep.py"),
             "--agents", "16", "--rounds", "2"],
            capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        assert row["devices"] == 16
        assert row["dp"] == 16
        assert row["spmd_mesh_dp"] == 16
        assert row["rounds"] >= 1
        assert row["dp_batches"] >= 2 * row["rounds"]  # decide + vote
        assert row["dp_bypasses"] == 0
        assert row["rounds_per_sec"] > 0
