"""Fused mega-round tests (ISSUE-16, ROADMAP item 1).

Covers the host-side pieces hermetically — template rendering, plan
building on the byte tokenizer, the FakeEngine mirror's sync profile and
numpy exchange twin, and the orchestrator's eligibility/fallback matrix
— plus one real-engine pin: the JaxEngine fused round compiles ONCE and
never retraces across rounds that vary round number, inbox contents, and
convergence state (the retrace-pinning acceptance criterion).  The
fused-vs-lockstep greedy ORACLE identity and the rounds/sec speedup live
in scripts/perf_gate.py's ``megaround`` scenario (perf_baseline.json).
"""

import dataclasses
import importlib.util
import json
import os
import re
import warnings

import numpy as np
import pytest

from bcg_tpu.config import BCGConfig, EngineConfig, GameConfig, MetricsConfig
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.engine.megaround import (
    MegaroundTemplate,
    MegaroundUnsupported,
    build_plan,
    decision_schema,
    vote_schema,
)
from bcg_tpu.engine.tokenizer import ByteTokenizer

_LADDER = (256, 384, 512, 768, 1024)


def _chat_parts(system: str, user: str):
    # A minimal chat template: the plan builder needs (prefix, suffix)
    # whose concatenation embeds the user prompt exactly once, like the
    # real model templates the engine binds.
    return (f"<s>[SYS]{system}[/SYS]\n{user}", "\n[END]")


def _tiny_plan(n=4, lo=0, hi=50, max_rounds=6):
    template = MegaroundTemplate(n_agents=n, lo=lo, hi=hi,
                                 max_rounds=max_rounds)
    return build_plan(template, ByteTokenizer(), _chat_parts, 2048, _LADDER)


class TestTemplate:
    def test_fixed_width_rendering(self):
        """Every (values, inbox, round) combination renders to the SAME
        byte length — the property that lets slots become static token
        columns."""
        t = MegaroundTemplate(n_agents=4, lo=0, hi=50, max_rounds=9)
        lengths = set()
        for values, round_num in [
            ([3, 17, 3, 42], 1),
            ([50, 0, 7, 9], 9),
            ([-1, -1, -1, -1], 0),
        ]:
            vals = np.asarray(values, np.int32)
            inbox = np.tile(vals, (4, 1))
            for _sys, user, _schema in t.decision_prompts(vals, inbox,
                                                          round_num):
                lengths.add(len(user.encode("utf-8")))
            for _sys, user, _schema in t.vote_prompts(vals, inbox,
                                                      round_num):
                lengths.add(len(user.encode("utf-8")))
        # One length per phase (tails differ), not per round state.
        assert len(lengths) == 2, lengths

    def test_slot_lines_feed_fake_engine_policies(self):
        """The rendered lines deliberately match the FakeEngine's stock
        prompt regexes (present slots parse, dash slots fail) so the
        fake mirror exercises the same policy code as lockstep
        prompts."""
        from bcg_tpu.engine.fake import _CURRENT_RE, _ROUND_RE, _VALUE_RE

        t = MegaroundTemplate(n_agents=3, lo=0, hi=50, max_rounds=6)
        vals = np.asarray([7, -1, 23], np.int32)
        inbox = np.asarray(
            [[-1, -1, 23], [7, -1, 23], [7, -1, -1]], np.int32
        )
        _sys, user, _schema = t.decision_prompts(vals, inbox, 2)[0]
        assert [int(v) for v in _VALUE_RE.findall(user)] == [23]
        assert int(_CURRENT_RE.search(user).group(1)) == 7
        assert int(_ROUND_RE.search(user).group(1)) == 2
        # Row 1 abstained: its own slot renders dashes and fails the
        # current-value regex rather than parsing as garbage.
        _sys2, user2, _schema2 = t.decision_prompts(vals, inbox, 2)[1]
        assert _CURRENT_RE.search(user2) is None

    def test_schemas(self):
        d = decision_schema(0, 50)
        assert d["properties"]["value"]["minimum"] == 0
        assert d["properties"]["value"]["maximum"] == 50
        v = vote_schema()
        assert v["properties"]["value"]["maximum"] == 1


class TestPlan:
    def test_static_key_is_round_state_free(self):
        """Two plans for the same game layout share one static key (one
        compiled program), and the key holds only hashable layout
        scalars — round number / values / inbox can never leak in."""
        k1 = _tiny_plan().static_key()
        k2 = _tiny_plan().static_key()
        assert k1 == k2
        assert hash(k1) == hash(k2)

    def test_layout_change_changes_key(self):
        assert _tiny_plan(n=4).static_key() != _tiny_plan(n=5).static_key()

    def test_prefix_precedes_every_dynamic_slot(self):
        """The static-prefix split: every dynamic slot column sits at or
        after prefix_len, and the prefix region is non-trivial (the
        engine prefills it once per plan, not once per round)."""
        plan = _tiny_plan()
        for phase in (plan.decide, plan.vote):
            dynamic = (phase.round_col, phase.own_col) + phase.inbox_cols
            assert all(col >= phase.prefix_len for col in dynamic)
            assert 0 < phase.prefix_len < phase.L

    def test_negative_range_unsupported(self):
        template = MegaroundTemplate(n_agents=4, lo=-5, hi=5, max_rounds=6)
        with pytest.raises(MegaroundUnsupported, match="negative"):
            build_plan(template, ByteTokenizer(), _chat_parts, 2048, _LADDER)


class TestFakeEngineMirror:
    def test_prepare_mirrors_range_gate(self):
        eng = FakeEngine()
        with pytest.raises(MegaroundUnsupported):
            eng.prepare_megaround(n_agents=4, lo=-1, hi=5, max_rounds=6)

    def test_fused_round_exchange_and_tally(self):
        """The numpy mirror reproduces the dense game_step bodies: a
        full-mask round where honest agents agree stops the game, and
        deliveries/received match the masked-matmul twin exactly."""
        from bcg_tpu.parallel.game_step import masked_exchange

        eng = FakeEngine(policy="consensus")
        plan = eng.prepare_megaround(n_agents=4, lo=0, hi=50, max_rounds=6)
        values = np.asarray([7, 7, 7, 7], np.int32)
        inbox = np.tile(values, (4, 1))
        mask = ~np.eye(4, dtype=bool)
        res = eng.run_megaround(
            plan, values, inbox, 2, mask, np.zeros(4, bool), values
        )
        assert list(res.proposed) == [7, 7, 7, 7]
        received, deliveries = masked_exchange(res.proposed, mask)
        np.testing.assert_array_equal(res.received, np.asarray(received))
        np.testing.assert_array_equal(res.deliveries, np.asarray(deliveries))
        assert res.terminate and res.has_consensus
        assert res.consensus_value == 7 and res.agreement_pct == 100.0
        assert res.vote_dict(["a", "b", "c", "d"]) == {
            "a": True, "b": True, "c": True, "d": True,
        }

    def test_sync_profile_matches_fused_entry(self):
        """The mirror carries the real fused entry's host-sync shape:
        one round -> one fused round in stats, syncs_per_round 1.0."""
        eng = FakeEngine()
        plan = eng.prepare_megaround(n_agents=3, lo=0, hi=50, max_rounds=6)
        values = np.asarray([3, 17, 42], np.int32)
        eng.run_megaround(
            plan, values, np.full((3, 3), -1, np.int32), 1,
            ~np.eye(3, dtype=bool), np.zeros(3, bool), values,
        )
        stats = eng.megaround_stats()
        assert stats["fused_rounds"] == 1
        assert stats["syncs_per_round"] == 1.0
        assert stats["rounds_per_sec"] > 0


def _sim_config(**agent_kw):
    cfg = BCGConfig(
        game=GameConfig(num_honest=3, num_byzantine=1, max_rounds=6, seed=0),
        engine=EngineConfig(backend="fake", model_name="bcg-tpu/tiny-test"),
        metrics=MetricsConfig(save_results=False),
    )
    return dataclasses.replace(
        cfg, agent=dataclasses.replace(cfg.agent, **agent_kw)
    )


class TestOrchestratorDispatch:
    def test_fused_game_converges(self):
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        sim = BCGSimulation(config=_sim_config(megaround=True))
        stats = sim.run()
        assert stats["consensus_outcome"] == "valid"
        assert sim.engine.megaround_rounds == stats["total_rounds"]
        assert sim.engine.megaround_stats()["syncs_per_round"] == 1.0

    def test_flag_off_stays_lockstep(self, monkeypatch):
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        monkeypatch.delenv("BCG_TPU_MEGAROUND", raising=False)
        sim = BCGSimulation(config=_sim_config(megaround=False))
        stats = sim.run()
        assert stats["consensus_outcome"] == "valid"
        assert sim.engine.megaround_rounds == 0

    @pytest.mark.parametrize(
        "break_it",
        ["structured", "batched", "protocol"],
        ids=["free-text", "sequential", "lossy-channel"],
    )
    def test_unsupported_configs_fall_back_with_warning(self, break_it):
        """The fallback matrix (DESIGN.md): any ineligible configuration
        plays the full lockstep game and says so ONCE."""
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        cfg = _sim_config(megaround=True)
        if break_it == "structured":
            cfg = dataclasses.replace(
                cfg, agent=dataclasses.replace(
                    cfg.agent, use_structured_output=False
                )
            )
        elif break_it == "batched":
            cfg = dataclasses.replace(
                cfg, agent=dataclasses.replace(
                    cfg.agent, use_batched_inference=False
                )
            )
        else:
            cfg = dataclasses.replace(
                cfg, communication=dataclasses.replace(
                    cfg.communication, protocol_type="lossy_sim"
                )
            )
        sim = BCGSimulation(config=cfg)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            stats = sim.run()
        mega_warnings = [
            w for w in caught
            if issubclass(w.category, RuntimeWarning)
            and "megaround" in str(w.message)
        ]
        assert len(mega_warnings) == 1, [str(w.message) for w in caught]
        assert sim.engine.megaround_rounds == 0
        assert stats["total_rounds"] >= 1


class TestJaxFusedRound:
    def test_round_state_never_retraces(self):
        """Retrace pinning on the real engine: round 1 compiles the
        fused program ONCE; rounds with different round numbers, inbox
        matrices, values, and convergence states reuse it (compile and
        retrace counters frozen)."""
        from bcg_tpu.engine.jax_engine import JaxEngine
        from bcg_tpu.obs import counters as obs_counters

        eng = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048,
        ))
        try:
            n = 3
            plan = eng.prepare_megaround(
                n_agents=n, lo=0, hi=50, max_rounds=6
            )
            mask = ~np.eye(n, dtype=bool)
            values = np.asarray([3, 17, 42], np.int32)
            initials = values.copy()
            inbox = np.full((n, n), -1, np.int32)
            res = eng.run_megaround(
                plan, values, inbox, 1, mask, np.zeros(n, bool), initials
            )
            snap = obs_counters.snapshot()
            compiles = snap.get("engine.compile.megaround", 0)
            retraces = snap.get("engine.retrace.megaround", 0)
            for round_num in (2, 3):
                res = eng.run_megaround(
                    plan, res.values, res.received, round_num, mask,
                    np.zeros(n, bool), initials,
                )
            snap = obs_counters.snapshot()
            assert snap.get("engine.compile.megaround", 0) == compiles
            assert snap.get("engine.retrace.megaround", 0) == retraces
            assert eng.megaround_rounds == 3
            assert eng.megaround_stats()["syncs_per_round"] == 1.0
            # Parses stay in-range or abstain; received is mask-shaped.
            assert all(-1 <= v <= 50 for v in res.proposed)
            assert (np.asarray(res.received)[~mask] == -1).all()
        finally:
            eng.shutdown()


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def megaround_gate():
    """One in-process run of the perf_gate megaround scenario — this
    file owns the ``megaround.`` namespace's resurface contract
    (tests/test_perf_gate.py NAMESPACE_OWNERS)."""
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(_REPO, "scripts", "perf_gate.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, mod.run_megaround_scenario()


class TestPerfGateMegaround:
    def test_scenario_green_and_nothing_stale(self, megaround_gate):
        mod, measured = megaround_gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(measured, mod.load_baseline(),
                                    ("megaround",))
        assert findings == [], "\n".join(findings)

    def test_acceptance_values(self, megaround_gate):
        """ISSUE-16 acceptance: greedy decisions/votes identical to the
        lockstep oracle, warm fused rounds faster than lockstep, zero
        steady-state retraces."""
        _, measured = megaround_gate
        assert measured["megaround.decision_mismatches"] == 0
        assert measured["megaround.vote_mismatches"] == 0
        assert measured["megaround.steady_retraces"] == 0
        assert measured["megaround.round_speedup"] > 1.0

    def test_removing_each_entry_resurfaces_its_finding(
        self, megaround_gate
    ):
        mod, measured = megaround_gate
        baseline = mod.load_baseline()
        entries = [
            n for n in baseline["metrics"] if n.startswith("megaround.")
        ]
        assert sorted(entries) == [
            "megaround.decision_mismatches", "megaround.round_speedup",
            "megaround.steady_retraces", "megaround.vote_mismatches",
        ]
        for removed in entries:
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(measured, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)
