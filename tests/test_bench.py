"""bench.py's never-rc=1 contract (VERDICT round-2 weak #1).

The driver records whatever single JSON line the bench prints; a bare
non-zero exit loses the round's number.  These tests pin the attempt/
retry harness: transient tunnel failures retry exactly once, anything
else becomes an error-JSON line, and a success after retry reports the
real number.
"""

import json

import pytest

import bench


GOOD = {
    "metric": "agent_decisions_per_sec",
    "value": 5.0,
    "unit": "decisions/sec",
    "vs_baseline": 7.46,
    "extra": {},
}


@pytest.fixture(autouse=True)
def fake_backend_env(monkeypatch):
    monkeypatch.setenv("BENCH_BACKEND", "fake")
    monkeypatch.delenv("BENCH_MODEL", raising=False)


def _last_json(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1])


def test_transient_failure_retries_once_then_reports(monkeypatch, capsys):
    calls = []

    def attempt(*a, **k):
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError(
                "UNAVAILABLE: http://127.0.0.1:1/remote_compile: transport"
            )
        return dict(GOOD)

    monkeypatch.setattr(bench, "_run_attempt", attempt)
    bench.main()
    out = _last_json(capsys)
    assert out["value"] == 5.0
    assert len(calls) == 2


def test_transient_failure_twice_reports_error_json(monkeypatch, capsys):
    def attempt(*a, **k):
        raise RuntimeError("Connection reset by peer")

    monkeypatch.setattr(bench, "_run_attempt", attempt)
    bench.main()
    out = _last_json(capsys)
    assert out["value"] == 0.0
    assert "failed again after one retry" in out["error"]
    assert "traceback_tail" in out


def test_nontransient_failure_no_retry(monkeypatch, capsys):
    calls = []

    def attempt(*a, **k):
        calls.append(1)
        raise ValueError("shape mismatch somewhere deep")

    monkeypatch.setattr(bench, "_run_attempt", attempt)
    bench.main()
    out = _last_json(capsys)
    assert out["value"] == 0.0
    assert "not retried (non-transient)" in out["error"]
    assert len(calls) == 1


def test_is_transient_classification():
    assert bench._is_transient(RuntimeError("DEADLINE_EXCEEDED: poll"))
    assert bench._is_transient(OSError("Broken pipe"))
    assert not bench._is_transient(ValueError("bad config"))
    # OOMs are deterministic: a retry would just repeat a long failure.
    assert not bench._is_transient(RuntimeError("RESOURCE_EXHAUSTED: hbm"))


def test_fake_backend_end_to_end_smoke(monkeypatch, capsys):
    """The real _run_attempt on the fake backend: one JSON line with the
    contract fields and the knob labels."""
    monkeypatch.setenv("BENCH_ROUNDS", "1")
    monkeypatch.setenv("BENCH_WARMUP", "1")
    bench.main()
    out = _last_json(capsys)
    assert out["metric"] == "agent_decisions_per_sec"
    assert out["value"] > 0
    for key in ("quantization", "kv_cache_dtype", "fast_forward",
                "prefix_caching", "scan_layers", "shared_core_votes",
                "boot_plus_first_round_s"):
        assert key in out["extra"]
    # Cold-boot metric is a real measurement, not the None fallback.
    assert out["extra"]["boot_plus_first_round_s"] is not None
