"""Consensus-game telemetry (bcg_tpu/obs/game_events.py) +
scripts/consensus_report.py.

The ISSUE-9 acceptance surface, asserted hermetically over FakeEngine
games:

* JSONL schema + manifest roundtrip — every emitted record type, its
  required fields, and the one-source-of-truth guarantee that the
  ``round_end`` stream carries exactly ``compute_statistics``'s
  ``rounds_data`` shape;
* a topology-masked game's ``deliveries`` records expose the ring mask;
* live ``game.*`` counters + the ``game.round_ms`` histogram are
  scrapeable on the Prometheus endpoint mid-process (ephemeral port via
  ``BCG_TPU_METRICS_PORT``), with zero steady-state retraces;
* the disabled-by-default path adds no counters, no sink thread, and no
  recorder;
* ``consensus_report.py`` aggregates two merged event files into a
  non-empty convergence table with no bcg_tpu import.
"""

import dataclasses
import json
import socket
import subprocess
import sys
import threading
import urllib.request

import pytest

from bcg_tpu.config import (
    BCGConfig,
    EngineConfig,
    GameConfig,
    MetricsConfig,
    NetworkConfig,
)
from bcg_tpu.game.statistics import compute_statistics
from bcg_tpu.obs import counters as obs_counters, export, game_events
from bcg_tpu.runtime import metrics as runtime_metrics
from bcg_tpu.runtime.orchestrator import BCGSimulation

REPO = __file__.rsplit("/tests/", 1)[0]
REPORT = f"{REPO}/scripts/consensus_report.py"

REQUIRED_EVENTS = {
    "game_start", "round_start", "decision", "deliveries", "vote",
    "round_end", "game_end",
}

ROUND_RECORD_KEYS = {
    "round", "honest_values", "byzantine_values", "honest_mean",
    "honest_std", "convergence_metric", "has_consensus",
    "consensus_value", "agreement_count",
}
CONVERGENCE_KEYS = {
    "distinct_honest_values", "value_spread", "margin_vs_threshold",
    "byzantine_influence",
}


def _game_config(seed=7, topology="fully_connected", num_honest=4,
                 num_byzantine=1, max_rounds=6):
    return dataclasses.replace(
        BCGConfig(),
        game=GameConfig(num_honest=num_honest, num_byzantine=num_byzantine,
                        max_rounds=max_rounds, seed=seed),
        network=NetworkConfig(topology_type=topology),
        engine=EngineConfig(backend="fake"),
        metrics=MetricsConfig(save_results=False),
        verbose=False,
    )


def _run_game(cfg):
    sim = BCGSimulation(config=cfg)
    try:
        sim.run()
    finally:
        sim.close()
    return sim


@pytest.fixture
def events_enabled(tmp_path, monkeypatch):
    """BCG_TPU_GAME_EVENTS pointed at a temp file, sink + aggregate
    isolated from whatever ran earlier in the process."""
    path = tmp_path / "game_events.jsonl"
    monkeypatch.setenv("BCG_TPU_GAME_EVENTS", str(path))
    game_events.reset_sink()
    game_events._reset_aggregate()
    yield path
    game_events.reset_sink()
    game_events._reset_aggregate()


def _read_events(path):
    return [json.loads(l) for l in path.read_text().splitlines()]


class TestSchemaRoundtrip:
    def test_manifest_and_required_event_types(self, events_enabled):
        _run_game(_game_config())
        game_events.reset_sink()  # drain to disk
        records = _read_events(events_enabled)
        assert records[0]["event"] == "manifest"
        assert records[0]["schema_version"] == export.EVENT_SCHEMA_VERSION
        assert records[0]["kind"] == "game"
        assert "BCG_TPU_GAME_EVENTS" in records[0]["flags"]
        kinds = {r["event"] for r in records[1:]}
        assert kinds >= REQUIRED_EVENTS
        # Every post-manifest record carries the common envelope.
        for r in records[1:]:
            assert "ts" in r and "game" in r and "round" in r, r

    def test_decision_vote_delivery_fields(self, events_enabled):
        _run_game(_game_config())
        game_events.reset_sink()
        records = _read_events(events_enabled)
        decisions = [r for r in records if r["event"] == "decision"]
        votes = [r for r in records if r["event"] == "vote"]
        deliveries = [r for r in records if r["event"] == "deliveries"]
        assert decisions and votes and deliveries
        roles = set()
        for d in decisions:
            assert d["role"] in ("honest", "byzantine")
            assert d["outcome"] in ("valid", "fallback", "invalid")
            assert d["value"] is None or isinstance(d["value"], int)
            roles.add(d["role"])
        assert roles == {"honest", "byzantine"}
        for v in votes:
            assert v["vote"] in ("stop", "continue", "abstain")
        for m in deliveries:
            assert m["count"] == len(m["senders"])

    def test_round_end_matches_compute_statistics(self, events_enabled):
        """One source of truth: the streamed round_end records carry
        exactly the rounds_data dicts compute_statistics derives from
        the same game (plus the convergence block + duration)."""
        sim = _run_game(_game_config())
        game_events.reset_sink()
        records = _read_events(events_enabled)
        round_ends = [r for r in records if r["event"] == "round_end"]
        rounds_data = compute_statistics(sim.game)["rounds_data"]
        assert len(round_ends) == len(rounds_data) == len(sim.game.rounds)
        for streamed, computed in zip(round_ends, rounds_data):
            assert ROUND_RECORD_KEYS <= set(streamed)
            assert CONVERGENCE_KEYS <= set(streamed)
            for key in ROUND_RECORD_KEYS:
                assert streamed[key] == computed[key], key
            assert streamed["duration_ms"] >= 0

    def test_game_end_totals(self, events_enabled):
        sim = _run_game(_game_config())
        game_events.reset_sink()
        records = _read_events(events_enabled)
        ends = [r for r in records if r["event"] == "game_end"]
        assert len(ends) == 1
        end = ends[0]
        assert end["converged"] == bool(sim.game.consensus_reached)
        assert end["rounds"] == len(sim.game.rounds)
        assert end["byzantine_influence"] == sum(
            r["byzantine_influence"] for r in records
            if r["event"] == "round_end"
        )

    def test_summary_published_for_bench(self, events_enabled):
        _run_game(_game_config())
        summary = game_events.summary()
        assert summary == runtime_metrics.LAST_GAME_STATS
        assert summary["games"] == summary["games_completed"] == 1
        assert summary["rounds"] >= 1
        assert summary["events_dropped"] >= 0


class TestTopologyMask:
    def test_ring_deliveries_are_masked(self, events_enabled):
        """On a ring every agent's round inbox is exactly its 2
        neighbors — the deliveries stream must show the mask, not the
        fully-connected n-1."""
        n = 6
        _run_game(_game_config(seed=3, topology="ring",
                               num_honest=n - 1, num_byzantine=1))
        game_events.reset_sink()
        records = _read_events(events_enabled)
        deliveries = [r for r in records if r["event"] == "deliveries"]
        assert deliveries
        for m in deliveries:
            assert m["count"] == 2, m
            assert m["agent"] not in m["senders"]
        start = [r for r in records if r["event"] == "game_start"][0]
        assert start["topology"] == "ring"


class TestLiveMetrics:
    def test_scrape_game_metrics_mid_process(self, tmp_path, monkeypatch):
        """Acceptance criterion: with BCG_TPU_GAME_EVENTS +
        BCG_TPU_METRICS_PORT set, a hermetic two-game FakeEngine run is
        scrapeable — ``game.*`` counters AND a conformant
        ``game.round_ms`` histogram family — with zero steady-state
        retraces."""
        path = tmp_path / "ev.jsonl"
        monkeypatch.setenv("BCG_TPU_GAME_EVENTS", str(path))
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        monkeypatch.setenv("BCG_TPU_METRICS_PORT", str(port))
        export.stop_http_server()
        game_events.reset_sink()
        game_events._reset_aggregate()
        before = obs_counters.snapshot()
        try:
            for seed in (7, 8):
                _run_game(_game_config(seed=seed))
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                body = resp.read().decode()
        finally:
            export.stop_http_server()
            game_events.reset_sink()
            game_events._reset_aggregate()
        # Counters are process-cumulative: presence on the scrape here,
        # exact movement via the registry delta below.
        assert "bcg_game_games_total" in body
        assert "bcg_game_rounds_total" in body
        assert "bcg_game_decisions_total" in body
        assert "# TYPE bcg_game_round_ms histogram" in body
        assert 'bcg_game_round_ms_bucket{le="+Inf"}' in body
        assert "bcg_game_round_ms_sum" in body
        assert "bcg_game_round_ms_count" in body
        moved = obs_counters.delta(before)
        assert moved.get("game.games") == 2
        assert moved.get("game.games.converged", 0) >= 1
        assert not any(k.startswith("engine.retrace.") for k in moved), moved


class TestDisabledByDefault:
    def test_no_recorder_no_counters_no_threads(self, monkeypatch):
        monkeypatch.delenv("BCG_TPU_GAME_EVENTS", raising=False)
        game_events.reset_sink()
        threads_before = {
            t.name for t in threading.enumerate() if t.is_alive()
        }
        before = obs_counters.snapshot()
        sim = BCGSimulation(config=_game_config())
        try:
            assert sim._recorder is None
            sim.run()
        finally:
            sim.close()
        moved = obs_counters.delta(before)
        assert not any(k.startswith("game.") for k in moved), moved
        new_threads = {
            t.name for t in threading.enumerate() if t.is_alive()
        } - threads_before
        assert not any("event-sink" in n for n in new_threads), new_threads


class TestConsensusReport:
    def test_two_merged_games_aggregate(self, tmp_path, monkeypatch):
        """Smoke over two event files from different configs: the
        report groups them into separate convergence-table rows, each
        non-empty, with no bcg_tpu import in the script."""
        paths = []
        for seed, topo in ((7, "fully_connected"), (3, "ring")):
            path = tmp_path / f"ev_{topo}.jsonl"
            monkeypatch.setenv("BCG_TPU_GAME_EVENTS", str(path))
            game_events.reset_sink()
            _run_game(_game_config(seed=seed, topology=topo))
            game_events.reset_sink()
            paths.append(str(path))
        proc = subprocess.run(
            [sys.executable, REPORT, *paths, "--rounds"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        out = proc.stdout
        assert "consensus outcomes by config" in out
        # Actual table rows (start with the numeric runs column), not
        # warnings or headers that happen to mention a topology.
        table_rows = [
            l for l in out.splitlines()
            if l.strip() and l.lstrip()[0].isdigit()
            and ("fully_connected" in l or "ring" in l)
        ]
        assert any("fully_connected" in l for l in table_rows)
        assert any("ring" in l for l in table_rows)
        # Both files came from ONE process (shared per-process run id +
        # stamped rank): each config row reports exactly 1 run and 1
        # contributing rank, not an anonymous pile of files.
        assert len(table_rows) == 2
        for row in table_rows:
            assert row.split()[:2] == ["1", "1"], row
        assert "100.0%" in out            # both seeded games converge
        assert "rounds-to-consensus distribution" in out
        assert "round duration" in out
        src = open(REPORT).read()
        assert "import bcg_tpu" not in src and "from bcg_tpu" not in src

    def test_report_errors_on_empty_input(self, tmp_path):
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        proc = subprocess.run(
            [sys.executable, REPORT, str(empty)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1
        assert "no game records" in proc.stderr

    def test_report_tolerates_missing_game_end(self, tmp_path):
        """A game whose tail was lost to sink backpressure is counted
        incomplete and excluded from the convergence rate, not
        guessed."""
        path = tmp_path / "truncated.jsonl"
        lines = [
            {"event": "manifest", "schema_version": 1, "run_id": "x",
             "flags": {}},
            {"event": "game_start", "game": "g1", "round": None,
             "num_honest": 3, "num_byzantine": 0,
             "topology": "fully_connected"},
            {"event": "round_end", "game": "g1", "round": 1,
             "has_consensus": False, "byzantine_influence": 0,
             "duration_ms": 2.0},
        ]
        path.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
        proc = subprocess.run(
            [sys.executable, REPORT, str(path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "without a game_end" in proc.stdout

    def test_report_warns_on_unknown_schema(self, tmp_path):
        path = tmp_path / "future.jsonl"
        lines = [
            {"event": "manifest", "schema_version": 99, "run_id": "x",
             "flags": {}},
            {"event": "game_end", "game": "g1", "round": 1,
             "converged": True, "rounds": 1, "byzantine_influence": 0},
        ]
        path.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
        proc = subprocess.run(
            [sys.executable, REPORT, str(path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "unknown schema_version" in proc.stdout
