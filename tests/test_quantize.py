"""int8 W8A8 weight quantization (models/quantize.py).

Properties tested:
* per-channel dequantization error is bounded;
* a quantized tiny model's logits track the bf16 model closely enough to
  agree on greedy tokens most of the time;
* the quantized engine still produces schema-valid JSON (the automaton
  guarantees structure regardless of weight numerics);
* quantized param pytrees shard over a tp mesh without error.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.models import init_params, prefill, spec_for_model
from bcg_tpu.models.quantize import dense, is_quantized, quantize_params, quantize_weight
from bcg_tpu.models.transformer import init_kv_cache


class TestQuantizeWeight:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
        qw = quantize_weight(w)
        assert qw["q"].dtype == jnp.int8
        assert qw["scale"].shape == (32,)
        deq = qw["q"].astype(jnp.float32) * qw["scale"]
        # Max error per element <= scale/2 (half a quantization step).
        assert float(jnp.max(jnp.abs(deq - w) / qw["scale"])) <= 0.5 + 1e-3

    def test_dense_matches_bf16_matmul(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.normal(k1, (4, 64), jnp.bfloat16)
        w = jax.random.normal(k2, (64, 32), jnp.bfloat16)
        exact = (x @ w).astype(jnp.float32)
        quant = dense(x, quantize_weight(w)).astype(jnp.float32)
        # W8A8 with per-token/per-channel scales: ~1% relative error on
        # well-conditioned gaussian data.
        rel = jnp.linalg.norm(quant - exact) / jnp.linalg.norm(exact)
        assert float(rel) < 0.03

    def test_passthrough_for_bf16(self):
        x = jnp.ones((2, 8), jnp.bfloat16)
        w = jnp.ones((8, 4), jnp.bfloat16)
        assert not is_quantized(w)
        np.testing.assert_array_equal(np.asarray(dense(x, w)), np.asarray(x @ w))


class TestQuantizedModel:
    def test_logits_track_bf16(self):
        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        qparams = quantize_params(params, spec)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, spec.vocab_size)
        valid = jnp.ones((2, 16), bool)
        cache = init_kv_cache(spec, 2, 17)
        qcache = init_kv_cache(spec, 2, 17)
        logits, _ = prefill(params, spec, tokens, valid, cache)
        qlogits, _ = prefill(qparams, spec, tokens, valid, qcache)
        lf = np.asarray(logits, np.float64)
        qf = np.asarray(qlogits, np.float64)
        cos = (lf * qf).sum() / (np.linalg.norm(lf) * np.linalg.norm(qf) + 1e-9)
        assert cos > 0.98

    def test_tied_embeddings_get_quantized_head(self):
        spec = dataclasses.replace(spec_for_model("bcg-tpu/tiny-test"), tie_embeddings=True)
        params = init_params(spec, jax.random.PRNGKey(0))
        assert "lm_head" not in params
        qparams = quantize_params(params, spec)
        assert is_quantized(qparams["lm_head"])
        # bf16 embedding table must survive for token gathers.
        assert qparams["embed"].dtype == jnp.bfloat16


class TestQuantizedEngine:
    def test_guided_json_still_valid(self):
        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=1024, quantization="int8",
        ))
        schema = {
            "type": "object",
            "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
            "required": ["decision"],
            "additionalProperties": False,
        }
        out = engine.generate_json("vote now", schema, temperature=0.7, max_tokens=24)
        assert out.get("decision") in ("stop", "continue")
        engine.shutdown()

    def test_rejects_unknown_quantization(self):
        with pytest.raises(ValueError, match="quantization"):
            JaxEngine(EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                                   quantization="fp4"))


class TestQuantizedSharding:
    def test_shards_over_tp_mesh(self):
        from bcg_tpu.parallel.mesh import build_mesh
        from bcg_tpu.parallel.sharding import shard_params

        spec = spec_for_model("bcg-tpu/tiny-test")
        qparams = quantize_params(init_params(spec, jax.random.PRNGKey(0)), spec)
        mesh = build_mesh(tp=2, dp=1, sp=1)
        sharded = shard_params(qparams, spec, mesh)
        layer = sharded["layers"][0]
        # Column-parallel weight: output dim split over tp; its scale too.
        wq = layer["wq"]
        assert wq["q"].sharding.spec == jax.sharding.PartitionSpec(None, "tp")
        assert wq["scale"].sharding.spec == jax.sharding.PartitionSpec("tp")
        # Row-parallel weight: input dim split; scale replicated.
        wo = layer["wo"]
        assert wo["q"].sharding.spec == jax.sharding.PartitionSpec("tp", None)
        assert wo["scale"].sharding.spec in (
            jax.sharding.PartitionSpec(None), jax.sharding.PartitionSpec(),
        )
        # And the sharded quantized model still runs.
        tokens = jnp.zeros((2, 8), jnp.int32)
        valid = jnp.ones((2, 8), bool)
        cache = init_kv_cache(spec, 2, 9)
        logits, _ = prefill(sharded, spec, tokens, valid, cache)
        assert logits.shape == (2, spec.vocab_size)


class TestW8A16Prefill:
    """Experimental BCG_TPU_W8A16_PREFILL row-threshold dispatch:
    at/above the threshold dense() skips activation quantization and
    multiplies the dequantized bf16 weight directly (W8A16)."""

    def test_matches_explicit_dequant(self, monkeypatch):
        import numpy as np

        from bcg_tpu.models.quantize import dense, quantize_weight

        monkeypatch.setenv("BCG_TPU_W8A16_PREFILL", "4")
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.standard_normal((32, 16)) * 0.1, jnp.bfloat16)
        qw = quantize_weight(w)
        x = jnp.asarray(rng.standard_normal((8, 32)) * 0.5, jnp.bfloat16)
        got = dense(x, qw)
        w_bf = (qw["q"].astype(jnp.float32) * qw["scale"]).astype(jnp.bfloat16)
        want = (x.astype(jnp.bfloat16) @ w_bf).astype(x.dtype)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_below_threshold_keeps_w8a8(self, monkeypatch):
        import numpy as np

        from bcg_tpu.models.quantize import dense, quantize_weight

        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.standard_normal((32, 16)) * 0.1, jnp.bfloat16)
        qw = quantize_weight(w)
        x = jnp.asarray(rng.standard_normal((2, 32)) * 0.5, jnp.bfloat16)
        monkeypatch.delenv("BCG_TPU_W8A16_PREFILL", raising=False)
        base = np.asarray(dense(x, qw))
        monkeypatch.setenv("BCG_TPU_W8A16_PREFILL", "1000")
        below = np.asarray(dense(x, qw))
        np.testing.assert_array_equal(base, below)
