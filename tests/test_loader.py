"""Checkpoint loader (models/loader.py): HF safetensors -> param pytree.

A synthetic HF-layout checkpoint is written for the tiny spec, then loaded
and compared against the source weights — including the [out, in] ->
[in, out] transposition, tied-embedding handling, and the streamed int8
quantization hook.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bcg_tpu.models import init_params, prefill, spec_for_model
from bcg_tpu.models.loader import (
    _LAYER_MAP,
    _TOP_MAP,
    _TRANSPOSED,
    find_checkpoint_dir,
    load_checkpoint_params,
)
from bcg_tpu.models.quantize import is_quantized, quantize_leaf_transform
from bcg_tpu.models.transformer import init_kv_cache


def _write_fake_checkpoint(tmp_path, spec, params):
    """Save ``params`` under HF tensor names (HF stores dense as [out, in]).

    NB: safetensors' numpy backend serializes the raw buffer without
    honoring strides — a transposed VIEW would silently save the
    untransposed bytes under the transposed shape — so every array is
    made contiguous first.
    """
    from safetensors.numpy import save_file

    tensors = {}
    for logical, hf_name in _TOP_MAP.items():
        if logical == "lm_head" and spec.tie_embeddings:
            continue
        arr = np.asarray(params[logical], np.float32)
        if logical in _TRANSPOSED:
            arr = arr.T
        tensors[hf_name] = np.ascontiguousarray(arr)
    for i, layer in enumerate(params["layers"]):
        for logical, template in _LAYER_MAP.items():
            if logical not in layer:
                continue
            arr = np.asarray(layer[logical], np.float32)
            if logical in _TRANSPOSED:
                arr = arr.T
            tensors[template.format(i=i)] = np.ascontiguousarray(arr)
    save_file(tensors, str(tmp_path / "model.safetensors"))
    return tmp_path


@pytest.fixture(scope="module")
def tiny():
    spec = spec_for_model("bcg-tpu/tiny-test")
    params = init_params(spec, jax.random.PRNGKey(0))
    return spec, params


class TestFindCheckpointDir:
    def test_env_override(self, tmp_path, monkeypatch, tiny):
        spec, params = tiny
        _write_fake_checkpoint(tmp_path, spec, params)
        monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", str(tmp_path))
        assert find_checkpoint_dir("any/model") == str(tmp_path)

    def test_direct_path(self, tmp_path, tiny):
        spec, params = tiny
        _write_fake_checkpoint(tmp_path, spec, params)
        assert find_checkpoint_dir(str(tmp_path)) == str(tmp_path)

    def test_missing(self, tmp_path):
        assert find_checkpoint_dir(str(tmp_path / "nope")) is None


class TestLoad:
    def test_roundtrip_matches_source_logits(self, tmp_path, tiny):
        spec, params = tiny
        _write_fake_checkpoint(tmp_path, spec, params)
        loaded = load_checkpoint_params(spec, str(tmp_path))

        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, spec.vocab_size)
        valid = jnp.ones((1, 12), bool)
        ref_logits, _ = prefill(params, spec, tokens, valid, init_kv_cache(spec, 1, 13))
        got_logits, _ = prefill(loaded, spec, tokens, valid, init_kv_cache(spec, 1, 13))
        np.testing.assert_allclose(
            np.asarray(got_logits), np.asarray(ref_logits), rtol=0.02, atol=0.02
        )

    def test_missing_checkpoint_raises(self):
        spec = spec_for_model("bcg-tpu/tiny-test")
        with pytest.raises(FileNotFoundError, match="zero-egress"):
            load_checkpoint_params(spec, "definitely/not-on-disk")

    def test_streamed_quantized_load(self, tmp_path, tiny):
        spec, params = tiny
        _write_fake_checkpoint(tmp_path, spec, params)
        loaded = load_checkpoint_params(
            spec, str(tmp_path), leaf_transform=quantize_leaf_transform(spec)
        )
        layer = loaded["layers"][0]
        assert is_quantized(layer["wq"]) and is_quantized(layer["w_down"])
        assert loaded["embed"].dtype == jnp.bfloat16      # gathers stay bf16
        assert loaded["layers"][0]["attn_norm"].dtype == jnp.bfloat16
        assert is_quantized(loaded["lm_head"])
        # Quantized load still produces working (close) logits.
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, spec.vocab_size)
        valid = jnp.ones((1, 8), bool)
        ref_logits, _ = prefill(params, spec, tokens, valid, init_kv_cache(spec, 1, 9))
        q_logits, _ = prefill(loaded, spec, tokens, valid, init_kv_cache(spec, 1, 9))
        lf = np.asarray(ref_logits, np.float64)
        qf = np.asarray(q_logits, np.float64)
        cos = (lf * qf).sum() / (np.linalg.norm(lf) * np.linalg.norm(qf) + 1e-9)
        assert cos > 0.98
