"""Telemetry export (bcg_tpu/obs/export.py) + HBM ledger
(bcg_tpu/obs/ledger.py).

Covers the ISSUE-6 export satellites: Prometheus text-exposition
conformance (HELP/TYPE lines, name sanitization, counter-vs-gauge
typing, escaping), an end-to-end scrape of the HTTP endpoint during a
FakeEngine serving run (serve counters + ledger gauges + seeded
engine.hlo.* gauges all present), the request-lifecycle JSONL sink, and
the ledger's charge/credit/headroom/reconcile semantics incl. the
engine boot/shutdown integration.
"""

import json
import re
import urllib.request

import pytest

from bcg_tpu.obs import counters as obs_counters, export, hlo as obs_hlo
from bcg_tpu.obs import ledger as obs_ledger
from bcg_tpu.obs.ledger import HbmLedger

_VALUE_LINE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]* -?[0-9.e+-]+$")


class TestPrometheusFormat:
    TYPED = {
        "counters": {"serve.requests": 3, "engine.spec.drafted": 12},
        "gauges": {"hbm.total_bytes": 1536.5, "engine.hlo.decode_loop.fusions": 7},
    }

    def test_help_type_value_triplets(self):
        text = export.render_prometheus(self.TYPED)
        lines = text.strip().splitlines()
        assert len(lines) == 3 * 4
        for i in range(0, len(lines), 3):
            assert lines[i].startswith("# HELP ")
            assert lines[i + 1].startswith("# TYPE ")
            assert _VALUE_LINE.match(lines[i + 2]), lines[i + 2]
            # HELP/TYPE/value agree on the metric name.
            name = lines[i + 2].split(" ")[0]
            assert lines[i].split(" ")[2] == name
            assert lines[i + 1].split(" ")[2] == name

    def test_counters_are_typed_counter_with_total_suffix(self):
        text = export.render_prometheus(self.TYPED)
        assert "# TYPE bcg_serve_requests_total counter" in text
        assert "bcg_serve_requests_total 3" in text
        assert "# TYPE bcg_engine_spec_drafted_total counter" in text

    def test_gauges_are_typed_gauge_without_suffix(self):
        text = export.render_prometheus(self.TYPED)
        assert "# TYPE bcg_hbm_total_bytes gauge" in text
        assert "bcg_hbm_total_bytes 1536.5" in text
        assert "bcg_engine_hlo_decode_loop_fusions 7" in text

    def test_name_sanitization(self):
        assert export.prometheus_name("serve.linger_le_1ms") == \
            "bcg_serve_linger_le_1ms"
        assert export.prometheus_name("weird-name with spaces") == \
            "bcg_weird_name_with_spaces"
        assert export.prometheus_name("a.b", counter=True) == "bcg_a_b_total"

    def test_help_escaping(self):
        text = export.render_prometheus(
            {"counters": {}, "gauges": {"x.back\\slash\nnewline": 1}}
        )
        help_line = [l for l in text.splitlines() if l.startswith("# HELP")][0]
        assert "\\\\" in help_line        # backslash escaped
        assert "\\n" in help_line         # newline escaped
        assert "\n" not in help_line      # and not literal

    def test_integer_values_render_bare(self):
        text = export.render_prometheus(
            {"counters": {"a.b": 5}, "gauges": {"c.d": 2.25}}
        )
        assert "bcg_a_b_total 5" in text
        assert "bcg_c_d 2.25" in text

    def test_empty_registry_renders_empty(self):
        assert export.render_prometheus({"counters": {}, "gauges": {}}) == ""

    def test_live_registry_roundtrip(self):
        obs_counters.inc("export.test_counter")
        obs_counters.set_gauge("export.test_gauge", 9)
        text = export.render_prometheus()
        assert "bcg_export_test_counter_total 1" in text
        assert "bcg_export_test_gauge 9" in text


class TestHistogramExposition:
    TYPED = {
        "counters": {},
        "gauges": {},
        "histograms": {
            "serve.e2e_ms": {
                "buckets": [[5.0, 2], [10.0, 3], [25.0, 3]],
                "sum": 31.5,
                "count": 4,
            },
        },
    }

    def test_conformant_family(self):
        """The spec's histogram family: TYPE histogram, cumulative
        ``_bucket{le=...}`` over the declared bounds, the mandatory
        ``+Inf`` bucket equal to ``_count``, then ``_sum``/``_count``."""
        text = export.render_prometheus(self.TYPED)
        assert "# TYPE bcg_serve_e2e_ms histogram" in text
        assert 'bcg_serve_e2e_ms_bucket{le="5"} 2' in text
        assert 'bcg_serve_e2e_ms_bucket{le="10"} 3' in text
        assert 'bcg_serve_e2e_ms_bucket{le="25"} 3' in text
        assert 'bcg_serve_e2e_ms_bucket{le="+Inf"} 4' in text
        assert "bcg_serve_e2e_ms_sum 31.5" in text
        assert "bcg_serve_e2e_ms_count 4" in text
        # Buckets stay together and ordered (one family block).
        bucket_lines = [
            l for l in text.splitlines() if "_bucket{" in l
        ]
        assert [l.split('le="')[1].split('"')[0] for l in bucket_lines] == \
            ["5", "10", "25", "+Inf"]

    def test_live_registry_histogram_roundtrip(self):
        h = obs_counters.histogram("export.test_hist_ms", (1, 10, 100))
        h.observe(0.5)
        h.observe(7)
        h.observe(5000)  # overflow bucket
        text = export.render_prometheus()
        assert "# TYPE bcg_export_test_hist_ms histogram" in text
        assert 'bcg_export_test_hist_ms_bucket{le="1"} 1' in text
        assert 'bcg_export_test_hist_ms_bucket{le="10"} 2' in text
        assert 'bcg_export_test_hist_ms_bucket{le="100"} 2' in text
        assert 'bcg_export_test_hist_ms_bucket{le="+Inf"} 3' in text
        assert "bcg_export_test_hist_ms_count 3" in text

    def test_scrape_serves_histogram_triplets(self):
        """Ephemeral-port scrape: a registry histogram arrives at the
        scraper as the full ``_bucket``/``_sum``/``_count`` family."""
        h = obs_counters.histogram("export.scrape_hist_ms", (2, 20))
        h.observe(1)
        h.observe(50)
        server, port = export.start_http_server(0)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                body = resp.read().decode()
        finally:
            server.shutdown()
            server.server_close()
        assert 'bcg_export_scrape_hist_ms_bucket{le="2"} 1' in body
        assert 'bcg_export_scrape_hist_ms_bucket{le="+Inf"} 2' in body
        assert "bcg_export_scrape_hist_ms_sum 51" in body
        assert "bcg_export_scrape_hist_ms_count 2" in body


class TestHttpEndpoint:
    def test_scrape_during_fake_serving_run(self):
        """Acceptance criterion: the endpoint serves engine.hlo.*,
        ledger gauges, and serve request counters during a FakeEngine
        serving run."""
        from bcg_tpu.api import run_simulation
        from bcg_tpu.engine.fake import FakeEngine
        from bcg_tpu.serve.engine import ServingEngine

        # Ledger + census gauges ride the same registry the serve run
        # bumps: charge a synthetic params share and publish the
        # checked-in decode_loop census (a FakeEngine lowers nothing).
        obs_ledger.charge("params", "test-scrape", 123456)
        obs_hlo.publish_gauges("decode_loop", {"fusions": 7, "step_ops": 42})
        server, port = export.start_http_server(0)
        try:
            serving = ServingEngine(FakeEngine(seed=0), linger_ms=1)
            out = run_simulation(n_agents=3, byzantine_count=0, max_rounds=1,
                                 backend="fake", seed=0, engine=serving)
            assert out["metrics"]["total_rounds"] >= 1
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/plain")
                body = resp.read().decode()
            serving.shutdown()
        finally:
            server.shutdown()
            server.server_close()
            obs_ledger.credit("params", "test-scrape")
        assert "bcg_serve_requests_total" in body
        assert "bcg_serve_dispatches_total" in body
        assert "bcg_hbm_params_bytes" in body
        assert "bcg_hbm_total_bytes" in body
        assert "bcg_engine_hlo_decode_loop_fusions 7" in body
        assert "bcg_engine_hlo_decode_loop_step_ops 42" in body

    def test_unknown_path_404(self):
        server, port = export.start_http_server(0)
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10
                )
            assert exc.value.code == 404
        finally:
            server.shutdown()
            server.server_close()

    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv("BCG_TPU_METRICS_PORT", raising=False)
        assert export.maybe_start_http_server() is None


class TestEventSink:
    def test_request_lifecycle_events(self, tmp_path, monkeypatch):
        from bcg_tpu.engine.fake import FakeEngine
        from bcg_tpu.serve.scheduler import AdmissionRejected, Scheduler

        path = tmp_path / "events.jsonl"
        monkeypatch.setenv("BCG_TPU_SERVE_EVENTS", str(path))
        export.reset_sink()
        try:
            sched = Scheduler(FakeEngine(seed=0), linger_ms=1,
                              bucket_rows=4, strict_admission=True)
            schema = {
                "type": "object",
                "properties": {"decision": {
                    "type": "string", "enum": ["stop", "continue"]}},
                "required": ["decision"],
            }
            payload = [("s", "Round 1: vote", schema)]
            out = sched.submit_and_wait(("json",), payload, [0.0], [16])
            assert len(out) == 1
            # Oversize under strict admission -> rejected event.
            with pytest.raises(AdmissionRejected):
                sched.submit_and_wait(("json",), payload * 5, [0.0] * 5,
                                      [16] * 5)
            sched.close()
        finally:
            export.reset_sink()
        events = [json.loads(l) for l in path.read_text().splitlines()]
        by_kind = {}
        for e in events:
            by_kind.setdefault(e["event"], []).append(e)
        assert set(by_kind) >= {"admitted", "dispatched", "completed",
                                "rejected"}
        done = by_kind["completed"][0]
        assert done["req_id"] == by_kind["admitted"][0]["req_id"]
        assert done["rows"] == 1 and "device_ms" in done
        assert "queue_wait_ms" in by_kind["dispatched"][0]
        assert by_kind["rejected"][0]["rows"] == 5

    def test_manifest_is_first_record(self, tmp_path):
        path = tmp_path / "manifested.jsonl"
        sink = export.EventSink(
            str(path), manifest=export.run_manifest(kind="serve")
        )
        sink.emit("admitted", req_id=1)
        sink.close()
        records = [json.loads(l) for l in path.read_text().splitlines()]
        assert records[0]["event"] == "manifest"
        assert records[0]["kind"] == "serve"
        assert records[0]["schema_version"] == export.EVENT_SCHEMA_VERSION
        assert len(records[0]["run_id"]) == 12
        assert isinstance(records[0]["flags"], dict)
        assert records[1]["event"] == "admitted"

    def test_overflow_drops_oldest_and_counts(self, tmp_path):
        """Bounded-queue overflow accounting: while the writer thread
        is locked out (the test holds the sink condition — an RLock, so
        same-thread emits still enter), emits past ``max_queue`` evict
        the OLDEST records and each eviction lands in the sink's drop
        counter.  What survives on disk is exactly the newest
        ``max_queue`` records."""
        drops_before = obs_counters.value("game.events_dropped")
        path = tmp_path / "overflow.jsonl"
        sink = export.EventSink(str(path), max_queue=4,
                                drop_counter="game.events_dropped")
        with sink._cond:  # writer thread cannot drain while held
            for i in range(10):
                sink.emit("e", i=i)
        sink.close()
        dropped = obs_counters.value("game.events_dropped") - drops_before
        assert dropped == 6
        records = [json.loads(l) for l in path.read_text().splitlines()]
        assert [r["i"] for r in records] == [6, 7, 8, 9]

    def test_disabled_sink_is_noop(self, monkeypatch):
        monkeypatch.delenv("BCG_TPU_SERVE_EVENTS", raising=False)
        export.reset_sink()
        try:
            export.emit_event("admitted", req_id=1)  # must not raise
        finally:
            export.reset_sink()


class TestLedger:
    def test_charge_credit_idempotent(self):
        led = HbmLedger(publish=False)
        led.charge("params", "a", 100)
        led.charge("params", "a", 150)   # re-charge replaces
        led.charge("kv_cache", "b", 50)
        assert led.total("params") == 150
        assert led.total() == 200
        led.credit("params", "a")
        led.credit("params", "never-charged")  # no-op
        assert led.total() == 50

    def test_unknown_account_raises(self):
        led = HbmLedger(publish=False)
        with pytest.raises(KeyError):
            led.charge("scratch", "k", 1)
        with pytest.raises(KeyError):
            led.credit("scratch", "k")

    def test_headroom_and_snapshot(self):
        led = HbmLedger(publish=False)
        assert led.headroom() is None
        led.set_limit(1000)
        led.charge("params", "p", 600)
        led.charge("spec_slots", "s", 100)
        assert led.headroom() == 300
        snap = led.snapshot()
        assert snap["params_bytes"] == 600
        assert snap["spec_slots_bytes"] == 100
        assert snap["total_bytes"] == 700
        assert snap["headroom_bytes"] == 300

    def test_gauges_published_on_mutation(self):
        obs_ledger.reset()
        try:
            obs_ledger.set_limit(10_000)
            obs_ledger.charge("kv_cache", "t", 4_000)
            snap = obs_counters.snapshot()
            assert snap["hbm.kv_cache_bytes"] == 4_000
            assert snap["hbm.total_bytes"] == 4_000
            assert snap["hbm.limit_bytes"] == 10_000
            assert snap["hbm.headroom_bytes"] == 6_000
        finally:
            obs_ledger.reset()

    def test_reconcile_on_cpu_returns_none_readings(self):
        led = HbmLedger(publish=False)
        led.charge("params", "p", 10)
        snap = led.reconcile()
        # CPU backend exposes no allocator stats.
        assert snap["device_bytes_in_use"] is None
        assert snap["unaccounted_bytes"] is None
        assert snap["total_bytes"] == 10

    def test_engine_boot_charges_and_shutdown_credits(self):
        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        base = obs_ledger.LEDGER.total("params")
        eng = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=512,
        ))
        charged = obs_ledger.LEDGER.total("params") - base
        assert charged == eng._param_bytes_per_device > 0
        eng.shutdown()
        assert obs_ledger.LEDGER.total("params") == base

    def test_paged_evict_readmit_cycles_stay_idempotent(self):
        """The paged radix index syncs its resident set through ONE
        keyed prefix_cache charge: insert/evict/re-admit cycles must
        track exactly (replace semantics), and eviction can never fire
        on a refcount-pinned chain mid-decode."""
        from bcg_tpu.engine.paged_kv import PagedKV
        from bcg_tpu.models.configs import MODEL_SPECS
        import numpy as np

        mgr = PagedKV(MODEL_SPECS["bcg-tpu/tiny-test"], 8, 2)
        key = object()
        mgr.set_ledger_key(key)
        bb = mgr.block_bytes_dev
        base = obs_ledger.LEDGER.total("prefix_cache")
        try:
            toks = np.array([1, 2, 3, 4], dtype=np.int32)
            for _cycle in range(3):
                mgr.insert([], toks, 0, mgr.alloc(2))
                assert (obs_ledger.LEDGER.total("prefix_cache") - base
                        == 2 * bb)
                # Pinned (in-flight): eviction must not fire.
                assert mgr.evict(2) == 0
                assert (obs_ledger.LEDGER.total("prefix_cache") - base
                        == 2 * bb)
                mgr.unpin_all()
                assert mgr.evict(2) == 2
                assert obs_ledger.LEDGER.total("prefix_cache") - base == 0
        finally:
            obs_ledger.credit("prefix_cache", key)

    def test_serve_snapshot_carries_hbm_block(self):
        from bcg_tpu.engine.fake import FakeEngine
        from bcg_tpu.serve.scheduler import Scheduler

        obs_ledger.charge("params", "serve-test", 777)
        try:
            sched = Scheduler(FakeEngine(seed=0), linger_ms=1)
            snap = sched.snapshot()
            sched.close()
        finally:
            obs_ledger.credit("params", "serve-test")
        assert snap["hbm"]["params_bytes"] >= 777
        assert "headroom_bytes" in snap["hbm"]
