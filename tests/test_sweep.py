"""Sweep service (bcg_tpu/sweep) — spec expansion, multi-tenant
scheduling, checkpoint/resume, multi-host partitioning, and the
perf_gate 'sweep' scenario's resurface contract (NAMESPACE_OWNERS).

The acceptance criteria asserted here:

* a spec expands to a DETERMINISTIC job list with stable content-hash
  ids (two hosts agree on the partition with no coordination);
* games-as-tenants: per-tenant quotas defer (retry-after) instead of
  rejecting, weighted-fair selection prevents starvation, priority
  classes order strictly;
* one command runs a whole grid to a single aggregated report, and
  re-running the same dir SKIPS completed jobs (resume at job
  granularity) — mid-game rounds resume from the
  BCG_TPU_SERVE_CHECKPOINT_EVERY checkpoints;
* a REAL 2-process CPU cluster partitions the job list, survives a
  SIGKILL mid-sweep, and after resume the merged per-job outcomes
  equal a single-process oracle run of the same spec with ZERO
  duplicate game_end events (consensus_report.duplicate_job_problems).
"""

import glob
import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO, "scripts", "perf_gate.py")
WORKER = os.path.join(REPO, "tests", "_sweep_worker.py")
REPORT = os.path.join(REPO, "scripts", "consensus_report.py")

from bcg_tpu.sweep import (  # noqa: E402
    JOB_DEFAULTS, PRESETS, SweepController, completed_job_ids, expand,
    game_end_jobs, job_id_for, load_spec, render_report, run_sweep,
)

DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string", "minLength": 1, "maxLength": 25},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string", "minLength": 1, "maxLength": 25},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ spec layer


class TestSpecExpansion:
    def test_expansion_is_deterministic(self):
        spec = {
            "axes": {
                "seed": [0, 1], "agents": [4, 6],
                "topology": ["ring", "fully_connected"],
            }
        }
        a = expand(spec)
        b = expand(spec)
        assert [j.job_id for j in a] == [j.job_id for j in b]
        assert len(a) == 8
        # Sorted-axis-name expansion order: agents varies slowest
        # (a < s < t alphabetically: agents, seed, topology).
        assert [j.params["agents"] for j in a] == [4] * 4 + [6] * 4

    def test_job_ids_are_content_hashes(self):
        # Same resolved params -> same id regardless of spec shape.
        via_axes = expand({"axes": {"seed": [3]}, "base": {"agents": 6}})[0]
        via_base = expand({"base": {"seed": 3, "agents": 6}, "axes": {}})[0]
        assert via_axes.job_id == via_base.job_id
        params = dict(JOB_DEFAULTS, seed=3, agents=6)
        assert via_axes.job_id == job_id_for(params)

    def test_unknown_axis_is_an_error(self):
        with pytest.raises(ValueError, match="unknown axis"):
            expand({"axes": {"agnets": [4]}})
        with pytest.raises(ValueError, match="unknown base"):
            expand({"base": {"topologyy": "ring"}, "axes": {}})

    def test_duplicate_job_is_an_error(self):
        with pytest.raises(ValueError, match="duplicate job"):
            expand({"axes": {"seed": [1, 1]}})

    def test_paper_grid_preset_is_acceptance_scale(self):
        jobs = expand(PRESETS["paper-grid"])
        assert len(jobs) >= 100
        assert len({j.job_id for j in jobs}) == len(jobs)
        agents = {j.params["agents"] for j in jobs}
        topos = {j.params["topology"] for j in jobs}
        assert len(agents) >= 2 and len(topos) >= 2  # mixed, per ROADMAP

    def test_to_config_maps_every_knob(self):
        job = expand({
            "base": {
                "agents": 6, "byzantine": 2, "topology": "ring",
                "seed": 9, "max_rounds": 3, "backend": "fake",
                "decide_tokens": 40, "vote_tokens": 20,
            },
            "axes": {},
        })[0]
        cfg = job.to_config()
        assert cfg.game.num_honest == 4 and cfg.game.num_byzantine == 2
        assert cfg.network.topology_type == "ring"
        assert cfg.game.seed == 9 and cfg.game.max_rounds == 3
        assert cfg.llm.max_tokens_decide == 40
        assert cfg.metrics.save_results is False

    def test_load_spec_preset_and_file(self, tmp_path):
        assert load_spec("smoke")["name"] == "smoke"
        p = tmp_path / "s.json"
        p.write_text(json.dumps({"axes": {"seed": [0]}}))
        assert load_spec(str(p))["axes"] == {"seed": [0]}
        with pytest.raises(ValueError, match="axes"):
            bad = tmp_path / "bad.json"
            bad.write_text("[]")
            load_spec(str(bad))


# ------------------------------------------------- tenant scheduling unit


class TestTenantScheduling:
    def _scheduler(self, **kw):
        from bcg_tpu.engine.fake import FakeEngine
        from bcg_tpu.serve.scheduler import Scheduler

        kw.setdefault("linger_ms", 0)
        kw.setdefault("max_queue_rows", 4096)
        kw.setdefault("deadline_ms", 0)
        return Scheduler(FakeEngine(seed=0, policy="consensus"), **kw)

    def _plug(self, sched):
        release = threading.Event()
        plugged = threading.Event()

        def hold():
            plugged.set()
            release.wait()

        t = threading.Thread(target=lambda: sched.run_exclusive(hold))
        t.start()
        assert plugged.wait(10)
        return release, t

    def _row(self, tag="x"):
        return ("sys", f"{tag} Your current value: 17. Decide.", DECISION)

    def _drain(self, sched):
        deadline = time.monotonic() + 10
        while sched.queue_depth_rows() > 0:
            assert time.monotonic() < deadline
            time.sleep(0.001)

    def test_quota_defers_with_retry_after(self):
        from bcg_tpu.serve.scheduler import AdmissionDeferred

        sched = self._scheduler()
        t = sched.register_tenant("job-a", quota_rows=4)
        release, plug = self._plug(sched)
        try:
            first = sched.submit(("json",), [self._row()] * 2, [0.0] * 2,
                                 [64] * 2, tenant="job-a")
            self._drain(sched)
            second = sched.submit(("json",), [self._row()] * 4, [0.0] * 4,
                                  [64] * 4, tenant="job-a")
            assert second.error is None  # exactly at quota: admitted
            over = sched.submit(("json",), [self._row()], [0.0], [64],
                                tenant="job-a")
            assert isinstance(over.error, AdmissionDeferred)
            assert over.error.retry_after_s > 0
        finally:
            release.set()
            plug.join(10)
        assert first.done.wait(30) and second.done.wait(30)
        sched.close()
        assert t.max_queued_rows <= 4  # quota exactness
        assert t.deferrals == 1
        snap = sched.snapshot()
        assert snap["deferred"] == 1
        assert snap["tenants"]["job-a"]["quota_rows"] == 4

    def test_weighted_fairness_orders_batch_selection(self):
        sched = self._scheduler(bucket_rows=4, strict_admission=False)
        sched.register_tenant("big", weight=1.0)
        sched.register_tenant("small", weight=1.0)
        release, plug = self._plug(sched)
        try:
            seed = sched.submit(("json",), [self._row("b")] * 4, [0.0] * 4,
                                [64] * 4, tenant="big")
            self._drain(sched)
            reqs = [sched.submit(("json",), [self._row("b")] * 4,
                                 [0.0] * 4, [64] * 4, tenant="big")
                    for _ in range(3)]
            small = sched.submit(("json",), [self._row("s")] * 4, [0.0] * 4,
                                 [64] * 4, tenant="small")
        finally:
            release.set()
            plug.join(10)
        for r in [seed, small] + reqs:
            assert r.done.wait(30)
        sched.close()
        # small's vtime (0) beat big's (4 after the seed batch): it
        # dispatched before at least two queued big requests.
        snap = sched.snapshot()
        assert snap["tenants"]["small"]["served_rows"] == 4
        assert snap["completed"] == 5

    def test_priority_class_beats_fairness(self):
        from bcg_tpu.serve.scheduler import Scheduler

        sched = self._scheduler(bucket_rows=4, strict_admission=False)
        sched.register_tenant("lowprio", priority=0)
        sched.register_tenant("highprio", priority=5)
        order = []
        release, plug = self._plug(sched)
        try:
            seed = sched.submit(("json",), [self._row("l")] * 4, [0.0] * 4,
                                [64] * 4, tenant="lowprio")
            self._drain(sched)
            lo = sched.submit(("json",), [self._row("l")] * 4, [0.0] * 4,
                              [64] * 4, tenant="lowprio")
            hi = sched.submit(("json",), [self._row("h")] * 4, [0.0] * 4,
                              [64] * 4, tenant="highprio")

            def track(req, name):
                req.done.wait(30)
                order.append(name)

            ts = [threading.Thread(target=track, args=(lo, "lo")),
                  threading.Thread(target=track, args=(hi, "hi"))]
            for t in ts:
                t.start()
        finally:
            release.set()
            plug.join(10)
        for t in ts:
            t.join(30)
        seed.done.wait(30)
        sched.close()
        # highprio submitted AFTER lowprio but dispatched first.
        assert order[0] == "hi", order

    def test_untenanted_requests_share_one_fair_account(self):
        """On a tenanted scheduler, untenanted (and unregistered-name)
        requests charge ONE shared anonymous account — they accrue
        virtual time like everyone else instead of keeping a permanent
        vtime of 0 that would outrank every tenant with history."""
        sched = self._scheduler()
        sched.register_tenant("job-x")
        out = sched.submit_and_wait(("json",), [self._row()] * 3,
                                    [0.0] * 3, [64] * 3)
        assert len(out) == 3
        assert sched._anon_tenant.served_rows == 3
        # Unregistered tenant names ride the same shared account.
        sched.submit_and_wait(("json",), [self._row()], [0.0], [64],
                              tenant="never-registered")
        assert sched._anon_tenant.served_rows == 4
        snap = sched.snapshot()
        assert "(untenanted)" not in snap["tenants"]
        sched.close()

    def test_default_tenant_behavior_unchanged(self):
        """No registered tenants: snapshot carries tenants=None and
        dispatch is the pre-tenancy FIFO (submit order preserved)."""
        sched = self._scheduler()
        out = sched.submit_and_wait(("json",), [self._row()], [0.0], [64])
        assert isinstance(out[0], dict) and "error" not in out[0]
        snap = sched.snapshot()
        assert snap["tenants"] is None
        assert snap["deferred"] == 0
        sched.close()

    def test_serving_engine_retries_deferrals_transparently(self):
        """A ServingEngine tenant over quota backs off and completes —
        the game thread sees latency, never AdmissionDeferred."""
        from bcg_tpu.serve.engine import ServingEngine

        sched = self._scheduler()
        sched.register_tenant("jobq", quota_rows=2)
        proxy = ServingEngine(sched._engine, scheduler=sched, tenant="jobq")
        outs = []

        def call():
            outs.append(proxy.batch_generate_json(
                [self._row()] * 2, temperature=0.0, max_tokens=64
            ))

        threads = [threading.Thread(target=call) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        sched.close()
        assert len(outs) == 4
        assert all("error" not in row for out in outs for row in out)

    def test_retry_after_derivation_monotone(self):
        from bcg_tpu.serve.scheduler import derive_retry_after_ms

        grid = [derive_retry_after_ms(20.0, 10.0, slo_ms=50,
                                      headroom_p50_ms=float(h))
                for h in range(0, 51, 5)]
        assert all(a >= b for a, b in zip(grid, grid[1:]))
        assert grid[0] == pytest.approx(4.0 * grid[-1])
        # No SLO: plain base, floored at 1 ms.
        assert derive_retry_after_ms(0.0, 0.0) == 1.0
        assert derive_retry_after_ms(25.0, 10.0) == 25.0


# --------------------------------------------------- single-process sweep


class TestSingleProcessSweep:
    def test_smoke_sweep_runs_and_resumes(self, tmp_path):
        out = str(tmp_path / "sweep")
        s = run_sweep("smoke", out, linger_ms=0)
        assert s["jobs"] == 4 and s["completed"] == 4 and s["failed"] == 0
        # Manifest: fleet-identity-stamped header + job lifecycle.
        man = [json.loads(l) for l in
               open(os.path.join(out, "sweep-manifest-r0.jsonl"))]
        header = next(r for r in man if r["event"] == "manifest")
        for key in ("run_id", "host", "process_index", "flags", "sweep"):
            assert key in header, sorted(header)
        ends = [r for r in man if r["event"] == "job_end"]
        assert len(ends) == 4
        assert all(r["status"] == "completed" for r in ends)
        # Event stream: every game carries its job id on start/end.
        events = [json.loads(l) for p in
                  glob.glob(os.path.join(out, "events-*.jsonl"))
                  for l in open(p)]
        game_ends = [r for r in events if r.get("event") == "game_end"]
        assert len(game_ends) == 4
        assert {r["job"] for r in game_ends} == set(completed_job_ids(out))
        # Resume: a second run of the same spec skips everything.
        s2 = run_sweep("smoke", out, linger_ms=0)
        assert s2["skipped"] == 4 and s2["completed"] == 0
        game_ends2 = [
            r for p in glob.glob(os.path.join(out, "events-*.jsonl"))
            for l in open(p)
            for r in [json.loads(l)] if r.get("event") == "game_end"
        ]
        assert len(game_ends2) == 4  # zero duplicate game_end
        report = render_report(out)
        assert "4 jobs ended" in report
        assert "100.0%" in report

    def test_game_end_recovery_closes_the_manifest_gap(self, tmp_path):
        """A game_end on disk without its manifest job_end (the kill
        window) must mark the job completed on resume, not rerun it."""
        out = str(tmp_path / "sweep")
        run_sweep("smoke", out, linger_ms=0)
        man_path = os.path.join(out, "sweep-manifest-r0.jsonl")
        records = [json.loads(l) for l in open(man_path)]
        dropped = next(r for r in records if r["event"] == "job_end")
        with open(man_path, "w") as f:
            for r in records:
                if not (r["event"] == "job_end"
                        and r["job"] == dropped["job"]):
                    f.write(json.dumps(r) + "\n")
        assert dropped["job"] not in completed_job_ids(out)
        assert dropped["job"] in game_end_jobs(out)
        s2 = run_sweep("smoke", out, linger_ms=0)
        assert s2["skipped"] == 4 and s2["completed"] == 0
        recovered = completed_job_ids(out)[dropped["job"]]
        assert recovered.get("recovered") is True

    def test_mid_game_round_checkpoint_resume(self, tmp_path, monkeypatch):
        """A job interrupted mid-game resumes from its newest round
        checkpoint: the resumed game continues (not restarts) and the
        outcome matches an uninterrupted oracle run."""
        monkeypatch.setenv("BCG_TPU_SERVE_CHECKPOINT_EVERY", "1")
        # The stubborn policy never converges, so the game reliably
        # outlives the 2-round interruption point (max_rounds 6).
        spec = {"name": "ckpt", "base": {"agents": 4, "byzantine": 1,
                                         "max_rounds": 6, "seed": 0,
                                         "fake_policy": "stubborn"},
                "axes": {}}
        oracle_dir = str(tmp_path / "oracle")
        o = run_sweep(spec, oracle_dir, linger_ms=0)
        oracle = o["results"][0]

        out = str(tmp_path / "interrupted")
        ctl = SweepController(spec, out, linger_ms=0)
        job = ctl.jobs[0]
        # Simulate the kill: run the game 2 rounds, checkpoint, abandon.
        os.makedirs(out, exist_ok=True)
        cfg = job.to_config()
        import dataclasses

        from bcg_tpu.runtime.orchestrator import BCGSimulation

        job_dir = os.path.join(out, "jobs", job.job_id)
        cfg = dataclasses.replace(cfg, metrics=dataclasses.replace(
            cfg.metrics, results_dir=job_dir))
        sim = BCGSimulation(config=cfg, sweep_job_id=job.job_id)
        sim.run_round()
        sim.run_round()
        assert not sim.game.game_over
        sim.close()
        assert glob.glob(os.path.join(job_dir, "checkpoints", "*.json"))
        # Resume through the controller: must pick the checkpoint up.
        s = run_sweep(spec, out, linger_ms=0)
        assert s["completed"] == 1
        result = s["results"][0]
        assert result.get("resumed_from_round", 0) >= 3
        assert result["converged"] == oracle["converged"]
        assert result["rounds"] == oracle["rounds"]

    def test_cli_run_expand_report(self, tmp_path, capsys):
        from bcg_tpu.sweep.__main__ import main

        assert main(["list"]) == 0
        assert "paper-grid" in capsys.readouterr().out
        assert main(["expand", "smoke"]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 4 and all(
            json.loads(l)["job"].startswith("j") for l in lines
        )
        out = str(tmp_path / "cli")
        assert main(["run", "smoke", "--out", out]) == 0
        text = capsys.readouterr().out
        assert "sweep smoke" in text and "sweep report" in text
        assert main(["report", out]) == 0
        assert "jobs ended" in capsys.readouterr().out

    def test_consensus_report_merges_sweep_events(self, tmp_path, capsys):
        """The sweep dir's event files flow through the existing
        manifest-grouped merge; duplicate-job detection stays silent on
        a clean sweep and fires on a doctored duplicate."""
        out = str(tmp_path / "sweep")
        run_sweep("smoke", out, linger_ms=0)
        cr = _load(REPORT, "consensus_report_sweep")
        paths = sorted(glob.glob(os.path.join(out, "events-*.jsonl")))
        problems = []
        games = []
        for p in paths:
            games.extend(cr.parse_file(p, problems))
        assert sum(1 for g in games if g.ended) == 4
        assert cr.duplicate_job_problems(games) == []
        # Doctor a duplicate: the same file parsed twice = every job
        # ended twice.
        twice = []
        for p in paths + paths:
            twice.extend(cr.parse_file(p, []))
        dups = cr.duplicate_job_problems(twice)
        assert len(dups) == 4 and "ran to completion twice" in dups[0]


# ------------------------------------------------------- perf_gate sweep


@pytest.fixture(scope="module")
def sweep_gate():
    mod = _load(GATE, "perf_gate_sweep")
    return mod, mod.run_sweep_scenario()


class TestSweepGate:
    def test_gate_green_at_head(self, sweep_gate):
        mod, measured = sweep_gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(measured, mod.load_baseline(), ("sweep",))
        assert findings == [], "\n".join(findings)

    def test_scenario_measures_the_advertised_metrics(self, sweep_gate):
        _, measured = sweep_gate
        for name in (
            "sweep.starvation_ratio", "sweep.fairness_batches",
            "sweep.quota_overrun_rows", "sweep.quota_deferrals",
            "sweep.retry_after_live_ms", "sweep.retry_after_monotonicity",
            "sweep.error_rows",
        ):
            assert name in measured, sorted(measured)
        assert measured["sweep.quota_overrun_rows"] == 0.0
        assert measured["sweep.retry_after_monotonicity"] == 1.0

    def test_removing_entry_resurfaces_unbaselined_failure(self, sweep_gate):
        mod, measured = sweep_gate
        baseline = mod.load_baseline()
        del baseline["metrics"]["sweep.starvation_ratio"]
        findings = mod.check_metrics(measured, baseline)
        assert any("sweep.starvation_ratio" in f and "no entry" in f
                   for f in findings), findings

    def test_fairness_off_injection_names_the_metric(self, sweep_gate):
        mod, _ = sweep_gate
        measured = mod.run_sweep_scenario("fairness-off")
        findings = mod.check_metrics(measured, mod.load_baseline())
        assert any("sweep.starvation_ratio" in f for f in findings), findings


# ----------------------------------------------- 2-process cluster sweep


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cluster_env(out_dir, run_id, linger_ms):
    return dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO,
        BCG_TPU_RUN_ID=run_id,
        BCG_TPU_SERVE_CHECKPOINT_EVERY="1",
        BCG_TPU_SERVE_LINGER_MS=str(linger_ms),
    )


def _launch_cluster(out_dir, spec_path, run_id, linger_ms):
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, coord, "2", str(pid), out_dir,
             spec_path],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_cluster_env(out_dir, run_id, linger_ms), cwd=REPO,
        ))
    return procs


def _outcomes_by_job(event_paths):
    """job -> (converged, rounds_to_consensus) over ENDED games, via
    the real consensus_report parser (the merge consumers use)."""
    cr = _load(REPORT, "consensus_report_cluster")
    games = []
    problems = []
    for p in event_paths:
        games.extend(cr.parse_file(p, problems))
    dups = cr.duplicate_job_problems(games)
    assert dups == [], dups
    return {
        g.job: (g.converged, g.rounds_to_consensus)
        for g in games if g.ended and g.job
    }, games


CLUSTER_SPEC = {
    "name": "cluster-grid",
    "base": {"max_rounds": 6, "byzantine": 0},
    "axes": {
        "agents": [4, 5],
        "fake_policy": ["consensus", "stubborn"],
        "seed": [0, 1, 2],
    },
}


class TestTwoProcessSweep:
    def test_kill_resume_matches_single_process_oracle(self, tmp_path):
        """The acceptance run: 12 jobs partitioned over a REAL
        2-process JAX CPU cluster, SIGKILLed mid-sweep, resumed with a
        second launch into the same dir — the completed job set is
        identical to the spec, no job ran twice (zero duplicate
        game_end), and per-job outcomes equal a single-process oracle
        run."""
        out = str(tmp_path / "cluster")
        os.makedirs(out)
        spec_path = str(tmp_path / "spec.json")
        with open(spec_path, "w") as f:
            json.dump(CLUSTER_SPEC, f)

        # Phase 1: launch with a slowed scheduler (40 ms linger per
        # dispatch) and SIGKILL both ranks once >= 2 jobs completed.
        procs = _launch_cluster(out, spec_path, "sweeptestrun1", 40)
        deadline = time.monotonic() + 120
        try:
            while time.monotonic() < deadline:
                if len(completed_job_ids(out)) >= 2:
                    break
                if all(p.poll() is not None for p in procs):
                    break  # sweep finished before the kill landed
                time.sleep(0.002)
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGKILL)
        finally:
            for p in procs:
                try:
                    p.communicate(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
        after_kill = set(completed_job_ids(out))

        # Phase 2: resume into the same dir (full speed).
        procs = _launch_cluster(out, spec_path, "sweeptestrun2", 0)
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, text) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {pid}:\n{text[-3000:]}"
        summaries = [
            json.loads(line.split("SWEEP-OK ", 1)[1])
            for text in outs
            for line in text.splitlines() if line.startswith("SWEEP-OK")
        ]
        assert len(summaries) == 2
        assert all(s["failed"] == 0 for s in summaries)
        assert {s["rank"] for s in summaries} == {0, 1}
        # Strided partition: 6 jobs per rank, every job accounted.
        assert all(s["partition"] == 6 for s in summaries)
        assert all(
            s["completed"] + s["skipped"] == s["partition"]
            for s in summaries
        )

        jobs = {j.job_id for j in expand(CLUSTER_SPEC)}
        done = completed_job_ids(out)
        assert set(done) == jobs  # identical job set, nothing missing
        assert after_kill <= set(done)

        # Oracle: the same spec, one process, fresh dir.
        oracle_dir = str(tmp_path / "oracle")
        o = run_sweep(CLUSTER_SPEC, oracle_dir, linger_ms=0)
        assert o["completed"] == 12 and o["failed"] == 0
        oracle_map, _ = _outcomes_by_job(
            sorted(glob.glob(os.path.join(oracle_dir, "events-*.jsonl")))
        )
        merged_map, games = _outcomes_by_job(
            sorted(glob.glob(os.path.join(out, "events-*.jsonl")))
        )
        assert merged_map == oracle_map  # merged report == oracle
        assert set(merged_map) == jobs
        # The deterministic policies split exactly: consensus games
        # converge, stubborn games never do.
        assert sum(1 for c, _ in merged_map.values() if c) == 6

    def test_cooperative_single_job_records_once(self, tmp_path):
        """A single-job sweep on the 2-process group runs
        cooperatively: both ranks play the SAME game and only rank 0
        records it — the merged report counts ONE game.  (The
        spmd_exchange arm of cooperative mode — exchange_values_global
        over the dp-across-hosts mesh — needs a backend with
        cross-process collectives; this CPU backend refuses
        multiprocess computations, same reason test_multihost.py is
        hardware-gated.  Its semantics are pinned single-process in
        test_parallel.py.)"""
        out = str(tmp_path / "coop")
        os.makedirs(out)
        spec = {
            "name": "coop",
            "base": {"agents": 4, "byzantine": 0, "max_rounds": 3,
                     "seed": 1},
            "axes": {},
        }
        spec_path = str(tmp_path / "coop.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        procs = _launch_cluster(out, spec_path, "sweepcooprun", 0)
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, text) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {pid}:\n{text[-3000:]}"
        summaries = [
            json.loads(line.split("SWEEP-OK ", 1)[1])
            for text in outs
            for line in text.splitlines() if line.startswith("SWEEP-OK")
        ]
        assert all(s["cooperative"] for s in summaries)
        assert all(s["completed"] == 1 for s in summaries)
        # One manifest (rank 0's), one game in the merged events.
        assert glob.glob(os.path.join(out, "sweep-manifest-r*.jsonl")) == [
            os.path.join(out, "sweep-manifest-r0.jsonl")
        ]
        merged_map, games = _outcomes_by_job(
            sorted(glob.glob(os.path.join(out, "events-*.jsonl")))
        )
        assert len(merged_map) == 1
        # Both ranks computed the identical deterministic outcome.
        (outcome,) = merged_map.values()
        assert outcome[0] is True  # 4 honest consensus-policy agents


# ----------------------------------------------------- acceptance (slow)


@pytest.mark.slow
def test_hundred_game_sweep_single_command(tmp_path):
    """ISSUE acceptance: one command runs the >= 100-job paper-grid
    (mixed agent counts / topologies / seeds) on the virtual-device CPU
    mesh to a single aggregated report."""
    from bcg_tpu.sweep.__main__ import main

    out = str(tmp_path / "grid")
    assert main(["run", "paper-grid", "--out", out, "--json"]) == 0
    done = completed_job_ids(out)
    assert len(done) == len(expand(PRESETS["paper-grid"])) >= 100
    report = render_report(out)
    assert "jobs ended" in report
    events = sorted(glob.glob(os.path.join(out, "events-*.jsonl")))
    game_ends = [
        r for p in events for l in open(p)
        for r in [json.loads(l)] if r.get("event") == "game_end"
    ]
    assert len(game_ends) == len(done)  # zero duplicates at scale
