"""Cross-module fixture, callee side: this module has NO jit of its
own.  ``scale`` only traces because entry.py jits a caller — exactly
the case the per-module jit-region fixpoint could not see and the
whole-program lift (interproc.propagate_jit_regions) exists to catch.
The np.asarray here must surface as BCG-HOST-SYNC in THIS file."""

import numpy as np


def scale(x, factor):
    host = np.asarray(x)  # host materialization inside a traced helper
    return host * factor


def offset(x, bias):
    # Not reachable from any jit region: must stay quiet.
    return np.asarray(x) + bias
