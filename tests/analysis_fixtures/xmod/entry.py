"""Cross-module fixture, caller side: the jit region lives here, the
violation lives in helper.py.  A per-module pass sees a clean file in
both places; the whole-program pass marks helper.scale as traced and
the host-sync rule fires at the np.asarray it contains."""

import jax

from tests.analysis_fixtures.xmod.helper import scale


@jax.jit
def fused_scale(x):
    return scale(x, 2.0)
