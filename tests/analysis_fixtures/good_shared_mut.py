"""Clean twin of bad_shared_mut.py: the same two thread roots mutate
the attribute, but every mutation site holds the one shared lock — a
common guard across all writers silences the rule."""

import threading


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        threading.Thread(
            target=self._drain, name="fx-drain", daemon=True
        ).start()
        threading.Thread(
            target=self._refill, name="fx-refill", daemon=True
        ).start()

    def _drain(self):
        with self._lock:
            self.total -= 1

    def _refill(self):
        with self._lock:
            self.total += 1
