"""Seeded BCG-OBS-NAME violations: metric names off the taxonomy
(3 findings)."""
from bcg_tpu.obs import counters as obs_counters


def record(entry):
    obs_counters.inc("Serve.Requests")            # finding 1: uppercase
    obs_counters.set_gauge("requests", 1)         # finding 2: one segment
    obs_counters.inc(f"{entry}.retrace")          # finding 3: no static
    #                                               subsystem prefix
