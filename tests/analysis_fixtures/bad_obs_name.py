"""Seeded BCG-OBS-NAME violations: metric names off the taxonomy
(6 findings)."""
from bcg_tpu.obs import counters as obs_counters


def record(entry):
    obs_counters.inc("Serve.Requests")            # finding 1: uppercase
    obs_counters.set_gauge("requests", 1)         # finding 2: one segment
    obs_counters.inc(f"{entry}.retrace")          # finding 3: no static
    #                                               subsystem prefix
    obs_counters.histogram("RoundMs", (1, 5))     # finding 4: histogram
    #                                               names are checked too
    obs_counters.inc("warp.requests")             # finding 5: unknown
    #                                               subsystem (namespace fork)
    obs_counters.inc("alerts.fired")              # finding 6: the registered
    #                                               subsystem is 'alert',
    #                                               singular — 'alerts' forks it
