"""GOOD: None-default with in-body init."""


def append_to(x, acc=None):
    if acc is None:
        acc = []
    acc.append(x)
    return acc
