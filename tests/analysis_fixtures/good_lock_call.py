"""Clean idiom for BCG-LOCK-CALL: queue state is copied under the lock,
the engine/device call runs after it is released."""

import threading


class GoodProxy:
    def __init__(self, engine):
        self._engine = engine
        self._cond = threading.Condition()
        self._pending = []

    def submit(self, prompts):
        with self._cond:
            self._pending.append(prompts)
            batch = [row for call in self._pending for row in call]
            self._pending = []
        return self._engine.batch_generate_json(batch)

    def upload(self, jax, table):
        with self._cond:
            pending = list(self._pending)
        device_table = jax.device_put(pending or table)
        with self._cond:
            self._table = device_table
        return device_table

    def acquire_via_engine(self):
        # The lock-ACQUIRING call runs before the lock is held — an
        # engine-owned lock accessor must not be flagged.
        with self._engine.lock():
            self._pending.clear()
