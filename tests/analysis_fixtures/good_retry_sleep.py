"""Clean idioms BCG-RETRY-SLEEP must stay quiet on: derived delays in
loops, constant sleeps outside loops, and loop-adjacent closures."""

import time


def backoff_retry(fn):
    delay = 0.05
    for _ in range(5):
        try:
            return fn()
        except RuntimeError:
            time.sleep(delay)  # derived: grows per attempt
            delay = min(delay * 2, 1.0)
    raise RuntimeError("gave up")


def jittered_poll(check, rng):
    while not check():
        time.sleep(0.05 * (1.0 + rng.random()))  # derived: jittered


def honor_retry_after(fn):
    while True:
        try:
            return fn()
        except TimeoutError as e:
            time.sleep(getattr(e, "retry_after_s", 0.1))  # server-supplied


def one_shot_settle():
    time.sleep(0.2)  # constant, but not in a loop


def build_wait_closures():
    waiters = []
    for _ in range(3):
        # The sleep is inside a nested function body, not the loop's
        # execution path — defining it per iteration is not polling.
        def waiter():
            time.sleep(0.1)

        waiters.append(waiter)
    return waiters
