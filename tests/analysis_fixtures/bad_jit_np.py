"""BAD: numpy ops inside a jitted function."""
import jax
import numpy as np


@jax.jit
def f(x):
    scale = np.sqrt(2.0)           # BCG-JIT-NP
    return x * np.maximum(scale, 1.0)  # BCG-JIT-NP
