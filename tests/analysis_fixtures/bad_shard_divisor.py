"""BAD: per-device accounting divided by raw device counts."""
import jax


def kv_bytes_per_device(total_bytes, mesh):
    return total_bytes / mesh.size            # BCG-SHARD-DIVISOR


def tree_bytes_per_device(total_bytes):
    per = total_bytes // jax.device_count()   # BCG-SHARD-DIVISOR
    return per + total_bytes / len(jax.devices())  # BCG-SHARD-DIVISOR
