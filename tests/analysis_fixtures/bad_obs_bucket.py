"""Seeded BCG-OBS-BUCKET violations: hand-rolled bucket counters —
bounds encoded in counter/gauge names instead of a first-class
Histogram (3 findings)."""
from bcg_tpu.obs import counters as obs_counters

_BUCKETS_MS = (1, 5, 10)


def record(ms):
    for bound in _BUCKETS_MS:                     # finding 1: le_ label
        if ms <= bound:
            obs_counters.inc(f"serve.linger_le_{bound}ms")
            return
    obs_counters.inc("serve.linger.bucket.overflow")   # finding 2: bucket
    obs_counters.set_gauge("serve.wait<=10ms", 1)      # finding 3: <=
