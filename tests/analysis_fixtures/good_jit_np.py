"""GOOD: jnp inside jit; numpy only at module/host scope."""
import jax
import jax.numpy as jnp
import numpy as np

SCALE = np.sqrt(2.0)  # host-side constant: fine


@jax.jit
def f(x):
    return x * jnp.maximum(SCALE, 1.0)
