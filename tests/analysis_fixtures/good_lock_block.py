"""Clean twin of bad_lock_block.py: state is copied under the lock and
every blocking operation (file I/O, sleep) happens after release — the
serve/scheduler.py dispatch shape."""

import threading
import time


class Flusher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []
        threading.Thread(
            target=self._loop, name="fx-flush", daemon=True
        ).start()

    def _loop(self):
        with self._lock:
            batch = list(self._buf)
            self._buf.clear()
        self._write_all(batch)
        time.sleep(0.5)

    def _write_all(self, batch):
        with open("/tmp/fx_out", "w") as fh:
            fh.write("".join(batch))
