"""BAD: mutable default arguments."""


def append_to(x, acc=[]):          # BCG-MUT-DEFAULT
    acc.append(x)
    return acc


def tally(key, counts={}):         # BCG-MUT-DEFAULT
    counts[key] = counts.get(key, 0) + 1
    return counts
