"""Clean twin of bad_lock_order.py: both thread roots acquire the two
locks in the SAME order (cond, then device lock), so the acquisition
graph has one edge and no cycle — a consistent global lock order is the
fix for an inversion."""

import threading


class Pipeline:
    def __init__(self):
        self._cond = threading.Condition()
        self._device_lock = threading.Lock()
        self._jobs = []
        threading.Thread(
            target=self._dispatch, name="fx-dispatch", daemon=True
        ).start()
        threading.Thread(
            target=self._supervise, name="fx-watchdog", daemon=True
        ).start()

    def _dispatch(self):
        with self._cond:
            with self._device_lock:
                self._jobs.pop()

    def _supervise(self):
        # same order as _dispatch: no inversion
        with self._cond:
            with self._device_lock:
                self._jobs.append(None)
