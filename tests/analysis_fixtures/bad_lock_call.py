"""Seeded BCG-LOCK-CALL violations: engine/device calls while holding a
scheduler/collective lock (3 findings: with-lock engine call, with-cond
device upload, engine call inside a *_locked helper)."""

import threading


class BadProxy:
    def __init__(self, engine):
        self._engine = engine
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._pending = []

    def submit(self, prompts):
        with self._lock:
            return self._engine.batch_generate_json(prompts)  # finding

    def upload(self, jax, table):
        with self._cond:
            return jax.device_put(table)  # finding

    def _dispatch_all_locked(self):
        batch = list(self._pending)
        self._pending = []
        return self._engine.batch_generate(batch)  # finding
