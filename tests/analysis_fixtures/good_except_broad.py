"""GOOD: narrow types, re-raise, logging, or exception use."""
import logging

log = logging.getLogger(__name__)


def f():
    try:
        risky()
    except ValueError:             # narrow: fine
        pass


def g():
    try:
        risky()
    except Exception as exc:       # reported: fine
        log.warning("risky failed: %s", exc)
        raise
