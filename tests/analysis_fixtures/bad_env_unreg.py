"""BAD: accessor called with a name the registry does not know."""
from bcg_tpu.config import env_flag
from bcg_tpu.runtime import envflags

A = envflags.get_bool("BCG_TPU_TIMNIG")   # BCG-ENV-UNREG (typo)
B = env_flag("BCG_TPU_NO_SUCH_FLAG")      # BCG-ENV-UNREG
