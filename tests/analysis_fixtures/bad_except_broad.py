"""BAD: broad excepts that swallow silently."""


def f():
    try:
        risky()
    except Exception:              # BCG-EXCEPT-BROAD
        pass


def g():
    try:
        risky()
    except:                        # BCG-EXCEPT-BROAD (bare)
        return None
