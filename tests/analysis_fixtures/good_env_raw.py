"""GOOD: registry accessors; raw reads only of EXTERNAL names."""
import os

from bcg_tpu.runtime.envflags import get_bool, get_int, get_str, is_set

TIMING = get_bool("BCG_TPU_TIMING")
ROUNDS = get_int("BENCH_ROUNDS")
MODEL = get_str("BENCH_MODEL")
XLA_FLAGS = os.environ.get("XLA_FLAGS", "")  # external env: allowed


def overridden():
    return is_set("BENCH_QUANTIZATION")
