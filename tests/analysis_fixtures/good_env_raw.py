"""GOOD: registry accessors; raw reads only of EXTERNAL names."""
import os

from bcg_tpu.runtime.envflags import get_bool, get_int, get_str, is_set

TIMING = get_bool("BCG_TPU_TIMING")
ROUNDS = get_int("BENCH_ROUNDS")
MODEL = get_str("BENCH_MODEL")
XLA_FLAGS = os.environ.get("XLA_FLAGS", "")  # external env: allowed
os.environ.setdefault("JAX_PLATFORMS", "cpu")  # external env: allowed


def overridden():
    return is_set("BENCH_QUANTIZATION")


def scenario_override():
    # Plain WRITES of registered names stay legal: harnesses (bench,
    # perf_gate scenarios) configure the flags they then read through
    # the registry.
    os.environ["BCG_TPU_SPEC"] = "1"
    return get_bool("BCG_TPU_SPEC")
