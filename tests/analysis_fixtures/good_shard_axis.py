"""GOOD: only axes parallel/mesh.py defines (dp/tp/sp)."""
from jax.sharding import NamedSharding, PartitionSpec as P

SPEC = P(None, "tp")


def shard(mesh, arr):
    return NamedSharding(mesh, P("dp", None, "sp"))
