"""Seeded violations for BCG-RETRY-SLEEP: constant-interval sleeps
inside retry/poll loops (3 findings)."""

import time
from time import sleep


def poll_until_ready(check):
    while not check():
        time.sleep(0.5)  # finding: fixed-cadence poll


def retry_flaky(fn):
    for _ in range(3):
        try:
            return fn()
        except RuntimeError:
            sleep(1)  # finding: constant retry interval (bare import)
    raise RuntimeError("gave up")


def nested_in_branch(check):
    while True:
        if check():
            return
        time.sleep(0.01)  # finding: loop-enclosed even through the if
