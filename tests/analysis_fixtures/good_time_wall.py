"""Clean idioms BCG-TIME-WALL must not flag: monotonic durations and
bare wall-clock timestamps (no arithmetic at the call site)."""
import time


def stamp_result(result):
    # Bare timestamp — stored, not subtracted: wall clock is CORRECT here.
    result["recorded_at"] = time.time()
    return result


def elapsed_since(t0):
    return time.perf_counter() - t0


def poll_until_done(check):
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if check():
            return True
    return False
