"""Clean distribution idioms BCG-OBS-BUCKET must not flag."""
from bcg_tpu.obs import counters as obs_counters

_hist = obs_counters.histogram("serve.queue_wait_ms", (1, 5, 10))


def record(ms, name):
    _hist.observe(ms)                                  # first-class histogram
    obs_counters.observe("serve.queue_wait_ms", ms)    # module-level observe
    obs_counters.inc("serve.requests")                 # plain counter
    obs_counters.value("serve.queue_wait_ms.bucket.le_5")  # flat READ: legal
    obs_counters.inc(name)                             # variable: trusted
