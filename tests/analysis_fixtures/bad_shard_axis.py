"""BAD: PartitionSpec axis names the mesh does not define."""
from jax.sharding import NamedSharding, PartitionSpec as P

SPEC = P(None, "model")            # BCG-SHARD-AXIS ("model" not a mesh axis)


def shard(mesh, arr):
    return NamedSharding(mesh, P("data", None))  # BCG-SHARD-AXIS
