"""GOOD: branches on static args, shapes, and None-checks only."""
import jax
import jax.numpy as jnp
from functools import partial


@partial(jax.jit, static_argnames=("mode",))
def f(x, mode, bias=None):
    if mode == "scale":            # static arg: fine
        x = x * 2
    if bias is not None:           # optional-arg idiom: fine
        x = x + bias
    if x.shape[0] > 4:             # shape metadata: fine
        x = x[:4]
    return jnp.where(x > 0, x, 0)  # traced select: the right tool
