"""BAD: python control flow on a traced parameter."""
import jax


@jax.jit
def f(x, threshold):
    if threshold > 0:              # BCG-JIT-BRANCH (traced param)
        return x * threshold
    return x


def g(x, n):
    while n > 0:                   # BCG-JIT-BRANCH via jit call-site below
        x = x + 1
        n = n - 1
    return x


g_jit = jax.jit(g)
