"""BAD: raw environment reads of registered flag names."""
import os

TIMING = os.environ.get("BCG_TPU_TIMING", "") not in ("", "0")  # BCG-ENV-RAW
VERBOSE = os.getenv("VERBOSE") == "1"                           # BCG-ENV-RAW
MODEL = os.environ["BENCH_MODEL"]                               # BCG-ENV-RAW


def overridden():
    return "BENCH_QUANTIZATION" in os.environ                   # BCG-ENV-RAW


def sticky_default():
    return os.environ.setdefault("BCG_TPU_SPEC", "1")           # BCG-ENV-RAW
