"""GOOD: accessors with registered names only."""
from bcg_tpu.config import env_flag
from bcg_tpu.runtime import envflags

A = envflags.get_bool("BCG_TPU_TIMING")
B = env_flag("BCG_TPU_FINE_SUFFIX")
