"""Seeded BCG-SHARED-MUT violation: one attribute mutated from two
distinct thread roots with no lock held at either site.  The lock
exists on the object — it just isn't used — so the finding is about the
unguarded mutation sites, not a missing lock object.  One violation
exactly (the rule reports per attribute, not per site)."""

import threading


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        threading.Thread(
            target=self._drain, name="fx-drain", daemon=True
        ).start()
        threading.Thread(
            target=self._refill, name="fx-refill", daemon=True
        ).start()

    def _drain(self):
        self.total -= 1  # unguarded, thread root 1

    def _refill(self):
        self.total += 1  # unguarded, thread root 2
