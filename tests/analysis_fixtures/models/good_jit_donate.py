"""GOOD: donation declared; key-only jits need none."""
import jax


def _quantize(w):
    return (w * 127).astype("int8")


def _init(key):
    return jax.random.normal(key, (8, 8))


def make(sharding):
    consuming = jax.jit(_quantize, out_shardings=sharding, donate_argnums=(0,))
    fresh = jax.jit(_init, out_shardings=sharding)  # key arg: nothing to donate
    return consuming, fresh
