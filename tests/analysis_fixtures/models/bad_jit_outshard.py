"""BAD (param-materializing module scope): jit without out_shardings."""
import jax
from functools import partial


def _init(key, shape):
    return jax.random.normal(key, shape)


init_fn = jax.jit(_init)           # BCG-JIT-OUTSHARD (+ no donate is fine: key-only)


@partial(jax.jit, static_argnums=1)   # BCG-JIT-OUTSHARD
def materialize(key, shape):
    return jax.random.normal(key, shape)
