"""GOOD: materializing jits pin out_shardings (and donate sources)."""
import jax


def _quantize(w):
    return (w * 127).astype("int8")


def make(sharding):
    return jax.jit(_quantize, out_shardings=sharding, donate_argnums=(0,))
