"""BAD: sharded-output jit consumes an array arg without donating it."""
import jax


def _quantize(w):
    return (w * 127).astype("int8")


def make(sharding):
    return jax.jit(_quantize, out_shardings=sharding)  # BCG-JIT-DONATE
