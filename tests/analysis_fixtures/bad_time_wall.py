"""Seeded BCG-TIME-WALL violations: wall-clock durations (3 findings)."""
import time


def elapsed_since(t0):
    return time.time() - t0  # finding 1: duration subtraction


def poll_until_done(check):
    deadline = time.time() + 5.0  # finding 2: deadline accumulation
    while time.time() < deadline:  # finding 3: deadline comparison
        if check():
            return True
    return False
