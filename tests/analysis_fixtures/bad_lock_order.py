"""Seeded BCG-LOCK-ORDER violation: the PR-15 device-lock-swap shape.

The dispatch thread nests the device lock under the queue condition;
the watchdog takes the device lock first and then wants the condition —
a two-lock inversion across two thread roots, i.e. the deadlock the
real scheduler avoids by REPLACING the device lock object instead of
ever nesting it under ``_cond``.  Exactly one cycle is seeded.
"""

import threading


class Pipeline:
    def __init__(self):
        self._cond = threading.Condition()
        self._device_lock = threading.Lock()
        self._jobs = []
        threading.Thread(
            target=self._dispatch, name="fx-dispatch", daemon=True
        ).start()
        threading.Thread(
            target=self._supervise, name="fx-watchdog", daemon=True
        ).start()

    def _dispatch(self):
        # queue cond -> device lock
        with self._cond:
            with self._device_lock:
                self._jobs.pop()

    def _supervise(self):
        # device lock -> queue cond: the inversion
        with self._device_lock:
            with self._cond:
                self._jobs.append(None)
