"""Seeded BCG-LOCK-BLOCK violations: blocking work performed while a
lock is held — directly (sleep, file I/O) and through a call chain the
interprocedural pass resolves.  Three violations exactly."""

import threading
import time


class Flusher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []
        threading.Thread(
            target=self._loop, name="fx-flush", daemon=True
        ).start()

    def _loop(self):
        with self._lock:
            time.sleep(0.5)  # 1: sleep under the lock
            with open("/tmp/fx_out", "w") as fh:  # 2: file I/O under it
                fh.write("x")
            self._write_all()  # 3: transitive file I/O under it

    def _write_all(self):
        with open("/tmp/fx_out2", "w") as fh:
            fh.write("".join(self._buf))
