"""GOOD: host syncs only in host-side orchestration code."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decode_step(x):
    return x * jnp.sum(x)


def orchestrate(x):
    out = decode_step(jnp.asarray(x))
    out.block_until_ready()        # host side: fine
    return np.asarray(out).item()  # host side: fine
