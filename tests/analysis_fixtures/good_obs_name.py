"""Clean metric-name idioms BCG-OBS-NAME must not flag."""
from bcg_tpu.obs import counters as obs_counters


def record(entry, name, account):
    obs_counters.inc("serve.requests")                      # 2 segments
    obs_counters.inc("engine.spec.drafted", 3)              # 3 segments
    obs_counters.set_gauge("engine.hlo.decode_loop.fusions", 7)  # 4 segments
    obs_counters.inc(f"engine.retrace.{entry}")             # prefixed f-string
    obs_counters.set_gauge(f"hbm.{account}_bytes", 0)       # fragment chars ok
    obs_counters.value(name)                                # variable: trusted
    obs_counters.histogram("game.round_ms", (1, 5)).observe(2)  # histogram
    obs_counters.observe("game.round_ms", 3)                # module observe
    obs_counters.set_gauge("fleet.heartbeat_ms", 0)         # fleet subsystem
    obs_counters.inc("sweep.jobs.completed")                # sweep subsystem
    obs_counters.inc("chaos.injected")                      # chaos subsystem
    obs_counters.inc("alert.fired")                         # alert subsystem
    obs_counters.set_gauge("alert.firing.slo_burn", 1)      # per-rule gauge
