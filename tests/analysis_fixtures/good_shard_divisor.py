"""GOOD: divide by the product of ENGAGED mesh axes only."""


def kv_bytes_per_device(total_bytes, mesh, engaged_axes):
    engaged = 1
    for ax in engaged_axes:
        engaged *= mesh.shape[ax]
    return total_bytes / engaged
