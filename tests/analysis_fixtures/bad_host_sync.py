"""BAD: host-sync calls inside jitted/traced regions."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decode_step(x):
    stop = x.sum().item()          # BCG-HOST-SYNC
    return x * stop


def loop(cache, n):
    def body(carry):
        i, c = carry
        host = np.asarray(c)       # BCG-HOST-SYNC
        c.block_until_ready()      # BCG-HOST-SYNC
        v = jax.device_get(c)      # BCG-HOST-SYNC
        return i + 1, c * host.shape[0] * v[0]

    def cond(carry):
        return carry[0] < n

    return jax.lax.while_loop(cond, body, (0, cache))
