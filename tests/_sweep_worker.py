"""Worker process for the hermetic multi-host sweep tests (not a
pytest module; launched by tests/test_sweep.py).

Runs as one rank of a REAL 2-process JAX CPU cluster (the
tests/_fleet_worker.py coordinator-handshake idiom): joins the process
group through bcg_tpu.parallel.distributed.initialize — which hands the
sweep controller its process identity — then runs the launcher's spec
through :func:`bcg_tpu.sweep.run_sweep` into the shared sweep dir.

* Multi-job spec: this rank runs the strided partition
  ``jobs[rank::world]``; completion lands in
  ``sweep-manifest-r<rank>.jsonl`` and per-rank game-event files.  The
  launcher may SIGKILL the cluster mid-sweep and relaunch with the same
  out_dir — the controller must then finish exactly the remaining job
  set (resume from manifests + game_end records + round checkpoints).
* Single-job spec: cooperative mode — both ranks play the SAME game and
  the SPMD exchange rides the dp-across-hosts mesh (only rank 0
  records events/manifest).

Usage: python tests/_sweep_worker.py <coordinator> <num_procs> <pid>
       <out_dir> <spec.json>
"""

import json
import sys


def main() -> None:
    coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    out_dir, spec_path = sys.argv[4], sys.argv[5]

    import jax

    jax.config.update("jax_platforms", "cpu")

    from bcg_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=coord, num_processes=nproc, process_id=pid
    )

    from bcg_tpu.obs import fleet
    from bcg_tpu.sweep import run_sweep

    assert fleet.process_index() == pid, fleet.identity()
    assert fleet.process_count() == nproc, fleet.identity()

    with open(spec_path) as f:
        spec = json.load(f)
    summary = run_sweep(spec, out_dir, max_concurrent=2, linger_ms=0)
    print(
        "SWEEP-OK "
        + json.dumps({
            "rank": summary["rank"],
            "world": summary["world"],
            "cooperative": summary["cooperative"],
            "partition": summary["partition"],
            "completed": summary["completed"],
            "failed": summary["failed"],
            "skipped": summary["skipped"],
        }),
        flush=True,
    )


if __name__ == "__main__":
    main()
