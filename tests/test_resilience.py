"""Chaos seam injection + recovery tier (runtime/resilience.py, the
serve dispatch retry/supervisor ladder, the sweep job-requeue policy,
and the EventSink dead-disk path).

Owns the perf-gate ``chaos.*`` namespace (tests/test_perf_gate.py
NAMESPACE_OWNERS): the gate-backed classes below pin the scenario green
at HEAD, the resurface contract (removing a baseline entry fails as
unbaselined, never silently), and the ``chaos-off`` injection failing
loudly by name — the never-vacuously-green contract.
"""

import glob
import importlib.util
import json
import os
import threading
import time

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.engine.paged_kv import PoolExhausted
from bcg_tpu.obs import counters as obs_counters, export as obs_export
from bcg_tpu.runtime import resilience
from bcg_tpu.runtime.resilience import (
    ChaosError,
    EngineDead,
    EngineHung,
    FaultPlan,
)
from bcg_tpu.serve.engine import ServingEngine, run_serving_simulations
from bcg_tpu.serve.scheduler import Scheduler, SchedulerClosed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO, "scripts", "perf_gate.py")

DECIDE = {
    "type": "object",
    "properties": {"value": {"type": "integer", "minimum": 0, "maximum": 50}},
}


@pytest.fixture
def chaos(monkeypatch):
    """Set a chaos spec for one test; plan cache reset both sides."""

    def arm(spec: str):
        monkeypatch.setenv("BCG_TPU_CHAOS", spec)
        resilience.reset()

    yield arm
    resilience.reset()


@pytest.fixture(autouse=True)
def _clean_plan():
    resilience.reset()
    yield
    resilience.reset()


# ------------------------------------------------------------- plan units


class TestFaultPlan:
    def test_parse_kinds_sites_occurrences(self):
        p = FaultPlan.parse(
            "seed=9;crash@serve.dispatch:2,5;hang@engine.generate:4:1.5;"
            "exhaust@kvpool.alloc:3+;diskfail@sink.write:1;"
            "freeze@fleet.heartbeat:1"
        )
        assert p.seed == 9
        kinds = [(d.kind, d.site) for d in p.directives]
        assert kinds == [
            ("crash", "serve.dispatch"), ("hang", "engine.generate"),
            ("exhaust", "kvpool.alloc"), ("diskfail", "sink.write"),
            ("freeze", "fleet.heartbeat"),
        ]
        assert p.directives[0].occurrences == {2, 5}
        assert p.directives[1].arg == 1.5
        assert p.directives[2].from_n == 3

    def test_occurrence_semantics(self):
        p = FaultPlan.parse("crash@serve.dispatch:2,4")
        fired = [p.fire("serve.dispatch") is not None for _ in range(5)]
        assert fired == [False, True, False, True, False]
        assert p.injected == {"crash@serve.dispatch": 2}

    def test_open_range_fires_from_n(self):
        p = FaultPlan.parse("exhaust@kvpool.alloc:3+")
        fired = [p.fire("kvpool.alloc") is not None for _ in range(5)]
        assert fired == [False, False, True, True, True]

    def test_seeded_probability_mode_is_reproducible(self):
        fires = []
        for _ in range(2):
            p = FaultPlan.parse("seed=11;crash@serve.dispatch:p0.5")
            fires.append(
                [p.fire("serve.dispatch") is not None for _ in range(20)]
            )
        assert fires[0] == fires[1]
        assert any(fires[0]) and not all(fires[0])

    @pytest.mark.parametrize("bad", [
        "boom@serve.dispatch:1",          # unknown kind
        "crash@serve.nowhere:1",          # unknown seam
        "crash@sink.write:1",             # kind/seam mismatch
        "crash@serve.dispatch",           # missing when
        "crash@serve.dispatch:",          # empty when
        "crash@serve.dispatch:p1.5",      # rate out of range
    ])
    def test_bad_specs_fail_at_parse(self, bad):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)

    def test_inject_noop_when_unset(self, monkeypatch):
        monkeypatch.delenv("BCG_TPU_CHAOS", raising=False)
        resilience.reset()
        for _ in range(3):
            resilience.inject("serve.dispatch")  # must not raise
        assert resilience.plan() is None
        assert resilience.stats() is None

    def test_inject_counts_and_raises(self, chaos):
        chaos("crash@serve.dispatch:1")
        before = obs_counters.snapshot()
        with pytest.raises(ChaosError):
            resilience.inject("serve.dispatch")
        moved = obs_counters.delta(before)
        assert moved.get("chaos.injected") == 1
        assert moved.get("chaos.injected.crash") == 1
        assert resilience.stats() == {"crash@serve.dispatch": 1}

    def test_classify_failure(self):
        assert resilience.classify_failure(ChaosError("x")) == "transient"
        assert resilience.classify_failure(PoolExhausted("x")) == "transient"
        assert resilience.classify_failure(EngineHung("x")) == "transient"
        assert resilience.classify_failure(TimeoutError()) == "transient"
        assert resilience.classify_failure(OSError()) == "transient"
        assert resilience.classify_failure(EngineDead("x")) == "permanent"
        assert resilience.classify_failure(ValueError("x")) == "permanent"
        # Deterministic path/permission errors recur identically per
        # attempt — they must never burn retry budget.
        assert resilience.classify_failure(
            FileNotFoundError("gone")) == "permanent"
        assert resilience.classify_failure(
            PermissionError("denied")) == "permanent"

    def test_backoff_caps_and_jitters(self):
        import random

        rng = random.Random(0)
        delays = [
            resilience.backoff_s(a, base_s=0.02, cap_s=0.5, rng=rng)
            for a in range(10)
        ]
        assert all(d <= 0.5 * 1.25 for d in delays)
        assert delays[1] != delays[2] or delays[2] != delays[3]  # jittered
        # exponential shape before the cap dominates
        assert resilience.backoff_s(4, base_s=0.02, cap_s=10.0, jitter=0.0) \
            == pytest.approx(0.32)


# -------------------------------------------------------- dispatch recovery


class TestDispatchRecovery:
    def test_crash_retried_and_recovered(self, chaos):
        chaos("crash@serve.dispatch:1")
        before = obs_counters.snapshot()
        sched = Scheduler(FakeEngine(seed=0), linger_ms=1,
                          max_dispatch_retries=2)
        out = sched.submit_and_wait(
            ("json",),
            [("s", "agent_1 value: 7. Your current value: 7.", DECIDE)],
            [0.0], [64],
        )
        snap = sched.snapshot()
        sched.close()
        moved = obs_counters.delta(before)
        assert out[0]["value"] == 7
        assert snap["failed"] == 0 and snap["completed"] == 1
        assert snap["engine_errors"] == 1
        rec = snap["recovery"]
        assert rec["dispatch_retries"] == 1
        assert rec["recoveries"] == 1
        assert rec["recovery_ms"]["count"] == 1
        assert moved.get("serve.dispatch_retries") == 1
        assert moved.get("serve.recoveries") == 1

    def test_pool_exhaustion_is_retryable(self, chaos):
        chaos("exhaust@serve.dispatch:1")
        sched = Scheduler(FakeEngine(seed=0), linger_ms=1,
                          max_dispatch_retries=1)
        out = sched.submit_and_wait(
            ("json",),
            [("s", "agent_1 value: 9. Your current value: 9.", DECIDE)],
            [0.0], [64],
        )
        sched.close()
        assert out[0]["value"] == 9
        assert sched.stats.recoveries == 1

    def test_bisecting_split_isolates_poison_request(self):
        class PoisonEngine(FakeEngine):
            def batch_generate_json(self, prompts, temperature=0.8,
                                    max_tokens=512):
                if any("POISON" in p[1] for p in prompts):
                    raise RuntimeError("poison row")
                return super().batch_generate_json(
                    prompts, temperature, max_tokens
                )

        sched = Scheduler(PoisonEngine(seed=0), linger_ms=150,
                          max_dispatch_retries=1)
        outs = {}
        barrier = threading.Barrier(3)

        def worker(name, text):
            barrier.wait()
            try:
                outs[name] = sched.submit_and_wait(
                    ("json",), [("s", text, DECIDE)], [0.0], [64]
                )
            except BaseException as e:
                outs[name] = e

        rows = [("a", "agent_1 value: 3. Your current value: 3."),
                ("b", "POISON"),
                ("c", "agent_1 value: 4. Your current value: 4.")]
        threads = [threading.Thread(target=worker, args=r) for r in rows]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = sched.snapshot()
        sched.close()
        # The poison request fails ALONE; its merge partners complete
        # with real results after the bisection isolates it.
        assert isinstance(outs["b"], RuntimeError)
        assert outs["a"][0]["value"] == 3
        assert outs["c"][0]["value"] == 4
        assert snap["completed"] == 2 and snap["failed"] == 1
        assert snap["recovery"]["batch_splits"] >= 1

    def test_zero_retries_preserves_fail_fast(self, chaos):
        """Default budget (0): first error fails the batch — the
        pre-recovery contract, byte-for-byte."""
        chaos("crash@serve.dispatch:1")
        sched = Scheduler(FakeEngine(seed=0), linger_ms=1)
        with pytest.raises(ChaosError):
            sched.submit_and_wait(("json",), [("s", "u", DECIDE)],
                                  [0.0], [64])
        snap = sched.snapshot()
        sched.close()
        assert snap["failed"] == 1
        assert snap["recovery"] is None  # no recovery surface when inert


# ------------------------------------------------------- engine supervisor


class TestEngineSupervisor:
    def test_hang_rebuilds_once_and_recovers(self, chaos):
        chaos("hang@serve.dispatch:1:5.0")
        built = []

        def factory():
            built.append(1)
            return FakeEngine(seed=0)

        sched = Scheduler(FakeEngine(seed=0), linger_ms=1, watchdog_s=1,
                          engine_factory=factory)
        t0 = time.monotonic()
        out = sched.submit_and_wait(
            ("json",),
            [("s", "agent_1 value: 5. Your current value: 5.", DECIDE)],
            [0.0], [64],
        )
        wall = time.monotonic() - t0
        snap = sched.snapshot()
        sched.close()
        assert out[0]["value"] == 5
        assert built == [1]
        assert snap["recovery"]["engine_rebuilds"] == 1
        assert snap["recovery"]["recoveries"] == 1
        # The watchdog cut the 5s hang at ~1s; recovery is bounded by
        # the watchdog, not the hang.
        assert wall < 4.0

    def test_second_hang_declares_scheduler_dead(self, chaos):
        chaos("hang@serve.dispatch:1,2:5.0")
        sched = Scheduler(FakeEngine(seed=0), linger_ms=1, watchdog_s=1,
                          engine_factory=lambda: FakeEngine(seed=0))
        with pytest.raises(EngineDead):
            sched.submit_and_wait(("json",), [("s", "u", DECIDE)],
                                  [0.0], [64])
        # The scheduler declared itself dead: later submitters fail
        # fast with SchedulerClosed instead of queueing forever.
        with pytest.raises(SchedulerClosed):
            sched.submit_and_wait(("json",), [("s", "v", DECIDE)],
                                  [0.0], [64])
        sched.close()

    def test_no_factory_hang_is_terminal(self, chaos):
        chaos("hang@serve.dispatch:1:5.0")
        sched = Scheduler(FakeEngine(seed=0), linger_ms=1, watchdog_s=1)
        with pytest.raises(EngineDead):
            sched.submit_and_wait(("json",), [("s", "u", DECIDE)],
                                  [0.0], [64])
        sched.close()


# ------------------------------------------------------------ kvpool seam


class TestKvPoolSeam:
    def test_alloc_seam_raises_then_recovers(self, chaos):
        from bcg_tpu.engine.paged_kv import PagedKV
        from bcg_tpu.models.configs import spec_for_model

        chaos("exhaust@kvpool.alloc:1")
        pool = PagedKV(spec_for_model("bcg-tpu/tiny-test"), num_blocks=8,
                       block_size=4)
        with pytest.raises(PoolExhausted, match="chaos"):
            pool.alloc(2)
        # Single-occurrence fault: the pool itself is untouched and the
        # next allocation succeeds — exactly the transient shape the
        # serve retry ladder absorbs.
        blocks = pool.alloc(2)
        assert len(blocks) == 2
        pool.close()


# ------------------------------------------------------ sink dead-disk path


class TestEventSinkDeadDisk:
    def _drain_until(self, predicate, timeout_s=5.0):
        t0 = time.monotonic()
        delay = 0.002
        while not predicate():
            if time.monotonic() - t0 > timeout_s:
                raise AssertionError("sink never hit the dead-disk path")
            time.sleep(delay)
            delay = min(delay * 2, 0.1)

    def test_serve_sink_counts_drops_after_disk_death(self, tmp_path,
                                                      chaos, capfd):
        chaos("diskfail@sink.write:1")
        before = obs_counters.value("serve.events_dropped")
        sink = obs_export.EventSink(str(tmp_path / "events.jsonl"))
        for i in range(5):
            sink.emit("probe", i=i)
        self._drain_until(
            lambda: obs_counters.value("serve.events_dropped") - before >= 5
        )
        # Post-death emits are counted too (warn-once, count-always).
        sink.emit("late", i=99)
        sink.close()
        dropped = obs_counters.value("serve.events_dropped") - before
        assert dropped == 6
        err = capfd.readouterr().err
        assert err.count("event sink write failed") == 1  # warn ONCE
        assert "serve.events_dropped" in err
        # Nothing landed on disk.
        path = tmp_path / "events.jsonl"
        assert not path.exists() or path.read_text() == ""

    def test_game_sink_uses_its_own_drop_counter(self, tmp_path, chaos):
        chaos("diskfail@sink.write:1")
        before_game = obs_counters.value("game.events_dropped")
        before_serve = obs_counters.value("serve.events_dropped")
        sink = obs_export.EventSink(
            str(tmp_path / "game.jsonl"), drop_counter="game.events_dropped"
        )
        for i in range(4):
            sink.emit("round_probe", i=i)
        self._drain_until(
            lambda: obs_counters.value("game.events_dropped")
            - before_game >= 4
        )
        sink.close()
        assert obs_counters.value("game.events_dropped") - before_game == 4
        assert obs_counters.value("serve.events_dropped") == before_serve

    def test_healthy_sink_unaffected(self, tmp_path):
        before = obs_counters.value("serve.events_dropped")
        sink = obs_export.EventSink(str(tmp_path / "ok.jsonl"))
        for i in range(3):
            sink.emit("probe", i=i)
        sink.close()
        lines = (tmp_path / "ok.jsonl").read_text().strip().splitlines()
        assert len(lines) == 3
        assert obs_counters.value("serve.events_dropped") == before


# --------------------------------------------------------- sweep job retry


def _sweep_spec():
    return {
        "name": "retry-sweep",
        "base": {"agents": 3, "byzantine": 0, "max_rounds": 3,
                 "backend": "fake"},
        "axes": {"seed": [1, 2, 3]},
    }


class TestSweepJobRetry:
    def test_transient_failure_requeues_completes_reports_once(
            self, tmp_path, chaos):
        from bcg_tpu.sweep.controller import render_report, run_sweep

        chaos("crash@sweep.job:2")
        before = obs_counters.snapshot()
        summary = run_sweep(
            _sweep_spec(), str(tmp_path), max_concurrent=1,
            engine=FakeEngine(seed=0), max_job_retries=2,
        )
        moved = obs_counters.delta(before)
        assert summary["completed"] == 3 and summary["failed"] == 0
        assert len(summary["results"]) == 3  # terminal outcome per job
        assert moved.get("sweep.jobs.retried") == 1

        # Manifest: the crashed attempt's job_end is failed/transient,
        # superseded by a completed job_end for the SAME job — exactly
        # one completed end per job id.
        records = [
            json.loads(line)
            for line in open(glob.glob(
                os.path.join(str(tmp_path), "sweep-manifest-r*.jsonl")
            )[0])
        ]
        ends = [r for r in records if r.get("event") == "job_end"]
        failed = [r for r in ends if r["status"] == "failed"]
        assert len(failed) == 1
        assert failed[0]["failure"] == "transient"
        completed = [r for r in ends if r["status"] == "completed"]
        assert len(completed) == 3
        assert len({r["job"] for r in completed}) == 3
        retried_end = [r for r in completed
                       if r["job"] == failed[0]["job"]]
        assert retried_end[0].get("attempt") == 1
        # Config-grouped report counts each job once (the completed end
        # supersedes the transient failed attempt): 3 jobs ended, no
        # failed-jobs footer.
        report = render_report(str(tmp_path))
        assert "3 jobs ended" in report
        assert "failed" not in report

        # Duplicate-game detection over the event files stays EMPTY:
        # the requeued job produced exactly one game_end.
        cr_path = os.path.join(REPO, "scripts", "consensus_report.py")
        spec = importlib.util.spec_from_file_location("cr_retry", cr_path)
        cr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cr)
        games, problems = [], []
        for path in sorted(glob.glob(
                os.path.join(str(tmp_path), "events-*.jsonl"))):
            games.extend(cr.parse_file(path, problems))
        assert cr.duplicate_job_problems(games) == []
        ended = [g for g in games if g.ended]
        assert len(ended) == 3

    def test_permanent_failure_never_retries(self, tmp_path):
        from bcg_tpu.sweep.controller import run_sweep

        class BrokenEngine(FakeEngine):
            def batch_generate_json(self, prompts, temperature=0.8,
                                    max_tokens=512):
                raise ValueError("deterministically broken config")

        before = obs_counters.snapshot()
        summary = run_sweep(
            {"name": "perm", "base": {"agents": 3, "byzantine": 0,
                                      "max_rounds": 2, "backend": "fake"},
             "axes": {"seed": [1]}},
            str(tmp_path), max_concurrent=1, engine=BrokenEngine(seed=0),
            max_job_retries=3,
        )
        moved = obs_counters.delta(before)
        assert summary["failed"] == 1
        assert summary["results"][0]["failure"] == "permanent"
        # A permanent failure burns zero retry budget.
        assert moved.get("sweep.jobs.retried", 0) == 0

    def test_retry_budget_exhaustion_is_terminal(self, tmp_path, chaos):
        from bcg_tpu.sweep.controller import run_sweep

        chaos("crash@sweep.job:1+")  # every attempt crashes
        summary = run_sweep(
            {"name": "always", "base": {"agents": 3, "byzantine": 0,
                                        "max_rounds": 2, "backend": "fake"},
             "axes": {"seed": [1]}},
            str(tmp_path), max_concurrent=1, engine=FakeEngine(seed=0),
            max_job_retries=2,
        )
        assert summary["failed"] == 1
        assert summary["completed"] == 0
        # 1 initial + 2 retries, then terminal.
        assert resilience.stats() == {"crash@sweep.job": 3}


# ----------------------------------------------- kill-style oracle identity


class TestKillStyleOracle:
    def test_faulted_run_outcome_identical_to_fault_free_oracle(
            self, chaos, monkeypatch):
        """Acceptance: a seeded serving run with an injected engine
        crash mid-wave, a device hang (watchdog + rebuild), and a
        PoolExhausted completes ALL games with outcomes identical to
        the fault-free oracle run — recovery is invisible to the game
        layer (FakeEngine responses are pure functions of prompt
        content, so retried batches reproduce byte-identical rows)."""
        monkeypatch.delenv("BCG_TPU_CHAOS", raising=False)
        resilience.reset()

        def play(engine_proxy):
            outs = []

            def make(i):
                def go(engine):
                    return run_simulation(
                        n_agents=4, byzantine_count=1, max_rounds=4,
                        backend="fake", seed=i, engine=engine,
                    )
                return go

            outs = run_serving_simulations(
                None, [make(i) for i in range(4)], serving=engine_proxy,
            )
            return outs

        def outcome(result):
            return (
                result["metrics"]["consensus_reached"],
                result["metrics"].get("consensus_value"),
                result["metrics"].get("total_rounds"),
            )

        # Oracle: no chaos, plain scheduler.
        oracle_serving = ServingEngine(FakeEngine(seed=0), linger_ms=2)
        oracle = [outcome(r) for r in play(oracle_serving)]
        oracle_serving.shutdown()

        # Faulted run: crash mid-wave + hang + exhaust, recovery on.
        chaos("seed=3;crash@serve.dispatch:2;hang@serve.dispatch:4:5.0;"
              "exhaust@serve.dispatch:6")
        sched = Scheduler(
            FakeEngine(seed=0), linger_ms=2, max_dispatch_retries=2,
            watchdog_s=1, engine_factory=lambda: FakeEngine(seed=0),
        )
        serving = ServingEngine(FakeEngine(seed=0), scheduler=sched)
        faulted_results = play(serving)
        snap = sched.snapshot()
        serving.shutdown()

        assert all(isinstance(r, dict) for r in faulted_results), (
            faulted_results
        )
        assert [outcome(r) for r in faulted_results] == oracle
        # All three faults actually fired and were recovered.
        assert resilience.stats() == {
            "crash@serve.dispatch": 1, "hang@serve.dispatch": 1,
            "exhaust@serve.dispatch": 1,
        }
        assert snap["failed"] == 0
        assert snap["recovery"]["recoveries"] == 3
        assert snap["recovery"]["engine_rebuilds"] == 1
        # No leaked futures.
        assert snap["pending"] == 0


# ------------------------------------------------------------- gate-backed


def _load_gate():
    spec = importlib.util.spec_from_file_location("perf_gate_chaos", GATE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def chaos_gate():
    resilience.reset()
    mod = _load_gate()
    measured = mod.run_chaos_scenario()
    resilience.reset()
    return mod, measured


class TestChaosGate:
    def test_scenario_green_at_head(self, chaos_gate):
        mod, measured = chaos_gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(measured, mod.load_baseline(), ("chaos",))
        assert findings == [], "\n".join(findings)

    def test_measures_the_advertised_metrics(self, chaos_gate):
        _, measured = chaos_gate
        for name in (
            "chaos.completed_fraction", "chaos.lost_futures",
            "chaos.dispatch_retries", "chaos.batch_splits",
            "chaos.recoveries", "chaos.engine_rebuilds",
            "chaos.faults_injected", "chaos.recovery_hist_sanity",
            "chaos.sweep_jobs_retried", "chaos.sweep_completed_fraction",
            "chaos.sweep_duplicate_job_problems",
        ):
            assert name in measured, name

    def test_removing_entry_resurfaces_unbaselined_failure(self, chaos_gate):
        mod, measured = chaos_gate
        baseline = mod.load_baseline()
        pruned = {
            "metrics": {
                k: v for k, v in baseline["metrics"].items()
                if k != "chaos.recoveries"
            }
        }
        findings = mod.check_metrics(measured, pruned)
        assert any("chaos.recoveries" in f and "no entry" in f
                   for f in findings), findings

    def test_chaos_off_injection_fails_naming_recovery_metrics(self):
        resilience.reset()
        mod = _load_gate()
        measured = mod.run_chaos_scenario("chaos-off")
        resilience.reset()
        findings = mod.check_metrics(measured, mod.load_baseline())
        named = "\n".join(findings)
        for metric in ("chaos.dispatch_retries", "chaos.recoveries",
                       "chaos.engine_rebuilds", "chaos.faults_injected",
                       "chaos.sweep_jobs_retried"):
            assert metric in named, (metric, findings)
