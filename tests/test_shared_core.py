"""Vote-phase shared-core prefix caching (VERDICT round-1 item #3).

The round's proposals + history block is identical across agents of a
role; under fully-connected reliable delivery the orchestrator switches
vote prompts to ``(core, tail)`` pairs and the engine serves the core
from a two-level cached KV prefix (role system -> per-round core),
prefilling only the tiny per-agent tail per row.
"""

import dataclasses

import pytest

from bcg_tpu.agents import create_agent
from bcg_tpu.config import BCGConfig, EngineConfig
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.runtime.orchestrator import BCGSimulation

GAME_STATE = {"round": 3, "max_rounds": 20, "vote_shared_core": True}


def _agent(aid, byz=False):
    a = create_agent(
        agent_id=aid, is_byzantine=byz, engine=FakeEngine(),
        value_range=(0, 50), byzantine_awareness="may_exist",
    )
    if not byz:
        a.set_initial_value(10)
    return a


def _deliver(agents):
    """Simulate full reliable delivery of every proposal."""
    proposals = {
        a.agent_id: (a.my_value, a.last_reasoning or f"Proposing value: {int(a.my_value)}")
        for a in agents if a.my_value is not None
    }
    for a in agents:
        a.receive_proposals([
            (sid, v, r) for sid, (v, r) in sorted(proposals.items())
            if sid != a.agent_id
        ])


class TestSharedCoreParts:
    def test_cores_identical_across_same_role_agents(self):
        agents = [_agent(f"agent_{i}") for i in range(4)]
        for i, a in enumerate(agents):
            a.my_value = 10 + i
            a.last_reasoning = f"reasoning of {a.agent_id}"
        _deliver(agents)
        prompts = [a.build_vote_round_prompt(GAME_STATE) for a in agents]
        assert all(isinstance(p, tuple) for p in prompts)
        cores = {p[0] for p in prompts}
        assert len(cores) == 1, "shared core must be byte-identical"
        tails = [p[1] for p in prompts]
        assert len(set(tails)) == 4, "tails must stay per-agent"
        for a, (_, tail) in zip(agents, prompts):
            assert f"You are {a.agent_id}." in tail

    def test_core_contains_every_proposal_once(self):
        agents = [_agent(f"agent_{i}") for i in range(3)]
        for i, a in enumerate(agents):
            a.my_value = 7 * (i + 1)
        _deliver(agents)
        core, _ = agents[0].build_vote_round_prompt(GAME_STATE)
        for a in agents:
            assert f"{a.agent_id}: {int(a.my_value)}" in core
        assert "(you)" not in core

    def test_abstainer_absent_from_core_present_in_tail(self):
        agents = [_agent(f"agent_{i}") for i in range(3)]
        agents[0].my_value = None  # abstained
        agents[1].my_value = 5
        agents[2].my_value = 5
        _deliver(agents)
        core, tail = agents[0].build_vote_round_prompt(GAME_STATE)
        assert "agent_0" not in core
        assert "You are agent_0. You ABSTAINED this round" in tail
        # Other agents' cores identical to the abstainer's.
        core1, _ = agents[1].build_vote_round_prompt(GAME_STATE)
        assert core1 == core

    def test_system_prompts_shared_per_role(self):
        honest = [_agent(f"agent_{i}") for i in range(3)]
        byz = [_agent(f"agent_{i}", byz=True) for i in range(3, 5)]
        hsp = {a.build_vote_system_prompt(GAME_STATE) for a in honest}
        bsp = {a.build_vote_system_prompt(GAME_STATE) for a in byz}
        assert len(hsp) == 1 and len(bsp) == 1
        assert hsp != bsp

    def test_fallback_mode_single_string_with_you_marker(self):
        a = _agent("agent_0")
        a.my_value = 12
        state = dict(GAME_STATE, vote_shared_core=False)
        vp = a.build_vote_round_prompt(state)
        assert isinstance(vp, str)
        assert "agent_0 (you): 12" in vp

    def test_byzantine_core_tail_structure(self):
        b = _agent("agent_9", byz=True)
        b.my_value = 3
        core, tail = b.build_vote_round_prompt(GAME_STATE)
        assert "BYZANTINE VOTING" in core
        assert "You are agent_9." in tail
        assert '"abstain"' in tail


class TestOrchestratorGating:
    def _cfg(self, **net):
        base = BCGConfig()
        return dataclasses.replace(
            base,
            game=dataclasses.replace(
                base.game, num_honest=3, num_byzantine=1, max_rounds=3, seed=0
            ),
            network=dataclasses.replace(base.network, **net),
            engine=dataclasses.replace(base.engine, backend="fake"),
            # Shared-core is opt-in (prompt text diverges from the
            # reference vote format); these tests exercise the opted-in
            # topology/protocol gating.
            agent=dataclasses.replace(base.agent, shared_core_votes=True),
            metrics=dataclasses.replace(base.metrics, save_results=False),
        )

    def test_default_config_keeps_reference_prompts(self):
        """Without the opt-in flag, vote prompts stay reference-shaped
        even on the eligible fully_connected + a2a_sim default config."""
        cfg = self._cfg()
        cfg = dataclasses.replace(
            cfg, agent=dataclasses.replace(cfg.agent, shared_core_votes=False)
        )
        assert BCGSimulation(config=cfg)._vote_shared_core is False
        from bcg_tpu.config import AgentConfig

        assert AgentConfig().shared_core_votes is False

    def test_fully_connected_enables_shared_core(self):
        sim = BCGSimulation(config=self._cfg())
        assert sim._vote_shared_core is True

    def test_ring_disables_shared_core(self):
        sim = BCGSimulation(config=self._cfg(topology_type="ring"))
        assert sim._vote_shared_core is False

    def test_lossy_channel_disables_shared_core(self):
        base = self._cfg()
        cfg = dataclasses.replace(
            base,
            communication=dataclasses.replace(
                base.communication, protocol_type="lossy_sim", drop_prob=0.3
            ),
        )
        sim = BCGSimulation(config=cfg)
        assert sim._vote_shared_core is False

    def test_game_results_identical_shared_vs_disabled(self):
        """The prompt restructuring must not change game OUTCOMES under
        the fake engine (it parses the same values either way)."""
        sim_a = BCGSimulation(config=self._cfg())
        stats_a = sim_a.run()
        sim_b = BCGSimulation(config=self._cfg())
        sim_b._vote_shared_core = False
        stats_b = sim_b.run()
        assert stats_a["total_rounds"] == stats_b["total_rounds"]
        assert stats_a["consensus_reached"] == stats_b["consensus_reached"]
        assert stats_a["consensus_value"] == stats_b["consensus_value"]


@pytest.mark.slow
class TestEngineSharedCore:
    SCHEMA = {
        "type": "object",
        "properties": {
            "decision": {"type": "string", "enum": ["stop", "continue"]}
        },
        "required": ["decision"],
        "additionalProperties": False,
    }

    def _engine(self, **kw):
        from bcg_tpu.engine.jax_engine import JaxEngine

        cfg = EngineConfig(
            model_name="bcg-tpu/tiny-test", backend="jax", max_model_len=1024,
            **kw,
        )
        return JaxEngine(cfg)

    def test_three_part_greedy_matches_joined(self):
        """(system, (core, tail), schema) must produce the same greedy
        output as (system, core+tail, schema) — the cached-core path is a
        pure optimization."""
        eng = self._engine()
        system = "You are an honest agent voting. " + "Rules. " * 30
        core = "=== PROPOSALS ===\n  agent_0: 5\n  agent_1: 5\n" * 4
        tails = [f"\n\nYou are agent_{i}. Decide now." for i in range(3)]
        split_rows = [(system, (core, t), self.SCHEMA) for t in tails]
        joined_rows = [(system, core + t, self.SCHEMA) for t in tails]
        out_split = eng.batch_generate_json(split_rows, temperature=0.0, max_tokens=48)
        eng2 = self._engine()
        out_joined = eng2.batch_generate_json(joined_rows, temperature=0.0, max_tokens=48)
        assert out_split == out_joined
        assert all(r.get("decision") in ("stop", "continue") for r in out_split)
        # One core entry, one system entry in the cache.
        composite_keys = [k for k, _b in eng._prefix_cache if "\x1e" in k]
        assert len(composite_keys) == 1

    def test_core_entry_reused_across_calls(self):
        eng = self._engine()
        system = "System prompt. " + "Pad. " * 30
        core = "Shared block. " * 40
        rows = [(system, (core, f"\n\nAgent {i}."), self.SCHEMA) for i in range(2)]
        eng.batch_generate_json(rows, temperature=0.0, max_tokens=48)
        n_entries = len(eng._prefix_cache)
        eng.batch_generate_json(rows, temperature=0.0, max_tokens=48)
        assert len(eng._prefix_cache) == n_entries  # no re-prefill growth

    def test_mixed_rows_core_and_plain(self):
        eng = self._engine()
        system = "System prompt. " + "Pad. " * 30
        core = "Shared block. " * 40
        rows = [
            (system, (core, "\n\nAgent 0."), self.SCHEMA),
            (system, "A plain user prompt with no core.", self.SCHEMA),
        ]
        out = eng.batch_generate_json(rows, temperature=0.0, max_tokens=48)
        assert all("decision" in r for r in out)

    def test_full_game_on_jax_engine_with_shared_core(self):
        """End-to-end: a short game through the real engine exercises the
        two-level vote path (orchestrator gates it on)."""
        base = BCGConfig()
        cfg = dataclasses.replace(
            base,
            game=dataclasses.replace(
                base.game, num_honest=2, num_byzantine=1, max_rounds=2, seed=1
            ),
            engine=dataclasses.replace(
                base.engine, model_name="bcg-tpu/tiny-test", backend="jax",
                max_model_len=1024,
            ),
            agent=dataclasses.replace(base.agent, shared_core_votes=True),
            llm=dataclasses.replace(
                base.llm, max_tokens_decide=80, max_tokens_vote=40
            ),
            metrics=dataclasses.replace(base.metrics, save_results=False),
        )
        sim = BCGSimulation(config=cfg)
        try:
            stats = sim.run()
        finally:
            sim.engine.shutdown()
            sim.close()
        assert stats["total_rounds"] >= 1
        assert sim.engine.failed_rows == 0
