"""Tests for the agent layer against the deterministic fake engine."""

import pytest

from bcg_tpu.agents import AgentMemory, ByzantineBCGAgent, HonestBCGAgent, create_agent
from bcg_tpu.engine.fake import FakeEngine


def honest(engine=None, awareness="may_exist", **kw):
    a = create_agent(
        "agent_0", False, engine or FakeEngine(), (0, 50), byzantine_awareness=awareness, **kw
    )
    a.set_initial_value(10)
    return a


def byz(engine=None, **kw):
    return create_agent("agent_1", True, engine or FakeEngine(), (0, 50), **kw)


GAME_STATE = {"round": 1, "max_rounds": 20}


class TestMemory:
    def test_round_summary_cap(self):
        m = AgentMemory()
        for i in range(10):
            m.add_round_summary(f"r{i}", max_history=5)
        assert m.last_k_rounds == [f"r{i}" for i in range(5, 10)]

    def test_strategy_cap_and_order(self):
        m = AgentMemory()
        for i in range(7):
            m.add_internal_strategy(i, f"s{i}")
        assert m.last_k_internal_strategies[0] == (2, "s2")
        assert len(m.last_k_internal_strategies) == 5

    def test_neighbor_stats(self):
        m = AgentMemory()
        m.update_neighbor_stat("a", 5)
        m.update_neighbor_stat("a", 7)
        assert m.neighbor_stats["a"] == {"last_value": 7, "message_count": 1}

    def test_snapshot_roundtrip(self):
        m = AgentMemory()
        m.add_round_summary("x")
        m.add_internal_strategy(1, "plan")
        m.update_neighbor_stat("b", 3)
        m2 = AgentMemory.from_snapshot(m.snapshot())
        assert m2.last_k_rounds == m.last_k_rounds
        assert m2.last_k_internal_strategies == m.last_k_internal_strategies
        assert m2.neighbor_stats == m.neighbor_stats


class TestPrompts:
    def test_honest_system_prompt_contains_rules(self):
        a = honest()
        sp = a.build_system_prompt(GAME_STATE)
        assert "HONEST" in sp and "Byzantine" in sp
        assert "between 0 and 50" in sp
        assert "Your Initial Value: 10" in sp
        assert "66%+" in sp

    def test_none_exist_variant(self):
        a = honest(awareness="none_exist")
        sp = a.build_system_prompt(GAME_STATE)
        assert "NO Byzantine" in sp
        assert "Cooperative" in sp

    def test_system_prompt_cached_and_invalidated(self):
        a = honest()
        sp1 = a.build_system_prompt(GAME_STATE)
        assert a.build_system_prompt({"max_rounds": 99}) is sp1  # cached
        a.set_initial_value(20)
        assert "Your Initial Value: 20" in a.build_system_prompt(GAME_STATE)

    def test_byzantine_system_prompt(self):
        b = byz()
        sp = b.build_system_prompt(GAME_STATE)
        assert "BYZANTINE" in sp and "secret" in sp.lower()
        assert "NO initial value" in sp

    def test_round_prompt_history_most_recent_first(self):
        a = honest()
        for i in range(1, 5):
            a.memory.add_round_summary(f"Round {i}: agent_0 value: {i}")
        rp = a.build_round_prompt(GAME_STATE)
        i3, i4 = rp.index("Round 3:"), rp.index("Round 4:")
        assert i4 < i3  # most recent first
        assert "Round 1:" not in rp  # only last 3 shown

    def test_vote_round_prompt_shows_current_proposals(self):
        a = honest()
        a.my_value = 12
        a.last_reasoning = "R" * 300
        a.receive_proposals([("agent_1", 13, "their reasons"), ("agent_2", 12, "")])
        vp = a.build_vote_round_prompt(GAME_STATE)
        assert "agent_0 (you): 12" in vp
        assert "agent_1: 13" in vp
        # own reasoning snipped to 200 chars
        assert "R" * 200 in vp and "R" * 201 not in vp

    def test_abstained_agent_shown(self):
        b = byz()
        b.my_value = None
        vp = b.build_vote_round_prompt(GAME_STATE)
        assert "ABSTAINED" in vp


class TestSchemas:
    def test_honest_decision_schema(self):
        s = honest().decision_schema()
        assert s["properties"]["value"] == {"type": "integer", "minimum": 0, "maximum": 50}
        assert s["required"] == ["internal_strategy", "value", "public_reasoning"]
        assert s["additionalProperties"] is False

    def test_byzantine_decision_schema_allows_abstain(self):
        s = byz().decision_schema()
        any_of = s["properties"]["value"]["anyOf"]
        assert {"type": "string", "enum": ["abstain"]} in any_of
        assert s["required"] == ["internal_strategy", "value"]

    def test_vote_schemas(self):
        assert honest().vote_schema()["properties"]["decision"]["enum"] == [
            "stop", "continue",
        ]
        assert byz().vote_schema()["properties"]["decision"]["enum"] == [
            "stop", "continue", "abstain",
        ]


class TestParsing:
    def test_honest_clamps_out_of_range(self):
        a = honest()
        v = a.parse_decision_response(
            {"internal_strategy": "s", "value": 999, "public_reasoning": "r"}, GAME_STATE
        )
        assert v == 50

    def test_honest_truncates_reasoning_to_600(self):
        a = honest()
        a.parse_decision_response(
            {"internal_strategy": "s" * 500, "value": 5, "public_reasoning": "x" * 700},
            GAME_STATE,
        )
        assert len(a.last_reasoning) == 600
        assert len(a.memory.last_k_internal_strategies[0][1]) == 400

    def test_honest_error_means_abstain(self):
        a = honest()
        assert a.parse_decision_response({"error": "boom"}, GAME_STATE) is None
        assert "FAILED" in a.last_reasoning

    def test_byzantine_abstain_records_strategy(self):
        b = byz()
        v = b.parse_decision_response(
            {"internal_strategy": "lurk", "value": "abstain", "public_reasoning": "hmm"},
            GAME_STATE,
        )
        assert v is None
        assert b.memory.last_k_internal_strategies[0][1] == "lurk"
        assert b.last_reasoning == "hmm"

    def test_byzantine_unexpected_type_is_abstain(self):
        b = byz()
        assert (
            b.parse_decision_response(
                {"internal_strategy": "s", "value": [1, 2]}, GAME_STATE
            )
            is None
        )

    def test_vote_parsing(self):
        a, b = honest(), byz()
        assert a.parse_vote_response({"decision": "stop"}, GAME_STATE) is True
        assert a.parse_vote_response({"decision": "continue"}, GAME_STATE) is False
        assert a.parse_vote_response({"error": "x"}, GAME_STATE) is False
        assert b.parse_vote_response({"decision": "abstain"}, GAME_STATE) is None
        assert b.parse_vote_response({"decision": " STOP "}, GAME_STATE) is True


class TestRetryLadder:
    def test_decide_retries_then_succeeds(self):
        eng = FakeEngine(fail_first_n_calls=2)
        a = honest(engine=eng)
        v = a.decide_next_value(GAME_STATE)
        assert v is not None
        assert eng.call_count == 3  # 2 failures + 1 success

    def test_decide_total_failure_abstains(self):
        eng = FakeEngine(fail_first_n_calls=99)
        a = honest(engine=eng)
        assert a.decide_next_value(GAME_STATE) is None
        assert eng.call_count == 3  # capped at max_json_retries

    def test_vote_total_failure_defaults_continue(self):
        eng = FakeEngine(fail_first_n_calls=99)
        a = honest(engine=eng)
        assert a.vote_to_terminate(GAME_STATE) is False


class TestFakePolicies:
    def test_consensus_policy_follows_mode(self):
        a = honest()
        a.memory.add_round_summary(
            "Round 1: agent_0 value: 10; agent_1 value: 30; agent_2 value: 30"
        )
        v = a.decide_next_value({"round": 2, "max_rounds": 20})
        assert v == 30

    def test_consensus_policy_keeps_current_value_without_history(self):
        a = honest()
        assert a.decide_next_value(GAME_STATE) == 10

    def test_vote_stop_when_unanimous(self):
        a = honest()
        a.my_value = 7
        a.receive_proposals([("agent_1", 7, ""), ("agent_2", 7, "")])
        assert a.vote_to_terminate(GAME_STATE) is True

    def test_vote_continue_when_split(self):
        a = honest()
        a.my_value = 7
        a.receive_proposals([("agent_1", 8, ""), ("agent_2", 7, "")])
        assert a.vote_to_terminate(GAME_STATE) is False

    def test_disrupt_policy_pushes_away(self):
        b = byz(engine=FakeEngine(policy="disrupt", seed=1))
        b.memory.add_round_summary("Round 1: agent_0 value: 5; agent_2 value: 5")
        v = b.decide_next_value(GAME_STATE)
        assert v is None or v >= 25  # abstain or far from mode

    def test_snapshot_restore(self):
        a = honest()
        a.my_value = 33
        a.receive_proposals([("agent_1", 2, "x")])
        a.last_reasoning = "why"
        a.memory.add_round_summary("Round 1: ...")
        blob = a.snapshot()
        fresh = honest()
        fresh.restore(blob)
        assert fresh.my_value == 33
        assert fresh.received_proposals == [("agent_1", 2, "x")]
        assert fresh.memory.last_k_rounds == ["Round 1: ..."]
