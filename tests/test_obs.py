"""Unified observability (bcg_tpu/obs): span tracer + counter registry.

Covers the ISSUE-4 acceptance surface: balanced-span invariant (every B
has an E, nesting valid), cross-thread parent handoff, Chrome-trace
JSON schema, counter ``delta()`` accounting over a scripted FakeEngine
serving run, compile/retrace counters incrementing exactly once per new
shape signature (steady-state decode: zero), and the disabled-tracer
overhead bound against the straggler micro-benchmark scenario.
"""

import json
import threading
import time

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.engine.interface import InferenceEngine
from bcg_tpu.obs import counters as obs_counters, tracer as obs_tracer
from bcg_tpu.obs.tracer import SpanAggregator, Tracer
from bcg_tpu.serve.engine import ServingEngine, run_serving_simulations

DECIDE = {
    "type": "object",
    "properties": {"value": {"type": "integer", "minimum": 0, "maximum": 50}},
}


@pytest.fixture
def traced(monkeypatch):
    monkeypatch.setenv("BCG_TPU_TRACE", "1")
    monkeypatch.delenv("BCG_TPU_TRACE_OUT", raising=False)
    monkeypatch.delenv("BCG_TPU_TRACE_RING", raising=False)
    obs_tracer.reset()
    yield obs_tracer.get_tracer()
    obs_tracer.reset()


@pytest.fixture
def untraced(monkeypatch):
    monkeypatch.delenv("BCG_TPU_TRACE", raising=False)
    monkeypatch.delenv("BCG_TPU_TRACE_OUT", raising=False)
    obs_tracer.reset()
    yield
    obs_tracer.reset()


def validate_balance(events):
    """Assert the balanced-span invariant — every B closed by an E at
    its thread's stack top — and return {span_id: B-or-X event}."""
    stacks = {}
    spans = {}
    for ev in events:
        ph = ev["ph"]
        if ph == "M":
            continue
        args = ev.get("args", {})
        if ph == "B":
            stacks.setdefault(ev["tid"], []).append(args["span_id"])
            spans[args["span_id"]] = ev
        elif ph == "E":
            stack = stacks.get(ev["tid"])
            assert stack, f"E without an open B on its thread: {ev}"
            assert stack.pop() == args["span_id"], f"unbalanced E: {ev}"
        elif ph == "X":
            assert "dur" in ev, f"X event without dur: {ev}"
            spans[args["span_id"]] = ev
    leftovers = {tid: s for tid, s in stacks.items() if s}
    assert not leftovers, f"B events never closed: {leftovers}"
    return spans


class TestTracer:
    def test_balanced_nested_spans_and_parents(self, traced):
        with obs_tracer.span("outer") as outer:
            with obs_tracer.span("inner"):
                pass
            with pytest.raises(RuntimeError):
                with obs_tracer.span("failing"):
                    raise RuntimeError("boom")
        data = traced.export()
        spans = validate_balance(data["traceEvents"])
        by_name = {ev["name"]: ev for ev in spans.values()}
        assert by_name["inner"]["args"]["parent_id"] == outer.span_id
        assert by_name["failing"]["args"]["parent_id"] == outer.span_id
        assert "parent_id" not in by_name["outer"]["args"]
        # The failing span still closed (its E carries the failure mark).
        failed_ends = [
            ev for ev in data["traceEvents"]
            if ev["ph"] == "E" and ev.get("args", {}).get("failed")
        ]
        assert len(failed_ends) == 1

    def test_cross_thread_parent_handoff(self, traced):
        with obs_tracer.span("request") as handle:
            def worker():
                with obs_tracer.span("device", parent=handle):
                    obs_tracer.complete("queue_wait", 0.002, parent=handle)

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        spans = validate_balance(traced.export()["traceEvents"])
        by_name = {ev["name"]: ev for ev in spans.values()}
        req, dev, qw = (by_name[n] for n in ("request", "device", "queue_wait"))
        assert dev["args"]["parent_id"] == req["args"]["span_id"]
        assert qw["args"]["parent_id"] == req["args"]["span_id"]
        assert dev["tid"] != req["tid"]  # the handoff crossed threads

    def test_ring_buffer_evicts_but_summary_survives(self):
        tracer = Tracer(ring_capacity=32)
        for _ in range(100):
            with tracer.span("tick"):
                pass
        assert len(tracer.events()) <= 32
        assert tracer.summarize()["tick"]["count"] == 100

    def test_summarize_percentiles(self):
        tracer = Tracer()
        for ms in range(1, 101):
            tracer.complete("op", ms / 1e3)
        row = tracer.summarize()["op"]
        assert row["count"] == 100
        assert abs(row["p50_ms"] - 50) <= 2
        assert abs(row["p95_ms"] - 95) <= 2
        assert row["total_ms"] == pytest.approx(5050, rel=0.01)

    def test_chrome_trace_schema(self, traced, tmp_path):
        with obs_tracer.span("alpha", args={"k": 1}):
            obs_tracer.complete("beta", 0.001)
        path = tmp_path / "trace.json"
        traced.export(str(path))
        data = json.loads(path.read_text())
        assert isinstance(data["traceEvents"], list) and data["traceEvents"]
        for ev in data["traceEvents"]:
            assert ev["ph"] in ("B", "E", "X", "M")
            assert isinstance(ev["name"], str)
            assert "pid" in ev and "tid" in ev
            if ev["ph"] != "M":
                assert isinstance(ev["ts"], (int, float))
        # Thread-name metadata present (Perfetto labels the lanes).
        assert any(ev["ph"] == "M" for ev in data["traceEvents"])
        # Counters ride along so one file is the full observability state.
        assert "counters" in data["otherData"]

    def test_disabled_span_is_shared_noop(self, untraced):
        assert obs_tracer.get_tracer() is None
        cm1 = obs_tracer.span("a")
        cm2 = obs_tracer.span("b")
        assert cm1 is cm2  # the shared no-op singleton — zero allocation
        with cm1 as handle:
            assert handle is None
        assert obs_tracer.current() is None
        obs_tracer.complete("c", 0.1)  # must not raise

    def test_trace_out_implies_enabled_and_flush_writes(
        self, monkeypatch, tmp_path
    ):
        out = tmp_path / "exported.json"
        monkeypatch.delenv("BCG_TPU_TRACE", raising=False)
        monkeypatch.setenv("BCG_TPU_TRACE_OUT", str(out))
        obs_tracer.reset()
        try:
            assert obs_tracer.enabled()
            with obs_tracer.span("only"):
                pass
            assert obs_tracer.flush() == str(out)
            data = json.loads(out.read_text())
            assert any(ev["name"] == "only" for ev in data["traceEvents"])
        finally:
            obs_tracer.reset()


class TestCounters:
    def test_counter_gauge_snapshot_delta(self):
        base = obs_counters.snapshot()
        obs_counters.inc("test_obs.widgets")
        obs_counters.inc("test_obs.widgets", 2)
        obs_counters.set_gauge("test_obs.depth", 7)
        snap = obs_counters.snapshot()
        assert snap["test_obs.widgets"] - base.get("test_obs.widgets", 0) == 3
        assert snap["test_obs.depth"] == 7
        d = obs_counters.delta(base)
        assert d["test_obs.widgets"] == 3
        assert "test_obs.depth" not in d  # gauges excluded from delta

    def test_counters_are_monotonic(self):
        with pytest.raises(ValueError):
            obs_counters.inc("test_obs.widgets", -1)

    def test_counter_gauge_name_clash_rejected(self):
        obs_counters.inc("test_obs.clash")
        with pytest.raises(TypeError):
            obs_counters.gauge("test_obs.clash")

    def test_value_read_does_not_create(self):
        assert obs_counters.value("test_obs.never_touched") == 0
        assert "test_obs.never_touched" not in obs_counters.snapshot()


class TestHistogram:
    def test_observe_buckets_and_flat_snapshot(self):
        h = obs_counters.histogram("test_obs.lat_ms", (1, 5, 25))
        for v in (0.5, 3, 3, 30, 1000):
            h.observe(v)
        flat = h.flat()
        # Cumulative buckets; the overflow (+Inf) bucket is .count.
        assert flat["test_obs.lat_ms.bucket.le_1"] == 1
        assert flat["test_obs.lat_ms.bucket.le_5"] == 3
        assert flat["test_obs.lat_ms.bucket.le_25"] == 3
        assert flat["test_obs.lat_ms.count"] == 5
        assert flat["test_obs.lat_ms.sum"] == 1036.5
        assert h.flat().items() <= obs_counters.snapshot().items()

    def test_bucket_derived_quantiles_are_ordered_and_bounded(self):
        h = obs_counters.histogram("test_obs.q_ms", (10, 100, 1000))
        for v in (5, 20, 50, 200, 5000):
            h.observe(v)
        q = h.quantiles()
        assert set(q) == {"p50", "p95", "p99"}
        assert 0 <= q["p50"] <= q["p95"] <= q["p99"] <= 1000
        # Overflow-bucket ranks clamp to the highest FINITE bound.
        assert q["p99"] == 1000

    def test_quantile_interpolates_within_bucket(self):
        h = obs_counters.histogram("test_obs.interp_ms", (0, 10))
        for _ in range(4):
            h.observe(5)
        # All mass in (0, 10]: the median interpolates to mid-bucket.
        assert h.quantile(0.5) == 5.0

    def test_bounds_validation(self):
        with pytest.raises(ValueError):
            obs_counters.histogram("test_obs.bad_desc", (5, 1))
        with pytest.raises(ValueError):
            obs_counters.histogram("test_obs.bad_inf", (1, float("inf")))
        with pytest.raises(ValueError):
            obs_counters.histogram("test_obs.bad_neg", (-1, 5))
        with pytest.raises(ValueError):
            obs_counters.histogram("test_obs.bad_empty", ())

    def test_conflicting_bounds_rejected_same_bounds_ok(self):
        obs_counters.histogram("test_obs.stable_ms", (1, 2))
        assert obs_counters.histogram("test_obs.stable_ms").bounds == (1.0, 2.0)
        assert obs_counters.histogram("test_obs.stable_ms", (1, 2)).bounds \
            == (1.0, 2.0)
        with pytest.raises(ValueError):
            obs_counters.histogram("test_obs.stable_ms", (1, 3))

    def test_undeclared_observe_rejected(self):
        with pytest.raises(KeyError):
            obs_counters.observe("test_obs.never_declared", 1)

    def test_type_clash_rejected(self):
        obs_counters.inc("test_obs.hist_clash")
        with pytest.raises(TypeError):
            obs_counters.histogram("test_obs.hist_clash", (1,))
        obs_counters.histogram("test_obs.hist_first", (1,))
        with pytest.raises(TypeError):
            obs_counters.counter("test_obs.hist_first")
        with pytest.raises(TypeError):
            obs_counters.gauge("test_obs.hist_first")

    def test_delta_carries_counts_not_sum(self):
        before = obs_counters.snapshot()
        h = obs_counters.histogram("test_obs.delta_ms", (1, 10))
        h.observe(0.5)
        h.observe(100)
        moved = obs_counters.delta(before)
        assert moved["test_obs.delta_ms.count"] == 2
        assert moved["test_obs.delta_ms.bucket.le_1"] == 1
        assert "test_obs.delta_ms.sum" not in moved

    def test_raw_baseline_idiom(self):
        """Per-instance share via construction-time raw() baselines —
        the SchedulerStats idiom."""
        h = obs_counters.histogram("test_obs.shared_ms", (1, 10))
        h.observe(0.5)
        base_counts, base_sum, base_n = h.raw()
        h.observe(5)
        counts, total, n = h.raw()
        own = [c - b for c, b in zip(counts, base_counts)]
        assert n - base_n == 1
        assert own == [0, 1, 0]
        assert total - base_sum == 5


class TestServeCounters:
    def test_delta_accounts_scripted_fake_run(self, untraced):
        """Scripted FakeEngine run: exact request/row movement in the
        process-wide registry (the satellite's delta() criterion)."""
        before = obs_counters.snapshot()
        serve = ServingEngine(FakeEngine(seed=0), linger_ms=0)
        for i in range(3):
            out = serve.batch_generate_json(
                [("sys", f"Your current value: {i}", DECIDE)], 0.5, 64
            )
            assert len(out) == 1
        serve.shutdown()
        moved = obs_counters.delta(before)
        assert moved["serve.requests"] == 3
        assert moved["serve.dispatched_rows"] == 3
        assert 1 <= moved["serve.dispatches"] <= 3
        # One queue-wait observation per dispatched request, now in the
        # first-class serve.queue_wait_ms histogram (delta carries its
        # monotonic .count / .bucket.* entries).
        assert moved["serve.queue_wait_ms.count"] == 3
        assert moved["serve.e2e_ms.count"] == 3

    def test_snapshot_latency_breakdown_and_hist_isolation(self, untraced):
        first = ServingEngine(FakeEngine(seed=0), linger_ms=0)
        first.batch_generate_json([("s", "u1", DECIDE)])
        first.batch_generate_json([("s", "u2", DECIDE)])
        snap1 = first.scheduler.snapshot()
        first.shutdown()
        assert sum(snap1["linger_hist_ms"].values()) == 2
        lat = snap1["latency_ms"]
        for stage in ("queue_wait", "admission", "batch_form", "device",
                      "scatter"):
            assert lat[stage]["count"] >= 1, stage
            assert set(lat[stage]) == {
                "count", "total_ms", "mean_ms", "p50_ms", "p95_ms"
            }
        assert snap1["mean_linger_ms"] == lat["queue_wait"]["mean_ms"]
        # A second scheduler's histogram is ITS OWN share of the
        # process-wide counters (construction-time baselines), not the
        # accumulated process total.
        second = ServingEngine(FakeEngine(seed=0), linger_ms=0)
        second.batch_generate_json([("s", "u3", DECIDE)])
        snap2 = second.scheduler.snapshot()
        second.shutdown()
        assert sum(snap2["linger_hist_ms"].values()) == 1


class TestDeviceMemoryMax:
    """Satellite: runtime.metrics._device_memory takes the MAX across
    all devices (device-0-only under-reported multi-chip peaks)."""

    class _Dev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    def test_max_across_devices(self, monkeypatch):
        import jax

        from bcg_tpu.runtime import metrics

        devs = [
            self._Dev({"bytes_in_use": 100, "peak_bytes_in_use": 300}),
            self._Dev({"bytes_in_use": 700, "peak_bytes_in_use": 900}),
            self._Dev({"bytes_in_use": 50, "peak_bytes_in_use": 60}),
        ]
        monkeypatch.setattr(jax, "devices", lambda: devs)
        assert metrics._device_memory() == (700, 900)

    def test_statless_backend_falls_back_to_none(self, monkeypatch):
        import jax

        from bcg_tpu.runtime import metrics

        monkeypatch.setattr(jax, "devices", lambda: [self._Dev(None)])
        assert metrics._device_memory() == (None, None)


class TestAcceptanceTrace:
    """ISSUE-4 acceptance: a traced FakeEngine serving run exports a
    Chrome trace with balanced, correctly-parented spans for at least
    round, decide, queue_wait, batch_form, device, prefill/decode."""

    REQUIRED = {
        "round", "decide", "vote", "serve.request", "serve.queue_wait",
        "serve.batch_form", "serve.device", "serve.scatter",
        "engine.prefill", "engine.decode",
    }

    def _run_games(self):
        def make(i):
            def go(engine):
                return run_simulation(
                    n_agents=3, byzantine_count=0, max_rounds=2,
                    backend="fake", seed=i, engine=engine,
                )
            return go

        outs = run_serving_simulations(
            FakeEngine(seed=0, policy="stubborn"),
            [make(i) for i in range(2)], linger_ms=1,
        )
        assert all(isinstance(o, dict) for o in outs), outs

    def test_traced_serving_game_trace(self, traced, tmp_path):
        self._run_games()
        path = tmp_path / "game.json"
        data = traced.export(str(path))
        events = data["traceEvents"]
        spans = validate_balance(events)
        names = {ev["name"] for ev in spans.values()}
        missing = self.REQUIRED - names
        assert not missing, f"span names missing from trace: {missing}"

        by_id = spans
        def parent_name(ev):
            pid = ev["args"].get("parent_id")
            return by_id[pid]["name"] if pid in by_id else None

        for ev in spans.values():
            if ev["name"] == "decide":
                assert parent_name(ev) == "round"
            if ev["name"] == "serve.queue_wait":
                # Cross-thread handoff: the X event on the scheduler
                # thread points back at the submitter's request span.
                assert parent_name(ev) == "serve.request"
            if ev["name"] == "serve.device":
                assert parent_name(ev) == "serve.request"
            if ev["name"] == "engine.prefill":
                # FakeEngine runs inside the scheduler's device span —
                # thread-local nesting parents it there.
                assert parent_name(ev) == "serve.device"
        # The request spans live on game threads, the device spans on
        # the dispatch thread — the parent links crossed threads.
        req_tids = {ev["tid"] for ev in spans.values()
                    if ev["name"] == "serve.request"}
        dev_tids = {ev["tid"] for ev in spans.values()
                    if ev["name"] == "serve.device"}
        assert req_tids and dev_tids and not (req_tids & dev_tids)
        # summarize(): per-name latency table over the run.
        table = traced.summarize()
        assert table["round"]["count"] == 4  # 2 games x 2 rounds
        assert {"count", "total_ms", "mean_ms", "p50_ms", "p95_ms"} == set(
            table["round"]
        )


class TestProfilerDelegation:
    def test_phases_become_spans_when_traced(self, traced):
        from bcg_tpu.runtime.profiler import SimulationProfiler

        prof = SimulationProfiler()
        with prof.phase("decide"):
            pass
        names = [e[1] for e in traced.events()]
        assert "decide" in names
        assert prof.phase_counts["decide"] == 1

    def test_phases_accumulate_untraced(self, untraced):
        from bcg_tpu.runtime.profiler import SimulationProfiler

        prof = SimulationProfiler()
        with prof.phase("vote"):
            time.sleep(0.005)
        assert prof.phase_counts["vote"] == 1
        assert prof.phase_seconds["vote"] >= 0.005
        assert prof.summary()["phase_counts"]["vote"] == 1


class TestRetraceCounters:
    """Compile/retrace accounting: exactly +1 per NEW shape signature,
    zero in steady state (the single most expensive silent regression
    this engine has)."""

    VOTE = {
        "type": "object",
        "properties": {
            "decision": {"type": "string", "enum": ["stop", "continue"]}
        },
        "required": ["decision"],
        "additionalProperties": False,
    }

    def test_steady_state_zero_then_new_shape_exactly_one(self):
        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=512,
        ))
        prompts = [("sys", "vote please", self.VOTE)]
        engine.batch_generate_json(prompts, temperature=0.0, max_tokens=16)
        after_first = obs_counters.snapshot()
        # Steady state: identical shapes -> ZERO engine.* movement.
        engine.batch_generate_json(prompts, temperature=0.0, max_tokens=16)
        steady = {
            k: v for k, v in obs_counters.delta(after_first).items()
            if k.startswith("engine.")
            # engine.prefill.positions_* are per-call PROGRESS counters
            # (real/padded prefill work) — they legitimately move every
            # call; this test pins the compile/retrace/spec families,
            # where any steady-state movement is a regression.
            and not k.startswith("engine.prefill.positions_")
        }
        assert steady == {}, f"steady-state decode retraced: {steady}"
        # A new token budget is a new decode-loop signature: exactly +1
        # compile AND +1 retrace on the matching counter.
        before_new = obs_counters.snapshot()
        engine.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        moved = obs_counters.delta(before_new)
        assert moved.get("engine.retrace.decode_loop") == 1, moved
        assert moved.get("engine.compile.decode_loop") == 1, moved
        # ... and once counted, the signature never counts again.
        before_repeat = obs_counters.snapshot()
        engine.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        repeat = {
            k: v for k, v in obs_counters.delta(before_repeat).items()
            if k.startswith("engine.")
            and not k.startswith("engine.prefill.positions_")  # per-call progress
        }
        assert repeat == {}, repeat
        engine.shutdown()

    def test_speculative_loop_steady_state_zero_retraces(self):
        """BCG_TPU_SPEC=1 steady state: per-row acceptance counts vary
        call to call (different prompts draft and accept differently)
        but live in the while-loop CARRY, not in any shape — so after
        the first compile, further calls must show ZERO compile/retrace
        movement on every jit entry point."""
        import dataclasses

        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        engine = JaxEngine(dataclasses.replace(
            EngineConfig(
                backend="jax", model_name="bcg-tpu/tiny-test",
                max_model_len=512,
            ),
            spec_decode=True,
        ))
        # Prompts chosen to vary acceptance: no echo, heavy echo of the
        # JSON skeleton, and a longer mixed one.
        variants = [
            [("sys", "vote now", self.VOTE)],
            [("sys", 'history: {"decision": "stop"} {"decision": "stop"} '
                     "vote again", self.VOTE)],
            [("sys", "round 5 results were mixed; vote once more please",
              self.VOTE)],
        ]
        engine.batch_generate_json(variants[0], temperature=0.0, max_tokens=32)
        after_first = obs_counters.snapshot()
        accepts = []
        for prompts in variants * 2:
            engine.batch_generate_json(prompts, temperature=0.0, max_tokens=32)
            accepts.append(
                obs_counters.value("engine.spec.accepted")
            )
        moved = {
            k: v for k, v in obs_counters.delta(after_first).items()
            if k.startswith("engine.compile") or k.startswith("engine.retrace")
        }
        assert moved == {}, f"speculative steady-state retraced: {moved}"
        # Non-vacuous: the calls really did accept varying amounts.
        deltas = {b - a for a, b in zip(accepts, accepts[1:])}
        assert len(deltas) > 1, deltas
        engine.shutdown()


class _DelayedCalls(InferenceEngine):
    """Per-call host-side delay in front of a shared proxy (the
    straggler micro-benchmark's workload shape, tests/test_serve.py)."""

    def __init__(self, engine, delay):
        self._engine = engine
        self._delay = delay

    def batch_generate_json(self, prompts, temperature=0.8, max_tokens=512):
        time.sleep(self._delay)
        return self._engine.batch_generate_json(prompts, temperature, max_tokens)

    def generate_json(self, prompt, schema, temperature=0.0, max_tokens=512,
                      system_prompt=None):
        time.sleep(self._delay)
        return self._engine.generate_json(
            prompt, schema, temperature, max_tokens, system_prompt=system_prompt
        )

    def generate(self, prompt, temperature=0.0, max_tokens=256, top_p=1.0,
                 system_prompt=None):
        return self._engine.generate(
            prompt, temperature, max_tokens, top_p, system_prompt=system_prompt
        )

    def batch_generate(self, prompts, temperature=0.0, max_tokens=256,
                       top_p=1.0):
        return self._engine.batch_generate(prompts, temperature, max_tokens,
                                           top_p)

    def shutdown(self):
        pass


class TestDisabledOverhead:
    """ISSUE-4 acceptance: BCG_TPU_TRACE=0 adds <5% wall-clock to the
    straggler micro-benchmark scenario.

    Measured as (spans the scenario emits) x (per-call cost of a
    disabled span), against the scenario's disabled wall-clock — the
    instrumentation is compiled in either way, so the disabled cost IS
    the number of no-op span entries times their unit cost."""

    FAST = 0.005
    GAMES, ROUNDS = 8, 2

    def _run_scenario(self):
        def make(i):
            delay = self.FAST * 10 if i == 0 else self.FAST

            def go(engine):
                return run_simulation(
                    n_agents=4, byzantine_count=0, max_rounds=self.ROUNDS,
                    backend="fake", seed=i,
                    engine=_DelayedCalls(engine, delay),
                )
            return go

        t0 = time.perf_counter()
        outs = run_serving_simulations(
            FakeEngine(seed=0, policy="stubborn"),
            [make(i) for i in range(self.GAMES)],
            max_concurrent=4, linger_ms=1,
        )
        assert all(isinstance(o, dict) for o in outs)
        return time.perf_counter() - t0

    def test_disabled_overhead_bound(self, untraced, monkeypatch):
        # Unit cost of the disabled fast path.
        probes = 20_000
        t0 = time.perf_counter()
        for _ in range(probes):
            with obs_tracer.span("probe"):
                pass
        per_span = (time.perf_counter() - t0) / probes

        # Scenario wall-clock with the tracer disabled (the shipped
        # default path).
        wall = self._run_scenario()

        # Span volume of the SAME scenario, counted by running it traced.
        monkeypatch.setenv("BCG_TPU_TRACE", "1")
        obs_tracer.reset()
        try:
            self._run_scenario()
            events = obs_tracer.get_tracer().events()
            span_calls = sum(1 for e in events if e[0] in ("B", "X"))
        finally:
            obs_tracer.reset()

        overhead = span_calls * per_span
        assert overhead < 0.05 * wall, (
            f"disabled tracer overhead {overhead * 1e3:.2f}ms is not <5% of "
            f"the {wall * 1e3:.0f}ms straggler scenario "
            f"({span_calls} spans x {per_span * 1e9:.0f}ns)"
        )
