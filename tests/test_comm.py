"""Tests for the communication layer: topology, A2A-sim protocol, network.

Covers reference semantics: neighbour-only routing (a2a_sim.py:169-171),
duplicate suppression (:173-175), inbox ordering (:231), reasoning cap
(:69-73), multicast illusion (:183-210), plus the grid topology the
reference never wired up.
"""

import numpy as np
import pytest

from bcg_tpu.comm import (
    A2AMessage,
    A2ASimProtocol,
    AgentNetwork,
    Decision,
    DecisionType,
    NetworkTopology,
    Phase,
    create_protocol,
    register_protocol,
)


def msg(sender, receiver, round=1, ts=1, phase=Phase.PROPOSE.value, value=5, reasoning="r"):
    return A2AMessage(
        sender_id=sender,
        receiver_id=receiver,
        round=round,
        phase=phase,
        decision=Decision(type=DecisionType.VALUE.value, value=value),
        reasoning=reasoning,
        timestamp=ts,
    )


class TestTopology:
    def test_fully_connected(self):
        t = NetworkTopology.fully_connected(4)
        assert all(len(v) == 3 for v in t.adjacency_list.values())
        assert t.avg_degree == 3.0

    def test_ring(self):
        t = NetworkTopology.ring(5)
        assert sorted(t.adjacency_list[0]) == [1, 4]
        assert t.avg_degree == 2.0

    def test_grid(self):
        t = NetworkTopology.grid(2, 3)
        assert t.num_agents == 6
        # corner has 2 neighbours, middle-edge has 3
        assert sorted(t.adjacency_list[0]) == [1, 3]
        assert sorted(t.adjacency_list[1]) == [0, 2, 4]

    def test_custom(self):
        t = NetworkTopology.custom({0: [1], 1: [0]})
        assert t.topology_type == "custom" and t.num_agents == 2

    def test_neighbor_mask_matches_adjacency(self):
        t = NetworkTopology.ring(4)
        m = t.neighbor_mask()
        assert m.shape == (4, 4)
        assert not m.diagonal().any()
        for i, nbrs in t.adjacency_list.items():
            assert set(np.where(m[i])[0]) == set(nbrs)


class TestA2ASim:
    def setup_method(self):
        self.topo = NetworkTopology.fully_connected(3)
        self.proto = A2ASimProtocol(3, self.topo.adjacency_list)

    def test_send_and_deliver(self):
        self.proto.send_message(0, 1, msg(0, 1))
        inbox = self.proto.deliver_messages(1, 1)
        assert len(inbox) == 1 and inbox[0].decision.value == 5

    def test_non_neighbor_send_rejected(self):
        ring = NetworkTopology.ring(4)
        proto = A2ASimProtocol(4, ring.adjacency_list)
        with pytest.raises(ValueError, match="not in neighbor set"):
            proto.send_message(0, 2, msg(0, 2))

    def test_duplicate_suppression(self):
        m = msg(0, 1)
        self.proto.send_message(0, 1, m)
        self.proto.send_message(0, 1, msg(0, 1))  # same key -> suppressed
        assert len(self.proto.deliver_messages(1, 1)) == 1
        assert self.proto.get_message_count(1) == 1

    def test_inbox_ordering_by_sender_then_timestamp(self):
        self.proto.send_message(2, 0, msg(2, 0, ts=1))
        self.proto.send_message(1, 0, msg(1, 0, ts=2))
        self.proto.send_message(1, 0, msg(1, 0, ts=1, phase="prepare"))
        inbox = self.proto.deliver_messages(0, 1)
        assert [(m.sender_id, m.timestamp) for m in inbox] == [(1, 1), (1, 2), (2, 1)]

    def test_broadcast_reaches_all_neighbors_identically(self):
        self.proto.broadcast_to_neighbors(
            0, 1, Phase.PROPOSE.value, Decision("value", 9), "hello", timestamp=1
        )
        for receiver in (1, 2):
            inbox = self.proto.deliver_messages(receiver, 1)
            assert len(inbox) == 1
            assert inbox[0].decision.value == 9 and inbox[0].reasoning == "hello"
        assert self.proto.deliver_messages(0, 1) == []  # no self-delivery
        assert self.proto.get_message_count(1) == 2

    def test_reasoning_truncated_to_500(self):
        m = msg(0, 1, reasoning="x" * 600)
        assert len(m.reasoning) == 500 and m.reasoning.endswith("...")

    def test_clear_round_buffer_frees_memory_keeps_count(self):
        self.proto.send_message(0, 1, msg(0, 1))
        self.proto.clear_round_buffer(1)
        assert self.proto.deliver_messages(1, 1) == []
        assert self.proto.get_message_count(1) == 1  # metric survives GC
        assert len(self.proto.delivered) == 0

    def test_message_roundtrip_serialization(self):
        m = msg(0, 1, value=7, reasoning="why")
        m2 = A2AMessage.from_dict(m.to_dict())
        assert m2 == m and m2.decision.value == 7

    def test_client_monotonic_timestamps(self):
        c = self.proto.create_client(0)
        c.send_to_neighbors(round=1, phase="propose", decision=Decision("value", 1), reasoning="")
        c.send_to_neighbors(round=1, phase="propose", decision=Decision("value", 2), reasoning="")
        inbox = self.proto.deliver_messages(1, 1)
        assert [m.timestamp for m in inbox] == [1, 2]

    def test_client_history(self):
        c = self.proto.create_client(0)
        c.update_history(1, [msg(1, 0)], {"v": 3})
        h = c.get_history()
        assert len(h) == 1 and h[0]["round"] == 1 and h[0]["local_state"] == {"v": 3}
        c.reset()
        assert c.get_history() == []

    def test_reset(self):
        self.proto.send_message(0, 1, msg(0, 1))
        self.proto.reset()
        assert self.proto.deliver_messages(1, 1) == []
        assert self.proto.get_message_count(1) == 0


class TestFactory:
    def test_create_a2a_sim(self):
        t = NetworkTopology.fully_connected(2)
        p = create_protocol("a2a_sim", 2, t.adjacency_list)
        assert isinstance(p, A2ASimProtocol)

    def test_unknown_protocol_raises(self):
        with pytest.raises(ValueError, match="Unknown protocol"):
            create_protocol("nope", 2, {})

    def test_register_custom(self):
        class Dummy(A2ASimProtocol):
            pass

        register_protocol("dummy", lambda num_agents, topology, config: Dummy(num_agents, topology))
        p = create_protocol("dummy", 2, NetworkTopology.ring(2).adjacency_list)
        assert isinstance(p, Dummy)


class TestNetwork:
    def make_net(self, n=3):
        topo = NetworkTopology.fully_connected(n)
        proto = A2ASimProtocol(n, topo.adjacency_list)
        net = AgentNetwork(topo, proto)
        for i in range(n):
            net.register_agent(f"agent_{i}", object(), i)
        return net

    def test_broadcast_and_receive_by_string_id(self):
        net = self.make_net()
        net.broadcast_message("agent_0", 1, Phase.PROPOSE, Decision("value", 4), "because")
        msgs = net.get_messages("agent_1", 1, Phase.PROPOSE)
        assert len(msgs) == 1 and msgs[0].decision.value == 4
        assert net.index_to_agent_id[msgs[0].sender_id] == "agent_0"

    def test_network_stats(self):
        net = self.make_net()
        net.broadcast_message("agent_0", 0, Phase.PROPOSE, Decision("value", 1), "")
        net.advance_round()
        stats = net.get_network_stats()
        assert stats["total_messages"] == 2
        assert stats["topology_type"] == "fully_connected"
        assert stats["avg_degree"] == 2.0

    def test_end_round_gc(self):
        net = self.make_net()
        net.broadcast_message("agent_0", 0, Phase.PROPOSE, Decision("value", 1), "")
        net.advance_round()
        net.end_round_gc(0)
        assert net.get_messages("agent_1", 0) == []
        assert net.get_network_stats()["total_messages"] == 2  # metric kept
