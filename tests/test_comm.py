"""Tests for the communication layer: topology, A2A-sim protocol, network.

Covers reference semantics: neighbour-only routing (a2a_sim.py:169-171),
duplicate suppression (:173-175), inbox ordering (:231), reasoning cap
(:69-73), multicast illusion (:183-210), plus the grid topology the
reference never wired up.
"""

import numpy as np
import pytest

from bcg_tpu.comm import (
    A2AMessage,
    A2ASimProtocol,
    AgentNetwork,
    Decision,
    DecisionType,
    NetworkTopology,
    Phase,
    create_protocol,
    register_protocol,
)


def msg(sender, receiver, round=1, ts=1, phase=Phase.PROPOSE.value, value=5, reasoning="r"):
    return A2AMessage(
        sender_id=sender,
        receiver_id=receiver,
        round=round,
        phase=phase,
        decision=Decision(type=DecisionType.VALUE.value, value=value),
        reasoning=reasoning,
        timestamp=ts,
    )


class TestTopology:
    def test_fully_connected(self):
        t = NetworkTopology.fully_connected(4)
        assert all(len(v) == 3 for v in t.adjacency_list.values())
        assert t.avg_degree == 3.0

    def test_ring(self):
        t = NetworkTopology.ring(5)
        assert sorted(t.adjacency_list[0]) == [1, 4]
        assert t.avg_degree == 2.0

    def test_grid(self):
        t = NetworkTopology.grid(2, 3)
        assert t.num_agents == 6
        # corner has 2 neighbours, middle-edge has 3
        assert sorted(t.adjacency_list[0]) == [1, 3]
        assert sorted(t.adjacency_list[1]) == [0, 2, 4]

    def test_custom(self):
        t = NetworkTopology.custom({0: [1], 1: [0]})
        assert t.topology_type == "custom" and t.num_agents == 2

    def test_neighbor_mask_matches_adjacency(self):
        t = NetworkTopology.ring(4)
        m = t.neighbor_mask()
        assert m.shape == (4, 4)
        assert not m.diagonal().any()
        for i, nbrs in t.adjacency_list.items():
            assert set(np.where(m[i])[0]) == set(nbrs)


class TestA2ASim:
    def setup_method(self):
        self.topo = NetworkTopology.fully_connected(3)
        self.proto = A2ASimProtocol(3, self.topo.adjacency_list)

    def test_send_and_deliver(self):
        self.proto.send_message(0, 1, msg(0, 1))
        inbox = self.proto.deliver_messages(1, 1)
        assert len(inbox) == 1 and inbox[0].decision.value == 5

    def test_non_neighbor_send_rejected(self):
        ring = NetworkTopology.ring(4)
        proto = A2ASimProtocol(4, ring.adjacency_list)
        with pytest.raises(ValueError, match="not in neighbor set"):
            proto.send_message(0, 2, msg(0, 2))

    def test_duplicate_suppression(self):
        m = msg(0, 1)
        self.proto.send_message(0, 1, m)
        self.proto.send_message(0, 1, msg(0, 1))  # same key -> suppressed
        assert len(self.proto.deliver_messages(1, 1)) == 1
        assert self.proto.get_message_count(1) == 1

    def test_inbox_ordering_by_sender_then_timestamp(self):
        self.proto.send_message(2, 0, msg(2, 0, ts=1))
        self.proto.send_message(1, 0, msg(1, 0, ts=2))
        self.proto.send_message(1, 0, msg(1, 0, ts=1, phase="prepare"))
        inbox = self.proto.deliver_messages(0, 1)
        assert [(m.sender_id, m.timestamp) for m in inbox] == [(1, 1), (1, 2), (2, 1)]

    def test_broadcast_reaches_all_neighbors_identically(self):
        self.proto.broadcast_to_neighbors(
            0, 1, Phase.PROPOSE.value, Decision("value", 9), "hello", timestamp=1
        )
        for receiver in (1, 2):
            inbox = self.proto.deliver_messages(receiver, 1)
            assert len(inbox) == 1
            assert inbox[0].decision.value == 9 and inbox[0].reasoning == "hello"
        assert self.proto.deliver_messages(0, 1) == []  # no self-delivery
        assert self.proto.get_message_count(1) == 2

    def test_reasoning_truncated_to_500(self):
        m = msg(0, 1, reasoning="x" * 600)
        assert len(m.reasoning) == 500 and m.reasoning.endswith("...")

    def test_clear_round_buffer_frees_memory_keeps_count(self):
        self.proto.send_message(0, 1, msg(0, 1))
        self.proto.clear_round_buffer(1)
        assert self.proto.deliver_messages(1, 1) == []
        assert self.proto.get_message_count(1) == 1  # metric survives GC
        assert len(self.proto.delivered) == 0

    def test_message_roundtrip_serialization(self):
        m = msg(0, 1, value=7, reasoning="why")
        m2 = A2AMessage.from_dict(m.to_dict())
        assert m2 == m and m2.decision.value == 7

    def test_client_monotonic_timestamps(self):
        c = self.proto.create_client(0)
        c.send_to_neighbors(round=1, phase="propose", decision=Decision("value", 1), reasoning="")
        c.send_to_neighbors(round=1, phase="propose", decision=Decision("value", 2), reasoning="")
        inbox = self.proto.deliver_messages(1, 1)
        assert [m.timestamp for m in inbox] == [1, 2]

    def test_client_history(self):
        c = self.proto.create_client(0)
        c.update_history(1, [msg(1, 0)], {"v": 3})
        h = c.get_history()
        assert len(h) == 1 and h[0]["round"] == 1 and h[0]["local_state"] == {"v": 3}
        c.reset()
        assert c.get_history() == []

    def test_reset(self):
        self.proto.send_message(0, 1, msg(0, 1))
        self.proto.reset()
        assert self.proto.deliver_messages(1, 1) == []
        assert self.proto.get_message_count(1) == 0


class TestFactory:
    def test_create_a2a_sim(self):
        t = NetworkTopology.fully_connected(2)
        p = create_protocol("a2a_sim", 2, t.adjacency_list)
        assert isinstance(p, A2ASimProtocol)

    def test_unknown_protocol_raises(self):
        with pytest.raises(ValueError, match="Unknown protocol"):
            create_protocol("nope", 2, {})

    def test_register_custom(self):
        class Dummy(A2ASimProtocol):
            pass

        register_protocol("dummy", lambda num_agents, topology, config: Dummy(num_agents, topology))
        p = create_protocol("dummy", 2, NetworkTopology.ring(2).adjacency_list)
        assert isinstance(p, Dummy)


class TestNetwork:
    def make_net(self, n=3):
        topo = NetworkTopology.fully_connected(n)
        proto = A2ASimProtocol(n, topo.adjacency_list)
        net = AgentNetwork(topo, proto)
        for i in range(n):
            net.register_agent(f"agent_{i}", object(), i)
        return net

    def test_broadcast_and_receive_by_string_id(self):
        net = self.make_net()
        net.broadcast_message("agent_0", 1, Phase.PROPOSE, Decision("value", 4), "because")
        msgs = net.get_messages("agent_1", 1, Phase.PROPOSE)
        assert len(msgs) == 1 and msgs[0].decision.value == 4
        assert net.index_to_agent_id[msgs[0].sender_id] == "agent_0"

    def test_network_stats(self):
        net = self.make_net()
        net.broadcast_message("agent_0", 0, Phase.PROPOSE, Decision("value", 1), "")
        net.advance_round()
        stats = net.get_network_stats()
        assert stats["total_messages"] == 2
        assert stats["topology_type"] == "fully_connected"
        assert stats["avg_degree"] == 2.0

    def test_end_round_gc(self):
        net = self.make_net()
        net.broadcast_message("agent_0", 0, Phase.PROPOSE, Decision("value", 1), "")
        net.advance_round()
        net.end_round_gc(0)
        assert net.get_messages("agent_1", 0) == []
        assert net.get_network_stats()["total_messages"] == 2  # metric kept


class TestLossySim:
    """Unreliable-channel variant: seeded drops and cross-round delays
    (bcg_tpu/comm/lossy_sim.py)."""

    def _proto(self, n=4, **kw):
        from bcg_tpu.comm.lossy_sim import LossySimProtocol

        t = NetworkTopology.fully_connected(n)
        return LossySimProtocol(n, t.adjacency_list, **kw)

    def test_zero_fault_rates_match_reliable_channel(self):
        lossy = self._proto(seed=7)
        reliable = create_protocol(
            "a2a_sim", 4, NetworkTopology.fully_connected(4).adjacency_list
        )
        for p in (lossy, reliable):
            p.send_message(0, 1, msg(0, 1, ts=2))
            p.send_message(2, 1, msg(2, 1, ts=1))
        assert lossy.deliver_messages(1, 1) == reliable.deliver_messages(1, 1)
        assert lossy.get_fault_stats() == {"dropped": 0, "delayed": 0}

    def test_drops_are_seeded_and_counted(self):
        a = self._proto(drop_prob=0.5, seed=11)
        b = self._proto(drop_prob=0.5, seed=11)
        for p in (a, b):
            for ts in range(40):
                p.send_message(0, 1, msg(0, 1, ts=ts))
        assert a.dropped_count == b.dropped_count > 0
        assert a.deliver_messages(1, 1) == b.deliver_messages(1, 1)
        # Sent-count includes dropped messages (interface counter).
        assert a.get_message_count(1) == 40
        assert len(a.deliver_messages(1, 1)) == 40 - a.dropped_count

    def test_delayed_messages_arrive_in_later_rounds(self):
        p = self._proto(delay_prob=1.0, max_delay_rounds=2, seed=3)
        for ts in range(10):
            p.send_message(0, 1, msg(0, 1, round=1, ts=ts))
        assert p.delayed_count == 10
        assert p.deliver_messages(1, 1) == []  # nothing on time
        late = [
            m for r in (2, 3) for m in p.deliver_messages(1, r)
        ]
        assert len(late) == 10
        # The message itself still says which round it was decided in.
        assert all(m.round == 1 for m in late)

    def test_invalid_send_still_raises(self):
        from bcg_tpu.comm.lossy_sim import LossySimProtocol

        t = NetworkTopology.ring(4)  # 0 and 2 are not neighbours
        p = LossySimProtocol(4, t.adjacency_list, drop_prob=1.0)
        with pytest.raises(ValueError, match="neighbor"):
            p.send_message(0, 2, msg(0, 2))

    def test_validation(self):
        with pytest.raises(ValueError, match="drop_prob"):
            self._proto(drop_prob=1.5)
        with pytest.raises(ValueError, match="max_delay_rounds"):
            self._proto(max_delay_rounds=0)

    def test_factory_builds_with_config(self):
        from bcg_tpu.comm.lossy_sim import LossySimProtocol

        p = create_protocol(
            "lossy_sim", 3, NetworkTopology.fully_connected(3).adjacency_list,
            config={"drop_prob": 0.25, "delay_prob": 0.1, "seed": 5},
        )
        assert isinstance(p, LossySimProtocol)
        assert p.drop_prob == 0.25 and p.delay_prob == 0.1

    def test_full_game_over_lossy_channel(self):
        """End-to-end: a fake-backend game over a 30%-loss channel runs to
        clean termination (missing proposals degrade to smaller inboxes,
        never crashes) and the network stats report realized channel
        faults."""
        import dataclasses

        from bcg_tpu.config import (
            BCGConfig, CommunicationConfig, EngineConfig, GameConfig, MetricsConfig,
        )
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        cfg = dataclasses.replace(
            BCGConfig(),
            game=GameConfig(num_honest=4, num_byzantine=1, max_rounds=4, seed=2),
            engine=EngineConfig(backend="fake"),
            communication=CommunicationConfig(
                protocol_type="lossy_sim", drop_prob=0.3
            ),
            metrics=MetricsConfig(save_results=False),
        )
        sim = BCGSimulation(config=cfg)
        stats = sim.run()
        assert stats["total_rounds"] >= 1
        net = sim.network.get_network_stats()
        assert "channel_dropped" in net and "channel_delayed" in net
        assert net["channel_dropped"] > 0  # 30% of >=20 sends: P(0)~1e-4

    def test_round_gc_releases_dropped_entries(self):
        p = self._proto(drop_prob=1.0, seed=1)
        for ts in range(8):
            p.send_message(0, 1, msg(0, 1, round=1, ts=ts))
        assert len(p.delivered) == 8
        p.clear_round_buffer(1)
        assert len(p.delivered) == 0  # dropped entries GC'd too

    def test_cli_rejects_channel_knobs_without_lossy(self):
        from bcg_tpu.cli import build_parser, config_from_args

        args = build_parser().parse_args(
            ["--honest", "2", "--backend", "fake", "--drop-prob", "0.3"]
        )
        with pytest.raises(SystemExit, match="lossy_sim"):
            config_from_args(args)

    def test_reset_restores_seed_stream(self):
        p = self._proto(drop_prob=0.5, seed=9)
        for ts in range(20):
            p.send_message(0, 1, msg(0, 1, ts=ts))
        first = p.dropped_count
        p.reset()
        for ts in range(20):
            p.send_message(0, 1, msg(0, 1, ts=ts))
        assert p.dropped_count == first

    def test_snapshot_restore_resumes_exact_fault_stream(self):
        """A restored channel must hold the in-flight delayed messages AND
        continue the fault RNG exactly where the original left off — a
        resumed seeded run is indistinguishable from an uninterrupted
        one."""
        a = self._proto(drop_prob=0.3, delay_prob=0.3, max_delay_rounds=2,
                        seed=13)
        for ts in range(25):
            a.send_message(0, 1, msg(0, 1, round=1, ts=ts))
        blob = a.snapshot()
        import json as _json

        blob = _json.loads(_json.dumps(blob))  # through real JSON
        b = self._proto(drop_prob=0.3, delay_prob=0.3, max_delay_rounds=2,
                        seed=999)  # wrong seed: restore must override
        b.restore(blob)
        assert b.get_fault_stats() == a.get_fault_stats()
        for r in (1, 2, 3):
            assert b.deliver_messages(1, r) == a.deliver_messages(1, r)
        # The continued fault stream matches the uninterrupted original.
        for ts in range(25, 50):
            m = msg(0, 1, round=2, ts=ts)
            a.send_message(0, 1, m)
            b.send_message(0, 1, m)
        assert b.get_fault_stats() == a.get_fault_stats()
        for r in (2, 3, 4):
            assert b.deliver_messages(1, r) == a.deliver_messages(1, r)
        # Dropped-message dedup entries survived the roundtrip too.
        assert len(b.delivered) == len(a.delivered)

    def test_spmd_exchange_rejects_lossy_protocol(self):
        import dataclasses

        from bcg_tpu.config import (
            BCGConfig, CommunicationConfig, EngineConfig, GameConfig,
            MetricsConfig, NetworkConfig,
        )
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        cfg = dataclasses.replace(
            BCGConfig(),
            game=GameConfig(num_honest=2, num_byzantine=0, max_rounds=2),
            engine=EngineConfig(backend="fake"),
            network=NetworkConfig(spmd_exchange=True),
            communication=CommunicationConfig(protocol_type="lossy_sim",
                                              drop_prob=0.5),
            metrics=MetricsConfig(save_results=False),
        )
        with pytest.raises(ValueError, match="spmd_exchange bypasses"):
            BCGSimulation(config=cfg)
