"""Tier-1 wiring for the static analyzer (bcg_tpu.analysis).

Three layers:

1. fixture tests — every rule ID fires on its seeded-violation fixture
   and stays quiet on the clean-idiom twin (``tests/analysis_fixtures/``);
2. repo meta-test — the full-package run is clean modulo the checked-in
   baseline (``lint_baseline.json``), no BCG-ENV-RAW findings are merely
   baselined (the env migration is enforced complete, not parked), and
   every baseline entry still matches a live finding (removing one makes
   its violation reappear — the baseline is load-bearing, not a mute);
3. envflags registry unit tests.
"""

import os
import subprocess
import sys

import pytest

from bcg_tpu.analysis import (
    RULE_IDS,
    analyze_paths,
    load_baseline,
    repo_root,
)
from bcg_tpu.analysis.core import BaselineEntry, ModuleContext
from bcg_tpu.runtime import envflags

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")

# rule ID -> (bad fixture, good fixture), paths relative to FIXTURES.
RULE_FIXTURES = {
    "BCG-HOST-SYNC": ("bad_host_sync.py", "good_host_sync.py"),
    "BCG-JIT-NP": ("bad_jit_np.py", "good_jit_np.py"),
    "BCG-JIT-BRANCH": ("bad_jit_branch.py", "good_jit_branch.py"),
    "BCG-JIT-OUTSHARD": (
        "models/bad_jit_outshard.py", "models/good_jit_outshard.py",
    ),
    "BCG-JIT-DONATE": (
        "models/bad_jit_donate.py", "models/good_jit_donate.py",
    ),
    "BCG-SHARD-AXIS": ("bad_shard_axis.py", "good_shard_axis.py"),
    "BCG-SHARD-DIVISOR": ("bad_shard_divisor.py", "good_shard_divisor.py"),
    "BCG-ENV-RAW": ("bad_env_raw.py", "good_env_raw.py"),
    "BCG-ENV-UNREG": ("bad_env_unreg.py", "good_env_unreg.py"),
    "BCG-EXCEPT-BROAD": ("bad_except_broad.py", "good_except_broad.py"),
    "BCG-MUT-DEFAULT": ("bad_mut_default.py", "good_mut_default.py"),
    "BCG-LOCK-CALL": ("bad_lock_call.py", "good_lock_call.py"),
    "BCG-TIME-WALL": ("bad_time_wall.py", "good_time_wall.py"),
    "BCG-RETRY-SLEEP": ("bad_retry_sleep.py", "good_retry_sleep.py"),
    "BCG-OBS-NAME": ("bad_obs_name.py", "good_obs_name.py"),
    "BCG-OBS-BUCKET": ("bad_obs_bucket.py", "good_obs_bucket.py"),
    "BCG-LOCK-ORDER": ("bad_lock_order.py", "good_lock_order.py"),
    "BCG-LOCK-BLOCK": ("bad_lock_block.py", "good_lock_block.py"),
    "BCG-SHARED-MUT": ("bad_shared_mut.py", "good_shared_mut.py"),
}


def _run_on(path):
    return analyze_paths(paths=[os.path.join(FIXTURES, path)], baseline=None)


class TestRuleFixtures:
    def test_every_rule_has_a_fixture_pair(self):
        assert sorted(RULE_FIXTURES) == sorted(RULE_IDS)
        for bad, good in RULE_FIXTURES.values():
            assert os.path.exists(os.path.join(FIXTURES, bad)), bad
            assert os.path.exists(os.path.join(FIXTURES, good)), good

    @pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
    def test_rule_fires_on_seeded_violation(self, rule_id):
        bad, _ = RULE_FIXTURES[rule_id]
        hits = [f for f in _run_on(bad).findings if f.rule == rule_id]
        assert hits, f"{rule_id} did not fire on {bad}"

    @pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
    def test_rule_quiet_on_clean_idiom(self, rule_id):
        _, good = RULE_FIXTURES[rule_id]
        hits = [f for f in _run_on(good).findings if f.rule == rule_id]
        assert not hits, (
            f"{rule_id} false-positive on {good}: "
            + "; ".join(f.format() for f in hits)
        )

    def test_expected_finding_counts_on_bad_fixtures(self):
        # The bad fixtures seed a known number of violations each —
        # a drop means a detection regression, not just "still fires".
        expected = {
            "BCG-HOST-SYNC": 4,
            "BCG-ENV-RAW": 5,
            "BCG-SHARD-DIVISOR": 3,
            "BCG-JIT-NP": 2,
            "BCG-JIT-BRANCH": 2,
            "BCG-SHARD-AXIS": 2,
            "BCG-ENV-UNREG": 2,
            "BCG-EXCEPT-BROAD": 2,
            "BCG-MUT-DEFAULT": 2,
            "BCG-JIT-OUTSHARD": 2,
            "BCG-JIT-DONATE": 1,
            "BCG-LOCK-CALL": 3,
            "BCG-TIME-WALL": 3,
            "BCG-RETRY-SLEEP": 3,
            "BCG-OBS-NAME": 6,
            "BCG-OBS-BUCKET": 3,
            # bad_lock_order.py seeds ONE two-lock inversion (the PR 15
            # device-lock-swap shape) between two thread roots.
            "BCG-LOCK-ORDER": 1,
            "BCG-LOCK-BLOCK": 3,
            "BCG-SHARED-MUT": 1,
        }
        for rule_id, want in expected.items():
            bad, _ = RULE_FIXTURES[rule_id]
            got = [f for f in _run_on(bad).findings if f.rule == rule_id]
            assert len(got) == want, (
                f"{rule_id}: expected {want} findings on {bad}, got "
                f"{len(got)}: " + "; ".join(f.format() for f in got)
            )

    def test_inline_suppression(self, tmp_path):
        src = (
            "def f(x, acc=[]):  # lint: ignore[BCG-MUT-DEFAULT]\n"
            "    return acc\n"
            "def g(x, acc=[]):\n"
            "    return acc\n"
        )
        p = tmp_path / "snippet.py"
        p.write_text(src)
        findings = analyze_paths(paths=[str(p)], baseline=None).findings
        muts = [f for f in findings if f.rule == "BCG-MUT-DEFAULT"]
        assert len(muts) == 1 and muts[0].line == 3


@pytest.fixture(scope="module")
def full_tree_raw():
    """ONE baseline-free full-tree analysis shared by the repo
    meta-tests — the tree walk (parse + whole-program index + rules) is
    the expensive part; baseline application is a pure cheap function
    (core.apply_baseline) each test replays as needed."""
    return analyze_paths(baseline=None)


class TestRepoClean:
    def test_repo_is_clean_modulo_baseline(self, full_tree_raw):
        from bcg_tpu.analysis.core import apply_baseline

        assert not full_tree_raw.parse_errors, full_tree_raw.parse_errors
        findings, _, unused = apply_baseline(
            full_tree_raw.findings, load_baseline()
        )
        assert not findings, "\n".join(f.format() for f in findings)

    def test_env_migration_complete_not_baselined(self, full_tree_raw):
        # The env-flag registry migration is a hard guarantee: no raw
        # read of a registered name may even be PARKED in the baseline.
        env_raw = [
            f for f in full_tree_raw.findings if f.rule == "BCG-ENV-RAW"
        ]
        assert not env_raw, "\n".join(f.format() for f in env_raw)

    def test_baseline_entries_are_load_bearing(self, full_tree_raw):
        from bcg_tpu.analysis.core import apply_baseline

        baseline = load_baseline()
        assert baseline, "baseline file missing or empty"
        # Without the baseline every entry's violation must reappear.
        raw = full_tree_raw
        live_keys = {f.key() for f in raw.findings}
        for entry in baseline:
            assert entry.key() in live_keys, (
                f"baseline entry no longer matches any finding (fixed? "
                f"delete it): {entry.rule} {entry.path} {entry.content!r}"
            )
        # And removing any one entry resurfaces exactly its findings.
        # apply_baseline is the same matcher analyze_paths uses, so one
        # analysis run backs every removal replay (the tree walk is the
        # expensive part, the matching is not).
        for removed in baseline:
            remaining = [e for e in baseline if e is not removed]
            resurfaced, _, _ = apply_baseline(raw.findings, remaining)
            assert any(
                f.key() == removed.key() for f in resurfaced
            ), f"removing baseline entry had no effect: {removed.rule}"

    def test_every_baseline_entry_has_a_reason(self):
        for entry in load_baseline():
            assert entry.reason.strip(), (
                f"baseline entry without justification: "
                f"{entry.rule} {entry.path}"
            )

    def test_baseline_count_caps_identical_lines(self, tmp_path):
        # Two textually identical violations share a baseline key; the
        # entry's count bounds how many it parks — a third copy added
        # later must resurface, not ride the existing entry.
        src = (
            "def f():\n    try:\n        risky()\n"
            "    except Exception:\n        pass\n"
            "def g():\n    try:\n        risky()\n"
            "    except Exception:\n        pass\n"
        )
        p = tmp_path / "dup.py"
        p.write_text(src)
        probe = analyze_paths(paths=[str(p)], baseline=None).findings
        assert len(probe) == 2 and len({f.key() for f in probe}) == 1
        entry = BaselineEntry(
            rule=probe[0].rule, path=probe[0].path,
            content=probe[0].content, reason="test", count=1,
        )
        capped = analyze_paths(paths=[str(p)], baseline=[entry])
        assert len(capped.findings) == 1 and len(capped.baselined) == 1
        entry.count = 2
        full = analyze_paths(paths=[str(p)], baseline=[entry])
        assert not full.findings and len(full.baselined) == 2

    def test_unknown_baseline_entry_is_reported_unused(self):
        from bcg_tpu.analysis.core import apply_baseline

        fake = BaselineEntry(
            rule="BCG-MUT-DEFAULT",
            path="bcg_tpu/no/such/file.py",
            content="def f(x=[]):",
            reason="synthetic",
        )
        _, _, unused = apply_baseline([], [fake])
        assert fake in unused

    def test_scan_scope_covers_scripts_and_bench(self):
        # ISSUE-6 satellite: the ENV-RAW migration guarantee extends to
        # scripts/ and bench.py — the default scan scope must include
        # them, or a raw read added to a script escapes the whole suite.
        from bcg_tpu.analysis.core import default_paths, iter_python_files

        paths = default_paths()
        names = {os.path.basename(p.rstrip(os.sep)) for p in paths}
        assert "scripts" in names and "bench.py" in names
        scanned = {
            os.path.relpath(f, repo_root()).replace(os.sep, "/")
            for f in iter_python_files(paths)
        }
        assert "scripts/hw_queue_report.py" in scanned
        assert "scripts/scale_sweep.py" in scanned
        assert "scripts/perf_gate.py" in scanned
        assert "scripts/microbench_prefill.py" in scanned

    def test_env_raw_fires_inside_scripts_scope(self, tmp_path):
        # A seeded raw read placed under a scripts-shaped path is caught
        # by the same analyze_paths call the repo meta-test uses.
        scripts_dir = tmp_path / "scripts"
        scripts_dir.mkdir()
        (scripts_dir / "probe.py").write_text(
            "import os\nMODE = os.environ.get('BCG_TPU_TIMING')\n"
        )
        findings = analyze_paths(paths=[str(scripts_dir)], baseline=None).findings
        assert any(f.rule == "BCG-ENV-RAW" for f in findings)

    def test_cli_exits_zero_on_repo(self):
        proc = subprocess.run(
            [sys.executable, "-m", "bcg_tpu.analysis"],
            cwd=repo_root(), capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_lint_script_diff_mode_runs(self):
        proc = subprocess.run(
            [sys.executable, os.path.join("scripts", "lint.py"), "--diff"],
            cwd=repo_root(), capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


class TestWholeProgram:
    """The interprocedural pass (bcg_tpu/analysis/interproc.py): cross-
    module jit-region lift, thread-root × lock machinery, and the CLI
    surfaces the concurrency rules ride on."""

    def test_cross_module_jit_lift_reaches_helper(self):
        # entry.py jits a caller; the np.asarray violation lives in
        # helper.py, which has no jit of its own — only the whole-
        # program lift can attribute the traced region across the
        # module boundary.  Exactly one finding, in the HELPER module,
        # and the jit-unreachable sibling function stays quiet.
        fix = os.path.join(FIXTURES, "xmod")
        findings = analyze_paths(paths=[fix], baseline=None).findings
        hs = [f for f in findings if f.rule == "BCG-HOST-SYNC"]
        assert len(hs) == 1, "\n".join(f.format() for f in findings)
        assert hs[0].path.endswith("xmod/helper.py")
        assert "np.asarray" in hs[0].content

    def test_helper_alone_is_clean(self):
        # Same helper analyzed WITHOUT its jitting caller in view: no
        # jit region reaches it, so the host-sync rule must stay quiet
        # — the cross-module finding above is the lift's work, not a
        # per-module rule change.
        helper = os.path.join(FIXTURES, "xmod", "helper.py")
        findings = analyze_paths(paths=[helper], baseline=None).findings
        assert not findings, "\n".join(f.format() for f in findings)

    def test_new_rule_baseline_entries_name_their_guard(self):
        # A concurrency suppression that does not say WHICH lock (or
        # which thread-confinement argument) makes the site safe is
        # unreviewable prose; require the rationale to name it.
        import re

        guard = re.compile(
            r"lock|cond|thread|confin|single|serializ|GIL", re.IGNORECASE
        )
        new_rules = {"BCG-LOCK-ORDER", "BCG-LOCK-BLOCK", "BCG-SHARED-MUT"}
        checked = 0
        for entry in load_baseline():
            if entry.rule not in new_rules:
                continue
            checked += 1
            assert guard.search(entry.reason), (
                f"{entry.rule} baseline entry for {entry.path} must name "
                f"the guarding lock or thread-confinement rationale: "
                f"{entry.reason!r}"
            )
        assert checked, "expected concurrency-rule baseline entries"

    def test_json_emits_finding_status(self):
        # Machine-readable output carries each finding's disposition so
        # CI tooling never joins the findings/baselined lists by hand.
        import json as json_mod

        bad = os.path.join(FIXTURES, "bad_lock_block.py")
        proc = subprocess.run(
            [sys.executable, "-m", "bcg_tpu.analysis",
             "--no-baseline", "--json", bad],
            cwd=repo_root(), capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        payload = json_mod.loads(proc.stdout)
        blocks = [
            f for f in payload["findings"] if f["rule"] == "BCG-LOCK-BLOCK"
        ]
        assert len(blocks) == 3
        for f in blocks:
            assert f["status"] == "new"
            assert {"rule", "path", "line", "message"} <= set(f)
        # Baselined findings carry the other disposition.
        proc = subprocess.run(
            [sys.executable, "-m", "bcg_tpu.analysis", "--json",
             os.path.join("bcg_tpu", "engine", "collective.py")],
            cwd=repo_root(), capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json_mod.loads(proc.stdout)
        assert payload["baselined"], "expected baselined collective findings"
        assert all(f["status"] == "baselined" for f in payload["baselined"])

    def test_lint_diff_flags_new_violation(self):
        # Regression gate for the pre-commit path: an untracked file
        # seeding a violation must flip scripts/lint.py --diff to exit
        # code 1 and be named in the JSON payload as NEW debt.
        import json as json_mod

        probe = os.path.join(repo_root(), "scripts", "_lint_diff_probe.py")
        try:
            with open(probe, "w", encoding="utf-8") as f:
                f.write(
                    "import os\n"
                    "MODE = os.environ.get('BCG_TPU_TIMING')\n"
                )
            proc = subprocess.run(
                [sys.executable, os.path.join("scripts", "lint.py"),
                 "--diff", "--json"],
                cwd=repo_root(), capture_output=True, text=True, timeout=120,
            )
            assert proc.returncode == 1, proc.stdout + proc.stderr
            payload = json_mod.loads(proc.stdout)
            hits = [
                f for f in payload["findings"]
                if f["path"].endswith("_lint_diff_probe.py")
            ]
            assert hits and all(f["status"] == "new" for f in hits)
        finally:
            if os.path.exists(probe):
                os.remove(probe)

    def test_locks_report_mode(self):
        proc = subprocess.run(
            [sys.executable, "-m", "bcg_tpu.analysis", "--locks"],
            cwd=repo_root(), capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "thread roots:" in proc.stdout
        assert "lock-order edges" in proc.stdout
        # Known roots and locks from the real tree anchor the report.
        assert "bcg-sweep-*" in proc.stdout
        assert "Scheduler._device_lock" in proc.stdout

    def test_lock_order_quiet_without_second_root(self):
        # The deadlock rule needs two independently spawned roots (or
        # one pooled root) covering different cycle edges — inverted
        # acquisition reached from a single thread cannot deadlock by
        # itself and must not fire.
        src = (
            "import threading\n"
            "class P:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "        threading.Thread(target=self._one).start()\n"
            "    def _one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        )
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "single_root.py")
            with open(p, "w", encoding="utf-8") as f:
                f.write(src)
            findings = analyze_paths(paths=[p], baseline=None).findings
            orders = [f for f in findings if f.rule == "BCG-LOCK-ORDER"]
            assert not orders, "\n".join(f.format() for f in orders)


class TestJitRegionResolution:
    def _ctx(self, tmp_path, src):
        p = tmp_path / "m.py"
        p.write_text(src)
        return ModuleContext(str(p), "m.py", src)

    def test_transitive_callee_is_a_region(self, tmp_path):
        ctx = self._ctx(
            tmp_path,
            "import jax\n"
            "def helper(x):\n"
            "    return x\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return helper(x)\n"
            "def unrelated(x):\n"
            "    return x\n",
        )
        names = {fn.name for fn in ctx.jit_regions}
        assert names == {"helper", "f"}

    def test_lax_while_body_is_a_region(self, tmp_path):
        ctx = self._ctx(
            tmp_path,
            "import jax\n"
            "def run(c):\n"
            "    def body(carry):\n"
            "        return carry\n"
            "    def cond(carry):\n"
            "        return True\n"
            "    return jax.lax.while_loop(cond, body, c)\n",
        )
        names = {fn.name for fn in ctx.jit_regions}
        assert names == {"body", "cond"}

    def test_lambda_lax_operand_is_a_region(self, tmp_path):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def run(c):\n"
            "    return jax.lax.while_loop(\n"
            "        lambda s: s < 3, lambda s: np.asarray(s), c)\n"
        )
        p = tmp_path / "m.py"
        p.write_text(src)
        findings = analyze_paths(paths=[str(p)], baseline=None).findings
        assert any(f.rule == "BCG-HOST-SYNC" for f in findings), findings

    def test_tree_map_function_is_not_a_region(self, tmp_path):
        # jax.tree.map applies its function EAGERLY on host —
        # convert-before-device_put must not be flagged as a jit region.
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def convert(leaf):\n"
            "    return np.asarray(leaf)\n"
            "def load(tree):\n"
            "    return jax.tree.map(convert, tree)\n"
        )
        p = tmp_path / "m.py"
        p.write_text(src)
        findings = analyze_paths(paths=[str(p)], baseline=None).findings
        assert not findings, [f.format() for f in findings]


class TestEnvFlags:
    def test_parse_bool_semantics(self):
        assert envflags.parse_bool(None, True) is True
        assert envflags.parse_bool("", False) is False
        for falsy in ("0", "false", "No", " OFF "):
            assert envflags.parse_bool(falsy, True) is False
        for truthy in ("1", "true", "anything"):
            assert envflags.parse_bool(truthy, False) is True

    def test_read_at_call_time(self, monkeypatch):
        monkeypatch.delenv("BCG_TPU_TIMING", raising=False)
        assert envflags.get_bool("BCG_TPU_TIMING") is False
        monkeypatch.setenv("BCG_TPU_TIMING", "1")
        assert envflags.get_bool("BCG_TPU_TIMING") is True

    def test_get_int_fallback_on_garbage(self, monkeypatch):
        monkeypatch.setenv("BENCH_ROUNDS", "not-a-number")
        assert envflags.get_int("BENCH_ROUNDS") == 3
        monkeypatch.setenv("BENCH_ROUNDS", "7")
        assert envflags.get_int("BENCH_ROUNDS") == 7

    def test_default_override(self, monkeypatch):
        monkeypatch.delenv("BENCH_PREFILL_CHUNK", raising=False)
        assert envflags.get_int("BENCH_PREFILL_CHUNK", 512) == 512
        monkeypatch.setenv("BENCH_PREFILL_CHUNK", "128")
        assert envflags.get_int("BENCH_PREFILL_CHUNK", 512) == 128

    def test_unregistered_name_raises(self):
        with pytest.raises(KeyError):
            envflags.get_bool("BCG_TPU_NO_SUCH_FLAG")
        with pytest.raises(KeyError):
            envflags.is_set("TOTALLY_UNKNOWN")

    def test_kind_mismatch_raises(self):
        with pytest.raises(TypeError):
            envflags.get_int("BCG_TPU_TIMING")
        with pytest.raises(TypeError):
            envflags.get_bool("BENCH_MODEL")

    def test_is_set(self, monkeypatch):
        monkeypatch.delenv("BENCH_QUANTIZATION", raising=False)
        assert envflags.is_set("BENCH_QUANTIZATION") is False
        monkeypatch.setenv("BENCH_QUANTIZATION", "int4")
        assert envflags.is_set("BENCH_QUANTIZATION") is True

    def test_config_env_flag_shim(self, monkeypatch):
        from bcg_tpu.config import env_flag

        monkeypatch.setenv("BCG_TPU_FINE_SUFFIX", "off")
        assert env_flag("BCG_TPU_FINE_SUFFIX") is False
        monkeypatch.setenv("BCG_TPU_FINE_SUFFIX", "1")
        assert env_flag("BCG_TPU_FINE_SUFFIX") is True

    def test_markdown_table_covers_registry(self):
        table = envflags.markdown_table()
        for name in envflags.REGISTRY:
            assert f"`{name}`" in table

    def test_readme_flag_table_matches_registry(self):
        # The README table is pasted from `python -m
        # bcg_tpu.runtime.envflags` — registering a new flag must force
        # a regeneration, or the "derived from the registry" claim rots.
        readme = open(os.path.join(repo_root(), "README.md")).read()
        assert envflags.markdown_table() in readme, (
            "README env-flag table is stale — re-run "
            "`python -m bcg_tpu.runtime.envflags` and paste the output"
        )
