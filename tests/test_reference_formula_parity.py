"""Mechanical formula parity against the reference implementation.

The round-3 verdict's residual doubt: our fake-policy sweeps prove the
statistics pipeline *runs*, not that it computes the same numbers the
reference would.  Both statistics layers are pure Python dict-in /
dict-out (`/root/reference/byzantine_consensus_game/byzantine_consensus.py:544-839`
vs ``bcg_tpu/game/statistics.py``), so parity can be pinned exactly:

1. run a real bcg_tpu simulation (orchestrator + fake backend, seeded),
   recording every game mutation (proposals, reasoning, votes) as a
   trace;
2. replay the identical trace into the reference's own
   ``ByzantineConsensusGame`` (imported from /root/reference at test
   time — never copied), with its random agent init overwritten by our
   game's seeded init;
3. assert ``get_statistics()`` equality key by key, across every
   outcome-taxonomy region the scripted policies reach (valid /
   invalid / timeout, with and without Byzantine agents).

Skipped when the reference checkout is absent (the test imports it; the
shipped package never does).
"""

from __future__ import annotations

import importlib.util
import pathlib
import sys

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.config import BCGConfig, EngineConfig
from bcg_tpu.game import ByzantineConsensusGame

REF_DIR = pathlib.Path("/root/reference/byzantine_consensus_game")

pytestmark = pytest.mark.skipif(
    not REF_DIR.is_dir(), reason="reference checkout not available"
)


# --------------------------------------------------------------- loader

def _load_reference_module():
    """Import the reference's byzantine_consensus.py in isolation.

    It does ``from config import BCG_CONFIG`` at module level, so its
    own config.py must transiently occupy sys.modules["config"]; both
    entries are restored/removed afterwards so the suite's import
    space stays clean.
    """
    saved_config = sys.modules.get("config")
    spec_c = importlib.util.spec_from_file_location("config", REF_DIR / "config.py")
    cfg = importlib.util.module_from_spec(spec_c)
    sys.modules["config"] = cfg
    try:
        spec_c.loader.exec_module(cfg)
        spec_b = importlib.util.spec_from_file_location(
            "_bcg_reference_game", REF_DIR / "byzantine_consensus.py"
        )
        mod = importlib.util.module_from_spec(spec_b)
        spec_b.loader.exec_module(mod)
        return mod
    finally:
        if saved_config is not None:
            sys.modules["config"] = saved_config
        else:
            sys.modules.pop("config", None)


@pytest.fixture(scope="module")
def ref():
    return _load_reference_module()


# ------------------------------------------------------------ recording

class RecordingGame(ByzantineConsensusGame):
    """Our game, with every mutating call journaled for replay."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.trace = []
        # The seeded initial assignment, captured before any round runs.
        self.initial_agents = {
            aid: (st.is_byzantine, st.initial_value)
            for aid, st in self.agents.items()
        }

    def update_agent_proposal(self, agent_id, new_value):
        self.trace.append(("update_agent_proposal", (agent_id, new_value)))
        return super().update_agent_proposal(agent_id, new_value)

    def store_round_reasoning(self, reasoning):
        self.trace.append(("store_round_reasoning", (dict(reasoning),)))
        return super().store_round_reasoning(reasoning)

    def advance_round(self, agent_votes=None):
        votes = None if agent_votes is None else dict(agent_votes)
        self.trace.append(("advance_round", (votes,)))
        return super().advance_round(agent_votes)


_TRACE_CACHE: dict = {}


def _run_traced(policy, honest, byz, rounds, seed, monkeypatch):
    """Run a full bcg_tpu simulation with the game journaled (cached —
    both tests below walk the same CASES matrix)."""
    key = (policy, honest, byz, rounds, seed)
    if key in _TRACE_CACHE:
        return _TRACE_CACHE[key]
    import bcg_tpu.runtime.orchestrator as orch

    captured = {}

    def factory(*args, **kwargs):
        game = RecordingGame(*args, **kwargs)
        captured["game"] = game
        return game

    monkeypatch.setattr(orch, "ByzantineConsensusGame", factory)
    import dataclasses

    cfg = dataclasses.replace(
        BCGConfig(), engine=EngineConfig(backend="fake", fake_policy=policy)
    )
    run_simulation(
        n_agents=honest + byz,
        byzantine_count=byz,
        max_rounds=rounds,
        backend="fake",
        seed=seed,
        config=cfg,
    )
    _TRACE_CACHE[key] = captured["game"]
    return captured["game"]


def _replay_into_reference(ref, game):
    """Build a reference game mirroring our seeded init, replay the trace."""
    ref_game = ref.ByzantineConsensusGame(
        num_honest=game.num_honest,
        num_byzantine=game.num_byzantine,
        value_range=tuple(game.value_range),
        consensus_threshold=game.consensus_threshold,
        max_rounds=game.max_rounds,
    )
    # Replace the reference's unseeded random init with OUR seeded one
    # (same ids, roles, initial values), exactly as its
    # _initialize_agents would have produced them (reference
    # byzantine_consensus.py:118-147: Byzantine agents start with
    # None current/proposed values).
    ref_game.agents = {
        aid: ref.AgentState(
            agent_id=aid,
            is_byzantine=is_byz,
            initial_value=init,
            current_value=init,
            proposed_value=init,
        )
        for aid, (is_byz, init) in game.initial_agents.items()
    }
    for method, args in game.trace:
        getattr(ref_game, method)(*args)
    return ref_game


# ----------------------------------------------------------- comparison

def _assert_equivalent(path, ours, theirs):
    if isinstance(theirs, dict):
        assert isinstance(ours, dict), path
        assert set(ours.keys()) == set(theirs.keys()), (
            f"{path}: key sets differ: only-ours="
            f"{set(ours) - set(theirs)} only-reference={set(theirs) - set(ours)}"
        )
        for k in theirs:
            _assert_equivalent(f"{path}.{k}", ours[k], theirs[k])
    elif isinstance(theirs, (list, tuple)):
        assert isinstance(ours, (list, tuple)), path
        assert len(ours) == len(theirs), f"{path}: length {len(ours)} != {len(theirs)}"
        for i, (a, b) in enumerate(zip(ours, theirs)):
            _assert_equivalent(f"{path}[{i}]", a, b)
    elif isinstance(theirs, float):
        assert ours == pytest.approx(theirs, rel=1e-12, abs=1e-12), (
            f"{path}: {ours!r} != {theirs!r}"
        )
    else:
        assert ours == theirs, f"{path}: {ours!r} != {theirs!r}"


# A config per outcome-taxonomy region (PARITY.md fake-policy table):
# silent -> valid consensus, oscillate -> invalid (vote w/o consensus),
# disrupt -> timeout, plus honest-only valid (median) and timeout
# (stubborn) paths, and an awareness-keyword-bearing default run.
CASES = [
    ("consensus", 4, 0, 6, 0),
    ("median", 5, 0, 6, 7),
    ("stubborn", 4, 0, 5, 3),
    ("mixed:consensus:silent", 6, 2, 8, 11),
    ("mixed:consensus:oscillate", 6, 2, 8, 5),
    ("mixed:consensus:disrupt", 6, 2, 6, 2),
    ("mixed:consensus:mimic", 8, 2, 8, 13),
    ("mixed:stubborn:oscillate", 4, 2, 5, 17),
]


@pytest.mark.parametrize("policy,honest,byz,rounds,seed", CASES)
def test_statistics_formula_parity(ref, monkeypatch, policy, honest, byz, rounds, seed):
    game = _run_traced(policy, honest, byz, rounds, seed, monkeypatch)
    assert game.trace, "simulation produced an empty trace"
    ref_game = _replay_into_reference(ref, game)

    # The replayed game must terminate identically before statistics
    # can be compared meaningfully.
    assert ref_game.game_over == game.game_over
    assert ref_game.termination_reason == game.termination_reason

    ours = game.get_statistics()
    theirs = ref_game.get_statistics()
    _assert_equivalent("statistics", ours, theirs)


def test_traces_cover_all_termination_reasons(monkeypatch):
    """The case matrix must keep exercising every taxonomy branch —
    if a policy change collapses the regions, this fails loudly
    instead of silently weakening the parity claim."""
    reasons = set()
    outcomes = set()
    for policy, honest, byz, rounds, seed in CASES:
        game = _run_traced(policy, honest, byz, rounds, seed, monkeypatch)
        reasons.add(game.termination_reason)
        outcomes.add(game.get_statistics()["consensus_outcome"])
    assert "vote_with_consensus" in reasons
    assert "max_rounds" in reasons
    assert {"valid", "timeout"} <= outcomes
    # Value-flipping adversaries force premature termination without
    # valid consensus (invalid or none).
    assert outcomes & {"invalid", "none"}
