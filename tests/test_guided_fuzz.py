"""Property fuzz for the schema -> byte-DFA compiler (bcg_tpu/guided/).

For randomly generated schemas from the supported subset, any random walk
through the DFA that lands on an accepting state must produce a string
that (a) json-parses and (b) satisfies the schema's constraints.  This is
the compiler-level analogue of the engine's guaranteed-parse property and
catches composition bugs (optional runs, enum + range + minLength
interactions) that the hand-written cases in test_guided.py cannot
enumerate.
"""

import json
import random

import numpy as np
import pytest

from bcg_tpu.guided import ast_to_dfa, schema_to_ast


def schema_to_dfa(schema):
    return ast_to_dfa(schema_to_ast(schema))


def _bfs_dist(dfa):
    """Min #bytes from each state to an accepting state (inf if none)."""
    n = dfa.transitions.shape[0]
    INF = 1 << 30
    dist = np.full(n, INF, dtype=np.int64)
    dist[dfa.accepting] = 0
    frontier = list(np.nonzero(dfa.accepting)[0])
    # Reverse-BFS over the transition relation.
    preds = [[] for _ in range(n)]
    for s in range(n):
        for t in set(int(x) for x in dfa.transitions[s] if x >= 0):
            preds[t].append(s)
    while frontier:
        nxt = []
        for t in frontier:
            for s in preds[t]:
                if dist[s] > dist[t] + 1:
                    dist[s] = dist[t] + 1
                    nxt.append(s)
        frontier = nxt
    return dist


def _random_pattern(rng: random.Random) -> str:
    """A random pattern from the supported subset, chosen so the SAME
    string is a valid Python regex with identical semantics (the fuzz
    oracle is ``re.fullmatch``)."""
    pieces = []
    for _ in range(rng.randint(1, 3)):
        kind = rng.choice(["lit", "esc", "digits", "class", "alt"])
        if kind == "lit":
            pieces.append(rng.choice(["id", "AB", "x9"]))
        elif kind == "esc":
            # Identity escapes of printable punctuation (round-4 widening).
            pieces.append(rng.choice([r"\!", r"\@", r"\#", r"\~", r"\%"]))
        elif kind == "digits":
            m = rng.randint(1, 3)
            pieces.append(rf"\d{{{m}}}")
        elif kind == "class":
            pieces.append(rng.choice(["[a-c]", "[xy]", "[0-4]"]))
            if rng.random() < 0.5:
                pieces.append(rng.choice(["+", "?"]))
        else:
            pieces.append(rng.choice(["(a|bc)", "(?:x|yz)"]))
    return "".join(pieces)


def _random_schema(rng: random.Random):
    props = {}
    required = []
    for i in range(rng.randint(1, 4)):
        name = f"f{i}"
        kind = rng.choice([
            "string", "int", "enum", "anyof", "bool",
            "pattern", "floatbounds", "exclusive", "array",
        ])
        if kind == "string":
            lo = rng.choice([0, 1, 3])
            hi = rng.choice([lo + 2, lo + 8])
            props[name] = {"type": "string", "minLength": lo, "maxLength": hi}
        elif kind == "pattern":
            props[name] = {"type": "string", "pattern": _random_pattern(rng)}
        elif kind == "floatbounds":
            # Non-integral inclusive bounds (round-4 ceil/floor fix).
            lo = rng.randint(-20, 10) + rng.choice([0.5, 0.25])
            hi = lo + rng.randint(1, 40)
            props[name] = {"type": "integer", "minimum": lo, "maximum": hi}
        elif kind == "exclusive":
            lo = rng.randint(-20, 10)
            props[name] = {
                "type": "integer",
                "exclusiveMinimum": lo,
                "exclusiveMaximum": lo + rng.randint(2, 40),
            }
        elif kind == "array":
            mn = rng.randint(0, 2)
            props[name] = {
                "type": "array",
                "items": {"type": "integer", "minimum": 0, "maximum": 9},
                "minItems": mn,
                "maxItems": mn + rng.randint(0, 3),
            }
        elif kind == "int":
            lo = rng.randint(-30, 20)
            hi = lo + rng.randint(0, 60)
            props[name] = {"type": "integer", "minimum": lo, "maximum": hi}
        elif kind == "enum":
            opts = rng.sample(["stop", "continue", "abstain", "wait", "go"],
                              rng.randint(1, 3))
            props[name] = {"type": "string", "enum": opts}
        elif kind == "anyof":
            props[name] = {"anyOf": [
                {"type": "integer", "minimum": 0, "maximum": 50},
                {"type": "string", "enum": ["abstain"]},
            ]}
        else:
            props[name] = {"type": "boolean"}
        if rng.random() < 0.7:
            required.append(name)
    return {
        "type": "object",
        "properties": props,
        "required": required,
        "additionalProperties": False,
    }


def _walk(dfa, dist, rng: random.Random, budget: int = 220) -> str:
    """Random guided walk: only bytes that keep acceptance reachable
    within the remaining budget (the engine's mask, at byte level)."""
    out = bytearray()
    state = 0
    while True:
        if dfa.accepting[state] and (rng.random() < 0.25 or budget <= 1):
            return out.decode("utf-8", errors="strict")
        options = [
            b for b in range(256)
            if dfa.transitions[state, b] >= 0
            and dist[dfa.transitions[state, b]] <= budget - 1
        ]
        if not options:
            assert dfa.accepting[state], "walk stuck at non-accepting state"
            return out.decode("utf-8", errors="strict")
        b = rng.choice(options)
        out.append(b)
        state = int(dfa.transitions[state, b])
        budget -= 1


def _validate(obj, schema):
    assert isinstance(obj, dict)
    props = schema["properties"]
    for key in schema["required"]:
        assert key in obj, f"missing required {key}"
    for key, val in obj.items():
        assert key in props, f"unexpected key {key}"
        sub = props[key]
        if "anyOf" in sub:
            ok = False
            for alt in sub["anyOf"]:
                try:
                    _validate_leaf(val, alt)
                    ok = True
                    break
                except AssertionError:
                    continue
            assert ok, f"{key}={val!r} matches no anyOf branch"
        else:
            _validate_leaf(val, sub)


def _validate_leaf(val, sub):
    import re

    t = sub.get("type")
    if t == "string":
        assert isinstance(val, str)
        if "enum" in sub:
            assert val in sub["enum"], (val, sub["enum"])
        if "pattern" in sub:
            assert re.fullmatch(sub["pattern"], val), (sub["pattern"], val)
        if "minLength" in sub:
            assert len(val) >= sub["minLength"]
        if "maxLength" in sub:
            assert len(val) <= sub["maxLength"]
    elif t == "integer":
        assert isinstance(val, int) and not isinstance(val, bool)
        # Float bounds compare directly: an int >= 4.5 iff it is >= 5,
        # which is exactly the JSON-schema semantics the compiler must
        # realize via ceil/floor.
        if "minimum" in sub:
            assert val >= sub["minimum"], (val, sub)
        if "maximum" in sub:
            assert val <= sub["maximum"], (val, sub)
        if "exclusiveMinimum" in sub:
            assert val > sub["exclusiveMinimum"], (val, sub)
        if "exclusiveMaximum" in sub:
            assert val < sub["exclusiveMaximum"], (val, sub)
    elif t == "array":
        assert isinstance(val, list)
        assert len(val) >= sub.get("minItems", 0), (val, sub)
        if "maxItems" in sub:
            assert len(val) <= sub["maxItems"], (val, sub)
        for item in val:
            _validate_leaf(item, sub["items"])
    elif t == "boolean":
        assert isinstance(val, bool)
    else:
        raise AssertionError(f"unknown leaf {sub}")


@pytest.mark.parametrize("seed", range(60))
def test_random_schema_walks_always_validate(seed):
    rng = random.Random(seed)
    schema = _random_schema(rng)
    dfa = schema_to_dfa(schema)
    dist = _bfs_dist(dfa)
    assert dist[0] < (1 << 30), "accepting state unreachable from start"
    for _ in range(8):
        text = _walk(dfa, dist, rng)
        obj = json.loads(text)  # (a) always parses
        _validate(obj, schema)  # (b) always satisfies the schema
