"""HLO kernel census (bcg_tpu/obs/hlo.py + scripts/hlo_census.py) and
its tier-1 drift gate against hlo_baseline.json.

Layers:

1. parser unit tests — kernel-launching-computation selection (entry +
   while body/cond; fusion internals excluded) on handwritten HLO;
2. the hermetic census scenario (module-scoped: three tiny CPU engines,
   one per decode-loop family) matches the checked-in baseline exactly
   — the ROADMAP-item-5 guardrail: a change that adds a kernel to the
   decode step fails HERE, not on hardware months later;
3. the baseline is load-bearing: every entry is exercised, removing an
   entry resurfaces its finding, every entry carries a reason.
"""

import importlib.util
import json
import os

import pytest

from bcg_tpu.obs import counters as obs_counters, hlo as obs_hlo
from bcg_tpu.obs.hlo import COUNT_METRICS, census_from_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script():
    path = os.path.join(REPO, "scripts", "hlo_census.py")
    spec = importlib.util.spec_from_file_location("hlo_census", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_HLO = """\
HloModule jit_loop, entry_computation_layout={(f32[8,8]{1,0})->f32[8,8]{1,0}}

%fused_computation (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8] parameter(0)
  %t = f32[8,8] tanh(f32[8,8] %p0)
  ROOT %g = f32[8,8] gather(f32[8,8] %t, f32[8,8] %t)
}

%region_body (arg: (s32[], f32[8,8])) -> (s32[], f32[8,8]) {
  %arg = (s32[], f32[8,8]) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[8,8]) %arg), index=0
  %x = f32[8,8] get-tuple-element((s32[], f32[8,8]) %arg), index=1
  %d = f32[8,8] dot(f32[8,8] %x, f32[8,8] %x)
  %f = f32[8,8] fusion(f32[8,8] %d), kind=kLoop, calls=%fused_computation
  %ar = f32[8,8] all-reduce(f32[8,8] %f), replica_groups={}
  ROOT %tup = (s32[], f32[8,8]) tuple(s32[] %i, f32[8,8] %ar)
}

%region_cond (arg: (s32[], f32[8,8])) -> pred[] {
  %arg = (s32[], f32[8,8]) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[8,8]) %arg), index=0
  %k = s32[] constant(4)
  ROOT %lt = pred[] compare(s32[] %i, s32[] %k), direction=LT
}

ENTRY %main (p: f32[8,8]) -> f32[8,8] {
  %p = f32[8,8] parameter(0)
  %z = s32[] constant(0)
  %init = (s32[], f32[8,8]) tuple(s32[] %z, f32[8,8] %p)
  %w = (s32[], f32[8,8]) while((s32[], f32[8,8]) %init), condition=%region_cond, body=%region_body
  ROOT %out = f32[8,8] get-tuple-element((s32[], f32[8,8]) %w), index=1
}
"""


class TestParser:
    def test_kernel_launching_selection(self):
        c = census_from_text(_HLO)
        # Entry (5 ops incl. the tuple-typed while) + body (7) + cond (4);
        # the fused computation's 3 internal ops are excluded.
        assert c["whiles"] == 1
        assert c["total_ops"] == 16
        assert c["fusions"] == 1          # the body's fusion instruction
        assert c["collectives"] == 1      # all-reduce in the body
        assert c["dots"] == 1
        # gather lives INSIDE the fusion: not a launched kernel.
        assert c["gathers"] == 0

    def test_step_family_is_while_bodies_only(self):
        c = census_from_text(_HLO)
        assert c["step_ops"] == 7
        assert c["step_fusions"] == 1
        assert c["step_dots"] == 1
        assert c["step_collectives"] == 1

    def test_empty_text(self):
        c = census_from_text("")
        assert c["total_ops"] == 0 and c["step_ops"] == 0


@pytest.fixture(scope="module")
def scenario():
    """The real census scenario, once per module (~12 s: three tiny
    engines, one guided call each)."""
    mod = _load_script()
    obs_hlo.reset()
    obs_hlo.enable(True)
    census = mod.run_scenario()
    yield mod, census
    obs_hlo.reset()


class TestCensusScenario:
    def test_all_loop_families_recorded(self, scenario):
        _, census = scenario
        for entry in ("prefill", "prefill_suffix", "decode_loop",
                      "ff_decode_loop", "spec_decode_loop",
                      "prefill_paged", "paged_decode_loop",
                      "paged_pallas_decode_loop",
                      "tpu_paged_decode_loop",
                      "tpu_paged_pallas_decode_loop",
                      "megaround"):
            assert entry in census, sorted(census)
            assert "error" not in census[entry], census[entry]
            assert census[entry]["total_ops"] > 0

    def test_megaround_fuses_both_phase_loops(self, scenario):
        """ROADMAP item 1: the whole consensus round is ONE jit module —
        the decide AND vote guided-decode while-loops lower inside the
        single ``megaround`` entry (plus the DFA parse loops), so its
        while-body kernel family strictly exceeds a single decode_loop
        entry's, and it carries at least one while per phase."""
        _, census = scenario
        mega = census["megaround"]
        single = census["decode_loop"]
        assert mega["whiles"] >= 2, mega
        assert mega["step_ops"] > single["step_ops"], (mega, single)
        assert mega["step_fusions"] > single["step_fusions"], (mega, single)

    def test_fused_paged_step_kernels_below_gather_baseline(self, scenario):
        """ISSUE-8 acceptance: on the TPU cross-lowering (the kernel's
        real Mosaic lowering — trace+lower needs no hardware), the
        fused paged decode loop's per-step op count is STRICTLY below
        the PR-7 XLA-gather path's, the per-layer attention gather/dot
        chains replaced by exactly one fused kernel custom-call per
        layer.  Both entries are also exact-pinned in hlo_baseline.json,
        so the gap is drift-gated in both directions."""
        _, census = scenario
        gather = census["tpu_paged_decode_loop"]
        fused = census["tpu_paged_pallas_decode_loop"]
        assert fused["step_ops"] < gather["step_ops"], (fused, gather)
        # One fused kernel per layer (tiny-test: 2 layers), none before.
        assert gather["step_custom_calls"] == 0
        assert fused["step_custom_calls"] == 2
        # The attention block gathers and score/value dots folded into
        # the kernel; the remaining gathers (write-path table lookups,
        # embedding, sampler) are common to both arms.
        assert fused["step_gathers"] < gather["step_gathers"]
        assert fused["step_dots"] < gather["step_dots"]

    def test_decode_loops_have_step_kernels(self, scenario):
        _, census = scenario
        for entry in ("decode_loop", "ff_decode_loop", "spec_decode_loop",
                      "paged_decode_loop"):
            assert census[entry]["step_fusions"] > 0
            assert census[entry]["whiles"] >= 1

    def test_cost_analysis_present_on_cpu(self, scenario):
        _, census = scenario
        assert census["prefill"]["flops"] > 0
        assert census["prefill"]["bytes_accessed"] > 0

    def test_gauges_published(self, scenario):
        _, census = scenario
        snap = obs_counters.snapshot()
        assert snap.get("engine.hlo.decode_loop.step_fusions") == \
            census["decode_loop"]["step_fusions"]
        assert snap.get("engine.hlo.prefill.flops") == \
            census["prefill"]["flops"]

    def test_table_renders_per_entry_counts(self, scenario):
        mod, census = scenario
        table = mod.render_table(census)
        assert "fusions" in table and "custom_calls" in table
        assert "decode_loop" in table and "prefill" in table


class TestDriftGate:
    def test_census_matches_checked_in_baseline(self, scenario):
        mod, census = scenario
        findings = mod.check_drift(census, mod.load_baseline())
        assert findings == [], "\n".join(findings)

    def test_added_kernel_in_decode_step_fails(self, scenario):
        """The acceptance-criterion probe: one more kernel in the decode
        step must be a drift finding naming the entry and metric."""
        mod, census = scenario
        mutated = {k: dict(v) for k, v in census.items()}
        mutated["decode_loop"]["step_fusions"] += 1
        mutated["decode_loop"]["step_ops"] += 1
        mutated["decode_loop"]["total_ops"] += 1
        mutated["decode_loop"]["fusions"] += 1
        findings = mod.check_drift(mutated, mod.load_baseline())
        assert any("decode_loop.step_fusions" in f and "added" in f
                   for f in findings), findings

    def test_removing_baseline_entry_resurfaces_finding(self, scenario):
        mod, census = scenario
        baseline = mod.load_baseline()
        assert baseline and baseline["entries"], "baseline missing/empty"
        for entry in list(baseline["entries"]):
            pruned = json.loads(json.dumps(baseline))
            del pruned["entries"][entry]
            findings = mod.check_drift(census, pruned)
            assert any(entry in f and "not pinned" in f for f in findings), (
                entry, findings
            )

    def test_stale_baseline_entry_is_a_finding(self, scenario):
        mod, census = scenario
        baseline = json.loads(json.dumps(mod.load_baseline()))
        baseline["entries"]["no_such_entry"] = {
            "reason": "synthetic", "counts": {"total_ops": 1},
        }
        findings = mod.check_drift(census, baseline)
        assert any("no_such_entry" in f and "stale" in f for f in findings)

    def test_backend_mismatch_refuses_comparison(self, scenario):
        mod, census = scenario
        baseline = json.loads(json.dumps(mod.load_baseline()))
        baseline["backend"] = "tpu"
        findings = mod.check_drift(census, baseline)
        assert len(findings) == 1 and "not comparable" in findings[0]

    def test_every_baseline_entry_has_a_reason(self):
        mod = _load_script()
        baseline = mod.load_baseline()
        for entry, pinned in baseline["entries"].items():
            assert pinned.get("reason", "").strip(), entry
            for metric in ("total_ops", "step_ops"):
                assert metric in pinned["counts"], (entry, metric)

    def test_baseline_pins_every_count_metric(self):
        mod = _load_script()
        baseline = mod.load_baseline()
        for entry, pinned in baseline["entries"].items():
            assert set(pinned["counts"]) == set(COUNT_METRICS), entry


class TestRecorderHygiene:
    def test_disabled_by_default_records_nothing(self, monkeypatch):
        monkeypatch.delenv("BCG_TPU_HLO_CENSUS", raising=False)
        obs_hlo.reset()
        try:
            sentinel = object()
            assert obs_hlo.wrap("x", sentinel) is sentinel
            obs_hlo.maybe_record("x", None, ())
            assert obs_hlo.snapshot() == {}
        finally:
            obs_hlo.reset()

    def test_recording_failure_is_contained(self):
        obs_hlo.reset()
        obs_hlo.enable(True)
        try:
            class Boom:
                def lower(self, *a, **k):
                    raise RuntimeError("no lowering here")

            obs_hlo.maybe_record("broken_entry", Boom(), (1,))
            snap = obs_hlo.snapshot()
            assert "error" in snap["broken_entry"]
            assert "RuntimeError" in snap["broken_entry"]["error"]
        finally:
            obs_hlo.reset()
