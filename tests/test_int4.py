"""Grouped int4 (W4A16) weight quantization (models/quantize.py,
ops/w4_matmul.py).

int4 is the CAPACITY knob that fits the reference's 14B preset
(reference config.py:20-25, README.md:33 "24GB+ VRAM") on one 16 GB
v5e chip.  Properties tested:

* pack/unpack layout matches an independent numpy oracle (low nibble =
  top-half row, high nibble = bottom-half row, arithmetic sign
  extension);
* grouped dequantization error is bounded by half a quantization step
  of each group's own scale;
* dense() on int4 tracks the bf16 matmul;
* the Pallas kernel (interpret mode) agrees with the XLA dequant
  fallback bit-for-bit at f32 accumulation tolerance;
* an int4 tiny model's logits track bf16 closely;
* engine integration: quantization="int4" serves schema-valid JSON;
* int4 trees stack for scan-over-layers and shard over a tp mesh.
"""

import pytest

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.models import init_params, prefill, spec_for_model
from bcg_tpu.models.quantize import (
    dense,
    dequantize_int4,
    int4_group_for,
    is_int4,
    quantize_params,
    quantize_weight_int4,
    unpack_int4,
)
from bcg_tpu.models.transformer import init_kv_cache, stack_layer_params
from bcg_tpu.ops.w4_matmul import w4a16_matmul, w4a16_supported


def _np_unpack(packed: np.ndarray) -> np.ndarray:
    """Numpy oracle for the nibble layout: independent of the jnp shift
    implementation under test."""
    low = (packed.astype(np.int8) << 4).astype(np.int8) >> 4
    high = packed.astype(np.int8) >> 4
    return np.concatenate([low, high], axis=0)


class TestPacking:
    def test_pack_unpack_roundtrip(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (256, 64), jnp.float32)
        qw = quantize_weight_int4(w)
        assert qw["q4"].dtype == jnp.int8
        assert qw["q4"].shape == (128, 64)
        assert qw["gscale"].shape == (2, 64)  # group = 128 -> 2 groups
        unpacked = np.asarray(unpack_int4(qw["q4"]))
        np.testing.assert_array_equal(unpacked, _np_unpack(np.asarray(qw["q4"])))
        assert unpacked.min() >= -8 and unpacked.max() <= 7

    def test_group_shrinks_for_tiny_dims(self):
        assert int4_group_for(64) == 32    # tiny-test hidden size
        assert int4_group_for(256) == 128
        assert int4_group_for(5120) == 128
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 32), jnp.float32)
        qw = quantize_weight_int4(w)
        assert qw["gscale"].shape == (2, 32)

    def test_dequant_error_bounded_per_group(self):
        w = jax.random.normal(jax.random.PRNGKey(2), (512, 96), jnp.float32)
        qw = quantize_weight_int4(w)
        deq = np.asarray(dequantize_int4(qw), np.float32)
        scale = np.repeat(np.asarray(qw["gscale"], np.float32), 128, axis=0)
        err = np.abs(deq - np.asarray(w)) / scale
        # Half a step of the group's own scale, plus bf16 scale rounding.
        assert err.max() <= 0.5 + 0.02


class TestDenseInt4:
    def test_tracks_bf16_matmul(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(3))
        x = jax.random.normal(k1, (4, 256), jnp.bfloat16)
        w = jax.random.normal(k2, (256, 64), jnp.bfloat16)
        exact = (x @ w).astype(jnp.float32)
        qw = quantize_weight_int4(w)
        assert is_int4(qw)
        got = dense(x, qw).astype(jnp.float32)
        rel = jnp.linalg.norm(got - exact) / jnp.linalg.norm(exact)
        # Grouped int4 on gaussian data: step = absmax/7 ~ 0.48 sigma, so
        # per-element noise ~ 0.48/sqrt(12) ~ 0.14 sigma — ~14% relative
        # output error is the THEORETICAL floor for this distribution
        # (real weight matrices quantize much better than max-entropy
        # gaussians).  This test pins correctness, not accuracy.
        assert float(rel) < 0.2

    def test_kernel_matches_fallback_interpret(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(4))
        x = jax.random.normal(k1, (8, 512), jnp.bfloat16)
        w = jax.random.normal(k2, (512, 128), jnp.bfloat16)
        qw = quantize_weight_int4(w)
        assert w4a16_supported(x.shape, qw["q4"].shape, qw["gscale"].shape)
        kernel = np.asarray(
            w4a16_matmul(x, qw["q4"], qw["gscale"], interpret=True), np.float32
        )
        oracle = np.asarray(
            (x @ dequantize_int4(qw)).astype(jnp.float32), np.float32
        )
        np.testing.assert_allclose(kernel, oracle, rtol=2e-2, atol=2e-1)

    @pytest.mark.slow
    def test_kernel_14b_serving_dims_interpret(self):
        """The exact (in, out) dims bench_14b serves through the kernel
        (Qwen3-14B w_gate/w_up: 5120 -> 17408; decode rows ~ 10 agents):
        interpret-mode ground truth so a hardware probe failure isolates
        Mosaic lowering, not math (round-3 verdict weak #2).  The
        VMEM-budgeted block picker must also accept these dims."""
        k1, k2 = jax.random.split(jax.random.PRNGKey(7))
        x = jax.random.normal(k1, (10, 5120), jnp.bfloat16)
        w = jax.random.normal(k2, (5120, 17408), jnp.bfloat16) * 0.02
        qw = quantize_weight_int4(w)
        assert w4a16_supported(x.shape, qw["q4"].shape, qw["gscale"].shape)
        out = w4a16_matmul(x, qw["q4"], qw["gscale"], interpret=True)
        assert out.shape == (10, 17408)
        oracle = np.asarray((x @ dequantize_int4(qw)).astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(out), oracle, rtol=2e-2,
                                   atol=2e-1)

    def test_kernel_pads_ragged_rows(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(5))
        x = jax.random.normal(k1, (10, 256), jnp.bfloat16)  # M=10: padded to 16
        w = jax.random.normal(k2, (256, 128), jnp.bfloat16)
        qw = quantize_weight_int4(w)
        out = w4a16_matmul(x, qw["q4"], qw["gscale"], interpret=True)
        assert out.shape == (10, 128)
        oracle = np.asarray((x @ dequantize_int4(qw)).astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(out), oracle, rtol=2e-2, atol=2e-1)

    def test_kernel_3d_leading_dims(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(6))
        x = jax.random.normal(k1, (2, 4, 256), jnp.bfloat16)
        w = jax.random.normal(k2, (256, 128), jnp.bfloat16)
        qw = quantize_weight_int4(w)
        out = w4a16_matmul(x, qw["q4"], qw["gscale"], interpret=True)
        assert out.shape == (2, 4, 128)


class TestInt4Model:
    @pytest.mark.slow
    def test_logits_track_bf16(self):
        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        qparams = quantize_params(params, spec, mode="int4")
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, spec.vocab_size)
        valid = jnp.ones((2, 16), bool)
        cache = init_kv_cache(spec, 2, 17)
        qcache = init_kv_cache(spec, 2, 17)
        logits, _ = prefill(params, spec, tokens, valid, cache)
        qlogits, _ = prefill(qparams, spec, tokens, valid, qcache)
        lf = np.asarray(logits, np.float64)
        qf = np.asarray(qlogits, np.float64)
        cos = (lf * qf).sum() / (np.linalg.norm(lf) * np.linalg.norm(qf) + 1e-9)
        assert cos > 0.95

    def test_stacks_for_scan(self):
        spec = spec_for_model("bcg-tpu/tiny-test")
        qparams = quantize_params(init_params(spec, jax.random.PRNGKey(0)), spec, mode="int4")
        stacked = stack_layer_params(qparams)
        wq = stacked["layers"]["wq"]
        assert wq["q4"].shape[0] == spec.num_layers
        assert wq["gscale"].shape[0] == spec.num_layers

    def test_tied_embeddings_get_int4_head(self):
        spec = dataclasses.replace(spec_for_model("bcg-tpu/tiny-test"), tie_embeddings=True)
        params = init_params(spec, jax.random.PRNGKey(0))
        qparams = quantize_params(params, spec, mode="int4")
        assert is_int4(qparams["lm_head"])
        assert qparams["embed"].dtype == jnp.bfloat16


@pytest.mark.slow
class TestInt4Engine:
    def test_guided_json_still_valid(self):
        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=1024, quantization="int4",
        ))
        schema = {
            "type": "object",
            "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
            "required": ["decision"],
            "additionalProperties": False,
        }
        out = engine.generate_json("vote now", schema, temperature=0.7, max_tokens=24)
        assert out.get("decision") in ("stop", "continue")
        engine.shutdown()


@pytest.mark.slow
class TestInt4Sharding:
    def test_shards_over_tp_mesh(self):
        from bcg_tpu.parallel.mesh import build_mesh
        from bcg_tpu.parallel.sharding import shard_params

        spec = spec_for_model("bcg-tpu/tiny-test")
        qparams = quantize_params(
            init_params(spec, jax.random.PRNGKey(0)), spec, mode="int4"
        )
        mesh = build_mesh(tp=2, dp=1, sp=1)
        sharded = shard_params(qparams, spec, mesh)
        layer = sharded["layers"][0]
        wq = layer["wq"]
        assert wq["q4"].sharding.spec == jax.sharding.PartitionSpec(None, "tp")
        assert wq["gscale"].sharding.spec == jax.sharding.PartitionSpec(None, "tp")
        wo = layer["wo"]
        assert wo["q4"].sharding.spec == jax.sharding.PartitionSpec("tp", None)
        assert wo["gscale"].sharding.spec in (
            jax.sharding.PartitionSpec(None, None),
            jax.sharding.PartitionSpec(),
        )
        tokens = jnp.zeros((2, 8), jnp.int32)
        valid = jnp.ones((2, 8), bool)
        cache = init_kv_cache(spec, 2, 9)
        logits, _ = prefill(sharded, spec, tokens, valid, cache)
        assert logits.shape == (2, spec.vocab_size)


class TestVmemBudget:
    """_pick_block_f must budget the x block and output tile, not just
    the packed strip: at 14B w_down shapes a block_m=128 x block alone
    is 4.5 MB, and strip-only budgeting picked a block_f that overflowed
    VMEM at compile time on real hardware (round-3 review finding)."""

    def test_14b_wdown_block_shrinks_with_row_block(self):
        from bcg_tpu.ops.w4_matmul import _pick_block_f

        P, F = 8704, 17408  # 14B w_down: D=17408 -> P=8704
        # Decode rows (bm=16): the 512-lane strip fits alongside a
        # small x block.
        assert _pick_block_f(P, F, 16) == 512
        # Full row block: 512 lanes + an 8.9 MB double-buffered x block
        # would exceed VMEM; the picker must back off.
        assert _pick_block_f(P, F, 128) == 256

    def test_supported_accounts_for_rows(self):
        P, F = 8704, 17408
        D = 2 * P
        gs_shape = (2 * (P // 128), F)
        assert w4a16_supported((16, D), (P, F), gs_shape)
        assert w4a16_supported((256, D), (P, F), gs_shape)

    def test_total_budget_within_vmem(self):
        from bcg_tpu.ops.w4_matmul import _pick_block_f

        for P, F in [(1024, 6144), (2048, 12288), (8704, 17408), (6912, 13824)]:
            for bm in (8, 16, 64, 128, 256):
                bf = _pick_block_f(P, F, bm)
                if bf == 0:
                    continue
                working = 2 * (bm * 2 * P * 2) + 2 * (P * bf) + bm * bf * 4
                assert working <= 14 * 1024 * 1024


@pytest.mark.slow
class TestStackedModeGuard:
    """Sharing a STACKED pre-quantized tree into an engine whose
    configured quantization mode differs must raise, exactly like the
    unstacked guard (round-3 review finding: the stacked branch silently
    served int8 weights under quantization='int4')."""

    def _stacked_engine(self, mode):
        cfg = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization=mode, scan_layers=True,
        )
        return JaxEngine(cfg)

    def test_mode_mismatch_raises(self):
        import pytest

        donor = self._stacked_engine("int8")
        cfg = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization="int4",
        )
        with pytest.raises(ValueError, match="int8-format"):
            JaxEngine(cfg, params=donor.params)
        cfg_none = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization=None,
        )
        with pytest.raises(ValueError, match="int8-format"):
            JaxEngine(cfg_none, params=donor.params)
        donor.shutdown()

    def test_mode_match_shares(self):
        donor = self._stacked_engine("int4")
        cfg = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization="int4",
        )
        eng = JaxEngine(cfg, params=donor.params)
        assert eng.scan_layers
        out = eng.generate("hi", max_tokens=4)
        assert isinstance(out, str)
        eng.shutdown()
        donor.shutdown()

    def test_mismatch_raises_with_scan_recipient(self):
        """Recipient configs with scan_layers=True must hit the guard
        too (review finding: the guard lived in a branch only reached
        when config.scan_layers was False)."""
        import pytest

        donor = self._stacked_engine("int8")
        cfg = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization="int4", scan_layers=True,
        )
        with pytest.raises(ValueError, match="int8-format"):
            JaxEngine(cfg, params=donor.params)
        donor.shutdown()

    def test_unstacked_quantized_under_none_raises(self):
        """An UNSTACKED pre-quantized shared tree under
        quantization=None must raise like the stacked case (review
        finding: guard coverage diverged purely on stacking layout)."""
        import pytest

        cfg8 = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization="int8",
        )
        donor = JaxEngine(cfg8)
        assert not donor.scan_layers
        cfg_none = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization=None,
        )
        with pytest.raises(ValueError, match="int8-format"):
            JaxEngine(cfg_none, params=donor.params)
        donor.shutdown()

    def test_unstacked_bf16_share_into_quantized_ok(self):
        """Sharing a bf16 unstacked tree into a quantized engine stays
        supported: the recipient quantizes its own copy."""
        cfg_none = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization=None,
        )
        donor = JaxEngine(cfg_none)
        cfg8 = EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=512, quantization="int8",
        )
        eng = JaxEngine(cfg8, params=donor.params)
        out = eng.generate("hi", max_tokens=4)
        assert isinstance(out, str)
        eng.shutdown()
        donor.shutdown()
