"""Tests for schema-guided decoding: schema->regex->DFA->token DFA.

The property being tested end-to-end: a string matches the byte DFA iff it
is a serialization the schema accepts, and the token DFA accepts exactly
the token sequences whose concatenated bytes the byte DFA accepts.
"""

import json

import numpy as np
import pytest

from bcg_tpu.guided import (
    GuidedBatch,
    ast_to_dfa,
    build_token_dfa,
    compile_schema,
    schema_to_ast,
)
from bcg_tpu.guided.schema_compiler import int_range_ast
from bcg_tpu.guided.token_dfa import _build_numpy, _load_native


def dfa_for(schema):
    return ast_to_dfa(schema_to_ast(schema))


def accepts(dfa, text: str) -> bool:
    return dfa.matches(text.encode("utf-8"))


HONEST_DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string"},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string"},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}

BYZ_DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string"},
        "value": {
            "anyOf": [
                {"type": "integer", "minimum": 0, "maximum": 50},
                {"type": "string", "enum": ["abstain"]},
            ]
        },
        "public_reasoning": {"type": "string"},
    },
    "required": ["internal_strategy", "value"],
    "additionalProperties": False,
}

VOTE = {
    "type": "object",
    "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
    "required": ["decision"],
    "additionalProperties": False,
}


class TestIntRange:
    @pytest.mark.parametrize(
        "lo,hi",
        [(0, 50), (0, 0), (5, 5), (1, 9), (0, 100), (17, 23), (99, 1001), (-20, 30), (-45, -7)],
    )
    def test_range_acceptance_is_exact(self, lo, hi):
        dfa = ast_to_dfa(int_range_ast(lo, hi))
        for v in range(lo - 15, hi + 16):
            assert dfa.matches(str(v).encode()) == (lo <= v <= hi), (v, lo, hi)

    def test_no_leading_zeros(self):
        dfa = ast_to_dfa(int_range_ast(0, 50))
        assert not dfa.matches(b"007")
        assert not dfa.matches(b"01")
        assert dfa.matches(b"0")

    def test_unbounded(self):
        dfa = ast_to_dfa(int_range_ast(None, None))
        for s in (b"0", b"-1", b"123456789", b"-987654"):
            assert dfa.matches(s)
        for s in (b"01", b"--3", b"", b"+5"):
            assert not dfa.matches(s)


class TestSchemaDFA:
    def test_honest_decision_accepts_valid_json(self):
        dfa = dfa_for(HONEST_DECISION)
        obj = {
            "internal_strategy": "watch agent_3",
            "value": 25,
            "public_reasoning": "converging to the majority",
        }
        assert accepts(dfa, json.dumps(obj))
        # Whitespace is bounded (<=3 chars between structural tokens) so a
        # weak model can't loop on separators: compact and indent<=2 forms
        # are in-grammar, deeper indentation is not.
        assert accepts(dfa, json.dumps(obj, indent=2))
        assert accepts(dfa, json.dumps(obj, separators=(",", ":")))
        assert not accepts(dfa, json.dumps(obj, indent=8))

    def test_honest_decision_rejects_bad_json(self):
        dfa = dfa_for(HONEST_DECISION)
        # out-of-range value
        assert not accepts(dfa, '{"internal_strategy": "s", "value": 51, "public_reasoning": "r"}')
        # missing required field
        assert not accepts(dfa, '{"internal_strategy": "s", "value": 5}')
        # wrong key order (schema order is the contract)
        assert not accepts(dfa, '{"value": 5, "internal_strategy": "s", "public_reasoning": "r"}')
        # trailing garbage
        assert not accepts(dfa, '{"internal_strategy": "s", "value": 5, "public_reasoning": "r"} x')
        # string where int expected
        assert not accepts(dfa, '{"internal_strategy": "s", "value": "5", "public_reasoning": "r"}')

    def test_byzantine_value_abstain_or_int(self):
        dfa = dfa_for(BYZ_DECISION)
        assert accepts(dfa, '{"internal_strategy": "lurk", "value": "abstain", "public_reasoning": "hmm"}')
        assert accepts(dfa, '{"internal_strategy": "lurk", "value": 50}')  # reasoning optional
        assert not accepts(dfa, '{"internal_strategy": "lurk", "value": "sneaky"}')
        assert not accepts(dfa, '{"value": 5}')  # strategy required

    def test_vote_schema(self):
        dfa = dfa_for(VOTE)
        assert accepts(dfa, '{"decision": "stop"}')
        assert accepts(dfa, '{"decision": "continue"}')
        assert not accepts(dfa, '{"decision": "maybe"}')
        assert not accepts(dfa, '{"decision": stop}')

    def test_string_escapes(self):
        dfa = dfa_for({"type": "string"})
        assert accepts(dfa, '"hello world"')
        assert accepts(dfa, '"say \\"hi\\" now"')
        assert accepts(dfa, '"line\\nbreak"')
        assert not accepts(dfa, '"unterminated')
        assert not accepts(dfa, '"raw " quote"')

    def test_boolean_null_number_array(self):
        assert accepts(dfa_for({"type": "boolean"}), "true")
        assert accepts(dfa_for({"type": "null"}), "null")
        num = dfa_for({"type": "number"})
        for s in ("3.25", "-1e9", "0.5", "42"):
            assert accepts(num, s)
        arr = dfa_for({"type": "array", "items": {"type": "integer"}})
        assert accepts(arr, "[1, 2, 3]")
        assert accepts(arr, "[]")
        assert not accepts(arr, "[1,]")

    def test_optional_in_middle_supported(self):
        # 'a' optional, 'b' required — general presence-subset path.
        schema = {
            "type": "object",
            "properties": {"a": {"type": "string"}, "b": {"type": "string"}},
            "required": ["b"],
        }
        dfa = dfa_for(schema)
        assert accepts(dfa, '{"b": "x"}')
        assert accepts(dfa, '{"a": "y", "b": "x"}')
        assert not accepts(dfa, '{"a": "y"}')  # b required
        assert not accepts(dfa, '{"b": "x", "a": "y"}')  # declaration order

    def test_absent_required_means_all_optional(self):
        schema = {"type": "object", "properties": {"a": {"type": "integer"}}}
        dfa = dfa_for(schema)
        assert accepts(dfa, "{}")
        assert accepts(dfa, '{"a": 3}')

    def test_cache_distinguishes_property_order(self):
        vocab = [bytes([i]) for i in range(256)]
        s1 = {"type": "object", "properties": {"a": {"type": "integer"}, "b": {"type": "integer"}},
              "required": ["a", "b"], "additionalProperties": False}
        s2 = {"type": "object", "properties": {"b": {"type": "integer"}, "a": {"type": "integer"}},
              "required": ["a", "b"], "additionalProperties": False}
        g1 = compile_schema(s1, vocab, vocab_id=3)
        g2 = compile_schema(s2, vocab, vocab_id=3)
        assert g1 is not g2

    def test_required_name_not_in_properties_raises(self):
        with pytest.raises(ValueError, match="not in properties"):
            schema_to_ast({"type": "object", "properties": {}, "required": ["x"]})


def byte_vocab():
    """Byte-level vocabulary: token i = bytes([i]) plus a few multi-byte
    merges, mimicking BPE structure."""
    toks = [bytes([i]) for i in range(256)]
    toks += [b'{"', b'":', b'", "', b'"}', b"abstain", b"stop", b"continue", b"decision"]
    return toks


class TestTokenDFA:
    def test_token_walk_matches_char_walk(self):
        vocab = byte_vocab()
        char_dfa = dfa_for(VOTE)
        tdfa = build_token_dfa(char_dfa, vocab, force_numpy=True)
        text = b'{"decision": "stop"}'
        # single-byte token path
        state = tdfa.start
        for b in text:
            state = int(tdfa.transitions[state, b])
            assert state >= 0
        assert tdfa.accepting[state]
        # multi-byte token path: '{"' + 'decision' + '":' ...
        seq = [vocab.index(b'{"'), vocab.index(b"decision"), vocab.index(b'":'),
               vocab.index(b" "), vocab.index(b'"'), vocab.index(b"stop"),
               vocab.index(b'"}')]
        state = tdfa.start
        for t in seq:
            state = int(tdfa.transitions[state, t])
            assert state >= 0, t
        assert tdfa.accepting[state]

    def test_forbidden_tokens_masked(self):
        vocab = byte_vocab()
        tdfa = build_token_dfa(dfa_for(VOTE), vocab, force_numpy=True)
        # From the start state, only '{' (or tokens starting with '{'/ws) are legal.
        start_row = tdfa.transitions[tdfa.start]
        assert start_row[ord("{")] >= 0
        assert start_row[ord("x")] < 0
        assert start_row[vocab.index(b'{"')] >= 0
        assert start_row[vocab.index(b"stop")] < 0

    def test_native_matches_numpy(self):
        if _load_native() is None:
            pytest.skip("no C++ toolchain")
        vocab = byte_vocab()
        char_dfa = dfa_for(BYZ_DECISION)
        a = build_token_dfa(char_dfa, vocab, force_numpy=True).transitions
        b = build_token_dfa(char_dfa, vocab, force_numpy=False).transitions
        np.testing.assert_array_equal(a, b)

    def test_zero_length_token_forbidden(self):
        vocab = byte_vocab() + [b""]
        tdfa = build_token_dfa(dfa_for(VOTE), vocab, force_numpy=True)
        assert (tdfa.transitions[:, len(vocab) - 1] == -1).all()


class TestGuidedBatch:
    def test_heterogeneous_batch(self):
        vocab = byte_vocab()
        g_vote = compile_schema(VOTE, vocab, vocab_id=1)
        g_byz = compile_schema(
            {"type": "object", "properties": {"decision": {"type": "string",
             "enum": ["stop", "continue", "abstain"]}}, "required": ["decision"],
             "additionalProperties": False},
            vocab, vocab_id=1,
        )
        batch = GuidedBatch([g_vote, g_byz, g_vote])
        assert batch.num_unique == 2

        states = batch.init_states
        mask = np.asarray(batch.token_mask(states))
        assert mask.shape == (3, len(vocab))
        assert mask[0, ord("{")] and mask[1, ord("{")]

        # Drive rows through '{"decision": "' on the host table and confirm
        # row 0 (honest vote) forbids the 'abstain' token where row 1
        # (Byzantine vote) allows it.
        tables = np.asarray(batch.tables)
        dfa_ids = np.asarray(batch.dfa_ids)
        prefix = b'{"decision": "'
        s0 = int(batch.init_states[0])
        s1 = int(batch.init_states[1])
        for b in prefix:
            s0 = int(tables[dfa_ids[0], s0, b])
            s1 = int(tables[dfa_ids[1], s1, b])
            assert s0 >= 0 and s1 >= 0
        abstain_tok = vocab.index(b"abstain")
        assert tables[dfa_ids[1], s1, abstain_tok] >= 0
        assert tables[dfa_ids[0], s0, abstain_tok] < 0

    def test_compile_cache(self):
        vocab = byte_vocab()
        a = compile_schema(VOTE, vocab, vocab_id=7)
        b = compile_schema(json.loads(json.dumps(VOTE)), vocab, vocab_id=7)
        assert a is b

    def test_step_and_eos(self):
        import jax
        import jax.numpy as jnp

        vocab = byte_vocab()
        g = compile_schema(VOTE, vocab, vocab_id=2)
        batch = GuidedBatch([g])

        # Single jitted step fn, reused each iteration (as the decode loop
        # does) — no per-step recompilation.
        @jax.jit
        def step(states, tok):
            return batch.step(states, tok), batch.eos_allowed(states)

        states = batch.init_states
        for b in b'{"decision": "stop"}':
            states, eos_ok = step(states, jnp.asarray([b], dtype=jnp.int32))
            assert int(states[0]) >= 0
        assert bool(np.asarray(batch.eos_allowed(states))[0])
        # Sticky negative state
        states = jnp.asarray([-1])
        states = batch.step(states, jnp.asarray([5]))
        assert int(states[0]) == -1


class TestCompletionPaths:
    def test_dist(self):
        import numpy as np
        from bcg_tpu.guided.token_dfa import completion_paths

        # 3 states: 0 --t0--> 1 --t1--> 2(accept); t2 loops on 0.
        trans = np.array([
            [1, -1, 0],
            [-1, 2, -1],
            [-1, -1, -1],
        ], dtype=np.int32)
        accepting = np.array([False, False, True])
        dist = completion_paths(trans, accepting)
        assert list(dist) == [2, 1, 0]

    def test_unreachable_accept(self):
        import numpy as np
        from bcg_tpu.guided.token_dfa import completion_paths

        trans = np.array([[0, -1]], dtype=np.int32)  # loops forever
        accepting = np.array([False])
        dist = completion_paths(trans, accepting)
        assert dist[0] > 1_000_000

    def test_real_schema_distances_small(self):
        from bcg_tpu.guided.dfa import ast_to_dfa
        from bcg_tpu.guided.schema_compiler import schema_to_ast
        from bcg_tpu.guided.token_dfa import build_token_dfa

        schema = {
            "type": "object",
            "properties": {
                "internal_strategy": {"type": "string", "minLength": 3},
                "value": {"type": "integer", "minimum": 0, "maximum": 50},
                "public_reasoning": {"type": "string", "minLength": 10},
            },
            "required": ["internal_strategy", "value", "public_reasoning"],
            "additionalProperties": False,
        }
        token_bytes = [bytes([b]) for b in range(256)]
        td = build_token_dfa(ast_to_dfa(schema_to_ast(schema)), token_bytes)
        # From the start, completing the whole minimal object takes at
        # most ~60 byte tokens; every reachable state can finish.
        assert 0 < td.dist[td.start] < 80
        reachable = td.transitions.max(axis=1) >= 0
        assert (td.dist[reachable] < 1000).all()


class TestConstOneOf:
    """const (a one-value enum) and oneOf (generation-side anyOf) —
    accepted by the reference's outlines-style guided backend."""

    def test_const_string_and_int(self):
        from bcg_tpu.guided.dfa import ast_to_dfa
        from bcg_tpu.guided.schema_compiler import schema_to_ast

        d = ast_to_dfa(schema_to_ast({"const": "abstain"}))
        assert d.matches(b'"abstain"') and not d.matches(b'"abstain2"')
        d = ast_to_dfa(schema_to_ast({"const": 7}))
        assert d.matches(b"7") and not d.matches(b"8")
        d = ast_to_dfa(schema_to_ast({"const": None}))
        assert d.matches(b"null")

    def test_oneof_alternates(self):
        import json as _json

        from bcg_tpu.guided.dfa import ast_to_dfa
        from bcg_tpu.guided.schema_compiler import schema_to_ast

        schema = {
            "type": "object",
            "properties": {"value": {"oneOf": [
                {"type": "integer", "minimum": 0, "maximum": 9},
                {"const": "abstain"},
            ]}},
            "required": ["value"],
            "additionalProperties": False,
        }
        d = ast_to_dfa(schema_to_ast(schema))
        assert d.matches(_json.dumps({"value": 5}).encode())
        assert d.matches(_json.dumps({"value": "abstain"}).encode())
        assert not d.matches(_json.dumps({"value": 77}).encode())

    def test_container_const_and_empty_alternations_raise(self):
        import pytest as _pytest

        from bcg_tpu.guided.schema_compiler import schema_to_ast

        with _pytest.raises(ValueError, match="only JSON scalars"):
            schema_to_ast({"const": [1, 2]})
        with _pytest.raises(ValueError, match="only JSON scalars"):
            schema_to_ast({"enum": [{"a": 1}]})
        for key in ("enum", "anyOf", "oneOf"):
            with _pytest.raises(ValueError, match=f"empty {key}"):
                schema_to_ast({key: []})


class TestArrayBoundsAndExclusive:
    """minItems/maxItems and exclusiveMinimum/Maximum (accepted by the
    reference's guided backend; previously ignored/unsupported here)."""

    def _dfa(self, schema):
        from bcg_tpu.guided.dfa import ast_to_dfa
        from bcg_tpu.guided.schema_compiler import schema_to_ast

        return ast_to_dfa(schema_to_ast(schema))

    def test_array_item_count_bounds(self):
        d = self._dfa({"type": "array",
                       "items": {"type": "integer", "minimum": 0, "maximum": 9},
                       "minItems": 2, "maxItems": 3})
        assert not d.matches(b"[1]")
        assert d.matches(b"[1, 2]")
        assert d.matches(b"[1, 2, 3]")
        assert not d.matches(b"[1, 2, 3, 4]")
        assert not d.matches(b"[]")

    def test_array_min_only_and_max_zero(self):
        d = self._dfa({"type": "array", "items": {"type": "integer"},
                       "minItems": 1})
        assert not d.matches(b"[]")
        assert d.matches(b"[1]") and d.matches(b"[1, 2, 3, 4, 5]")
        d0 = self._dfa({"type": "array", "items": {"type": "integer"},
                        "maxItems": 0})
        assert d0.matches(b"[]") and not d0.matches(b"[1]")

    def test_array_invalid_bounds_raise(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="array bounds"):
            self._dfa({"type": "array", "items": {"type": "integer"},
                       "minItems": 3, "maxItems": 2})

    def test_exclusive_integer_bounds(self):
        d = self._dfa({"type": "integer",
                       "exclusiveMinimum": 0, "exclusiveMaximum": 10})
        assert not d.matches(b"0")
        assert d.matches(b"1") and d.matches(b"9")
        assert not d.matches(b"10")

    def test_exclusive_combines_with_inclusive(self):
        d = self._dfa({"type": "integer", "minimum": 3, "exclusiveMinimum": 4,
                       "maximum": 9})
        assert not d.matches(b"4")
        assert d.matches(b"5") and d.matches(b"9")

    def test_exclusive_bound_edges(self):
        import pytest as _pytest

        # Non-integral bounds: 9 < 9.5 must be admitted; 0 > -0.5 too.
        d = self._dfa({"type": "integer", "exclusiveMaximum": 9.5,
                       "exclusiveMinimum": -0.5})
        assert d.matches(b"0") and d.matches(b"9")
        assert not d.matches(b"10") and not d.matches(b"-1")
        # Draft-04 boolean form fails loudly instead of mis-compiling.
        with _pytest.raises(ValueError, match="draft-04"):
            self._dfa({"type": "integer", "minimum": 5,
                       "exclusiveMinimum": True})

    def test_non_integral_inclusive_bounds(self):
        # minimum=4.5 admits 5, not 4 (int() truncation would admit 4);
        # maximum=8.5 admits 8, not 9.
        d = self._dfa({"type": "integer", "minimum": 4.5, "maximum": 8.5})
        assert not d.matches(b"4")
        assert d.matches(b"5") and d.matches(b"8")
        assert not d.matches(b"9")
        # Combined with exclusive bounds the ceil'd inclusive minimum
        # still participates in max()/min() correctly.
        d2 = self._dfa({"type": "integer", "minimum": 5.5,
                        "exclusiveMinimum": 3, "maximum": 9})
        assert not d2.matches(b"5")
        assert d2.matches(b"6")

    def test_number_bounds_warn_unenforced(self):
        import warnings as _warnings

        from bcg_tpu.guided.schema_compiler import schema_to_ast

        with _warnings.catch_warnings(record=True) as got:
            _warnings.simplefilter("always")
            schema_to_ast({"type": "number", "minimum": 0.5})
        assert any("not enforced" in str(w.message) for w in got)
        with _warnings.catch_warnings(record=True) as got:
            _warnings.simplefilter("always")
            schema_to_ast({"type": "number"})
        assert not got
