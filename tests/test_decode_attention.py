"""Pallas decode-attention kernel (interpret mode on CPU) + int8 KV."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.models.transformer import _xla_attention
from bcg_tpu.ops.decode_attention import (
    decode_attention,
    dequantize_kv,
    quantize_kv,
)


def _case(key, B, S, H, Hkv, Dh):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (B, H, Dh), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, Dh), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, Dh), jnp.float32)
    lens = jax.random.randint(ks[3], (B,), 1, S + 1)
    mask = jnp.arange(S)[None, :] < lens[:, None]   # [B, S]
    return q, k, v, mask


def _reference(q, k, v, mask, scale):
    # decode step == T=1 full attention
    out = _xla_attention(q[:, None], k, v, mask[:, None, :], scale)
    return out[:, 0]


@pytest.mark.parametrize("shape", [
    (2, 256, 4, 2, 128),    # GQA
    (1, 512, 8, 8, 128),    # MHA, exact block
    (3, 700, 4, 1, 128),    # ragged S, all heads share one kv head
])
def test_matches_reference(shape):
    B, S, H, Hkv, Dh = shape
    q, k, v, mask = _case(jax.random.PRNGKey(0), B, S, H, Hkv, Dh)
    scale = 1.0 / np.sqrt(Dh)
    ref = _reference(q, k, v, mask, scale)
    out = decode_attention(q, k, v, mask, scale, block_s=256, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_int8_kv_close_to_fp():
    B, S, H, Hkv, Dh = 2, 384, 4, 2, 128
    q, k, v, mask = _case(jax.random.PRNGKey(1), B, S, H, Hkv, Dh)
    scale = 1.0 / np.sqrt(Dh)
    ref = _reference(q, k, v, mask, scale)
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    # decode_attention consumes the cache's int8 layout: k/v
    # [B, Hkv, S, Dh], scales [B, Hkv, S]
    out = decode_attention(q, kq.transpose(0, 2, 1, 3),
                           vq.transpose(0, 2, 1, 3), mask, scale,
                           k_scale=ks.transpose(0, 2, 1),
                           v_scale=vs.transpose(0, 2, 1),
                           block_s=128, interpret=True)
    # int8 with per-(token, head) scales: ~1% relative error budget
    err = np.abs(np.asarray(out) - np.asarray(ref)).max()
    assert err < 0.05, err


def _chunk_case(key, B, K, S, H, Hkv, Dh):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (B, K, H, Dh), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, Dh), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, Dh), jnp.float32)
    lens = jax.random.randint(ks[3], (B,), 1, S - K)
    # Per-position mask: each chunk position additionally sees its causal
    # predecessors, mirroring decode_chunk's mask construction.
    base = jnp.arange(S)[None, None, :] < lens[:, None, None]  # [B, 1, S]
    causal = (
        jnp.arange(S)[None, None, :]
        <= (lens[:, None] + jnp.arange(K)[None, :])[:, :, None]
    )
    mask = jnp.broadcast_to(base, (B, K, S)) | (causal & ~base)
    return q, k, v, mask


@pytest.mark.parametrize("shape", [
    (2, 4, 256, 4, 2, 128),   # GQA, FF_CHUNK-sized chunk
    (1, 4, 300, 8, 8, 128),   # MHA, ragged S
    (3, 2, 256, 4, 1, 128),   # group=4, K=2
])
def test_chunk_matches_reference(shape):
    from bcg_tpu.ops.decode_attention import chunk_decode_attention

    B, K, S, H, Hkv, Dh = shape
    q, k, v, mask = _chunk_case(jax.random.PRNGKey(4), B, K, S, H, Hkv, Dh)
    scale = 1.0 / np.sqrt(Dh)
    ref = _xla_attention(q, k, v, mask, scale)
    out = chunk_decode_attention(q, k, v, mask, scale, block_s=128,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_chunk_int8_close_to_fp():
    from bcg_tpu.ops.decode_attention import chunk_decode_attention

    B, K, S, H, Hkv, Dh = 2, 4, 256, 4, 2, 128
    q, k, v, mask = _chunk_case(jax.random.PRNGKey(5), B, K, S, H, Hkv, Dh)
    scale = 1.0 / np.sqrt(Dh)
    ref = _xla_attention(q, k, v, mask, scale)
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    out = chunk_decode_attention(q, kq.transpose(0, 2, 1, 3),
                                 vq.transpose(0, 2, 1, 3), mask, scale,
                                 k_scale=ks.transpose(0, 2, 1),
                                 v_scale=vs.transpose(0, 2, 1),
                                 block_s=128, interpret=True)
    err = np.abs(np.asarray(out) - np.asarray(ref)).max()
    assert err < 0.05, err


def test_quantize_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 16, 2, 64)) * 4.0
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (3, 16, 2)
    back = dequantize_kv(q, s)
    # round() error is at most half a quantization step of the row scale;
    # the global absmax bounds every row's scale.
    atol = float(np.abs(np.asarray(x)).max()) / 127 * 0.51
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=atol)


def test_quantize_zero_row_safe():
    x = jnp.zeros((1, 4, 1, 32))
    q, s = quantize_kv(x)
    assert np.isfinite(np.asarray(s)).all()
    assert (np.asarray(dequantize_kv(q, s)) == 0).all()


def test_fully_masked_rows_finite():
    B, S, H, Hkv, Dh = 1, 128, 2, 2, 128
    q, k, v, _ = _case(jax.random.PRNGKey(3), B, S, H, Hkv, Dh)
    mask = jnp.zeros((B, S), bool)
    out = decode_attention(q, k, v, mask, 0.1, block_s=128, interpret=True)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
class TestInt8CacheEndToEnd:
    def test_decode_logits_close_to_bf16(self):
        import jax
        from bcg_tpu.models import init_params, prefill, spec_for_model
        from bcg_tpu.models.transformer import decode_step, init_kv_cache

        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        B, L = 2, 32
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L), 0, spec.vocab_size)
        valid = jnp.ones((B, L), bool)

        outs = []
        for quant in (False, True):
            cache = init_kv_cache(spec, B, L + 4, quantized=quant)
            logits, cache = prefill(params, spec, tokens, valid, cache)
            vm = jnp.zeros((B, L + 4), bool).at[:, : L + 1].set(True)
            tok = jnp.argmax(logits, -1)
            step_logits, _ = decode_step(
                params, spec, tok, jnp.int32(L), jnp.full((B,), L), cache, vm
            )
            outs.append(np.asarray(step_logits))
        # int8 KV introduces small quantization noise; logits must stay
        # close and the argmax should (at tiny scale) agree.
        assert np.abs(outs[0] - outs[1]).max() < 0.15
        assert (outs[0].argmax(-1) == outs[1].argmax(-1)).mean() >= 0.5

    def test_guided_generation_with_int8_cache(self):
        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        eng = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=1024, kv_cache_dtype="int8",
        ))
        schema = {
            "type": "object",
            "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
            "required": ["decision"],
            "additionalProperties": False,
        }
        out = eng.batch_generate_json(
            [("sys", f"p{i}", schema) for i in range(3)],
            temperature=0.5, max_tokens=48,
        )
        for r in out:
            assert r.get("decision") in ("stop", "continue"), r


class TestServing8BShapes:
    """The exact kernel configuration bench_8b serves (Qwen3-8B dims:
    H=32, Hkv=8, Dh=128, group=4; S a multiple of ALIGN_S so the
    block-1024 all-heads grid is picked) — interpret-mode ground truth
    for the shapes whose Mosaic lowering the hardware probes
    (scripts/probe_int8_decode.py) validate.  Round-3 verdict weak #2:
    every kernel must have its serving shape pinned hermetically, so a
    hardware probe failure isolates Mosaic lowering, not math."""

    def test_int8_allheads_8b_serving_shape(self):
        B, S, H, Hkv, Dh = 2, 2048, 32, 8, 128
        q, k, v, mask = _case(jax.random.PRNGKey(11), B, S, H, Hkv, Dh)
        scale = 1.0 / np.sqrt(Dh)
        ref = _reference(q, k, v, mask, scale)
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        # block_s=None exercises _pick_block: S % 1024 == 0 -> 1024.
        out = decode_attention(q, kq.transpose(0, 2, 1, 3),
                               vq.transpose(0, 2, 1, 3), mask, scale,
                               k_scale=ks.transpose(0, 2, 1),
                               v_scale=vs.transpose(0, 2, 1),
                               block_s=None, interpret=True)
        err = np.abs(np.asarray(out) - np.asarray(ref)).max()
        assert err < 0.05, err

    def test_chunk_int8_8b_serving_shape(self):
        from bcg_tpu.ops.decode_attention import chunk_decode_attention

        B, K, S, H, Hkv, Dh = 2, 4, 2048, 32, 8, 128
        q, k, v, mask = _chunk_case(jax.random.PRNGKey(12), B, K, S, H, Hkv, Dh)
        scale = 1.0 / np.sqrt(Dh)
        ref = _xla_attention(q, k, v, mask, scale)
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        out = chunk_decode_attention(q, kq.transpose(0, 2, 1, 3),
                                     vq.transpose(0, 2, 1, 3), mask, scale,
                                     k_scale=ks.transpose(0, 2, 1),
                                     v_scale=vs.transpose(0, 2, 1),
                                     block_s=None, interpret=True)
        err = np.abs(np.asarray(out) - np.asarray(ref)).max()
        assert err < 0.05, err

    def test_int8_14b_group5_pads_rows(self):
        """14B dims (H=40, Hkv=8 -> GQA group 5): the wrapper pads the
        query-row axis to the next power of two so the kernel only sees
        probe-validated row counts; outputs must still match the
        unpadded reference exactly (padded rows sliced away)."""
        B, S, H, Hkv, Dh = 2, 2048, 40, 8, 128
        q, k, v, mask = _case(jax.random.PRNGKey(13), B, S, H, Hkv, Dh)
        scale = 1.0 / np.sqrt(Dh)
        ref = _reference(q, k, v, mask, scale)
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        out = decode_attention(q, kq.transpose(0, 2, 1, 3),
                               vq.transpose(0, 2, 1, 3), mask, scale,
                               k_scale=ks.transpose(0, 2, 1),
                               v_scale=vs.transpose(0, 2, 1),
                               block_s=None, interpret=True)
        assert out.shape == (B, H, Dh)
        err = np.abs(np.asarray(out) - np.asarray(ref)).max()
        assert err < 0.05, err

    def test_chunk_int8_14b_group5_pads_rows(self):
        from bcg_tpu.ops.decode_attention import chunk_decode_attention

        B, K, S, H, Hkv, Dh = 2, 4, 2048, 40, 8, 128
        q, k, v, mask = _chunk_case(jax.random.PRNGKey(14), B, K, S, H, Hkv, Dh)
        scale = 1.0 / np.sqrt(Dh)
        ref = _xla_attention(q, k, v, mask, scale)
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        out = chunk_decode_attention(q, kq.transpose(0, 2, 1, 3),
                                     vq.transpose(0, 2, 1, 3), mask, scale,
                                     k_scale=ks.transpose(0, 2, 1),
                                     v_scale=vs.transpose(0, 2, 1),
                                     block_s=None, interpret=True)
        assert out.shape == (B, K, H, Dh)
        err = np.abs(np.asarray(out) - np.asarray(ref)).max()
        assert err < 0.05, err
