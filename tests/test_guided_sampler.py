"""Fused guided-sampling Pallas kernel (ops/guided_sampler.py).

Four layers of guarantees:

* **Kernel parity** (interpret mode — the same program hardware
  lowers): greedy draws TOKEN-IDENTICAL to the XLA masked-sampler
  reference (engine/speculative.make_masked_sampler) across lane-
  aligned and off-lane vocabs, dead states, exhausted budgets, and the
  speculative loop's ``forbid`` residual; DFA transitions identical.
* **Distribution** (the sampled arm): draws stay inside the reference's
  filtered support and match its renormalized probabilities within 4
  sigma over thousands of seeded draws — the same statistical-contract
  idiom as the speculative loop's residual-distribution checks.
* **Engine integration**: ``fused_sampler="pallas"`` greedy outputs
  identical to the default across the plain, fast-forward, and
  speculative loop families; temp>0 still emits valid guided JSON;
  zero steady-state retraces for the fused loops' (new) jit entry
  keys; the env override and the stats surface agree; the geometry
  guard falls back LOUDLY (naming the knob) only on explicit pallas.
* **The win, gated**: the perf-gate ``sampler`` scenario's parity and
  engagement metrics conform to perf_baseline.json, with the
  load-bearing resurface contract owned here for the sampler.*
  namespace.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.engine.speculative import make_masked_logits, make_masked_sampler
from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.ops import guided_sampler as gs

SCHEMA = {
    "type": "object",
    "properties": {
        "decision": {"type": "string", "enum": ["stop", "continue"]},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
    },
    "required": ["decision", "value"],
    "additionalProperties": False,
}

PROMPTS = [
    ("You are honest agent_1 in a consensus game.",
     "Round 2. agent_2 value: 17. Decide.", SCHEMA),
    ("You are byzantine agent_2 in a consensus game.",
     "Round 2. agent_1 value: 16. Decide.", SCHEMA),
]


def _cfg(**kw):
    return EngineConfig(
        backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048,
        **kw,
    )


def _case(rng, B, V, n_dfa, n_states, minb_forbid=0.4):
    """One random sampler-argument set with realistic structure: int16
    tables/min_budget (the GuidedBatch dtypes), dead (-1) states,
    near-exhausted budgets, forbid on a third of the rows."""
    logits = jnp.asarray(rng.randn(B, V).astype(np.float32) * 3)
    tables = jnp.asarray(
        rng.randint(0, n_states, (n_dfa, n_states, V)).astype(np.int16)
    )
    accepting = jnp.asarray(rng.rand(n_dfa, n_states) < 0.5)
    minb = rng.randint(1, 6, (n_dfa, n_states, V)).astype(np.int16)
    minb[rng.rand(n_dfa, n_states, V) < minb_forbid] = np.iinfo(np.int16).max
    args = dict(
        tables=tables, accepting=accepting,
        min_budget=jnp.asarray(minb),
        dfa_ids=jnp.asarray(rng.randint(0, n_dfa, (B,)).astype(np.int32)),
        states=jnp.asarray(rng.randint(-1, n_states, (B,)).astype(np.int32)),
        emitted=jnp.asarray(rng.randint(0, 12, (B,)).astype(np.int32)),
        row_budget=jnp.asarray(rng.randint(2, 16, (B,)).astype(np.int32)),
        forbid=jnp.asarray(np.where(
            rng.rand(B) < 0.33, rng.randint(0, V, B), -1
        ).astype(np.int32)),
    )
    return logits, args


class TestKernelParity:
    """make_fused_sampler (interpret) vs make_masked_sampler, the
    conformance oracle.  Geometries: the tiny-test vocab (512,
    lane-aligned — what every hermetic engine test serves), an off-lane
    vocab (300 — exercises the wrapper's pad path), and a wide-DFA
    shape (the stacked-table form multi-schema batches produce)."""

    GEOMETRIES = [
        pytest.param(512, 2, 8, id="tiny-test-v512"),
        pytest.param(300, 2, 5, id="offlane-v300"),
        pytest.param(256, 4, 40, id="wide-dfa-40-states"),
    ]

    @pytest.mark.parametrize("top_p", [1.0, 0.9])
    @pytest.mark.parametrize("V,n_dfa,n_states", GEOMETRIES)
    def test_greedy_token_identical(self, V, n_dfa, n_states, top_p):
        rng = np.random.RandomState(V + n_states)
        eos = 3
        ref = make_masked_sampler(eos, top_p)
        fused = gs.make_fused_sampler(eos, top_p, interpret=True)
        for trial in range(8):
            logits, a = _case(rng, 8, V, n_dfa, n_states)
            key = jax.random.PRNGKey(trial)
            rt = jnp.zeros(8, jnp.float32)  # all greedy
            t_r, s_r, _ = ref(
                logits, a["states"], key, a["emitted"], a["tables"],
                a["accepting"], a["min_budget"], a["dfa_ids"], rt,
                a["row_budget"], forbid=a["forbid"],
            )
            t_f, s_f, _ = fused(
                logits, a["states"], key, a["emitted"], a["tables"],
                a["accepting"], a["min_budget"], a["dfa_ids"], rt,
                a["row_budget"], forbid=a["forbid"],
            )
            np.testing.assert_array_equal(np.asarray(t_r), np.asarray(t_f))
            np.testing.assert_array_equal(np.asarray(s_r), np.asarray(s_f))

    def test_dead_end_forces_eos(self):
        """A state with no legal token (everything past budget) must
        emit EOS with state -1 — the reference's post-draw override."""
        eos = 3
        fused = gs.make_fused_sampler(eos, 1.0, interpret=True)
        V, B = 256, 4
        logits = jnp.zeros((B, V), jnp.float32)
        minb = jnp.full((1, 2, V), np.iinfo(np.int16).max, jnp.int16)
        tok, states, _ = fused(
            logits, jnp.zeros(B, jnp.int32), jax.random.PRNGKey(0),
            jnp.zeros(B, jnp.int32), jnp.zeros((1, 2, V), jnp.int16),
            jnp.zeros((1, 2), bool), minb, jnp.zeros(B, jnp.int32),
            jnp.zeros(B, jnp.float32), jnp.full((B,), 8, jnp.int32),
        )
        assert (np.asarray(tok) == eos).all()
        assert (np.asarray(states) == -1).all()


class TestTopPDistribution:
    def test_sampled_arm_matches_reference_distribution_4_sigma(self):
        """The fused draw (threshold-scan nucleus + inverse-CDF binary
        search) against the reference's renormalized top-p
        distribution: every kept token's empirical frequency within 4
        sigma over 3000 seeded draws, and NO draw ever lands outside
        the reference's filtered support."""
        eos, top_p, B, V = 3, 0.8, 4, 64
        rng = np.random.RandomState(7)
        fused = gs.make_fused_sampler(eos, top_p, interpret=True)
        ml = make_masked_logits(eos, top_p)
        logits, a = _case(rng, B, V, 1, 4, minb_forbid=0.5)
        states = jnp.maximum(a["states"], 0)
        rt = jnp.full((B,), 0.8, jnp.float32)
        lg, _, _ = ml(
            logits, states, a["emitted"], a["tables"], a["accepting"],
            a["min_budget"], a["dfa_ids"], rt, a["row_budget"],
        )
        lg_np = np.asarray(lg)
        kept = np.isfinite(lg_np)
        probs = np.where(kept, np.exp(lg_np - lg_np.max(-1, keepdims=True)), 0.0)
        probs /= probs.sum(-1, keepdims=True)

        N = 3000
        counts = np.zeros((B, V))
        draw = jax.jit(lambda key: fused(
            logits, states, key, a["emitted"], a["tables"], a["accepting"],
            a["min_budget"], a["dfa_ids"], rt, a["row_budget"],
        )[0])
        for i in range(N):
            t = np.asarray(draw(jax.random.PRNGKey(i)))
            counts[np.arange(B), t] += 1
        # EOS-forced dead rows collapse to a point mass; exclude them
        # from the per-token bands (they trivially pass anyway).
        freq = counts / N
        for b in range(B):
            outside = counts[b][~kept[b]]
            # Dead-end rows force EOS, which may sit outside the mask.
            if probs[b].sum() == 0:
                continue
            assert outside.sum() == 0, f"row {b} drew outside the support"
            for t in range(V):
                p = probs[b, t]
                sd = np.sqrt(max(p * (1 - p), 1e-12) / N)
                assert abs(freq[b, t] - p) <= 4 * sd + 1e-9, (b, t, p, freq[b, t])


class TestEngineIntegration:
    @pytest.mark.parametrize("family_kw", [
        pytest.param({}, id="plain"),
        pytest.param({"decode_fast_forward": True}, id="ff"),
        pytest.param({"spec_decode": True}, id="spec"),
    ])
    def test_greedy_parity_across_loop_families(self, family_kw):
        ref = JaxEngine(_cfg(**family_kw))
        fused = JaxEngine(_cfg(fused_sampler="pallas", **family_kw))
        try:
            r_ref = ref.batch_generate_json(PROMPTS, temperature=0.0,
                                            max_tokens=48)
            r_fus = fused.batch_generate_json(PROMPTS, temperature=0.0,
                                              max_tokens=48)
        finally:
            ref.shutdown()
            fused.shutdown()
        assert r_ref == r_fus

    def test_sampled_rows_emit_valid_guided_json(self):
        """temp>0 through the fused kernel: the guided mask still
        guarantees parseable schema-conformant output (the seeded e2e
        arm of the distribution contract)."""
        eng = JaxEngine(_cfg(fused_sampler="pallas"))
        try:
            out = eng.batch_generate_json(PROMPTS, temperature=0.9,
                                          max_tokens=48)
        finally:
            eng.shutdown()
        for r in out:
            assert r.get("decision") in ("stop", "continue"), r
            assert 0 <= r.get("value", -1) <= 50, r

    def test_zero_steady_state_retraces_for_fused_entry_keys(self):
        """The fused loops' jit entry keys (loop key + sampler marker)
        pin at zero retraces on an identical-shape warm repeat — the
        fused sampler must not introduce shape-keyed instability."""
        eng = JaxEngine(_cfg(fused_sampler="pallas", spec_decode=True))
        try:
            eng.batch_generate_json(PROMPTS, temperature=0.0, max_tokens=48)
            before = obs_counters.snapshot()
            eng.batch_generate_json(PROMPTS, temperature=0.0, max_tokens=48)
            moved = obs_counters.delta(before)
        finally:
            eng.shutdown()
        jit_movement = {
            k: v for k, v in moved.items()
            if k.startswith(("engine.compile.", "engine.retrace."))
        }
        assert jit_movement == {}, jit_movement

    def test_env_flag_overrides_config_and_stats_reflect(self, monkeypatch):
        monkeypatch.setenv("BCG_TPU_FUSED_SAMPLER", "pallas")
        eng = JaxEngine(_cfg(fused_sampler="xla"))
        try:
            stats = eng.sampler_stats()
            assert stats["impl"] == "pallas"
            assert stats["interpret"] is True  # explicit pallas off-TPU
            assert stats["fused_calls"] == 0  # nothing ran yet
            eng.batch_generate_json(PROMPTS[:1], temperature=0.0,
                                    max_tokens=48)
            assert eng.sampler_stats()["fused_calls"] > 0
            assert eng.sampler_stats()["kv_dtype"] == "bfloat16"
        finally:
            eng.shutdown()

    def test_default_off_tpu_is_xla_and_namespace_clean(self):
        """auto resolves to xla off-TPU: no fused counters, no kernel —
        the configuration every existing baseline was recorded under."""
        eng = JaxEngine(_cfg())
        try:
            assert eng.sampler_stats()["impl"] == "xla"
            eng.batch_generate_json(PROMPTS[:1], temperature=0.0,
                                    max_tokens=48)
            assert eng.sampler_stats()["fused_calls"] == 0
        finally:
            eng.shutdown()


class TestGeometryGuardFallback:
    def test_explicit_pallas_over_guard_warns_naming_the_knob(
        self, monkeypatch
    ):
        """Explicit pallas with a vocab past MAX_VOCAB falls back LOUDLY
        through the shared _kernel_fallback_warn helper — the warning
        must name the causing knob (geometry guard), mirroring the int8
        decode kernel's cause attribution."""
        monkeypatch.setattr(gs, "MAX_VOCAB", 128)  # tiny-test vocab is 512
        with pytest.warns(UserWarning, match="geometry guard"):
            eng = JaxEngine(_cfg(fused_sampler="pallas"))
        try:
            assert eng.sampler_stats()["impl"] == "xla"
        finally:
            eng.shutdown()

    def test_auto_over_guard_is_silent(self, monkeypatch, recwarn):
        monkeypatch.setattr(gs, "MAX_VOCAB", 128)
        eng = JaxEngine(_cfg(fused_sampler="auto"))
        try:
            assert eng.sampler_stats()["impl"] == "xla"
        finally:
            eng.shutdown()
        assert not [
            w for w in recwarn if "fused guided-sampling" in str(w.message)
        ]

    def test_bad_value_raises(self):
        with pytest.raises(ValueError, match="fused_sampler"):
            JaxEngine(_cfg(fused_sampler="vulkan"))


# --------------------------------------------------------- gate-backed
SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "perf_gate.py")


@pytest.fixture(scope="module")
def sampler_gate_metrics():
    spec = importlib.util.spec_from_file_location("perf_gate", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, mod.run_sampler_scenario()


class TestGateBacked:
    def test_parity_is_exact_and_kernel_engaged(self, sampler_gate_metrics):
        _, m = sampler_gate_metrics
        assert m["sampler.parity_mismatches"] == 0.0
        assert m["sampler.fused_kernel_invocations"] > 0

    def test_metrics_conform_to_perf_baseline(self, sampler_gate_metrics):
        mod, m = sampler_gate_metrics
        findings = mod.check_metrics(m, mod.load_baseline())
        findings += mod.check_stale(m, mod.load_baseline(), ("sampler",))
        assert findings == [], findings

    def test_removing_a_sampler_entry_resurfaces_its_finding(
        self, sampler_gate_metrics
    ):
        mod, m = sampler_gate_metrics
        baseline = mod.load_baseline()
        for removed in m:
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(m, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)

    def test_injected_parity_regression_is_named(self, sampler_gate_metrics):
        mod, _ = sampler_gate_metrics
        measured = mod.run_sampler_scenario(inject="fail-rows")
        findings = mod.check_metrics(measured, mod.load_baseline())
        assert any("sampler.parity_mismatches" in f for f in findings), findings
