"""Cross-game batching (engine/collective.py).

A content-deterministic stub engine (response is a pure function of the
prompt) lets concurrent execution be compared exactly against sequential:
merged dispatch must route every row back to its caller unchanged.
Deadlock-freedom is exercised by games that terminate at different rounds
and by retry-desynchronized call patterns.
"""

import threading

import pytest

from bcg_tpu.engine.collective import CollectiveEngine, run_concurrent_simulations
from bcg_tpu.engine.interface import InferenceEngine


class StubEngine(InferenceEngine):
    """Pure-function engine: result depends only on the prompt row, so
    call order / batching cannot change outcomes.  Counts inner calls and
    records batch sizes so merging is observable."""

    def __init__(self):
        self.calls = []
        self.settings = []  # (temps, budgets) as lists, per inner call
        self.lock = threading.Lock()

    def _row(self, system_prompt, user_prompt, schema):
        h = abs(hash((system_prompt, user_prompt))) % 50
        if "enum" in str(schema):
            return {"decision": "stop" if h % 3 == 0 else "continue"}
        return {"internal_strategy": f"s{h}", "value": h,
                "public_reasoning": f"reason {h} for consensus"}

    def batch_generate_json(self, prompts, temperature=0.8, max_tokens=512):
        n = len(prompts)
        temps = list(temperature) if isinstance(temperature, (list, tuple)) \
            else [temperature] * n
        budgets = list(max_tokens) if isinstance(max_tokens, (list, tuple)) \
            else [max_tokens] * n
        with self.lock:
            self.calls.append(n)
            self.settings.append((temps, budgets))
        return [self._row(*p) for p in prompts]

    def generate_json(self, prompt, schema, temperature=0.0, max_tokens=512,
                      system_prompt=None):
        return self.batch_generate_json([(system_prompt or "", prompt, schema)],
                                        temperature, max_tokens)[0]

    def generate(self, prompt, temperature=0.0, max_tokens=256, top_p=1.0,
                 system_prompt=None):
        return "text"

    def batch_generate(self, prompts, temperature=0.0, max_tokens=256, top_p=1.0):
        with self.lock:
            self.calls.append(len(prompts))
        return ["text"] * len(prompts)

    def shutdown(self):
        pass


VOTE = {"type": "object",
        "properties": {"decision": {"enum": ["stop", "continue"]}}}
DECIDE = {"type": "object", "properties": {"value": {"type": "integer"}}}


class TestMergeAndScatter:
    def test_rows_route_back_to_callers(self):
        inner = StubEngine()
        coll = CollectiveEngine(inner, participants=3)
        results = {}

        def worker(name):
            prompts = [(f"sys-{name}", f"user-{name}-{i}", DECIDE) for i in range(4)]
            results[name] = coll.batch_generate_json(prompts, 0.5, 300)
            coll.retire()

        threads = [threading.Thread(target=worker, args=(n,)) for n in "abc"]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # One merged inner call of 12 rows, not three of 4.
        assert inner.calls == [12]
        for name in "abc":
            expect = inner.batch_generate_json(
                [(f"sys-{name}", f"user-{name}-{i}", DECIDE) for i in range(4)])
            assert results[name] == expect

    def test_mixed_phases_merge_with_per_row_settings(self):
        """A decide call (temp 0.5, 300 tok) and a vote call (0.3, 200)
        merge into ONE inner batch; settings ride per-row."""
        inner = StubEngine()
        coll = CollectiveEngine(inner, participants=2)
        out = {}

        def decider():
            out["d"] = coll.batch_generate_json([("s", "u", DECIDE)], 0.5, 300)
            coll.retire()

        def voter():
            out["v"] = coll.batch_generate_json([("s", "u2", VOTE)], 0.3, 200)
            coll.retire()

        ts = [threading.Thread(target=decider), threading.Thread(target=voter)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert inner.calls == [2]
        assert inner.settings == [([0.5, 0.3], [300, 200])] or \
            inner.settings == [([0.3, 0.5], [200, 300])]
        assert "value" in out["d"][0] and out["v"][0]["decision"] in ("stop", "continue")

    def test_error_propagates_to_all_callers(self):
        class Boom(StubEngine):
            def batch_generate_json(self, prompts, temperature=0.8, max_tokens=512):
                raise RuntimeError("device on fire")

        coll = CollectiveEngine(Boom(), participants=2)
        errs = []

        def worker():
            try:
                coll.batch_generate_json([("s", "u", DECIDE)], 0.5, 300)
            except RuntimeError as e:
                errs.append(str(e))
            finally:
                coll.retire()

        ts = [threading.Thread(target=worker) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == ["device on fire", "device on fire"]


class TestConcurrentSimulations:
    def _run(self, concurrency, runs=4):
        from bcg_tpu.api import run_simulation

        inner = StubEngine()

        def make(r):
            def go(engine):
                return run_simulation(
                    n_agents=3, byzantine_count=1, max_rounds=3 + r,
                    backend="fake", seed=r, engine=engine,
                )
            return go

        outs = run_concurrent_simulations(inner, [make(r) for r in range(runs)],
                                          concurrency)
        for o in outs:
            if isinstance(o, BaseException):
                raise o
        return inner, [o["metrics"] for o in outs]

    def test_concurrent_matches_sequential(self):
        _, seq = self._run(concurrency=1)
        _, conc = self._run(concurrency=4)
        assert conc == seq  # stub is content-deterministic → exact equality

    def test_different_game_lengths_no_deadlock(self):
        # max_rounds varies per run; retiring games shrink the barrier.
        inner, metrics = self._run(concurrency=3, runs=5)
        assert len(metrics) == 5
        assert all("consensus_reached" in m for m in metrics)

    def test_merging_happened(self):
        inner, _ = self._run(concurrency=4)
        # With 4 concurrent 4-agent games, early rounds must batch >4 rows.
        assert max(inner.calls) > 4


@pytest.mark.slow
class TestConcurrentGamesUnderMesh:
    def test_two_games_share_a_tp2_engine(self):
        """BENCH_CONCURRENCY on a pod slice: two lockstep games merge
        their phase batches into ONE tp=2-sharded JaxEngine — cross-game
        batching (engine/collective.py) composed with a real mesh, not a
        stub.  (The reference runs sweeps as sequential CLI invocations
        against its TP vLLM engine; here merged batches share each
        weight stream.)"""
        from bcg_tpu.api import run_simulation
        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.interface import create_engine

        eng = create_engine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=1024, tensor_parallel_size=2,
        ))
        try:

            def make(r):
                def go(engine):
                    return run_simulation(
                        n_agents=2, byzantine_count=1, max_rounds=2,
                        backend="jax", seed=r, engine=engine,
                    )
                return go

            outs = run_concurrent_simulations(
                eng, [make(r) for r in range(2)], 2
            )
            for o in outs:
                if isinstance(o, BaseException):
                    raise o
            assert eng.mesh is not None and eng.mesh.shape["tp"] == 2
            for o in outs:
                assert o["metrics"]["total_rounds"] >= 1
        finally:
            eng.shutdown()


class FlakyStub(StubEngine):
    """Returns an invalid decision for some rows on their first attempt,
    driving the orchestrator's retry ladder so concurrent games
    desynchronize (one re-deciding while others vote) — the barrier must
    still make progress and every game must complete."""

    def __init__(self, fail_every: int = 5):
        super().__init__()
        self.n = 0
        self.fail_every = fail_every

    def _row(self, system_prompt, user_prompt, schema):
        with self.lock:
            self.n += 1
            n = self.n
        if "enum" not in str(schema) and n % self.fail_every == 0:
            return {"error": "synthetic_failure"}
        return super()._row(system_prompt, user_prompt, schema)


class TestRetryDesyncStress:
    def test_flaky_engine_concurrent_games_complete(self):
        import random

        from bcg_tpu.api import run_simulation

        inner = FlakyStub(fail_every=5)

        def make(r):
            def go(engine):
                # Random thread-start jitter widens the interleavings.
                import time

                time.sleep(random.random() * 0.01)
                return run_simulation(
                    n_agents=4, byzantine_count=1, max_rounds=4,
                    backend="fake", seed=r, engine=engine,
                )
            return go

        outs = run_concurrent_simulations(inner, [make(r) for r in range(6)], 3)
        for o in outs:
            if isinstance(o, BaseException):
                raise o
        assert len(outs) == 6
        assert all("consensus_reached" in o["metrics"] for o in outs)


@pytest.mark.slow
class TestRealEngineIntegration:
    def test_two_concurrent_games_on_jax_engine(self):
        """Full-stack check: two simulation threads share one REAL JaxEngine
        through the collective barrier — merged guided batches, tiny model,
        games complete with coherent metrics."""
        from bcg_tpu.api import run_simulation
        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048,
        ))

        def make(r):
            def go(coll):
                return run_simulation(
                    n_agents=3, byzantine_count=1, max_rounds=2,
                    backend="jax", seed=r, engine=coll,
                )
            return go

        outs = run_concurrent_simulations(engine, [make(r) for r in range(2)], 2)
        engine.shutdown()
        for o in outs:
            if isinstance(o, BaseException):
                raise o
        assert all("consensus_reached" in o["metrics"] for o in outs)

    def test_merged_games_chunk_under_hbm_provisioner(self):
        """G merged games under a tight device-memory limit must CHUNK
        through the hbm_utilization provisioner instead of allocating the
        full merged-batch KV (the round-1 G=3/G=4 single-chip OOM class).
        Games still complete with coherent metrics."""
        from bcg_tpu.api import run_simulation
        from bcg_tpu.config import EngineConfig
        from bcg_tpu.engine.jax_engine import JaxEngine

        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=512,
        ))
        # Tight budget: roughly three rows' worth of worst-case cache
        # above the (tiny) weights — a merged 2x3-agent batch must split.
        per_row_worst = 900 * engine.spec.num_kv_heads * engine.spec.head_dim \
            * 4 * engine.spec.num_layers
        engine._mem_limit = int(
            (engine._param_bytes + 3.2 * per_row_worst)
            / engine.config.hbm_utilization
        )

        def make(r):
            def go(coll):
                return run_simulation(
                    n_agents=3, byzantine_count=1, max_rounds=2,
                    backend="jax", seed=r, engine=coll,
                )
            return go

        outs = run_concurrent_simulations(engine, [make(r) for r in range(2)], 2)
        events = engine.provision_chunk_events
        engine.shutdown()
        for o in outs:
            if isinstance(o, BaseException):
                raise o
        assert all("consensus_reached" in o["metrics"] for o in outs)
        assert events >= 1, "provisioner never engaged on the merged batch"


class TestExperimentsConcurrency:
    def test_run_preset_concurrent(self):
        from bcg_tpu.experiments import PRESETS, run_preset

        out = run_preset(PRESETS["q1-baseline"], runs=3, backend="fake",
                         max_rounds=4, seed=0, concurrency=3)
        assert len(out["per_run"]) == 3
        assert "consensus_rate" in out["aggregate"] or out["aggregate"]


class TestWatchdog:
    def test_dead_thread_without_retire_is_force_retired(self, monkeypatch):
        """A watched worker that dies WITHOUT retiring (the crash shape
        the barrier docstring warns about) no longer hangs the barrier:
        with BCG_TPU_COLLECTIVE_WATCHDOG_S set, a waiting caller reaps it
        and dispatch proceeds."""
        monkeypatch.setenv("BCG_TPU_COLLECTIVE_WATCHDOG_S", "1")
        coll = CollectiveEngine(StubEngine(), participants=2)

        dead = threading.Thread(target=lambda: None)
        coll.watch(dead)
        dead.start()
        dead.join()  # died without retire()

        out = {}

        def worker():
            out["r"] = coll.batch_generate_json([("s", "u", DECIDE)], 0.5, 300)
            coll.retire()

        t = threading.Thread(target=worker)
        coll.watch(t)
        t.start()
        t.join(timeout=10)
        assert not t.is_alive(), "barrier hung despite the watchdog"
        assert "value" in out["r"][0]

    def test_retire_idempotent_after_force_retire(self, monkeypatch):
        """A worker whose thread the watchdog already reaped must not
        shrink the barrier twice when its own retire() still runs."""
        monkeypatch.setenv("BCG_TPU_COLLECTIVE_WATCHDOG_S", "1")
        coll = CollectiveEngine(StubEngine(), participants=2)
        me = threading.current_thread()
        with coll._cond:
            coll._watched[me] = True  # simulate: watchdog reaped us
            coll._active -= 1
        coll.retire()  # our own (late) retire must be a no-op
        assert coll._active == 1

    def test_watchdog_off_keeps_legacy_behavior(self):
        """Default (flag unset): watch() bookkeeping alone must not
        change barrier arithmetic for normally-retiring workers."""
        inner = StubEngine()
        coll = CollectiveEngine(inner, participants=2)
        results = {}

        def worker(name):
            results[name] = coll.batch_generate_json(
                [(f"s-{name}", f"u-{name}", DECIDE)], 0.5, 300)
            coll.retire()

        ts = [threading.Thread(target=worker, args=(n,)) for n in "ab"]
        for t in ts:
            coll.watch(t)
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert inner.calls == [2]
        assert set(results) == {"a", "b"}
