"""Experiment presets + multi-host mesh helpers (hermetic, fake engine /
virtual CPU devices)."""

import jax
import pytest

from bcg_tpu.experiments import PRESETS, aggregate, run_preset, run_scale_sweep
from bcg_tpu.parallel.distributed import build_hybrid_mesh, process_info


class TestExperiments:
    def test_q1_baseline_runs_and_aggregates(self):
        out = run_preset(PRESETS["q1-baseline"], runs=2, backend="fake",
                         max_rounds=5, seed=0)
        agg = out["aggregate"]
        assert agg["runs"] == 2
        assert 0.0 <= agg["consensus_rate"] <= 1.0
        assert agg["mean_rounds"] is not None
        assert len(out["per_run"]) == 2

    def test_q2_has_byzantine_metrics(self):
        out = run_preset(PRESETS["q2"], runs=1, backend="fake", max_rounds=5, seed=1)
        m = out["per_run"][0]
        assert m["num_byzantine"] == 2

    def test_seeded_runs_reproduce(self):
        a = run_preset(PRESETS["q1-baseline"], runs=1, backend="fake",
                       max_rounds=5, seed=7)
        b = run_preset(PRESETS["q1-baseline"], runs=1, backend="fake",
                       max_rounds=5, seed=7)
        assert a["per_run"][0]["total_rounds"] == b["per_run"][0]["total_rounds"]
        assert a["per_run"][0]["consensus_value"] == b["per_run"][0]["consensus_value"]

    def test_scale_sweep_byzantine_fraction(self):
        outs = run_scale_sweep([8], byzantine_fraction=0.25, runs=1,
                               backend="fake", max_rounds=3, seed=0)
        assert outs[0]["per_run"][0]["num_byzantine"] == 2
        assert outs[0]["per_run"][0]["num_honest"] == 6

    def test_model_sweep_runs_each_model(self):
        from bcg_tpu.experiments import run_model_sweep

        outs = run_model_sweep(
            ["bcg-tpu/tiny-test", "bcg-tpu/bench-1b"], runs=1,
            backend="fake", max_rounds=3, seed=0,
        )
        assert [o["preset"] for o in outs] == [
            "model-sweep:bcg-tpu/tiny-test", "model-sweep:bcg-tpu/bench-1b",
        ]
        for o in outs:
            # Q2 composition (8H+2B) per BASELINE.json config 5.
            assert o["per_run"][0]["num_byzantine"] == 2
            assert o["per_run"][0]["num_honest"] == 8

    def test_drop_prob_routes_over_lossy_channel(self, monkeypatch):
        import bcg_tpu.comm.lossy_sim as ls

        built = []
        orig = ls.LossySimProtocol.__init__

        def spy(self, *a, **k):
            built.append(k.get("drop_prob"))
            return orig(self, *a, **k)

        monkeypatch.setattr(ls.LossySimProtocol, "__init__", spy)
        out = run_preset(PRESETS["q1-baseline"], runs=1, backend="fake",
                         max_rounds=4, seed=5, drop_prob=0.5)
        assert built == [0.5]  # the game really ran over the lossy channel
        assert out["aggregate"]["runs"] == 1

    def test_aggregate_empty_values(self):
        agg = aggregate([{"consensus_reached": True, "total_rounds": 3}])
        assert agg["byzantine_infiltration_rate"] is None
        assert agg["consensus_rate"] == 1.0


class TestHybridMesh:
    # conftest forces 8 virtual CPU devices.

    def test_full_dp(self):
        mesh = build_hybrid_mesh(tp=1, sp=1)
        assert mesh.shape == {"dp": 8, "tp": 1, "sp": 1}

    def test_tp_sp_inner(self):
        mesh = build_hybrid_mesh(tp=2, sp=2)
        assert mesh.shape == {"dp": 2, "tp": 2, "sp": 2}

    def test_explicit_dp_subset(self):
        mesh = build_hybrid_mesh(tp=2, sp=1, dp=2)
        assert mesh.shape == {"dp": 2, "tp": 2, "sp": 1}

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            build_hybrid_mesh(tp=3, sp=1)

    def test_oversize_raises(self):
        with pytest.raises(ValueError):
            build_hybrid_mesh(tp=2, sp=2, dp=4)

    def test_process_info_single_host(self):
        info = process_info()
        assert info["process_count"] == 1
        assert info["global_device_count"] == jax.device_count()


class TestScale64:
    """BASELINE scale-sweep sizes (16/32/64 agents): the game, comm, and
    metrics layers must handle the O(N^2) message fan-out and the
    statistics payload at the largest configured sweep size."""

    def test_64_agent_game_end_to_end(self):
        from bcg_tpu.api import run_simulation

        out = run_simulation(
            n_agents=64, max_rounds=4, byzantine_count=16,
            backend="fake", seed=9,
        )
        m = out["metrics"]
        assert m["num_honest"] == 48
        assert m["num_byzantine"] == 16
        assert m["total_agents"] == 64
        assert 1 <= m["total_rounds"] <= 4
        # Per-round record splits all 64 agents' values by role.
        r0 = m["rounds_data"][0]
        assert len(r0["honest_values"]) == 48
        # The fake Byzantine policy proposes (does not abstain), so
        # every Byzantine agent's value must be recorded.
        assert len(r0["byzantine_values"]) == 16

    def test_scale_sweep_multiple_sizes(self):
        outs = run_scale_sweep(
            [16, 32, 64], byzantine_fraction=0.25, runs=1,
            backend="fake", max_rounds=3,
        )
        assert len(outs) == 3
        for o in outs:
            assert o["aggregate"]["runs"] == 1
