"""Unit tests for the consensus game state machine.

Covers the reference semantics the SURVEY calls out: unanimity-on-honest-
initial-value consensus (byzantine_consensus.py:182-249), 2/3 stop vote
(:373-398), deadline-always-loses (:507-518), and the 1/2-stop milestone
(:314-371), plus edge cases with 0/1 honest values.
"""

import pytest

from bcg_tpu.game import ByzantineConsensusGame


def make_game(nh=4, nb=0, seed=0, **kw):
    return ByzantineConsensusGame(
        num_honest=nh, num_byzantine=nb, seed=seed, **kw
    )


def set_all(game, value):
    for aid in game.agents:
        game.update_agent_proposal(aid, value)


class TestInit:
    def test_seeded_determinism(self):
        g1, g2 = make_game(seed=42, nb=2, nh=6), make_game(seed=42, nb=2, nh=6)
        assert {a: s.initial_value for a, s in g1.agents.items()} == {
            a: s.initial_value for a, s in g2.agents.items()
        }
        assert [s.is_byzantine for s in g1.agents.values()] == [
            s.is_byzantine for s in g2.agents.values()
        ]

    def test_byzantine_have_no_initial_value(self):
        g = make_game(nh=3, nb=2, seed=1)
        for s in g.agents.values():
            if s.is_byzantine:
                assert s.initial_value is None and s.current_value is None
            else:
                lo, hi = g.value_range
                assert lo <= s.initial_value <= hi

    def test_counts(self):
        g = make_game(nh=5, nb=3, seed=7)
        byz = sum(s.is_byzantine for s in g.agents.values())
        assert byz == 3 and len(g.agents) == 8


class TestConsensus:
    def test_unanimous_on_initial_value_is_consensus(self):
        g = make_game(nh=4, seed=0)
        target = g.agents["agent_0"].initial_value
        set_all(g, target)
        g.apply_proposals()
        ok, pct = g.check_consensus()
        assert ok and pct == 100.0

    def test_unanimous_on_non_initial_value_is_not_consensus(self):
        g = make_game(nh=4, seed=0, value_range=(0, 50))
        initials = {s.initial_value for s in g.agents.values()}
        outsider = next(v for v in range(0, 51) if v not in initials)
        set_all(g, outsider)
        g.apply_proposals()
        ok, pct = g.check_consensus()
        assert not ok and pct == 100.0

    def test_partial_agreement(self):
        g = make_game(nh=4, seed=0)
        vals = [10, 10, 10, 20]
        for aid, v in zip(sorted(g.agents), vals):
            g.update_agent_proposal(aid, v)
        g.apply_proposals()
        ok, pct = g.check_consensus()
        assert not ok and pct == 75.0

    def test_byzantine_values_ignored(self):
        g = make_game(nh=3, nb=2, seed=3)
        honest = [aid for aid, s in g.agents.items() if not s.is_byzantine]
        target = g.agents[honest[0]].initial_value
        for aid in honest:
            g.update_agent_proposal(aid, target)
        for aid, s in g.agents.items():
            if s.is_byzantine:
                g.update_agent_proposal(aid, target + 1)
        g.apply_proposals()
        ok, _ = g.check_consensus()
        assert ok

    def test_single_honest_value_trivial_consensus(self):
        g = make_game(nh=1, nb=1, seed=0)
        aid = next(a for a, s in g.agents.items() if not s.is_byzantine)
        g.update_agent_proposal(aid, g.agents[aid].initial_value)
        g.apply_proposals()
        ok, pct = g.check_consensus()
        assert ok and pct == 100.0

    def test_all_abstained_no_consensus(self):
        g = make_game(nh=0, nb=2, seed=0)
        ok, pct = g.check_consensus()
        assert not ok and pct == 0.0


class TestVoting:
    def test_two_thirds_terminates(self):
        g = make_game(nh=3, seed=0)
        ids = sorted(g.agents)
        assert g.should_terminate_by_vote({ids[0]: True, ids[1]: True, ids[2]: False})
        assert not g.should_terminate_by_vote(
            {ids[0]: True, ids[1]: False, ids[2]: False}
        )

    def test_abstain_does_not_count_as_stop(self):
        g = make_game(nh=3, seed=0)
        ids = sorted(g.agents)
        assert not g.should_terminate_by_vote(
            {ids[0]: True, ids[1]: None, ids[2]: None}
        )

    def test_vote_breakdown_by_role(self):
        g = make_game(nh=2, nb=1, seed=5)
        byz = next(a for a, s in g.agents.items() if s.is_byzantine)
        honest = [a for a, s in g.agents.items() if not s.is_byzantine]
        info = g.get_all_termination_votes({byz: True, honest[0]: True, honest[1]: None})
        assert info["byzantine_stop_votes"] == 1
        assert info["honest_stop_votes"] == 1
        assert info["honest_abstentions"] == 1
        assert info["total_abstentions"] == 1


class TestTermination:
    def test_vote_with_consensus_wins(self):
        g = make_game(nh=3, seed=0)
        target = g.agents["agent_0"].initial_value
        set_all(g, target)
        g.advance_round({aid: True for aid in g.agents})
        assert g.game_over and g.honest_agents_won
        assert g.termination_reason == "vote_with_consensus"
        assert g.consensus_value == target

    def test_vote_without_consensus_loses(self):
        g = make_game(nh=3, seed=0)
        for i, aid in enumerate(sorted(g.agents)):
            g.update_agent_proposal(aid, i * 10)
        g.advance_round({aid: True for aid in g.agents})
        assert g.game_over and g.honest_agents_won is False
        assert g.termination_reason == "vote_without_consensus"

    def test_deadline_always_loses_even_with_agreement(self):
        g = make_game(nh=3, seed=0, max_rounds=2)
        target = g.agents["agent_0"].initial_value
        for _ in range(2):
            set_all(g, target)
            g.advance_round({aid: False for aid in g.agents})
        assert g.game_over
        assert g.termination_reason == "max_rounds"
        assert g.honest_agents_won is False
        assert g.consensus_reached is False

    def test_half_stop_milestone_recorded_once(self):
        g = make_game(nh=4, seed=0, max_rounds=10)
        ids = sorted(g.agents)
        set_all(g, g.agents[ids[0]].initial_value)
        g.advance_round({ids[0]: True, ids[1]: True, ids[2]: False, ids[3]: False})
        assert g.first_half_stop_reached
        assert g.first_half_stop_info["round"] == 1
        first = g.first_half_stop_info
        set_all(g, g.agents[ids[0]].initial_value)
        g.advance_round({aid: True for aid in ids})
        assert g.first_half_stop_info is first  # not overwritten

    def test_game_state_hides_byzantine_identity(self):
        g = make_game(nh=2, nb=2, seed=0)
        state = g.get_game_state()
        for payload in state["agent_states"].values():
            assert "is_byzantine" not in payload


class TestCheckpoint:
    def test_snapshot_roundtrip(self):
        import json

        g = make_game(nh=3, nb=1, seed=9, max_rounds=5)
        set_all(g, 7)
        g.advance_round({aid: False for aid in g.agents})
        blob = json.dumps(g.snapshot())
        g2 = ByzantineConsensusGame.from_snapshot(json.loads(blob))
        assert g2.current_round == g.current_round
        assert g2.get_game_state() == g.get_game_state()
        # RNG stream continues identically after restore.
        assert g.rng.randint(0, 10**9) == g2.rng.randint(0, 10**9)
