"""Runtime host-sync auditor (bcg_tpu/obs/hostsync.py) in tier-1.

ISSUE-12 contracts asserted here:

* **Zero surface off** — with ``BCG_TPU_HOSTSYNC`` unset the module is
  inert: nothing registered, nothing intercepted, and the Prometheus
  exposition of an audited run minus the audit namespace is
  BYTE-identical to an unaudited run of the same workload (subprocess
  pin); the tracer export carries no trace of the namespace.
* **Attribution** — span-first (the innermost open tracer span), jit-
  entry fallback when tracing is off, unattributed syncs counted rather
  than dropped; >= 95% coverage in the hermetic perf_gate scenario.
* **Surfaces** — the ``game.host_syncs`` per-round histogram observed
  around the orchestrator's round span, the serve ``SchedulerStats``
  ``hostsync`` block, and the ``runtime.metrics.LAST_HOSTSYNC`` publish
  bench.py attaches on success and error paths.
* **Drift gate** — the perf_gate ``hostsync`` scenario is green against
  justified ``perf_baseline.json`` entries, ``--inject-regression
  hostsync-off`` fails naming the metrics, and removing any
  ``hostsync.*`` entry resurfaces an unbaselined-metric finding (this
  file is the namespace's registered owner —
  tests/test_perf_gate.py NAMESPACE_OWNERS).
* **Static↔runtime cross-link** — every justified ``BCG-HOST-SYNC``
  suppression in ``lint_baseline.json`` must register its runtime
  verification in ``HOST_SYNC_SUPPRESSION_COVERAGE`` below, so static
  baseline entries stop being unverifiable prose.
* **Disabled overhead** — auditing compiled in but off adds <5% to the
  straggler micro-benchmark's wall-clock (the PR 4 tracer idiom:
  no-op unit cost x call volume).
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.serve import run_serving_simulations
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.engine.interface import InferenceEngine
from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.obs import hostsync as obs_hostsync
from bcg_tpu.obs import tracer as obs_tracer
from bcg_tpu.runtime import metrics as runtime_metrics
from bcg_tpu.serve.scheduler import Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE_SCRIPT = os.path.join(REPO, "scripts", "perf_gate.py")


def _load_gate():
    spec = importlib.util.spec_from_file_location("perf_gate", GATE_SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def audited(monkeypatch):
    monkeypatch.setenv("BCG_TPU_HOSTSYNC", "1")
    obs_hostsync.reset()
    yield obs_hostsync.auditor()
    obs_hostsync.reset()


@pytest.fixture
def unaudited(monkeypatch):
    monkeypatch.delenv("BCG_TPU_HOSTSYNC", raising=False)
    obs_hostsync.reset()
    yield
    obs_hostsync.reset()


@pytest.fixture
def untraced(monkeypatch):
    monkeypatch.delenv("BCG_TPU_TRACE", raising=False)
    monkeypatch.delenv("BCG_TPU_TRACE_OUT", raising=False)
    obs_tracer.reset()
    yield
    obs_tracer.reset()


@pytest.fixture
def traced(monkeypatch):
    monkeypatch.setenv("BCG_TPU_TRACE", "1")
    monkeypatch.delenv("BCG_TPU_TRACE_OUT", raising=False)
    obs_tracer.reset()
    yield obs_tracer.get_tracer()
    obs_tracer.reset()


# The deterministic hermetic workload every surface test runs: the
# perf_gate scenario's converging FakeEngine game geometry.
def _run_game():
    return run_simulation(
        n_agents=5, byzantine_count=1, max_rounds=6, backend="fake", seed=7,
    )


# Worker for the exact-bytes subprocess pin: plays the game, bumps one
# deterministic non-audit counter (so the unaudited exposition is
# non-empty and the byte comparison can't pass vacuously), prints the
# exposition.
_EXPO_WORKER = """
import sys
sys.path.insert(0, sys.argv[1])
from bcg_tpu.api import run_simulation
from bcg_tpu.obs import counters as obs_counters, export as obs_export
out = run_simulation(n_agents=5, byzantine_count=1, max_rounds=6,
                     backend="fake", seed=7)
assert out["metrics"]["total_rounds"] >= 1
obs_counters.inc("engine.probe", 3)
sys.stdout.write(obs_export.render_prometheus())
"""


class TestZeroSurface:
    """Acceptance: flag off => no counters registered, no interception
    installed, exposition and tracer export byte-identical to pre-PR."""

    def test_disabled_module_is_inert(self, unaudited):
        before = set(obs_counters.snapshot())
        assert obs_hostsync.auditor() is None
        assert not obs_hostsync.enabled()
        obs_hostsync.note("probe_site", entry="decode_loop")
        with obs_hostsync.jit_entry("prefill"):
            obs_hostsync.note("probe_site")
        obs_hostsync.publish()
        assert obs_hostsync.total() == 0
        assert obs_hostsync.summary() is None
        FakeEngine(seed=0, policy="consensus").batch_generate_json(
            [("sys", "Round 1. Decide.", {"type": "object"})]
        )
        _run_game()
        new = set(obs_counters.snapshot()) - before
        assert not [n for n in new if "hostsync" in n or "host_syncs" in n], new

    def test_disabled_leaves_device_get_unwrapped(self, unaudited):
        import jax

        assert jax.device_get.__name__ != "_audited_device_get"

    def test_exposition_exact_bytes_vs_unaudited_subprocess(self):
        """The only exposition difference an enabled auditor may make
        is the audit namespace itself: filtering ``hostsync`` /
        ``host_syncs`` lines out of the audited run's exposition must
        reproduce the unaudited run's exposition EXACTLY, byte for
        byte (fresh subprocess per arm = a pristine registry, which an
        in-process test cannot get back once other tests registered
        audit counters)."""
        def scrape(flag_on: bool) -> str:
            env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
            env.pop("BCG_TPU_HOSTSYNC", None)
            if flag_on:
                env["BCG_TPU_HOSTSYNC"] = "1"
            proc = subprocess.run(
                [sys.executable, "-c", _EXPO_WORKER, REPO],
                capture_output=True, text=True, timeout=180, env=env,
                cwd=REPO,
            )
            assert proc.returncode == 0, proc.stderr
            return proc.stdout

        expo_off = scrape(flag_on=False)
        expo_on = scrape(flag_on=True)
        assert "bcg_engine_probe_total" in expo_off  # non-vacuous
        assert "hostsync" not in expo_off
        # The audited run really surfaced the namespace...
        # (the dotted name already ends in "total": the exposition's
        # counter-suffix rule does not double it)
        assert "bcg_engine_hostsync_total " in expo_on
        assert "bcg_game_host_syncs_bucket" in expo_on
        # ... and removing it reproduces the unaudited bytes exactly.
        kept = [
            line for line in expo_on.splitlines()
            if "hostsync" not in line and "host_syncs" not in line
        ]
        filtered = "\n".join(kept) + ("\n" if kept else "")
        assert filtered == expo_off

    def test_tracer_export_carries_no_audit_when_off(self, unaudited,
                                                     traced):
        _run_game()
        export = traced.export()
        assert "hostsync" not in json.dumps(export)
        assert "host_syncs" not in json.dumps(export)


class TestAttribution:
    def _delta(self, before):
        return {
            k: v for k, v in obs_counters.delta(before).items()
            if k.startswith("engine.hostsync.")
        }

    def test_span_attribution_wins_over_entry(self, audited, traced):
        before = obs_counters.snapshot()
        with obs_tracer.span("decide"):
            obs_hostsync.note("probe_site", entry="decode_loop")
        moved = self._delta(before)
        assert moved["engine.hostsync.span.decide"] == 1
        assert moved["engine.hostsync.attributed"] == 1
        assert "engine.hostsync.span.jit_decode_loop" not in moved

    def test_span_names_sanitize_into_the_taxonomy(self, audited, traced):
        before = obs_counters.snapshot()
        with obs_tracer.span("serve.request"):
            obs_hostsync.note("probe_site")
        moved = self._delta(before)
        assert moved["engine.hostsync.span.serve_request"] == 1

    def test_jit_entry_attribution_with_tracing_off(self, audited,
                                                    untraced):
        """Satellite: auditor on, tracing off — syncs still attribute,
        to jit-entry names (explicit ``entry=`` and the thread-local
        stack both)."""
        before = obs_counters.snapshot()
        obs_hostsync.note("probe_site", entry="decode_loop")
        with obs_hostsync.jit_entry("prefill"):
            obs_hostsync.note("probe_site")
        moved = self._delta(before)
        assert moved["engine.hostsync.span.jit_decode_loop"] == 1
        assert moved["engine.hostsync.span.jit_prefill"] == 1
        assert moved["engine.hostsync.attributed"] == 2
        assert "engine.hostsync.unattributed" not in moved

    def test_unattributed_syncs_are_counted_not_dropped(self, audited,
                                                        untraced):
        before = obs_counters.snapshot()
        obs_hostsync.note("orphan_site")
        moved = self._delta(before)
        assert moved["engine.hostsync.total"] == 1
        assert moved["engine.hostsync.unattributed"] == 1
        assert moved["engine.hostsync.span.unattributed"] == 1

    def test_device_get_interception_counts_and_uninstalls(self, audited):
        import jax
        import numpy as np

        assert jax.device_get.__name__ == "_audited_device_get"
        before = obs_counters.snapshot()
        jax.device_get(np.arange(3))
        moved = self._delta(before)
        assert moved["engine.hostsync.site.device_get"] == 1
        obs_hostsync.reset()
        assert jax.device_get.__name__ != "_audited_device_get"

    def test_site_table_and_summary_shape(self, audited, untraced):
        obs_hostsync.note("probe_site", n=3, entry="decode_loop")
        summary = obs_hostsync.summary()
        assert summary["total"] >= 3
        assert summary["by_site"]["probe_site"] >= 3
        assert summary["by_span"]["jit_decode_loop"] >= 3
        assert 0.0 <= summary["attribution_coverage"] <= 1.0


class TestRoundHistogram:
    def test_game_observes_syncs_per_round(self, audited, untraced):
        """The orchestrator observes each round's sync delta into
        game.host_syncs: a lockstep FakeEngine round is 2 batched
        engine calls (decide + vote) x 3 mirrored decode-path syncs —
        ROADMAP item 1's baseline structure."""
        rounds_before = obs_counters.value("game.host_syncs.count")
        syncs_before = obs_counters.value("game.host_syncs.sum")
        out = _run_game()
        rounds = obs_counters.value("game.host_syncs.count") - rounds_before
        syncs = obs_counters.value("game.host_syncs.sum") - syncs_before
        assert rounds == out["metrics"]["total_rounds"]
        assert syncs / rounds == 6.0

    def test_game_syncs_attribute_fully(self, audited, untraced):
        before_total = obs_counters.value("engine.hostsync.total")
        before_attr = obs_counters.value("engine.hostsync.attributed")
        _run_game()
        total = obs_counters.value("engine.hostsync.total") - before_total
        attr = obs_counters.value("engine.hostsync.attributed") - before_attr
        assert total > 0
        assert attr == total

    def test_overlapping_rounds_are_counted_not_observed(self, audited,
                                                         untraced):
        """Concurrent games share one process-wide sync total, so a
        round overlapping another cannot be split honestly — it must be
        COUNTED (engine.hostsync.rounds_overlapped), never observed
        wrong into the histogram or dropped silently."""
        hist_before = obs_counters.value("game.host_syncs.count")
        overlap_before = obs_counters.value(
            "engine.hostsync.rounds_overlapped"
        )
        w1 = audited.begin_round()
        w2 = audited.begin_round()  # a second game's round opens
        obs_hostsync.note("probe_site", entry="decode_loop")
        audited.end_round(w2)
        audited.end_round(w1)
        assert obs_counters.value(
            "engine.hostsync.rounds_overlapped"
        ) - overlap_before == 2
        assert obs_counters.value("game.host_syncs.count") == hist_before
        # A fresh, un-overlapped round observes again.
        w3 = audited.begin_round()
        audited.end_round(w3)
        assert obs_counters.value(
            "game.host_syncs.count"
        ) == hist_before + 1

    def test_spec_mirror_carries_the_spec_readbacks(self, audited,
                                                    untraced, monkeypatch):
        """The real spec loop reads drafted/accepted vectors back (2
        extra syncs per call) and attributes EVERY post-loop readback
        to its own entry name: the FakeEngine mirror must carry the
        same 5-syncs-per-call, jit_spec_decode_loop-attributed profile
        when BCG_TPU_SPEC is on."""
        monkeypatch.setenv("BCG_TPU_SPEC", "1")
        before = obs_counters.snapshot()
        FakeEngine(seed=0, policy="consensus").batch_generate_json(
            [("sys", "Round 1. Decide.", {"type": "object"})]
        )
        moved = obs_counters.delta(before)
        assert moved["engine.hostsync.total"] == 5
        assert moved["engine.hostsync.site.spec_readback"] == 2
        # decode_readback + steps_readback + 2x spec_readback all land
        # under the spec loop's entry (jax_engine.py loop_entry parity).
        assert moved["engine.hostsync.span.jit_spec_decode_loop"] == 4
        assert "engine.hostsync.span.jit_decode_loop" not in moved

    def test_failed_round_does_not_poison_future_rounds(self, audited,
                                                        untraced):
        """A round that raises must still close its audit window: a
        leaked entry would mark every later round overlapped and
        silently stop the game.host_syncs histogram for the process."""
        class _Boom(InferenceEngine):
            def batch_generate_json(self, prompts, temperature=0.8,
                                    max_tokens=512):
                raise RuntimeError("injected engine failure")

            def generate_json(self, prompt, schema, temperature=0.0,
                              max_tokens=512, system_prompt=None):
                raise RuntimeError("injected engine failure")

            def generate(self, prompt, temperature=0.0, max_tokens=256,
                         top_p=1.0, system_prompt=None):
                raise RuntimeError("injected engine failure")

            def batch_generate(self, prompts, temperature=0.0,
                               max_tokens=256, top_p=1.0):
                raise RuntimeError("injected engine failure")

            def shutdown(self):
                pass

        hist_before = obs_counters.value("game.host_syncs.count")
        with pytest.raises(RuntimeError):
            run_simulation(n_agents=2, byzantine_count=0, max_rounds=1,
                           backend="fake", seed=0, engine=_Boom())
        # The failed round observed nothing...
        assert obs_counters.value("game.host_syncs.count") == hist_before
        # ... and did not leak its window: the next round still
        # observes as un-overlapped.
        window = audited.begin_round()
        audited.end_round(window)
        assert obs_counters.value(
            "game.host_syncs.count"
        ) == hist_before + 1

    def test_round_span_attribution_when_traced(self, audited, traced):
        """With tracing on the mirror's syncs attribute to the engine
        span names (span wins over the jit-entry tag)."""
        before = obs_counters.snapshot()
        _run_game()
        moved = obs_counters.delta(before)
        assert moved.get("engine.hostsync.span.engine_prefill", 0) > 0
        assert moved.get("engine.hostsync.span.engine_decode", 0) > 0


class TestSchedulerSnapshot:
    def test_snapshot_carries_per_request_sync_counts(self, audited,
                                                      untraced):
        sched = Scheduler(
            FakeEngine(seed=0, policy="consensus"), linger_ms=0,
            bucket_rows=4, max_queue_rows=64, deadline_ms=0,
            strict_admission=False,
        )
        payload = [("sys", "Round 1. Decide.",
                    {"type": "object", "properties": {},
                     "additionalProperties": True})]
        try:
            for _ in range(3):
                sched.submit_and_wait(("json",), list(payload), [0.0], [16])
            snap = sched.snapshot()
        finally:
            sched.close()
        hs = snap["hostsync"]
        assert hs is not None
        # 3 mirrored syncs per dispatched batch.
        assert hs["syncs"] == 3 * snap["dispatches"]
        assert hs["syncs_per_dispatch"] == 3.0
        assert hs["syncs_per_request"] == round(
            hs["syncs"] / snap["completed"], 4
        )

    def test_snapshot_block_is_none_when_off(self, unaudited):
        sched = Scheduler(
            FakeEngine(seed=0, policy="consensus"), linger_ms=0,
            bucket_rows=4, max_queue_rows=64, deadline_ms=0,
            strict_admission=False,
        )
        try:
            snap = sched.snapshot()
        finally:
            sched.close()
        assert snap["hostsync"] is None


class TestBenchPublish:
    def test_last_hostsync_published_on_engine_calls(self, audited,
                                                     untraced):
        runtime_metrics.publish_hostsync(None)
        FakeEngine(seed=0, policy="consensus").batch_generate_json(
            [("sys", "Round 1. Decide.", {"type": "object"})]
        )
        last = runtime_metrics.LAST_HOSTSYNC
        assert last is not None
        assert last["total"] >= 3
        assert "by_site" in last and "by_span" in last

    def test_bench_helper_reads_the_publish(self, audited, untraced):
        import bench

        runtime_metrics.publish_hostsync({"total": 7})
        assert bench._hostsync_stats_or_none() == {"total": 7}
        assert "BCG_TPU_HOSTSYNC" in bench._CONFIG_OVERRIDE_ENVS

    def test_helper_none_when_never_published(self, unaudited):
        import bench

        runtime_metrics.publish_hostsync(None)
        assert bench._hostsync_stats_or_none() is None


@pytest.fixture(scope="module")
def hostsync_gate():
    """One in-process run of the perf_gate hostsync scenario — this
    file owns the ``hostsync.`` namespace's resurface contract
    (tests/test_perf_gate.py NAMESPACE_OWNERS)."""
    mod = _load_gate()
    return mod, mod.run_hostsync_scenario()


class TestPerfGateHostsync:
    def test_scenario_green_and_nothing_stale(self, hostsync_gate):
        mod, measured = hostsync_gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(measured, mod.load_baseline(),
                                    ("hostsync",))
        assert findings == [], "\n".join(findings)

    def test_acceptance_values(self, hostsync_gate):
        _, measured = hostsync_gate
        # ONE packed readback per fused mega-round (ROADMAP item 1);
        # the 2-calls x 3-syncs lockstep profile is pinned separately.
        assert measured["hostsync.syncs_per_round"] == 1.0
        assert measured["hostsync.syncs_per_round_lockstep"] == 6.0
        # 3 real-engine materializations / 3 decisions in one call.
        assert measured["hostsync.syncs_per_decision"] == 1.0
        # Acceptance criterion: >= 95% attributed (tracing off here, so
        # the jit-entry fallback carries the whole table).
        assert measured["hostsync.attribution_coverage"] >= 0.95
        assert measured["hostsync.error_rows"] == 0

    def test_hostsync_off_fails_naming_the_metrics(self, hostsync_gate):
        """Acceptance: the auditor silently off can never read as a
        green sync gate — the injection must fail naming the pinned
        metrics."""
        mod, _ = hostsync_gate
        measured = mod.run_hostsync_scenario(inject="hostsync-off")
        findings = mod.check_metrics(measured, mod.load_baseline())
        for name in ("hostsync.syncs_per_round",
                     "hostsync.syncs_per_decision",
                     "hostsync.attribution_coverage"):
            assert any(name in f for f in findings), (name, findings)

    def test_removing_each_entry_resurfaces_its_finding(self, hostsync_gate):
        mod, measured = hostsync_gate
        baseline = mod.load_baseline()
        hostsync_entries = [
            n for n in baseline["metrics"] if n.startswith("hostsync.")
        ]
        assert sorted(hostsync_entries) == [
            "hostsync.attribution_coverage", "hostsync.error_rows",
            "hostsync.syncs_per_decision", "hostsync.syncs_per_round",
            "hostsync.syncs_per_round_lockstep",
        ]
        for removed in hostsync_entries:
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(measured, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)

    @pytest.mark.slow
    def test_cli_injection_exits_nonzero_and_names_metric(self):
        """Subprocess CLI arm (slow: cold jax import + engine boot).
        The exit-code/naming contract is already pinned in-process
        above; the shared main() plumbing is pinned by
        tests/test_perf_gate.py's CLI tests — this run keeps the exact
        `--scenarios hostsync --inject-regression hostsync-off`
        invocation honest in the full suite."""
        proc = subprocess.run(
            [sys.executable, GATE_SCRIPT, "--scenarios", "hostsync",
             "--inject-regression", "hostsync-off"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "hostsync.syncs_per_round" in proc.stderr
        assert "PERF REGRESSION" in proc.stderr


# (path, stripped content) of every justified BCG-HOST-SYNC suppression
# in lint_baseline.json -> one sentence naming the runtime verification
# that covers it (a test in this file observing the site through the
# auditor, or the reason the auditor provably cannot reach it).  The
# cross-link test below asserts set equality BOTH ways, so a future
# static suppression without a registered runtime story fails tier-1 —
# baseline entries stop being unverifiable prose.  Today the set is
# empty, and that emptiness is now a VERIFIED claim rather than a blind
# spot: the whole-program pass (bcg_tpu/analysis/interproc.py) lifts
# jit-region resolution across module boundaries, so helpers that only
# trace because another module jits a caller are inside the static
# rule's reach (51 cross-module-marked functions at last count, see
# ``python -m bcg_tpu.analysis --locks`` for the program index), and
# the full-tree run still reports zero BCG-HOST-SYNC findings to park.
# The eager seams the auditor instruments remain OUTSIDE every traced
# region — which is exactly why the runtime auditor exists.
HOST_SYNC_SUPPRESSION_COVERAGE = {}


class TestStaticRuntimeCrossLink:
    def test_every_suppression_registers_runtime_coverage(self):
        with open(os.path.join(REPO, "lint_baseline.json")) as f:
            baseline = json.load(f)
        entries = {
            (e["path"], e["content"])
            for e in baseline["suppressions"]
            if e["rule"] == "BCG-HOST-SYNC"
        }
        assert entries == set(HOST_SYNC_SUPPRESSION_COVERAGE), (
            "BCG-HOST-SYNC suppressions and HOST_SYNC_SUPPRESSION_COVERAGE "
            "disagree — every justified static host-sync suppression must "
            "register the runtime verification that observes (or provably "
            "cannot reach) its site, and stale registrations must be "
            f"pruned: baseline={sorted(entries)}, "
            f"covered={sorted(HOST_SYNC_SUPPRESSION_COVERAGE)}"
        )

    def test_cross_link_enforcement_is_live(self):
        """De-vacuification of the empty-set equality above: drive a
        REAL cross-module host-sync violation (the xmod fixture, whose
        np.asarray only traces because a sibling module jits its
        caller) through the real analyzer, baseline it the way a future
        PR would, and assert that suppression (a) actually parks the
        finding and (b) is exactly the shape the set-equality test
        rejects until a runtime story is registered here."""
        from bcg_tpu.analysis import analyze_paths
        from bcg_tpu.analysis.core import BaselineEntry

        fix = os.path.join(REPO, "tests", "analysis_fixtures", "xmod")
        raw = analyze_paths(paths=[fix], baseline=None)
        hs = [f for f in raw.findings if f.rule == "BCG-HOST-SYNC"]
        assert len(hs) == 1 and hs[0].path.endswith("helper.py"), (
            "xmod fixture must yield exactly the cross-module host-sync "
            "finding: " + "; ".join(f.format() for f in raw.findings)
        )
        entry = BaselineEntry(
            rule="BCG-HOST-SYNC", path=hs[0].path, content=hs[0].content,
            reason="hypothetical future suppression",
        )
        parked = analyze_paths(paths=[fix], baseline=[entry])
        assert not any(
            f.rule == "BCG-HOST-SYNC" for f in parked.findings
        ), "the baseline entry failed to park the cross-module finding"
        assert (entry.path, entry.content) not in (
            HOST_SYNC_SUPPRESSION_COVERAGE
        ), "fixture suppressions must never be registered as covered"
        # The equality assertion above would now fail on exactly this
        # delta — the enforcement is live, not an empty==empty truism.
        would_be_baseline = {(entry.path, entry.content)}
        assert would_be_baseline != set(HOST_SYNC_SUPPRESSION_COVERAGE)

    def test_auditor_observes_the_documented_engine_sites(self,
                                                          hostsync_gate):
        """The runtime complement of the static rule: the decode-path
        sites DESIGN.md documents (prefill barrier, decode readback,
        step readback) are all actually observed by the auditor in the
        hermetic scenario — the real-engine arm's counters moved for
        each one."""
        site_table = {
            name[len("engine.hostsync.site."):]: value
            for name, value in obs_counters.snapshot().items()
            if name.startswith("engine.hostsync.site.")
        }
        for site in ("prefill_barrier", "decode_readback",
                     "steps_readback"):
            assert site_table.get(site, 0) > 0, (site, site_table)
        # Tracing was off in the scenario: the attribution table is the
        # jit-entry fallback's work (satellite: auditor-on, tracing-off
        # still attributes).
        span_table = {
            name[len("engine.hostsync.span."):]: value
            for name, value in obs_counters.snapshot().items()
            if name.startswith("engine.hostsync.span.")
        }
        assert any(k.startswith("jit_") for k in span_table), span_table


class _DelayedCalls(InferenceEngine):
    """Per-call host-side delay in front of a shared proxy (the
    straggler micro-benchmark's workload shape — tests/test_obs.py)."""

    def __init__(self, engine, delay):
        self._engine = engine
        self._delay = delay

    def batch_generate_json(self, prompts, temperature=0.8, max_tokens=512):
        time.sleep(self._delay)
        return self._engine.batch_generate_json(prompts, temperature,
                                                max_tokens)

    def generate_json(self, prompt, schema, temperature=0.0, max_tokens=512,
                      system_prompt=None):
        time.sleep(self._delay)
        return self._engine.generate_json(
            prompt, schema, temperature, max_tokens,
            system_prompt=system_prompt,
        )

    def generate(self, prompt, temperature=0.0, max_tokens=256, top_p=1.0,
                 system_prompt=None):
        return self._engine.generate(prompt, temperature, max_tokens, top_p,
                                     system_prompt=system_prompt)

    def batch_generate(self, prompts, temperature=0.0, max_tokens=256,
                       top_p=1.0):
        return self._engine.batch_generate(prompts, temperature, max_tokens,
                                           top_p)

    def shutdown(self):
        pass


class TestDisabledOverhead:
    """Satellite acceptance: BCG_TPU_HOSTSYNC=0 adds <5% wall-clock to
    the straggler micro-benchmark scenario — measured the PR 4 way:
    (note calls the scenario would make) x (per-call cost of a disabled
    note), against the scenario's disabled wall-clock."""

    FAST = 0.005
    GAMES, ROUNDS = 8, 2

    def _run_scenario(self):
        def make(i):
            delay = self.FAST * 10 if i == 0 else self.FAST

            def go(engine):
                return run_simulation(
                    n_agents=4, byzantine_count=0, max_rounds=self.ROUNDS,
                    backend="fake", seed=i,
                    engine=_DelayedCalls(engine, delay),
                )
            return go

        t0 = time.perf_counter()
        outs = run_serving_simulations(
            FakeEngine(seed=0, policy="stubborn"),
            [make(i) for i in range(self.GAMES)],
            max_concurrent=4, linger_ms=1,
        )
        assert all(isinstance(o, dict) for o in outs)
        return time.perf_counter() - t0

    def test_disabled_overhead_bound(self, unaudited, untraced,
                                     monkeypatch):
        # Unit cost of the disabled fast path.
        probes = 20_000
        t0 = time.perf_counter()
        for _ in range(probes):
            obs_hostsync.note("probe_site", entry="decode_loop")
        per_note = (time.perf_counter() - t0) / probes

        # Scenario wall-clock with the auditor disabled (the shipped
        # default path).
        wall = self._run_scenario()

        # Note volume of the SAME scenario, counted by running it
        # audited.
        monkeypatch.setenv("BCG_TPU_HOSTSYNC", "1")
        obs_hostsync.reset()
        before = obs_counters.value("engine.hostsync.total")
        try:
            self._run_scenario()
            notes = obs_counters.value("engine.hostsync.total") - before
        finally:
            obs_hostsync.reset()

        assert notes > 0
        overhead = notes * per_note
        assert overhead < 0.05 * wall, (
            f"disabled auditor overhead {overhead * 1e3:.2f}ms is not <5% "
            f"of the {wall * 1e3:.0f}ms straggler scenario "
            f"({notes} notes x {per_note * 1e9:.0f}ns)"
        )
