"""scripts/scale_sweep.py smoke — the one-agent-per-chip sweep driver
had NO test coverage: a schema drift in its JSON line (the thing sweep
harnesses and BASELINE config 4 consume) or a dp-derivation bug would
only surface on hardware.

Runs the real script as a subprocess on a virtual 8-CPU-device mesh
(the hermetic invocation its own docstring advertises) with a tiny
model/window, and pins the emitted JSON schema: every advertised key
present, throughput fields populated (> 0), and the mesh layout fields
consistent with the requested agent count.

Since the script became a thin wrapper over a one-job bcg_tpu.sweep
run, this file is the byte-compat pin for the conversion: the KEY SET
is asserted EXACTLY (not just as a subset — a wrapper that silently
grew or renamed fields would break downstream harnesses), and the
sweep manifest it now writes must carry the fleet identity exactly
like the serve/game JSONL sinks.
"""

import glob
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "scale_sweep.py")

# Every key the script's docstring + BASELINE config 4 harnesses rely
# on — pinned as the EXACT emitted set (wrapper byte-compat contract).
EXPECTED_KEYS = {
    "agents", "devices", "dp", "model", "rounds", "rounds_per_sec",
    "decisions_per_sec", "dp_batches", "dp_bypasses", "sp_bypasses",
    "spmd_mesh_dp", "consensus",
}


def test_scale_sweep_emits_schema_on_virtual_devices(tmp_path):
    sweep_dir = str(tmp_path / "scale")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PYTHONPATH=REPO,
    )
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--agents", "8", "--rounds", "2",
         "--max-model-len", "256", "--decide-tokens", "24",
         "--vote-tokens", "16", "--sweep-dir", sweep_dir],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # The LAST stdout line is the one JSON row (stage noise may precede).
    json_lines = [
        l for l in proc.stdout.splitlines() if l.strip().startswith("{")
    ]
    assert json_lines, proc.stdout
    row = json.loads(json_lines[-1])
    assert set(row) == EXPECTED_KEYS, sorted(row)  # exact: no drift
    assert row["agents"] == 8
    assert row["devices"] == 8
    # dp is the largest divisor of the agent count that fits the mesh.
    assert row["dp"] == 8
    assert row["spmd_mesh_dp"] == 8          # --spmd-exchange layout
    assert 1 <= row["rounds"] <= 2
    assert row["rounds_per_sec"] > 0
    assert row["decisions_per_sec"] > 0
    assert row["dp_batches"] >= 1            # batches actually sharded
    assert isinstance(row["consensus"], bool)

    # Wrapper conversion: the run went through the sweep tier — its
    # manifest exists in --sweep-dir and the header carries the fleet
    # identity (run id / host / process rank / flag overrides), the
    # same stamping contract as the serve/game event sinks.
    manifests = glob.glob(os.path.join(sweep_dir, "sweep-manifest-r*.jsonl"))
    assert len(manifests) == 1, manifests
    records = [json.loads(l) for l in open(manifests[0])]
    header = next(r for r in records if r["event"] == "manifest")
    for key in ("run_id", "host", "process_index", "process_count",
                "flags", "schema_version"):
        assert key in header, sorted(header)
    assert header["kind"] == "sweep"
    ends = [r for r in records if r["event"] == "job_end"]
    assert len(ends) == 1 and ends[0]["status"] == "completed"
