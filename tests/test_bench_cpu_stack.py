"""The 8B serving flag stack, end-to-end through bench.py on the CPU.

The most expensive round-3/4 failure mode: the chip returns for a short
window and bench_8b dies on a host-side bug before any number lands.
This test runs the EXACT flag combination the 8B bench serves —
int8 weights + int8 KV + scan-over-layers + chunked prefill +
fast-forward + compact JSON, prefix caching off — through the real
bench entrypoint (size-class gating, attach probe, warmup, measured
window, contract JSON) with the tiny model on the in-process CPU
backend (``BENCH_FORCE_CPU=1``).  If this passes, a hardware bench_8b
failure isolates to scale or Mosaic lowering, never bench plumbing.
"""

import json
import os
import subprocess
import sys

import pytest

from bcg_tpu.runtime.envflags import get_bool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(
    get_bool("BCG_TPU_SKIP_SLOW"),
    reason="~10 min of 1-core work; BCG_TPU_SKIP_SLOW=1 opts out for "
           "interim local runs (default ON — this is the 8B-path "
           "insurance the driver's suite must keep)",
)
@pytest.mark.slow
def test_bench_8b_flag_stack_on_cpu():
    env = dict(
        os.environ,
        BENCH_FORCE_CPU="1",
        BENCH_MODEL="bcg-tpu/tiny-test",
        BENCH_BACKEND="jax",
        BENCH_QUANTIZATION="int8",
        BENCH_KV_DTYPE="int8",
        BENCH_SCAN_LAYERS="1",
        BENCH_PREFIX_CACHING="0",
        BENCH_PREFILL_CHUNK="64",
        BENCH_ROUNDS="1",
        BENCH_WARMUP="1",
        BENCH_ATTACH_TIMEOUT="120",
    )
    # Drop the conftest's 8-virtual-device flag: the bench subprocess is
    # single-device, and compiling every program for 8 CPU devices
    # triples this test's wall-clock for nothing.
    env["XLA_FLAGS"] = ""
    # Persistent compile cache: the first run pays ~10 min of 1-core XLA
    # compilation for the full 8B program stack; subsequent suite runs
    # replay it in seconds.
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.expanduser("~/.cache/bcg_tpu_xla_cpu"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=900, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert "error" not in result, result
    assert result["value"] > 0.0
    extra = result["extra"]
    assert extra["quantization"] == "int8"
    assert extra["kv_cache_dtype"] == "int8"
    assert extra["scan_layers"] is True
    assert extra["prefill_chunk"] == 64
    assert extra["prefix_caching"] is False
    assert extra["platform"] == "cpu"
