"""System-prompt KV prefix caching (engine + transformer).

The decisive property: prefilling a prompt in two stages — cached prefix
KV, then the suffix via ``prefill_with_prefix`` — must reproduce the
logits of a single full prefill (same math, different association order),
and the engine must produce identical-quality guided JSON with the
feature on or off.
"""

import pytest

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.chat_template import format_chat_parts, format_chat_prompt
from bcg_tpu.engine.chat_template import prefix_split_safe
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.models import init_params, prefill, prefill_with_prefix, spec_for_model
from bcg_tpu.models.transformer import init_kv_cache

SCHEMA = {
    "type": "object",
    "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
    "required": ["decision"],
    "additionalProperties": False,
}


class TestChatParts:
    def test_parts_join_to_full_prompt(self):
        for model in [
            "Qwen/Qwen3-14B", "Qwen/Qwen3-4B-Instruct-2507", "Qwen/Qwen2.5-7B",
            "meta-llama/Meta-Llama-3-8B-Instruct", "mistralai/Mistral-7B-Instruct",
            "bcg-tpu/tiny-test",
        ]:
            prefix, suffix = format_chat_parts(model, "sys text", "user text")
            assert prefix + suffix == format_chat_prompt(model, "sys text", "user text")

    def test_split_safety_classification(self):
        assert prefix_split_safe("Qwen/Qwen3-14B")
        assert prefix_split_safe("meta-llama/Meta-Llama-3-8B-Instruct")
        assert not prefix_split_safe("mistralai/Mistral-Small-Instruct-2409")
        assert prefix_split_safe("bcg-tpu/tiny-test")


class TestSplitPrefillMatchesFull:
    def _run(self, quantized_kv: bool):
        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        B, P_len, S_len = 2, 6, 5
        key = jax.random.PRNGKey(3)
        tokens = jax.random.randint(key, (B, P_len + S_len), 0, spec.vocab_size)
        valid = jnp.ones((B, P_len + S_len), bool)

        cache_full = init_kv_cache(spec, B, P_len + S_len + 1, quantized=quantized_kv)
        full_logits, _ = prefill(params, spec, tokens, valid, cache_full)

        # Stage 1: prefix alone; stage 2: suffix against the prefix cache.
        cache = init_kv_cache(spec, B, P_len + S_len + 1, quantized=quantized_kv)
        _, cache = prefill(
            params, spec, tokens[:, :P_len], valid[:, :P_len], cache
        )
        split_logits, _ = prefill_with_prefix(
            params, spec, tokens[:, P_len:], valid[:, P_len:], cache,
            prefix_valid=valid[:, :P_len],
            prefix_lens=jnp.full((B,), P_len, jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(split_logits), np.asarray(full_logits),
            rtol=0.08 if quantized_kv else 0.02,
            atol=0.08 if quantized_kv else 0.02,
        )

    @pytest.mark.slow
    def test_bf16_cache(self):
        self._run(quantized_kv=False)

    def test_int8_cache(self):
        self._run(quantized_kv=True)

    def test_left_padded_prefix_rope_offset(self):
        """Rows with different prefix lengths must get per-row RoPE offsets."""
        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        P, Ls = 8, 4
        key = jax.random.PRNGKey(5)
        row = jax.random.randint(key, (1, P + Ls), 0, spec.vocab_size)
        plen = 5  # row's real prefix is 5 tokens, left-padded into 8 slots

        # Reference: contiguous full prefill of the 9 real tokens.
        cache_full = init_kv_cache(spec, 1, plen + Ls + 1)
        full_logits, _ = prefill(
            params, spec, row[:, P - plen:], jnp.ones((1, plen + Ls), bool),
            cache_full,
        )

        prefix_tokens = row[:, :P]
        prefix_valid = jnp.arange(P)[None, :] >= (P - plen)
        cache = init_kv_cache(spec, 1, P + Ls + 1)
        _, cache = prefill(params, spec, prefix_tokens, prefix_valid, cache)
        split_logits, _ = prefill_with_prefix(
            params, spec, row[:, P:], jnp.ones((1, Ls), bool), cache,
            prefix_valid=prefix_valid,
            prefix_lens=jnp.full((1,), plen, jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(split_logits), np.asarray(full_logits), rtol=0.02, atol=0.02
        )


class TestEnginePrefixCaching:
    def test_guided_json_and_cache_population(self):
        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048,
        ))
        prompts = [
            ("You are honest agent", "vote now round 1", SCHEMA),
            ("You are byzantine agent", "vote now round 1", SCHEMA),
        ]
        out = engine.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        assert all(o.get("decision") in ("stop", "continue") for o in out)
        assert len(engine._prefix_cache) == 2  # one entry per distinct system prompt
        # Second round: same prefixes, new suffixes — entries are reused.
        out2 = engine.batch_generate_json(
            [(s, "vote now round 2", SCHEMA) for s, _, _ in prompts],
            temperature=0.0, max_tokens=24,
        )
        assert all(o.get("decision") in ("stop", "continue") for o in out2)
        assert len(engine._prefix_cache) == 2
        engine.shutdown()

    def test_per_row_temperature_and_budget(self):
        """One batch can mix greedy and sampled rows with different token
        budgets — every row still yields schema-valid JSON and respects
        its own budget (guaranteed parse is per-row)."""
        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=1024,
        ))
        bounded = {
            "type": "object",
            "properties": {"note": {"type": "string", "minLength": 1, "maxLength": 20}},
            "required": ["note"],
            "additionalProperties": False,
        }
        # NB: budgets must cover each schema's shortest completion for the
        # byte tokenizer ('{"decision": "stop"}' is 20 bytes) — a budget
        # below that yields a clean EMPTY output by design (see
        # TestGuaranteedParse in test_jax_engine.py).
        texts = engine._run_guided(
            [("p1 ", "", "s1"), ("p2 ", "", "s2")],
            [bounded, SCHEMA],
            temperature=[0.0, 0.9],
            max_tokens=[40, 30],
        )
        import json as _json

        a = _json.loads(texts[0])
        b = _json.loads(texts[1])
        assert isinstance(a.get("note"), str)
        assert b.get("decision") in ("stop", "continue")
        # Row budgets: the encoded outputs fit their own caps.
        assert len(engine.tokenizer.encode(texts[0])) <= 40
        assert len(engine.tokenizer.encode(texts[1])) <= 30
        engine.shutdown()

    def test_cache_length_alignment(self):
        """With a kv alignment set (the int8-Pallas configuration), the
        allocated decode cache length rounds up to the alignment so the
        decode kernels never jnp.pad (= copy) the cache per step — and
        the extra masked slots leave greedy output unchanged."""
        mk = lambda: JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=1024,
        ))
        engine = mk()
        engine._kv_align = 64
        prepped = engine._prepare_prefixed_batch(
            [("You are the honest system prompt. ", "", "vote now")], [24], 25
        )
        assert prepped is not None
        assert prepped[-1] % 64 == 0  # total cache length S
        rows = [("You are the honest system prompt. ", "vote now", SCHEMA)]
        out_aligned = engine.batch_generate_json(rows, temperature=0.0, max_tokens=24)
        plain = mk()
        out_plain = plain.batch_generate_json(rows, temperature=0.0, max_tokens=24)
        assert out_aligned == out_plain
        engine.shutdown()
        plain.shutdown()

    def test_prefix_fallback_counted_and_warned(self):
        """A prefix the prompt window cannot hold disengages prefix
        caching LOUDLY: warn-once + a prefix_fallbacks counter (silent
        disengagement hid a disabled cache in round 2)."""
        import pytest

        engine = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=128,
        ))
        rows = [("system prompt far too long for the window " * 3,
                 "vote", SCHEMA)]
        with pytest.warns(UserWarning, match="prefix caching disengaged"):
            out = engine.batch_generate_json(rows, temperature=0.0, max_tokens=24)
        assert engine.prefix_fallbacks == 1
        assert len(engine._prefix_cache) == 0
        assert out[0].get("decision") in ("stop", "continue")
        engine.shutdown()

    def test_matches_uncached_engine_greedy(self):
        cfg = EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                           max_model_len=2048)
        on = JaxEngine(cfg)
        off = JaxEngine(dataclasses.replace(cfg, prefix_caching=False))
        prompts = [("system prompt here", "decide", SCHEMA)]
        r_on = on.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        r_off = off.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
        assert r_on == r_off
        on.shutdown()
        off.shutdown()


class TestPagedEvictionSafety:
    def test_eviction_pressure_never_corrupts_in_flight_batches(self):
        """Paged engine with a pool barely larger than one call's
        working set: alternating distinct system prompts forces radix
        eviction on nearly every call, but refcount pins guarantee the
        CURRENT batch's chain survives — outputs stay token-identical
        to an unpressured dense engine throughout, and the ledger's
        prefix_cache account keeps tracking the post-eviction resident
        set exactly (idempotent keyed charge)."""
        import numpy as np

        from bcg_tpu.obs import ledger as obs_ledger

        cfg = EngineConfig(backend="jax", model_name="bcg-tpu/tiny-test",
                           max_model_len=2048)
        dense = JaxEngine(cfg)
        paged = JaxEngine(dataclasses.replace(
            cfg, paged_kv=True, kv_block_size=16, kv_pool_blocks=48,
        ))
        sys_a = "You are the honest consensus agent with detailed rules. " * 2
        sys_b = "You are the byzantine saboteur with long instructions. " * 2
        try:
            for round_no in range(3):
                for sysp in (sys_a, sys_b):
                    rows = [(sysp, f"Round {round_no}. decide.", SCHEMA)]
                    r_d = dense.batch_generate_json(
                        rows, temperature=0.0, max_tokens=24
                    )
                    r_p = paged.batch_generate_json(
                        rows, temperature=0.0, max_tokens=24
                    )
                    assert r_p == r_d
                    # Ledger tracks the resident set exactly after every
                    # evict/re-admit cycle.
                    charged = obs_ledger.LEDGER._entries["prefix_cache"][
                        id(paged)
                    ]
                    assert charged == (
                        paged._paged.resident_blocks
                        * paged._paged.block_bytes_dev
                    )
            assert int(np.asarray(
                paged.kv_pool_stats()["blocks_resident"]
            )) > 0
        finally:
            dense.shutdown()
            paged.shutdown()
