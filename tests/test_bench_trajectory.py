"""Cross-run bench trajectory report (scripts/bench_trajectory.py).

The acceptance contract, asserted against the REAL checked-in
BENCH_r01-r05 records: the accelerator-outage runs r03-r05 (and the
r02 driver crash) classify as OUTAGES — excluded from regression
analysis — and the script exits 0; a genuine measured drop below the
threshold exits 2 naming the metric.  Kept bcg_tpu-import-free like
the script itself.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "bench_trajectory.py")
BENCH_FILES = [
    os.path.join(REPO, f"BENCH_r0{i}.json") for i in range(1, 6)
]


@pytest.fixture(scope="module")
def mod():
    spec = importlib.util.spec_from_file_location("bench_trajectory", SCRIPT)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def _write(path, payload):
    with open(path, "w") as f:
        json.dump(payload, f)
    return str(path)


def _measured(n, value, vs_baseline=1.0, extra=None):
    return {
        "n": n, "rc": 0,
        "parsed": {
            "metric": "agent_decisions_per_sec", "value": value,
            "unit": "decisions/sec", "vs_baseline": vs_baseline,
            "extra": extra or {},
        },
    }


class TestImportFree:
    def test_no_bcg_tpu_import(self):
        src = open(SCRIPT).read()
        tops = [
            line.split()[1].split(".")[0]
            for line in src.splitlines()
            if line.startswith(("import ", "from "))
        ]
        assert "bcg_tpu" not in tops


class TestCheckedInTrajectory:
    """The real BENCH_r01-r05 files — the records that motivated the
    outage-vs-regression distinction."""

    def test_r03_to_r05_classify_as_outages(self, mod):
        runs = mod.order_runs([mod.load_run(p) for p in BENCH_FILES])
        status = {r.label: r.status for r in runs}
        assert status["BENCH_r01"] == "measured"
        assert status["BENCH_r02"] == "outage"  # driver crash, rc=1
        for label in ("BENCH_r03", "BENCH_r04", "BENCH_r05"):
            assert status[label] == "outage", label
        # The outage notes carry the attach failure, not a number.
        notes = {r.label: r.note for r in runs}
        assert "accelerator attach failed" in notes["BENCH_r03"]

    def test_no_regression_and_rc_zero(self, mod):
        runs = mod.order_runs([mod.load_run(p) for p in BENCH_FILES])
        assert mod.find_regressions(runs, threshold=0.7) == []
        proc = subprocess.run(
            [sys.executable, SCRIPT] + BENCH_FILES,
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert "4 outage(s)" in proc.stdout
        assert "excluded from regression analysis" in proc.stdout
        assert "REGRESSION" not in proc.stdout

    def test_trend_table_reports_best_known_good(self, mod):
        runs = mod.order_runs([mod.load_run(p) for p in BENCH_FILES])
        report = mod.render_report(runs, threshold=0.7)
        assert "decisions_per_sec (best-known-good 7.292)" in report
        assert "100.0% of best" in report


class TestClassification:
    def test_null_vs_baseline_is_outage(self, mod, tmp_path):
        run = mod.load_run(_write(tmp_path / "b.json", {
            "n": 9, "rc": 0,
            "parsed": {"metric": "agent_decisions_per_sec", "value": 0.0,
                       "unit": "decisions/sec", "vs_baseline": None},
        }))
        assert run.status == "outage"
        assert "null vs_baseline" in run.note

    def test_error_field_is_outage_even_with_numeric_vs_baseline(
            self, mod, tmp_path):
        # The pre-PR-6 poisoned shape: vs_baseline 0.0 WITH an error.
        run = mod.load_run(_write(tmp_path / "b.json", {
            "n": 9, "rc": 0,
            "parsed": {"value": 0.0, "vs_baseline": 0.0,
                       "error": "backend unavailable"},
        }))
        assert run.status == "outage"
        assert "backend unavailable" in run.note

    def test_empty_parsed_is_outage(self, mod, tmp_path):
        run = mod.load_run(_write(tmp_path / "b.json",
                                  {"n": 2, "rc": 1, "parsed": {}}))
        assert run.status == "outage"
        assert "rc=1" in run.note

    def test_bare_bench_payload_accepted(self, mod, tmp_path):
        run = mod.load_run(_write(tmp_path / "b.json", {
            "metric": "agent_decisions_per_sec", "value": 5.0,
            "unit": "decisions/sec", "vs_baseline": 2.0,
            "extra": {"rounds_per_sec": 0.25},
        }))
        assert run.status == "measured"
        assert run.metrics["decisions_per_sec"] == 5.0
        assert run.metrics["rounds_per_sec"] == 0.25


class TestRegression:
    def test_real_drop_exits_two_naming_metric(self, mod, tmp_path):
        a = _write(tmp_path / "BENCH_r01.json", _measured(1, 10.0))
        b = _write(tmp_path / "BENCH_r02.json", _measured(2, 3.0))
        proc = subprocess.run(
            [sys.executable, SCRIPT, a, b],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert proc.returncode == 2
        assert "BENCH REGRESSION" in proc.stderr
        assert "decisions_per_sec" in proc.stderr
        assert "best-known-good 10" in proc.stderr

    def test_outage_after_good_run_is_not_a_regression(self, mod, tmp_path):
        a = _write(tmp_path / "BENCH_r01.json", _measured(1, 10.0))
        b = _write(tmp_path / "BENCH_r02.json", {
            "n": 2, "rc": 0,
            "parsed": {"value": 0.0, "vs_baseline": None,
                       "error": "attach timeout"},
        })
        runs = mod.order_runs([mod.load_run(p) for p in (a, b)])
        assert mod.find_regressions(runs, 0.7) == []

    def test_within_threshold_is_green(self, mod, tmp_path):
        a = _write(tmp_path / "a.json", _measured(1, 10.0))
        b = _write(tmp_path / "b.json", _measured(2, 8.0))
        runs = mod.order_runs([mod.load_run(p) for p in (a, b)])
        assert mod.find_regressions(runs, 0.7) == []

    def test_recovery_after_outage_compares_to_best_known_good(
            self, mod, tmp_path):
        # measured 10 -> outage -> measured 4: the comparison spans the
        # outage (best-known-good 10), so the drop IS caught.
        files = [
            _write(tmp_path / "BENCH_r01.json", _measured(1, 10.0)),
            _write(tmp_path / "BENCH_r02.json", {
                "n": 2, "rc": 0,
                "parsed": {"value": 0.0, "vs_baseline": None,
                           "error": "attach timeout"},
            }),
            _write(tmp_path / "BENCH_r03.json", _measured(3, 4.0)),
        ]
        runs = mod.order_runs([mod.load_run(p) for p in files])
        findings = mod.find_regressions(runs, 0.7)
        assert len(findings) == 1
        assert "best-known-good 10" in findings[0]

    def test_single_measured_run_cannot_regress(self, mod, tmp_path):
        a = _write(tmp_path / "a.json", _measured(1, 10.0))
        runs = [mod.load_run(a)]
        assert mod.find_regressions(runs, 0.7) == []


class TestCli:
    def test_directory_glob(self, mod, tmp_path):
        _write(tmp_path / "BENCH_r01.json", _measured(1, 10.0))
        _write(tmp_path / "BENCH_r02.json", _measured(2, 11.0))
        proc = subprocess.run(
            [sys.executable, SCRIPT, str(tmp_path)],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert "2 measured, 0 outage(s)" in proc.stdout

    def test_no_files_is_usage_error(self, mod, tmp_path):
        proc = subprocess.run(
            [sys.executable, SCRIPT, str(tmp_path)],
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert proc.returncode == 1
