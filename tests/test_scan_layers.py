"""Scan-over-layers (transformer.stack_layer_params / _run_layers).

The stacked execution path exists to shrink 8B-class programs below the
remote-compile size limit (VERDICT round-1 item #2); it must be
numerically IDENTICAL to the unrolled per-layer loop — same blocks, same
cache contents, same logits — and must shard on a mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.models import init_params, prefill, spec_for_model
from bcg_tpu.models.transformer import (
    decode_chunk,
    decode_step,
    init_kv_cache,
    layers_stacked,
    prefill_with_prefix,
    stack_layer_params,
)

SPEC = spec_for_model("bcg-tpu/tiny-test")


@pytest.fixture(scope="module")
def params():
    return init_params(SPEC, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def stacked(params):
    return stack_layer_params(params)


def _prompt(B=2, L=16, seed=1):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, 256, size=(B, L)), jnp.int32)
    valid = jnp.ones((B, L), bool).at[0, :3].set(False)  # left padding
    return tokens, valid


def test_stack_is_idempotent(stacked):
    assert layers_stacked(stacked)
    again = stack_layer_params(stacked)
    assert again is stacked


def test_prefill_equivalence(params, stacked):
    tokens, valid = _prompt()
    B, L = tokens.shape
    cache_l = init_kv_cache(SPEC, B, L + 4)
    cache_s = init_kv_cache(SPEC, B, L + 4, stacked=True)
    logits_l, new_l = prefill(params, SPEC, tokens, valid, cache_l)
    logits_s, new_s = prefill(stacked, SPEC, tokens, valid, cache_s)
    np.testing.assert_allclose(logits_l, logits_s, rtol=6e-2, atol=6e-2)
    # Cache contents match up to bf16 reassociation noise (scan and the
    # unrolled loop fuse differently).
    for li in range(SPEC.num_layers):
        np.testing.assert_allclose(
            np.asarray(new_l[li]["k"], np.float32),
            np.asarray(new_s["k"][li], np.float32),
            rtol=6e-2, atol=6e-2,
        )


def test_decode_step_equivalence(params, stacked):
    tokens, valid = _prompt()
    B, L = tokens.shape
    S = L + 4
    _, cache_l = prefill(params, SPEC, tokens, valid, init_kv_cache(SPEC, B, S))
    _, cache_s = prefill(
        stacked, SPEC, tokens, valid, init_kv_cache(SPEC, B, S, stacked=True)
    )
    tok = jnp.asarray([5, 9], jnp.int32)
    lens = valid.sum(axis=1).astype(jnp.int32)
    mask = jnp.zeros((B, S), bool).at[:, :L].set(valid).at[:, L].set(True)
    logits_l, _ = decode_step(params, SPEC, tok, L, lens, cache_l, mask)
    logits_s, _ = decode_step(stacked, SPEC, tok, L, lens, cache_s, mask)
    np.testing.assert_allclose(logits_l, logits_s, rtol=6e-2, atol=6e-2)


@pytest.mark.slow
def test_decode_chunk_equivalence(params, stacked):
    tokens, valid = _prompt()
    B, L = tokens.shape
    K, S = 4, L + 8
    _, cache_l = prefill(params, SPEC, tokens, valid, init_kv_cache(SPEC, B, S))
    _, cache_s = prefill(
        stacked, SPEC, tokens, valid, init_kv_cache(SPEC, B, S, stacked=True)
    )
    chunk = jnp.asarray([[7, 8, 9, 10], [3, 4, 5, 6]], jnp.int32)
    chunk_valid = jnp.asarray([[1, 1, 1, 0], [1, 1, 0, 0]], bool)
    lens = valid.sum(axis=1).astype(jnp.int32)
    positions = lens[:, None] + jnp.arange(K)[None]
    cache_valid = jnp.zeros((B, S), bool).at[:, :L].set(valid)
    logits_l, _ = decode_chunk(
        params, SPEC, chunk, chunk_valid, L, positions, cache_l, cache_valid
    )
    logits_s, _ = decode_chunk(
        stacked, SPEC, chunk, chunk_valid, L, positions, cache_s, cache_valid
    )
    np.testing.assert_allclose(logits_l, logits_s, rtol=6e-2, atol=6e-2)


def test_prefill_with_prefix_equivalence(params, stacked):
    """Suffix prefill against pre-populated cache slots works under scan
    (used by chunked prefill, which scan-mode 8B serving relies on)."""
    tokens, valid = _prompt(B=2, L=8, seed=3)
    B, L = tokens.shape
    P, S = 8, 24
    ptoks, pvalid = _prompt(B=2, L=P, seed=4)
    _, cache_l = prefill(params, SPEC, ptoks, pvalid, init_kv_cache(SPEC, B, S))
    _, cache_s = prefill(
        stacked, SPEC, ptoks, pvalid, init_kv_cache(SPEC, B, S, stacked=True)
    )
    plens = pvalid.sum(axis=1).astype(jnp.int32)
    logits_l, _ = prefill_with_prefix(
        params, SPEC, tokens, valid, cache_l, pvalid, plens
    )
    logits_s, _ = prefill_with_prefix(
        stacked, SPEC, tokens, valid, cache_s, pvalid, plens
    )
    np.testing.assert_allclose(logits_l, logits_s, rtol=6e-2, atol=6e-2)


def test_quantized_stack_equivalence(params):
    """int8 leaves stack inside their {"q", "scale"} dicts."""
    from bcg_tpu.models.quantize import quantize_params

    qparams = quantize_params(params, SPEC)
    qstacked = stack_layer_params(qparams)
    assert qstacked["layers"]["wq"]["q"].shape[0] == SPEC.num_layers
    tokens, valid = _prompt()
    B, L = tokens.shape
    logits_l, _ = prefill(qparams, SPEC, tokens, valid, init_kv_cache(SPEC, B, L + 2))
    logits_s, _ = prefill(
        qstacked, SPEC, tokens, valid, init_kv_cache(SPEC, B, L + 2, stacked=True)
    )
    # int8-quantized bf16 math: scan vs unrolled reassociates reductions,
    # and on CPU XLA (jax 0.4.37) a single tail element lands at 0.078
    # abs — widen just past it; a real stacking bug moves everything.
    np.testing.assert_allclose(logits_l, logits_s, rtol=8e-2, atol=8e-2)


def test_stacked_params_shard_on_mesh(stacked):
    from bcg_tpu.parallel.mesh import build_mesh
    from bcg_tpu.parallel.sharding import shard_params

    mesh = build_mesh(tp=2, dp=4)
    sharded = shard_params(stacked, SPEC, mesh)
    wq = sharded["layers"]["wq"]  # [Lyr, D, H*Dh]
    assert wq.shape == (SPEC.num_layers, SPEC.hidden_size, SPEC.q_size)
    spec_axes = wq.sharding.spec
    assert spec_axes[0] is None  # layer axis replicates
    # Output dim shards over tp (Megatron column-parallel).
    assert spec_axes[-1] == "tp"


@pytest.mark.slow
def test_engine_greedy_equivalence_scan_vs_unrolled():
    """Whole-engine proof: guided greedy generation is identical with
    scan_layers on and off (same schema, same prompt, temperature 0)."""
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine

    schema = {
        "type": "object",
        "properties": {
            "value": {"type": "integer", "minimum": 0, "maximum": 50},
        },
        "required": ["value"],
    }
    base = EngineConfig(
        model_name="bcg-tpu/tiny-test", backend="jax", max_model_len=512,
        prefix_caching=False,
    )
    prompts = [("You are agent_1.", "Pick a value.", schema)]
    eng_scan = JaxEngine(dataclasses.replace(base, scan_layers=True))
    eng_plain = JaxEngine(base)
    out_scan = eng_scan.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
    out_plain = eng_plain.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
    assert out_scan == out_plain


def test_engine_scan_with_prefix_caching():
    """Scan mode composes with prefix caching (stacked-entry assembly):
    same greedy output with the cache on and off."""
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine

    schema = {
        "type": "object",
        "properties": {
            "value": {"type": "integer", "minimum": 0, "maximum": 50},
        },
        "required": ["value"],
    }
    base = EngineConfig(
        model_name="bcg-tpu/tiny-test", backend="jax", max_model_len=512,
        scan_layers=True,
    )
    prompts = [
        ("You are agent_1. " + "Rules. " * 40, "Pick a value.", schema),
        ("You are agent_2. " + "Rules. " * 40, "Pick a value.", schema),
    ]
    eng_cached = JaxEngine(base)
    eng_plain = JaxEngine(dataclasses.replace(base, prefix_caching=False))
    out_cached = eng_cached.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
    out_plain = eng_plain.batch_generate_json(prompts, temperature=0.0, max_tokens=24)
    assert out_cached == out_plain
    assert len(eng_cached._prefix_cache) == 2
