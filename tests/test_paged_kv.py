"""Block-paged KV cache with radix-tree prefix sharing.

Six layers of guarantees:

* **Host bookkeeping** (no engine): radix match/insert over token ids,
  refcount pins blocking eviction mid-call, LRU order at refcount 0,
  allocator pressure and exhaustion, ledger idempotence across
  evict/re-admit cycles.
* **Transformer parity**: paged write/gather against the dense slab is
  BIT-identical (bf16 and int8 pools) — the property the engine-level
  token-identity claims reduce to.
* **Fused kernel parity** (interpret mode): the Pallas paged-attention
  kernel against the XLA gather oracle at the real preset GQA
  geometries, bf16 + int8 layouts, single-step and K+1 verify-chunk
  forms, multi-page programs — then the same engine-level suite
  (greedy parity, spec+int8 compose, eviction pressure, retrace pins)
  rerun under ``paged_kv_impl="pallas"``.
* **Engine parity + stability**: greedy outputs token-identical paged
  vs dense (incl. speculative decoding and the int8-KV compose), and
  zero steady-state retraces while block-table CONTENTS vary.
* **Paged chunked prefill**: long prompts streamed through the pool
  chunk-by-chunk stay token-identical to the one-pass dense path, the
  chunk entry points pin at zero steady-state retraces, and admission
  at a boundary-sized pool leaves room for the entry builds' transient
  scratch blocks (the pre-reserve math demonstrably exhausts).
* **The win, gated**: per-game real prefill positions drop
  superlinearly with agent count, radix hit rate across rounds, and a
  strictly higher admission cap than the dense provisioner at the same
  synthetic HBM budget — asserted here (tier-1) against the same
  numbers ``scripts/perf_gate.py``'s ``paged`` scenario gates in CI.
"""

import dataclasses
import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.engine.paged_kv import PagedKV, PoolExhausted
from bcg_tpu.models import init_params, prefill, spec_for_model
from bcg_tpu.models.transformer import decode_step, init_kv_cache, prefill_paged
from bcg_tpu.obs import counters as obs_counters, ledger as obs_ledger
from bcg_tpu.ops.paged_attention import (
    PALLAS_INTERPRET,
    init_block_pool,
    paged_chunk_attention,
    paged_decode_attention,
    paged_write,
)

SCHEMA = {
    "type": "object",
    "properties": {
        "decision": {"type": "string", "enum": ["stop", "continue"]},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
    },
    "required": ["decision", "value"],
    "additionalProperties": False,
}


def _cfg(**kw):
    return EngineConfig(
        backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048,
        **kw,
    )


def _mgr(num_blocks=16, block_size=2):
    return PagedKV(
        spec_for_model("bcg-tpu/tiny-test"), num_blocks, block_size
    )


class TestRadixIndex:
    def test_lookup_matches_longest_full_block_chain(self):
        mgr = _mgr()
        toks = np.arange(7, dtype=np.int32)  # 3 full blocks + 1 leftover
        path, blocks = mgr.lookup(toks)
        assert path == [] and blocks == []
        ids = mgr.alloc(3)
        mgr.insert([], toks, 0, ids)
        path, blocks = mgr.lookup(toks)
        assert blocks == ids and len(path) == 3
        # A diverging sequence shares exactly its common prefix blocks.
        other = np.array([0, 1, 2, 3, 9, 9], dtype=np.int32)
        path2, blocks2 = mgr.lookup(other)
        assert blocks2 == ids[:2]
        mgr.unpin_all()

    def test_shared_chain_between_different_sequences(self):
        mgr = _mgr()
        a = np.array([5, 6, 7, 8], dtype=np.int32)
        ids = mgr.alloc(2)
        mgr.insert([], a, 0, ids)
        # Second sequence with the same first block grafts only its own
        # second block; the first is shared (same node, same id).
        b = np.array([5, 6, 1, 2], dtype=np.int32)
        path_b, blocks_b = mgr.lookup(b)
        assert blocks_b == ids[:1]
        ids_b = mgr.alloc(1)
        mgr.insert(path_b, b, 2, ids_b)
        assert mgr.resident_blocks == 3
        mgr.unpin_all()

    def test_duplicate_insert_reuses_node_and_keeps_caller_ownership(self):
        mgr = _mgr()
        toks = np.array([1, 2, 3, 4], dtype=np.int32)
        ids = mgr.alloc(2)
        mgr.insert([], toks, 0, ids)
        dup = mgr.alloc(2)
        grafted = mgr.insert([], toks, 0, dup)
        # The existing nodes win; the duplicate ids are NOT freed by
        # insert (the caller keeps and frees them — a double-free here
        # once meant one block allocated twice).
        assert [n.block for n in grafted] == ids
        assert mgr.resident_blocks == 2
        assert all(i not in mgr._free for i in dup)
        mgr.free(dup)
        mgr.unpin_all()

    def test_refcount_pin_blocks_eviction_mid_call(self):
        """The satellite guarantee: eviction under allocation pressure
        must never free a block an in-flight batch references."""
        mgr = _mgr(num_blocks=6, block_size=2)  # 5 usable
        toks = np.array([1, 2, 3, 4], dtype=np.int32)
        ids = mgr.alloc(2)
        mgr.insert([], toks, 0, ids)  # insert pins the grafted path
        # 3 free remain; asking for 5 must NOT evict the pinned chain.
        with pytest.raises(PoolExhausted):
            mgr.alloc(5)
        assert mgr.resident_blocks == 2
        path, blocks = mgr.lookup(toks)
        assert blocks == ids  # still resident
        # After the call's unpin, the same pressure may evict.
        mgr.unpin_all()
        got = mgr.alloc(5)
        assert len(got) == 5 and mgr.resident_blocks == 0

    def test_eviction_is_lru_and_leaf_only(self):
        mgr = _mgr(num_blocks=8, block_size=2)
        old = np.array([1, 2], dtype=np.int32)
        young = np.array([3, 4, 5, 6], dtype=np.int32)  # chain of 2
        mgr.insert([], old, 0, mgr.alloc(1))
        mgr.insert([], young, 0, mgr.alloc(2))
        mgr.unpin_all()
        mgr.lookup(young)  # touch: young chain is now most recent
        mgr.unpin_all()
        assert mgr.evict(1) == 1
        # The stale single-block chain went first; the touched chain
        # survives intact (its interior node is not a leaf).
        _, blocks = mgr.lookup(young)
        assert len(blocks) == 2
        _, blocks_old = mgr.lookup(old)
        assert blocks_old == []
        mgr.unpin_all()

    def test_ledger_charge_idempotent_across_evict_readmit(self):
        """Satellite 3: evict/re-admit cycles must leave the
        prefix_cache account exactly tracking the resident set — the
        keyed charge REPLACES, never accumulates."""
        mgr = _mgr(num_blocks=8, block_size=2)
        key = object()
        mgr.set_ledger_key(key)
        bb = mgr.block_bytes_dev
        try:
            toks = np.array([1, 2, 3, 4], dtype=np.int32)
            for _cycle in range(3):
                mgr.insert([], toks, 0, mgr.alloc(2))
                mgr.unpin_all()
                assert obs_ledger.LEDGER._entries["prefix_cache"][key] == 2 * bb
                assert mgr.evict(2) == 2
                assert obs_ledger.LEDGER._entries["prefix_cache"][key] == 0
        finally:
            obs_ledger.credit("prefix_cache", key)

    def test_stats_surface(self):
        mgr = _mgr(num_blocks=8, block_size=2)
        toks = np.array([1, 2, 3, 4], dtype=np.int32)
        mgr.lookup(toks)  # cold miss: 0 of 4 positions
        mgr.insert([], toks, 0, mgr.alloc(2))
        mgr.unpin_all()
        mgr.lookup(toks)  # warm hit: 4 of 4 positions
        mgr.unpin_all()
        s = mgr.stats()
        assert s["blocks_total"] == 7
        assert s["blocks_resident"] == 2
        assert s["blocks_free"] == 5
        assert s["free_block_headroom_bytes"] == 5 * mgr.block_bytes_dev
        assert s["prefix_hit_rate"] == 0.5  # 4 hit of 8 looked-up positions


class TestTransformerParity:
    @pytest.mark.parametrize("quantized", [False, True])
    def test_paged_prefill_decode_bit_identical_to_dense(self, quantized):
        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        B, L, bs = 2, 10, 4
        tokens = jax.random.randint(
            jax.random.PRNGKey(3), (B, L), 0, spec.vocab_size
        )
        valid = jnp.ones((B, L), bool)

        S = L + 6
        cache = init_kv_cache(spec, B, S, quantized=quantized)
        logits_d, cache = prefill(params, spec, tokens, valid, cache)
        vm = np.zeros((B, S), bool)
        vm[:, :L] = True
        ref = [logits_d]
        tok = jnp.argmax(logits_d, -1)
        plens = jnp.full((B,), L, jnp.int32)
        for i in range(3):
            vm[:, L + i] = True
            lg, cache = decode_step(
                params, spec, tok, L + i, plens + i, cache, jnp.asarray(vm)
            )
            ref.append(lg)
            tok = jnp.argmax(lg, -1)

        nblk = -(-S // bs)
        Sp = nblk * bs
        pool = init_block_pool(spec, 32, bs, quantized=quantized)
        tbl = np.stack(
            [np.arange(1, 1 + nblk), np.arange(10, 10 + nblk)]
        ).astype(np.int32)
        entries = [
            {**pool[li], "tbl": jnp.asarray(tbl)}
            for li in range(spec.num_layers)
        ]
        logits_p, entries = prefill_paged(
            params, spec, tokens, valid, entries,
            jnp.zeros((B, 0), bool), jnp.zeros((B,), jnp.int32),
        )
        np.testing.assert_array_equal(np.asarray(logits_p), np.asarray(ref[0]))
        vmp = np.zeros((B, Sp), bool)
        vmp[:, :L] = True
        tok = jnp.argmax(logits_p, -1)
        for i in range(3):
            vmp[:, L + i] = True
            lg, entries = decode_step(
                params, spec, tok, L + i, plens + i, entries, jnp.asarray(vmp)
            )
            np.testing.assert_array_equal(np.asarray(lg), np.asarray(ref[i + 1]))
            tok = jnp.argmax(lg, -1)


class TestPallasKernelParity:
    """The fused Pallas paged-attention kernel (interpret mode on this
    CPU host — the same launch config hardware lowers) against the XLA
    block-gather reference, which is bit-identical to dense by
    construction and therefore the oracle.  Geometries are the real
    preset GQA head ratios (``models/configs.py``): group 4 is the
    8B/llama family, group 7 (Qwen2.5-7B) exercises the padded-GQA
    dispatch (``pow2_rows``), group 2 is the CPU test preset.  Masks
    always leave >= 1 attendable slot per query row: a fully-masked row
    is unreachable from the engine (decode always attends the current
    position; padded chunk rows are masked consumers whose outputs are
    never read), and the two impls legitimately differ there (the
    kernel's ``l == 0`` guard returns 0; finite ``_NEG_INF`` softmax
    returns the uniform mean)."""

    # (H, Hkv, Dh) — GQA group ratios from the model presets.
    GEOMETRIES = [
        pytest.param(32, 8, 128, id="qwen3-8b-group4"),
        pytest.param(28, 4, 128, id="qwen2.5-7b-group7-nonpow2"),
        pytest.param(4, 2, 16, id="tiny-test-group2"),
    ]

    @staticmethod
    def _entry(H, Hkv, Dh, quantized, key, B=2, bs=8, nblk=4, pool_n=12):
        spec = dataclasses.replace(
            spec_for_model("bcg-tpu/tiny-test"),
            num_heads=H, num_kv_heads=Hkv, head_dim=Dh, num_layers=1,
        )
        S = nblk * bs
        pool = init_block_pool(spec, pool_n, bs, quantized=quantized)[0]
        ks = jax.random.split(key, 3)
        # Non-contiguous, per-row disjoint physical blocks (row 1's
        # table overlaps nothing of row 0's) — the shapes radix sharing
        # actually produces.
        tbl = jnp.asarray(np.stack(
            [np.arange(1, 1 + nblk), np.arange(5, 5 + nblk)]
        ).astype(np.int32))
        entry = paged_write(
            {**pool, "tbl": tbl},
            jax.random.normal(ks[0], (B, S, Hkv, Dh), jnp.float32),
            jax.random.normal(ks[1], (B, S, Hkv, Dh), jnp.float32),
            jnp.int32(0),
        )
        return entry, ks[2], S

    @pytest.mark.parametrize("quantized", [False, True])
    @pytest.mark.parametrize("H,Hkv,Dh", GEOMETRIES)
    def test_decode_step_matches_gather_oracle(self, H, Hkv, Dh, quantized):
        entry, key, S = self._entry(
            H, Hkv, Dh, quantized, jax.random.PRNGKey(H * Dh + quantized)
        )
        ks = jax.random.split(key, 2)
        q = jax.random.normal(ks[0], (2, 1, H, Dh), jnp.float32)
        lens = jax.random.randint(ks[1], (2,), 1, S + 1)
        mask = jnp.arange(S)[None, :] < lens[:, None]
        scale = 1.0 / np.sqrt(Dh)
        ref = paged_decode_attention(q, entry, mask, scale, impl="xla")
        out = paged_decode_attention(
            q, entry, mask, scale, impl=PALLAS_INTERPRET
        )
        # int8 pools dequantize to IDENTICAL f32 values on both paths
        # (tight); bf16 pools differ only in accumulation/rounding order.
        atol = 1e-5 if quantized else 3e-2
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=atol, rtol=atol
        )

    @pytest.mark.parametrize("quantized", [False, True])
    @pytest.mark.parametrize("H,Hkv,Dh", GEOMETRIES)
    def test_verify_chunk_matches_gather_oracle(self, H, Hkv, Dh, quantized):
        """The ``[B, K]``-token chunk form — the speculative loop's K+1
        verify window (spec_k=3 -> K=4) and the fast-forward chunk."""
        K = 4
        entry, key, S = self._entry(
            H, Hkv, Dh, quantized, jax.random.PRNGKey(3 * H + Dh + quantized)
        )
        ks = jax.random.split(key, 2)
        q = jax.random.normal(ks[0], (2, K, H, Dh), jnp.float32)
        lens = jax.random.randint(ks[1], (2,), 1, S - K + 1)
        # Chunk position k attends [0, lens + k) — the decode-window
        # causal mask, never empty (lens >= 1).
        mask = (
            jnp.arange(S)[None, None, :]
            < (lens[:, None] + jnp.arange(K)[None, :])[:, :, None]
        )
        scale = 1.0 / np.sqrt(Dh)
        ref = paged_chunk_attention(q, entry, mask, scale, impl="xla")
        out = paged_chunk_attention(
            q, entry, mask, scale, impl=PALLAS_INTERPRET
        )
        atol = 1e-5 if quantized else 3e-2
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=atol, rtol=atol
        )

    def test_multi_page_programs_and_null_padding(self, monkeypatch):
        """BCG_TPU_PAGED_PAGES_PER_PROGRAM=3 over a 4-block table: the
        wrapper pads to 6 pages (2 programs x 3 pages) with null-block
        pages whose mask columns are False — grouping and padding must
        not change the math."""
        monkeypatch.setenv("BCG_TPU_PAGED_PAGES_PER_PROGRAM", "3")
        H, Hkv, Dh = 4, 2, 16
        entry, key, S = self._entry(
            H, Hkv, Dh, False, jax.random.PRNGKey(11)
        )
        ks = jax.random.split(key, 2)
        q = jax.random.normal(ks[0], (2, 1, H, Dh), jnp.float32)
        lens = jax.random.randint(ks[1], (2,), 1, S + 1)
        mask = jnp.arange(S)[None, :] < lens[:, None]
        scale = 1.0 / np.sqrt(Dh)
        out = paged_decode_attention(
            q, entry, mask, scale, impl=PALLAS_INTERPRET
        )
        monkeypatch.delenv("BCG_TPU_PAGED_PAGES_PER_PROGRAM")
        ref = paged_decode_attention(q, entry, mask, scale, impl="xla")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-2, rtol=3e-2
        )


class TestEnginePagedParity:
    def test_greedy_token_identical_and_radix_persists(self):
        prompts = [
            ("You are honest agent_1 in a consensus game.",
             "Round 1. decide now.", SCHEMA),
            ("You are byzantine agent_2 in a consensus game.",
             "Round 1. decide now.", SCHEMA),
        ]
        dense = JaxEngine(_cfg())
        r_dense = dense.batch_generate_json(
            prompts, temperature=0.0, max_tokens=40
        )
        dense.shutdown()
        paged = JaxEngine(_cfg(paged_kv=True))
        try:
            r_paged = paged.batch_generate_json(
                prompts, temperature=0.0, max_tokens=40
            )
            assert r_paged == r_dense
            stats1 = paged.kv_pool_stats()
            assert stats1["blocks_resident"] > 0
            # Round 2 reuses the resident chains: hit rate appears and
            # identical-shape calls with DIFFERENT table contents must
            # not retrace (contents are traced values, not shapes).
            before = obs_counters.snapshot()
            paged.batch_generate_json(
                [(s, "Round 1. decide now.", SCHEMA)
                 for s, _, _ in prompts],
                temperature=0.0, max_tokens=40,
            )
            paged.batch_generate_json(
                [("You are sneaky agent_9 in a consensus game.",
                  "Round 1. decide now.", SCHEMA),
                 ("You are honest agent_1 in a consensus game.",
                  "Round 1. decide now.", SCHEMA)],
                temperature=0.0, max_tokens=40,
            )
            moved = obs_counters.delta(before)
            retraces = {
                k: v for k, v in moved.items()
                if k.startswith(("engine.retrace.", "engine.compile."))
            }
            assert retraces == {}, retraces
            stats2 = paged.kv_pool_stats()
            assert stats2["prefix_hit_rate"] > 0
            # Private decode blocks were all returned: only the radix-
            # resident set holds blocks between calls.
            assert (stats2["blocks_free"]
                    == stats2["blocks_total"] - stats2["blocks_resident"])
        finally:
            paged.shutdown()

    def test_spec_decode_int8_compose_token_identical(self):
        """The acceptance compose: speculative decoding + int8 KV over
        the paged pool, greedy outputs identical to the dense twin."""
        prompts = [
            ("You are honest agent_1 in a consensus game.",
             "Round 1. decide now.", SCHEMA),
            ("You are byzantine agent_2 in a consensus game.",
             "Round 1. decide now.", SCHEMA),
        ]
        extra = dict(spec_decode=True, kv_cache_dtype="int8")
        with pytest.warns(UserWarning, match="int8 KV cache"):
            dense = JaxEngine(_cfg(**extra))
        r_dense = dense.batch_generate_json(
            prompts, temperature=0.0, max_tokens=40
        )
        dense.shutdown()
        with pytest.warns(UserWarning, match="int8 KV cache"):
            paged = JaxEngine(_cfg(paged_kv=True, **extra))
        try:
            r_paged = paged.batch_generate_json(
                prompts, temperature=0.0, max_tokens=40
            )
            assert r_paged == r_dense
        finally:
            paged.shutdown()

    def test_paged_rejects_sequence_parallel(self):
        # prefill_chunk composes now (paged chunked prefill, PR 8);
        # TestPagedChunkedPrefill owns its parity/retrace guarantees.
        # sp > 1 must be a LOUD boot error: pool blocks are shared
        # across rows, so the sequence dim structurally cannot shard —
        # silently serving replicated would defeat the configured
        # parallelism (and a broken guard would serve wrong attention).
        from jax.sharding import Mesh

        mesh = Mesh(
            np.asarray(jax.devices()[:2]).reshape(1, 1, 2),
            ("dp", "tp", "sp"),
        )
        with pytest.raises(ValueError, match="sequence parallelism"):
            JaxEngine(_cfg(paged_kv=True), mesh=mesh)


class TestEnginePallasParity:
    """The engine-level acceptance suite rerun under the fused kernel
    (``paged_kv_impl="pallas"`` resolves to interpret mode on this CPU
    host — the explicit-pallas-off-TPU contract): greedy output stays
    token-identical to the dense path, composes with speculative
    decoding + int8 KV, survives eviction pressure, and varying
    block-table CONTENTS never retrace."""

    PROMPTS = [
        ("You are honest agent_1 in a consensus game.",
         "Round 1. decide now.", SCHEMA),
        ("You are byzantine agent_2 in a consensus game.",
         "Round 1. decide now.", SCHEMA),
    ]

    def test_impl_resolution_and_stats_surface(self):
        eng = JaxEngine(_cfg(paged_kv=True, paged_kv_impl="pallas"))
        try:
            assert eng.paged_kv_impl == "pallas"
            assert eng._paged_loop_impl == PALLAS_INTERPRET
            stats = eng.kv_pool_stats()
            assert stats["impl"] == "pallas"
            assert stats["interpret"] is True
            assert stats["pages_per_program"] >= 1
        finally:
            eng.shutdown()
        with pytest.raises(ValueError, match="paged_kv_impl"):
            JaxEngine(_cfg(paged_kv=True, paged_kv_impl="mosaic"))

    def test_greedy_parity_and_zero_retraces_varying_tables(self):
        dense = JaxEngine(_cfg())
        r_dense = dense.batch_generate_json(
            self.PROMPTS, temperature=0.0, max_tokens=40
        )
        dense.shutdown()
        eng = JaxEngine(_cfg(paged_kv=True, paged_kv_impl="pallas"))
        try:
            r_pal = eng.batch_generate_json(
                self.PROMPTS, temperature=0.0, max_tokens=40
            )
            assert r_pal == r_dense
            # Same-shape calls with DIFFERENT table contents (a fresh
            # system prompt displaces pool blocks) must not retrace —
            # the table is the kernel's scalar-prefetch OPERAND, never
            # part of the compile key.
            before = obs_counters.snapshot()
            eng.batch_generate_json(
                [("You are sneaky agent_9 in a consensus game.",
                  "Round 1. decide now.", SCHEMA),
                 self.PROMPTS[0]],
                temperature=0.0, max_tokens=40,
            )
            moved = obs_counters.delta(before)
            retraces = {
                k: v for k, v in moved.items()
                if k.startswith(("engine.retrace.", "engine.compile."))
            }
            assert retraces == {}, retraces
        finally:
            eng.shutdown()

    def test_spec_decode_int8_compose_token_identical(self):
        """The full acceptance compose under the fused kernel: the
        speculative loop's K+1 verify chunks + in-kernel int8 dequant,
        greedy output identical to the dense twin."""
        extra = dict(spec_decode=True, kv_cache_dtype="int8")
        with pytest.warns(UserWarning, match="int8 KV cache"):
            dense = JaxEngine(_cfg(**extra))
        r_dense = dense.batch_generate_json(
            self.PROMPTS, temperature=0.0, max_tokens=40
        )
        dense.shutdown()
        with pytest.warns(UserWarning, match="int8 KV cache"):
            eng = JaxEngine(
                _cfg(paged_kv=True, paged_kv_impl="pallas", **extra)
            )
        try:
            r_pal = eng.batch_generate_json(
                self.PROMPTS, temperature=0.0, max_tokens=40
            )
            assert r_pal == r_dense
        finally:
            eng.shutdown()

    def test_eviction_pressure_parity(self):
        """The 48-block-pool eviction scenario
        (tests/test_prefix_cache.py TestPagedEvictionSafety) rerun
        under the fused kernel: alternating distinct prompts force
        radix eviction on nearly every call, and outputs stay
        token-identical to an unpressured dense engine throughout."""
        dense = JaxEngine(_cfg())
        eng = JaxEngine(_cfg(paged_kv=True, paged_kv_impl="pallas",
                             kv_block_size=16, kv_pool_blocks=48))
        # Three ~21-block prompt chains against 47 usable blocks: no
        # two chains fit alongside a call's scratch, so each call
        # evicts the LRU chain (measured: eviction from call 2 on).
        sys_a = "You are the honest consensus agent with detailed rules. " * 6
        sys_b = "You are the byzantine saboteur with long instructions. " * 6
        sys_c = "You are a careful mediator weighing both proposals. " * 6
        evicted0 = obs_counters.value("kvpool.evicted_blocks")
        try:
            for round_no in range(2):
                for sysp in (sys_a, sys_b, sys_c):
                    rows = [(sysp, f"Round {round_no}. decide.", SCHEMA)]
                    r_d = dense.batch_generate_json(
                        rows, temperature=0.0, max_tokens=24
                    )
                    r_p = eng.batch_generate_json(
                        rows, temperature=0.0, max_tokens=24
                    )
                    assert r_p == r_d
            assert obs_counters.value("kvpool.evicted_blocks") > evicted0
        finally:
            dense.shutdown()
            eng.shutdown()


class TestPagedChunkedPrefill:
    """Paged chunked prefill — the lifted ``paged + prefill_chunk``
    boot exclusion: long prompts stream through the block pool
    chunk-by-chunk (``transformer.prefill_paged_chunk_at``) instead of
    requiring a one-pass activation slab, for batch prefills AND the
    radix entry builds."""

    LONG_A = ("You are the honest consensus agent. Your detailed "
              "operating rules follow here. " * 8)[:540]
    LONG_B = ("You are the byzantine saboteur agent. Your elaborate "
              "secret instructions follow. " * 8)[:540]

    def test_boot_aligns_chunk_to_block_size(self):
        eng = JaxEngine(_cfg(paged_kv=True, prefill_chunk=24,
                             kv_block_size=16))
        try:
            # The chunk history gather reads whole table columns, so the
            # chunk size aligns UP to the pool's block size at boot.
            assert eng.prefill_chunk == 32
        finally:
            eng.shutdown()

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_long_prompt_token_identical_to_dense(self, impl):
        """A prompt several chunks long, prefilled chunk-by-chunk
        through the pool, greedily decodes the same tokens as the
        one-pass DENSE engine — under both the gather reference and the
        fused kernel."""
        rows = [(self.LONG_A, "Round 1. decide now.", SCHEMA),
                (self.LONG_B, "Round 1. decide now.", SCHEMA)]
        dense = JaxEngine(_cfg())
        r_dense = dense.batch_generate_json(
            rows, temperature=0.0, max_tokens=40
        )
        dense.shutdown()
        eng = JaxEngine(_cfg(paged_kv=True, paged_kv_impl=impl,
                             prefill_chunk=128, kv_block_size=16))
        try:
            assert all("error" not in r for r in r_dense)
            r_chunked = eng.batch_generate_json(
                rows, temperature=0.0, max_tokens=40
            )
            assert r_chunked == r_dense
        finally:
            eng.shutdown()

    def test_zero_steady_state_retraces_for_chunk_entry_points(self):
        """Second-round calls at the same shape buckets (different
        prompt CONTENT, so different table contents and different radix
        builds) add no compiled chunk-prefill programs and move no
        compile/retrace counters — chunk width is static, the history
        window and write position are traced values."""
        eng = JaxEngine(_cfg(paged_kv=True, prefill_chunk=128,
                             kv_block_size=16))
        try:
            eng.batch_generate_json(
                [(self.LONG_A, "Round 1. decide now.", SCHEMA),
                 (self.LONG_B, "Round 1. decide now.", SCHEMA)],
                temperature=0.0, max_tokens=24,
            )
            compiled = eng._prefill_paged_chunk_at._cache_size()
            assert compiled > 0  # chunked prefill actually engaged
            before = obs_counters.snapshot()
            # Same char lengths -> same token-length buckets (byte
            # tokenizer), fresh content -> cold radix builds + new
            # table contents through the SAME compiled programs.
            eng.batch_generate_json(
                [(self.LONG_B[:-1] + "!", "Round 1. decide now.", SCHEMA),
                 (self.LONG_A[:-1] + "?", "Round 1. decide now.", SCHEMA)],
                temperature=0.0, max_tokens=24,
            )
            assert eng._prefill_paged_chunk_at._cache_size() == compiled
            moved = obs_counters.delta(before)
            retraces = {
                k: v for k, v in moved.items()
                if k.startswith(("engine.retrace.", "engine.compile."))
            }
            assert retraces == {}, retraces
        finally:
            eng.shutdown()

    def test_admission_boundary_never_pool_exhausted(self):
        """The ISSUE-8 admission fix, demonstrated load-bearing at a
        boundary-sized pool.  Geometry: max_model_len=700 sits between
        the 512/1024 suffix-ladder rungs, so a cold ~540-token entry
        build allocates a 64-block rung — 31 blocks of transient
        scratch past the worst-case row window (44 blocks).  The
        PRE-FIX admission math ((pool-1)//blocks_per_row = 2 rows at 89
        blocks) dispatches both rows in ONE call, whose second entry
        build then needs 64 blocks while the first row's chain is
        refcount-pinned -> PoolExhausted mid-prefill.  cap_for's
        scratch reserve (_paged_build_scratch_blocks) admits 1 row per
        call instead, and the same two-row request completes by
        chunking into two sequential calls with eviction between."""
        from bcg_tpu.engine.jax_engine import JaxEngine as _JE

        rows = [(self.LONG_A, "Round 1. decide.", SCHEMA),
                (self.LONG_B, "Round 1. decide.", SCHEMA)]

        def boot():
            return _JE(EngineConfig(
                backend="jax", model_name="bcg-tpu/tiny-test",
                max_model_len=700, paged_kv=True, kv_block_size=16,
                kv_pool_blocks=89, prefill_chunk=128,
            ))

        eng = boot()
        try:
            window = eng.worst_case_decode_window()
            blocks_per_row = -(-window // 16)
            assert eng._paged_scratch_blocks == 31
            assert eng.kv_pool_stats()["scratch_reserve_blocks"] == 31
            # New math: 1 row; the math this PR replaces said 2.
            assert eng.cap_for(window) == 1
            assert (eng._paged.num_blocks - 1) // blocks_per_row == 2
            r = eng.batch_generate_json(rows, temperature=0.0,
                                        max_tokens=40)
            assert all("error" not in x for x in r), r
        finally:
            eng.shutdown()

        # Regression arm: restore the pre-fix admission math and watch
        # the SAME request exhaust the pool mid-prefill.
        eng = boot()
        eng._paged_scratch_blocks = 0
        try:
            assert eng.cap_for(window) == 2
            with pytest.raises(PoolExhausted):
                eng.batch_generate_json(rows, temperature=0.0,
                                        max_tokens=40)
        finally:
            eng.shutdown()


class TestAdmission:
    def test_free_block_cap_and_serve_snapshot(self):
        """The serving surface of the win: derive_row_cap answers from
        free-block accounting (no device limit needed), and the
        scheduler snapshot carries the pool's headroom block."""
        from bcg_tpu.serve.scheduler import Scheduler, derive_row_cap

        engine = JaxEngine(_cfg(paged_kv=True, kv_pool_blocks=513,
                                kv_block_size=16))
        try:
            cap = derive_row_cap(engine)
            # worst window 2048 tokens -> 128 blocks/row over 512 usable
            # minus the 63-block entry-build scratch reserve (the
            # bucketed remainder-prefill pad tail admission must leave
            # room for — see JaxEngine._paged_build_scratch_blocks).
            assert engine.kv_pool_stats()["scratch_reserve_blocks"] == 63
            assert cap == 3
            sched = Scheduler(engine, linger_ms=1)
            try:
                snap = sched.snapshot()
                assert snap["row_cap"] == 3
                assert snap["kv_pool"]["blocks_total"] == 512
                assert snap["kv_pool"]["free_block_headroom_bytes"] > 0
            finally:
                sched.close()
        finally:
            engine.shutdown()

    def test_budget_guard_warns_in_blocks(self):
        engine = JaxEngine(_cfg(paged_kv=True, kv_pool_blocks=66,
                                kv_block_size=16))
        try:
            with pytest.warns(UserWarning, match="paged pool"):
                engine._check_kv_budget(8, [64], 65)
        finally:
            engine.shutdown()


@pytest.fixture(scope="module")
def paged_gate_metrics():
    """One run of the perf-gate ``paged`` scenario — tier-1 asserts the
    acceptance criteria against the SAME numbers CI gates."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "perf_gate.py",
    )
    spec = importlib.util.spec_from_file_location("perf_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, mod.run_paged_scenario()


class TestSuperlinearSharing:
    def test_positions_real_per_agent_strictly_decreasing(self, paged_gate_metrics):
        _, m = paged_gate_metrics
        assert m["paged.positions_real_monotone"] == 1.0
        # Superlinear: doubling agents far more than halves the shared
        # cost — per-agent positions at N=8 must be well under N=2's.
        assert m["paged.positions_real_per_agent_slope"] < 0.6

    def test_round_over_round_hit_rate_and_parity(self, paged_gate_metrics):
        _, m = paged_gate_metrics
        assert m["paged.greedy_parity_mismatches"] == 0.0
        assert m["paged.prefix_hit_rate"] > 0.5

    def test_admission_cap_strictly_beats_dense_at_same_budget(
        self, paged_gate_metrics
    ):
        _, m = paged_gate_metrics
        assert m["paged.row_cap_gain"] > 1.0

    def test_metrics_conform_to_perf_baseline(self, paged_gate_metrics):
        """The load-bearing-baseline contract extends to the paged
        scenario: every metric baselined, every bound met."""
        mod, m = paged_gate_metrics
        findings = mod.check_metrics(m, mod.load_baseline())
        findings += mod.check_stale(m, mod.load_baseline(), ("paged",))
        assert findings == [], findings

    def test_removing_a_paged_entry_resurfaces_its_finding(
        self, paged_gate_metrics
    ):
        """Deleting a paged baseline entry RESURFACES its check instead
        of silencing it (the test_perf_gate contract, owned here for
        the paged.* namespace)."""
        import json

        mod, m = paged_gate_metrics
        baseline = mod.load_baseline()
        for removed in m:
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(m, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)
