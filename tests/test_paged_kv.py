"""Block-paged KV cache with radix-tree prefix sharing.

Four layers of guarantees:

* **Host bookkeeping** (no engine): radix match/insert over token ids,
  refcount pins blocking eviction mid-call, LRU order at refcount 0,
  allocator pressure and exhaustion, ledger idempotence across
  evict/re-admit cycles.
* **Transformer parity**: paged write/gather against the dense slab is
  BIT-identical (bf16 and int8 pools) — the property the engine-level
  token-identity claims reduce to.
* **Engine parity + stability**: greedy outputs token-identical paged
  vs dense (incl. speculative decoding and the int8-KV compose), and
  zero steady-state retraces while block-table CONTENTS vary.
* **The win, gated**: per-game real prefill positions drop
  superlinearly with agent count, radix hit rate across rounds, and a
  strictly higher admission cap than the dense provisioner at the same
  synthetic HBM budget — asserted here (tier-1) against the same
  numbers ``scripts/perf_gate.py``'s ``paged`` scenario gates in CI.
"""

import dataclasses
import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.engine.paged_kv import PagedKV, PoolExhausted
from bcg_tpu.models import init_params, prefill, spec_for_model
from bcg_tpu.models.transformer import decode_step, init_kv_cache, prefill_paged
from bcg_tpu.obs import counters as obs_counters, ledger as obs_ledger
from bcg_tpu.ops.paged_attention import init_block_pool

SCHEMA = {
    "type": "object",
    "properties": {
        "decision": {"type": "string", "enum": ["stop", "continue"]},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
    },
    "required": ["decision", "value"],
    "additionalProperties": False,
}


def _cfg(**kw):
    return EngineConfig(
        backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048,
        **kw,
    )


def _mgr(num_blocks=16, block_size=2):
    return PagedKV(
        spec_for_model("bcg-tpu/tiny-test"), num_blocks, block_size
    )


class TestRadixIndex:
    def test_lookup_matches_longest_full_block_chain(self):
        mgr = _mgr()
        toks = np.arange(7, dtype=np.int32)  # 3 full blocks + 1 leftover
        path, blocks = mgr.lookup(toks)
        assert path == [] and blocks == []
        ids = mgr.alloc(3)
        mgr.insert([], toks, 0, ids)
        path, blocks = mgr.lookup(toks)
        assert blocks == ids and len(path) == 3
        # A diverging sequence shares exactly its common prefix blocks.
        other = np.array([0, 1, 2, 3, 9, 9], dtype=np.int32)
        path2, blocks2 = mgr.lookup(other)
        assert blocks2 == ids[:2]
        mgr.unpin_all()

    def test_shared_chain_between_different_sequences(self):
        mgr = _mgr()
        a = np.array([5, 6, 7, 8], dtype=np.int32)
        ids = mgr.alloc(2)
        mgr.insert([], a, 0, ids)
        # Second sequence with the same first block grafts only its own
        # second block; the first is shared (same node, same id).
        b = np.array([5, 6, 1, 2], dtype=np.int32)
        path_b, blocks_b = mgr.lookup(b)
        assert blocks_b == ids[:1]
        ids_b = mgr.alloc(1)
        mgr.insert(path_b, b, 2, ids_b)
        assert mgr.resident_blocks == 3
        mgr.unpin_all()

    def test_duplicate_insert_reuses_node_and_keeps_caller_ownership(self):
        mgr = _mgr()
        toks = np.array([1, 2, 3, 4], dtype=np.int32)
        ids = mgr.alloc(2)
        mgr.insert([], toks, 0, ids)
        dup = mgr.alloc(2)
        grafted = mgr.insert([], toks, 0, dup)
        # The existing nodes win; the duplicate ids are NOT freed by
        # insert (the caller keeps and frees them — a double-free here
        # once meant one block allocated twice).
        assert [n.block for n in grafted] == ids
        assert mgr.resident_blocks == 2
        assert all(i not in mgr._free for i in dup)
        mgr.free(dup)
        mgr.unpin_all()

    def test_refcount_pin_blocks_eviction_mid_call(self):
        """The satellite guarantee: eviction under allocation pressure
        must never free a block an in-flight batch references."""
        mgr = _mgr(num_blocks=6, block_size=2)  # 5 usable
        toks = np.array([1, 2, 3, 4], dtype=np.int32)
        ids = mgr.alloc(2)
        mgr.insert([], toks, 0, ids)  # insert pins the grafted path
        # 3 free remain; asking for 5 must NOT evict the pinned chain.
        with pytest.raises(PoolExhausted):
            mgr.alloc(5)
        assert mgr.resident_blocks == 2
        path, blocks = mgr.lookup(toks)
        assert blocks == ids  # still resident
        # After the call's unpin, the same pressure may evict.
        mgr.unpin_all()
        got = mgr.alloc(5)
        assert len(got) == 5 and mgr.resident_blocks == 0

    def test_eviction_is_lru_and_leaf_only(self):
        mgr = _mgr(num_blocks=8, block_size=2)
        old = np.array([1, 2], dtype=np.int32)
        young = np.array([3, 4, 5, 6], dtype=np.int32)  # chain of 2
        mgr.insert([], old, 0, mgr.alloc(1))
        mgr.insert([], young, 0, mgr.alloc(2))
        mgr.unpin_all()
        mgr.lookup(young)  # touch: young chain is now most recent
        mgr.unpin_all()
        assert mgr.evict(1) == 1
        # The stale single-block chain went first; the touched chain
        # survives intact (its interior node is not a leaf).
        _, blocks = mgr.lookup(young)
        assert len(blocks) == 2
        _, blocks_old = mgr.lookup(old)
        assert blocks_old == []
        mgr.unpin_all()

    def test_ledger_charge_idempotent_across_evict_readmit(self):
        """Satellite 3: evict/re-admit cycles must leave the
        prefix_cache account exactly tracking the resident set — the
        keyed charge REPLACES, never accumulates."""
        mgr = _mgr(num_blocks=8, block_size=2)
        key = object()
        mgr.set_ledger_key(key)
        bb = mgr.block_bytes_dev
        try:
            toks = np.array([1, 2, 3, 4], dtype=np.int32)
            for _cycle in range(3):
                mgr.insert([], toks, 0, mgr.alloc(2))
                mgr.unpin_all()
                assert obs_ledger.LEDGER._entries["prefix_cache"][key] == 2 * bb
                assert mgr.evict(2) == 2
                assert obs_ledger.LEDGER._entries["prefix_cache"][key] == 0
        finally:
            obs_ledger.credit("prefix_cache", key)

    def test_stats_surface(self):
        mgr = _mgr(num_blocks=8, block_size=2)
        toks = np.array([1, 2, 3, 4], dtype=np.int32)
        mgr.lookup(toks)  # cold miss: 0 of 4 positions
        mgr.insert([], toks, 0, mgr.alloc(2))
        mgr.unpin_all()
        mgr.lookup(toks)  # warm hit: 4 of 4 positions
        mgr.unpin_all()
        s = mgr.stats()
        assert s["blocks_total"] == 7
        assert s["blocks_resident"] == 2
        assert s["blocks_free"] == 5
        assert s["free_block_headroom_bytes"] == 5 * mgr.block_bytes_dev
        assert s["prefix_hit_rate"] == 0.5  # 4 hit of 8 looked-up positions


class TestTransformerParity:
    @pytest.mark.parametrize("quantized", [False, True])
    def test_paged_prefill_decode_bit_identical_to_dense(self, quantized):
        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        B, L, bs = 2, 10, 4
        tokens = jax.random.randint(
            jax.random.PRNGKey(3), (B, L), 0, spec.vocab_size
        )
        valid = jnp.ones((B, L), bool)

        S = L + 6
        cache = init_kv_cache(spec, B, S, quantized=quantized)
        logits_d, cache = prefill(params, spec, tokens, valid, cache)
        vm = np.zeros((B, S), bool)
        vm[:, :L] = True
        ref = [logits_d]
        tok = jnp.argmax(logits_d, -1)
        plens = jnp.full((B,), L, jnp.int32)
        for i in range(3):
            vm[:, L + i] = True
            lg, cache = decode_step(
                params, spec, tok, L + i, plens + i, cache, jnp.asarray(vm)
            )
            ref.append(lg)
            tok = jnp.argmax(lg, -1)

        nblk = -(-S // bs)
        Sp = nblk * bs
        pool = init_block_pool(spec, 32, bs, quantized=quantized)
        tbl = np.stack(
            [np.arange(1, 1 + nblk), np.arange(10, 10 + nblk)]
        ).astype(np.int32)
        entries = [
            {**pool[li], "tbl": jnp.asarray(tbl)}
            for li in range(spec.num_layers)
        ]
        logits_p, entries = prefill_paged(
            params, spec, tokens, valid, entries,
            jnp.zeros((B, 0), bool), jnp.zeros((B,), jnp.int32),
        )
        np.testing.assert_array_equal(np.asarray(logits_p), np.asarray(ref[0]))
        vmp = np.zeros((B, Sp), bool)
        vmp[:, :L] = True
        tok = jnp.argmax(logits_p, -1)
        for i in range(3):
            vmp[:, L + i] = True
            lg, entries = decode_step(
                params, spec, tok, L + i, plens + i, entries, jnp.asarray(vmp)
            )
            np.testing.assert_array_equal(np.asarray(lg), np.asarray(ref[i + 1]))
            tok = jnp.argmax(lg, -1)


class TestEnginePagedParity:
    def test_greedy_token_identical_and_radix_persists(self):
        prompts = [
            ("You are honest agent_1 in a consensus game.",
             "Round 1. decide now.", SCHEMA),
            ("You are byzantine agent_2 in a consensus game.",
             "Round 1. decide now.", SCHEMA),
        ]
        dense = JaxEngine(_cfg())
        r_dense = dense.batch_generate_json(
            prompts, temperature=0.0, max_tokens=40
        )
        dense.shutdown()
        paged = JaxEngine(_cfg(paged_kv=True))
        try:
            r_paged = paged.batch_generate_json(
                prompts, temperature=0.0, max_tokens=40
            )
            assert r_paged == r_dense
            stats1 = paged.kv_pool_stats()
            assert stats1["blocks_resident"] > 0
            # Round 2 reuses the resident chains: hit rate appears and
            # identical-shape calls with DIFFERENT table contents must
            # not retrace (contents are traced values, not shapes).
            before = obs_counters.snapshot()
            paged.batch_generate_json(
                [(s, "Round 1. decide now.", SCHEMA)
                 for s, _, _ in prompts],
                temperature=0.0, max_tokens=40,
            )
            paged.batch_generate_json(
                [("You are sneaky agent_9 in a consensus game.",
                  "Round 1. decide now.", SCHEMA),
                 ("You are honest agent_1 in a consensus game.",
                  "Round 1. decide now.", SCHEMA)],
                temperature=0.0, max_tokens=40,
            )
            moved = obs_counters.delta(before)
            retraces = {
                k: v for k, v in moved.items()
                if k.startswith(("engine.retrace.", "engine.compile."))
            }
            assert retraces == {}, retraces
            stats2 = paged.kv_pool_stats()
            assert stats2["prefix_hit_rate"] > 0
            # Private decode blocks were all returned: only the radix-
            # resident set holds blocks between calls.
            assert (stats2["blocks_free"]
                    == stats2["blocks_total"] - stats2["blocks_resident"])
        finally:
            paged.shutdown()

    def test_spec_decode_int8_compose_token_identical(self):
        """The acceptance compose: speculative decoding + int8 KV over
        the paged pool, greedy outputs identical to the dense twin."""
        prompts = [
            ("You are honest agent_1 in a consensus game.",
             "Round 1. decide now.", SCHEMA),
            ("You are byzantine agent_2 in a consensus game.",
             "Round 1. decide now.", SCHEMA),
        ]
        extra = dict(spec_decode=True, kv_cache_dtype="int8")
        with pytest.warns(UserWarning, match="int8 KV cache"):
            dense = JaxEngine(_cfg(**extra))
        r_dense = dense.batch_generate_json(
            prompts, temperature=0.0, max_tokens=40
        )
        dense.shutdown()
        with pytest.warns(UserWarning, match="int8 KV cache"):
            paged = JaxEngine(_cfg(paged_kv=True, **extra))
        try:
            r_paged = paged.batch_generate_json(
                prompts, temperature=0.0, max_tokens=40
            )
            assert r_paged == r_dense
        finally:
            paged.shutdown()

    def test_paged_rejects_sequence_parallel_and_chunked_prefill(self):
        with pytest.raises(ValueError, match="prefill_chunk"):
            JaxEngine(_cfg(paged_kv=True, prefill_chunk=128))
        # sp > 1 must be a LOUD boot error: pool blocks are shared
        # across rows, so the sequence dim structurally cannot shard —
        # silently serving replicated would defeat the configured
        # parallelism (and a broken guard would serve wrong attention).
        from jax.sharding import Mesh

        mesh = Mesh(
            np.asarray(jax.devices()[:2]).reshape(1, 1, 2),
            ("dp", "tp", "sp"),
        )
        with pytest.raises(ValueError, match="sequence parallelism"):
            JaxEngine(_cfg(paged_kv=True), mesh=mesh)


class TestAdmission:
    def test_free_block_cap_and_serve_snapshot(self):
        """The serving surface of the win: derive_row_cap answers from
        free-block accounting (no device limit needed), and the
        scheduler snapshot carries the pool's headroom block."""
        from bcg_tpu.serve.scheduler import Scheduler, derive_row_cap

        engine = JaxEngine(_cfg(paged_kv=True, kv_pool_blocks=513,
                                kv_block_size=16))
        try:
            cap = derive_row_cap(engine)
            # worst window 2048 tokens -> 128 blocks/row over 512 usable.
            assert cap == 4
            sched = Scheduler(engine, linger_ms=1)
            try:
                snap = sched.snapshot()
                assert snap["row_cap"] == 4
                assert snap["kv_pool"]["blocks_total"] == 512
                assert snap["kv_pool"]["free_block_headroom_bytes"] > 0
            finally:
                sched.close()
        finally:
            engine.shutdown()

    def test_budget_guard_warns_in_blocks(self):
        engine = JaxEngine(_cfg(paged_kv=True, kv_pool_blocks=66,
                                kv_block_size=16))
        try:
            with pytest.warns(UserWarning, match="paged pool"):
                engine._check_kv_budget(8, [64], 65)
        finally:
            engine.shutdown()


@pytest.fixture(scope="module")
def paged_gate_metrics():
    """One run of the perf-gate ``paged`` scenario — tier-1 asserts the
    acceptance criteria against the SAME numbers CI gates."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "perf_gate.py",
    )
    spec = importlib.util.spec_from_file_location("perf_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, mod.run_paged_scenario()


class TestSuperlinearSharing:
    def test_positions_real_per_agent_strictly_decreasing(self, paged_gate_metrics):
        _, m = paged_gate_metrics
        assert m["paged.positions_real_monotone"] == 1.0
        # Superlinear: doubling agents far more than halves the shared
        # cost — per-agent positions at N=8 must be well under N=2's.
        assert m["paged.positions_real_per_agent_slope"] < 0.6

    def test_round_over_round_hit_rate_and_parity(self, paged_gate_metrics):
        _, m = paged_gate_metrics
        assert m["paged.greedy_parity_mismatches"] == 0.0
        assert m["paged.prefix_hit_rate"] > 0.5

    def test_admission_cap_strictly_beats_dense_at_same_budget(
        self, paged_gate_metrics
    ):
        _, m = paged_gate_metrics
        assert m["paged.row_cap_gain"] > 1.0

    def test_metrics_conform_to_perf_baseline(self, paged_gate_metrics):
        """The load-bearing-baseline contract extends to the paged
        scenario: every metric baselined, every bound met."""
        mod, m = paged_gate_metrics
        findings = mod.check_metrics(m, mod.load_baseline())
        findings += mod.check_stale(m, mod.load_baseline(), ("paged",))
        assert findings == [], findings

    def test_removing_a_paged_entry_resurfaces_its_finding(
        self, paged_gate_metrics
    ):
        """Deleting a paged baseline entry RESURFACES its check instead
        of silencing it (the test_perf_gate contract, owned here for
        the paged.* namespace)."""
        import json

        mod, m = paged_gate_metrics
        baseline = mod.load_baseline()
        for removed in m:
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(m, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)
