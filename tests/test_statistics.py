"""Tests for Q1/Q2/Q3 statistics (reference byzantine_consensus.py:544-839)
and the shared per-round record (round_record / round_convergence — the
single source of truth behind BOTH ``rounds_data`` and the live
game-event stream)."""

from bcg_tpu.game import ByzantineConsensusGame
from bcg_tpu.game.statistics import round_convergence, round_record


def play_to_consensus(game, target, rounds=1, final_votes=True):
    for _ in range(rounds):
        for aid in game.agents:
            game.update_agent_proposal(aid, target)
        votes = {aid: final_votes for aid in game.agents}
        game.advance_round(votes)
        if game.game_over:
            break
    return game


def test_empty_before_first_round():
    g = ByzantineConsensusGame(num_honest=2, seed=0)
    assert g.get_statistics() == {}


def test_valid_outcome_and_quality_score():
    g = ByzantineConsensusGame(num_honest=4, seed=0, max_rounds=10)
    target = g.agents["agent_0"].initial_value
    play_to_consensus(g, target)
    s = g.get_statistics()
    assert s["consensus_outcome"] == "valid"
    assert s["consensus_is_valid"] is True
    assert s["honest_unanimous"] is True
    assert s["unanimous_value"] == target
    assert s["convergence_speed"] == 1
    assert s["convergence_rate"] == 1.0
    assert s["stability_rounds"] == 1
    # quality = 50*1 + 30*centrality + 20*(1 - 1/10)
    assert s["consensus_quality_score"] == 50 + 30 * s["centrality"] + 20 * 0.9
    assert s["inclusivity"] == 1.0
    assert s["agreement_rate"] == 100.0


def test_timeout_outcome():
    g = ByzantineConsensusGame(num_honest=3, seed=0, max_rounds=1)
    target = g.agents["agent_0"].initial_value
    play_to_consensus(g, target, final_votes=False)
    s = g.get_statistics()
    assert s["termination_reason"] == "max_rounds"
    assert s["consensus_outcome"] == "timeout"
    assert s["honest_agents_won"] is False


def test_invalid_outcome_unanimous_non_initial():
    g = ByzantineConsensusGame(num_honest=3, seed=0, value_range=(0, 50))
    initials = {s.initial_value for s in g.agents.values()}
    outsider = next(v for v in range(51) if v not in initials)
    play_to_consensus(g, outsider)
    s = g.get_statistics()
    assert s["consensus_outcome"] == "invalid"
    assert s["consensus_reached"] is False  # game-level flag follows check_consensus


def test_none_outcome_disagreement():
    g = ByzantineConsensusGame(num_honest=3, seed=0)
    for i, aid in enumerate(sorted(g.agents)):
        g.update_agent_proposal(aid, i)
    g.advance_round({aid: True for aid in g.agents})
    s = g.get_statistics()
    assert s["consensus_outcome"] == "none"


def test_byzantine_infiltration():
    g = ByzantineConsensusGame(num_honest=3, num_byzantine=2, seed=1)
    honest = [a for a, st in g.agents.items() if not st.is_byzantine]
    byz = [a for a, st in g.agents.items() if st.is_byzantine]
    target = g.agents[honest[0]].initial_value
    for aid in honest:
        g.update_agent_proposal(aid, target)
    # one byzantine copies consensus, one deviates
    g.update_agent_proposal(byz[0], target)
    g.update_agent_proposal(byz[1], (target + 1) % 51)
    g.advance_round({aid: True for aid in g.agents})
    s = g.get_statistics()
    assert s["byzantine_infiltration"] == 50.0
    assert s["byzantine_agent_ids"] == sorted(byz) or set(
        s["byzantine_agent_ids"]
    ) == set(byz)


def test_q3_keyword_detection_honest_only():
    g = ByzantineConsensusGame(num_honest=2, num_byzantine=1, seed=2)
    honest = [a for a, st in g.agents.items() if not st.is_byzantine]
    byz = [a for a, st in g.agents.items() if st.is_byzantine]
    g.store_round_reasoning(
        {
            honest[0]: "agent_3 looks Suspicious, possibly Byzantine behaviour",
            honest[1]: "converging to the median",
            byz[0]: "I am suspicious and malicious",  # must NOT count
        }
    )
    target = g.agents[honest[0]].initial_value
    play_to_consensus(g, target)
    s = g.get_statistics()
    assert s["keyword_counts"]["suspicious"] == 1
    assert s["keyword_counts"]["byzantine"] == 1
    assert s["keyword_counts"]["malicious"] == 0
    assert s["total_keyword_mentions"] == 2
    assert s["honest_reasoning_count"] == 2


def test_rounds_data_structure():
    g = ByzantineConsensusGame(num_honest=2, seed=0, max_rounds=5)
    play_to_consensus(g, g.agents["agent_0"].initial_value, rounds=2)
    s = g.get_statistics()
    rd = s["rounds_data"]
    assert len(rd) == s["total_rounds"]
    assert {"round", "honest_values", "has_consensus", "consensus_value"} <= set(rd[0])


def test_round_record_is_the_rounds_data_shape():
    """round_record() IS the rounds_data element — key set and values
    pinned (the reference output shape the game-event stream reuses)."""
    g = ByzantineConsensusGame(num_honest=3, num_byzantine=1, seed=1)
    target = next(
        st.initial_value for a, st in g.agents.items() if not st.is_byzantine
    )
    play_to_consensus(g, target)
    s = g.get_statistics()
    r = g.rounds[0]
    rec = round_record(r)
    assert rec == s["rounds_data"][0]
    assert set(rec) == {
        "round", "honest_values", "byzantine_values", "honest_mean",
        "honest_std", "convergence_metric", "has_consensus",
        "consensus_value", "agreement_count",
    }
    # include_byzantine=False empties the byzantine column only.
    masked = round_record(r, include_byzantine=False)
    assert masked["byzantine_values"] == []
    assert {k: v for k, v in masked.items() if k != "byzantine_values"} == \
        {k: v for k, v in rec.items() if k != "byzantine_values"}


def test_round_convergence_metrics():
    g = ByzantineConsensusGame(num_honest=3, seed=0, value_range=(0, 50))
    for aid, v in zip(sorted(g.agents), [10, 20, 20]):
        g.update_agent_proposal(aid, v)
    g.advance_round({aid: False for aid in g.agents})
    conv = round_convergence(g.rounds[0], g.consensus_threshold)
    assert conv["distinct_honest_values"] == 2
    assert conv["value_spread"] == 10
    assert conv["margin_vs_threshold"] == round(
        g.rounds[0].convergence_metric - g.consensus_threshold, 3
    )
    assert conv["byzantine_influence"] == 0  # no byzantine proposals given


def test_byzantine_influence_counts_adoptions_only():
    """Influence = honest agents who CHANGED to a value a byzantine
    proposed last round; keeping one's own matching value is not an
    adoption."""
    g = ByzantineConsensusGame(num_honest=3, num_byzantine=1, seed=3)
    honest = sorted(a for a, st in g.agents.items() if not st.is_byzantine)
    # h0 adopts 42 (was something else), h1 already held 42, h2 moves
    # to a non-byzantine value.
    prev = {honest[0]: 7, honest[1]: 42, honest[2]: 9}
    g.update_agent_proposal(honest[0], 42)
    g.update_agent_proposal(honest[1], 42)
    g.update_agent_proposal(honest[2], 11)
    byz = next(a for a, st in g.agents.items() if st.is_byzantine)
    g.update_agent_proposal(byz, 0)
    g.advance_round({aid: False for aid in g.agents})
    conv = round_convergence(
        g.rounds[0], g.consensus_threshold, honest_ids=honest,
        prev_values=prev, prev_byzantine_proposals=[42],
    )
    assert conv["byzantine_influence"] == 1
    # No byzantine proposals last round -> influence is structurally 0.
    conv0 = round_convergence(
        g.rounds[0], g.consensus_threshold, honest_ids=honest,
        prev_values=prev, prev_byzantine_proposals=[],
    )
    assert conv0["byzantine_influence"] == 0


def test_consensus_preference_flags():
    g = ByzantineConsensusGame(num_honest=3, seed=0, value_range=(0, 50))
    # Force known initials by rebuilding agent states.
    for aid, v in zip(sorted(g.agents), [10, 20, 30]):
        st = g.agents[aid]
        st.initial_value = v
        st.current_value = v
        st.proposed_value = v
    play_to_consensus(g, 10)
    s = g.get_statistics()
    assert s["consensus_is_initial"] is True
    assert s["consensus_is_extreme"] is True  # 10 == min, range >= 2
    assert s["consensus_is_median"] is False
    assert s["consensus_distance_from_median"] == 10
    assert s["centrality"] == 1.0 - 10 / 20
