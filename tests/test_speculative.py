"""Prompt-lookup speculative decoding (engine/speculative.py +
JaxEngine._get_spec_decode_loop + models decode_chunk_spec).

The decisive properties:

* temperature 0 is TOKEN-IDENTICAL to the plain loop (guided and free
  sigs) — drafts are verified against the same masked argmax the plain
  loop samples from, so acceptance can never change the sequence;
* the hermetic guided-JSON decision benchmark runs in >=30% fewer
  device decode iterations with speculation on (obs counter deltas, not
  wall clock — CI-assertable on CPU);
* temperature > 0 rejection sampling preserves the masked-sampler
  distribution (unit-level residual test + seeded end-to-end check);
* speculation disabled (the default) compiles the same jit entry
  points as before and creates no engine.spec.* counters.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.engine.speculative import (
    accept_draft,
    draft_tokens,
    make_masked_logits,
    make_masked_sampler,
    ngram_draft_np,
    spec_decode_slots,
    spec_mirror_np,
)
from bcg_tpu.guided.processor import GuidedBatch, compile_schema
from bcg_tpu.obs import counters as obs_counters

VOTE = {
    "type": "object",
    "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
    "required": ["decision"],
    "additionalProperties": False,
}
DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string", "minLength": 1, "maxLength": 25},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string", "minLength": 1, "maxLength": 25},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}


def _base_config(**kw):
    return EngineConfig(
        backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048, **kw
    )


# --------------------------------------------------------------- drafter
class TestDrafter:
    """Traced n-gram matcher against the numpy oracle."""

    V = 64
    EOS = 63

    def _draft(self, hists, toks, k=4, n=3, budget=100):
        B = len(hists)
        H = max(len(h) for h in hists) + 8
        hist = np.full((B, H), -1, dtype=np.int32)
        for i, h in enumerate(hists):
            hist[i, : len(h)] = h
        cur0 = np.asarray([len(h) for h in hists], np.int32)
        batch = GuidedBatch.permissive(B, self.V)
        draft, dmask, states_v, st_final = draft_tokens(
            jnp.asarray(hist), jnp.asarray(cur0), jnp.asarray(toks, dtype=jnp.int32),
            jnp.zeros(B, jnp.int32), jnp.zeros(B, bool),
            batch.tables, batch.min_budget, batch.chain_tok, batch.chain_len,
            batch.dfa_ids, jnp.zeros(B, jnp.int32),
            jnp.full((B,), budget, jnp.int32),
            k=k, n=n, eos_id=self.EOS,
        )
        out = []
        for i in range(B):
            row = np.asarray(draft[i])[np.asarray(dmask[i])]
            out.append(row.tolist())
        return out

    def test_matches_numpy_reference_on_random_histories(self):
        rng = np.random.default_rng(0)
        hists, toks = [], []
        for _ in range(16):
            # Small alphabet forces repeats -> plenty of matches.
            h = rng.integers(0, 6, size=rng.integers(8, 60)).tolist()
            hists.append(h)
            toks.append(int(rng.integers(0, 6)))
        got = self._draft(hists, toks)
        for h, t, g in zip(hists, toks, got):
            ref = ngram_draft_np(h, t, 3, 4)
            # The permissive automaton truncates only at EOS (excluded
            # from drafting by design), which the small alphabet never
            # produces — so the traced draft IS the oracle continuation.
            assert g == ref, (h, t, g, ref)

    def test_most_recent_match_wins(self):
        # The gram — the last n-1 history tokens (1, 2) plus the sampled
        # token 3 — occurs twice with different continuations: the
        # drafter must continue the LATER occurrence.
        h = [1, 2, 3, 7, 7, 5, 1, 2, 3, 9, 8, 4, 1, 2]
        got = self._draft([h], [3], k=3, n=3)
        assert got[0] == [9, 8, 4]

    def test_no_match_and_short_history(self):
        assert self._draft([[1, 2]], [5], n=3)[0] == []
        assert self._draft([[0]], [0], n=3)[0] == []

    def test_eos_never_drafted(self):
        h = [1, 2, 3, self.EOS, 9, 9, 1, 2]
        # Match at (1,2,3): continuation starts with EOS -> truncated
        # immediately.
        assert self._draft([h], [3], k=3, n=3)[0] == []

    def test_budget_truncates_draft(self):
        h = [1, 2, 3, 4, 5, 6, 7, 1, 2]
        # budget 2: the sampled token takes 1, so only 1 draft slot is
        # affordable (min_budget is 1 everywhere in the permissive DFA).
        assert self._draft([h], [3], k=4, n=3, budget=2)[0] == [4]

    def test_grammar_truncates_draft(self):
        """A grammar-illegal n-gram continuation is cut AT DRAFT TIME:
        the most recent match's continuation is garbage, so the drafter
        must drop it and fall through to the forced chain — every
        proposed token walks legally through the DFA
        (GuidedBatch.walk is the oracle)."""
        tb = [bytes([i]) for i in range(256)]
        guide = compile_schema(VOTE, tb, vocab_id=401)
        batch = GuidedBatch([guide])
        td = guide.token_dfa
        tok = ord('"')
        base = int(td.transitions[td.transitions[td.start, ord("{")], tok])
        assert base >= 0
        # History: a previous LEGAL emission, then a poisoned copy whose
        # '{"' continuation is garbage, ending just after '{' so the
        # bigram source picks the poisoned (most recent) occurrence.
        row = (
            [ord(c) for c in '{"decision": "stop"}']
            + [ord(c) for c in '{"zz']
            + [ord("{")]
        )
        hist = np.full((1, 64), -1, np.int32)
        hist[0, : len(row)] = row
        draft, dmask, _sv, _sf = draft_tokens(
            jnp.asarray(hist), jnp.asarray([len(row)], jnp.int32),
            jnp.asarray([tok], jnp.int32),
            jnp.asarray([base], jnp.int32), jnp.zeros(1, bool),
            batch.tables, batch.min_budget, batch.chain_tok,
            batch.chain_len, batch.dfa_ids, jnp.zeros(1, jnp.int32),
            jnp.full((1,), 64, jnp.int32), k=4, n=2, eos_id=0,
        )
        n_drafted = int(np.asarray(dmask[0]).sum())
        assert n_drafted > 0  # forced chain drafts past the dead n-gram
        assert int(np.asarray(draft)[0, 0]) == ord("d")  # not the 'z'
        _states, legal = batch.walk(jnp.asarray([base], jnp.int32), draft[:1])
        assert np.asarray(legal)[0][:n_drafted].all()


class TestGuidedBatchWalk:
    def test_walk_matches_step_and_flags_illegal(self):
        tb = [bytes([i]) for i in range(256)]
        guide = compile_schema(VOTE, tb, vocab_id=402)
        batch = GuidedBatch([guide])
        td = guide.token_dfa
        seq = [ord(c) for c in '{"decision"']
        states, legal = batch.walk(
            jnp.asarray([td.start], jnp.int32), jnp.asarray([seq], jnp.int32)
        )
        assert np.asarray(legal).all()
        # Walking token-by-token through step() lands on the same state.
        st = jnp.asarray([td.start], jnp.int32)
        for t in seq:
            st = batch.step(st, jnp.asarray([t], jnp.int32))
        assert int(np.asarray(states)[0, -1]) == int(np.asarray(st)[0])
        # An illegal token freezes the state and reports False.
        bad = jnp.asarray([[ord("z"), ord("z")]], jnp.int32)
        states2, legal2 = batch.walk(st, bad)
        assert not np.asarray(legal2).any()
        assert (np.asarray(states2) == int(np.asarray(st)[0])).all()


# ---------------------------------------------------------- conformance
@pytest.fixture(scope="module")
def engine_pair():
    jax.config.update("jax_platforms", "cpu")
    std = JaxEngine(_base_config())
    spec = JaxEngine(_base_config(spec_decode=True))
    yield std, spec
    std.shutdown()
    spec.shutdown()


class TestTemperatureZeroConformance:
    def test_decision_benchmark_30pct_fewer_steps_and_identical(self, engine_pair):
        """Acceptance criterion: the hermetic guided-JSON decision
        benchmark emits byte-identical token sequences at temperature 0
        while taking >=30% fewer device decode iterations (counter
        deltas, not wall clock)."""
        std, spec = engine_pair
        prompts = [
            ("honest agent system prompt", "Round 3: propose a value", DECISION),
            ("byzantine agent system prompt", "Round 3: vote now", VOTE),
            ("honest agent system prompt", "Round 4: propose a value", DECISION),
        ]
        s0_std, s0_spec = std.total_decode_steps, spec.total_decode_steps
        r_std = std.batch_generate_json(prompts, temperature=0.0, max_tokens=80)
        steps_std = std.total_decode_steps - s0_std
        before = obs_counters.snapshot()
        r_spec = spec.batch_generate_json(prompts, temperature=0.0, max_tokens=80)
        steps_spec = spec.total_decode_steps - s0_spec
        moved = obs_counters.delta(before)
        assert r_spec == r_std
        assert all("error" not in r for r in r_std)
        assert steps_spec <= 0.7 * steps_std, (steps_spec, steps_std)
        drafted = moved.get("engine.spec.drafted", 0)
        accepted = moved.get("engine.spec.accepted", 0)
        assert drafted > 0 and 0 < accepted <= drafted
        assert moved.get("engine.spec.rejected", 0) == drafted - accepted

    def test_free_sig_identical(self, engine_pair):
        std, spec = engine_pair
        prompts = [
            "repeat after me: alpha beta gamma alpha beta",
            "the quick brown fox",
        ]
        f_std = std.batch_generate(prompts, temperature=0.0, max_tokens=32)
        f_spec = spec.batch_generate(prompts, temperature=0.0, max_tokens=32)
        assert f_spec == f_std

    def test_second_round_echo_improves_on_plain(self, engine_pair):
        """A round-2 prompt embedding round-1's own output (the BCG
        history echo) must still be token-identical — and speculation
        must beat the plain loop on it (the n-gram source now contains
        the literal answer).  Enum-only schema: free-string positions on
        a random-weight model can sit on argmax near-ties where the
        chunked verify pass and the single-token plain step reassociate
        float reductions differently (the pre-existing fast-forward
        chunk loop shows the same flip), which would test numerics, not
        the acceptance logic."""
        std, spec = engine_pair
        r1 = spec.batch_generate_json(
            [("sys", "Round 1: vote", VOTE)], temperature=0.0,
            max_tokens=60,
        )[0]
        echo = f"Round 1 votes: agent_0 said {json.dumps(r1)}. Round 2: vote"
        r_std = std.batch_generate_json(
            [("sys", echo, VOTE)], temperature=0.0, max_tokens=60
        )
        n_std = std.last_decode_steps
        r_spec = spec.batch_generate_json(
            [("sys", echo, VOTE)], temperature=0.0, max_tokens=60
        )
        n_spec = spec.last_decode_steps
        assert r_spec == r_std
        assert n_spec < n_std

    def test_mixed_budgets_and_padding_rows(self, engine_pair):
        """Per-row budgets differ and the batch pads (real_B=3 -> B=4):
        padded speculative decode must keep real rows identical."""
        std, spec = engine_pair
        prompts = [("s", f"user prompt {i}", VOTE) for i in range(3)]
        r_std = std.batch_generate_json(prompts, temperature=0.0, max_tokens=[24, 48, 30])
        r_spec = spec.batch_generate_json(prompts, temperature=0.0, max_tokens=[24, 48, 30])
        assert r_spec == r_std


@pytest.mark.slow
class TestInt8KvComposes:
    def test_int8_kv_spec_matches_int8_plain(self):
        """Speculative decode over an int8 KV cache (off-TPU this
        exercises the QUANTIZED per-row scatter write + full-dequant
        chunk fallback) must match the plain int8-KV loop token for
        token — both attend the same stored cache, so the quantization
        error is identical."""
        jax.config.update("jax_platforms", "cpu")
        base = _base_config(kv_cache_dtype="int8")
        with pytest.warns(UserWarning, match="int8"):
            std = JaxEngine(base)
        spec = JaxEngine(dataclasses.replace(base, spec_decode=True))
        prompts = [
            ("honest system", "vote on round 3", VOTE),
            ("byzantine system", "decide round 3", DECISION),
        ]
        r_std = std.batch_generate_json(prompts, temperature=0.0, max_tokens=60)
        r_spec = spec.batch_generate_json(prompts, temperature=0.0, max_tokens=60)
        assert r_spec == r_std
        assert all("error" not in r for r in r_std)
        assert spec.last_decode_steps < std.last_decode_steps
        std.shutdown()
        spec.shutdown()


# ------------------------------------------------- temperature > 0 paths
class TestRejectionSampling:
    def test_residual_preserves_distribution(self):
        """Unit-level: 'accept draft d w.p. p(d), else resample with d
        forbidden' must reproduce p exactly — the forbid path IS the
        renormalized leave-one-out residual.  4-sigma band over 20k
        trials."""
        V, eos = 4, 3
        logits = jnp.log(jnp.asarray([[0.45, 0.30, 0.20, 0.05]]))
        batch = GuidedBatch.permissive(1, V)
        ml = make_masked_logits(eos, 1.0)
        sampler = make_masked_sampler(eos, 1.0)
        temps = jnp.ones((1,))
        budgets = jnp.full((1,), 100, jnp.int32)
        states = jnp.zeros((1,), jnp.int32)
        lg, _, _ = ml(logits, states, jnp.zeros((1,), jnp.int32),
                      batch.tables, batch.accepting, batch.min_budget,
                      batch.dfa_ids, temps, budgets)
        p = np.asarray(jax.nn.softmax(lg, axis=-1))[0]
        d = 1  # deterministic draft token

        def one(key):
            ku, ks = jax.random.split(key)
            u = jax.random.uniform(ku)
            tok, _, _ = sampler(
                logits, states, ks, jnp.zeros((1,), jnp.int32),
                batch.tables, batch.accepting, batch.min_budget,
                batch.dfa_ids, temps, budgets,
                forbid=jnp.asarray([d], jnp.int32),
            )
            return jnp.where(u < p[d], d, tok[0])

        n = 20000
        toks = np.asarray(jax.vmap(one)(jax.random.split(jax.random.PRNGKey(7), n)))
        freq = np.bincount(toks, minlength=V) / n
        for v in range(V):
            sigma = np.sqrt(p[v] * (1 - p[v]) / n)
            assert abs(freq[v] - p[v]) < 4 * sigma + 1e-9, (v, freq, p)

    def test_end_to_end_distribution_close_to_plain(self, engine_pair):
        """Seeded end-to-end check: the spec loop's vote distribution at
        temperature 1 matches the plain loop's within binomial noise
        (different RNG consumption, same law)."""
        std, spec = engine_pair
        B = 96
        prompts = [("s", "vote on the proposal", VOTE)] * B

        def stop_frac(engine):
            out = engine.batch_generate_json(prompts, temperature=1.0,
                                             max_tokens=24)
            assert all("error" not in r for r in out)
            return sum(r["decision"] == "stop" for r in out) / B

        f_std, f_spec = stop_frac(std), stop_frac(spec)
        # 4-sigma two-sample band at n=96/side, worst-case p=0.5.
        assert abs(f_std - f_spec) < 4 * np.sqrt(2 * 0.25 / B), (f_std, f_spec)

    def test_verify_pass_accepts_probable_drafts(self):
        """accept_draft's greedy arm: a draft equal to the argmax chain
        is fully accepted; a corrupted tail truncates acceptance."""
        V, eos, K = 8, 7, 3
        batch = GuidedBatch.permissive(1, V)
        ml = make_masked_logits(eos, 1.0)
        # logits_all[., j] puts all mass on token j+1 -> greedy chain
        # 1, 2, 3 for draft indices 0..2 (position 0 is the sampled tok).
        la = np.full((1, K + 1, V), -20.0, np.float32)
        for j in range(K):
            la[0, j, j + 1] = 10.0
        la[0, K, 0] = 10.0
        common = dict(
            states_v=jnp.zeros((1, K), jnp.int32),
            emitted=jnp.zeros((1,), jnp.int32),
            tables=batch.tables, accepting=batch.accepting,
            min_budget=batch.min_budget, dfa_ids=batch.dfa_ids,
            row_temp=jnp.zeros((1,)),
            row_budget=jnp.full((1,), 100, jnp.int32),
            masked_logits=ml, eos_id=eos,
        )
        acc, forbid, nl, _ = accept_draft(
            jnp.asarray(la), jnp.asarray([[1, 2, 3]], jnp.int32),
            jnp.ones((1, K), bool), rng=jax.random.PRNGKey(0), **common,
        )
        assert int(acc[0]) == 3 and int(forbid[0]) == -1
        assert int(np.argmax(np.asarray(nl)[0])) == 0  # bonus position
        acc2, forbid2, nl2, _ = accept_draft(
            jnp.asarray(la), jnp.asarray([[1, 5, 3]], jnp.int32),
            jnp.ones((1, K), bool), rng=jax.random.PRNGKey(0), **common,
        )
        assert int(acc2[0]) == 1 and int(forbid2[0]) == 5
        # Carry logits come from the last ACCEPTED position (chunk pos 1
        # predicts draft index 1 -> argmax 2, the token the next
        # iteration will sample).
        assert int(np.argmax(np.asarray(nl2)[0])) == 2


# ------------------------------------------------------ engine plumbing
class TestDisabledDefault:
    def test_default_engine_has_no_spec_surface(self):
        jax.config.update("jax_platforms", "cpu")
        eng = JaxEngine(_base_config())
        before = obs_counters.snapshot()
        eng.batch_generate_json([("s", "vote", VOTE)], temperature=0.0,
                                max_tokens=16)
        moved = obs_counters.delta(before)
        assert not any(k.startswith("engine.spec") for k in moved), moved
        # Same jit entry points as before this feature existed.
        assert set(eng._jit_shapes) == {"prefill", "decode_loop"}
        assert not any(
            isinstance(k, tuple) and k and k[0] == "spec"
            for k in eng._decode_loops
        )
        eng.shutdown()


class TestProvisioning:
    def test_spec_slots_cover_worst_case(self):
        assert spec_decode_slots(100, 4) == 106
        assert spec_decode_slots(1, 1) == 4

    def test_worst_case_window_grows_with_spec(self):
        jax.config.update("jax_platforms", "cpu")
        plain = JaxEngine(_base_config())
        w_plain = plain.worst_case_decode_window()
        plain.shutdown()
        spec = JaxEngine(_base_config(spec_decode=True, spec_k=4))
        w_spec = spec.worst_case_decode_window()
        spec.shutdown()
        assert w_plain == 2048  # plain loop: exactly max_model_len
        assert w_spec == 2048 + 4 + 1  # + K+1 verify-window overhang

    def test_serve_admission_uses_worst_case_window(self):
        from bcg_tpu.serve.scheduler import derive_row_cap

        seen = {}

        class _Eng:
            max_model_len = 1000

            def cap_for(self, S):
                seen["S"] = S
                return 7

            def worst_case_decode_window(self):
                return 1234

        assert derive_row_cap(_Eng()) == 7
        assert seen["S"] == 1234

        class _Legacy:
            max_model_len = 1000

            def cap_for(self, S):
                seen["S"] = S
                return 3

        assert derive_row_cap(_Legacy()) == 3
        assert seen["S"] == 1000

    def test_env_flags_enable_and_tune(self, monkeypatch):
        jax.config.update("jax_platforms", "cpu")
        monkeypatch.setenv("BCG_TPU_SPEC", "1")
        monkeypatch.setenv("BCG_TPU_SPEC_K", "6")
        monkeypatch.setenv("BCG_TPU_SPEC_NGRAM", "2")
        eng = JaxEngine(_base_config())
        assert eng.spec_decode and eng.spec_k == 6 and eng.spec_ngram == 2
        eng.shutdown()


# ------------------------------------------------------- hermetic mirror
class TestFakeMirror:
    def test_numpy_mirror_counts(self):
        # Output "abcabcabc" over prompt "abcabc": pure self-echo, so
        # after the first cycle nearly everything drafts and accepts.
        prompt = list(b"abcabcabc")
        out = list(b"abcabcabcabc")
        drafted, accepted, iters = spec_mirror_np(prompt, out, 3, 4)
        assert accepted > 0 and accepted <= drafted
        assert iters + accepted == len(out)

    def test_fake_engine_mirrors_counters_and_span(self, monkeypatch):
        from bcg_tpu.engine.fake import FakeEngine
        from bcg_tpu.obs import tracer as obs_tracer

        prompts = [("sys " * 30, "agent_1 value: 17. agent_2 value: 17.", DECISION)]
        monkeypatch.delenv("BCG_TPU_SPEC", raising=False)
        plain_out = FakeEngine(seed=0).batch_generate_json(prompts)
        monkeypatch.setenv("BCG_TPU_SPEC", "1")
        monkeypatch.setenv("BCG_TPU_TRACE", "1")
        obs_tracer.reset()
        try:
            eng = FakeEngine(seed=0)
            before = obs_counters.snapshot()
            out = eng.batch_generate_json(prompts)
            assert "error" not in out[0]
            # The mirror observes; it must never alter responses.
            assert out == plain_out
            moved = obs_counters.delta(before)
            assert moved.get("engine.spec.drafted", 0) > 0
            assert 0 < moved.get("engine.spec.accepted", 0) <= moved[
                "engine.spec.drafted"
            ]
            names = [e[1] for e in obs_tracer.get_tracer().events()]
            assert "engine.spec_verify" in names
        finally:
            obs_tracer.reset()

    def test_fake_engine_off_by_default(self, monkeypatch):
        from bcg_tpu.engine.fake import FakeEngine

        monkeypatch.delenv("BCG_TPU_SPEC", raising=False)
        eng = FakeEngine(seed=0)
        before = obs_counters.snapshot()
        eng.batch_generate_json([("s", "u", VOTE)])
        moved = obs_counters.delta(before)
        assert not any(k.startswith("engine.spec") for k in moved)


class TestServeStats:
    def test_snapshot_carries_acceptance_rate(self):
        from bcg_tpu.serve.scheduler import SchedulerStats

        stats = SchedulerStats()  # baselines at current counter values
        obs_counters.inc("engine.spec.drafted", 10)
        obs_counters.inc("engine.spec.accepted", 6)
        obs_counters.inc("engine.spec.rejected", 4)
        snap = stats.snapshot()
        assert snap["spec"] == {
            "drafted": 10, "accepted": 6, "rejected": 4,
            "acceptance_rate": 0.6,
        }
        # A scheduler constructed AFTER the movement sees none of it.
        assert SchedulerStats().snapshot()["spec"] is None
