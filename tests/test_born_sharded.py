"""Born-sharded parameter instantiation (models/loader.py
init_random_params_sharded) and the analytic boot-memory accounting
(loader.boot_peak_report) — the flagship-scale boot path that replaces
eager unsharded ``init_params`` for hermetic presets.

Runs on the 8-virtual-device CPU mesh from conftest.py
(XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""

import jax
import numpy as np
import pytest

from bcg_tpu.models.configs import MODEL_SPECS, spec_for_model
from bcg_tpu.models.loader import boot_peak_report, init_random_params_sharded
from bcg_tpu.models.quantize import quantize_leaf_transform
from bcg_tpu.models.transformer import (
    assemble_param_tree,
    init_params,
    param_plan,
    stack_layer_params,
)
from bcg_tpu.parallel import build_mesh
from bcg_tpu.parallel.sharding import param_sharding

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

TINY = "bcg-tpu/tiny-test"


def _walk(params):
    """(logical, leaf) pairs over a param tree, quantized sub-leaves
    included ("layers.0.wq.q" style paths)."""
    for top, v in params.items():
        if top == "layers":
            for li, layer in enumerate(v):
                for name, leaf in layer.items():
                    if isinstance(leaf, dict):
                        for sub, s in leaf.items():
                            yield f"layers.{li}.{name}.{sub}", s
                    else:
                        yield f"layers.{li}.{name}", leaf
        elif isinstance(v, dict):
            for sub, s in v.items():
                yield f"{top}.{sub}", s
        else:
            yield top, v


class TestBornShardedInit:
    def test_plan_matches_eager_structure(self):
        spec = spec_for_model(TINY)
        eager = init_params(spec, jax.random.PRNGKey(0))
        plan_tree = assemble_param_tree(
            (logical, jax.ShapeDtypeStruct(shape, jax.numpy.bfloat16))
            for logical, _kind, shape in param_plan(spec)
        )
        assert jax.tree.structure(eager) == jax.tree.structure(plan_tree)
        for a, b in zip(jax.tree.leaves(eager), jax.tree.leaves(plan_tree)):
            assert a.shape == b.shape

    def test_values_mesh_shape_invariant(self):
        # Same seed -> same weights at mesh=None, tp=2 and dp2/tp2/sp2:
        # the partitionable-RNG scope makes the served model independent
        # of the parallelism config.
        spec = spec_for_model(TINY)
        key = jax.random.PRNGKey(0)
        base = init_random_params_sharded(spec, key)
        for mesh in (build_mesh(dp=1, tp=2, sp=1), build_mesh(dp=2, tp=2, sp=2)):
            got = init_random_params_sharded(spec, key, mesh=mesh)
            jax.tree.map(
                lambda x, y: np.testing.assert_array_equal(
                    np.asarray(x), np.asarray(y)
                ),
                base, got,
            )

    def test_every_leaf_carries_prescribed_sharding(self):
        spec = spec_for_model(TINY)
        mesh = build_mesh(dp=2, tp=2, sp=2)
        params = init_random_params_sharded(
            spec, jax.random.PRNGKey(0), mesh=mesh
        )
        for logical, leaf in _walk(params):
            expected = param_sharding(logical, spec, mesh)
            assert leaf.sharding == expected, (
                f"{logical}: {leaf.sharding} != {expected}"
            )

    def test_quantized_leaves_carry_prescribed_sharding(self):
        # The acceptance property: quantize happens INSIDE the per-leaf
        # jit, and the {"q","scale"} outputs land directly under their
        # param_sharding — no unsharded full-precision leaf in between.
        spec = spec_for_model(TINY)
        mesh = build_mesh(dp=1, tp=2, sp=1)
        params = init_random_params_sharded(
            spec, jax.random.PRNGKey(0), mesh=mesh,
            leaf_transform=quantize_leaf_transform(spec, "int8"),
        )
        wq = params["layers"][0]["wq"]
        assert sorted(wq.keys()) == ["q", "scale"]
        for logical, leaf in _walk(params):
            expected = param_sharding(logical, spec, mesh)
            assert leaf.sharding == expected, (
                f"{logical}: {leaf.sharding} != {expected}"
            )

    def test_quantized_values_match_post_hoc_quantization(self):
        # Born-quantized == quantize-after-init for the same weights
        # (same _quantize_impl, just jitted per leaf with out_shardings).
        spec = spec_for_model(TINY)
        mesh = build_mesh(dp=1, tp=2, sp=1)
        transform = quantize_leaf_transform(spec, "int8")
        born = init_random_params_sharded(
            spec, jax.random.PRNGKey(0), mesh=mesh, leaf_transform=transform,
        )
        plain = init_random_params_sharded(spec, jax.random.PRNGKey(0))
        ref = transform("layers.0.wq", plain["layers"][0]["wq"])
        np.testing.assert_array_equal(
            np.asarray(born["layers"][0]["wq"]["q"]), np.asarray(ref["q"])
        )

    def test_stack_keeps_sharding_and_values(self):
        spec = spec_for_model(TINY)
        mesh = build_mesh(dp=1, tp=2, sp=1)
        transform = quantize_leaf_transform(spec, "int8")
        params = init_random_params_sharded(
            spec, jax.random.PRNGKey(0), mesh=mesh, leaf_transform=transform,
        )
        reference = init_random_params_sharded(
            spec, jax.random.PRNGKey(0), mesh=mesh, leaf_transform=transform,
        )
        stacked = stack_layer_params(params, consume=True, mesh=mesh, spec=spec)
        wq = stacked["layers"]["wq"]
        assert wq["q"].shape[0] == spec.num_layers
        assert wq["q"].sharding == param_sharding(
            "layers.wq.q", spec, mesh, stacked=True
        )
        # Values survive the donated, out_sharded stack.
        ref_stack = np.stack(
            [np.asarray(l["wq"]["q"]) for l in reference["layers"]]
        )
        np.testing.assert_array_equal(np.asarray(wq["q"]), ref_stack)


class TestBootAccounting:
    """Analytic (eval_shape, no weights) per-device boot-peak accounting
    for flagship specs — the 14B acceptance criterion."""

    def _assert_contract(self, report):
        headroom = max(
            report["max_leaf_group_bytes"], report["max_init_transient_bytes"]
        )
        assert report["peak_bytes_per_device"] <= (
            report["final_bytes_per_device"] + headroom
        )

    def test_14b_int4_tp8_peak_bound(self):
        spec = MODEL_SPECS["bcg-tpu/bench-14b"]
        mesh = build_mesh(dp=1, tp=8, sp=1)
        report = boot_peak_report(spec, mesh=mesh, quantization="int4")
        self._assert_contract(report)
        # No unsharded full-precision leaf at any point: the biggest
        # init transient is a SHARD, strictly below the full fp32 embed
        # the old eager init staged on one device.
        full_embed_fp32 = spec.vocab_size * spec.hidden_size * 4
        assert report["max_init_transient_bytes"] < full_embed_fp32
        # Absolute scale: a 14B int4 boot fits one 16 GB v5e chip's
        # share with the decode budget untouched (~1.6 GB peak at tp=8).
        assert report["peak_bytes_per_device"] < 4 << 30

    def test_14b_int8_tp2_peak_bound(self):
        spec = MODEL_SPECS["bcg-tpu/bench-14b"]
        mesh = build_mesh(dp=1, tp=2, sp=1)
        report = boot_peak_report(spec, mesh=mesh, quantization="int8")
        self._assert_contract(report)
        # int8 14B across two 16 GB chips: weights ~7.5 GB/device, the
        # boot transient must not add more than one leaf-group on top.
        assert report["peak_bytes_per_device"] < 12 << 30

    def test_single_device_path(self):
        spec = spec_for_model(TINY)
        report = boot_peak_report(spec, mesh=None, quantization=None)
        self._assert_contract(report)
        assert report["devices"] == 1

    def test_peak_drops_with_tp(self):
        spec = MODEL_SPECS["bcg-tpu/bench-8b"]
        r2 = boot_peak_report(
            spec, mesh=build_mesh(dp=1, tp=2, sp=1), quantization="int8"
        )
        r8 = boot_peak_report(
            spec, mesh=build_mesh(dp=1, tp=8, sp=1), quantization="int8"
        )
        assert r8["peak_bytes_per_device"] < r2["peak_bytes_per_device"]
