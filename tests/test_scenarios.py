"""Adversary library + scenario registry (bcg_tpu/scenarios/).

Owns the perf-gate ``scenarios.*`` namespace (tests/test_perf_gate.py
NAMESPACE_OWNERS): the gate-backed class at the bottom pins the
scenario green at HEAD, the resurface contract (removing a baseline
entry fails as "no entry"), and the scenarios-off injection failing
loudly instead of vacuously green.

Above it, the subsystem's own contracts:

* strategy library — the two pure value formulas (equivocation spread,
  clique target), the catalog/lookup surface, and the prompt-block
  substitution the LLM path grafts in;
* scenario registry — param overlays for the sweep layer, the
  role-aware scripted-policy mirror, apply_scenario onto a BCGConfig;
* sweep integration — adversary-grid expansion, overlay precedence
  (explicit keys beat the registry), derived-policy engine keying;
* end-to-end — an equivocation game's ``deliveries`` events carry
  per-receiver divergent values; a plain strategy's do not.
"""

import dataclasses
import importlib.util
import json
import os

import pytest

from bcg_tpu.config import (
    BCGConfig,
    EngineConfig,
    GameConfig,
    MetricsConfig,
    NetworkConfig,
)
from bcg_tpu.engine.fake import BYZANTINE_POLICIES
from bcg_tpu.obs import game_events
from bcg_tpu.runtime.orchestrator import BCGSimulation
from bcg_tpu.scenarios.registry import (
    SCENARIOS,
    apply_scenario,
    get_scenario,
    scenario_names,
    scenario_params,
    scripted_fake_policy,
)
from bcg_tpu.scenarios.strategies import (
    STRATEGIES,
    clique_target,
    equivocation_value,
    get_strategy,
    persona_block,
    strategy_names,
    task_block,
)
from bcg_tpu.sweep.spec import JOB_DEFAULTS, expand, load_spec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO, "scripts", "perf_gate.py")


# ------------------------------------------------------------- strategies


class TestStrategyLibrary:
    def test_equivocation_value_receiver_zero_is_identity(self):
        for base in (0, 7, 50):
            assert equivocation_value(base, 0, 0, 50) == base

    def test_equivocation_value_spreads_within_range(self):
        lo, hi = 10, 20
        seen = set()
        for receiver in range(8):
            v = equivocation_value(14, receiver, lo, hi)
            assert lo <= v <= hi
            seen.add(v)
        # 8 receivers over an 11-value span: all distinct.
        assert len(seen) == 8

    def test_equivocation_value_wraps_modularly(self):
        # base at the top of the range wraps to the bottom, never out.
        assert equivocation_value(50, 1, 0, 50) == 0

    def test_clique_target_is_deterministic_and_in_range(self):
        lo, hi = 0, 50
        for seed in (None, 0, 1, 2, 99):
            t = clique_target(seed, lo, hi)
            assert lo <= t <= hi
            assert t == clique_target(seed, lo, hi)
        # None and 0 share the pre-agreed target (seed or 0).
        assert clique_target(None, lo, hi) == clique_target(0, lo, hi)

    def test_clique_target_varies_with_seed(self):
        targets = {clique_target(s, 0, 50) for s in range(8)}
        assert len(targets) > 1

    def test_catalog_and_lookup(self):
        assert set(strategy_names()) == set(STRATEGIES)
        assert get_strategy("disrupt").fake_policy == "disrupt"
        with pytest.raises(KeyError, match="unknown byzantine strategy"):
            get_strategy("nope")

    def test_every_fake_policy_is_engine_valid(self):
        """A strategy's scripted mirror must name a real FakeEngine
        byzantine policy — a typo here would otherwise only fail at
        engine boot inside a sweep job."""
        for s in STRATEGIES.values():
            assert s.fake_policy in BYZANTINE_POLICIES, s.name

    def test_exactly_the_structured_strategies_flag_their_layer(self):
        assert get_strategy("equivocate").equivocates
        assert get_strategy("clique").clique
        for name in ("disrupt", "oscillate", "mimic", "silent"):
            s = get_strategy(name)
            assert not s.equivocates and not s.clique, name

    def test_persona_block_resolves_clique_target(self):
        s = get_strategy("clique")
        block = persona_block(s, 0, 50, seed=0)
        assert str(clique_target(0, 0, 50)) in block
        assert "{target}" not in block
        assert "STRATEGY DIRECTIVE (clique)" in block

    def test_default_strategy_keeps_reference_persona(self):
        assert persona_block(get_strategy("disrupt"), 0, 50, 0) == ""
        assert task_block(get_strategy("disrupt"), 0, 50, 0) is None

    def test_task_block_substitutes_snapshot(self):
        s = get_strategy("adaptive")
        text = task_block(s, 0, 50, 0, snapshot="spread=12 mode=30")
        assert "spread=12 mode=30" in text
        assert "{snapshot}" not in text
        assert "{snapshot}" not in task_block(s, 0, 50, 0)


# --------------------------------------------------------------- registry


class TestRegistry:
    def test_catalog_and_lookup(self):
        assert set(scenario_names()) == set(SCENARIOS)
        with pytest.raises(KeyError, match="unknown scenario"):
            get_scenario("nope")

    def test_every_scenario_names_a_real_strategy(self):
        for s in SCENARIOS.values():
            assert s.strategy in STRATEGIES, s.name

    def test_scenario_params_overlay_shape(self):
        p = scenario_params("silent-ring")
        assert p["strategy"] == "silent"
        assert p["topology"] == "ring"
        # Every overlay key is a sweep job parameter.
        assert set(p) <= set(JOB_DEFAULTS)
        # Channel key only present when the scenario sets it.
        assert "drop_prob" not in p
        assert scenario_params("oscillate-lossy")["drop_prob"] == 0.2

    def test_awareness_variant_rides_the_overlay(self):
        assert scenario_params("mimic-unaware")["awareness"] == "none_exist"

    def test_scripted_policy_is_role_aware(self):
        assert scripted_fake_policy("clique") == "mixed:consensus:clique"
        with pytest.raises(KeyError):
            scripted_fake_policy("nope")

    def test_apply_scenario_onto_fake_config(self):
        base = dataclasses.replace(
            BCGConfig(), engine=EngineConfig(backend="fake"),
        )
        cfg = apply_scenario(base, "oscillate-lossy")
        assert cfg.game.byzantine_strategy == "oscillate"
        assert cfg.game.num_byzantine == 2
        assert cfg.engine.fake_policy == "mixed:consensus:oscillate"
        assert cfg.communication.protocol_type == "lossy_sim"
        assert cfg.communication.drop_prob == 0.2

    def test_apply_scenario_leaves_ideal_channel_alone(self):
        cfg = apply_scenario(BCGConfig(), "baseline-disrupt")
        assert cfg.communication.protocol_type != "lossy_sim"
        assert cfg.network.topology_type == "fully_connected"


# ------------------------------------------------------- sweep integration


class TestSweepIntegration:
    def test_adversary_grid_expands_every_scenario(self):
        jobs = expand(load_spec("adversary-grid"))
        assert len(jobs) == len(SCENARIOS) * 3
        strategies = {j.params["strategy"] for j in jobs}
        assert strategies == set(STRATEGIES)

    def test_overlay_fills_registry_values(self):
        jobs = expand({"axes": {"scenario": ["silent-ring"]}})
        (job,) = jobs
        assert job.params["topology"] == "ring"
        assert job.params["strategy"] == "silent"
        assert job.params["agents"] == 6

    def test_explicit_keys_beat_the_overlay(self):
        jobs = expand({
            "base": {"agents": 8},
            "axes": {"scenario": ["silent-ring"]},
        })
        (job,) = jobs
        assert job.params["agents"] == 8        # pinned
        assert job.params["topology"] == "ring"  # still overlaid

    def test_unknown_scenario_fails_expansion_loudly(self):
        with pytest.raises(KeyError, match="unknown scenario"):
            expand({"axes": {"scenario": ["typo-grid"]}})

    def test_strategy_jobs_derive_distinct_engine_keys(self):
        """Two jobs whose strategies script different FakeEngine
        policies must never share one engine."""
        jobs = expand({
            "axes": {"scenario": ["clique-collusion", "silent-ring"]},
        })
        keys = {j.engine_key() for j in jobs}
        assert len(keys) == 2
        for job in jobs:
            assert job.engine_key()[-1] == scripted_fake_policy(
                str(job.params["strategy"])
            )

    def test_explicit_fake_policy_wins_over_strategy(self):
        jobs = expand({
            "base": {"fake_policy": "mixed:consensus:disrupt"},
            "axes": {"scenario": ["clique-collusion"]},
        })
        (job,) = jobs
        cfg = job.to_config()
        assert cfg.engine.fake_policy == "mixed:consensus:disrupt"
        assert job.engine_key()[-1] == "mixed:consensus:disrupt"

    def test_strategy_reaches_the_game_config(self):
        jobs = expand({"axes": {"scenario": ["adaptive-margin"]}})
        cfg = jobs[0].to_config()
        assert cfg.game.byzantine_strategy == "adaptive"
        assert cfg.engine.fake_policy == "mixed:consensus:adaptive"

    def test_lossy_scenario_configures_the_channel(self):
        jobs = expand({"axes": {"scenario": ["oscillate-lossy"]}})
        cfg = jobs[0].to_config()
        assert cfg.communication.protocol_type == "lossy_sim"
        assert cfg.communication.drop_prob == 0.2


# ------------------------------------------------------------- end-to-end


def _scenario_config(name, seed=0):
    base = dataclasses.replace(
        BCGConfig(),
        game=GameConfig(seed=seed),
        network=NetworkConfig(),
        engine=EngineConfig(backend="fake"),
        metrics=MetricsConfig(save_results=False),
        verbose=False,
    )
    return apply_scenario(base, name)


@pytest.fixture
def events_enabled(tmp_path, monkeypatch):
    path = tmp_path / "game_events.jsonl"
    monkeypatch.setenv("BCG_TPU_GAME_EVENTS", str(path))
    game_events.reset_sink()
    game_events._reset_aggregate()
    yield path
    game_events.reset_sink()
    game_events._reset_aggregate()


def _divergent_rows(path):
    """(round, sender) pairs whose receivers logged different values —
    the same tabulation consensus_report.py runs over deliveries."""
    per = {}
    strategy = None
    for line in path.read_text().splitlines():
        rec = json.loads(line)
        if rec.get("event") == "game_start":
            strategy = rec.get("strategy")
        if rec.get("event") != "deliveries" or rec.get("values") is None:
            continue
        for sender, value in zip(rec["senders"], rec["values"]):
            per.setdefault((rec["round"], sender), set()).add(value)
    return strategy, sum(1 for vals in per.values() if len(vals) > 1)


def _run_scenario_game(name, path):
    sim = BCGSimulation(config=_scenario_config(name))
    try:
        sim.run()
    finally:
        sim.close()
    game_events.reset_sink()  # drain to disk
    return [json.loads(l) for l in path.read_text().splitlines()]


def _byzantine_decisions(records):
    return [
        (r["round"], r["agent"], r["value"]) for r in records
        if r["event"] == "decision" and r["role"] == "byzantine"
    ]


class TestScenarioEndToEnd:
    def test_equivocation_game_emits_divergent_deliveries(
        self, events_enabled
    ):
        _run_scenario_game("equivocation-split", events_enabled)
        strategy, divergent = _divergent_rows(events_enabled)
        assert strategy == "equivocate"
        assert divergent >= 1

    def test_plain_strategy_game_never_diverges(self, events_enabled):
        _run_scenario_game("clique-collusion", events_enabled)
        strategy, divergent = _divergent_rows(events_enabled)
        assert strategy == "clique"
        assert divergent == 0

    def test_clique_mirror_holds_the_shared_target(self, events_enabled):
        """Every byzantine decision of the scripted clique mirror is
        the seed-derived shared target — no runtime coordination, both
        colluders land on it independently."""
        records = _run_scenario_game("clique-collusion", events_enabled)
        lo, hi = next(
            r for r in records if r["event"] == "game_start"
        )["value_range"]
        decisions = _byzantine_decisions(records)
        assert decisions
        target = clique_target(0, lo, hi)
        assert all(value == target for _, _, value in decisions), decisions

    def test_adaptive_mirror_targets_the_antipode(self, events_enabled):
        """The scripted adaptive mirror is an exact oracle: each round
        it proposes the modular antipode of the mode of the values it
        RECEIVED last round (smallest-on-ties), reconstructed here from
        the per-receiver deliveries telemetry."""
        from collections import Counter

        records = _run_scenario_game("adaptive-margin", events_enabled)
        lo, hi = next(
            r for r in records if r["event"] == "game_start"
        )["value_range"]
        span = hi - lo + 1
        received = {
            (r["round"], r["agent"]):
                [v for v in r.get("values", []) if v is not None and v >= 0]
            for r in records if r["event"] == "deliveries"
        }
        decisions = _byzantine_decisions(records)
        assert decisions
        for rnd, agent, value in decisions:
            observed = received.get((rnd - 1, agent), [])
            if observed:
                counts = Counter(observed)
                best = max(counts.values())
                mode = min(v for v, c in counts.items() if c == best)
                expected = lo + (mode - lo + span // 2) % span
            else:
                expected = hi
            assert value == expected, (rnd, agent, value, expected)

    def test_equivocate_mirror_spreads_its_round_base(
        self, events_enabled
    ):
        """The scripted equivocate mirror proposes ``lo + round mod
        span`` as its base, and the exchange layer spreads it: every
        value an equivocating sender delivered in round r is a
        per-receiver offset of that base (equivocation_value over some
        receiver index)."""
        records = _run_scenario_game("equivocation-split", events_enabled)
        start = next(r for r in records if r["event"] == "game_start")
        lo, hi = start["value_range"]
        span = hi - lo + 1
        byz = {agent for _, agent, _ in _byzantine_decisions(records)}
        assert byz
        for rnd, agent, value in _byzantine_decisions(records):
            assert value == lo + rnd % span, (rnd, agent, value)
        n = int(start["num_honest"]) + int(start["num_byzantine"])
        allowed = {
            (rnd, lo + (rnd % span + i) % span)
            for rnd in range(1, 1 + int(start["max_rounds"]))
            for i in range(n)
        }
        for r in records:
            if r["event"] != "deliveries" or r.get("values") is None:
                continue
            for sender, value in zip(r["senders"], r["values"]):
                if sender in byz:
                    assert (r["round"], value) in allowed, (r, sender)


# ------------------------------------------------------------- gate-backed


def _load_gate():
    spec = importlib.util.spec_from_file_location("perf_gate_scn", GATE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def scenarios_gate():
    mod = _load_gate()
    measured = mod.run_scenarios_scenario()
    return mod, measured


class TestScenariosGate:
    def test_scenario_green_at_head(self, scenarios_gate):
        mod, measured = scenarios_gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(
            measured, mod.load_baseline(), ("scenarios",)
        )
        assert findings == [], "\n".join(findings)

    def test_measures_the_advertised_metrics(self, scenarios_gate):
        _, measured = scenarios_gate
        for name in (
            "scenarios.influence_disrupt",
            "scenarios.influence_clique",
            "scenarios.influence_adaptive",
            "scenarios.influence_equivocate",
            "scenarios.equivocation_divergence_rows",
            "scenarios.offstrategy_divergence_rows",
            "scenarios.clique_shared_target_agreement",
            "scenarios.strategies_covered",
            "scenarios.error_rows",
        ):
            assert name in measured, sorted(measured)

    def test_equivocation_diverges_and_nothing_else_does(
        self, scenarios_gate
    ):
        """ISSUE acceptance: per-receiver divergence >= 1 under the
        equivocate strategy and EXACTLY 0 everywhere else (the all-off
        equivocators mask reduces to a plain broadcast)."""
        _, measured = scenarios_gate
        assert measured["scenarios.equivocation_divergence_rows"] >= 1
        assert measured["scenarios.offstrategy_divergence_rows"] == 0

    def test_clique_holds_its_shared_target(self, scenarios_gate):
        _, measured = scenarios_gate
        assert measured["scenarios.clique_shared_target_agreement"] == 1.0

    def test_removing_entry_resurfaces_unbaselined_failure(
        self, scenarios_gate
    ):
        mod, measured = scenarios_gate
        baseline = mod.load_baseline()
        pruned = {
            "metrics": {
                k: v for k, v in baseline["metrics"].items()
                if k != "scenarios.equivocation_divergence_rows"
            }
        }
        findings = mod.check_metrics(measured, pruned)
        assert any(
            "scenarios.equivocation_divergence_rows" in f and "no entry" in f
            for f in findings
        ), findings

    def test_scenarios_off_injection_fails_naming_metrics(self):
        mod = _load_gate()
        measured = mod.run_scenarios_scenario("scenarios-off")
        findings = mod.check_metrics(measured, mod.load_baseline())
        named = "\n".join(findings)
        for metric in (
            "scenarios.influence_disrupt",
            "scenarios.influence_clique",
            "scenarios.equivocation_divergence_rows",
            "scenarios.clique_shared_target_agreement",
            "scenarios.strategies_covered",
        ):
            assert metric in named, (metric, findings)
