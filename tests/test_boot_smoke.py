"""Wire scripts/boot_smoke.py into the tier-1 suite: every preset
(14B/32B included) must abstract-boot — plan + shardings + HBM
accounting — without materializing weights."""

import importlib.util
import os

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _load_boot_smoke():
    path = os.path.join(REPO, "scripts", "boot_smoke.py")
    spec = importlib.util.spec_from_file_location("boot_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_all_presets_abstract_boot():
    boot_smoke = _load_boot_smoke()
    problems = boot_smoke.run_all(verbose=False)
    assert problems == []


def test_smoke_catches_bad_sharding():
    # The smoke is only worth wiring in if it actually FAILS on an
    # inconsistency: a mesh whose tp doesn't divide the 14B vocab dim
    # must surface as a placement problem, not pass silently.
    boot_smoke = _load_boot_smoke()
    from bcg_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(dp=1, tp=5, sp=1)  # 151936 % 5 != 0
    problems = boot_smoke.check_preset("bcg-tpu/bench-14b", mesh, "int8")
    assert any("does not place" in p for p in problems)
