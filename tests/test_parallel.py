"""Multi-device tests on the virtual 8-CPU mesh: mesh/sharding, ring
attention exactness, SPMD game step parity with the host game."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.comm import NetworkTopology
from bcg_tpu.game import ByzantineConsensusGame
from bcg_tpu.models import init_params, spec_for_model
from bcg_tpu.parallel import build_mesh, shard_params
from bcg_tpu.parallel.game_step import (
    check_consensus_spmd,
    exchange_values,
    spmd_round_arrays,
    tally_votes,
)
from bcg_tpu.ops.ring_attention import ring_attention
from bcg_tpu.models.transformer import _xla_attention

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


class TestMesh:
    def test_build_mesh_shapes(self):
        mesh = build_mesh(dp=2, tp=2, sp=2)
        assert dict(mesh.shape) == {"dp": 2, "tp": 2, "sp": 2}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="devices"):
            build_mesh(dp=4, tp=4, sp=4)

    def test_shard_params_tp(self):
        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        mesh = build_mesh(dp=1, tp=2, sp=1)
        sharded = shard_params(params, spec, mesh)
        wq = sharded["layers"][0]["wq"]
        # Column-parallel: output dim split over tp.
        assert wq.sharding.spec == jax.sharding.PartitionSpec(None, "tp")
        norm = sharded["layers"][0]["attn_norm"]
        assert norm.sharding.spec == jax.sharding.PartitionSpec(None)


class TestRingAttention:
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_full_attention(self, sp):
        mesh = build_mesh(dp=1, tp=1, sp=sp)
        B, T, H, Hkv, Dh = 2, 32, 4, 2, 16
        key = jax.random.PRNGKey(1)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, T, H, Dh), jnp.float32)
        k = jax.random.normal(kk, (B, T, Hkv, Dh), jnp.float32)
        v = jax.random.normal(kv, (B, T, Hkv, Dh), jnp.float32)

        ring = ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
        causal = jnp.tril(jnp.ones((T, T), bool))[None]
        full = _xla_attention(q, k, v, jnp.broadcast_to(causal, (B, T, T)),
                              1.0 / np.sqrt(Dh))
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   rtol=2e-4, atol=2e-4)

    def test_non_causal(self):
        mesh = build_mesh(dp=1, tp=1, sp=4)
        B, T, H, Dh = 1, 16, 2, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, Dh))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, T, H, Dh))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, T, H, Dh))
        ring = ring_attention(q, k, v, mesh, causal=False)
        full = _xla_attention(q, k, v, jnp.ones((B, T, T), bool), 1.0 / np.sqrt(Dh))
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   rtol=2e-4, atol=2e-4)

    def test_indivisible_length_raises(self):
        mesh = build_mesh(dp=1, tp=1, sp=8)
        x = jnp.zeros((1, 12, 2, 8))
        with pytest.raises(ValueError, match="divisible"):
            ring_attention(x, x, x, mesh)

    def test_composed_mesh_dp_tp_sp(self):
        """On a dp x tp x sp mesh the batch shards over dp and heads
        over tp (replicating them would all-gather tp-sharded heads into
        every device and defeat the O(L/sp) memory point); results must
        still match full attention."""
        mesh = build_mesh(dp=2, tp=2, sp=2)
        B, T, H, Hkv, Dh = 4, 16, 4, 2, 8
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(5), 3)
        q = jax.random.normal(kq, (B, T, H, Dh), jnp.float32)
        k = jax.random.normal(kk, (B, T, Hkv, Dh), jnp.float32)
        v = jax.random.normal(kv, (B, T, Hkv, Dh), jnp.float32)
        pad = jnp.array([0, 3, 9, 1])
        valid = jnp.arange(T)[None, :] >= pad[:, None]

        ring = ring_attention(q, k, v, mesh, axis_name="sp", causal=True,
                              kv_valid=valid)
        causal = jnp.tril(jnp.ones((T, T), bool))[None]
        mask = causal & valid[:, None, :] & valid[:, :, None]
        full = _xla_attention(q, k, v, mask, 1.0 / np.sqrt(Dh))
        vmask = np.asarray(valid)
        np.testing.assert_allclose(
            np.asarray(ring)[vmask], np.asarray(full)[vmask],
            rtol=2e-4, atol=2e-4,
        )

    @pytest.mark.parametrize("sp", [2, 4])
    def test_kv_valid_matches_masked_full_attention(self, sp):
        """Left-padded rows (the engine's batch layout): ring with a
        kv_valid mask must equal full attention under causal & validity
        masking, and fully-padded query rows must output 0."""
        mesh = build_mesh(dp=1, tp=1, sp=sp)
        B, T, H, Hkv, Dh = 3, 32, 4, 2, 16
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(kq, (B, T, H, Dh), jnp.float32)
        k = jax.random.normal(kk, (B, T, Hkv, Dh), jnp.float32)
        v = jax.random.normal(kv, (B, T, Hkv, Dh), jnp.float32)
        pad = jnp.array([0, 5, 19])  # row pad counts (left-padding)
        valid = jnp.arange(T)[None, :] >= pad[:, None]  # [B, T]

        ring = ring_attention(q, k, v, mesh, axis_name="sp", causal=True,
                              kv_valid=valid)
        causal = jnp.tril(jnp.ones((T, T), bool))[None]
        mask = causal & valid[:, None, :] & valid[:, :, None]
        full = _xla_attention(q, k, v, mask, 1.0 / np.sqrt(Dh))
        r, f = np.asarray(ring), np.asarray(full)
        # Pad q rows: engine's flash path zeroes them; _xla_attention's
        # f32 softmax over all -inf is NaN there — compare valid rows.
        vmask = np.asarray(valid)
        np.testing.assert_allclose(r[vmask], f[vmask], rtol=2e-4, atol=2e-4)
        assert not np.isnan(r).any()
        np.testing.assert_array_equal(r[~vmask], 0.0)

    def test_strongly_negative_logits_survive_empty_blocks(self):
        """Underflow regression: with heavy left-padding most ring steps
        see a fully-masked kv block.  A 0.0 sentinel max from those
        blocks would inflate the running max, underflowing exp() when
        every VALID logit is below ~-87; the merge must reference only
        finite block maxima."""
        sp = 4
        mesh = build_mesh(dp=1, tp=1, sp=sp)
        B, T, H, Hkv, Dh = 2, 32, 2, 2, 16
        # q·k * scale ≈ -25*16/4 = -100 on every valid pair.
        q = jnp.full((B, T, H, Dh), 5.0, jnp.float32)
        k = jnp.full((B, T, Hkv, Dh), -5.0, jnp.float32)
        kv0 = jax.random.normal(jax.random.PRNGKey(9), (B, T, Hkv, Dh))
        v = kv0.astype(jnp.float32)
        pad = jnp.array([28, 30])  # only the last shard holds valid kv
        valid = jnp.arange(T)[None, :] >= pad[:, None]
        ring = ring_attention(q, k, v, mesh, axis_name="sp", causal=True,
                              kv_valid=valid)
        causal = jnp.tril(jnp.ones((T, T), bool))[None]
        mask = causal & valid[:, None, :] & valid[:, :, None]
        full = _xla_attention(q, k, v, mask, 1.0 / np.sqrt(Dh))
        r, f = np.asarray(ring), np.asarray(full)
        vmask = np.asarray(valid)
        # All valid logits equal → softmax = running mean of valid v;
        # any underflow collapses the output to 0 instead.
        assert np.abs(r[vmask]).max() > 0.1
        np.testing.assert_allclose(r[vmask], f[vmask], rtol=2e-4, atol=2e-4)


class TestSpDecodeAttention:
    """Flash-decoding over a sequence-sharded cache: partials merge via
    pmax/psum of O(B*H) stats; must equal full-cache attention exactly,
    including rows whose valid slots all live on one shard."""

    def _ref(self, q, k, v, mask, scale):
        from bcg_tpu.models.transformer import _xla_attention

        return _xla_attention(q[:, None], k, v, mask[:, None, :], scale)[:, 0]

    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_full_cache_attention(self, sp):
        from bcg_tpu.ops.ring_attention import sp_decode_attention

        mesh = build_mesh(dp=1, tp=1, sp=sp)
        B, S, H, Hkv, Dh = 3, 64, 4, 2, 16
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(7), 3)
        q = jax.random.normal(kq, (B, H, Dh), jnp.float32)
        k = jax.random.normal(kk, (B, S, Hkv, Dh), jnp.float32)
        v = jax.random.normal(kv, (B, S, Hkv, Dh), jnp.float32)
        # Row 0: all slots; row 1: a short prefix (one shard's worth);
        # row 2: a scattered window.
        mask = jnp.stack([
            jnp.ones(S, bool),
            jnp.arange(S) < 6,
            (jnp.arange(S) % 3 == 0) & (jnp.arange(S) < 40),
        ])
        scale = 1.0 / np.sqrt(Dh)
        out = sp_decode_attention(q, k, v, mask, mesh, scale=scale)
        ref = self._ref(q, k, v, mask, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_strongly_negative_logits_survive_empty_shards(self):
        """Underflow regression (advisor r4): a short left-padded row on
        large sp leaves most cache shards fully masked.  pmax of a 0.0
        sentinel from the empty shards inflates the global max; when
        every valid logit is below ~-87 the f32 exp underflows and the
        output collapses to 0 instead of the true softmax average."""
        from bcg_tpu.ops.ring_attention import sp_decode_attention

        sp = 8
        mesh = build_mesh(dp=1, tp=1, sp=sp)
        B, S, H, Hkv, Dh = 2, 64, 4, 2, 16
        # q·k * scale ≈ -100 on every valid slot (all logits equal).
        q = jnp.full((B, H, Dh), 5.0, jnp.float32)
        k = jnp.full((B, S, Hkv, Dh), -5.0, jnp.float32)
        v = jax.random.normal(
            jax.random.PRNGKey(11), (B, S, Hkv, Dh), jnp.float32
        )
        # Valid slots confined to the LAST shard (slots 56..) — the
        # other 7 shards are empty and must not poison the merge.
        mask = jnp.arange(S)[None, :] >= jnp.array([56, 62])[:, None]
        scale = 1.0 / np.sqrt(Dh)
        out = sp_decode_attention(q, k, v, mask, mesh, scale=scale)
        ref = self._ref(q, k, v, mask, scale)
        assert np.abs(np.asarray(out)).max() > 0.1
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_composed_mesh(self):
        from bcg_tpu.ops.ring_attention import sp_decode_attention

        mesh = build_mesh(dp=2, tp=2, sp=2)
        B, S, H, Hkv, Dh = 4, 32, 4, 2, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, H, Dh))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, Hkv, Dh))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, Hkv, Dh))
        mask = jnp.arange(S)[None, :] < jnp.array([32, 5, 17, 1])[:, None]
        scale = 1.0 / np.sqrt(Dh)
        out = sp_decode_attention(q, k, v, mask, mesh, scale=scale)
        ref = self._ref(q, k, v, mask, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_indivisible_cache_raises(self):
        from bcg_tpu.ops.ring_attention import sp_decode_attention

        mesh = build_mesh(dp=1, tp=1, sp=8)
        with pytest.raises(ValueError, match="divisible"):
            sp_decode_attention(
                jnp.zeros((1, 2, 8)), jnp.zeros((1, 12, 2, 8)),
                jnp.zeros((1, 12, 2, 8)), jnp.ones((1, 12), bool), mesh,
            )

    @pytest.mark.parametrize("dims", [(1, 1, 4), (2, 2, 2)])
    def test_int8_cache_local_dequant_matches(self, dims):
        """The int8 storage layout [B, Hkv, S, Dh]: each shard
        dequantizes only its local slice; result must equal full-cache
        attention over the fully-dequantized cache.  Parametrized over a
        composed dp x tp x sp mesh so the quantized kv/scales shard
        specs execute with dp/tp actually bound."""
        from bcg_tpu.models.transformer import _xla_attention
        from bcg_tpu.ops.decode_attention import dequantize_kv, quantize_kv
        from bcg_tpu.ops.ring_attention import sp_decode_attention

        dp, tp, sp = dims
        mesh = build_mesh(dp=dp, tp=tp, sp=sp)
        B, S, H, Hkv, Dh = 2, 32, 4, 2, 16
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(11), 3)
        q = jax.random.normal(kq, (B, H, Dh), jnp.float32)
        k_full = jax.random.normal(kk, (B, S, Hkv, Dh), jnp.float32)
        v_full = jax.random.normal(kv, (B, S, Hkv, Dh), jnp.float32)
        # Engine storage layout: [B, Hkv, S, Dh] + scales [B, Hkv, S].
        kq8, ks = quantize_kv(k_full.transpose(0, 2, 1, 3))
        vq8, vs = quantize_kv(v_full.transpose(0, 2, 1, 3))
        mask = jnp.arange(S)[None, :] < jnp.array([32, 11])[:, None]
        scale = 1.0 / np.sqrt(Dh)

        out = sp_decode_attention(q, kq8, vq8, mask, mesh, scale=scale,
                                  k_scale=ks, v_scale=vs)
        k_deq = dequantize_kv(kq8, ks).transpose(0, 2, 1, 3)
        v_deq = dequantize_kv(vq8, vs).transpose(0, 2, 1, 3)
        ref = _xla_attention(q[:, None], k_deq, v_deq,
                             mask[:, None, :], scale)[:, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("sp", [2, 4])
    def test_chunk_queries_match_full_attention(self, sp):
        """K>1 chunks (the fast-forward loop's shape): per-query masks
        over the sharded cache, incl. intra-chunk causal structure."""
        from bcg_tpu.models.transformer import _xla_attention
        from bcg_tpu.ops.ring_attention import sp_chunk_decode_attention

        mesh = build_mesh(dp=1, tp=1, sp=sp)
        B, K, S, H, Hkv, Dh = 2, 4, 32, 4, 2, 16
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(9), 3)
        q = jax.random.normal(kq, (B, K, H, Dh), jnp.float32)
        k = jax.random.normal(kk, (B, S, Hkv, Dh), jnp.float32)
        v = jax.random.normal(kv, (B, S, Hkv, Dh), jnp.float32)
        # Each chunk query attends a row-specific prefix plus its own
        # causally-visible chunk slots (as decode_chunk builds it).
        prior = [10, 3]
        mask_np = np.zeros((B, K, S), bool)
        for b in range(B):
            for j in range(K):
                mask_np[b, j, :prior[b] + j + 1] = True
        mask = jnp.asarray(mask_np)
        out = sp_chunk_decode_attention(q, k, v, mask, mesh)
        ref = _xla_attention(q, k, v, mask, 1.0 / np.sqrt(Dh))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestSequenceParallelPrefill:
    """prefill_sp (ring attention over the sp mesh axis) must reproduce
    the single-device prefill exactly: same last-position logits, same
    KV cache — for list-form layers and for the stacked lax.scan form."""

    @pytest.mark.parametrize("stacked", [False, True])
    def test_matches_plain_prefill(self, stacked):
        from bcg_tpu.models.transformer import (
            init_kv_cache, prefill, prefill_sp, stack_layer_params,
        )

        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        if stacked:
            params = stack_layer_params(params)
        mesh = build_mesh(dp=1, tp=1, sp=4)
        B, L, S = 3, 64, 96
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L), 0,
                                    spec.vocab_size)
        pad = jnp.array([0, 7, 33])
        valid = jnp.arange(L)[None, :] >= pad[:, None]
        tokens = jnp.where(valid, tokens, 0)

        ref_logits, ref_cache = prefill(
            params, spec, tokens, valid,
            init_kv_cache(spec, B, S, stacked=stacked),
        )
        sp_logits, sp_cache = prefill_sp(
            params, spec, tokens, valid,
            init_kv_cache(spec, B, S, stacked=stacked),
            mesh,
        )
        # bf16 activations accumulate ~0.05 abs noise through the layers
        # when the reduction order changes; greedy choice must not move.
        np.testing.assert_allclose(
            np.asarray(sp_logits, np.float32),
            np.asarray(ref_logits, np.float32),
            rtol=5e-2, atol=6e-2,
        )
        assert (np.argmax(np.asarray(sp_logits), -1)
                == np.argmax(np.asarray(ref_logits), -1)).all()
        # Compare cache only at valid token slots: pad positions hold
        # whatever the masked attention produced there (never attended
        # later — suffix calls mask prefix slots by validity).
        vmask = np.zeros((B, S), bool)
        vmask[:, :L] = np.asarray(valid)
        for a, b in zip(jax.tree.leaves(sp_cache), jax.tree.leaves(ref_cache)):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            if a.ndim == 4 and a.shape[:2] == (B, S):  # [B, S, Hkv, Dh]
                a, b = a[vmask], b[vmask]
            elif a.ndim == 5:  # stacked [Lyr, B, S, Hkv, Dh]
                a, b = a[:, vmask], b[:, vmask]
            np.testing.assert_allclose(a, b, rtol=5e-2, atol=6e-2)

    @pytest.mark.slow
    def test_chunked_ring_matches_one_pass_ring(self):
        """prefill_chunk_at's ring branch (chunk attends the WHOLE
        sp-sharded cache) must reproduce one-pass prefill_sp: same final
        logits, same cache at written slots — chunk boundaries invisible
        under sp."""
        from bcg_tpu.models.transformer import (
            init_kv_cache, prefill_chunk_at, prefill_sp,
        )

        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        mesh = build_mesh(dp=1, tp=1, sp=4)
        B, L, C, S = 2, 64, 32, 96
        tokens = jax.random.randint(jax.random.PRNGKey(2), (B, L), 0,
                                    spec.vocab_size)
        valid = jnp.ones((B, L), bool)

        ref_logits, ref_cache = prefill_sp(
            params, spec, tokens, valid, init_kv_cache(spec, B, S), mesh,
        )

        cache = init_kv_cache(spec, B, S)
        H = L - C  # fixed history window, as the engine drives it
        ring = (mesh, "sp")
        for start in (0, C):
            hist = jnp.zeros((B, H), bool).at[:, :start].set(True)
            logits, cache = prefill_chunk_at(
                params, spec, tokens[:, start:start + C],
                valid[:, start:start + C], cache, hist,
                jnp.full((B,), start, jnp.int32), jnp.int32(start),
                ring=ring,
            )
        np.testing.assert_allclose(
            np.asarray(logits, np.float32),
            np.asarray(ref_logits, np.float32), rtol=5e-2, atol=6e-2,
        )
        assert (np.argmax(np.asarray(logits), -1)
                == np.argmax(np.asarray(ref_logits), -1)).all()
        for a, b in zip(jax.tree.leaves(cache), jax.tree.leaves(ref_cache)):
            a = np.asarray(a, np.float32)[:, :L]
            b = np.asarray(b, np.float32)[:, :L]
            np.testing.assert_allclose(a, b, rtol=5e-2, atol=6e-2)

    def test_suffix_via_ring_chunk_matches_prefill_with_prefix(self):
        """The cached-prefix suffix path under sp: the suffix served as
        ONE ring chunk (prefill_chunk_at, whole-sharded-cache mask) must
        match prefill_with_prefix — same final logits and suffix cache —
        including rows with DIFFERENT cached-prefix lengths."""
        from bcg_tpu.models.transformer import (
            init_kv_cache, prefill, prefill_chunk_at, prefill_with_prefix,
        )

        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        mesh = build_mesh(dp=1, tp=1, sp=4)
        B, P, Ls, S = 2, 32, 32, 96
        key = jax.random.PRNGKey(4)
        kp, ks = jax.random.split(key)
        # Per-row prefix lengths 32 and 20 (row 1 left-padded).
        plens = jnp.array([32, 20])
        prefix_valid = jnp.arange(P)[None, :] >= (P - plens)[:, None]
        ptoks = jnp.where(
            prefix_valid,
            jax.random.randint(kp, (B, P), 0, spec.vocab_size), 0,
        )
        suffix = jax.random.randint(ks, (B, Ls), 0, spec.vocab_size)
        sv = jnp.ones((B, Ls), bool)

        def with_prefix_cache(f):
            cache = init_kv_cache(spec, B, S)
            _, cache = prefill(params, spec, ptoks, prefix_valid, cache)
            return f(cache)

        ref_logits, ref_cache = with_prefix_cache(lambda c: prefill_with_prefix(
            params, spec, suffix, sv, c, prefix_valid, plens,
        ))
        sp_logits, sp_cache = with_prefix_cache(lambda c: prefill_chunk_at(
            params, spec, suffix, sv, c, prefix_valid,
            plens.astype(jnp.int32), jnp.int32(P), ring=(mesh, "sp"),
        ))
        np.testing.assert_allclose(
            np.asarray(sp_logits, np.float32),
            np.asarray(ref_logits, np.float32), rtol=5e-2, atol=6e-2,
        )
        assert (np.argmax(np.asarray(sp_logits), -1)
                == np.argmax(np.asarray(ref_logits), -1)).all()
        for a, b in zip(jax.tree.leaves(sp_cache), jax.tree.leaves(ref_cache)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32)[:, P:P + Ls],
                np.asarray(b, np.float32)[:, P:P + Ls],
                rtol=5e-2, atol=6e-2,
            )

    def test_indivisible_length_raises(self):
        from bcg_tpu.models.transformer import init_kv_cache, prefill_sp

        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        mesh = build_mesh(dp=1, tp=1, sp=4)
        tokens = jnp.zeros((1, 30), jnp.int32)
        with pytest.raises(ValueError, match="divisible"):
            prefill_sp(params, spec, tokens, jnp.ones((1, 30), bool),
                       init_kv_cache(spec, 1, 32), mesh)


class TestSPMDGameStep:
    def setup_method(self):
        self.mesh = build_mesh(dp=8, tp=1, sp=1)

    def test_exchange_matches_topology(self):
        topo = NetworkTopology.ring(8)
        mask = jnp.asarray(topo.neighbor_mask())
        values = jnp.asarray([10, 11, 12, 13, 14, -1, 16, 17], jnp.int32)
        received = np.asarray(exchange_values(values, mask, self.mesh))
        # agent 0 hears only ring neighbours 1 and 7
        assert received[0, 1] == 11 and received[0, 7] == 17
        assert received[0, 2] == -1  # non-neighbour
        assert received[4, 5] == -1  # agent 5 abstained
        assert received[3, 3] == -1  # no self-delivery

    @pytest.mark.parametrize("topo_name", ["ring", "grid", "full"])
    def test_masked_exchange_matches_spmd_body_n64(self, topo_name):
        """ISSUE-16 satellite: the mega-round's dense masked-matmul
        exchange (masked_exchange) must be value-identical to the
        shard_map collective form (exchange_values) at the 64-agent
        one-agent-per-chip scale, for every stock topology — same mask
        matrix into both, per-cell received values AND the per-receiver
        ``deliveries`` counts the orchestrator's delivery events read."""
        from bcg_tpu.parallel.game_step import masked_exchange

        n = 64
        topo = {
            "ring": lambda: NetworkTopology.ring(n),
            "grid": lambda: NetworkTopology.grid(8, 8),
            "full": lambda: NetworkTopology.fully_connected(n),
        }[topo_name]()
        mask = topo.receiver_mask()
        rng = np.random.default_rng(16)
        values_np = rng.integers(0, 50, size=n).astype(np.int32)
        values_np[rng.choice(n, size=7, replace=False)] = -1  # abstainers
        spmd = np.asarray(exchange_values(
            jnp.asarray(values_np), jnp.asarray(mask), self.mesh
        ))
        received, deliveries = masked_exchange(
            jnp.asarray(values_np), jnp.asarray(mask)
        )
        np.testing.assert_array_equal(np.asarray(received), spmd)
        # deliveries[i] == number of proposals receiver i actually got
        # in the collective form (delivered cells are exactly the >= 0
        # cells: abstainers and non-neighbours read -1).
        np.testing.assert_array_equal(
            np.asarray(deliveries), (spmd >= 0).sum(axis=1)
        )

    @pytest.mark.parametrize("topo_name", ["ring", "grid", "full"])
    def test_matrix_exchange_matches_spmd_form_n64(self, topo_name):
        """ISSUE-18 satellite: the equivocation-capable proposal-MATRIX
        exchange must agree between its dense mega-round form
        (masked_exchange_matrix) and its shard_map collective form
        (exchange_proposals) at the 64-agent scale — same equivocated
        matrix into both, per-cell received values identical; and with
        nobody equivocating both reduce to the scalar-broadcast
        exchange (the identity that keeps non-adversary rounds
        byte-stable on the fused path)."""
        from bcg_tpu.parallel.game_step import (
            equivocate_proposals,
            exchange_proposals,
            masked_exchange_matrix,
        )

        n, lo, hi = 64, 0, 50
        topo = {
            "ring": lambda: NetworkTopology.ring(n),
            "grid": lambda: NetworkTopology.grid(8, 8),
            "full": lambda: NetworkTopology.fully_connected(n),
        }[topo_name]()
        mask = jnp.asarray(topo.receiver_mask())
        rng = np.random.default_rng(18)
        values_np = rng.integers(lo, hi + 1, size=n).astype(np.int32)
        values_np[rng.choice(n, size=7, replace=False)] = -1  # abstainers
        equiv_np = np.zeros(n, dtype=bool)
        equiv_np[rng.choice(n, size=9, replace=False)] = True
        matrix = equivocate_proposals(
            jnp.asarray(values_np), jnp.asarray(equiv_np), lo, hi
        )
        dense, _ = masked_exchange_matrix(matrix, mask)
        spmd = np.asarray(exchange_proposals(matrix, mask, self.mesh))
        np.testing.assert_array_equal(np.asarray(dense), spmd)
        # An equivocating non-abstaining sender delivers receiver-
        # dependent values to its delivered cells; receiver 0's cell
        # (when delivered) carries the base value.
        mask_np = np.asarray(mask)
        for j in np.flatnonzero(equiv_np & (values_np >= 0)):
            delivered = spmd[mask_np[:, j], j]
            if delivered.size > 1:
                assert len(set(delivered.tolist())) > 1, j
            if mask_np[0, j]:
                assert spmd[0, j] == values_np[j]
        # Nobody equivocating: matrix paths reduce to the scalar form.
        plain = equivocate_proposals(
            jnp.asarray(values_np), jnp.zeros(n, dtype=bool), lo, hi
        )
        scalar = np.asarray(exchange_values(
            jnp.asarray(values_np), mask, self.mesh
        ))
        np.testing.assert_array_equal(
            np.asarray(exchange_proposals(plain, mask, self.mesh)), scalar
        )

    def test_exchange_values_global_matches_sharded_form(self):
        """The sweep tier's cooperative (dp-across-hosts) exchange
        (exchange_values_global: host inputs -> global placement ->
        masked gather -> replicated output) must be value-identical to
        the sharded single-host form on the same mesh — the hermetic
        pin for the arm a multi-process backend runs across DCN."""
        from bcg_tpu.parallel.game_step import exchange_values_global

        topo = NetworkTopology.ring(8)
        mask_np = np.asarray(topo.neighbor_mask())
        values_np = np.asarray([10, 11, 12, 13, 14, -1, 16, 17], np.int32)
        sharded = np.asarray(exchange_values(
            jnp.asarray(values_np), jnp.asarray(mask_np), self.mesh
        ))
        replicated = exchange_values_global(values_np, mask_np, self.mesh)
        np.testing.assert_array_equal(sharded, replicated)

    def test_tally_matches_host_game(self):
        game = ByzantineConsensusGame(num_honest=8, num_byzantine=0, seed=0)
        votes_py = {f"agent_{i}": (True if i < 6 else (None if i == 6 else False))
                    for i in range(8)}
        info = game.get_all_termination_votes(votes_py)
        votes = jnp.asarray([1] * 6 + [-1, 0], jnp.int32)
        tally = tally_votes(votes, self.mesh)
        assert int(tally["stop"]) == info["total_stop_votes"]
        assert int(tally["abstain"]) == info["total_abstentions"]
        assert bool(tally["terminate"]) == game.should_terminate_by_vote(votes_py)

    def test_termination_threshold_edge(self):
        # 5/8 < 2/3, 6/8 >= 2/3 — must match reference arithmetic.
        for stops, expect in ((5, False), (6, True)):
            votes = jnp.asarray([1] * stops + [0] * (8 - stops), jnp.int32)
            assert bool(tally_votes(votes, self.mesh)["terminate"]) is expect

    def test_consensus_check_matches_host_game(self):
        game = ByzantineConsensusGame(num_honest=6, num_byzantine=2, seed=5)
        ids = sorted(game.agents)
        target = next(
            st.initial_value for st in game.agents.values() if not st.is_byzantine
        )
        for aid in ids:
            game.update_agent_proposal(aid, target)
        game.apply_proposals()
        expect_ok, expect_pct = game.check_consensus()

        values = jnp.asarray(
            [game.agents[a].current_value for a in ids], jnp.int32
        )
        byz = jnp.asarray([game.agents[a].is_byzantine for a in ids])
        inits = jnp.asarray(
            [game.agents[a].initial_value if game.agents[a].initial_value is not None
             else -1 for a in ids], jnp.int32,
        )
        out = check_consensus_spmd(values, byz, inits, self.mesh)
        assert bool(out["has_consensus"]) == expect_ok
        assert abs(float(out["agreement_pct"]) - expect_pct) < 1e-5

    def test_agreement_pct_uses_modal_value(self):
        # Host: Counter([1,2,2,...]).most_common -> agreement = mode share.
        game = ByzantineConsensusGame(num_honest=8, num_byzantine=0, seed=2)
        ids = sorted(game.agents)
        vals = [1, 2, 2, 2, 3, 3, 2, 1]
        for aid, v in zip(ids, vals):
            game.update_agent_proposal(aid, v)
        game.apply_proposals()
        _, expect_pct = game.check_consensus()

        values = jnp.asarray(vals, jnp.int32)
        byz = jnp.zeros(8, bool)
        inits = jnp.asarray(
            [game.agents[a].initial_value for a in ids], jnp.int32
        )
        out = check_consensus_spmd(values, byz, inits, self.mesh)
        assert abs(float(out["agreement_pct"]) - expect_pct) < 1e-4
        assert int(out["consensus_value"]) == 2  # modal value

    def test_consensus_rejects_non_initial_value(self):
        byz = jnp.zeros(8, bool)
        inits = jnp.asarray([1, 2, 3, 4, 5, 6, 7, 8], jnp.int32)
        values = jnp.full((8,), 25, jnp.int32)  # unanimous but not initial
        out = check_consensus_spmd(values, byz, inits, self.mesh)
        assert not bool(out["has_consensus"])

    def test_full_round_arrays_jit(self):
        topo = NetworkTopology.fully_connected(8)
        mask = jnp.asarray(topo.neighbor_mask())
        proposals = jnp.full((8,), 7, jnp.int32)
        votes = jnp.ones((8,), jnp.int32)
        byz = jnp.zeros(8, bool)
        inits = jnp.asarray([7, 3, 9, 7, 5, 2, 8, 4], jnp.int32)
        received, tally, consensus = spmd_round_arrays(
            proposals, votes, mask, byz, inits, self.mesh
        )
        assert received.shape == (8, 8)
        assert bool(tally["terminate"])
        assert bool(consensus["has_consensus"])  # 7 is agent_0's initial


class TestSPMDExchangeIntegration:
    """The orchestrator's SPMD broadcast/receive path must be
    indistinguishable from the host A2A protocol at the game level."""

    def _run(self, spmd: bool, topology: str = "fully_connected"):
        import dataclasses

        from bcg_tpu.config import BCGConfig
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        base = BCGConfig()
        cfg = dataclasses.replace(
            base,
            game=dataclasses.replace(
                base.game, num_honest=6, num_byzantine=2, max_rounds=6, seed=3
            ),
            network=dataclasses.replace(
                base.network, topology_type=topology, spmd_exchange=spmd
            ),
            engine=dataclasses.replace(base.engine, backend="fake"),
            metrics=dataclasses.replace(base.metrics, save_results=False),
        )
        sim = BCGSimulation(config=cfg)
        try:
            while not sim.game.game_over:
                sim.run_round()
            stats = sim.game.get_statistics()
            msgs = (sim.network.protocol.get_total_message_count()
                    + sim._spmd_message_count)
            return stats, msgs
        finally:
            sim.close()

    def test_identical_game_stats_fully_connected(self):
        host_stats, host_msgs = self._run(spmd=False)
        spmd_stats, spmd_msgs = self._run(spmd=True)
        assert spmd_stats == host_stats
        assert spmd_msgs == host_msgs

    def test_identical_game_stats_ring(self):
        host_stats, host_msgs = self._run(spmd=False, topology="ring")
        spmd_stats, spmd_msgs = self._run(spmd=True, topology="ring")
        assert spmd_stats == host_stats
        assert spmd_msgs == host_msgs

    def test_identical_game_stats_asymmetric_custom(self):
        # Directed adjacency: delivery must follow the SENDER's out-edges
        # (host protocol semantics), not the receiver's rows.
        import dataclasses

        from bcg_tpu.config import BCGConfig
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        adj = {0: [1, 2], 1: [2], 2: [0], 3: [0, 1, 2]}
        results = []
        for spmd in (False, True):
            base = BCGConfig()
            cfg = dataclasses.replace(
                base,
                game=dataclasses.replace(
                    base.game, num_honest=3, num_byzantine=1, max_rounds=5, seed=9
                ),
                network=dataclasses.replace(
                    base.network, topology_type="custom",
                    custom_adjacency=adj, spmd_exchange=spmd,
                ),
                engine=dataclasses.replace(base.engine, backend="fake"),
                metrics=dataclasses.replace(base.metrics, save_results=False),
            )
            sim = BCGSimulation(config=cfg)
            try:
                while not sim.game.game_over:
                    sim.run_round()
                results.append((
                    sim.game.get_statistics(),
                    sim.network.protocol.get_total_message_count()
                    + sim._spmd_message_count,
                    {aid: a.received_proposals for aid, a in sim.agents.items()},
                ))
            finally:
                sim.close()
        assert results[0][0] == results[1][0]
        assert results[0][1] == results[1][1]
        assert results[0][2] == results[1][2]
