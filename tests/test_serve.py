"""Continuous-batching serving subsystem (bcg_tpu/serve).

Layers:

1. scheduler unit tests on stub/fake engines — merge/scatter routing,
   signature grouping, per-row settings, linger-deadline dispatch on
   partial buckets, per-request deadlines, backpressure;
2. admission control at synthetic KV budgets — strict rejection and
   KV-cap-bounded merging via a ``cap_for``-exposing engine;
3. crash isolation — 8 concurrent FakeEngine games with one crashing
   mid-round: the other 7 complete, the scheduler thread exits cleanly,
   no futures leak; plus engine/fault.py per-call corruption stress;
4. integration — BCG_TPU_SERVE routing in experiments/api, periodic
   checkpointing (BCG_TPU_SERVE_CHECKPOINT_EVERY) + resume;
5. a slow-marked straggler micro-benchmark: one game delayed 10x per
   call must NOT set the pace of the whole workload (serving beats the
   collective barrier on wall-clock).
"""

import threading
import time

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.engine.fault import FaultInjectingEngine
from bcg_tpu.engine.interface import InferenceEngine
from bcg_tpu.serve import (
    AdmissionRejected,
    RequestCancelled,
    Scheduler,
    SchedulerClosed,
    ServingEngine,
    derive_row_cap,
    run_serving_simulations,
)

VOTE = {"type": "object",
        "properties": {"decision": {"enum": ["stop", "continue"]}}}
DECIDE = {"type": "object", "properties": {"value": {"type": "integer"}}}


class StubEngine(InferenceEngine):
    """Pure-function engine (result depends only on the prompt row) with
    call/row accounting, so merging and scatter are observable."""

    def __init__(self, call_delay: float = 0.0):
        self.calls = []          # rows per inner call
        self.settings = []       # (temps, budgets) lists per inner call
        self.call_delay = call_delay
        self.lock = threading.Lock()

    def _row(self, system_prompt, user_prompt, schema):
        h = abs(hash((system_prompt, user_prompt))) % 50
        if "enum" in str(schema):
            return {"decision": "stop" if h % 3 == 0 else "continue"}
        return {"internal_strategy": f"s{h}", "value": h,
                "public_reasoning": f"reason {h} for consensus"}

    def batch_generate_json(self, prompts, temperature=0.8, max_tokens=512):
        n = len(prompts)
        temps = list(temperature) if isinstance(temperature, (list, tuple)) \
            else [temperature] * n
        budgets = list(max_tokens) if isinstance(max_tokens, (list, tuple)) \
            else [max_tokens] * n
        if self.call_delay:
            time.sleep(self.call_delay)
        with self.lock:
            self.calls.append(n)
            self.settings.append((temps, budgets))
        return [self._row(*p) for p in prompts]

    def generate_json(self, prompt, schema, temperature=0.0, max_tokens=512,
                      system_prompt=None):
        return self.batch_generate_json([(system_prompt or "", prompt, schema)],
                                        temperature, max_tokens)[0]

    def generate(self, prompt, temperature=0.0, max_tokens=256, top_p=1.0,
                 system_prompt=None):
        return f"text:{top_p}"

    def batch_generate(self, prompts, temperature=0.0, max_tokens=256, top_p=1.0):
        with self.lock:
            self.calls.append(len(prompts))
        return [f"text:{top_p}"] * len(prompts)

    def shutdown(self):
        pass


class CappedStubEngine(StubEngine):
    """Synthetic KV budget: the `cap_for`/`max_model_len` surface the
    scheduler derives its admission cap from (engine/jax_engine.py)."""

    def __init__(self, cap: int, **kw):
        super().__init__(**kw)
        self.cap = cap
        self.max_model_len = 2048

    def cap_for(self, S: int):
        return self.cap


# ---------------------------------------------------------------- unit tests


class TestMergeAndScatter:
    def test_rows_route_back_to_callers(self):
        inner = StubEngine()
        serve = ServingEngine(inner, linger_ms=5)
        results = {}

        def worker(name):
            prompts = [(f"sys-{name}", f"user-{name}-{i}", DECIDE) for i in range(4)]
            results[name] = serve.batch_generate_json(prompts, 0.5, 300)

        threads = [threading.Thread(target=worker, args=(n,)) for n in "abc"]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        serve.shutdown()

        # Scatter must route every row back unchanged regardless of how
        # the arrival-driven batches formed.
        for name in "abc":
            expect = inner.batch_generate_json(
                [(f"sys-{name}", f"user-{name}-{i}", DECIDE) for i in range(4)])
            assert results[name] == expect

    def test_coinciding_calls_merge(self):
        """Requests arriving within the linger window form ONE device
        batch (the continuous-batching analog of the collective merge)."""
        inner = StubEngine()
        serve = ServingEngine(inner, linger_ms=200)
        out = {}
        barrier = threading.Barrier(3)

        def worker(name):
            barrier.wait()
            out[name] = serve.batch_generate_json(
                [(f"s-{name}", f"u-{name}", DECIDE)], 0.5, 300)

        threads = [threading.Thread(target=worker, args=(n,)) for n in "abc"]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        serve.shutdown()
        assert inner.calls == [3]
        assert serve.scheduler.stats.merged_dispatches == 1

    def test_mixed_phases_merge_with_per_row_settings(self):
        """A decide call (temp 0.5, 300 tok) and a vote call (0.3, 200)
        share the ("json",) signature; settings ride per-row."""
        inner = StubEngine()
        serve = ServingEngine(inner, linger_ms=200)
        out = {}
        barrier = threading.Barrier(2)

        def decider():
            barrier.wait()
            out["d"] = serve.batch_generate_json([("s", "u", DECIDE)], 0.5, 300)

        def voter():
            barrier.wait()
            out["v"] = serve.batch_generate_json([("s", "u2", VOTE)], 0.3, 200)

        ts = [threading.Thread(target=decider), threading.Thread(target=voter)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        serve.shutdown()
        assert inner.calls == [2]
        assert inner.settings in (
            [([0.5, 0.3], [300, 200])], [([0.3, 0.5], [200, 300])]
        )
        assert "value" in out["d"][0]
        assert out["v"][0]["decision"] in ("stop", "continue")

    def test_free_text_groups_by_top_p(self):
        """Different top_p = different signature: never merged."""
        inner = StubEngine()
        serve = ServingEngine(inner, linger_ms=100)
        out = {}
        barrier = threading.Barrier(2)

        def caller(name, top_p):
            barrier.wait()
            out[name] = serve.batch_generate([f"p-{name}"], 0.0, 64, top_p)

        ts = [threading.Thread(target=caller, args=("a", 1.0)),
              threading.Thread(target=caller, args=("b", 0.9))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        serve.shutdown()
        assert sorted(inner.calls) == [1, 1]
        assert out["a"] == ["text:1.0"] and out["b"] == ["text:0.9"]

    def test_engine_error_reaches_only_that_batch(self):
        class Boom(StubEngine):
            def batch_generate_json(self, prompts, temperature=0.8, max_tokens=512):
                raise RuntimeError("device on fire")

        serve = ServingEngine(Boom(), linger_ms=1)
        with pytest.raises(RuntimeError, match="device on fire"):
            serve.batch_generate_json([("s", "u", DECIDE)], 0.5, 300)
        # The scheduler survives the engine error: next call still works
        # through the free-text path (crash-isolated completion).
        assert serve.batch_generate(["p"]) == ["text:1.0"]
        serve.shutdown()
        snap = serve.scheduler.snapshot()
        assert snap["engine_errors"] == 1
        assert snap["failed"] == 1 and snap["completed"] == 1

    def test_conformance_matches_inner_engine(self):
        """Full InferenceEngine surface through the proxy == direct
        FakeEngine output (deterministic policies)."""
        direct = FakeEngine(seed=0)
        serve = ServingEngine(FakeEngine(seed=0), linger_ms=0)
        schema = {"type": "object", "properties": {
            "value": {"type": "integer", "minimum": 0, "maximum": 50}}}
        prompt = "agent_1 value: 9; agent_2 value: 9\nYour current value: 3"
        assert serve.generate_json(prompt, schema) == \
            direct.generate_json(prompt, schema)
        batch = [("sys", prompt, schema), ("sys", "Your current value: 5", schema)]
        assert serve.batch_generate_json(batch) == direct.batch_generate_json(batch)
        assert serve.batch_generate(["a", "bb"]) == direct.batch_generate(["a", "bb"])
        assert serve.generate("abc") == direct.generate("abc")
        assert serve.generate("abc", system_prompt="s") == \
            direct.generate("abc", system_prompt="s")
        serve.shutdown()

    def test_shutdown_idempotent_and_closed_rejects(self):
        serve = ServingEngine(StubEngine(), linger_ms=1)
        serve.shutdown()
        serve.shutdown()
        with pytest.raises(SchedulerClosed):
            serve.batch_generate_json([("s", "u", DECIDE)])


class TestLingerDispatch:
    def test_partial_bucket_dispatches_at_linger_deadline(self):
        """A 3-row request against a 64-row bucket must NOT wait for the
        bucket to fill — the linger deadline dispatches it."""
        inner = StubEngine()
        serve = ServingEngine(inner, linger_ms=30, bucket_rows=64,
                              strict_admission=False)
        t0 = time.monotonic()
        out = serve.batch_generate_json(
            [("s", f"u{i}", DECIDE) for i in range(3)], 0.5, 300)
        elapsed = time.monotonic() - t0
        serve.shutdown()
        assert len(out) == 3
        assert inner.calls == [3]          # dispatched without a full bucket
        assert elapsed >= 0.02             # ... but only after the linger
        assert elapsed < 2.0
        hist = serve.scheduler.snapshot()["linger_hist_ms"]
        assert sum(hist.values()) == 1

    def test_zero_linger_dispatches_immediately(self):
        inner = StubEngine()
        serve = ServingEngine(inner, linger_ms=0)
        t0 = time.monotonic()
        serve.batch_generate_json([("s", "u", DECIDE)])
        assert time.monotonic() - t0 < 1.0
        serve.shutdown()

    def test_full_bucket_dispatches_before_linger(self):
        """When queued rows reach the bucket, dispatch fires immediately
        even with a long linger."""
        inner = StubEngine()
        serve = ServingEngine(inner, linger_ms=5000, bucket_rows=4,
                              strict_admission=False)
        outs = {}

        def worker(i):
            outs[i] = serve.batch_generate_json(
                [("s", f"u{i}-{j}", DECIDE) for j in range(2)], 0.5, 300)

        t0 = time.monotonic()
        ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        elapsed = time.monotonic() - t0
        serve.shutdown()
        assert elapsed < 2.0, "bucket-fill must not wait out the linger"
        assert len(outs) == 2


class TestSLO:
    def test_violations_counted_against_slow_dispatch(self):
        """A scripted 50 ms device dispatch against a 10 ms objective:
        every completed request is a violation — counted in the
        snapshot, in the process-wide serve.slo.violations counter, and
        observed (negative headroom) in the headroom histogram."""
        from bcg_tpu.obs import counters as obs_counters

        before = obs_counters.value("serve.slo.violations")
        sched = Scheduler(StubEngine(call_delay=0.05), linger_ms=1,
                          slo_ms=10)
        for i in range(2):
            out = sched.submit_and_wait(
                ("json",), [("s", f"u{i}", DECIDE)], [0.0], [16])
            assert len(out) == 1
        snap = sched.snapshot()
        sched.close()
        assert snap["slo"]["slo_ms"] == 10
        assert snap["slo"]["violations"] == 2
        assert obs_counters.value("serve.slo.violations") - before == 2
        headroom = snap["slo"]["headroom_ms"]
        assert headroom["count"] == 2
        # Negative headroom floors into the le=0 bucket: quantiles of
        # an all-violating run read exactly 0, never a spurious
        # positive value; the true signed magnitude survives in sum_ms.
        assert headroom["p50_ms"] == 0.0
        assert headroom["p99_ms"] == 0.0
        assert headroom["sum_ms"] < 0
        e2e = snap["hist_ms"]["e2e"]
        assert e2e["count"] == 2
        assert e2e["p50_ms"] >= 10.0       # the 50 ms dispatch dominates
        assert snap["hist_ms"]["device"]["count"] == 2

    def test_within_slo_no_violations(self):
        from bcg_tpu.obs import counters as obs_counters

        before = obs_counters.value("serve.slo.violations")
        sched = Scheduler(StubEngine(), linger_ms=1, slo_ms=60_000)
        sched.submit_and_wait(("json",), [("s", "u", DECIDE)], [0.0], [16])
        snap = sched.snapshot()
        sched.close()
        assert snap["slo"]["violations"] == 0
        assert snap["slo"]["headroom_ms"]["count"] == 1
        assert obs_counters.value("serve.slo.violations") == before

    def test_no_slo_by_default(self, monkeypatch):
        """Without BCG_TPU_SERVE_SLO_MS the snapshot's slo block is None
        and the scheduler registers no headroom histogram."""
        monkeypatch.delenv("BCG_TPU_SERVE_SLO_MS", raising=False)
        sched = Scheduler(StubEngine(), linger_ms=1)
        sched.submit_and_wait(("json",), [("s", "u", DECIDE)], [0.0], [16])
        snap = sched.snapshot()
        sched.close()
        assert snap["slo"] is None
        assert "slo_headroom" not in sched.stats._hists
        # The plain latency histograms still populate.
        assert snap["hist_ms"]["e2e"]["count"] == 1

    def test_env_flag_configures_objective(self, monkeypatch):
        monkeypatch.setenv("BCG_TPU_SERVE_SLO_MS", "25")
        sched = Scheduler(StubEngine(), linger_ms=1)
        assert sched.stats.slo_ms == 25
        sched.close()


class TestDeadlines:
    def test_queued_request_cancelled_at_deadline(self):
        """A request stuck behind a slow in-flight batch is cancelled at
        its deadline instead of waiting forever."""
        inner = StubEngine(call_delay=0.4)
        serve = ServingEngine(inner, linger_ms=0, deadline_ms=100)
        errs = []
        first = threading.Thread(
            target=lambda: serve.batch_generate_json([("s", "u0", DECIDE)]))
        first.start()
        time.sleep(0.05)  # first batch is now mid-dispatch (sleeping)

        def second():
            try:
                serve.batch_generate_json([("s", "u1", DECIDE)])
            except RequestCancelled as e:
                errs.append(e)

        t = threading.Thread(target=second)
        t.start()
        t.join(timeout=5)
        first.join(timeout=5)
        serve.shutdown()
        assert len(errs) == 1
        assert serve.scheduler.snapshot()["cancelled"] == 1

    def test_no_deadline_waits_out_slow_batches(self):
        inner = StubEngine(call_delay=0.15)
        serve = ServingEngine(inner, linger_ms=0, deadline_ms=0)
        out = serve.batch_generate_json([("s", "u", DECIDE)])
        serve.shutdown()
        assert len(out) == 1


class TestDeriveRowCap:
    """Both forms of ``worst_case_decode_window`` must be honored: the
    JaxEngine method AND a plain int attribute (stubs, foreign engines).
    The int form was once silently ignored in favor of max_model_len —
    under-sizing the admission window exactly for engines that declared
    a wider one."""

    class WindowedStub(CappedStubEngine):
        def __init__(self, window, **kw):
            super().__init__(cap=0, **kw)
            self.worst_case_decode_window = window
            self.seen = None

        def cap_for(self, S: int):
            self.seen = S
            return 7

    def test_int_valued_window_is_honored(self):
        stub = self.WindowedStub(window=3000)
        assert derive_row_cap(stub) == 7
        assert stub.seen == 3000  # NOT the 2048 max_model_len

    def test_callable_window_still_works(self):
        stub = self.WindowedStub(window=lambda: 2500)
        assert derive_row_cap(stub) == 7
        assert stub.seen == 2500

    def test_absent_window_falls_back_to_max_len(self):
        inner = CappedStubEngine(cap=4)
        seen = []
        inner.cap_for = lambda S: seen.append(S) or 4
        assert derive_row_cap(inner) == 4
        assert seen == [2048]


class TestAdmission:
    def test_oversize_request_rejected_at_synthetic_budget(self):
        """Strict admission (explicit bucket): a request that can never
        fit the device bucket is refused, not queued forever."""
        serve = ServingEngine(StubEngine(), linger_ms=1, bucket_rows=4)
        with pytest.raises(AdmissionRejected):
            serve.batch_generate_json(
                [("s", f"u{i}", DECIDE) for i in range(6)], 0.5, 300)
        snap = serve.scheduler.snapshot()
        serve.shutdown()
        assert snap["rejected"] == 1
        assert snap["row_cap"] == 4

    def test_derived_kv_cap_bounds_merging(self):
        """With a cap_for-exposing engine, merged batches never exceed
        the KV-budget cap; admitted concurrency cannot overcommit HBM."""
        inner = CappedStubEngine(cap=4)
        assert derive_row_cap(inner) == 4
        serve = ServingEngine(inner, linger_ms=100)
        assert serve.scheduler.row_cap == 4
        outs = {}
        barrier = threading.Barrier(3)

        def worker(i):
            barrier.wait()
            outs[i] = serve.batch_generate_json(
                [("s", f"u{i}-{j}", DECIDE) for j in range(2)], 0.5, 300)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        serve.shutdown()
        assert len(outs) == 3
        assert all(n <= 4 for n in inner.calls), inner.calls
        assert sum(inner.calls) == 6

    def test_derived_cap_passes_oversize_alone(self):
        """Derived (non-strict) cap: a single oversize request dispatches
        ALONE — the engine's own provisioner chunks it, exactly as the
        collective path relies on — instead of being rejected."""
        inner = CappedStubEngine(cap=4)
        serve = ServingEngine(inner, linger_ms=1)
        out = serve.batch_generate_json(
            [("s", f"u{i}", DECIDE) for i in range(6)], 0.5, 300)
        snap = serve.scheduler.snapshot()
        serve.shutdown()
        assert len(out) == 6
        assert inner.calls == [6]
        assert snap["oversize_dispatches"] == 1
        assert snap["rejected"] == 0


class TestBackpressure:
    def test_submissions_block_at_queue_watermark_then_complete(self):
        inner = StubEngine(call_delay=0.02)
        serve = ServingEngine(inner, linger_ms=0, max_queue_rows=2)
        outs = []
        lock = threading.Lock()

        def worker(i):
            r = serve.batch_generate_json([("s", f"u{i}", DECIDE)], 0.5, 300)
            with lock:
                outs.append(r)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        snap = serve.scheduler.snapshot()
        serve.shutdown()
        assert len(outs) == 8
        assert snap["completed"] == 8
        assert snap["max_queue_rows"] <= 2
        assert snap["backpressure_blocks"] >= 1

    def test_oversize_request_admits_on_empty_queue(self):
        """A lone request larger than the backpressure watermark must
        still be served once the queue drains — not block forever."""
        inner = StubEngine()
        serve = ServingEngine(inner, linger_ms=1, max_queue_rows=2)
        out = serve.batch_generate_json(
            [("s", f"u{i}", DECIDE) for i in range(5)], 0.5, 300)
        serve.shutdown()
        assert len(out) == 5
        assert inner.calls == [5]

    def test_admission_waiter_detects_dead_scheduler(self):
        """A submitter blocked on queue admission must raise, not hang,
        when the scheduler thread died without close() bookkeeping."""
        sched = Scheduler(StubEngine(), linger_ms=0, max_queue_rows=1)
        # Simulate abnormal scheduler-thread death: stop the loop via
        # the closed flag, then clear it (no close() cleanup ran) and
        # pin the queue at the watermark so admission can never succeed.
        with sched._cond:
            sched._closed = True
            sched._cond.notify_all()
        sched._thread.join(timeout=5)
        assert not sched._thread.is_alive()
        sched._closed = False
        sched._queue_rows = 1
        t0 = time.monotonic()
        with pytest.raises(SchedulerClosed, match="died"):
            sched.submit_and_wait(("json",), [("s", "u", DECIDE)], [0.5], [100])
        assert time.monotonic() - t0 < 10


# --------------------------------------------------------- crash isolation


class CrashAfter(InferenceEngine):
    """Per-game wrapper that dies on its Nth guided call — the crashing
    GAME, not the shared engine."""

    def __init__(self, engine, crash_on_call: int):
        self._engine = engine
        self._crash_on = crash_on_call
        self._calls = 0

    def batch_generate_json(self, prompts, temperature=0.8, max_tokens=512):
        self._calls += 1
        if self._calls >= self._crash_on:
            raise RuntimeError("game crashed mid-round")
        return self._engine.batch_generate_json(prompts, temperature, max_tokens)

    def generate_json(self, prompt, schema, temperature=0.0, max_tokens=512,
                      system_prompt=None):
        return self.batch_generate_json(
            [(system_prompt or "", prompt, schema)], temperature, max_tokens)[0]

    def generate(self, prompt, temperature=0.0, max_tokens=256, top_p=1.0,
                 system_prompt=None):
        return self._engine.generate(prompt, temperature, max_tokens, top_p,
                                     system_prompt=system_prompt)

    def batch_generate(self, prompts, temperature=0.0, max_tokens=256, top_p=1.0):
        return self._engine.batch_generate(prompts, temperature, max_tokens, top_p)

    def shutdown(self):
        pass


class TestCrashIsolation:
    def test_one_crashing_game_of_eight_fails_alone(self):
        """Acceptance: 8 concurrent FakeEngine games, 1 crashes mid-round
        -> the other 7 complete with correct results, the scheduler
        thread exits cleanly, no futures leak."""
        inner = FakeEngine(seed=0)
        serving = ServingEngine(inner, linger_ms=2)

        def make(i):
            def go(engine):
                # Game 3 dies on its 3rd guided call: mid-game, mid-round
                # (each round makes a decide call and a vote call).
                eng = CrashAfter(engine, 3) if i == 3 else engine
                return run_simulation(n_agents=4, byzantine_count=1,
                                      max_rounds=4, backend="fake", seed=i,
                                      engine=eng)
            return go

        outs = run_serving_simulations(
            inner, [make(i) for i in range(8)], serving=serving)
        serving.shutdown()

        assert isinstance(outs[3], RuntimeError)
        survivors = [o for i, o in enumerate(outs) if i != 3]
        assert all(isinstance(o, dict) for o in survivors)
        assert all("consensus_reached" in o["metrics"] for o in survivors)
        # Correctness of survivors: identical to the same games run
        # solo on an identical fake engine (content-deterministic).
        solo = run_simulation(n_agents=4, byzantine_count=1, max_rounds=4,
                              backend="fake", seed=5, engine=FakeEngine(seed=0))
        assert outs[5]["metrics"]["consensus_value"] == \
            solo["metrics"]["consensus_value"]

        # Clean exit, no leaked futures.
        sched = serving.scheduler
        assert not sched._thread.is_alive()
        assert sched._queue == [] and sched.queue_depth_rows() == 0
        s = sched.stats
        assert s.submitted == s.completed + s.failed + s.cancelled + s.rejected
        assert s.rejected == 0 and s.cancelled == 0

    def test_fault_injection_stress_all_games_complete(self):
        """engine/fault.py corrupts a seeded fraction of responses on the
        SHARED engine: every game's retry ladder degrades gracefully and
        all complete under arrival-driven dispatch (retries desync the
        games' call patterns — the no-barrier analog of the collective
        retry-desync stress)."""
        inner = FaultInjectingEngine(FakeEngine(seed=1), rate=0.2, seed=7)

        def make(i):
            def go(engine):
                return run_simulation(n_agents=4, byzantine_count=1,
                                      max_rounds=4, backend="fake", seed=i,
                                      engine=engine)
            return go

        outs = run_serving_simulations(
            inner, [make(i) for i in range(8)], max_concurrent=4, linger_ms=2)
        assert all(isinstance(o, dict) for o in outs), outs
        assert all("consensus_reached" in o["metrics"] for o in outs)
        assert inner.injected > 0  # faults actually fired


# ------------------------------------------------------------- integration


class TestIntegration:
    def test_experiments_route_through_serving(self, monkeypatch):
        monkeypatch.setenv("BCG_TPU_SERVE", "1")
        from bcg_tpu.experiments import PRESETS, run_preset
        from bcg_tpu.runtime import metrics

        metrics.publish_serve_stats(None)
        out = run_preset(PRESETS["q1-baseline"], runs=3, backend="fake",
                         max_rounds=4, seed=0, concurrency=3)
        assert len(out["per_run"]) == 3
        assert out["aggregate"]["consensus_rate"] is not None
        # The serving scheduler actually ran (stats mirror published).
        assert metrics.LAST_SERVE_STATS is not None
        assert metrics.LAST_SERVE_STATS["completed"] > 0

    def test_api_serve_flag_wraps_created_engine(self, monkeypatch):
        monkeypatch.setenv("BCG_TPU_SERVE", "1")
        from bcg_tpu.runtime import metrics

        metrics.publish_serve_stats(None)
        out = run_simulation(n_agents=4, byzantine_count=0, max_rounds=4,
                             backend="fake", seed=0)
        assert out["metrics"]["consensus_reached"] is not None
        assert metrics.LAST_SERVE_STATS is not None

    def test_serving_matches_collective_results(self):
        """Same games, same deterministic engine: serving and collective
        proxies must produce identical metrics."""
        from bcg_tpu.engine.collective import run_concurrent_simulations

        def make(i):
            def go(engine):
                return run_simulation(n_agents=3, byzantine_count=1,
                                      max_rounds=3 + i, backend="fake",
                                      seed=i, engine=engine)
            return go

        coll = run_concurrent_simulations(
            FakeEngine(seed=0), [make(i) for i in range(4)], 4)
        serve = run_serving_simulations(
            FakeEngine(seed=0), [make(i) for i in range(4)], linger_ms=2)
        for c, s in zip(coll, serve):
            assert c["metrics"]["consensus_value"] == s["metrics"]["consensus_value"]
            assert c["metrics"]["total_rounds"] == s["metrics"]["total_rounds"]


class TestServeCheckpointing:
    def test_periodic_checkpoint_and_resume(self, tmp_path, monkeypatch):
        """BCG_TPU_SERVE_CHECKPOINT_EVERY=2 writes a resumable snapshot
        every 2 rounds even with result sinks off; resume_simulation
        continues the game."""
        import dataclasses

        from bcg_tpu.config import (
            BCGConfig, EngineConfig, GameConfig, MetricsConfig,
        )
        from bcg_tpu.runtime.checkpoint import resume_simulation
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        monkeypatch.setenv("BCG_TPU_SERVE_CHECKPOINT_EVERY", "2")
        cfg = BCGConfig(
            game=GameConfig(num_honest=4, num_byzantine=1, max_rounds=10,
                            seed=11),
            engine=EngineConfig(backend="fake", model_name="bcg-tpu/tiny-test"),
            metrics=MetricsConfig(save_results=False,
                                  results_dir=str(tmp_path)),
        )
        engine = FakeEngine(seed=2, policy="stubborn")  # never converges
        sim = BCGSimulation(config=cfg, engine=engine)
        sim.run_round()
        ckpt_dir = tmp_path / "checkpoints"
        assert not ckpt_dir.exists()  # round 1: not yet due
        sim.run_round()
        # Round 2: periodic checkpoint fired.  With result sinks off the
        # file carries the process-unique sim uid (concurrent games must
        # not clobber one shared run_001 path).
        ckpts = list(ckpt_dir.glob(f"run_{sim.run_number}_g*.json"))
        assert len(ckpts) == 1
        ckpt = ckpts[0]
        saved_round = sim.game.current_round
        sim.run_round()
        sim.close()

        monkeypatch.delenv("BCG_TPU_SERVE_CHECKPOINT_EVERY")
        sim2 = resume_simulation(
            str(ckpt), config=cfg, engine=FakeEngine(seed=2, policy="stubborn")
        )
        # Round 3 ran AFTER the checkpoint: the resume restarts from the
        # round-2 snapshot, not the crash point.
        assert sim2.game.current_round == saved_round
        sim2.run_round()
        assert sim2.game.current_round == saved_round + 1
        sim2.close()

    def test_concurrent_games_write_distinct_checkpoints(self, tmp_path,
                                                         monkeypatch):
        """G concurrent games (all run '001' with sinks off) must write G
        checkpoint files, not clobber one."""
        monkeypatch.setenv("BCG_TPU_SERVE_CHECKPOINT_EVERY", "1")
        import dataclasses  # noqa: F401  (parity with sibling test imports)

        from bcg_tpu.config import (
            BCGConfig, EngineConfig, GameConfig, MetricsConfig,
        )

        def make(i):
            def go(engine):
                cfg = BCGConfig(
                    game=GameConfig(num_honest=3, num_byzantine=0,
                                    max_rounds=2, seed=i),
                    engine=EngineConfig(backend="fake",
                                        model_name="bcg-tpu/tiny-test"),
                    metrics=MetricsConfig(save_results=False,
                                          results_dir=str(tmp_path)),
                )
                from bcg_tpu.runtime.orchestrator import BCGSimulation

                sim = BCGSimulation(config=cfg, engine=engine)
                sim.run_round()
                sim.close()
                return sim.run_number
            return go

        outs = run_serving_simulations(
            FakeEngine(seed=0, policy="stubborn"),
            [make(i) for i in range(3)], linger_ms=2)
        assert all(o == "001" for o in outs)  # the collision precondition
        ckpts = list((tmp_path / "checkpoints").glob("run_001_g*.json"))
        assert len(ckpts) == 3


# ------------------------------------------------- straggler micro-benchmark


class DelayedCalls(InferenceEngine):
    """Models a game's slow HOST-side work: sleeps on the caller thread
    before each guided call, then delegates to the shared proxy."""

    def __init__(self, engine, delay: float):
        self._engine = engine
        self._delay = delay

    def batch_generate_json(self, prompts, temperature=0.8, max_tokens=512):
        time.sleep(self._delay)
        return self._engine.batch_generate_json(prompts, temperature, max_tokens)

    def generate_json(self, prompt, schema, temperature=0.0, max_tokens=512,
                      system_prompt=None):
        time.sleep(self._delay)
        return self._engine.generate_json(prompt, schema, temperature,
                                          max_tokens, system_prompt=system_prompt)

    def generate(self, prompt, temperature=0.0, max_tokens=256, top_p=1.0,
                 system_prompt=None):
        return self._engine.generate(prompt, temperature, max_tokens, top_p,
                                     system_prompt=system_prompt)

    def batch_generate(self, prompts, temperature=0.0, max_tokens=256, top_p=1.0):
        return self._engine.batch_generate(prompts, temperature, max_tokens, top_p)

    def shutdown(self):
        pass


@pytest.mark.slow
class TestStragglerBenchmark:
    def test_serving_beats_collective_on_straggler_workload(self):
        """CPU micro-benchmark (acceptance): 16 games, game 0 delayed 10x
        per call, wave size / max concurrency 4.  Collective runs
        lockstep waves — every game in the straggler's wave decides at
        straggler pace, and later waves queue behind it.  Serving admits
        arrivals continuously, so the straggler delays only itself.
        (Prototyped ratio ~1.7x; asserted at >1.1x for CI headroom.)"""
        N, R, FAST = 16, 5, 0.005
        SLOW = FAST * 10

        def make(i):
            delay = SLOW if i == 0 else FAST

            def go(engine):
                return run_simulation(
                    n_agents=4, byzantine_count=0, max_rounds=R,
                    backend="fake", seed=i,
                    engine=DelayedCalls(engine, delay),
                )
            return go

        from bcg_tpu.engine.collective import run_concurrent_simulations

        # stubborn: games never converge -> exactly R rounds each, so
        # both arms run the identical call count.
        t0 = time.monotonic()
        coll_outs = run_concurrent_simulations(
            FakeEngine(seed=0, policy="stubborn"),
            [make(i) for i in range(N)], 4)
        coll_s = time.monotonic() - t0

        t0 = time.monotonic()
        serve_outs = run_serving_simulations(
            FakeEngine(seed=0, policy="stubborn"),
            [make(i) for i in range(N)], max_concurrent=4, linger_ms=1)
        serve_s = time.monotonic() - t0

        assert all(isinstance(o, dict) for o in coll_outs)
        assert all(isinstance(o, dict) for o in serve_outs)
        assert all(o["metrics"]["total_rounds"] == R for o in serve_outs)
        assert serve_s * 1.1 < coll_s, (
            f"serving {serve_s:.3f}s should beat collective {coll_s:.3f}s "
            "on the straggler workload"
        )


# ------------------------------------------------- fair-share refund (ISSUE 15)


class TestFairShareRefund:
    def test_engine_error_refunds_served_rows_charge(self):
        """A dispatch that fails must refund the tenants' served_rows
        charge taken at selection: without the refund, a crashing
        tenant's traffic permanently deflates its own virtual time and
        its next requests OUTRANK every healthy tenant exactly because
        its dispatches keep dying."""
        class Boom(StubEngine):
            def __init__(self):
                super().__init__()
                self.fail = True

            def batch_generate_json(self, prompts, temperature=0.8,
                                    max_tokens=512):
                if self.fail:
                    raise RuntimeError("device on fire")
                return super().batch_generate_json(
                    prompts, temperature, max_tokens
                )

        eng = Boom()
        sched = Scheduler(eng, linger_ms=1)
        crashy = sched.register_tenant("crashy", weight=1.0)
        with pytest.raises(RuntimeError, match="device on fire"):
            sched.submit_and_wait(
                ("json",), [("s", f"u{i}", DECIDE) for i in range(4)],
                [0.0] * 4, [64] * 4, tenant="crashy",
            )
        # Charged 4 at selection, refunded 4 at failure.
        assert crashy.served_rows == 0
        # Control: a successful dispatch keeps its charge.
        eng.fail = False
        sched.submit_and_wait(("json",), [("s", "ok", DECIDE)], [0.0], [64],
                              tenant="crashy")
        assert crashy.served_rows == 1
        sched.close()

    def test_untenanted_failure_refunds_anonymous_account(self):
        class AlwaysBoom(StubEngine):
            def batch_generate_json(self, prompts, temperature=0.8,
                                    max_tokens=512):
                raise RuntimeError("boom")

        sched = Scheduler(AlwaysBoom(), linger_ms=1)
        sched.register_tenant("bystander")  # activates fair ordering
        with pytest.raises(RuntimeError):
            sched.submit_and_wait(("json",), [("s", "u", DECIDE)],
                                  [0.0], [64])
        assert sched._anon_tenant.served_rows == 0
        sched.close()


# --------------------------------------- tenant deferral hardening (ISSUE 15)


class _SchedulerScript:
    """Scripted Scheduler stand-in for the ServingEngine deferral loop:
    defers the first ``defer_n`` submits (or forever with -1), each
    carrying a fixed retry-after."""

    def __init__(self, defer_n, retry_after_s=0.01):
        self.calls = 0
        self.defer_n = defer_n
        self.retry_after_s = retry_after_s
        self._thread = threading.current_thread()  # alive by construction

    def submit_and_wait(self, sig, payload, temps, budgets, tenant=None):
        from bcg_tpu.serve.scheduler import AdmissionDeferred

        self.calls += 1
        if self.defer_n < 0 or self.calls <= self.defer_n:
            raise AdmissionDeferred(
                "quota full", retry_after_s=self.retry_after_s
            )
        return [{"ok": True}] * len(payload)

    def close(self):
        pass


class TestDeferralHardening:
    def test_transient_deferrals_retry_through(self):
        script = _SchedulerScript(defer_n=2)
        serve = ServingEngine(StubEngine(), scheduler=script, tenant="t",
                              defer_wait_ceiling_s=30)
        out = serve.batch_generate_json([("s", "u", DECIDE)], 0.0, 64)
        assert out == [{"ok": True}]
        assert script.calls == 3  # 2 deferrals + the success

    def test_wedged_scheduler_hits_the_ceiling(self):
        """An endlessly-deferring (wedged) scheduler must surface
        SchedulerClosed once cumulative backoff passes the ceiling —
        never spin the fixed-sleep loop forever."""
        script = _SchedulerScript(defer_n=-1, retry_after_s=0.02)
        serve = ServingEngine(StubEngine(), scheduler=script, tenant="t",
                              defer_wait_ceiling_s=0.15)
        t0 = time.monotonic()
        with pytest.raises(SchedulerClosed, match="ceiling"):
            serve.batch_generate_json([("s", "u", DECIDE)], 0.0, 64)
        wall = time.monotonic() - t0
        assert wall < 2.0  # bounded, not unbounded spin
        assert script.calls >= 2  # it DID retry before giving up

    def test_retry_delays_are_jittered(self):
        """Two proxies' backoff sequences must decorrelate (per-proxy
        seeded jitter): equal fixed sleeps re-herd every deferred
        tenant into the same later dispatch window."""
        delays = {}
        for name in ("a", "b"):
            serve = ServingEngine(StubEngine(),
                                  scheduler=_SchedulerScript(defer_n=0),
                                  tenant=name)
            seq = [serve._defer_rng.uniform(0.75, 1.25) for _ in range(4)]
            delays[name] = seq
        assert delays["a"] != delays["b"]

    def test_dead_scheduler_thread_raises_immediately(self):
        script = _SchedulerScript(defer_n=-1)
        dead = threading.Thread(target=lambda: None)
        dead.start()
        dead.join()
        script._thread = dead
        serve = ServingEngine(StubEngine(), scheduler=script, tenant="t")
        with pytest.raises(SchedulerClosed, match="died"):
            serve.batch_generate_json([("s", "u", DECIDE)], 0.0, 64)
