"""Flash / blockwise attention vs the stock XLA einsum path.

The Pallas kernel itself runs on TPU (and in interpret mode in CI);
the blockwise scan is its everywhere-fallback — both must match
``_xla_attention`` bit-for-reasonable-tolerance on random GQA shapes
with the engine's real masking pattern (left-padded prompts + causal
over a longer KV cache).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.models.transformer import _xla_attention
from bcg_tpu.ops.attention import _pad_to, blockwise_attention, flash_attention


def _random_case(key, B, T, S, H, Hkv, Dh, dtype=jnp.float32):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (B, T, H, Dh), dtype)
    k = jax.random.normal(ks[1], (B, S, Hkv, Dh), dtype)
    v = jax.random.normal(ks[2], (B, S, Hkv, Dh), dtype)
    # Engine-shaped mask: left-padded valid prompt + causal into a cache
    # that is longer than the prompt (decode slots not yet written).
    lens = jax.random.randint(ks[3], (B,), 1, T + 1)
    t_idx = jnp.arange(T)[None, :, None]
    s_idx = jnp.arange(S)[None, None, :]
    start = (T - lens)[:, None, None]
    mask = (t_idx >= start) & (s_idx >= start) & (s_idx <= t_idx)
    # Rows with no attendable key (pad rows) are meaningless: the XLA
    # reference softmaxes uniform over -1e30 there while flash returns 0.
    # Compare only rows that attend to something.
    row_valid = mask.any(axis=-1)[..., None, None]  # [B, T, 1, 1]
    return q, k, v, mask, row_valid


@pytest.mark.parametrize("shape", [
    (2, 64, 64, 4, 2, 32),      # GQA, square
    (1, 17, 40, 4, 4, 16),      # MHA, ragged sizes, cache longer than T
    (3, 128, 200, 8, 2, 64),    # cache longer than prompt
])
def test_blockwise_matches_xla(shape):
    B, T, S, H, Hkv, Dh = shape
    q, k, v, mask, rv = _random_case(jax.random.PRNGKey(0), B, T, S, H, Hkv, Dh)
    scale = 1.0 / np.sqrt(Dh)
    ref = np.asarray(_xla_attention(q, k, v, mask, scale) * rv)
    out = np.asarray(blockwise_attention(q, k, v, mask, scale, block_kv=64) * rv)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_blockwise_fully_masked_rows_are_finite():
    B, T, S, H, Hkv, Dh = 1, 8, 8, 2, 2, 16
    q, k, v, _, _ = _random_case(jax.random.PRNGKey(1), B, T, S, H, Hkv, Dh)
    mask = jnp.zeros((B, T, S), bool)  # pad rows attend to nothing
    out = blockwise_attention(q, k, v, mask, 0.25, block_kv=8)
    assert np.isfinite(np.asarray(out)).all()


def test_flash_dispatches_to_blockwise_off_tpu():
    # On CPU (the test backend) flash_attention must silently fall back
    # and still be correct.
    B, T, S, H, Hkv, Dh = 2, 32, 48, 4, 2, 32
    q, k, v, mask, rv = _random_case(jax.random.PRNGKey(2), B, T, S, H, Hkv, Dh)
    scale = 1.0 / np.sqrt(Dh)
    ref = np.asarray(_xla_attention(q, k, v, mask, scale) * rv)
    out = np.asarray(flash_attention(q, k, v, mask, scale) * rv)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_pallas_kernel_interpret_mode():
    """Run the production Pallas launch config (interpret=True) on CPU."""
    from bcg_tpu.ops.attention import _pallas_flash

    B, T, S, H, Hkv, Dh = 1, 128, 256, 2, 1, 128
    q, k, v, mask, rv = _random_case(jax.random.PRNGKey(3), B, T, S, H, Hkv, Dh)
    scale = 1.0 / np.sqrt(Dh)
    ref = _xla_attention(q, k, v, mask, scale) * rv
    out = _pallas_flash(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), mask, scale,
        block_q=128, block_kv=128, interpret=True,
    )
    out = out.transpose(0, 2, 1, 3) * rv
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_pallas_kernel_interpret_14b_chunk_dims():
    """The 14B chunked-prefill geometry (H=40, GQA group 5) through the
    production Pallas launch config in interpret mode — pins the MATH at
    the exact shape scripts/probe_flash_prefill.py lowers on hardware,
    so a probe failure isolates Mosaic lowering, not the kernel logic
    (the same split the int8 serving-shape tests make)."""
    from bcg_tpu.ops.attention import _pallas_flash

    B, T, S, H, Hkv, Dh = 2, 128, 256, 40, 8, 128
    q, k, v, mask, rv = _random_case(jax.random.PRNGKey(7), B, T, S, H, Hkv, Dh)
    scale = 1.0 / np.sqrt(Dh)
    ref = _xla_attention(q, k, v, mask, scale) * rv
    out = _pallas_flash(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), mask, scale,
        block_q=128, block_kv=128, interpret=True,
    )
    out = out.transpose(0, 2, 1, 3) * rv
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_pad_to():
    x = jnp.ones((2, 3))
    assert _pad_to(x, 1, 4).shape == (2, 4)
    assert _pad_to(x, 0, 2).shape == (2, 3)
