"""Packed-int4 KV cache (models/quantize.py int4-KV contract +
transformer/paged layouts + engine kv_dtype plumbing).

Layers:

* **Packing contract**: quantize/unpack/dequant roundtrip within the
  half-step bound, low-nibble-first halves, bf16 scales (the layout
  marker ``kv_is_int4`` keys every dispatch on).
* **Cache paths**: dense slab and block pool allocate packed shapes,
  writes quantize through the shared dispatch, reads dequantize
  identically on the slab, the paged gather, and the paged Pallas
  kernel's in-VMEM nibble unpack (interpret mode).
* **Engine**: int4 decisions stay within the established quantization
  tolerance vs bf16 (the int8 suite's idiom), paged int4 (fused
  kernel) is token-identical to dense int4, steady-state retraces stay
  zero for the int4 jit entry keys, and BCG_TPU_KV_DTYPE resolves
  bf16/int8(alias)/int4 over the config field.
* **Capacity, gated**: slot bytes are exactly half int8's, cap_for
  admission and pool auto-sizing come out >= 1.8x at the same
  synthetic HBM budget, and the perf-gate ``int4`` scenario conforms
  to perf_baseline.json with the resurface contract owned here for
  the int4.* namespace.
"""

import dataclasses
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.models import init_params, prefill, spec_for_model
from bcg_tpu.models.quantize import (
    dequantize_kv_int4,
    quantize_kv_int4,
    unpack_kv_int4,
)
from bcg_tpu.models.transformer import (
    _cache_attention,
    _dequant_slice,
    _write_cache,
    _xla_attention,
    decode_step,
    init_kv_cache,
    kv_is_int4,
)
from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.ops.paged_attention import (
    PALLAS_INTERPRET,
    init_block_pool,
    paged_decode_attention,
    paged_write,
)

SCHEMA = {
    "type": "object",
    "properties": {
        "decision": {"type": "string", "enum": ["stop", "continue"]},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
    },
    "required": ["decision", "value"],
    "additionalProperties": False,
}

PROMPTS = [
    ("You are honest agent_1 in a consensus game.",
     "Round 2. agent_2 value: 17. Decide.", SCHEMA),
    ("You are byzantine agent_2 in a consensus game.",
     "Round 2. agent_1 value: 16. Decide.", SCHEMA),
]


def _cfg(**kw):
    return EngineConfig(
        backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048,
        **kw,
    )


class TestPackingContract:
    def test_roundtrip_half_step_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 7, 2, 16),
                              jnp.float32) * 5
        packed, scale = quantize_kv_int4(x)
        assert packed.shape == (3, 7, 2, 8) and packed.dtype == jnp.int8
        assert scale.shape == (3, 7, 2) and scale.dtype == jnp.bfloat16
        back = dequantize_kv_int4(packed, scale)
        # Half-step bound against the bf16-ROUNDED scale (what dequant
        # reads): |err| <= scale / 2 per element.
        bound = np.asarray(scale.astype(jnp.float32))[..., None] / 2 + 1e-6
        assert (np.abs(np.asarray(back) - np.asarray(x)) <= bound).all()

    def test_nibble_halves_low_first(self):
        """dims [0, Dh/2) in the low nibble, [Dh/2, Dh) in the high —
        the shared contract the paged kernel's in-VMEM unpack mirrors."""
        x = jnp.asarray(np.arange(-8, 8, dtype=np.float32))[None, :] / 1.0
        packed, scale = quantize_kv_int4(x)
        un = np.asarray(unpack_kv_int4(packed))
        q = np.clip(np.round(np.asarray(x) / np.asarray(
            scale.astype(jnp.float32))[..., None]), -8, 7)
        np.testing.assert_array_equal(un, q.astype(np.int8))

    def test_odd_head_dim_rejected(self):
        with pytest.raises(ValueError, match="even head dim"):
            quantize_kv_int4(jnp.zeros((2, 15)))
        spec = dataclasses.replace(
            spec_for_model("bcg-tpu/tiny-test"), head_dim=15
        )
        with pytest.raises(ValueError, match="even head dim"):
            init_kv_cache(spec, 1, 8, quantized="int4")
        with pytest.raises(ValueError, match="even head dim"):
            init_block_pool(spec, 4, 2, quantized="int4")


class TestCachePaths:
    def test_dense_slab_layout_and_marker(self):
        spec = spec_for_model("bcg-tpu/tiny-test")
        entry = init_kv_cache(spec, 2, 8, quantized="int4")[0]
        assert kv_is_int4(entry)
        assert entry["k"].shape == (2, spec.num_kv_heads, 8,
                                    spec.head_dim // 2)
        assert entry["k_scale"].dtype == jnp.bfloat16
        int8_entry = init_kv_cache(spec, 2, 8, quantized=True)[0]
        assert not kv_is_int4(int8_entry)

    def test_write_then_read_matches_manual_dequant(self):
        spec = dataclasses.replace(
            spec_for_model("bcg-tpu/tiny-test"),
            num_heads=4, num_kv_heads=2, head_dim=16, num_layers=1,
        )
        B, S = 2, 8
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, 2, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, 2, 16))
        entry = _write_cache(
            init_kv_cache(spec, B, S + 2, quantized="int4")[0],
            k, v, jnp.int32(0),
        )
        got = _dequant_slice(entry, "k", S, jnp.float32)
        want = dequantize_kv_int4(*quantize_kv_int4(k))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)

    def test_dense_attention_matches_dequant_oracle(self):
        spec = dataclasses.replace(
            spec_for_model("bcg-tpu/tiny-test"),
            num_heads=4, num_kv_heads=2, head_dim=16, num_layers=1,
        )
        B, S = 2, 8
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, 2, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, 2, 16))
        entry = _write_cache(
            init_kv_cache(spec, B, S, quantized="int4")[0],
            k, v, jnp.int32(0),
        )
        q = jax.random.normal(jax.random.PRNGKey(3), (B, 1, 4, 16))
        mask = jnp.ones((B, S), bool)
        out = _cache_attention(q, entry, mask, 0.25, "xla")
        kd = dequantize_kv_int4(*quantize_kv_int4(k)).astype(q.dtype)
        vd = dequantize_kv_int4(*quantize_kv_int4(v)).astype(q.dtype)
        want = _xla_attention(q, kd, vd, mask[:, None, :], 0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    def test_paged_kernel_matches_gather_oracle_int4(self):
        """The fused paged kernel's in-VMEM nibble unpack (interpret
        mode) against the XLA gather+dequant reference — int4's arm of
        the TestPallasKernelParity suite, incl. a non-pow2 GQA group."""
        for H, Hkv, Dh in ((4, 2, 16), (28, 4, 128)):
            spec = dataclasses.replace(
                spec_for_model("bcg-tpu/tiny-test"),
                num_heads=H, num_kv_heads=Hkv, head_dim=Dh, num_layers=1,
            )
            B, bs, nblk = 2, 8, 2
            S = bs * nblk
            pool = init_block_pool(spec, 12, bs, quantized="int4")[0]
            tbl = jnp.asarray(np.stack(
                [np.arange(1, 1 + nblk), np.arange(5, 5 + nblk)]
            ).astype(np.int32))
            ks = jax.random.split(jax.random.PRNGKey(H + Dh), 4)
            entry = paged_write(
                {**pool, "tbl": tbl},
                jax.random.normal(ks[0], (B, S, Hkv, Dh), jnp.float32),
                jax.random.normal(ks[1], (B, S, Hkv, Dh), jnp.float32),
                jnp.int32(0),
            )
            q = jax.random.normal(ks[2], (B, 1, H, Dh), jnp.float32)
            lens = jax.random.randint(ks[3], (B,), 1, S + 1)
            mask = jnp.arange(S)[None, :] < lens[:, None]
            scale = 1.0 / np.sqrt(Dh)
            ref = paged_decode_attention(q, entry, mask, scale, impl="xla")
            out = paged_decode_attention(q, entry, mask, scale,
                                         impl=PALLAS_INTERPRET)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=1e-5, rtol=1e-5)


class TestEngineInt4:
    def test_decode_logits_close_to_bf16(self):
        """The int8 suite's tolerance idiom at int4's coarser grid:
        logits drift bounded, argmax mostly stable at tiny scale — the
        'established quantization tolerance' the ISSUE pins."""
        spec = spec_for_model("bcg-tpu/tiny-test")
        params = init_params(spec, jax.random.PRNGKey(0))
        B, L = 2, 32
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (B, L), 0, spec.vocab_size
        )
        valid = jnp.ones((B, L), bool)
        outs = []
        for quant in (False, "int4"):
            cache = init_kv_cache(spec, B, L + 4, quantized=quant)
            logits, cache = prefill(params, spec, tokens, valid, cache)
            vm = jnp.zeros((B, L + 4), bool).at[:, : L + 1].set(True)
            tok = jnp.argmax(logits, -1)
            step_logits, _ = decode_step(
                params, spec, tok, jnp.int32(L), jnp.full((B,), L), cache, vm
            )
            outs.append(np.asarray(step_logits))
        # int4's grid is 16x coarser than int8's (15 levels vs 255), so
        # the drift bound scales accordingly; argmax agreement stays the
        # structural sanity floor.
        assert np.abs(outs[0] - outs[1]).max() < 1.2
        assert (outs[0].argmax(-1) == outs[1].argmax(-1)).mean() >= 0.5

    @pytest.mark.parametrize("extra", [
        pytest.param({}, id="dense"),
        pytest.param({"paged_kv": True}, id="paged"),
        pytest.param({"paged_kv": True, "paged_kv_impl": "pallas"},
                     id="paged-pallas"),
    ])
    def test_guided_json_valid_and_tolerant(self, extra):
        eng = JaxEngine(_cfg(kv_cache_dtype="int4", **extra))
        try:
            out = eng.batch_generate_json(PROMPTS, temperature=0.0,
                                          max_tokens=48)
        finally:
            eng.shutdown()
        for r in out:
            assert r.get("decision") in ("stop", "continue"), r
            assert 0 <= r.get("value", -1) <= 50, r

    def test_paged_pallas_token_identical_to_dense_int4(self):
        dense = JaxEngine(_cfg(kv_cache_dtype="int4"))
        paged = JaxEngine(_cfg(kv_cache_dtype="int4", paged_kv=True,
                               paged_kv_impl="pallas"))
        try:
            r_d = dense.batch_generate_json(PROMPTS, temperature=0.0,
                                            max_tokens=48)
            r_p = paged.batch_generate_json(PROMPTS, temperature=0.0,
                                            max_tokens=48)
            pool = paged.kv_pool_stats()
        finally:
            dense.shutdown()
            paged.shutdown()
        assert r_d == r_p
        assert pool["kv_dtype"] == "int4"

    def test_spec_decode_composes_with_int4(self):
        """Speculative decoding over an int4 cache: per-row compacted
        scatter writes through the packed layout, greedy outputs match
        the plain int4 loop."""
        plain = JaxEngine(_cfg(kv_cache_dtype="int4"))
        spec_eng = JaxEngine(_cfg(kv_cache_dtype="int4", spec_decode=True))
        try:
            r_plain = plain.batch_generate_json(PROMPTS, temperature=0.0,
                                                max_tokens=48)
            r_spec = spec_eng.batch_generate_json(PROMPTS, temperature=0.0,
                                                  max_tokens=48)
        finally:
            plain.shutdown()
            spec_eng.shutdown()
        assert r_plain == r_spec

    def test_zero_steady_state_retraces_for_int4_entry_keys(self):
        eng = JaxEngine(_cfg(kv_cache_dtype="int4", paged_kv=True))
        try:
            eng.batch_generate_json(PROMPTS, temperature=0.0, max_tokens=48)
            before = obs_counters.snapshot()
            eng.batch_generate_json(PROMPTS, temperature=0.0, max_tokens=48)
            moved = obs_counters.delta(before)
        finally:
            eng.shutdown()
        jit_movement = {
            k: v for k, v in moved.items()
            if k.startswith(("engine.compile.", "engine.retrace."))
        }
        assert jit_movement == {}, jit_movement


class TestKvDtypeSwitch:
    def test_env_flag_overrides_and_aliases(self, monkeypatch):
        for raw, want in (("bf16", "bfloat16"), ("bfloat16", "bfloat16"),
                          ("int8", "int8"), ("int4", "int4")):
            monkeypatch.setenv("BCG_TPU_KV_DTYPE", raw)
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                eng = JaxEngine(_cfg(kv_cache_dtype="bfloat16"))
            try:
                assert eng.kv_dtype == want, raw
                assert eng.sampler_stats()["kv_dtype"] == want
            finally:
                eng.shutdown()
        monkeypatch.delenv("BCG_TPU_KV_DTYPE")

    def test_bad_dtype_rejected(self, monkeypatch):
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            JaxEngine(_cfg(kv_cache_dtype="fp8"))
        monkeypatch.setenv("BCG_TPU_KV_DTYPE", "int3")
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            JaxEngine(_cfg())

    def test_slot_bytes_exactly_half_of_int8(self):
        import warnings

        bytes_by = {}
        for dtype in ("int8", "int4"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                eng = JaxEngine(_cfg(kv_cache_dtype=dtype))
            bytes_by[dtype] = eng._kv_slot_bytes
            eng.shutdown()
        assert bytes_by["int8"] == 2 * bytes_by["int4"]

    def test_paged_block_bytes_honest(self):
        """kv_pool/admission snapshots report the PACKED bytes: an int4
        pool's per-block device bytes are half an int8 pool's at the
        same block count, read off the actual leaves."""
        import warnings

        bb = {}
        for dtype in ("int8", "int4"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                eng = JaxEngine(_cfg(kv_cache_dtype=dtype, paged_kv=True,
                                     kv_pool_blocks=64))
            stats = eng.kv_pool_stats()
            bb[dtype] = (eng._paged.block_bytes_dev,
                         stats["free_block_headroom_bytes"])
            eng.shutdown()
        assert bb["int8"][0] == 2 * bb["int4"][0]
        assert bb["int8"][1] == 2 * bb["int4"][1]


# --------------------------------------------------------- gate-backed
SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "perf_gate.py")


@pytest.fixture(scope="module")
def int4_gate_metrics():
    spec = importlib.util.spec_from_file_location("perf_gate", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, mod.run_int4_scenario()


class TestGateBacked:
    def test_row_cap_gain_at_least_1_8x(self, int4_gate_metrics):
        """ISSUE-10 acceptance: cap_for-derived row cap >= 1.8x the
        int8 cap at the same HBM budget, and the paged pool affords the
        same gain in blocks."""
        _, m = int4_gate_metrics
        assert m["int4.row_cap_gain"] >= 1.8
        assert m["int4.pool_blocks_gain"] >= 1.8

    def test_parity_and_validity(self, int4_gate_metrics):
        _, m = int4_gate_metrics
        assert m["int4.paged_parity_mismatches"] == 0.0
        assert m["int4.error_rows"] == 0.0

    def test_metrics_conform_to_perf_baseline(self, int4_gate_metrics):
        mod, m = int4_gate_metrics
        findings = mod.check_metrics(m, mod.load_baseline())
        findings += mod.check_stale(m, mod.load_baseline(), ("int4",))
        assert findings == [], findings

    def test_removing_an_int4_entry_resurfaces_its_finding(
        self, int4_gate_metrics
    ):
        mod, m = int4_gate_metrics
        baseline = mod.load_baseline()
        for removed in m:
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(m, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)
