"""Pre-quantized checkpoint artifacts (models/artifact.py).

The reference re-quantizes (or re-loads bf16) at every engine boot
(vllm_agent.py:100-157); the artifact path saves the quantized tree
once and boots straight from it.  Properties pinned here:

* convert -> load round-trips the exact quantized tree (int8 and int4:
  quantized payloads and scales bit-identical, bf16 leaves bit-identical);
* the engine boots from an artifact directory and serves schema-valid
  JSON, with logits identical to a streamed-quantization boot;
* mode/shape mismatches raise instead of silently serving the wrong
  weights;
* stacked (scan-mode) trees are refused at save time.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcg_tpu.config import EngineConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.models.artifact import (
    MANIFEST,
    artifact_mode,
    convert_checkpoint,
    load_quantized_artifact,
    save_quantized_artifact,
)
from bcg_tpu.models.configs import spec_for_model
from bcg_tpu.models.hf_fixture import build_checkpoint
from bcg_tpu.models.loader import load_checkpoint_params
from bcg_tpu.models.quantize import (
    ensure_quantized_head,
    quantize_leaf_transform,
)

TINY = "bcg-hf/tiny"


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    root = tmp_path_factory.mktemp("artifact_src")
    return build_checkpoint(TINY, out_dir=str(root / "bcg-hf--tiny"))


def _streamed_tree(mode):
    spec = spec_for_model(TINY)
    params = load_checkpoint_params(
        spec, TINY, leaf_transform=quantize_leaf_transform(spec, mode)
    )
    return ensure_quantized_head(params, spec, mode=mode), spec


def _assert_leaf_equal(a, b, name):
    if isinstance(a, dict):
        assert isinstance(b, dict), name
        assert set(a) == set(b), name
        for k in a:
            _assert_leaf_equal(a[k], b[k], f"{name}.{k}")
        return
    an, bn = np.asarray(a), np.asarray(b)
    assert an.dtype == bn.dtype, f"{name}: {an.dtype} != {bn.dtype}"
    assert an.shape == bn.shape, f"{name}: {an.shape} != {bn.shape}"
    np.testing.assert_array_equal(an, bn, err_msg=name)


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_round_trip_exact(hf_checkpoint, monkeypatch, tmp_path, mode):
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint))
    original, spec = _streamed_tree(mode)
    out = str(tmp_path / f"artifact-{mode}")
    save_quantized_artifact(original, spec, mode, out)
    assert artifact_mode(out) == mode

    loaded = load_quantized_artifact(spec, out, mode)
    assert set(loaded) == set(original)
    for name in original:
        if name == "layers":
            continue
        _assert_leaf_equal(original[name], loaded[name], name)
    assert len(loaded["layers"]) == len(original["layers"])
    for i, (la, lb) in enumerate(zip(original["layers"], loaded["layers"])):
        assert set(la) == set(lb)
        for k in la:
            _assert_leaf_equal(la[k], lb[k], f"layers.{i}.{k}")


@pytest.mark.slow
def test_convert_cli_and_engine_boot(hf_checkpoint, monkeypatch, tmp_path):
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint))
    art = str(tmp_path / "art")
    convert_checkpoint(TINY, "int8", art)

    cfg = EngineConfig(
        backend="jax", model_name=TINY, max_model_len=512, quantization="int8",
    )
    ref_engine = JaxEngine(cfg)
    ref_params = ref_engine.params

    # Point discovery at the artifact instead of the HF checkpoint.
    parent = str(tmp_path / "artroot")
    os.makedirs(parent, exist_ok=True)
    os.rename(art, os.path.join(parent, "bcg-hf--tiny"))
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", parent)

    eng = JaxEngine(cfg)
    # Identical weights -> identical serving behavior.
    for i, layer in enumerate(ref_params["layers"]):
        for k in layer:
            _assert_leaf_equal(layer[k], eng.params["layers"][i][k], f"layers.{i}.{k}")
    schema = {
        "type": "object",
        "properties": {"value": {"type": "integer", "minimum": 0, "maximum": 9}},
        "required": ["value"],
        "additionalProperties": False,
    }
    out = eng.generate_json("pick", schema, temperature=0.5, max_tokens=16)
    assert isinstance(out.get("value"), int)
    eng.shutdown()
    ref_engine.shutdown()


def test_mode_mismatch_raises(hf_checkpoint, monkeypatch, tmp_path):
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint))
    original, spec = _streamed_tree("int8")
    out = str(tmp_path / "a8")
    save_quantized_artifact(original, spec, "int8", out)
    with pytest.raises(ValueError, match="int8-quantized"):
        load_quantized_artifact(spec, out, "int4")
    with pytest.raises(ValueError, match="int8-quantized"):
        load_quantized_artifact(spec, out, None)


def test_engine_mode_mismatch_raises(hf_checkpoint, monkeypatch, tmp_path):
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint))
    parent = str(tmp_path / "root")
    convert_checkpoint(TINY, "int8", os.path.join(parent, "bcg-hf--tiny"))
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", parent)
    cfg = EngineConfig(
        backend="jax", model_name=TINY, max_model_len=512, quantization="int4",
    )
    with pytest.raises(ValueError, match="int8-quantized"):
        JaxEngine(cfg)


def test_shape_mismatch_raises(hf_checkpoint, monkeypatch, tmp_path):
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint))
    original, spec = _streamed_tree("int8")
    out = str(tmp_path / "a8")
    save_quantized_artifact(original, spec, "int8", out)
    other = spec_for_model("bcg-hf/bench-1b")
    with pytest.raises(ValueError, match="was saved for model"):
        load_quantized_artifact(other, out, "int8")
    # Same name, different dims (e.g. a stale artifact after a spec
    # edit) must hit the dimension check.
    import dataclasses

    drifted = dataclasses.replace(spec, intermediate_size=spec.intermediate_size * 2)
    with pytest.raises(ValueError, match="does not match"):
        load_quantized_artifact(drifted, out, "int8")


def test_stacked_tree_refused(hf_checkpoint, monkeypatch, tmp_path):
    from bcg_tpu.models.transformer import stack_layer_params

    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint))
    original, spec = _streamed_tree("int8")
    stacked = stack_layer_params(original)
    with pytest.raises(ValueError, match="unstacked"):
        save_quantized_artifact(stacked, spec, "int8", str(tmp_path / "x"))


def test_manifest_contents(hf_checkpoint, monkeypatch, tmp_path):
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint))
    original, spec = _streamed_tree("int4")
    out = str(tmp_path / "a4")
    save_quantized_artifact(original, spec, "int4", out)
    with open(os.path.join(out, MANIFEST)) as f:
        m = json.load(f)
    assert m["mode"] == "int4"
    assert m["num_layers"] == spec.num_layers
    # int4 leaves record packed int8 payloads + bf16 group scales.
    assert m["dtypes"]["layers.0.wq.q4"] == "int8"
    assert m["dtypes"]["layers.0.wq.gscale"] == "bfloat16"
    assert m["dtypes"]["embed"] == "bfloat16"


def test_mesh_sharded_artifact_load(hf_checkpoint, monkeypatch, tmp_path):
    """With a mesh, artifact leaves land under their param_sharding
    placement AS THEY LOAD (a tp-requiring model must never
    materialize unsharded on one device)."""
    from bcg_tpu.parallel.mesh import build_mesh
    from bcg_tpu.parallel.sharding import param_sharding

    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(hf_checkpoint))
    original, spec = _streamed_tree("int8")
    out = str(tmp_path / "a8")
    save_quantized_artifact(original, spec, "int8", out)

    mesh = build_mesh(tp=2, dp=1, sp=1)
    loaded = load_quantized_artifact(spec, out, "int8", mesh=mesh)
    wq = loaded["layers"][0]["wq"]
    assert wq["q"].sharding == param_sharding("layers.0.wq.q", spec, mesh)
    assert wq["scale"].sharding == param_sharding("layers.0.wq.scale", spec, mesh)
    assert loaded["embed"].sharding == param_sharding("embed", spec, mesh)
    # Values unchanged by placement.
    _assert_leaf_equal(original["layers"][0]["wq"], wq, "layers.0.wq")
