"""Fleet observability plane (bcg_tpu/obs/fleet.py +
scripts/fleet_report.py + the perf-gate "fleet" scenario).

The ISSUE-11 acceptance surface:

* identity stamping is OFF by default — the Prometheus exposition is
  byte-identical to the unstamped form in a single-process run — and
  ON under BCG_TPU_FLEET / a shard dir / a multi-process group, where
  every sample carries ``process=``/``host=`` labels and stays
  v0.0.4-conformant (scrape-tested on an ephemeral port);
* the ``/metrics`` port offsets by process_index (the multi-rank local
  cluster collision fix) and the bound port lands in the run manifest;
* both JSONL run manifests carry the fleet identity, and ranks of one
  run share the run id (BCG_TPU_RUN_ID);
* metric shards round-trip through scripts/fleet_report.py: counters
  sum, gauges stay per-rank, histograms merge bucket-wise with
  quantiles matching the in-process registry oracle; the straggler
  rule's two implementations (runtime + report, mirrored by value)
  reach the same verdicts;
* the perf-gate "fleet" scenario is green on a REAL 2-process CPU
  cluster, its baseline entries are load-bearing (resurface contract
  owned HERE — test_perf_gate.py skip-lists the fleet.* namespace),
  and the injected-straggler arm fails loudly when detection is
  disabled.
"""

import importlib.util
import json
import os
import socket
import subprocess
import sys
import urllib.request

import pytest

from bcg_tpu.obs import counters as obs_counters, export, fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLEET_REPORT = os.path.join(REPO, "scripts", "fleet_report.py")
PERF_GATE = os.path.join(REPO, "scripts", "perf_gate.py")


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_fleet(monkeypatch):
    """Fleet state isolated: env cleared, caches dropped before AND
    after (set_process_provider / run_id / writer are module globals)."""
    for flag in ("BCG_TPU_FLEET", "BCG_TPU_RUN_ID",
                 "BCG_TPU_METRICS_SHARD_DIR", "BCG_TPU_METRICS_SHARD_MS"):
        monkeypatch.delenv(flag, raising=False)
    fleet.reset()
    yield
    fleet.reset()


# -------------------------------------------------------------- identity
class TestIdentity:
    def test_single_process_default(self, clean_fleet):
        ident = fleet.identity()
        assert ident["process_index"] == 0
        assert ident["process_count"] == 1
        assert len(ident["run_id"]) == 12
        assert ident["pid"] == os.getpid()
        assert not fleet.enabled()
        assert fleet.prom_label_body() == ""

    def test_run_id_env_shared(self, clean_fleet, monkeypatch):
        monkeypatch.setenv("BCG_TPU_RUN_ID", "sweep42")
        assert fleet.run_id() == "sweep42"
        assert fleet.identity()["run_id"] == "sweep42"

    def test_process_provider_engages_stamping(self, clean_fleet):
        fleet.set_process_provider(lambda: (3, 8))
        assert fleet.process_index() == 3
        assert fleet.process_count() == 8
        assert fleet.enabled()
        body = fleet.prom_label_body()
        assert body.startswith('process="3",host="')

    def test_flag_forces_stamping_single_process(self, clean_fleet,
                                                 monkeypatch):
        monkeypatch.setenv("BCG_TPU_FLEET", "1")
        assert fleet.enabled()
        assert 'process="0"' in fleet.prom_label_body()

    def test_manifest_carries_identity_and_run_id(self, clean_fleet,
                                                  monkeypatch):
        monkeypatch.setenv("BCG_TPU_RUN_ID", "manifestrun")
        manifest = export.run_manifest(kind="game")
        assert manifest["run_id"] == "manifestrun"
        assert manifest["host"] == fleet.identity()["host"]
        assert manifest["process_index"] == 0
        assert manifest["process_count"] == 1
        assert "metrics_port" in manifest  # None while the endpoint is off
        # Both sinks of one process share the run id.
        assert export.run_manifest(kind="serve")["run_id"] == "manifestrun"


# ------------------------------------------------------------- exposition
class TestLabeledExposition:
    TYPED = {
        "counters": {"serve.requests": 3},
        "gauges": {"hbm.total_bytes": 1536.5},
        "histograms": {
            "serve.e2e_ms": {
                "buckets": [[5.0, 2], [10.0, 3]], "sum": 17.5, "count": 4,
            },
        },
    }

    def test_byte_identical_when_stamping_off(self, clean_fleet):
        """Acceptance criterion: with fleet stamping off the exposition
        is byte-identical to the unstamped (pre-fleet) renderer."""
        expected = (
            "# HELP bcg_hbm_total_bytes bcg_tpu registry gauge "
            "'hbm.total_bytes'\n"
            "# TYPE bcg_hbm_total_bytes gauge\n"
            "bcg_hbm_total_bytes 1536.5\n"
            "# HELP bcg_serve_e2e_ms bcg_tpu registry histogram "
            "'serve.e2e_ms'\n"
            "# TYPE bcg_serve_e2e_ms histogram\n"
            'bcg_serve_e2e_ms_bucket{le="5"} 2\n'
            'bcg_serve_e2e_ms_bucket{le="10"} 3\n'
            'bcg_serve_e2e_ms_bucket{le="+Inf"} 4\n'
            "bcg_serve_e2e_ms_sum 17.5\n"
            "bcg_serve_e2e_ms_count 4\n"
            "# HELP bcg_serve_requests_total bcg_tpu registry counter "
            "'serve.requests'\n"
            "# TYPE bcg_serve_requests_total counter\n"
            "bcg_serve_requests_total 3\n"
        )
        assert export.render_prometheus(self.TYPED) == expected

    def test_labels_on_every_sample_when_stamping_on(self, clean_fleet):
        fleet.set_process_provider(lambda: (2, 4))
        text = export.render_prometheus(self.TYPED)
        host = fleet.identity()["host"]
        assert f'bcg_serve_requests_total{{process="2",host="{host}"}} 3' \
            in text
        assert f'bcg_hbm_total_bytes{{process="2",host="{host}"}} 1536.5' \
            in text
        # Histogram buckets merge identity labels with le; sum/count
        # take the plain label set.
        bucket5 = (f'bcg_serve_e2e_ms_bucket'
                   f'{{process="2",host="{host}",le="5"}} 2')
        bucket_inf = (f'bcg_serve_e2e_ms_bucket'
                      f'{{process="2",host="{host}",le="+Inf"}} 4')
        assert bucket5 in text
        assert bucket_inf in text
        assert f'bcg_serve_e2e_ms_sum{{process="2",host="{host}"}} 17.5' \
            in text
        # HELP/TYPE metadata lines never carry labels (spec: labels
        # belong to samples).
        for line in text.splitlines():
            if line.startswith("#"):
                assert "process=" not in line

    def test_labeled_scrape_is_conformant(self, clean_fleet, monkeypatch):
        """Ephemeral-port scrape with stamping on: every sample line
        parses as <name>{labels} <value> with v0.0.4 content type."""
        monkeypatch.setenv("BCG_TPU_FLEET", "1")
        obs_counters.inc("fleet.probe")
        server, port = export.start_http_server(0)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4"
                )
                body = resp.read().decode()
        finally:
            server.shutdown()
            server.server_close()
        import re

        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*\{[a-zA-Z0-9_]+="[^"]*"'
            r'(,[a-zA-Z0-9_]+="[^"]*")*\} -?[0-9.e+-]+$'
        )
        samples = [l for l in body.splitlines() if not l.startswith("#")]
        assert samples
        for line in samples:
            assert sample.match(line), line
        assert 'bcg_fleet_probe_total{process="0",host="' in body

    def test_port_offsets_by_process_index(self, clean_fleet, monkeypatch):
        """Satellite: rank r binds base+r, so every rank of a local
        cluster is scrapeable instead of warn-and-skipping on the bind
        collision; the bound port surfaces in the run manifest."""
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            free = s.getsockname()[1]
        fleet.set_process_provider(lambda: (2, 4))
        monkeypatch.setenv("BCG_TPU_METRICS_PORT", str(free - 2))
        export.stop_http_server()
        try:
            bound = export.maybe_start_http_server()
            assert bound == free
            assert export.current_http_port() == free
            assert export.run_manifest(kind="serve")["metrics_port"] == free
        finally:
            export.stop_http_server()


# ------------------------------------------------- watermarks + shard writer
class TestLivenessAndShards:
    def test_watermark_advances_and_freezes(self, clean_fleet, monkeypatch):
        monkeypatch.setenv("BCG_TPU_FLEET", "1")
        fleet.note_round()
        fleet.note_dispatch()
        # clean_fleet reset the internal watermark to 0, so two
        # advances publish exactly 2 regardless of earlier tests.
        assert obs_counters.value("fleet.watermark") == 2
        fleet.freeze_watermark()
        fleet.note_round()
        assert obs_counters.value("fleet.watermark") == 2

    def test_watermark_noop_when_stamping_off(self, clean_fleet):
        before = obs_counters.value("fleet.watermark", -1)
        fleet.note_round()
        assert obs_counters.value("fleet.watermark", -1) == before

    def test_shard_writer_roundtrip(self, clean_fleet, monkeypatch,
                                    tmp_path):
        monkeypatch.setenv("BCG_TPU_RUN_ID", "shardrun")
        monkeypatch.setenv("BCG_TPU_METRICS_SHARD_DIR", str(tmp_path))
        monkeypatch.setenv("BCG_TPU_METRICS_SHARD_MS", "60000")
        writer = fleet.maybe_start_shard_writer()
        assert writer is not None
        assert os.path.basename(writer.path) == "shard-shardrun-0.jsonl"
        obs_counters.inc("fleet.probe", 5)
        obs_counters.histogram("fleet.probe_ms", (5, 10, 25, 50, 100, 250))
        fleet.flush_shards()
        rec = fleet.read_last_record(writer.path)
        assert rec["schema_version"] == fleet.SHARD_SCHEMA_VERSION
        assert rec["identity"]["run_id"] == "shardrun"
        assert rec["counters"]["fleet.probe"] >= 5
        assert rec["gauges"]["fleet.heartbeat_ms"] > 0
        assert rec["gauges"]["fleet.process_count"] == 1
        assert "fleet.probe_ms" in rec["histograms"]
        assert fleet.summary()["shard_path"] == writer.path

    def test_read_last_record_skips_truncated_tail(self, tmp_path):
        path = tmp_path / "shard-x-0.jsonl"
        good = {"schema_version": 1, "identity": {"process_index": 0}}
        path.write_text(json.dumps(good) + "\n" + '{"truncated": tr')
        assert fleet.read_last_record(str(path)) == good


# ------------------------------------------------------ straggler detection
def _record(proc, watermark, hb_ms, flush_ms=100, host="h"):
    return {
        "schema_version": 1,
        "flush_ms": flush_ms,
        "heartbeat_ms": hb_ms,
        "identity": {"run_id": "r", "process_index": proc, "host": host},
        "counters": {},
        "gauges": {"fleet.watermark": watermark,
                   "fleet.heartbeat_ms": hb_ms},
        "histograms": {},
    }


class TestStragglerRule:
    def test_watermark_lag_flags(self):
        records = [_record(0, 12, 1000.0), _record(1, 1, 1000.0)]
        flagged = fleet.detect_stragglers(records, 3, now_ms=1000.0)
        assert [f["process_index"] for f in flagged] == [1]
        assert flagged[0]["reasons"] == ["watermark"]

    def test_heartbeat_lag_flags(self):
        records = [_record(0, 5, 10_000.0), _record(1, 5, 9_000.0)]
        flagged = fleet.detect_stragglers(records, 3, now_ms=10_000.0)
        assert [f["process_index"] for f in flagged] == [1]
        assert flagged[0]["reasons"] == ["heartbeat"]

    def test_factor_zero_disables(self):
        records = [_record(0, 12, 1000.0), _record(1, 0, 100.0)]
        assert fleet.detect_stragglers(records, 0, now_ms=1000.0) == []

    def test_single_rank_never_flags(self):
        assert fleet.detect_stragglers([_record(0, 0, 1.0)], 3) == []

    def test_report_mirror_reaches_same_verdicts(self):
        """The import-free fleet_report mirror and the runtime rule
        must agree verdict-for-verdict on the same records."""
        fr = _load(FLEET_REPORT, "fleet_report_mirror")
        cases = [
            [_record(0, 12, 1000.0), _record(1, 1, 1000.0)],
            [_record(0, 5, 10_000.0), _record(1, 5, 9_000.0)],
            [_record(0, 6, 1000.0), _record(1, 6, 1000.0)],
            [_record(0, 0, 1000.0), _record(1, 0, 1000.0)],
        ]
        for records in cases:
            for factor in (0, 2, 3, 10):
                ours = fleet.detect_stragglers(
                    records, factor, now_ms=10_000.0
                )
                theirs = fr.detect_stragglers(
                    records, factor, now_ms=10_000.0
                )
                assert [f["process_index"] for f in ours] == \
                    [f["process_index"] for f in theirs], (records, factor)
                assert [f["reasons"] for f in ours] == \
                    [f["reasons"] for f in theirs]

    def test_runtime_check_publishes_gauge(self, clean_fleet, monkeypatch,
                                           tmp_path):
        """check_stragglers reads PEER shards from the dir and exports
        fleet.stragglers — the serve scheduler's per-dispatch hook."""
        monkeypatch.setenv("BCG_TPU_RUN_ID", "livecheck")
        monkeypatch.setenv("BCG_TPU_METRICS_SHARD_DIR", str(tmp_path))
        monkeypatch.setenv("BCG_TPU_METRICS_SHARD_MS", "60000")
        monkeypatch.setenv("BCG_TPU_FLEET", "1")
        for _ in range(8):
            fleet.note_round()
        fleet.flush_shards()
        # A lagging peer rank appears in the shard dir.
        lagging = _record(1, 0, 50.0)
        lagging["identity"]["run_id"] = "livecheck"
        (tmp_path / "shard-livecheck-1.jsonl").write_text(
            json.dumps(lagging) + "\n"
        )
        flagged = fleet.check_stragglers(force=True)
        assert [f["process_index"] for f in flagged] == [1]
        assert obs_counters.value("fleet.stragglers") == 1


# ----------------------------------------------------------- shard merging
class TestFleetReportMerge:
    BOUNDS = (5.0, 10.0, 25.0, 50.0)

    def _shard(self, proc, values, counter, gauge, host):
        hist = obs_counters.Histogram(f"probe{proc}", self.BOUNDS)
        for v in values:
            hist.observe(v)
        return {
            "schema_version": 1,
            "flush_ms": 100,
            "heartbeat_ms": 1000.0,
            "identity": {"run_id": "merge", "process_index": proc,
                         "host": host},
            "counters": {"game.rounds": counter},
            "gauges": {"fleet.watermark": gauge},
            "histograms": {
                "game.round_ms": {
                    "buckets": [[b, c] for b, c in hist.cumulative()],
                    "sum": hist.sum,
                    "count": hist.count,
                },
            },
        }

    def test_counters_sum_with_skew_and_hosts(self):
        fr = _load(FLEET_REPORT, "fleet_report_merge")
        records = [
            self._shard(0, [], 10, 5, "host-a"),
            self._shard(1, [], 30, 6, "host-b"),
        ]
        merged = fr.merge_counters(records)
        row = merged["game.rounds"]
        assert row["total"] == 40
        assert row["per_host"] == {"host-a": 10, "host-b": 30}
        assert row["median_rank"] == 20
        assert row["p95_rank"] == 30
        assert row["skew"] == 1.5
        gauges = fr.merge_gauges(records)
        assert gauges["fleet.watermark"] == {
            "0@host-a": 5, "1@host-b": 6,
        }

    def test_histogram_merge_matches_single_stream_oracle(self):
        """Bucket-wise merge of two ranks' histograms must produce the
        same quantiles as one registry histogram observing the union —
        the perf-gate fleet scenario's oracle contract, unit-scale."""
        fr = _load(FLEET_REPORT, "fleet_report_hist")
        values_a = [2, 7, 7, 12, 30]
        values_b = [4, 8, 20, 45, 45, 60]
        records = [
            self._shard(0, values_a, 0, 0, "a"),
            self._shard(1, values_b, 0, 0, "b"),
        ]
        problems = []
        merged = fr.merge_histograms(records, problems)["game.round_ms"]
        assert problems == []
        assert merged["count"] == len(values_a) + len(values_b)
        oracle = obs_counters.Histogram("oracle", self.BOUNDS)
        for v in values_a + values_b:
            oracle.observe(v)
        got = fr.histogram_quantiles(merged)
        want = oracle.quantiles()
        for q in ("p50", "p95", "p99"):
            assert got[q] == pytest.approx(want[q], rel=1e-9), q

    def test_bound_mismatch_is_reported_not_blended(self):
        fr = _load(FLEET_REPORT, "fleet_report_bounds")
        a = self._shard(0, [2], 0, 0, "a")
        b = self._shard(1, [2], 0, 0, "b")
        b["histograms"]["game.round_ms"]["buckets"] = [[1.0, 1], [99.0, 1]]
        problems = []
        merged = fr.merge_histograms([a, b], problems)["game.round_ms"]
        assert merged["count"] == 1  # rank b skipped, not blended
        assert problems and "bounds" in problems[0]

    def test_cli_report_and_watch(self, tmp_path):
        """Script smoke: fleet table on merged shards (rc 0), --watch
        flags the lagging rank (rc 3), and the script keeps the
        bcg_tpu-import-free contract."""
        healthy = self._shard(0, [2, 7], 10, 8, "host-a")
        lagging = self._shard(1, [4], 30, 0, "host-b")
        (tmp_path / "shard-merge-0.jsonl").write_text(
            json.dumps(healthy) + "\n"
        )
        (tmp_path / "shard-merge-1.jsonl").write_text(
            json.dumps(lagging) + "\n"
        )
        proc = subprocess.run(
            [sys.executable, FLEET_REPORT, str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "run merge: 2 rank(s) on 2 host(s)" in proc.stdout
        assert "game.rounds" in proc.stdout
        assert "host-a=10 host-b=30" in proc.stdout
        assert "game.round_ms" in proc.stdout
        watch = subprocess.run(
            [sys.executable, FLEET_REPORT, str(tmp_path), "--watch"],
            capture_output=True, text=True, timeout=60,
        )
        assert watch.returncode == 3, watch.stdout + watch.stderr
        assert "STRAGGLER" in watch.stdout
        assert "1@host-b" in watch.stdout
        src = open(FLEET_REPORT).read()
        assert "import bcg_tpu" not in src and "from bcg_tpu" not in src


# ------------------------------------------------- consensus_report grouping
class TestConsensusReportRunGrouping:
    def test_two_rank_files_of_one_run_merge_into_one_row(self, tmp_path):
        """Satellite: rank files sharing a stamped run_id report as ONE
        run (ranks=2), not two independent runs."""
        report = _load(
            os.path.join(REPO, "scripts", "consensus_report.py"),
            "consensus_report_fleet",
        )
        for proc in (0, 1):
            lines = [
                {"event": "manifest", "schema_version": 1,
                 "run_id": "fleetrun", "process_index": proc,
                 "host": f"host-{proc}", "flags": {}},
                {"event": "game_start", "game": "g1", "round": None,
                 "num_honest": 4, "num_byzantine": 1,
                 "topology": "fully_connected"},
                {"event": "round_end", "game": "g1", "round": 1,
                 "has_consensus": True, "byzantine_influence": 0,
                 "duration_ms": 2.0},
                {"event": "game_end", "game": "g1", "round": 1,
                 "converged": True, "rounds": 1,
                 "byzantine_influence": 0},
            ]
            (tmp_path / f"ev-{proc}.jsonl").write_text(
                "\n".join(json.dumps(l) for l in lines) + "\n"
            )
        problems = []
        games = []
        for proc in (0, 1):
            games.extend(
                report.parse_file(str(tmp_path / f"ev-{proc}.jsonl"),
                                  problems)
            )
        out = report.render_report(games, problems)
        rows = [l for l in out.splitlines() if "fully_connected" in l]
        assert len(rows) == 1, out  # ONE row for the run, not two
        fields = rows[0].split()
        assert fields[0] == "1"  # runs column: ONE run, not two
        assert fields[1] == "2"  # ranks column: two contributing ranks
        assert "100.0%" in rows[0]


# --------------------------------------------------- gate-backed (2-process)
@pytest.fixture(scope="module")
def fleet_gate():
    mod = _load(PERF_GATE, "perf_gate_fleet")
    measured = mod.run_fleet_scenario()
    return mod, measured


class TestFleetGate:
    def test_green_at_head(self, fleet_gate):
        """Acceptance criterion: the fleet scenario is green on a real
        2-process CPU cluster — all-rank shard completeness, merged
        quantiles matching the single-stream oracle, zero drops, and
        the frozen rank flagged."""
        mod, measured = fleet_gate
        findings = mod.check_metrics(measured, mod.load_baseline())
        findings += mod.check_stale(measured, mod.load_baseline(),
                                    ("fleet",))
        assert findings == [], "\n".join(findings)

    def test_advertised_metrics_measured(self, fleet_gate):
        _, measured = fleet_gate
        assert sorted(measured) == [
            "fleet.counter_merge_error",
            "fleet.events_dropped",
            "fleet.merged_p50_rel_err",
            "fleet.merged_p95_rel_err",
            "fleet.shard_completeness",
            "fleet.straggler_flagged",
        ]

    def test_hard_contracts(self, fleet_gate):
        _, measured = fleet_gate
        assert measured["fleet.shard_completeness"] == 1.0
        assert measured["fleet.counter_merge_error"] == 0
        assert measured["fleet.events_dropped"] == 0
        assert measured["fleet.straggler_flagged"] == 1.0

    def test_removing_a_fleet_entry_resurfaces_its_finding(self,
                                                           fleet_gate):
        """Resurface contract for the fleet.* namespace (skip-listed in
        test_perf_gate.py; owned here)."""
        mod, measured = fleet_gate
        baseline = mod.load_baseline()
        fleet_entries = [
            n for n in baseline["metrics"] if n.startswith("fleet.")
        ]
        assert sorted(fleet_entries) == sorted(measured)
        for removed in fleet_entries:
            pruned = json.loads(json.dumps(baseline))
            del pruned["metrics"][removed]
            findings = mod.check_metrics(measured, pruned)
            assert any(
                removed in f and "no entry" in f for f in findings
            ), (removed, findings)

    def test_straggler_detection_disabled_fails_loudly(self, fleet_gate):
        """Acceptance criterion: with detection disabled
        (BCG_TPU_FLEET_STRAGGLER_FACTOR=0) the injected-straggler arm
        must FAIL naming fleet.straggler_flagged — never vacuously
        green."""
        mod, _ = fleet_gate
        measured = mod.run_fleet_scenario(inject="straggler-off")
        assert measured["fleet.straggler_flagged"] == 0.0
        findings = mod.check_metrics(measured, mod.load_baseline())
        hits = [f for f in findings if "fleet.straggler_flagged" in f]
        assert hits, findings
        assert ">=" in hits[0]
