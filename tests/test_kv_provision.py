"""Engaged-axes KV provisioning: ``JaxEngine.cap_for`` /
``_check_kv_budget`` must derive their divisor from the mesh axes
``kv_cache_tree_sharding`` actually engages for the given B/S/Hkv —
NOT from ``mesh.size`` (ADVICE round-5 medium: the dp-bypass path
replicates the batch axis, so the flat divisor overcommitted per-device
HBM by up to dp×)."""

import dataclasses
from functools import partial

import jax
import pytest

from bcg_tpu.config import BCGConfig
from bcg_tpu.engine.jax_engine import JaxEngine
from bcg_tpu.models.transformer import init_kv_cache
from bcg_tpu.parallel.mesh import mesh_from_engine_config
from bcg_tpu.parallel.sharding import kv_cache_bytes_per_device

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _engine(dp=1, tp=1, sp=1, **kw):
    cfg = dataclasses.replace(
        BCGConfig().engine, backend="jax", model_name="bcg-tpu/tiny-test",
        max_model_len=512, data_parallel_size=dp,
        tensor_parallel_size=tp, sequence_parallel_size=sp, **kw,
    )
    mesh = mesh_from_engine_config(cfg) if dp * tp * sp > 1 else None
    return JaxEngine(cfg, mesh=mesh)


def _set_budget(eng, kv_bytes: float) -> None:
    """Give the engine a device-memory limit whose KV budget is exactly
    ``kv_bytes`` (prefix reserve zeroed for determinism)."""
    eng.prefix_caching = False
    eng._mem_limit = int(
        (eng._param_bytes_per_device + kv_bytes) / eng.config.hbm_utilization
    )


def _placed_bytes(eng, B: int, S: int) -> int:
    """Bytes kv_cache_tree_sharding actually places per device for a
    [B, S] cache of this engine's layout — the ground truth the engine's
    accounting must match."""
    shapes = jax.eval_shape(partial(
        init_kv_cache, eng.spec, B, S,
        quantized=eng.kv_quantized, stacked=eng.scan_layers,
    ))
    return kv_cache_bytes_per_device(
        eng.mesh, shapes, quantized=eng.kv_quantized, stacked=eng.scan_layers,
    )


class TestEngagedAxesBytes:
    def test_matches_placed_cache_exactly(self):
        # _kv_bytes_per_device == what the placement function places,
        # for dp-divisible and dp-indivisible batches alike.
        eng = _engine(dp=8)
        for B in (1, 3, 8, 16):
            assert eng._kv_bytes_per_device(B, 256) == _placed_bytes(eng, B, 256)
        eng.shutdown()

    def test_dp_indivisible_batch_replicates(self):
        eng = _engine(dp=8)
        S = 256
        full_row = eng.spec.num_layers * eng._kv_slot_bytes * S
        # B=3 does not divide dp=8: every device holds all 3 rows.
        assert eng._kv_bytes_per_device(3, S) == 3 * full_row
        # B=8 divides: each device holds one row's bytes.
        assert eng._kv_bytes_per_device(8, S) == full_row
        eng.shutdown()

    def test_axis_failing_divisibility_guard_does_not_divide(self):
        # tiny-test has Hkv=2; tp=8 fails the Hkv % tp guard, so the
        # cache replicates over tp and tp must NOT divide the bytes.
        eng = _engine(tp=8)
        S = 256
        full = 8 * eng.spec.num_layers * eng._kv_slot_bytes * S
        assert eng._kv_bytes_per_device(8, S) == full
        # Sanity: the old flat divisor would claim mesh.size× less.
        assert full // eng._mesh_devices < full
        eng.shutdown()

    def test_engaged_tp_divides(self):
        # Hkv=2, tp=2 engages on the kv-head axis of the bf16 cache.
        eng = _engine(tp=2)
        S = 256
        full = eng.spec.num_layers * eng._kv_slot_bytes * S
        assert eng._kv_bytes_per_device(1, S) == full // 2
        eng.shutdown()


class TestCapFor:
    def test_dp_engaged_cap(self):
        eng = _engine(dp=8)
        S = 256
        per_row = eng._kv_bytes_per_device(8, S) / 8
        _set_budget(eng, 20.5 * per_row)
        cap = eng.cap_for(S)
        assert cap == 20
        # The cap's regime is self-consistent: >= dp, so the caller
        # dp-aligns and the per-row cost it assumed is the one placed.
        assert cap >= 8
        eng.shutdown()

    def test_dp_bypass_cap_counts_replicated_rows(self):
        # Budget fits 5 dp-SHARDED rows -> engaged cap 5 < dp=8, so dp
        # cannot engage (_dp_mult drops the alignment) and every row
        # costs its FULL replicated bytes.  One replicated row costs
        # exactly dp sharded rows' per-device bytes, so "can't afford dp
        # sharded rows" means "can't afford even one replicated row":
        # the honest cap is the serve-minimum 1 — NOT the 5 the flat
        # mesh.size divisor handed out, which would place 5 × replicated
        # bytes (an 8× overcommit) on every device.
        eng = _engine(dp=8)
        S = 256
        row_sharded = eng._kv_bytes_per_device(8, S) / 8
        row_replicated = eng._kv_bytes_per_device(1, S)
        assert row_replicated == 8 * row_sharded
        budget = 5.5 * row_sharded
        _set_budget(eng, budget)
        cap = eng.cap_for(S)
        assert cap == 1
        # What the OLD flat divisor would have derived — and what those
        # rows would actually place per device (the overcommit).
        old_cap = int(budget // row_sharded)
        assert old_cap == 5
        assert _placed_bytes(eng, old_cap, S) == old_cap * row_replicated
        assert _placed_bytes(eng, old_cap, S) > budget
        eng.shutdown()

    def test_budget_above_one_replicated_row_reenters_engaged_regime(self):
        # A budget that affords >= dp sharded rows always engages: 3.5
        # replicated rows' worth IS 28 sharded rows, so the cap is 28
        # and the caller's dp alignment makes the assumed per-row cost
        # the placed one.
        eng = _engine(dp=8)
        S = 256
        row_replicated = eng._kv_bytes_per_device(1, S)
        _set_budget(eng, 3.5 * row_replicated)
        cap = eng.cap_for(S)
        # 3.5 replicated rows == 28 sharded rows (± one row of rounding
        # through the integer mem-limit reconstruction).
        assert cap in (27, 28)
        assert cap >= 8
        eng.shutdown()

    def test_cap_matches_placed_bytes_when_engaged(self):
        # The derived cap, fed back through the placement function at
        # the dp-aligned chunk size the caller would run, fits the
        # budget — and the next aligned size up would not.
        from bcg_tpu.engine.jax_engine import _chunk_size

        eng = _engine(dp=8)
        S = 256
        row_sharded = eng._kv_bytes_per_device(8, S) / 8
        budget = 20.5 * row_sharded
        _set_budget(eng, budget)
        cap = eng.cap_for(S)
        assert cap == 20
        chunk = _chunk_size(cap, 8)  # largest dp-aligned batch under cap
        assert chunk == 16
        assert _placed_bytes(eng, chunk, S) <= budget
        assert _placed_bytes(eng, chunk + 8, S) > budget
        eng.shutdown()

    def test_unknown_limit_returns_none(self):
        eng = _engine(dp=8)
        eng._mem_limit = None
        assert eng.cap_for(256) is None
        eng.shutdown()

    def test_single_device_cap_unchanged(self):
        # mesh=None engines keep the plain slot-bytes arithmetic.
        eng = _engine()
        S = 256
        per_row = S * eng._kv_slot_bytes * eng.spec.num_layers
        _set_budget(eng, 2.5 * per_row)
        assert eng.cap_for(S) == 2
        eng.shutdown()


class TestCheckKvBudget:
    def test_warns_on_dp_bypass_overcommit(self):
        # A batch the OLD flat divisor judged affordable: B=3 rows on
        # dp=8 with budget for 3 rows /8.  Engaged-axes accounting sees
        # the replication and warns.
        eng = _engine(dp=8)
        S_worst = eng.max_model_len - 24 - 1 + 24 + 1
        row = eng.spec.num_layers * eng._kv_slot_bytes * S_worst
        _set_budget(eng, 3 * row / 8)
        with pytest.warns(UserWarning, match="worst-case KV cache"):
            eng._check_kv_budget(3, [24] * 3, 24 + 1)
        assert eng._kv_budget_warned
        eng.shutdown()

    def test_no_warning_when_engaged_fits(self):
        eng = _engine(dp=8)
        S_worst = eng.max_model_len - 24 - 1 + 24 + 1
        row = eng.spec.num_layers * eng._kv_slot_bytes * S_worst
        # 8 rows dp-shard to one row per device; budget 2 rows/device.
        _set_budget(eng, 2 * row)
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("error")
            eng._check_kv_budget(8, [24] * 8, 24 + 1)
        assert not eng._kv_budget_warned
        eng.shutdown()


class TestProvisionerEndToEnd:
    def test_oversized_batch_still_serves_under_mesh(self):
        # Provisioned chunking composes with the dp mesh end to end.
        eng = _engine(dp=2)
        S = 256
        row = eng._kv_bytes_per_device(2, S) / 2
        _set_budget(eng, 40 * row)
        out = eng.batch_generate_json(
            [("sys", f"user {i}", {
                "type": "object",
                "properties": {"value": {"type": "integer"}},
                "required": ["value"],
            }) for i in range(4)],
            temperature=0.0, max_tokens=24,
        )
        assert len(out) == 4
        assert all(isinstance(o, dict) for o in out)
        eng.shutdown()
