"""Fault injection (engine/fault.py): the retry/degradation ladder as a
controlled experimental axis."""

import dataclasses

import pytest

from bcg_tpu.api import run_simulation
from bcg_tpu.config import BCGConfig, EngineConfig
from bcg_tpu.engine.fake import FakeEngine
from bcg_tpu.engine.fault import FaultInjectingEngine
from bcg_tpu.engine.interface import create_engine

SCHEMA = {
    "type": "object",
    "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
    "required": ["decision"],
}


class TestWrapper:
    def test_rate_zero_is_identity(self):
        inner = FakeEngine(seed=0)
        faulty = FaultInjectingEngine(FakeEngine(seed=0), rate=0.0, seed=1)
        prompts = [("sys", f"u{i}", SCHEMA) for i in range(6)]
        assert faulty.batch_generate_json(prompts) == inner.batch_generate_json(prompts)
        assert faulty.injected == 0

    def test_rate_one_corrupts_everything(self):
        faulty = FaultInjectingEngine(FakeEngine(seed=0), rate=1.0, seed=2)
        out = faulty.batch_generate_json([("sys", "u", SCHEMA)] * 8)
        assert faulty.injected == 8
        # Every corruption must FAIL the validity predicates one way or
        # another: error key, missing field, wrong type, or short string.
        for r in out:
            valid = (
                isinstance(r.get("decision"), str)
                and r["decision"] in ("stop", "continue")
                and "error" not in r
            )
            assert not valid, r

    def test_bad_rate_rejected(self):
        with pytest.raises(ValueError):
            FaultInjectingEngine(FakeEngine(seed=0), rate=1.5)

    def test_negative_rate_rejected_at_create_engine(self):
        with pytest.raises(ValueError, match="fault_rate"):
            create_engine(EngineConfig(backend="fake", fault_rate=-0.2))

    def test_byzantine_shape_corruptions_always_invalid(self):
        """drop_field / wrong_type must hit a field the Byzantine validity
        predicate checks (public_reasoning is unchecked for Byzantine),
        so nominal rate == effective rate."""
        from bcg_tpu.runtime.orchestrator import BCGSimulation

        faulty = FaultInjectingEngine(FakeEngine(seed=0), rate=1.0, seed=5)
        byz = {"internal_strategy": "lurk quietly", "value": 12,
               "public_reasoning": "blend in with the honest agents"}
        for _ in range(40):
            corrupted = faulty._corrupt(dict(byz))
            assert not BCGSimulation._is_valid_byzantine_decision_response(corrupted), corrupted

    def test_create_engine_wraps(self):
        cfg = EngineConfig(backend="fake", fault_rate=0.5, fault_seed=3)
        engine = create_engine(cfg)
        assert isinstance(engine, FaultInjectingEngine)
        assert engine.rate == 0.5


class TestGameUnderFaults:
    @pytest.mark.parametrize("rate", [0.2, 0.5])
    def test_game_completes_and_degrades_gracefully(self, rate):
        base = BCGConfig()
        cfg = dataclasses.replace(
            base,
            engine=dataclasses.replace(
                base.engine, backend="fake", fault_rate=rate, fault_seed=11
            ),
        )
        out = run_simulation(
            n_agents=4, byzantine_count=1, max_rounds=5, backend="fake",
            seed=4, config=cfg,
        )
        m = out["metrics"]
        # The game must never crash: faults degrade to retries, abstains,
        # and CONTINUE votes (reference main.py:348-351,451-454 semantics).
        assert "consensus_reached" in m
        assert m["total_rounds"] >= 1


class TestObservability:
    def test_corruptions_count_in_registry(self):
        """`self.injected` alone is invisible to /metrics, the fleet
        shard merge, and bench JSON — every corruption must move the
        engine.faults.injected counter too (ISSUE 15 satellite)."""
        from bcg_tpu.obs import counters as obs_counters

        before = obs_counters.value("engine.faults.injected")
        faulty = FaultInjectingEngine(FakeEngine(seed=0), rate=1.0, seed=3)
        faulty.batch_generate_json([("sys", "u", SCHEMA)] * 5)
        assert obs_counters.value("engine.faults.injected") - before == 5
        assert faulty.injected == 5

    def test_env_flags_override_config(self, monkeypatch):
        """BCG_TPU_FAULT_RATE / _SEED wrap the created engine even when
        the config fields are zero (the bench/sweep A/B convention)."""
        monkeypatch.setenv("BCG_TPU_FAULT_RATE", "0.5")
        monkeypatch.setenv("BCG_TPU_FAULT_SEED", "13")
        engine = create_engine(EngineConfig(backend="fake"))
        assert isinstance(engine, FaultInjectingEngine)
        assert engine.rate == 0.5
        assert engine.rng.random() == __import__("random").Random(13).random()

    def test_env_rate_validates_before_boot(self, monkeypatch):
        monkeypatch.setenv("BCG_TPU_FAULT_RATE", "1.5")
        with pytest.raises(ValueError, match="outside"):
            create_engine(EngineConfig(backend="fake"))
        monkeypatch.setenv("BCG_TPU_FAULT_RATE", "not-a-float")
        with pytest.raises(ValueError, match="not a float"):
            create_engine(EngineConfig(backend="fake"))
