"""Real-checkpoint readiness (VERDICT round-5 item 9): the day real
Qwen/Llama/Mistral safetensors appear on a host, nothing else must be
missing — the whole ``models/loader.py`` boot path (discovery ->
shard inventory -> tensor layout -> tokenizer byte table) is verified
here END TO END, mirroring the reference's checkpoint boot
(``vllm_agent.py:100-157``).

Two arms over ONE shared readiness routine:

* The HERMETIC arm runs the routine against the genuine-HF-layout
  ``bcg-hf/tiny`` fixture (models/hf_fixture.py — real tokenizer.json,
  real safetensors shards, real config.json), so the readiness check
  itself is exercised green on every CI run.
* The GATED arm discovers a REAL checkpoint for any registered model
  preset (``BCG_TPU_CHECKPOINT_DIR`` / HF cache, the exact
  ``find_checkpoint_dir`` walk the engine boots through) and is
  SKIPPED when none exists — on a weights-bearing host it runs the
  same routine, plus a full ``load_checkpoint_params`` when the model
  is small enough for host RAM (or ``BCG_TPU_SKIP_SLOW`` is unset and
  the operator opts in by pointing the env at the weights).
"""

import os

import pytest

from bcg_tpu.config import MODEL_PRESETS
from bcg_tpu.models.configs import spec_for_model
from bcg_tpu.models.loader import find_checkpoint_dir

# Layer tensors every supported family must ship (bias/q_norm tensors
# are family-optional — the loader probes them by presence).
_REQUIRED_LAYER_KEYS = (
    "input_layernorm.weight",
    "self_attn.q_proj.weight", "self_attn.k_proj.weight",
    "self_attn.v_proj.weight", "self_attn.o_proj.weight",
    "post_attention_layernorm.weight",
    "mlp.gate_proj.weight", "mlp.up_proj.weight", "mlp.down_proj.weight",
)
_REQUIRED_TOP_KEYS = ("model.embed_tokens.weight", "model.norm.weight")

# Full-tree load ceiling for the gated arm: a tiny/7B-int8-class
# checkpoint loads on a test host; a 32B bf16 tree must not OOM CI.
_FULL_LOAD_CEILING_BYTES = 4 << 30


def _shard_tensor_index(ckpt_dir):
    """tensor name -> shape over every safetensors shard in the dir —
    the same index the loader builds before streaming."""
    from safetensors import safe_open

    index = {}
    for fname in sorted(os.listdir(ckpt_dir)):
        if not fname.endswith(".safetensors"):
            continue
        with safe_open(os.path.join(ckpt_dir, fname),
                       framework="numpy") as f:
            for name in f.keys():
                index[name] = tuple(f.get_slice(name).get_shape())
    return index


def _readiness_check(model_name: str, ckpt_dir: str, full_load: bool):
    """The boot-path contract, checkpoint-agnostic:

    1. discovery resolves the dir the engine would use;
    2. the shard inventory covers EVERY tensor the loader fetches, at
       the shapes ``spec_for_model`` predicts (HF stores projections
       [out, in]; loader transposes);
    3. the tokenizer loads with an intact byte table (the DFA
       invariant: per-token bytes concatenate back to the text);
    4. (full_load) ``load_checkpoint_params`` streams the whole tree
       and the resulting pytree matches the spec's layer count.
    """
    from bcg_tpu.engine.tokenizer import tokenizer_for_model

    spec = spec_for_model(model_name)
    found = find_checkpoint_dir(model_name)
    assert found is not None, (
        f"discovery lost {model_name!r} although the caller found "
        f"{ckpt_dir!r}"
    )
    index = _shard_tensor_index(found)

    for key in _REQUIRED_TOP_KEYS:
        assert key in index, f"{model_name}: missing {key}"
    assert index["model.embed_tokens.weight"] == (
        spec.vocab_size, spec.hidden_size
    )
    for i in range(spec.num_layers):
        for key in _REQUIRED_LAYER_KEYS:
            full = f"model.layers.{i}.{key}"
            assert full in index, f"{model_name}: missing {full}"
    q_out = spec.num_heads * spec.head_dim
    kv_out = spec.num_kv_heads * spec.head_dim
    assert index["model.layers.0.self_attn.q_proj.weight"] == (
        q_out, spec.hidden_size
    )
    assert index["model.layers.0.self_attn.k_proj.weight"] == (
        kv_out, spec.hidden_size
    )
    # Tied-embedding families may omit lm_head; untied ones must have it.
    if "lm_head.weight" in index:
        assert index["lm_head.weight"] == (spec.vocab_size, spec.hidden_size)

    tok = tokenizer_for_model(model_name)
    tb = tok.token_bytes()
    sample = '{"value": 17, "public_reasoning": "readiness probe"}'
    ids = tok.encode(sample)
    assert ids, "tokenizer produced no ids"
    assert b"".join(tb[i] for i in ids) == sample.encode("utf-8")

    if full_load:
        import jax.numpy as jnp

        from bcg_tpu.models.loader import load_checkpoint_params

        params = load_checkpoint_params(spec, model_name, ckpt_dir=found)
        assert len(params["layers"]) == spec.num_layers
        assert params["embed"].shape == (spec.vocab_size, spec.hidden_size)
        assert params["embed"].dtype == jnp.bfloat16


# ------------------------------------------------------------- hermetic


def test_readiness_routine_green_on_hf_fixture(tmp_path, monkeypatch):
    """The readiness check itself, proven against the genuine HF
    artifact layout — so the gated real-weights arm below can never rot
    unexercised."""
    from bcg_tpu.models.hf_fixture import build_checkpoint

    name = "bcg-hf/tiny"
    out = build_checkpoint(
        name, out_dir=str(tmp_path / "bcg-hf--tiny")
    )
    monkeypatch.setenv("BCG_TPU_CHECKPOINT_DIR", os.path.dirname(out))
    _readiness_check(name, out, full_load=True)


# ---------------------------------------------------------------- gated


def _discover_real_checkpoint():
    """(model_name, dir) for the first registered REAL model preset
    with local safetensors — the bcg-tpu/bcg-hf synthetic families
    don't count as real weights."""
    for preset, name in sorted(MODEL_PRESETS.items()):
        if name.startswith("bcg-"):
            continue
        found = find_checkpoint_dir(name)
        if found is not None:
            return name, found
    return None, None


def test_real_checkpoint_boots_loader_end_to_end():
    """GATED: skipped unless a real local checkpoint exists (set
    BCG_TPU_CHECKPOINT_DIR on a weights-bearing host).  Runs the full
    readiness routine on the real safetensors; the whole-tree load
    engages below the RAM ceiling, inventory/tokenizer checks always."""
    name, ckpt_dir = _discover_real_checkpoint()
    if name is None:
        pytest.skip(
            "no local real-model checkpoint (set BCG_TPU_CHECKPOINT_DIR "
            "to a dir of HF safetensors to enable)"
        )
    total_bytes = sum(
        os.path.getsize(os.path.join(ckpt_dir, f))
        for f in os.listdir(ckpt_dir) if f.endswith(".safetensors")
    )
    _readiness_check(
        name, ckpt_dir, full_load=total_bytes <= _FULL_LOAD_CEILING_BYTES
    )
