"""Phase-level profiler (runtime/profiler.py) — the timing
instrumentation the reference lacks entirely (SURVEY.md §5.1)."""

import time

from bcg_tpu.runtime.profiler import SimulationProfiler, jax_trace


def test_phase_accumulation_and_summary():
    prof = SimulationProfiler()
    with prof.phase("decide"):
        time.sleep(0.01)
    with prof.phase("decide"):
        time.sleep(0.01)
    with prof.phase("vote"):
        time.sleep(0.005)
    prof.count_round(num_decisions=8)
    prof.count_round(num_decisions=8)

    s = prof.summary()
    assert s["rounds"] == 2
    assert s["decisions"] == 16
    assert s["phase_counts"]["decide"] == 2
    assert s["phase_counts"]["vote"] == 1
    assert s["phase_seconds"]["decide"] >= 0.02
    assert s["phase_seconds"]["vote"] >= 0.005
    assert s["total_seconds"] >= s["phase_seconds"]["decide"]
    assert s["decisions_per_sec"] > 0


def test_phase_records_time_on_exception():
    prof = SimulationProfiler()
    try:
        with prof.phase("broken"):
            time.sleep(0.005)
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert prof.phase_counts["broken"] == 1
    assert prof.phase_seconds["broken"] >= 0.005


def test_jax_trace_no_dir_is_passthrough():
    ran = False
    with jax_trace(None):
        ran = True
    assert ran


def test_jax_trace_writes_profile(tmp_path):
    import jax
    import jax.numpy as jnp

    log_dir = str(tmp_path / "trace")
    with jax_trace(log_dir):
        (jnp.ones((4, 4)) @ jnp.ones((4, 4))).block_until_ready()
    import os

    found = []
    for root, _dirs, files in os.walk(log_dir):
        found.extend(files)
    assert found, "jax.profiler produced no trace files"
