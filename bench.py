#!/usr/bin/env python
"""BCG benchmark — one JSON line for the driver.

Runs the Byzantine Consensus Game (8 honest + 2 Byzantine, the Q2
resilience config from BASELINE.json) end-to-end on the real accelerator:
JAX engine, random-weight ``bcg-tpu/bench-1b`` model (full 151936-token
Qwen3 vocabulary so guided-decode masking and sampling cost are
realistic), schema-guided JSON decoding for every decision and vote.

Headline metric: **agent-decisions/sec** — LLM-generated agent actions
(decide + vote calls) per wall-clock second, measured over post-warmup
rounds so one-time XLA compilation is excluded (the reference's engine
boot is likewise excluded from its steady-state throughput).

``vs_baseline``: the reference publishes no numbers (SURVEY.md §6), so
the denominator is DERIVED, not measured: an HBM-bandwidth roofline of
the reference's own stack (vLLM bf16 decode on one A100-80GB) at its
own config (``max_num_seqs: 4`` [reference config.py:38], ~300-token
guided decisions [config.py:55]), evaluated at the SAME parameter count
as the model this bench actually ran.  The efficiency assumption is
generous to the reference (prefill, sampling and guided-JSON masking
charged at zero cost), so the denominator is an upper bound on the
reference's rate and ``vs_baseline`` a lower bound on the speedup.
Sources + arithmetic: BASELINE.md appendix A.  The absolute ``value``
remains the number to track round over round.

This script NEVER exits non-zero for a run-time failure: every outcome —
including transient tunnel/remote-compile flakes (retried once) — is
reported as a JSON line, with ``value: 0`` and an ``error`` field on
failure (a bare rc=1 cost round 2 its recorded number).

Env overrides: BENCH_ROUNDS (measured rounds, default 3),
BENCH_MODEL (spec name), BENCH_BACKEND=fake for a hermetic smoke run,
BENCH_QUANTIZATION (default int8 — measured fastest WITH fast-forward:
3.34 dec/s vs 3.22 bf16+ff vs 3.00 bf16 plain vs 2.27 int8 plain on
the single-chip bench, 2026-07-30; set ``bfloat16``/``none`` for
full-precision parity runs), BENCH_KV_DTYPE (default bfloat16 below the
6B-parameter size class, int8 at/above it), BENCH_FAST_FORWARD /
BENCH_COMPACT_JSON (default ON — forced-chain fast-forward decoding
and whitespace-free generation grammar; set 0 to disable; composes
with BENCH_KV_DTYPE=int8 via the Pallas chunk decode kernel),
BENCH_CONCURRENCY (G concurrent games merged into shared device
batches per phase; decisions/sec then counts all G games),
BENCH_PREFIX_CACHING (0 to disable cached prefix KV for models whose
weights leave no room), BENCH_SHARED_CORE (1 to enable vote-phase
shared-core prompt caching — opt-in because its prompt text diverges
from the reference's vote format), BENCH_PROFILE_DIR (capture a
jax.profiler trace of the measured window; real backends only),
BENCH_FORCE_CPU (1 = run the real jax path on the host CPU in-process
— the hermetic flag-stack smoke tests/test_bench_cpu_stack.py uses).
The emitted JSON labels every knob.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
import traceback

from bcg_tpu.runtime import envflags

# --- Reference baseline denominator (BASELINE.md appendix A) ---------
# Decode at batch 4 is weight-streaming-bound, so the reference's
# steady-state rate on its own hardware is bounded by
#   steps/s = HBM_GB/s * efficiency / weight_bytes
#   dec/s   = steps/s * max_num_seqs / decision_tokens
# A100-80GB HBM2e = 1935 GB/s (NVIDIA A100 datasheet).  0.75 of spec
# bandwidth is at the TOP of what vLLM's decode achieves at batch 4,
# and prefill/sampling/guided-masking are charged at zero cost — both
# choices favor the reference, making vs_baseline a lower bound.
A100_HBM_GBPS = 1935.0
A100_DECODE_EFFICIENCY = 0.75
REFERENCE_MAX_NUM_SEQS = 4        # /root/reference/.../config.py:38
REFERENCE_DECISION_TOKENS = 300   # /root/reference/.../config.py:55


def reference_a100_decisions_per_sec(spec) -> float:
    """Roofline upper bound of the reference's decisions/sec on one
    A100-80GB for a bf16 model with this bench's spec (the reference
    serves unquantized checkpoints, vllm_agent.py).  Only the bytes a
    decode step actually STREAMS count: the input-embedding table is a
    one-row gather, so an untied table is excluded — including it would
    lower the denominator and break the upper-bound property.  (A tied
    table is already streamed once as the LM head.)"""
    streamed = spec.param_count - (
        0 if spec.tie_embeddings else spec.vocab_size * spec.hidden_size
    )
    weight_bytes = 2.0 * streamed
    steps_per_sec = (
        A100_HBM_GBPS * 1e9 * A100_DECODE_EFFICIENCY / weight_bytes
    )
    return REFERENCE_MAX_NUM_SEQS * steps_per_sec / REFERENCE_DECISION_TOKENS

# Size-class threshold shared with the engine's int8-KV warning
# (bcg_tpu.models.configs.LARGE_MODEL_PARAMS); derived from the spec's
# parameter count, not the model-name string (VERDICT round-2 weak #6).

# Substrings that mark an exception as a transient environment failure
# (axon tunnel / remote-compile helper dying mid-run) worth one retry.
# Deterministic failures (OOM, lowering errors, bugs) must NOT retry:
# they would double a long failure and report the same error anyway.
_TRANSIENT_MARKERS = (
    "remote_compile",
    "response body",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Connection reset",
    "Socket closed",
    "Broken pipe",
    "transport",
)


def _env_flag(name: str, default: bool) -> bool:
    return envflags.get_bool(name, default)


def _progress(msg: str) -> None:
    """Stage stamp on stderr (stdout stays the driver's single JSON line).

    The axon tunnel can hang for tens of minutes mid-run; a silent bench
    is undiagnosable after the fact (round-4 opener: 25 min of nothing,
    then a timeout with no indication whether boot, compile, warmup, or
    the measured window died).  These stamps name the last stage reached.
    """
    sys.stderr.write(f"bench[{time.strftime('%H:%M:%S')}]: {msg}\n")
    sys.stderr.flush()


def _is_transient(exc: BaseException) -> bool:
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _TRANSIENT_MARKERS)


# Env overrides that change the SERVED configuration.  The watcher's
# results/hw_r*/bench_default.json is only a same-config citation for a
# run with none of these set — a failed BENCH_MODEL=bcg-tpu/bench-14b
# run once risked embedding the default-config number labeled as
# same-config (ADVICE round-5 low; provenance in the permanent record).
# Measurement-window knobs (BENCH_ROUNDS/WARMUP/ATTACH_TIMEOUT/
# PROFILE_DIR) don't change the config and stay out of this list.
# BCG_TPU_* operational flags that change the served path (kernel
# kill-switches, ladder/precision A/B knobs) count as overrides too.
_CONFIG_OVERRIDE_ENVS = (
    "BENCH_MODEL", "BENCH_BACKEND", "BENCH_QUANTIZATION", "BENCH_KV_DTYPE",
    "BENCH_FAST_FORWARD", "BENCH_COMPACT_JSON", "BENCH_PREFIX_CACHING",
    "BENCH_SHARED_CORE", "BENCH_PREFILL_CHUNK", "BENCH_SCAN_LAYERS",
    "BENCH_ATTENTION_IMPL", "BENCH_CONCURRENCY", "BENCH_FORCE_CPU",
    "BENCH_SERVE", "BENCH_SPEC",
    "BCG_TPU_DISABLE_INT8_DECODE_KERNEL", "BCG_TPU_DISABLE_W4_KERNEL",
    "BCG_TPU_ALLOW_PADDED_GROUP_KERNEL", "BCG_TPU_FINE_SUFFIX",
    "BCG_TPU_W8A16_PREFILL",
    "BCG_TPU_SPEC", "BCG_TPU_SPEC_K", "BCG_TPU_SPEC_NGRAM",
    "BCG_TPU_FUSED_SAMPLER", "BCG_TPU_KV_DTYPE",
    "BCG_TPU_PAGED_KV", "BCG_TPU_KV_BLOCK_SIZE", "BCG_TPU_KV_POOL_BLOCKS",
    "BCG_TPU_PAGED_KV_IMPL", "BCG_TPU_PAGED_PAGES_PER_PROGRAM",
    "BCG_TPU_GAME_EVENTS", "BCG_TPU_SERVE_SLO_MS",
    "BCG_TPU_FLEET", "BCG_TPU_METRICS_SHARD_DIR",
    "BCG_TPU_FLEET_STRAGGLER_FACTOR", "BCG_TPU_HOSTSYNC",
    "BCG_TPU_COMPILE_OBS", "BCG_TPU_PROFILE", "BCG_TPU_PROFILE_ROUNDS",
    "BCG_TPU_SWEEP_MAX_CONCURRENT", "BCG_TPU_SWEEP_TENANT_QUOTA_ROWS",
    # Resilience tier: injected faults corrupt/crash the measured
    # window, and retry/watchdog budgets change how (and whether) it
    # recovers — none of these may be recorded as default-config runs.
    "BCG_TPU_CHAOS", "BCG_TPU_FAULT_RATE", "BCG_TPU_FAULT_SEED",
    "BCG_TPU_SERVE_MAX_DISPATCH_RETRIES", "BCG_TPU_SERVE_WATCHDOG_S",
    "BCG_TPU_SERVE_DEFER_WAIT_S", "BCG_TPU_SWEEP_MAX_JOB_RETRIES",
    # The fused mega-round replaces the lockstep decide/exchange/vote
    # host loop with one jit entry per round — a different measured
    # execution shape, so a megaround run is never a default-config row.
    "BCG_TPU_MEGAROUND",
    # A scenario overlay rewrites the game shape, adversary strategy,
    # topology, and channel — a registry-driven run measures a
    # different game than the default config.
    "BCG_TPU_SCENARIO",
    # Alerting plane: the evaluator thread snapshots the registry every
    # BCG_TPU_ALERT_MS inside the measured window (in-window overhead,
    # like BCG_TPU_PROFILE), and the JSONL sink adds a drainer thread —
    # an alerting run is not a default-config number.  BCG_TPU_ALERT_MS
    # itself stays out: a period knob on an already-declared override,
    # same reasoning as BCG_TPU_METRICS_SHARD_MS.
    "BCG_TPU_ALERTS", "BCG_TPU_ALERT_EVENTS",
    # BCG_TPU_RUN_ID / BCG_TPU_METRICS_SHARD_MS stay out: a run label
    # and a flush period are provenance/measurement knobs, not a change
    # to the served configuration.  BCG_TPU_SWEEP_DIR stays out for the
    # same reason (an output path); the two sweep knobs above are IN —
    # tenant concurrency and quotas change how a measured serving
    # window batches.  BCG_TPU_PROFILE* are IN despite
    # being measurement knobs: an in-window jax.profiler capture
    # perturbs the measured wall-clock, so a profiled run must not be
    # recorded as the default-config number.
)


def _serve_stats_or_none():
    """Latest serving-scheduler snapshot when BENCH_SERVE ran the
    window through bcg_tpu/serve; None on the collective path."""
    if not envflags.get_bool("BENCH_SERVE"):
        return None
    from bcg_tpu.runtime import metrics as _metrics

    return _metrics.LAST_SERVE_STATS


def _spec_stats_or_none():
    """Speculative-decoding counters + acceptance rate when the window
    drafted anything (BCG_TPU_SPEC / BENCH_SPEC); None otherwise.
    Attached on success AND error — same idiom as serve_stats: a
    mid-wave crash must not lose the acceptance profile the completed
    calls already recorded."""
    try:
        from bcg_tpu.obs import counters as _counters

        drafted = _counters.value("engine.spec.drafted")
        if not drafted:
            return None
        accepted = _counters.value("engine.spec.accepted")
        return {
            "drafted": drafted,
            "accepted": accepted,
            "rejected": _counters.value("engine.spec.rejected"),
            "acceptance_rate": round(accepted / drafted, 4),
        }
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _kv_pool_stats_or_none():
    """Latest paged KV-pool snapshot (block headroom, radix hit rate,
    the ACTIVE paged-attention impl + kernel knobs) published by the
    engine after each paged call; None on dense engines.  Read from
    runtime.metrics (not the engine object) so the ERROR path — where
    no engine handle survives — keeps the pool forensics too."""
    try:
        from bcg_tpu.runtime import metrics as _metrics

        return _metrics.LAST_KV_POOL
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _sampler_stats_or_none():
    """Latest guided-sampler self-description (resolved impl, interpret
    mode, fused-kernel invocation count, resolved KV dtype) published
    by the engine at boot and per call; None before any engine booted.
    Read from runtime.metrics (not the engine object) so the ERROR
    path — where no engine handle survives — still says which
    sampler/KV configuration the failed run actually served, making
    hardware A/B runs of both ISSUE-10 features self-describing in
    results/."""
    try:
        from bcg_tpu.runtime import metrics as _metrics

        return _metrics.LAST_SAMPLER
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _game_stats_or_none():
    """Cumulative game-telemetry summary (games converged, rounds,
    byzantine adoptions, event-sink drops) when BCG_TPU_GAME_EVENTS
    recorded anything; None otherwise.  Read from runtime.metrics (not
    a recorder object) so the ERROR path — where no simulation handle
    survives — keeps the consensus profile too."""
    try:
        from bcg_tpu.runtime import metrics as _metrics

        return _metrics.LAST_GAME_STATS
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _hostsync_stats_or_none():
    """Host-sync auditor summary (syncs per phase site, syncs/round,
    top attribution spans) when BCG_TPU_HOSTSYNC audited the window;
    None otherwise.  Read from runtime.metrics (not the auditor object)
    so the ERROR path — where no engine handle survives — keeps the
    sync profile the completed calls already published."""
    try:
        from bcg_tpu.runtime import metrics as _metrics

        return _metrics.LAST_HOSTSYNC
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _megaround_stats_or_none():
    """Fused mega-round summary (fused_rounds, syncs_per_round — 1.0 by
    construction, rounds_per_sec) when the BCG_TPU_MEGAROUND path ran
    any fused rounds; None otherwise.  Read from runtime.metrics (not
    the engine object) so the ERROR path — where no engine handle
    survives — keeps the profile the completed fused rounds already
    published."""
    try:
        from bcg_tpu.runtime import metrics as _metrics

        return _metrics.LAST_MEGAROUND
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _compile_stats_or_none():
    """Compile-cost summary (per-entry compile_ms totals, first-compile
    vs retrace split, cache-entry population, retrace-cause records)
    when BCG_TPU_COMPILE_OBS observed the window; None otherwise.  Read
    from runtime.metrics (not the observer object) so the ERROR path —
    where no engine handle survives — keeps the compile profile; a
    first-compile death is exactly when it matters."""
    try:
        from bcg_tpu.runtime import metrics as _metrics

        return _metrics.LAST_COMPILE_OBS
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _alerts_stats_or_none():
    """Alert-engine verdict for the window (rules evaluated, fired/
    resolved transition counts, flaps, currently-firing rules) when
    BCG_TPU_ALERTS evaluated it; None otherwise.  Read from
    runtime.metrics (not the engine object) so the ERROR path — where
    no engine handle survives — keeps the last published verdict: a
    crash with `engine_errors` firing is the whole point of the plane."""
    try:
        from bcg_tpu.runtime import metrics as _metrics

        return _metrics.LAST_ALERTS
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _fault_stats_or_none():
    """Fault-injection self-description: FaultInjectingEngine's
    corruption count (engine.faults.injected — the registry twin of its
    `.injected` attribute, which alone is invisible to /metrics and
    this JSON) with the rate/seed in effect, plus the chaos injector's
    per-seam counts when BCG_TPU_CHAOS ran (runtime/resilience.py).
    Attached on success AND error paths — a resilience experiment's
    result line must say which faults actually fired, especially when
    the run died."""
    try:
        from bcg_tpu.obs import counters as _counters
        from bcg_tpu.runtime import resilience as _resilience

        injected = _counters.value("engine.faults.injected")
        chaos = _resilience.stats()
        if not injected and not chaos:
            return None
        out = {"injected": injected}
        raw_rate = envflags.get_str("BCG_TPU_FAULT_RATE")
        if raw_rate:
            out["rate"] = float(raw_rate)
            out["seed"] = envflags.get_int("BCG_TPU_FAULT_SEED")
        if chaos:
            out["chaos"] = chaos
        return out
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _fleet_stats_or_none():
    """Fleet identity block (run id, rank, host, shard path, heartbeat
    age, straggler count) when fleet stamping is on (BCG_TPU_FLEET /
    shard dir / multi-process group); None single-process.  Attached on
    success AND error paths — a rank that dies mid-sweep must leave a
    bench line that says WHICH rank it was and whether its peers had
    already flagged it lagging."""
    try:
        from bcg_tpu.obs import fleet as _fleet

        return _fleet.summary()
    except Exception:
        # Inside the never-rc=1 contract (see _obs_payload).
        return None


def _obs_payload() -> dict:
    """Observability attachments for the bench JSON — counters always
    (compile/retrace accounting, serve linger buckets, engine.hlo.* /
    hbm.* gauges), span summary when tracing ran (BCG_TPU_TRACE), plus
    the structured HBM-ledger and HLO-census views when they carry
    anything.  Attached on success AND error: a failed run's counters
    are exactly the forensics a mid-wave crash otherwise loses."""
    out = {}
    try:
        from bcg_tpu.obs import counters as _counters, tracer as _tracer

        snap = _counters.snapshot()
        if snap:
            out["counters"] = snap
        summary = _tracer.summarize()
        if summary:
            out["span_summary"] = summary
    except Exception:
        # Inside the never-rc=1 contract: observability must not be able
        # to take the result line down with it.
        pass
    try:
        from bcg_tpu.obs import hlo as _hlo, ledger as _ledger

        led = _ledger.snapshot()
        if led.get("total_bytes"):
            out["hbm_ledger"] = led
        census = _hlo.snapshot()
        if census:
            out["hlo_census"] = census
    except Exception:
        # Same never-rc=1 contract as above.
        pass
    return out


def _is_default_config() -> bool:
    return not any(envflags.is_set(v) for v in _CONFIG_OVERRIDE_ENVS)


def _error_result(exc: BaseException, retried: bool) -> dict:
    tb = traceback.format_exception(type(exc), exc, exc.__traceback__)
    out = {
        "metric": "agent_decisions_per_sec",
        "value": 0.0,
        "unit": "decisions/sec",
        # null, not 0.0: an outage measured NOTHING — recording it as
        # "0% of baseline" poisoned the BENCH_r02-r05 trajectory, where
        # accelerator-attach failures read as catastrophic regressions.
        "vs_baseline": None,
        "error": f"{type(exc).__name__}: {str(exc)[:400]}"
                 + ("; failed again after one retry" if retried
                    else "; not retried (non-transient)"),
        "traceback_tail": "".join(tb)[-1000:],
    }
    out.update(_obs_payload())
    # Serving profile of the failed attempt: only boot phases used to
    # survive a failed run — a mid-wave crash lost the scheduler stats
    # the wave had already published to LAST_SERVE_STATS.
    try:
        serve_stats = _serve_stats_or_none()
        if serve_stats:
            out["serve_stats"] = serve_stats
    except Exception:
        pass
    spec_stats = _spec_stats_or_none()
    if spec_stats:
        out["spec_stats"] = spec_stats
    # Paged-pool snapshot of the failed attempt (incl. which attention
    # impl served it) — same mid-crash-forensics idiom as serve_stats.
    kv_pool = _kv_pool_stats_or_none()
    if kv_pool:
        out["kv_pool"] = kv_pool
    # Sampler/KV-dtype self-description of the failed attempt (published
    # at engine BOOT, so even a first-compile death reports which
    # configuration it was) — same idiom.
    sampler = _sampler_stats_or_none()
    if sampler:
        out["sampler"] = sampler
    # Consensus-game telemetry of the failed attempt (games converged
    # before the crash, byzantine adoptions, event-sink drops) — same
    # mid-crash-forensics idiom as serve_stats/kv_pool.
    game_stats = _game_stats_or_none()
    if game_stats:
        out["game_stats"] = game_stats
    # Host-sync profile of the failed attempt (syncs per site,
    # syncs/round, attribution spans) — same mid-crash-forensics idiom
    # as serve_stats/kv_pool.
    hostsync_stats = _hostsync_stats_or_none()
    if hostsync_stats:
        out["hostsync"] = hostsync_stats
    # Fused mega-round profile of the failed attempt (fused rounds,
    # syncs/round, rounds/sec) — a fused-path crash must still show how
    # many rounds fused before it died.
    megaround_stats = _megaround_stats_or_none()
    if megaround_stats:
        out["megaround"] = megaround_stats
    # Compile-cost profile of the failed attempt (which entries
    # compiled, how long, what retraced and WHY) — the forensics a
    # first-compile OOM or a retrace storm otherwise loses.
    compile_stats = _compile_stats_or_none()
    if compile_stats:
        out["compile"] = compile_stats
    # Fleet identity of the failed attempt (which rank, which shard
    # file, heartbeat age at death) — the line a multi-host sweep's
    # post-mortem greps for.
    fleet_stats = _fleet_stats_or_none()
    if fleet_stats:
        out["fleet"] = fleet_stats
    # Fault-injection profile of the failed attempt (corrupted
    # responses, chaos seams fired): a resilience experiment that died
    # must still say which faults it had injected by then.
    fault_stats = _fault_stats_or_none()
    if fault_stats:
        out["faults"] = fault_stats
    # Alerting verdict of the failed attempt (what fired before the
    # death, what never resolved) — the timeline a post-mortem starts
    # from.
    alerts_stats = _alerts_stats_or_none()
    if alerts_stats:
        out["alerts"] = alerts_stats
    # Boot-phase breakdown of the failed attempt (engine boots record
    # into runtime.metrics.LAST_BOOT_PHASES even when construction
    # dies mid-phase): a RESOURCE_EXHAUSTED error line now names the
    # phase — init / quantize / stack / first compile — it died in.
    try:
        from bcg_tpu.runtime import metrics as _metrics

        if _metrics.LAST_BOOT_PHASES:
            out["boot_phases"] = _metrics.LAST_BOOT_PHASES
    except Exception:
        pass
    # Honesty + provenance on outage: `value` stays 0.0 (this run
    # measured nothing), but if the hardware-recovery watcher recorded a
    # same-config result EARLIER (results/hw_r*/bench_default.json), cite
    # it so a tunnel outage at the driver's bench minute doesn't erase
    # the round's actual measured number from the record.  Only when
    # this run IS the default config: the watcher file is the default
    # arm, and an overridden run (BENCH_MODEL/BENCH_KV_DTYPE/...) must
    # not embed another config's number as "same-config".
    try:
        import glob as _glob

        rounds = [
            d for d in _glob.glob("results/hw_r*")
            if os.path.isdir(d) and d.rsplit("hw_r", 1)[1].isdigit()
        ] if _is_default_config() else []
        if rounds:
            newest = max(rounds, key=lambda d: int(d.rsplit("hw_r", 1)[1]))
            path = os.path.join(newest, "bench_default.json")
            if os.path.exists(os.path.join(newest, "bench_default.done")):
                with open(path) as f:
                    prior = json.loads(f.read().strip().splitlines()[-1])
                if prior.get("value"):
                    out["watcher_recorded_this_round"] = {
                        "note": "NOT this run's measurement — same-config "
                                "result recorded by scripts/hw_watcher.sh "
                                "earlier this round, cited because this "
                                "run could not attach the accelerator",
                        "source": path,
                        "value": prior["value"],
                        "unit": prior.get("unit"),
                        "vs_baseline": prior.get("vs_baseline"),
                    }
    except Exception:
        pass
    return out


# Engines built by attempts that later FAILED: the retry must free their
# device state before building a second engine (see _teardown_live_engines;
# an un-torn-down 14B first attempt OOM'd the retry's init on 2026-08-01).
_LIVE_ENGINES: list = []


def _teardown_live_engines() -> None:
    """Free a failed attempt's device state (weights, prefix KV, cached
    decode loops) and WAIT for the allocator to reflect it.  On the
    remote-attached chip frees complete asynchronously — an immediate
    rebuild of an 8B/14B engine races them into RESOURCE_EXHAUSTED even
    after the host-side references are gone."""
    import gc

    while _LIVE_ENGINES:
        eng = _LIVE_ENGINES.pop()
        try:
            eng.shutdown()
        except Exception:
            pass
    gc.collect()
    try:
        import jax

        dev = jax.devices()[0]
        limit = (dev.memory_stats() or {}).get("bytes_limit")
    except Exception:
        return
    if not limit:
        return
    # monotonic, not time.time(): this is a duration wait, and the wall
    # clock can step under NTP (BCG-TIME-WALL).
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        try:
            used = (dev.memory_stats() or {}).get("bytes_in_use", 0)
        except Exception:
            return
        if used < 0.2 * limit:
            return
        time.sleep(3)
    _progress("teardown wait expired with device memory still high "
              "(retry may OOM)")


def _run_attempt(cfg, model: str, backend: str, concurrency: int,
                 warmup_rounds: int, measured_rounds: int) -> dict:
    """One full bench attempt: build sim, warm up, measure, return the
    result JSON dict (which may be a guard-error dict).  Raises on any
    engine/runtime failure — the caller decides whether to retry."""
    from bcg_tpu.runtime.orchestrator import BCGSimulation

    t_boot0 = time.perf_counter()
    first_round_s = None  # boot + compile + first full round (cold cost)
    _progress("building engine + weights (BCGSimulation)")
    sim = BCGSimulation(config=cfg)
    _progress(f"engine built in {time.perf_counter() - t_boot0:.1f}s")
    n_agents = cfg.game.num_honest + cfg.game.num_byzantine
    engine = sim.engine  # reuse across games: compiled loops persist
    _LIVE_ENGINES.append(engine)

    if backend == "fake":
        platform = "none"  # fake engine never touches a device
    else:
        import jax

        platform = jax.devices()[0].platform

    def fresh_sim(seed):
        return BCGSimulation(
            config=dataclasses.replace(
                cfg, game=dataclasses.replace(cfg.game, seed=seed)
            ),
            engine=engine,
        )

    # BENCH_CONCURRENCY=G batches G lockstep games into shared device
    # batches per phase (engine/collective.py): decode streams the whole
    # model per step regardless of rows, so G concurrent games cost far
    # less than G sequential runs.  Each round is a thread wave over a
    # fresh CollectiveEngine; terminated games are replaced BETWEEN waves
    # so the merged batch stays G * agents rows (stable compiled shapes).
    # BENCH_SERVE=1 routes the same window through the arrival-driven
    # serving scheduler (bcg_tpu/serve) instead: no barrier, batches form
    # on bucket-fill/linger, scheduler stats land in the bench JSON.
    bench_serve = envflags.get_bool("BENCH_SERVE")

    def run_wave(sims) -> None:
        def make(s):
            def go(proxy):
                s.set_engine(proxy)
                try:
                    s.run_round()
                finally:
                    s.set_engine(engine)
            return go

        if bench_serve:
            from bcg_tpu.serve import run_serving_simulations

            outs = run_serving_simulations(
                engine, [make(s) for s in sims]
            )
        else:
            from bcg_tpu.engine.collective import run_concurrent_simulations

            outs = run_concurrent_simulations(
                engine, [make(s) for s in sims], len(sims)
            )
        for o in outs:
            if isinstance(o, BaseException):
                raise o

    warm_seed = 1000
    seed = 1

    def _counters():
        return (
            getattr(engine, "total_decode_steps", 0),
            getattr(engine, "total_rows", 0),
            getattr(engine, "failed_rows", 0),
            getattr(engine, "prefill_tokens", 0),
            getattr(engine, "prefill_seconds", 0.0),
            getattr(engine, "decode_seconds", 0.0),
            getattr(engine, "decode_kv_bytes", 0),
            getattr(engine, "decode_weight_passes", 0),
        )
    if concurrency > 1:
        sims = [fresh_sim(warm_seed + i) for i in range(concurrency)]

        def replace_done(sims, next_seed):
            out = []
            for s in sims:
                if s.game.game_over:
                    out.append(fresh_sim(next_seed))
                    next_seed += 1
                else:
                    out.append(s)
            return out, next_seed

        warmed, saw_round2 = 0, False
        while warmed < warmup_rounds or not saw_round2:
            run_wave(sims)
            if first_round_s is None:
                first_round_s = time.perf_counter() - t_boot0
            warmed += 1
            _progress(f"warmup wave {warmed} done "
                      f"(+{time.perf_counter() - t_boot0:.1f}s)")
            saw_round2 = saw_round2 or any(
                len(s.game.rounds) >= 2 for s in sims
            )
            sims, seed = replace_done(sims, seed)
            if warmed >= warmup_rounds + 6:
                break

        from bcg_tpu.runtime.profiler import jax_trace

        waves = 0
        w0 = _counters()
        t0 = time.perf_counter()
        prof_dir = envflags.get_str("BENCH_PROFILE_DIR") if backend != "fake" else None
        _progress("measured window start")
        with jax_trace(prof_dir):
            while waves < measured_rounds:
                # Replace at the TOP (like the single-game path): the
                # final wave's terminations aren't pointlessly rebuilt
                # on the clock.
                sims, seed = replace_done(sims, seed)
                run_wave(sims)
                waves += 1
                _progress(f"measured wave {waves}/{measured_rounds}")
        elapsed = time.perf_counter() - t0
        rounds_done = waves * concurrency
    else:
        # Warmup: round 1 pays XLA compilation for the initial shapes; a
        # round >= 2 covers the history-grown prompt bucket.  Terminated
        # games are replaced, and warmup keeps going until a round >= 2
        # has actually run (a replacement game restarts at round 1), so
        # the measured window is compile-free.
        warmed = 0
        saw_round2 = False
        while warmed < warmup_rounds or not saw_round2:
            if sim.game.game_over:
                sim = fresh_sim(warm_seed)
                warm_seed += 1
            sim.run_round()
            if first_round_s is None:
                first_round_s = time.perf_counter() - t_boot0
            warmed += 1
            _progress(f"warmup round {warmed} done "
                      f"(+{time.perf_counter() - t_boot0:.1f}s)")
            saw_round2 = saw_round2 or len(sim.game.rounds) >= 2
            if warmed >= warmup_rounds + 6:  # pathological termination streak
                break

        # A game may terminate at any round (random-weight votes are
        # correlated); keep starting fresh games until N rounds are
        # measured.
        from bcg_tpu.runtime.profiler import jax_trace

        rounds_done = 0
        w0 = _counters()
        t0 = time.perf_counter()
        # BENCH_PROFILE_DIR=<dir>: capture a jax.profiler trace of the
        # measured window (device timeline per op — the prefill-MFU
        # attribution the microbench cannot see inside fused programs).
        # Real backends only: start_trace initializes the default
        # backend, which on the fake path would attach the (possibly
        # dead) tunnel a fake bench never needs.
        prof_dir = envflags.get_str("BENCH_PROFILE_DIR") if backend != "fake" else None
        _progress("measured window start")
        with jax_trace(prof_dir):
            while rounds_done < measured_rounds:
                if sim.game.game_over:
                    sim = fresh_sim(seed)  # no engine re-init, no compile
                    seed += 1
                sim.run_round()
                rounds_done += 1
                _progress(f"measured round {rounds_done}/{measured_rounds}")
        elapsed = time.perf_counter() - t0

    # Sanity: a real engine must actually have DECODED across the WHOLE
    # measured window, not just the final call.  When LLM calls error out,
    # agents silently abstain and rounds finish in milliseconds — a broad
    # exception-to-error-dict path once turned a Pallas lowering bug into
    # a 6x-too-good number here.  Refuse to report a throughput whose
    # window never (or mostly never) ran the model.
    w1 = _counters()
    window_steps = w1[0] - w0[0]
    window_rows = w1[1] - w0[1]
    window_failed = w1[2] - w0[2]
    failed_fraction = window_failed / window_rows if window_rows else 0.0
    if backend != "fake" and window_steps <= 0:
        out = {
            "metric": "agent_decisions_per_sec",
            "value": 0.0,
            "unit": "decisions/sec",
            "vs_baseline": None,  # measured nothing (see _error_result)
            "error": "engine produced no decode steps during the measured "
                     "window - every LLM call failed; see run logs",
        }
        out.update(_obs_payload())
        return out
    if backend != "fake" and failed_fraction > 0.5:
        out = {
            "metric": "agent_decisions_per_sec",
            "value": 0.0,
            "unit": "decisions/sec",
            "vs_baseline": None,  # measured nothing (see _error_result)
            "error": f"{failed_fraction:.0%} of generation rows in the "
                     "measured window returned error dicts - throughput "
                     "would mostly measure instant failures; see run logs",
        }
        out.update(_obs_payload())
        return out

    # decide + vote are each one guided LLM generation per agent per round.
    decisions = 2 * n_agents * rounds_done
    decisions_per_sec = decisions / elapsed

    # Achieved bandwidth / MFU over the measured window (VERDICT round-1
    # weak #5: the bench JSON itself must carry utilization, not leave it
    # to back-of-envelope).  v5e chip peaks; decode traffic = one full
    # weight pass per loop iteration + the allocated KV window per step
    # (engine accounting, jax_engine._decode_batch).
    V5E_HBM_GBPS = 819.0
    V5E_BF16_TFLOPS = 197.0
    V5E_INT8_TFLOPS = 394.0
    perf = {}
    if backend != "fake":
        dp_tokens = w1[3] - w0[3]
        dp_secs = w1[4] - w0[4]
        dc_secs = w1[5] - w0[5]
        dc_kv = w1[6] - w0[6]
        dc_passes = w1[7] - w0[7]
        spec = engine.spec
        matmul_params = spec.num_layers * spec.matmul_params_per_layer
        param_bytes = getattr(engine, "_param_bytes", 0)
        peak_tflops = (
            V5E_INT8_TFLOPS if cfg.engine.quantization == "int8"
            else V5E_BF16_TFLOPS
        )
        if dp_secs > 0 and dp_tokens:
            prefill_tflops = 2 * matmul_params * dp_tokens / dp_secs / 1e12
            perf["prefill_mfu"] = round(prefill_tflops / peak_tflops, 4)
            perf["prefill_tflops"] = round(prefill_tflops, 2)
            perf["prefill_tokens"] = dp_tokens
            perf["prefill_seconds"] = round(dp_secs, 2)
        if dc_secs > 0 and dc_passes:
            decode_bytes = dc_kv + dc_passes * param_bytes
            gbps = decode_bytes / dc_secs / 1e9
            perf["decode_gbps"] = round(gbps, 1)
            perf["decode_hbm_util"] = round(gbps / V5E_HBM_GBPS, 4)
            perf["decode_seconds"] = round(dc_secs, 2)
            # ~rows per loop iteration = agents x concurrent games
            # (retry sub-batches are smaller; this is an upper-ish bound).
            perf["decode_tok_per_sec"] = round(
                window_steps * n_agents * concurrency / dc_secs, 1
            )
        perf["prefix_fallbacks"] = getattr(engine, "prefix_fallbacks", 0)

    from bcg_tpu.models.configs import spec_for_model

    bench_spec = spec_for_model(model)
    baseline_dps = (
        reference_a100_decisions_per_sec(bench_spec)
        if bench_spec is not None else None
    )
    result = {
        "metric": "agent_decisions_per_sec",
        "value": round(decisions_per_sec, 3),
        "unit": "decisions/sec",
        "vs_baseline": (
            round(decisions_per_sec / baseline_dps, 3) if baseline_dps else 0.0
        ),
        "extra": {
            "rounds_per_sec": round(rounds_done / elapsed, 4),
            "rounds_measured": rounds_done,
            "concurrency": concurrency,
            "agents": n_agents,
            "model": model,
            "backend": backend,
            "checkpoint": (
                "none" if backend == "fake"
                else "hf" if model.startswith("bcg-hf/")
                else "random"
            ),
            "quantization": cfg.engine.quantization,
            "kv_cache_dtype": cfg.engine.kv_cache_dtype,
            "fast_forward": cfg.engine.decode_fast_forward,
            "spec_decode": cfg.engine.spec_decode,
            "compact_json": cfg.engine.guided_compact_json,
            "prefix_caching": cfg.engine.prefix_caching,
            "prefill_chunk": cfg.engine.prefill_chunk,
            "scan_layers": cfg.engine.scan_layers,
            "shared_core_votes": cfg.agent.shared_core_votes,
            "platform": platform,
            "elapsed_sec": round(elapsed, 2),
            # Cold cost: engine build + weight init/load + first-round
            # compiles + the first full round (time-to-first-decision).
            "boot_plus_first_round_s": (
                round(first_round_s, 2) if first_round_s is not None else None
            ),
            # Per-phase boot breakdown (seconds + allocator readings):
            # init_params / quantize / stack / shard / first_compile —
            # the phase attribution the next boot-time OOM needs
            # (runtime/metrics.py BootPhaseRecorder).
            "boot_phases": getattr(engine, "boot_phases", None),
            # BENCH_SERVE=1: latest serving-scheduler snapshot (queue
            # depth, batch occupancy, linger histogram, rejections).
            "serve_stats": _serve_stats_or_none(),
            # BCG_TPU_SPEC/BENCH_SPEC: speculative-decoding draft
            # acceptance over the whole run (engine.spec.* counters).
            "spec_stats": _spec_stats_or_none(),
            # BCG_TPU_PAGED_KV: block-pool snapshot (free-block headroom
            # bytes, radix prefix hit rate); None on dense engines.
            "kv_pool": (
                engine.kv_pool_stats()
                if hasattr(engine, "kv_pool_stats") else None
            ),
            # BCG_TPU_FUSED_SAMPLER / BCG_TPU_KV_DTYPE: sampler impl +
            # interpret mode + fused-kernel invocation count + the
            # RESOLVED kv dtype (env override wins over the config
            # field echoed above).
            "sampler": _sampler_stats_or_none(),
            # BCG_TPU_GAME_EVENTS: cumulative consensus-game telemetry
            # (converged/rounds/byzantine adoptions/event drops).
            "game_stats": _game_stats_or_none(),
            # BCG_TPU_HOSTSYNC: host-sync audit of the window (total/
            # attributed transfers, syncs per phase site, syncs/round,
            # top attribution spans); None when the auditor is off.
            "hostsync": _hostsync_stats_or_none(),
            # BCG_TPU_MEGAROUND: fused mega-round profile (fused_rounds,
            # syncs_per_round — 1.0 by construction, rounds_per_sec);
            # None when no round took the fused path.
            "megaround": _megaround_stats_or_none(),
            # BCG_TPU_COMPILE_OBS: compile-cost profile (per-entry
            # compile_ms totals, first-compile vs retrace split,
            # cache-entry population, retrace causes); None when the
            # observer is off.
            "compile": _compile_stats_or_none(),
            # Fleet identity (run id, rank, host, shard path, heartbeat
            # age, straggler count) when fleet stamping is on; None
            # single-process.
            "fleet": _fleet_stats_or_none(),
            # BCG_TPU_FAULT_RATE / BCG_TPU_CHAOS: fault-injection
            # profile (corrupted responses + chaos seams fired); None
            # when neither injector ran.
            "faults": _fault_stats_or_none(),
            # BCG_TPU_ALERTS: alert-engine verdict (rules evaluated,
            # fired/resolved counts, flaps, still-firing rules); None
            # when the evaluator is off.
            "alerts": _alerts_stats_or_none(),
            "window_decode_steps": window_steps,
            "window_failed_row_fraction": round(failed_fraction, 4),
            "baseline_denominator_dec_per_sec": (
                round(baseline_dps, 3) if baseline_dps else None
            ),
            "baseline_note": "denominator = A100-80GB HBM roofline of the "
            "reference's stack at THIS model's parameter count (upper "
            "bound, favors the reference; derivation: BASELINE.md "
            "appendix A); reference publishes no measured numbers",
        },
    }
    result["extra"].update(perf)
    result["extra"].update(_obs_payload())
    return result


def main() -> None:
    force_cpu = _env_flag("BENCH_FORCE_CPU", False)
    if force_cpu:
        # Hermetic mode: run the REAL jax path on the host CPU — the
        # whole bench stack (size-class gating, engine boot, measured
        # window) minus the accelerator.  The env var alone is not
        # enough under this environment's axon sitecustomize, so force
        # it in-process before any backend init.
        import jax

        jax.config.update("jax_platforms", "cpu")
    model = envflags.get_str("BENCH_MODEL")
    backend = envflags.get_str("BENCH_BACKEND")
    quant_env = envflags.get_str("BENCH_QUANTIZATION")
    # 3 measured rounds (~10 s window): 2-round windows showed +-8% noise
    # from retry-ladder luck; the attach/warmup cost already dominates.
    measured_rounds = envflags.get_int("BENCH_ROUNDS")
    # Two warmup rounds: round 1 compiles the initial shapes; round 2
    # covers the history-grown prompt's length bucket, so the measured
    # window is (normally) compile-free.
    warmup_rounds = envflags.get_int("BENCH_WARMUP")
    concurrency = envflags.get_int("BENCH_CONCURRENCY")

    from bcg_tpu.config import BCGConfig
    from bcg_tpu.models.configs import (
        LARGE_MODEL_PARAMS, XL_MODEL_PARAMS, spec_for_model,
    )

    # The remote-attached TPU can hang for many minutes when its tunnel is
    # unhealthy (observed: ~10 min stall then UNAVAILABLE).  Probe the
    # backend in a subprocess with a deadline so the bench reports an
    # explicit error line instead of stalling the driver indefinitely.
    if backend == "jax":
        import subprocess

        attach_timeout = envflags.get_int("BENCH_ATTACH_TIMEOUT")
        cpu_stmt = (
            'jax.config.update("jax_platforms", "cpu"); ' if force_cpu else ""
        )
        try:
            subprocess.run(
                [sys.executable, "-c",
                 f"import jax; {cpu_stmt}jax.devices(); "
                 "import jax.numpy as jnp; "
                 "(jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()"],
                timeout=attach_timeout, check=True, capture_output=True,
            )
        except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as e:
            stderr = e.stderr or b""
            if isinstance(stderr, bytes):
                stderr = stderr.decode(errors="replace")
            print(json.dumps({
                "metric": "agent_decisions_per_sec",
                "value": 0.0,
                "unit": "decisions/sec",
                "vs_baseline": None,  # measured nothing (see _error_result)
                "error": f"accelerator attach failed: {type(e).__name__} "
                         f"(timeout={attach_timeout}s); backend unavailable",
                "stderr_tail": stderr[-500:],
            }))
            return
        # Wording matters: hw_watcher.sh greps step logs for
        # unavailable|attach|connection refused|response body closed to
        # classify failures as outages — a success stamp containing any
        # of those markers would make every later failure of the step
        # look like an outage and retry forever.
        _progress("accelerator probe OK (device responds)")

    # bcg-hf/* models run the REAL checkpoint pipeline (AutoTokenizer +
    # safetensors + config.json from local disk, models/hf_fixture.py)
    # instead of in-process random init — the weights are still random,
    # but every loading/tokenization/DFA step is the one a hub
    # checkpoint would take.  Built once; reused across runs.
    if model.startswith("bcg-hf/"):
        # Inside the never-rc=1 contract: a fixture build failure (bad
        # name, disk error) must also come out as an error JSON line.
        try:
            from bcg_tpu.models.hf_fixture import build_checkpoint

            build_checkpoint(model)
        except Exception as exc:
            print(json.dumps(_error_result(exc, retried=False)))
            return

    spec = spec_for_model(model)
    large_model = spec is not None and spec.param_count >= LARGE_MODEL_PARAMS
    xl_model = spec is not None and spec.param_count >= XL_MODEL_PARAMS
    if xl_model and not envflags.is_set("BENCH_QUANTIZATION"):
        # 14B-class: int8 weights alone are >= 12 GB — single-chip
        # serving needs the int4 capacity path unless overridden.
        quant_env = "int4"
    # int8 KV default for the large size class: the bf16 cache alone
    # pushes a 16 GB chip past capacity next to int8 weights (measured
    # compile-time OOM); smaller models default bf16 (int8 KV loses
    # wall-clock there).
    kv_dtype = envflags.get_str(
        "BENCH_KV_DTYPE", "int8" if large_model else "bfloat16"
    )
    base = BCGConfig()
    cfg = dataclasses.replace(
        base,
        game=dataclasses.replace(
            base.game,
            num_honest=8,
            num_byzantine=2,
            max_rounds=warmup_rounds + measured_rounds + 8,
            seed=0,
        ),
        engine=dataclasses.replace(
            base.engine, model_name=model, backend=backend,
            quantization=(
                None if quant_env.lower() in ("", "none", "bfloat16", "bf16", "off")
                else quant_env
            ),
            kv_cache_dtype=kv_dtype,
            # BENCH_ATTENTION_IMPL=xla|pallas|auto: prefill-attention
            # kernel override — the bisect knob for remote Mosaic
            # compile failures at new model geometries (a 14B prefill
            # compile crashed the helper on 2026-08-01; xla isolates
            # whether the flash kernel is the crasher).
            attention_impl=envflags.get_str("BENCH_ATTENTION_IMPL"),
            decode_fast_forward=_env_flag("BENCH_FAST_FORWARD", True),
            # Prompt-lookup speculative decoding (supersedes
            # fast-forward when on; BCG_TPU_SPEC also enables it at the
            # engine level).
            spec_decode=_env_flag("BENCH_SPEC", False),
            guided_compact_json=_env_flag("BENCH_COMPACT_JSON", True),
            # Off by default for the large size class: weights + KV
            # leave no room for cached prefix KV on a 16 GB chip — the
            # round-3 plain bench-8b run OOMed at first decode with
            # prefix entries resident.
            prefix_caching=_env_flag("BENCH_PREFIX_CACHING", not large_model),
            # Chunked prefill slice (tokens; 0 = whole prompt in one
            # pass).  Default ON for the large size class: whole-prompt
            # prefill activations alone exceed the HBM left after
            # weights + KV cache there.
            prefill_chunk=envflags.get_int(
                "BENCH_PREFILL_CHUNK", 512 if large_model else 0
            ),
            # Scan-over-layers: O(1)-in-depth program, required for
            # 8B-class compiles through the remote-compile helper
            # (default ON for the large size class, off elsewhere — the
            # unrolled form keeps better cache-update aliasing in the
            # decode loop).
            scan_layers=_env_flag("BENCH_SCAN_LAYERS", large_model),
        ),
        agent=dataclasses.replace(
            base.agent,
            shared_core_votes=_env_flag("BENCH_SHARED_CORE", False),
        ),
        metrics=dataclasses.replace(
            base.metrics, save_results=False, generate_plots=False
        ),
    )

    try:
        result = _run_attempt(
            cfg, model, backend, concurrency, warmup_rounds, measured_rounds
        )
    except Exception as exc:  # never a bare rc=1: report as JSON
        transient = _is_transient(exc)
        result = None if transient else _error_result(exc, retried=False)
        sys.stderr.write(
            f"bench: failure ({type(exc).__name__}: {str(exc)[:200]}); "
            f"{'retrying once' if transient else 'not retried'}\n"
        )
        # Drop the failed attempt's frames BEFORE retrying: the live
        # traceback pins _run_attempt's locals — the whole engine, its
        # device weight buffers and compiled loops — and a second engine
        # on top of an un-collected 8B first one OOMs the chip.
        del exc
        if transient:
            import gc

            gc.collect()
            # Shut the failed attempt's engine down and wait for the
            # device allocator to drain before rebuilding (frees are
            # async on the remote-attached chip).
            _teardown_live_engines()
            try:
                result = _run_attempt(
                    cfg, model, backend, concurrency,
                    warmup_rounds, measured_rounds,
                )
            except Exception as exc2:
                result = _error_result(exc2, retried=True)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
