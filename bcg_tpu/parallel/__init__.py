"""Parallelism: device meshes, weight sharding, collective game step.

Replaces the reference's delegated distribution (vLLM tensor_parallel +
torch.distributed/NCCL, vllm_agent.py:139-145, 541-545) with native JAX
SPMD: a named Mesh over ICI, NamedSharding partition specs for weights
and KV caches, and XLA collectives (all_gather/psum) inserted by the
compiler from sharding annotations.
"""

from bcg_tpu.parallel.distributed import (
    build_hybrid_mesh,
    initialize,
    process_info,
    shutdown,
)
from bcg_tpu.parallel.mesh import build_mesh, mesh_axes
from bcg_tpu.parallel.sharding import (
    param_sharding, shard_params, kv_cache_sharding, kv_scale_sharding,
)

__all__ = [
    "build_mesh",
    "build_hybrid_mesh",
    "initialize",
    "mesh_axes",
    "param_sharding",
    "process_info",
    "shard_params",
    "shutdown",
    "kv_cache_sharding",
    "kv_scale_sharding",
]
