"""Device mesh construction.

Axes (scaling-book layout):

* ``dp`` — data parallel: independent agent groups / replicated weights
* ``tp`` — tensor parallel: heads + MLP intermediate dim over ICI
* ``sp`` — sequence parallel: ring-attention shards of the KV sequence

Single chip = 1x1x1 mesh; the same code path runs everywhere.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "tp", "sp")


def mesh_axes() -> Sequence[str]:
    return AXES


def build_mesh(
    dp: int = 1,
    tp: int = 1,
    sp: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp * sp
    if need > len(devices):
        raise ValueError(
            f"mesh dp={dp} tp={tp} sp={sp} needs {need} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(dp, tp, sp)
    return Mesh(grid, AXES)


def mesh_from_engine_config(engine_config, devices=None) -> Mesh:
    return build_mesh(
        dp=engine_config.data_parallel_size,
        tp=engine_config.tensor_parallel_size,
        sp=engine_config.sequence_parallel_size,
        devices=devices,
    )
