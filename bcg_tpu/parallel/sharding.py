"""Weight / activation partition specs (Megatron-style TP over the mesh).

Column-parallel in-projections (wq/wk/wv/w_gate/w_up shard their OUTPUT
dim over ``tp``), row-parallel out-projections (wo/w_down shard their
INPUT dim) — XLA inserts the single all-reduce per block that this layout
implies.  Embedding and lm_head shard the vocab dim; norms replicate.

KV caches shard heads over ``tp`` and batch over ``dp``; with ``sp`` the
sequence dim shards for ring attention (:mod:`bcg_tpu.ops.ring_attention`).
"""

from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bcg_tpu.models.configs import ModelSpec

# Logical leaf name (last path component) -> PartitionSpec.
_SPECS = {
    # [V, D] vocab-sharded embedding
    "embed": P("tp", None),
    "final_norm": P(None),
    # [D, V]
    "lm_head": P(None, "tp"),
    "attn_norm": P(None),
    "mlp_norm": P(None),
    # column-parallel: output dim sharded
    "wq": P(None, "tp"),
    "wk": P(None, "tp"),
    "wv": P(None, "tp"),
    "w_gate": P(None, "tp"),
    "w_up": P(None, "tp"),
    # row-parallel: input dim sharded
    "wo": P("tp", None),
    "w_down": P("tp", None),
    # per-head norms replicate
    "q_norm": P(None),
    "k_norm": P(None),
    # qkv projection biases follow their weight's OUTPUT dim
    "bq": P("tp"),
    "bk": P("tp"),
    "bv": P("tp"),
}


def param_sharding(
    logical_name: str, spec: ModelSpec, mesh: Mesh, stacked: bool = False
) -> NamedSharding:
    """Sharding for a logical parameter path like ``layers.3.wq``.

    int8-quantized weights appear as ``...wq.q`` / ``...wq.scale`` leaves
    (models/quantize.py): ``q`` shards exactly like the parent weight;
    ``scale`` is per-OUTPUT-channel, so it follows the output dim — sharded
    over ``tp`` for column-parallel parents (wq/wk/wv/w_gate/w_up, and the
    vocab-dim lm_head), replicated for row-parallel parents (wo/w_down,
    whose sharded dim is the input).

    ``stacked``: the leaf carries a leading [num_layers] dim
    (scan-over-layers layout, transformer.stack_layer_params) — the
    layer axis replicates and every other axis keeps its spec.
    """
    parts = logical_name.split(".")
    leaf = parts[-1]
    quant_kind = None
    if leaf in ("q", "scale", "q4", "gscale") and len(parts) >= 2 and parts[-2] in _SPECS:
        quant_kind = leaf
        leaf = parts[-2]
    pspec = _SPECS.get(leaf, P(None))
    # Head-count must divide tp; otherwise replicate rather than crash.
    tp = mesh.shape.get("tp", 1)
    if leaf in ("wq", "wo", "bq") and spec.num_heads % tp != 0:
        pspec = P(None)
    if leaf in ("wk", "wv", "bk", "bv") and spec.num_kv_heads % tp != 0:
        pspec = P(None)
    if quant_kind == "scale":
        # Per-output-channel vector: keep the weight's OUTPUT-dim axis.
        pspec = P(pspec[-1] if len(pspec) > 0 else None)
    elif quant_kind == "gscale":
        # int4 group scales are [in/group, out]: the group dim always
        # replicates and only the output dim follows the parent (sharded
        # for column-parallel, replicated for row-parallel).  Replicated
        # groups mean every shard has the scale rows for whatever slice
        # of q4's packed rows GSPMD hands it — q4's nibble pairs (row i
        # packs global rows i and i+P) never constrain the scale layout.
        pspec = P(None, pspec[-1] if len(pspec) > 0 else None)
    if stacked:
        pspec = P(*((None,) + tuple(pspec)))
    return NamedSharding(mesh, pspec)


def shard_params(params: Dict, spec: ModelSpec, mesh: Mesh) -> Dict:
    """Apply partition specs to every leaf of the param pytree.

    Handles both layouts: per-layer list (``layers.3.wq``) and stacked
    scan-over-layers (``layers.wq`` with a leading layer dim)."""
    stacked_layers = isinstance(params.get("layers"), dict)

    def place(path_parts, subtree):
        if isinstance(subtree, dict):
            return {k: place(path_parts + [k], v) for k, v in subtree.items()}
        if isinstance(subtree, list):
            return [place(path_parts + [str(i)], v) for i, v in enumerate(subtree)]
        logical = ".".join(path_parts)
        stacked = stacked_layers and path_parts and path_parts[0] == "layers"
        return jax.device_put(
            subtree, param_sharding(logical, spec, mesh, stacked=stacked)
        )

    return place([], params)


def kv_cache_sharding(mesh: Mesh, quantized: bool = False) -> NamedSharding:
    """Sharding for KV-cache k/v leaves: batch over dp, sequence over sp,
    kv-heads over tp.

    bf16 caches are [B, S, Hkv, Dh]; quantized (int8) caches store
    [B, Hkv, S, Dh] (models/transformer.py init_kv_cache), so the axis
    order flips.  int8 scale leaves ([B, Hkv, S]) need
    ``kv_scale_sharding`` instead.
    """
    if quantized:
        return NamedSharding(mesh, P("dp", "tp", "sp", None))
    return NamedSharding(mesh, P("dp", "sp", "tp", None))


def kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    """int8 KV scale leaves [B, Hkv, S]: dp x tp x sp."""
    return NamedSharding(mesh, P("dp", "tp", "sp"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[B, ...] activations: batch over dp."""
    return NamedSharding(mesh, P("dp"))


def kv_cache_tree_sharding(mesh: Mesh, cache_shapes, quantized: bool = False,
                           stacked: bool = False):
    """Per-leaf shardings for an ``init_kv_cache``-shaped pytree.

    Applies :func:`kv_cache_sharding` / :func:`kv_scale_sharding`'s axis
    layout with per-axis divisibility guards (an axis whose size doesn't
    divide its mesh dimension is replicated instead — mirroring
    ``ops/ring_attention.py``'s dp_ax/tp_ax guards), and a leading
    ``None`` under scan-over-layers stacking.  ``cache_shapes`` is the
    cache itself or a ``jax.eval_shape`` result — only ``.shape`` and
    ``.ndim`` of the leaves are read.  Centralizing this here keeps the
    engine's cache placement and the memory guards (which divide
    per-row bytes by the FULL mesh size) from drifting apart.
    """
    lead = (None,) if stacked else ()
    if quantized:
        kv = lead + ("dp", "tp", "sp", None)      # [B, Hkv, S, Dh] int8
        scale = lead + ("dp", "tp", "sp")         # [B, Hkv, S]
    else:
        kv = lead + ("dp", "sp", "tp", None)      # [B, S, Hkv, Dh]
        scale = None

    def place(leaf):
        axes = kv if leaf.ndim == len(kv) else scale
        spec = tuple(
            ax
            if ax is not None and leaf.shape[i] % mesh.shape.get(ax, 1) == 0
            and mesh.shape.get(ax, 1) > 1
            else None
            for i, ax in enumerate(axes)
        )
        return NamedSharding(mesh, P(*spec))

    return jax.tree.map(place, cache_shapes)


def paged_pool_tree_sharding(mesh: Mesh, pool_shapes, quantized: bool = False,
                             stacked: bool = False):
    """Per-leaf shardings for a block-paged KV pool
    (:func:`bcg_tpu.ops.paged_attention.init_block_pool`) — the same
    axis logic as :func:`kv_cache_tree_sharding` with the dense
    ``[B, S]`` pair replaced by ``[N_blocks, block_size]``: blocks are
    SHARED across batch rows, so neither pool dim may shard over ``dp``
    or ``sp`` (every device must read any block) — only the kv-head dim
    partitions, over ``tp``, with the same divisibility guard."""
    lead = (None,) if stacked else ()
    if quantized:
        kv = lead + (None, "tp", None, None)      # [N, Hkv, bs, Dh] int8
        scale = lead + (None, "tp", None)         # [N, Hkv, bs]
    else:
        kv = lead + (None, None, "tp", None)      # [N, bs, Hkv, Dh]
        scale = None

    def place(leaf):
        axes = kv if leaf.ndim == len(kv) else scale
        spec = tuple(
            ax
            if ax is not None and leaf.shape[i] % mesh.shape.get(ax, 1) == 0
            and mesh.shape.get(ax, 1) > 1
            else None
            for i, ax in enumerate(axes)
        )
        return NamedSharding(mesh, P(*spec))

    return jax.tree.map(place, pool_shapes)


def paged_table_sharding(mesh: Mesh, stacked: bool = False) -> NamedSharding:
    """Sharding for a paged entry's block-table leaf (``[B, nblk]``
    int32; a leading ``[num_layers]`` dim under scan stacking): fully
    REPLICATED.  The table is the Pallas paged kernel's scalar-prefetch
    operand — every device's kernel instance resolves every row's pool
    slots from it — and it is tiny (a few KB), so replication is both
    required and free.  Placing it explicitly keeps the donated cache
    tree's layout deterministic instead of letting GSPMD choose."""
    return NamedSharding(mesh, P(*((None,) * (3 if stacked else 2))))


def shard_bytes(shape, dtype, sharding=None) -> int:
    """Bytes of ONE device's shard of an array (full bytes when
    ``sharding`` is None).  The single shard-size computation behind
    every per-device HBM accounting path — the provisioner
    (:func:`kv_cache_bytes_per_device`), the weight-budget term
    (:func:`tree_bytes_per_device`) and the analytic boot report
    (``models/loader.boot_peak_report``) must not drift apart."""
    import numpy as np

    dims = sharding.shard_shape(tuple(shape)) if sharding is not None else shape
    n = 1
    for d in dims:
        n *= d
    return n * np.dtype(dtype).itemsize


def kv_cache_bytes_per_device(
    mesh: Mesh, cache_shapes, quantized: bool = False, stacked: bool = False
) -> int:
    """Bytes ONE device actually holds for a cache placed by
    :func:`kv_cache_tree_sharding`.

    The engine's HBM provisioner must divide by the mesh axes that
    ENGAGE for the given shapes — an axis that fails its divisibility
    guard (or a batch that skips dp alignment on the dp-bypass path)
    replicates, so dividing per-row bytes by the full ``mesh.size``
    overcommits per-device HBM by up to that axis's size (ADVICE
    round-5 medium).  Summing each leaf's ``shard_shape`` bytes under
    the SAME placement function keeps the accounting and the layout
    from drifting apart.  ``cache_shapes`` is a cache pytree or a
    ``jax.eval_shape`` result.
    """
    shardings = kv_cache_tree_sharding(
        mesh, cache_shapes, quantized=quantized, stacked=stacked
    )
    is_sharding = lambda s: isinstance(s, NamedSharding)  # noqa: E731
    return sum(
        shard_bytes(leaf.shape, leaf.dtype, sh)
        for leaf, sh in zip(
            jax.tree.leaves(cache_shapes),
            jax.tree.leaves(shardings, is_leaf=is_sharding),
        )
    )


def tree_bytes_per_device(tree) -> int:
    """Per-device bytes of a pytree of (possibly sharded) arrays: a leaf
    with a ``NamedSharding`` counts its SHARD size; anything else counts
    whole.  Used for the weight term of the engine's HBM budget — the
    former ``param_bytes / tp`` estimate over-divided leaves that the
    head-divisibility guards in :func:`param_sharding` replicate."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        shape = getattr(leaf, "shape", None)
        if (
            isinstance(sharding, NamedSharding)
            and shape is not None
            and hasattr(leaf, "dtype")
        ):
            total += shard_bytes(shape, leaf.dtype, sharding)
        else:
            total += int(getattr(leaf, "nbytes", 0))
    return total
