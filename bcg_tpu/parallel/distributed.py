"""Multi-host distributed runtime.

The reference's only distributed machinery is vLLM's internal
NCCL/torch.distributed stack, reached through ``tensor_parallel_size``
and ``distributed_executor_backend='mp'`` (``vllm_agent.py:139-142``)
and torn down via ``torch.distributed.destroy_process_group``
(``vllm_agent.py:541-551``).  The TPU-native equivalent is the JAX
distributed runtime plus XLA collectives: this module initializes the
process group (GCE metadata auto-detect on Cloud TPU, or explicit
coordinator for manual clusters) and builds **hybrid meshes** whose
inner axes (tp, sp) ride ICI within a slice while the outer axis (dp)
crosses DCN between hosts/slices — the layout where every
bandwidth-hungry collective (psum/all_gather from tensor and sequence
parallelism) stays on ICI and only data-parallel traffic touches DCN.
"""

from __future__ import annotations

import atexit
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

from bcg_tpu.parallel.mesh import build_mesh

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Join (or create) the multi-host process group.

    With no arguments, JAX auto-detects the topology on Cloud TPU (GCE
    metadata / megascale env).  All hosts must call this before any
    device computation.  Idempotent; registers shutdown at exit —
    the analogue of the reference's ``destroy_process_group`` teardown.
    """
    global _initialized
    if _initialized:
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    # NOTE: must run before anything touches the XLA backend (even
    # jax.devices()/process_count()) — jax.distributed.initialize raises
    # once backends exist, so this function deliberately queries nothing.
    jax.distributed.initialize(**kwargs)
    _initialized = True
    # Hand the observability plane its process identity LAZILY: the
    # provider closure queries the backend only when fleet telemetry
    # first needs the rank, so initialize() itself still touches
    # nothing (callers may have more backend config to apply).
    from bcg_tpu.obs import fleet

    fleet.set_process_provider(
        lambda: (jax.process_index(), jax.process_count())
    )
    atexit.register(shutdown)


def shutdown() -> None:
    global _initialized
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass  # already torn down (interpreter exit ordering)
        _initialized = False


def build_hybrid_mesh(
    tp: int = 1,
    sp: int = 1,
    dp: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """dp x tp x sp mesh where tp/sp are ICI-contiguous within each host
    and dp spans hosts over DCN.

    ``jax.devices()`` orders devices host-major, so reshaping to
    (dp, tp, sp) with tp*sp dividing the per-host device count keeps
    every tp/sp group inside one host's ICI domain.  ``dp`` defaults to
    "all remaining devices".  Degenerates to the single-host mesh when
    process_count == 1 — the same code path runs everywhere.
    """
    devices = list(devices if devices is not None else jax.devices())
    inner = tp * sp
    n_local = len([d for d in devices if d.process_index == devices[0].process_index])
    multihost = any(
        d.process_index != devices[0].process_index for d in devices
    )
    # tp/sp groups must not straddle a host boundary: with device order
    # host-major, that requires the per-host device count to be an exact
    # multiple of tp*sp (otherwise some dp row spans two hosts' devices).
    if multihost and (inner > n_local or n_local % inner != 0):
        raise ValueError(
            f"tp*sp={inner} does not pack into the {n_local} devices of "
            "one host; a tp/sp collective group would cross DCN — resize "
            "them or move the extra parallelism to dp"
        )
    if dp is None:
        if len(devices) % inner:
            raise ValueError(
                f"{len(devices)} devices not divisible by tp*sp={inner}"
            )
        dp = len(devices) // inner
    return build_mesh(dp=dp, tp=tp, sp=sp, devices=devices)


def mesh_spans_processes(mesh: Mesh) -> bool:
    """True when the mesh's devices live on more than one process —
    the dp-across-hosts layout.  Callers use this to pick the
    global-placement collective forms (``parallel/game_step.
    exchange_values_global``): a single-device local array fed to a
    cross-process mesh would make XLA stage an implicit inter-host
    transfer (refused outright on CPU, silently DCN-expensive on TPU).
    """
    return len({d.process_index for d in mesh.devices.flat}) > 1


def process_info() -> dict:
    """Cluster shape summary for logs/metrics."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
