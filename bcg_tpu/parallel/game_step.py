"""SPMD game round step: one-agent-per-chip message exchange and vote
tally as XLA collectives.

This is the TPU-native form of the A2A broadcast/receive/vote phases
(reference ``a2a_sim.py`` + ``byzantine_consensus.py:251-398``): per-agent
(value, vote) scalars live sharded over the ``dp`` mesh axis; "broadcast
to neighbours" is one ``all_gather`` over ICI followed by a static
topology mask; vote counting and consensus checks are pure array math on
the gathered tensors.  Semantics match the host game exactly (tested
against it) — this path exists for the 16/64-agent one-agent-per-chip
scale sweeps (BASELINE.json configs 4-5) where host-side Python routing
would serialize the round.

Value conventions: ``value < 0`` encodes abstention (no proposal);
votes are ints {1: stop, 0: continue, -1: abstain}.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bcg_tpu.parallel.compat import shard_map


def _masked_receive(all_vals: jax.Array, mask_rows: jax.Array) -> jax.Array:
    """The exchange body shared by every delivery path: row i of the
    result holds agent j's value iff ``mask_rows[i, j]`` AND j proposed
    (``all_vals[j] >= 0``), else -1.  ``all_vals`` is the full [n] value
    vector, ``mask_rows`` the (possibly sharded) receiver-mask rows —
    the shard_map collectives and the dense mega-round program both call
    this, so topology semantics can never fork between them."""
    return jnp.where(mask_rows & (all_vals >= 0)[None, :], all_vals[None, :], -1)


def masked_exchange(
    values: jax.Array,         # [n] int32, -1 = abstain
    receiver_mask: jax.Array,  # [n, n] bool, mask[i, j] = i receives from j
) -> Tuple[jax.Array, jax.Array]:
    """Dense (replicated, jit-composable) topology-masked exchange — the
    mega-round form of :func:`exchange_values`: no mesh, no collective,
    so it inlines into the fused round program.  Returns ``(received,
    deliveries)`` where ``received[i, j]`` is agent j's value as seen by
    agent i (-1 = not delivered) and ``deliveries[i]`` is the number of
    proposals delivered to receiver i — the adjacency mask applied as a
    masked matmul over the proposed-indicator vector, which is also the
    per-receiver count the orchestrator's ``deliveries`` game event and
    message accounting read."""
    received = _masked_receive(values, receiver_mask)
    proposed = (values >= 0).astype(jnp.int32)
    deliveries = receiver_mask.astype(jnp.int32) @ proposed
    return received, deliveries


def _masked_receive_matrix(
    proposals: jax.Array, mask_rows: jax.Array
) -> jax.Array:
    """Per-receiver generalization of :func:`_masked_receive`:
    ``proposals[i, j]`` is the value sender j addressed TO receiver i
    (equivocating senders put different values in different rows; a
    broadcasting sender's column is constant).  Row i of the result
    holds that value iff ``mask_rows[i, j]`` AND the sender proposed
    (``proposals[i, j] >= 0``), else -1."""
    return jnp.where(mask_rows & (proposals >= 0), proposals, -1)


def masked_exchange_matrix(
    proposals: jax.Array,      # [n, n] int32, [i, j] = j's value for i
    receiver_mask: jax.Array,  # [n, n] bool, mask[i, j] = i receives from j
) -> Tuple[jax.Array, jax.Array]:
    """Per-receiver form of :func:`masked_exchange` — the mask·values
    matmul generalized to an elementwise mask over a proposal MATRIX,
    which is what equivocating adversaries need (ROADMAP item 2: one
    sender, different values to different receivers).  When every
    column of ``proposals`` is constant (nobody equivocates) this is
    numerically identical to ``masked_exchange(proposals[0], mask)``
    (tested), so the fused mega-round program routes ALL rounds through
    it without changing the non-equivocating semantics."""
    received = _masked_receive_matrix(proposals, receiver_mask)
    delivered = receiver_mask & (proposals >= 0)
    deliveries = delivered.astype(jnp.int32).sum(axis=1)
    return received, deliveries


def equivocate_proposals(
    values: jax.Array,        # [n] int32 base proposals, -1 = abstain
    equivocators: jax.Array,  # [n] bool, True = sender equivocates
    lo: int,
    hi: int,
) -> jax.Array:
    """Expand base proposals to the per-receiver proposal matrix:
    column j is constant (the broadcast value) for honest/non-
    equivocating senders, and the deterministic per-receiver spread
    :func:`bcg_tpu.scenarios.strategies.equivocation_value` for
    equivocating senders that proposed.  Abstaining senders stay -1
    for every receiver.  Pure jnp, inlines into the fused round
    program; the all-False case is exactly ``broadcast_to(values)``,
    preserving the mega-round's greedy identity to the lockstep
    oracle."""
    from bcg_tpu.scenarios.strategies import equivocation_value

    n = values.shape[0]
    broadcast = jnp.broadcast_to(values[None, :], (n, n))
    receiver_idx = jnp.arange(n, dtype=values.dtype)[:, None]
    spread = equivocation_value(values[None, :], receiver_idx, lo, hi)
    return jnp.where(
        equivocators[None, :] & (values >= 0)[None, :], spread, broadcast
    )


def tally_votes_dense(votes: jax.Array) -> Dict[str, jax.Array]:
    """Dense form of :func:`tally_votes` (same vote conventions, same
    2n/3 rule from reference byzantine_consensus.py:373-398) — scalar
    outputs, no mesh, so the mega-round program can inline it."""
    stop = (votes == 1).sum()
    cont = (votes == 0).sum()
    abstain = (votes == -1).sum()
    total = stop + cont + abstain
    return {
        "stop": stop,
        "continue": cont,
        "abstain": abstain,
        "terminate": stop * 3 >= total * 2,
        "half_stop": stop * 2 >= total,
    }


def check_consensus_dense(
    values: jax.Array,          # [n] int32 current values, -1 = none
    is_byzantine: jax.Array,    # [n] bool
    initial_values: jax.Array,  # [n] int32 honest initials, -1 for Byz
) -> Dict[str, jax.Array]:
    """Dense form of :func:`check_consensus_spmd` — the reference's
    exact rule (byzantine_consensus.py:182-249): ALL honest agents hold
    the same value AND it is some honest agent's initial value.  Scalar
    outputs; shares the pairwise-equality modal count with the spmd
    body so the two paths cannot diverge semantically."""
    honest_valid = (~is_byzantine) & (values >= 0)
    n_honest = honest_valid.sum()
    same = honest_valid[:, None] & honest_valid[None, :] & (
        values[:, None] == values[None, :]
    )
    counts = jnp.where(honest_valid, same.sum(axis=1), 0)
    modal_idx = jnp.argmax(counts)
    ref = values[modal_idx]
    modal_count = counts[modal_idx]
    agreement = jnp.where(
        n_honest > 0, modal_count / jnp.maximum(n_honest, 1) * 100.0, 0.0
    )
    all_equal = (modal_count == n_honest) & (n_honest > 0)
    from_initial = (
        (initial_values == ref) & ~is_byzantine & (initial_values >= 0)
    ).any()
    return {
        "has_consensus": all_equal & from_initial,
        "consensus_value": ref,
        "agreement_pct": agreement,
    }


def exchange_values(
    values: jax.Array,        # [n] int32, -1 = abstain, sharded over dp
    neighbor_mask: jax.Array, # [n, n] bool (static topology)
    mesh: Mesh,
    axis_name: str = "dp",
) -> jax.Array:
    """Neighbour-masked all-gather: returns [n, n] where row i holds
    agent j's value if j is i's neighbour AND j proposed, else -1."""

    def body(local_vals, mask_rows):
        all_vals = jax.lax.all_gather(local_vals, axis_name, tiled=True)  # [n]
        return _masked_receive(all_vals, mask_rows)

    f = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name, None)),
        out_specs=P(axis_name, None),
    )
    return f(values, neighbor_mask)


def exchange_proposals(
    proposals: jax.Array,      # [n, n] int32, [i, j] = j's value for i
    receiver_mask: jax.Array,  # [n, n] bool (static topology)
    mesh: Mesh,
    axis_name: str = "dp",
) -> jax.Array:
    """Per-receiver (equivocation-capable) form of :func:`exchange_values`:
    each sender owns a COLUMN of per-receiver values instead of one
    scalar, so the gather runs over sender columns and each shard then
    masks its own receiver rows.  With every column constant this
    returns exactly what ``exchange_values(proposals[0], mask, mesh)``
    returns (tested) — the SPMD twin of
    :func:`masked_exchange_matrix`."""
    n = proposals.shape[0]
    rows_per = n // mesh.shape[axis_name]

    def body(local_cols, mask_rows):
        # local_cols [n, n/dp]: this shard's sender columns; gather the
        # full matrix, then keep only this shard's receiver rows.
        all_props = jax.lax.all_gather(
            local_cols, axis_name, axis=1, tiled=True
        )
        idx = jax.lax.axis_index(axis_name)
        local_rows = jax.lax.dynamic_slice_in_dim(
            all_props, idx * rows_per, rows_per, axis=0
        )
        return _masked_receive_matrix(local_rows, mask_rows)

    f = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(axis_name, None)),
        out_specs=P(axis_name, None),
    )
    return f(proposals, receiver_mask)


def exchange_values_global(
    values_np,         # [n] int32 host array, IDENTICAL on every process
    neighbor_mask_np,  # [n, n] bool host array, identical on every process
    mesh: Mesh,
    axis_name: str = "dp",
):
    """Multi-process form of :func:`exchange_values` for meshes whose
    ``dp`` axis spans hosts (the sweep tier's cooperative one-big-game
    mode): inputs are plain host arrays — identical on every rank,
    because every rank runs the same lockstep game — distributed over
    the GLOBAL mesh via ``make_array_from_callback``, exchanged with
    the same masked all-gather, then all-gathered once more over rows
    so the output is REPLICATED: every host reads the full [n, n]
    received matrix from its addressable shard.  (A local ``jnp.
    asarray`` input would make XLA stage a cross-process transfer,
    which the CPU backend refuses and DCN makes implicit — the
    explicit global placement is the point.)  Returns a NumPy array.
    """
    import numpy as np

    values_np = np.asarray(values_np, dtype=np.int32)
    mask_np = np.asarray(neighbor_mask_np, dtype=bool)
    values = jax.make_array_from_callback(
        values_np.shape, NamedSharding(mesh, P(axis_name)),
        lambda idx: values_np[idx],
    )
    mask = jax.make_array_from_callback(
        mask_np.shape, NamedSharding(mesh, P(axis_name, None)),
        lambda idx: mask_np[idx],
    )

    def body(local_vals, mask_rows):
        all_vals = jax.lax.all_gather(local_vals, axis_name, tiled=True)
        received = _masked_receive(all_vals, mask_rows)
        # Second gather: replicate the full matrix onto every device so
        # each HOST can read the whole round locally.
        return jax.lax.all_gather(received, axis_name, tiled=True)

    # check_rep=False: the trailing all_gather DOES replicate the
    # output over dp, but shard_map's static replication checker cannot
    # see through a tiled gather to prove it.
    f = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name, None)),
        out_specs=P(None, None),
        check_rep=False,
    )
    out = f(values, mask)
    return np.asarray(out.addressable_shards[0].data)


def tally_votes(
    votes: jax.Array,   # [n] int32: 1 stop / 0 continue / -1 abstain
    mesh: Mesh,
    axis_name: str = "dp",
) -> Dict[str, jax.Array]:
    """Global stop/continue/abstain counts + 2/3 termination flag
    (reference byzantine_consensus.py:373-398 hardcodes 2n/3)."""

    def body(local_votes):
        stop = jax.lax.psum((local_votes == 1).sum(), axis_name)
        cont = jax.lax.psum((local_votes == 0).sum(), axis_name)
        abstain = jax.lax.psum((local_votes == -1).sum(), axis_name)
        total = stop + cont + abstain
        terminate = stop * 3 >= total * 2
        half = stop * 2 >= total
        return (
            jnp.broadcast_to(stop, local_votes.shape),
            jnp.broadcast_to(cont, local_votes.shape),
            jnp.broadcast_to(abstain, local_votes.shape),
            jnp.broadcast_to(terminate, local_votes.shape),
            jnp.broadcast_to(half, local_votes.shape),
        )

    f = shard_map(
        body, mesh=mesh, in_specs=(P(axis_name),),
        out_specs=(P(axis_name),) * 5,
    )
    stop, cont, abstain, term, half = f(votes)
    return {
        "stop": stop[0],
        "continue": cont[0],
        "abstain": abstain[0],
        "terminate": term[0],
        "half_stop": half[0],
    }


def check_consensus_spmd(
    values: jax.Array,          # [n] int32 current values, -1 = none
    is_byzantine: jax.Array,    # [n] bool (host-side knowledge)
    initial_values: jax.Array,  # [n] int32 honest initials, -1 for Byz
    mesh: Mesh,
    axis_name: str = "dp",
) -> Dict[str, jax.Array]:
    """Device-side consensus check with the reference's exact rule
    (byzantine_consensus.py:182-249): ALL honest agents hold the same
    value AND that value is some honest agent's initial value."""

    def body(vals, byz, inits):
        all_vals = jax.lax.all_gather(vals, axis_name, tiled=True)
        all_byz = jax.lax.all_gather(byz, axis_name, tiled=True)
        all_inits = jax.lax.all_gather(inits, axis_name, tiled=True)

        honest_valid = (~all_byz) & (all_vals >= 0)
        n_honest = honest_valid.sum()
        # Modal honest value via pairwise equality counts (O(n^2), n<=64)
        # — matches the host game's Counter().most_common (state.py:221-223).
        same = honest_valid[:, None] & honest_valid[None, :] & (
            all_vals[:, None] == all_vals[None, :]
        )
        counts = jnp.where(honest_valid, same.sum(axis=1), 0)
        modal_idx = jnp.argmax(counts)
        ref = all_vals[modal_idx]
        modal_count = counts[modal_idx]
        agreement = jnp.where(
            n_honest > 0, modal_count / jnp.maximum(n_honest, 1) * 100.0, 0.0
        )
        all_equal = (modal_count == n_honest) & (n_honest > 0)
        from_initial = ((all_inits == ref) & ~all_byz & (all_inits >= 0)).any()
        has_consensus = all_equal & from_initial
        shape = vals.shape
        return (
            jnp.broadcast_to(has_consensus, shape),
            jnp.broadcast_to(ref, shape),
            jnp.broadcast_to(agreement, shape),
        )

    f = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        out_specs=(P(axis_name),) * 3,
    )
    ok, value, agreement = f(values, is_byzantine, initial_values)
    return {
        "has_consensus": ok[0],
        "consensus_value": value[0],
        "agreement_pct": agreement[0],
    }


def spmd_round_arrays(
    proposals: jax.Array,       # [n] int32, -1 abstain
    votes: jax.Array,           # [n] int32 {1,0,-1}
    neighbor_mask: jax.Array,   # [n, n] bool
    is_byzantine: jax.Array,
    initial_values: jax.Array,
    mesh: Mesh,
    axis_name: str = "dp",
) -> Tuple[jax.Array, Dict, Dict]:
    """One full post-decision round on device: exchange + tally + check.

    Jit-compatible; the host orchestrator converts between this and its
    object model when running at one-agent-per-chip scale."""
    received = exchange_values(proposals, neighbor_mask, mesh, axis_name)
    tally = tally_votes(votes, mesh, axis_name)
    consensus = check_consensus_spmd(
        proposals, is_byzantine, initial_values, mesh, axis_name
    )
    return received, tally, consensus


def shard_agents(n_agents: int, mesh: Mesh, axis_name: str = "dp") -> NamedSharding:
    if n_agents % mesh.shape[axis_name]:
        raise ValueError(
            f"{n_agents} agents not divisible by {axis_name}={mesh.shape[axis_name]}"
        )
    return NamedSharding(mesh, P(axis_name))
